"""Explained variance.

Behavior parity with /root/reference/torchmetrics/functional/regression/
explained_variance.py:22-140, with the boolean-indexed assignments
re-expressed as ``jnp.where`` selects (identical numerics, jit-safe).
"""
from typing import Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _check_same_shape

Array = jax.Array


def _explained_variance_update(preds: Array, target: Array) -> Tuple[int, Array, Array, Array, Array]:
    _check_same_shape(preds, target)
    n_obs = preds.shape[0]
    diff = target - preds
    sum_error = jnp.sum(diff, axis=0)
    sum_squared_error = jnp.sum(diff * diff, axis=0)
    sum_target = jnp.sum(target, axis=0)
    sum_squared_target = jnp.sum(target * target, axis=0)
    return n_obs, sum_error, sum_squared_error, sum_target, sum_squared_target


def _explained_variance_compute(
    n_obs: Array,
    sum_error: Array,
    sum_squared_error: Array,
    sum_target: Array,
    sum_squared_target: Array,
    multioutput: str = "uniform_average",
) -> Array:
    diff_avg = sum_error / n_obs
    numerator = sum_squared_error / n_obs - diff_avg * diff_avg
    target_avg = sum_target / n_obs
    denominator = sum_squared_target / n_obs - target_avg * target_avg

    nonzero_numerator = numerator != 0
    nonzero_denominator = denominator != 0
    valid_score = nonzero_numerator & nonzero_denominator
    safe_denominator = jnp.where(valid_score, denominator, 1.0)
    output_scores = jnp.ones_like(diff_avg)
    output_scores = jnp.where(valid_score, 1.0 - numerator / safe_denominator, output_scores)
    output_scores = jnp.where(nonzero_numerator & ~nonzero_denominator, 0.0, output_scores)

    if multioutput == "raw_values":
        return output_scores
    if multioutput == "uniform_average":
        return jnp.mean(output_scores)
    if multioutput == "variance_weighted":
        denom_sum = jnp.sum(denominator)
        return jnp.sum(denominator / denom_sum * output_scores)
    raise ValueError(
        "Argument `multioutput` must be either `raw_values`,"
        f" `uniform_average` or `variance_weighted`. Received {multioutput}."
    )


def explained_variance(
    preds: Array,
    target: Array,
    multioutput: str = "uniform_average",
) -> Union[Array, Tuple[Array, ...]]:
    """Computes explained variance.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([3., -0.5, 2., 7.])
        >>> preds = jnp.array([2.5, 0.0, 2., 8.])
        >>> explained_variance(preds, target)
        Array(0.95717347, dtype=float32)
    """
    n_obs, sum_error, sum_squared_error, sum_target, sum_squared_target = _explained_variance_update(preds, target)
    return _explained_variance_compute(
        n_obs, sum_error, sum_squared_error, sum_target, sum_squared_target, multioutput
    )
