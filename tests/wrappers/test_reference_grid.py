"""Reference-parity sweep for the wrapper utilities.

Breadth parity with /root/reference/tests/wrappers/ (test_bootstrapping,
test_classwise, test_minmax, test_multioutput, test_tracker): value parity
against the reference for the deterministic wrappers (Classwise, MinMax,
Multioutput, Tracker) over multi-step histories, and behavioral/statistical
contracts for BootStrapper (whose resampling RNG differs from torch by
construction, so bit parity is impossible — the reference's own test
asserts distributional closeness the same way)."""
import numpy as np
import pytest

import jax.numpy as jnp

from metrics_tpu.classification import Accuracy, ConfusionMatrix, Precision, Recall
from metrics_tpu.regression import MeanSquaredError, R2Score
from metrics_tpu.wrappers import (
    BootStrapper,
    ClasswiseWrapper,
    MetricTracker,
    MinMaxMetric,
    MultioutputWrapper,
)
from tests.helpers.reference import load_reference_module

torch = pytest.importorskip("torch")

_rng = np.random.default_rng(17)
NC = 4
STEPS = 5
PREDS = _rng.random((STEPS, 24, NC)).astype(np.float32)
PREDS /= PREDS.sum(-1, keepdims=True)
TARGET = _rng.integers(0, NC, (STEPS, 24))


def test_classwise_wrapper_reference_parity():
    ref_tm = load_reference_module("torchmetrics")
    ours = ClasswiseWrapper(Accuracy(num_classes=NC, average="none"), labels=["a", "b", "c", "d"])
    ref = ref_tm.ClasswiseWrapper(
        ref_tm.Accuracy(num_classes=NC, average="none"), labels=["a", "b", "c", "d"]
    )
    for i in range(STEPS):
        ours.update(jnp.asarray(PREDS[i]), jnp.asarray(TARGET[i]))
        ref.update(torch.as_tensor(PREDS[i]), torch.as_tensor(TARGET[i]))
    got, want = ours.compute(), ref.compute()
    assert set(got) == set(want)
    for k in want:
        np.testing.assert_allclose(float(got[k]), float(want[k]), atol=1e-6, err_msg=k)


def test_minmax_reference_parity_over_history():
    # update-based parity: forward-mode nested-metric accumulation is a
    # known reference wart (its Metric.forward double-updates the CHILD
    # metric's uncached states); update() semantics agree exactly
    ref_tm = load_reference_module("torchmetrics")
    ours = MinMaxMetric(Accuracy())
    ref = ref_tm.MinMaxMetric(ref_tm.Accuracy())
    for i in range(STEPS):
        p = jnp.asarray((PREDS[i].argmax(-1) + (i % 2)) % NC)  # alternate quality
        ours.update(p, jnp.asarray(TARGET[i]))
        ref.update(torch.as_tensor(np.asarray(p)), torch.as_tensor(TARGET[i]))
        ours.compute()
        ref.compute()
    got, want = ours.compute(), ref.compute()
    for k in ("raw", "min", "max"):
        np.testing.assert_allclose(float(got[k]), float(want[k]), atol=1e-6, err_msg=k)


@pytest.mark.parametrize("metric_pair", ["r2", "mse"])
def test_multioutput_reference_parity(metric_pair):
    ref_tm = load_reference_module("torchmetrics")
    if metric_pair == "r2":
        ours = MultioutputWrapper(R2Score(), num_outputs=3)
        ref = ref_tm.MultioutputWrapper(ref_tm.R2Score(), num_outputs=3)
    else:
        ours = MultioutputWrapper(MeanSquaredError(), num_outputs=3)
        ref = ref_tm.MultioutputWrapper(ref_tm.MeanSquaredError(), num_outputs=3)
    p = _rng.random((STEPS, 16, 3)).astype(np.float32)
    t = _rng.random((STEPS, 16, 3)).astype(np.float32)
    for i in range(STEPS):
        ours.update(jnp.asarray(p[i]), jnp.asarray(t[i]))
        ref.update(torch.as_tensor(p[i]), torch.as_tensor(t[i]))
    np.testing.assert_allclose(
        np.asarray(ours.compute()).ravel(),
        np.asarray([float(v) for v in ref.compute()]),
        atol=1e-5,
    )


def test_tracker_reference_parity_full_history():
    ref_tm = load_reference_module("torchmetrics")
    ours = MetricTracker(Accuracy(), maximize=True)
    ref = ref_tm.MetricTracker(ref_tm.Accuracy(), maximize=True)
    for i in range(STEPS):
        ours.increment()
        ref.increment()
        for j in range(2):
            ours.update(jnp.asarray(PREDS[i]), jnp.asarray(TARGET[(i + j) % STEPS]))
            ref.update(torch.as_tensor(PREDS[i]), torch.as_tensor(TARGET[(i + j) % STEPS]))
    np.testing.assert_allclose(
        np.asarray([float(v) for v in ours.compute_all()]),
        np.asarray([float(v) for v in ref.compute_all()]),
        atol=1e-6,
    )
    got_best, got_idx = ours.best_metric(return_step=True)
    want_best, want_idx = ref.best_metric(return_step=True)
    np.testing.assert_allclose(float(got_best), float(want_best), atol=1e-6)
    assert int(got_idx) == int(want_idx)
    assert ours.n_steps == ref.n_steps


def test_tracker_collection_reference_parity():
    ref_tm = load_reference_module("torchmetrics")
    from metrics_tpu import MetricCollection

    ours = MetricTracker(MetricCollection([Precision(), Recall()]), maximize=[True, True])
    ref = ref_tm.MetricTracker(
        ref_tm.MetricCollection([ref_tm.Precision(), ref_tm.Recall()]), maximize=[True, True]
    )
    binary_preds = (PREDS[..., 0] > 0.25).astype(np.int64)
    binary_target = (TARGET > 1).astype(np.int64)
    for i in range(3):
        ours.increment()
        ref.increment()
        ours.update(jnp.asarray(binary_preds[i]), jnp.asarray(binary_target[i]))
        ref.update(torch.as_tensor(binary_preds[i]), torch.as_tensor(binary_target[i]))
    got_all = ours.compute_all()   # {name: [n_steps] array} on both sides
    want_all = ref.compute_all()
    for k in ("Precision", "Recall"):
        np.testing.assert_allclose(
            np.asarray(got_all[k]), np.asarray(want_all[k].numpy()), atol=1e-6, err_msg=k
        )


# ---------------------------------------------------------------------------
# BootStrapper: the resampling draws differ from torch by construction, so
# the contract is statistical (the reference's own test takes the same view)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("sampling_strategy", ["poisson", "multinomial"])
def test_bootstrapper_statistics_bracket_true_value(sampling_strategy):
    true_metric = Accuracy()
    boot = BootStrapper(
        Accuracy(),
        num_bootstraps=40,
        mean=True,
        std=True,
        quantile=jnp.asarray([0.05, 0.95]),
        raw=True,
        sampling_strategy=sampling_strategy,
        seed=7,
    )
    for i in range(STEPS):
        p = jnp.asarray(PREDS[i])
        t = jnp.asarray(TARGET[i])
        true_metric.update(p, t)
        boot.update(p, t)
    out = boot.compute()
    truth = float(true_metric.compute())
    assert abs(float(out["mean"]) - truth) < 0.1
    assert 0.0 <= float(out["std"]) < 0.2
    q_lo, q_hi = np.asarray(out["quantile"]).ravel()
    assert q_lo <= float(out["mean"]) <= q_hi
    assert out["raw"].shape[0] == 40


def test_bootstrapper_reference_arg_surface():
    """Same constructor contract as the reference: an invalid
    sampling_strategy raises on both implementations."""
    ref_tm = load_reference_module("torchmetrics")
    with pytest.raises(ValueError):
        BootStrapper(Accuracy(), sampling_strategy="bad")
    with pytest.raises(ValueError):
        ref_tm.BootStrapper(ref_tm.Accuracy(), sampling_strategy="bad")


def test_wrapped_confusion_matrix_tracker():
    """Non-scalar metric values flow through the tracker (reference
    test_tracker parametrizes ConfusionMatrix the same way)."""
    ours = MetricTracker(ConfusionMatrix(num_classes=NC), maximize=True)
    for i in range(2):
        ours.increment()
        ours.update(jnp.asarray(PREDS[i]), jnp.asarray(TARGET[i]))
    all_cm = ours.compute_all()
    assert np.asarray(all_cm).shape == (2, NC, NC)
    # non-scalar values have no 'best': warn + None (the reference fails
    # with an opaque tensor-conversion error here; None mirrors its own
    # collection-branch contract)
    with pytest.warns(UserWarning, match="best"):
        value, step = ours.best_metric(return_step=True)
    assert value is None and step is None


def test_tracker_best_metric_size_one_values():
    """Size-1 per-step values (e.g. a single-output multioutput history)
    still produce a real best value — only genuinely non-scalar histories
    degrade to None."""
    from metrics_tpu.core.metric import Metric

    class OneDim(Metric):
        def __init__(self, **kw):
            super().__init__(**kw)
            self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")

        def _update(self, x, y):
            self.total = self.total + jnp.sum(x)

        def _compute(self):
            return self.total[None]  # shape (1,)

    t = MetricTracker(OneDim(), maximize=True)
    for i in range(3):
        t.increment()
        t.update(jnp.asarray([float(i)]), jnp.asarray([0.0]))
    value, step = t.best_metric(return_step=True)
    assert value == 2.0 and step == 2
