#!/usr/bin/env python
"""Fail if any ``metrics_tpu/`` module calls ``print()`` or a bare
``warnings.warn`` directly.

All user-facing output from library code must route through the rank-zero
helpers in ``metrics_tpu/utils/prints.py`` (``rank_zero_print`` /
``rank_zero_info`` / ``rank_zero_warn``) so multi-host jobs emit one copy
and logging stays filterable. A raw ``print()`` — or a raw
``warnings.warn()``, which is just print with a category — in library code
spams every process in a pod job.

AST-based: only real call sites count — doctest examples and other string
content never false-positive. Both ``warnings.warn(...)`` attribute calls
and ``warn(...)`` calls after ``from warnings import warn`` are flagged.
Exit status 0 when clean, 1 with a ``path:line`` listing otherwise. Run
from anywhere:

    python scripts/check_no_print.py
"""
import ast
import pathlib
import sys

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
PACKAGE = REPO_ROOT / "metrics_tpu"

# the one module allowed to touch print/warnings.warn: it defines the
# gated helpers
ALLOWED = {PACKAGE / "utils" / "prints.py"}


def offender_lines(path: pathlib.Path):
    """(lineno, kind) of every raw ``print(...)`` / ``warnings.warn(...)``
    call expression in ``path``."""
    tree = ast.parse(path.read_text(), filename=str(path))
    warn_aliases = {
        alias.asname or alias.name
        for node in ast.walk(tree)
        if isinstance(node, ast.ImportFrom) and node.module == "warnings"
        for alias in node.names
        if alias.name == "warn"
    }
    # `import warnings` / `import warnings as w` — every bound module name
    module_aliases = {
        alias.asname or alias.name
        for node in ast.walk(tree)
        if isinstance(node, ast.Import)
        for alias in node.names
        if alias.name == "warnings"
    }
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Name) and func.id == "print":
            out.append((node.lineno, "print()"))
        elif (
            isinstance(func, ast.Attribute)
            and func.attr == "warn"
            and isinstance(func.value, ast.Name)
            and func.value.id in module_aliases
        ):
            out.append((node.lineno, "warnings.warn()"))
        elif isinstance(func, ast.Name) and func.id in warn_aliases:
            out.append((node.lineno, "warnings.warn()"))
    return out


def main() -> int:
    offenders = []
    for path in sorted(PACKAGE.rglob("*.py")):
        if path in ALLOWED:
            continue
        for lineno, kind in offender_lines(path):
            offenders.append(f"{path.relative_to(REPO_ROOT)}:{lineno} ({kind})")
    if offenders:
        sys.stderr.write(
            "raw print()/warnings.warn() calls found in metrics_tpu/ — use the"
            " rank-zero helpers from metrics_tpu/utils/prints.py instead:\n"
        )
        for offender in offenders:
            sys.stderr.write(f"  {offender}\n")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
