"""Generate stored oracle fixtures for the image inference metrics.

Run from the repo root:

    python scripts/make_image_oracle.py [--weights-dir DIR]

Always (re)writes ``tests/image/fixtures/image_engine_scores.csv`` — FID,
KID mean, and Inception Score computed over the deterministic corpus
(tests/image/inference_corpus.py) with a SEED-0 random-weight extractor.
Random weights make the absolute values meaningless as image-quality
numbers, but the scores are fully deterministic, so the csv pins the whole
statistic machinery (feature plumbing, f64 eigh trace-sqrtm, MMD, entropy
splits) against numeric drift, unconditionally, in every environment.

With ``--weights-dir`` pointing at the npz artifacts produced by
``scripts/fetch_and_convert_weights.py`` (a networked environment), also
writes ``image_real_weight_scores.csv`` (ours, pretrained weights) and —
when ``torch_fidelity`` is importable — ``image_official_scores.csv``
(the official implementations on the same corpus). The fixture test then
bounds |ours − official| from the stored csvs in every environment.
"""
import argparse
import csv
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
sys.path.insert(0, os.path.join(ROOT, "tests"))

# the engine drift pin must be bit-comparable to the test suite's runs, so
# use the suite's exact backend config (8-virtual-device forced CPU);
# conv accumulation order shifts the float32 scores ~1e-3 across device
# configs otherwise
from tests.helpers.force_cpu import setup_forced_cpu  # noqa: E402

setup_forced_cpu()

FIXDIR = os.path.join(ROOT, "tests", "image", "fixtures")


def _write(path, scores):
    with open(path, "w", newline="") as fh:
        w = csv.writer(fh)
        w.writerow(["metric", "value"])
        for k in sorted(scores):
            w.writerow([k, f"{scores[k]:.6f}"])
    print(f"wrote {path} ({len(scores)} values)")


def compute_ours(weights_path=None, lpips_weights_path=None):
    """FID/KID/IS — plus LPIPS when ``lpips_weights_path`` is given — over
    the corpus with our metrics; ``weights_path=None`` uses the shared
    seed-0 drift-pin extractors (tests/image/inference_corpus.py, the ONE
    definition the fixture test also uses)."""
    import jax.numpy as jnp

    from image.inference_corpus import engine_scores, lpips_pairs

    if weights_path is None:
        out = engine_scores()
    else:
        from metrics_tpu.models.inception import build_fid_inception

        feat = build_fid_inception(2048, weights_path)
        logits = build_fid_inception("logits_unbiased", weights_path)
        out = engine_scores(feat=feat, logits=logits)

    if lpips_weights_path is not None:
        from metrics_tpu.image import LearnedPerceptualImagePatchSimilarity

        a, b = lpips_pairs()
        lp = LearnedPerceptualImagePatchSimilarity(
            net_type="alex", net_weights_path=lpips_weights_path
        )
        lp.update(jnp.asarray(a), jnp.asarray(b))
        out["lpips_alex"] = float(lp.compute())
    return out


def compute_official():
    """Official-implementation scores over the same corpus (requires
    torch_fidelity, which drives its own pretrained InceptionV3): saves the
    corpus as PNG folders and runs ``calculate_metrics`` with the exact
    flags the reference metrics correspond to."""
    import tempfile

    import torch_fidelity
    from PIL import Image

    from image.inference_corpus import fid_sets

    real, fake = fid_sets()
    with tempfile.TemporaryDirectory() as tmp:
        dirs = {}
        for name, imgs in (("real", real), ("fake", fake)):
            d = os.path.join(tmp, name)
            os.makedirs(d)
            for i, img in enumerate(imgs):
                Image.fromarray(img.transpose(1, 2, 0)).save(os.path.join(d, f"{i:03d}.png"))
            dirs[name] = d
        out = torch_fidelity.calculate_metrics(
            input1=dirs["fake"],
            input2=dirs["real"],
            fid=True,
            kid=True,
            isc=True,
            kid_subset_size=10,
            kid_subsets=4,
            isc_splits=2,
            verbose=False,
        )
    return {
        "fid": float(out["frechet_inception_distance"]),
        "kid_mean": float(out["kernel_inception_distance_mean"]),
        "is_mean": float(out["inception_score_mean"]),
        "is_std": float(out["inception_score_std"]),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--weights-dir", default=None)
    args = ap.parse_args()

    os.makedirs(FIXDIR, exist_ok=True)
    _write(os.path.join(FIXDIR, "image_engine_scores.csv"), compute_ours(None))

    if args.weights_dir:
        npz = os.path.join(args.weights_dir, "inception_fid.npz")
        lpips_npz = os.path.join(args.weights_dir, "lpips_alex.npz")
        _write(
            os.path.join(FIXDIR, "image_real_weight_scores.csv"),
            compute_ours(npz, lpips_npz if os.path.exists(lpips_npz) else None),
        )
        try:
            import torch_fidelity  # noqa: F401
        except ImportError:
            print("torch_fidelity not installed — image_official_scores.csv not written")
        else:
            _write(os.path.join(FIXDIR, "image_official_scores.csv"), compute_official())


if __name__ == "__main__":
    main()
