"""Inception Score.

Behavior parity with /root/reference/torchmetrics/image/inception.py:28-171.
``feature`` accepts any callable ``imgs -> [N, num_classes]`` logits
extractor or 'logits_unbiased'/int for the bundled Flax InceptionV3.

State modes: by DEFAULT the metric streams exact per-split sufficient
statistics — softmax-probability sums ``[splits, C]``, per-sample
``Σ_c p log p`` sums ``[splits]``, and per-split counts — because each
split's KL term depends on its samples only through those moments:

    ``kl_k = plogp_sum_k / n_k − Σ_c m_c log m_c``,  ``m = prob_sum_k / n_k``

Samples land in splits ROUND-ROBIN by arrival index (deterministic,
chunking-invariant) instead of the reference's host-RNG shuffle-then-
contiguous-split; with i.i.d. streams the split populations are
exchangeable either way, but per-value parity requires ``exact=True``,
which restores the reference's unbounded feature list and shuffle
bit-for-bit (see docs/differences.md). Moment leaves are
``moments_merge_fx()``-reduced: element-wise summable, so cross-rank
merge is addition and the fused bucketing path masks pad rows via
``n_valid`` (``__fused_mask_valid__``) — pad rows never touch the
round-robin cursor, keeping the assignment identical to the unpadded
stream.
"""
from typing import Any, Callable, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.core.metric import Metric
from metrics_tpu.sketches.compat import register_exact_list_states, warn_exact_buffer
from metrics_tpu.sketches.moments import moments_merge_fx
from metrics_tpu.utils.data import dim_zero_cat

Array = jax.Array


class InceptionScore(Metric):
    """Computes the Inception Score (mean and std over splits).

    Args:
        feature: 'logits_unbiased' / int depth for the bundled Flax
            InceptionV3, or any callable ``imgs -> [N, num_classes]``.
        splits: number of KL splits (reference default 10).
        seed: host RNG seed for the ``exact=True`` shuffle (unused by the
            streaming default, whose round-robin assignment is
            deterministic).
        num_classes: logits width ``C`` for callable extractors (ignored
            otherwise; 'logits_unbiased' emits 1008, an int depth emits
            itself); default 1008.
        exact: restore the reference's unbounded feature list and
            shuffle-then-split behavior (bit-for-bit legacy path).
    """

    __exact_mode_attr__ = "_exact"
    __traced_callable_attrs__ = ("inception",)
    __fused_mask_valid__ = True
    is_differentiable = False
    higher_is_better = True

    def __init__(
        self,
        feature: Union[str, int, Callable] = "logits_unbiased",
        splits: int = 10,
        seed: int = None,
        feature_extractor_weights_path: str = None,
        num_classes: Optional[int] = None,
        exact: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)

        if isinstance(feature, (str, int)):
            valid_int_input = ("logits_unbiased", 64, 192, 768, 2048)
            if feature not in valid_int_input:
                raise ValueError(
                    f"Integer input to argument `feature` must be one of {valid_int_input}, but got {feature}."
                )
            from metrics_tpu.models.inception import build_fid_inception

            self.inception = build_fid_inception(feature, feature_extractor_weights_path)
            num_classes = 1008 if feature == "logits_unbiased" else feature
        elif callable(feature):
            self.inception = feature
            num_classes = 1008 if num_classes is None else num_classes
        else:
            raise TypeError("Got unknown input to argument `feature`")
        if not (isinstance(num_classes, int) and num_classes > 0):
            raise ValueError(f"Argument `num_classes` expected to be a positive int, got {num_classes}")
        self._num_classes = num_classes

        if not (isinstance(splits, int) and splits > 0):
            raise ValueError(f"Argument `splits` expected to be a positive int, got {splits}")
        self.splits = splits
        self._rng = np.random.RandomState(seed)

        self._exact = bool(exact)
        if self._exact:
            register_exact_list_states(self, ("features",), dist_reduce_fx=None)
            warn_exact_buffer("InceptionScore", "extracted features")
        else:
            self.add_state(
                "prob_sum",
                default=jnp.zeros((splits, num_classes), jnp.float32),
                dist_reduce_fx=moments_merge_fx(),
            )
            self.add_state(
                "plogp_sum",
                default=jnp.zeros((splits,), jnp.float32),
                dist_reduce_fx=moments_merge_fx(),
            )
            self.add_state(
                "split_count",
                default=jnp.zeros((splits,), jnp.float32),
                dist_reduce_fx=moments_merge_fx(),
            )

    def _update(self, imgs: Array, n_valid: Optional[Array] = None) -> None:
        features = self.inception(imgs)
        if self._exact:
            self.features.append(features)
            return
        logits = jnp.asarray(features, jnp.float32)
        if logits.shape[-1] != self._num_classes:
            raise ValueError(
                f"Extractor emitted logits of width {logits.shape[-1]} but the streaming"
                f" split state was sized for num_classes={self._num_classes} — pass the"
                " extractor's true width via `num_classes` (or use `exact=True`)."
            )
        prob = jax.nn.softmax(logits, axis=1)
        log_prob = jax.nn.log_softmax(logits, axis=1)
        plogp = jnp.sum(prob * log_prob, axis=1)  # [B]

        b = logits.shape[0]
        row = jnp.arange(b, dtype=jnp.int32)
        valid = row < n_valid if n_valid is not None else jnp.ones((b,), bool)
        # round-robin split assignment by global arrival index; pad rows
        # (masked by n_valid) neither land anywhere nor advance the cursor
        cursor = jnp.sum(self.split_count).astype(jnp.int32)
        arrival = cursor + jnp.cumsum(valid.astype(jnp.int32)) - 1
        assign = jnp.where(valid, arrival % self.splits, self.splits)
        onehot = (assign[:, None] == jnp.arange(self.splits)[None, :]).astype(jnp.float32)

        self.prob_sum = self.prob_sum + jnp.matmul(
            onehot.T, prob, precision=jax.lax.Precision.HIGHEST
        )
        self.plogp_sum = self.plogp_sum + jnp.matmul(
            onehot.T, plogp, precision=jax.lax.Precision.HIGHEST
        )
        self.split_count = self.split_count + jnp.sum(onehot, axis=0)

    def _compute_exact(self) -> Tuple[Array, Array]:
        features = dim_zero_cat(self.features)
        idx = self._rng.permutation(features.shape[0])
        features = features[idx]

        prob = jax.nn.softmax(features, axis=1)
        log_prob = jax.nn.log_softmax(features, axis=1)

        prob_chunks = jnp.array_split(prob, self.splits, axis=0)
        log_prob_chunks = jnp.array_split(log_prob, self.splits, axis=0)

        kl_ = []
        for p, log_p in zip(prob_chunks, log_prob_chunks):
            m_p = jnp.mean(p, axis=0, keepdims=True)
            kl = p * (log_p - jnp.log(m_p))
            kl_.append(jnp.exp(jnp.mean(jnp.sum(kl, axis=1))))
        kl = jnp.stack(kl_)
        return jnp.mean(kl), jnp.std(kl, ddof=1)

    def _compute(self) -> Tuple[Array, Array]:
        getattr(self.inception, "finalize", lambda: None)()  # flush async range check of the last batch
        if self._exact:
            return self._compute_exact()

        n = jnp.maximum(self.split_count, 1.0)  # [S]
        marginal = self.prob_sum / n[:, None]  # [S, C]
        cross = jnp.sum(marginal * jnp.log(jnp.clip(marginal, 1e-38, None)), axis=1)
        kl = jnp.exp(self.plogp_sum / n - cross)  # [S]
        return jnp.mean(kl), jnp.std(kl, ddof=1)
