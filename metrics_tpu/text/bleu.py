"""Modular BLEUScore.

Behavior parity with /root/reference/torchmetrics/text/bleu.py:29-120. String
tokenization/counting is host-side (inherently so — SURVEY §7.8); the
accumulated n-gram numerator/denominator/length states are device arrays
with ``dist_reduce_fx="sum"`` so the metric syncs over the mesh like any
other.
"""
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.text.bleu import _bleu_score_compute, _bleu_score_update, _tokenize_fn

Array = jax.Array


class BLEUScore(Metric):
    """Calculate BLEU score of machine-translated text with one or more references.

    Args:
        n_gram: Gram value ranged from 1 to 4 (default 4).
        smooth: Whether to apply add-one smoothing (Lin & Och 2004).

    Example:
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
        >>> metric = BLEUScore()
        >>> metric(preds, target)
        Array(0.75983566, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    __jit_unsafe__ = True  # update consumes Python strings

    def __init__(self, n_gram: int = 4, smooth: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.n_gram = n_gram
        self.smooth = smooth
        self.tokenizer: Callable[[str], Sequence[str]] = _tokenize_fn

        self.add_state("preds_len", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("target_len", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("numerator", default=jnp.zeros(n_gram), dist_reduce_fx="sum")
        self.add_state("denominator", default=jnp.zeros(n_gram), dist_reduce_fx="sum")

    def _update(self, preds: Sequence[str], target: Sequence[Sequence[str]]) -> None:
        preds_ = [preds] if isinstance(preds, str) else preds
        target_ = [[tgt] if isinstance(tgt, str) else tgt for tgt in target]
        if len(preds_) != len(target_):
            raise ValueError(f"Corpus has different size {len(preds_)} != {len(target_)}")

        numerator = np.zeros(self.n_gram)
        denominator = np.zeros(self.n_gram)
        preds_len, target_len = _bleu_score_update(
            preds_, target_, numerator, denominator, 0.0, 0.0, self.n_gram, self.tokenizer
        )
        self.preds_len = self.preds_len + preds_len
        self.target_len = self.target_len + target_len
        self.numerator = self.numerator + jnp.asarray(numerator, self.numerator.dtype)
        self.denominator = self.denominator + jnp.asarray(denominator, self.denominator.dtype)

    def _compute(self) -> Array:
        return _bleu_score_compute(
            self.preds_len, self.target_len, self.numerator, self.denominator, self.n_gram, self.smooth
        )
