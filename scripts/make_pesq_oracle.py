"""Generate the stored PESQ oracle fixtures for tests/audio/test_pesq_engine.py.

Run from the repo root:

    python scripts/make_pesq_oracle.py

Always (re)writes ``tests/audio/fixtures/pesq_engine_scores.csv`` — the
in-repo engine's scores over the deterministic corpus, asserted
unconditionally as a drift pin. When the official ``pesq`` C binding
(https://pypi.org/project/pesq/, the reference's scorer —
/root/reference/torchmetrics/functional/audio/pesq.py) is importable, also
writes ``pesq_official_scores.csv``; the fixture test then bounds
|engine − official| per item from the stored values, unconditionally, in
every environment from then on.
"""
import csv
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests"))

from audio.pesq_corpus import score_with  # noqa: E402

FIXDIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests", "audio", "fixtures"
)


def _write(path: str, scores: dict) -> None:
    with open(path, "w", newline="") as fh:
        w = csv.writer(fh)
        w.writerow(["item_id", "score"])
        for k in sorted(scores):
            w.writerow([k, f"{scores[k]:.6f}"])
    print(f"wrote {path} ({len(scores)} items)")


def main() -> None:
    os.makedirs(FIXDIR, exist_ok=True)

    from metrics_tpu.functional.audio._pesq_engine import pesq as engine_pesq

    _write(os.path.join(FIXDIR, "pesq_engine_scores.csv"), score_with(engine_pesq))

    try:
        import pesq as pesq_binding
    except ImportError:
        print("official `pesq` binding not installed — pesq_official_scores.csv not written")
        return

    def official(ref, deg, fs, mode):
        return pesq_binding.pesq(fs, ref, deg, mode)

    _write(os.path.join(FIXDIR, "pesq_official_scores.csv"), score_with(official))


if __name__ == "__main__":
    main()
