"""End-to-end fleet observatory test (ISSUE 13 acceptance): three REAL
publisher subprocesses + the merge-tree collector, with fault injection
that must trip AND clear all three fleet alarm classes while the wire
hazards (a byte-identical duplicate, a post-watermark straggler) are
counted and absorbed without corrupting the fold.

Real wall clock (publishers pace themselves and alarm clearing IS time
passing) plus three jax subprocess startups, so this is deliberately the
suite's slow-ish fleet test (~25s); every injected fault is deterministic
(a scheduled stall window, a scheduled polling pause, one corrupt file,
counted dup/late ships) so the assertions do not race the box."""
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent.parent
sys.path.insert(0, str(REPO_ROOT / "examples"))

FLEET_ALARM_CLASSES = ("publisher_stale", "snapshot_backlog", "fold_error")


def test_fleet_faults_trip_and_clear_every_fleet_alarm_class(tmp_path):
    import fleet_collector

    report = fleet_collector.run(
        duration=8.0,
        inject="all",
        out_dir=str(tmp_path),
        n_publishers=3,
        interval=0.2,
        poll_interval=0.25,
        late_window_s=3.0,
        window_s=4.0,
        batch_size=32,
        seed=0,
        verbose=False,
    )
    for cls in FLEET_ALARM_CLASSES:
        assert cls in report["alarms_fired"], (cls, report["alarms_fired"])
        assert cls in report["alarms_fired_and_cleared"], (
            cls,
            report["alarms_fired_and_cleared"],
        )
    totals = report["totals"]
    # the wire hazards were really exercised — and absorbed exactly once
    assert totals["duplicates"] > 0
    assert totals["late_dropped"] > 0
    assert totals["fold_errors"] == 1  # the one corrupt file, nothing else
    assert totals["publishers"] == 3
    assert totals["absorbed"] > 0
    # every publisher shipped and exited cleanly
    assert report["publisher_exit_codes"] == [0, 0, 0]
    assert all(not p["stale"] for p in report["publishers"])
    # the global fold computed real fleet-wide values
    assert 0.0 <= report["fleet_values"]["acc"] <= 1.0
    assert report["final_status"] == "ok"
    # artifacts materialized
    assert (tmp_path / "fleet.prom").exists()
    assert (tmp_path / "report.json").exists()
    assert (tmp_path / "health_alarms.jsonl").exists()
    page = (tmp_path / "fleet.prom").read_text()
    assert "metrics_tpu_fleet_snapshots_total" in page
    assert 'metrics_tpu_fleet_metric_value{metric="acc"}' in page
