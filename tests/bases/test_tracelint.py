"""tracelint static-analyzer tests: per-rule positive/negative fixtures,
suppression pragmas, baseline round-trip, JSON reporter schema, and the
tier-1 package gate (the whole of ``metrics_tpu/`` must be clean against
the checked-in baseline).
"""
import json
import os
import pathlib
import subprocess
import sys
from collections import Counter

import pytest

from metrics_tpu.analysis import (
    RULE_REGISTRY,
    analyze_paths,
    analyze_source,
    default_package_root,
    get_rules,
    load_baseline,
    render_github,
    render_json,
    save_baseline,
    split_by_baseline,
    suppressed_rules,
)
from metrics_tpu.analysis.cli import DEFAULT_BASELINE, main as cli_main

REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]

_METRIC_PREAMBLE = """
import numpy as np
import jax
import jax.numpy as jnp
from metrics_tpu.core.metric import Metric
"""


def _check(source, relpath="classification/fixture.py", rules=None):
    kept, suppressed = analyze_source(
        _METRIC_PREAMBLE + source, relpath, rules=get_rules(rules) if rules else None
    )
    return kept, suppressed


def _rules_of(violations):
    return {v.rule for v in violations}


# ---------------------------------------------------------------------------
# TL-TRACE
# ---------------------------------------------------------------------------

class TestTraceRule:
    def test_float_on_traced_update_flags(self):
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds):
        self.total = self.total + float(jnp.sum(preds))
    def _compute(self):
        return self.total
"""
        )
        assert "TL-TRACE" in _rules_of(kept)

    def test_item_in_compute_flags(self):
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds):
        self.total = self.total + jnp.sum(preds)
    def _compute(self):
        return self.total.item()
"""
        )
        assert "TL-TRACE" in _rules_of(kept)

    def test_np_asarray_on_param_flags(self):
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds):
        host = np.asarray(preds)
        self.total = self.total + host.sum()
    def _compute(self):
        return self.total
"""
        )
        assert "TL-TRACE" in _rules_of(kept)

    def test_if_on_traced_value_flags(self):
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds):
        if jnp.max(preds) > 1:
            preds = preds / jnp.max(preds)
        self.total = self.total + jnp.sum(preds)
    def _compute(self):
        return self.total
"""
        )
        assert "TL-TRACE" in _rules_of(kept)

    def test_shape_checks_and_clean_update_pass(self):
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds, target):
        if preds.ndim == 2 and preds.shape[0] > 0:
            preds = preds.reshape(-1)
        self.total = self.total + jnp.sum(preds * target)
    def _compute(self):
        return self.total
"""
        )
        assert "TL-TRACE" not in _rules_of(kept)

    def test_issubdtype_predicate_is_static(self):
        """`jnp.issubdtype(x.dtype, ...)` is dtype metadata, not a traced
        value — branching on it (the SlicedMetric slice-id validation
        idiom) compiles away exactly like a `.dtype` read."""
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, ids, preds):
        if not jnp.issubdtype(ids.dtype, jnp.integer):
            raise ValueError("ids must be integers")
        self.total = self.total + jnp.sum(preds)
    def _compute(self):
        return self.total
"""
        )
        assert "TL-TRACE" not in _rules_of(kept)

    def test_issubdtype_member_import_is_static_too(self):
        """The member-import spelling must get the same static-predicate
        exemption as the jnp-alias spelling."""
        kept, _ = _check(
            """
from jax.numpy import issubdtype
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, ids, preds):
        if not issubdtype(ids.dtype, jnp.integer):
            raise ValueError("ids must be integers")
        self.total = self.total + jnp.sum(preds)
    def _compute(self):
        return self.total
"""
        )
        assert "TL-TRACE" not in _rules_of(kept)

    def test_is_concrete_guard_exempts(self):
        """The eager-only guard pattern (utils/checks.py) must not flag."""
        kept, _ = _check(
            """
from metrics_tpu.utils.checks import _is_concrete
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds):
        if _is_concrete(preds):
            if bool(jnp.any(jnp.isnan(preds))):
                raise RuntimeError("nan")
        self.total = self.total + jnp.sum(preds)
    def _compute(self):
        return self.total
"""
        )
        assert "TL-TRACE" not in _rules_of(kept)

    def test_jit_unsafe_class_exempt(self):
        kept, _ = _check(
            """
class M(Metric):
    __jit_unsafe__ = True  # host-side reference implementation
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds):
        self.total = self.total + float(np.asarray(preds).sum())
    def _compute(self):
        return float(self.total)
"""
        )
        assert "TL-TRACE" not in _rules_of(kept)

    def test_functional_kernel_item_flags(self):
        kept, _ = _check(
            """
def kernel_update(state, preds):
    return state + jnp.sum(preds).item()
""",
            relpath="functional/classification/fixture.py",
        )
        assert "TL-TRACE" in _rules_of(kept)

    def test_functional_kernel_clean_passes(self):
        kept, _ = _check(
            """
def kernel_update(state, preds):
    return state + jnp.sum(preds)
""",
            relpath="functional/classification/fixture.py",
        )
        assert "TL-TRACE" not in _rules_of(kept)


# ---------------------------------------------------------------------------
# TL-RECOMPILE
# ---------------------------------------------------------------------------

class TestRecompileRule:
    def test_shape_arg_in_static_position_flags(self):
        kept, _ = _check(
            """
fn = jax.jit(lambda x, n: x * n, static_argnums=(1,))
def run(x):
    return fn(x, x.shape[0])
"""
        )
        assert "TL-RECOMPILE" in _rules_of(kept)

    def test_len_and_int_args_flag(self):
        kept, _ = _check(
            """
from functools import partial
@partial(jax.jit, static_argnums=(1,))
def fn(x, n):
    return x * n
def run(x, items):
    return fn(x, len(items)) + fn(x, int(x.sum()))
"""
        )
        assert sum(v.rule == "TL-RECOMPILE" for v in kept) == 2

    def test_static_argnames_maps_to_positional_call(self):
        """The stoi idiom: static_argnames args passed positionally."""
        kept, _ = _check(
            """
from functools import partial
@partial(jax.jit, static_argnames=("bucket",))
def fn(x, bucket):
    return x[:bucket]
def run(x):
    return fn(x, int(x.sum())) + fn(x, bucket=len(x))
"""
        )
        assert sum(v.rule == "TL-RECOMPILE" for v in kept) == 2

    def test_dynamic_scalar_arg_passes(self):
        """Without static_argnums, a Python scalar traces as a weak 0-d
        array and shares ONE compilation — no hazard, no flag."""
        kept, _ = _check(
            """
fn = jax.jit(lambda x, n: x * n)
def run(x, items):
    return fn(x, x.shape[0]) + fn(x, len(items))
"""
        )
        assert "TL-RECOMPILE" not in _rules_of(kept)

    def test_coerced_scalar_passes(self):
        """jnp.asarray-wrapped values in dynamic positions never flag."""
        kept, _ = _check(
            """
fn = jax.jit(lambda x, n: x * n, static_argnums=(1,))
def run(x):
    return fn(x, jnp.asarray(x.shape[0]))
"""
        )
        assert "TL-RECOMPILE" not in _rules_of(kept)


# ---------------------------------------------------------------------------
# TL-STATE
# ---------------------------------------------------------------------------

class TestStateRule:
    def test_unknown_reducer_flags(self):
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="avg")
"""
        )
        assert "TL-STATE" in _rules_of(kept)

    def test_known_reducers_and_callable_pass(self):
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("a", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("b", default=jnp.asarray(0.0), dist_reduce_fx=None)
        self.add_state("c", default=jnp.asarray(0.0), dist_reduce_fx=jnp.sum)
"""
        )
        assert "TL-STATE" not in _rules_of(kept)

    def test_state_write_in_compute_flags(self):
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds):
        self.total = self.total + jnp.sum(preds)
    def _compute(self):
        self.total = self.total * 2
        return self.total
"""
        )
        assert "TL-STATE" in _rules_of(kept)

    def test_state_write_in_update_and_reset_pass(self):
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds):
        self.total = self.total + jnp.sum(preds)
    def reset(self):
        self.total = jnp.asarray(0.0)
        super().reset()
    def _compute(self):
        return self.total
"""
        )
        assert "TL-STATE" not in _rules_of(kept)

    def test_list_state_without_declaration_flags(self):
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("preds", default=[], dist_reduce_fx="cat")
"""
        )
        assert "TL-STATE" in _rules_of(kept)

    def test_list_state_with_declaration_passes(self):
        kept, _ = _check(
            """
class M(Metric):
    __jit_unsafe__ = False  # append-only update traces
    def __init__(self):
        super().__init__()
        self.add_state("preds", default=[], dist_reduce_fx="cat")
"""
        )
        assert "TL-STATE" not in _rules_of(kept)

    def test_wrapper_without_declaration_flags(self):
        kept, _ = _check(
            """
class W(Metric):
    def __init__(self, base):
        super().__init__()
        self.metric = base
""",
            relpath="wrappers/fixture.py",
        )
        assert "TL-STATE" in _rules_of(kept)

    def test_instance_level_declaration_counts(self):
        """The _capacity.py idiom: self.__dict__["__jit_unsafe__"] = ..."""
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("preds", default=[], dist_reduce_fx="cat")
        self.__dict__["__jit_unsafe__"] = False
"""
        )
        assert "TL-STATE" not in _rules_of(kept)

    def test_host_counter_writes_pass_anywhere(self):
        """The incremental-read-plane carve-out: host-side epoch/dirty-set
        counters, fold memos, and per-slice value caches are NOT registered
        state — writing them from traced methods or ad-hoc helpers is legal
        (they are trace-time no-ops and the read plane rebuilds them from
        real state on any degrade)."""
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self._dirty = np.ones(8, dtype=bool)
        self._fold_memo = {}
        self._svc = None
    def _update(self, preds, ids):
        self.total = self.total + jnp.sum(preds)
        self._dirty[np.asarray(ids)] = True
    def _read_slices(self, ids):
        self._fold_memo[0] = self.total
        self._svc = np.zeros(8)
        self._last_read_cache_hit = True
        self._dirty[:] = False
        return self.total
"""
        )
        assert "TL-STATE" not in _rules_of(kept)

    def test_cache_plane_write_outside_lifecycle_flags(self):
        """Direct epoch-cache writes outside the lifecycle bypass
        ``_mark_state_written()``'s subclass degrade hook — the blunt
        ``self._computed = None`` invalidation this plane replaced."""
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds):
        self.total = self.total + jnp.sum(preds)
    def invalidate(self):
        self._computed = None
        self._write_epoch += 1
    def _compute(self):
        return self.total
"""
        )
        assert "TL-STATE" in _rules_of(kept)

    def test_cache_plane_write_via_mark_hooks_passes(self):
        """The sanctioned out-of-band write path: ``_mark_state_written``
        overrides (and the compute cycle itself) may stamp the cache."""
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self._fold_memo = {}
    def _update(self, preds):
        self.total = self.total + jnp.sum(preds)
    def _mark_state_written(self):
        self._write_epoch += 1
        self._computed = None
        self._fold_memo.clear()
    def _mark_fused_written(self):
        self._update_called = True
        self._write_epoch += 1
        self._computed = None
    def _compute(self):
        return self.total
"""
        )
        assert "TL-STATE" not in _rules_of(kept)


# ---------------------------------------------------------------------------
# TL-COLLECTIVE
# ---------------------------------------------------------------------------

class TestCollectiveRule:
    def test_raw_psum_outside_transport_flags(self):
        kept, _ = _check(
            """
def my_sync(x):
    return jax.lax.psum(x, "rank")
"""
        )
        assert "TL-COLLECTIVE" in _rules_of(kept)

    def test_from_import_collective_flags(self):
        kept, _ = _check(
            """
from jax.lax import all_gather
def my_sync(x):
    return all_gather(x, "rank")
"""
        )
        assert "TL-COLLECTIVE" in _rules_of(kept)

    def test_process_allgather_flags(self):
        kept, _ = _check(
            """
from jax.experimental import multihost_utils
def my_sync(x):
    return multihost_utils.process_allgather(x)
"""
        )
        assert "TL-COLLECTIVE" in _rules_of(kept)

    def test_transport_layer_allowed(self):
        kept, _ = _check(
            """
def sync_impl(x):
    return jax.lax.psum(x, "rank")
""",
            relpath="parallel/fixture.py",
        )
        assert "TL-COLLECTIVE" not in _rules_of(kept)

    def test_aggregate_module_allowed(self):
        kept, _ = _check(
            """
from jax.experimental import multihost_utils
def agg(x):
    return multihost_utils.process_allgather(x)
""",
            relpath="observability/aggregate.py",
        )
        assert "TL-COLLECTIVE" not in _rules_of(kept)


# ---------------------------------------------------------------------------
# TL-PRINT
# ---------------------------------------------------------------------------

class TestPrintRule:
    def test_print_flags(self):
        kept, _ = _check("""
def f():
    print("hello")
""")
        assert "TL-PRINT" in _rules_of(kept)

    def test_warnings_warn_flags(self):
        kept, _ = _check("""
import warnings
def f():
    warnings.warn("x")
""")
        assert "TL-PRINT" in _rules_of(kept)

    def test_from_import_warn_flags(self):
        kept, _ = _check("""
from warnings import warn
def f():
    warn("x")
""")
        assert "TL-PRINT" in _rules_of(kept)

    def test_rank_zero_helpers_pass(self):
        kept, _ = _check("""
from metrics_tpu.utils.prints import rank_zero_warn
def f():
    rank_zero_warn("x")
""")
        assert "TL-PRINT" not in _rules_of(kept)

    def test_prints_module_allowed(self):
        kept, _ = _check("""
def rank_zero_print(*args):
    print(*args)
""", relpath="utils/prints.py")
        assert "TL-PRINT" not in _rules_of(kept)

    def test_doctest_print_never_flags(self):
        """AST-based: print inside a docstring example is not a call site."""
        kept, _ = _check('''
def f():
    """Example:
        >>> print("hello")
    """
    return 1
''')
        assert "TL-PRINT" not in _rules_of(kept)

    def test_check_no_print_alias_still_works(self):
        """The legacy script invocation is an alias over TL-PRINT."""
        result = subprocess.run(
            [sys.executable, str(REPO_ROOT / "scripts" / "check_no_print.py")],
            capture_output=True,
            text=True,
        )
        assert result.returncode == 0, result.stderr


# ---------------------------------------------------------------------------
# TL-BLOCK
# ---------------------------------------------------------------------------

class TestBlockRule:
    def test_item_in_pipeline_worker_flags(self):
        kept, _ = _check(
            """
class H:
    def _worker(self):
        while True:
            batch = self._queue.get()
            self.total = batch.item()
""",
            relpath="core/pipeline.py",
        )
        assert "TL-BLOCK" in _rules_of(kept)

    def test_block_until_ready_in_async_function_flags_anywhere(self):
        kept, _ = _check(
            """
def send_async(preds):
    preds.block_until_ready()
    return preds
""",
            relpath="classification/accuracy.py",
        )
        assert "TL-BLOCK" in _rules_of(kept)

    def test_device_get_in_enqueue_path_flags(self):
        kept, _ = _check(
            """
class H:
    def _enqueue(self, batch):
        host = jax.device_get(batch)
        self._queue.put(host)
""",
            relpath="core/pipeline.py",
        )
        assert "TL-BLOCK" in _rules_of(kept)

    def test_float_on_batch_value_in_update_async_flags(self):
        kept, _ = _check(
            """
def update_async(self, preds):
    return float(jnp.sum(preds))
""",
            relpath="core/pipeline.py",
        )
        assert "TL-BLOCK" in _rules_of(kept)

    def test_non_hot_function_in_pipeline_passes(self):
        # flush() is the sanctioned drain point: blocking there is the API
        kept, _ = _check(
            """
class H:
    def flush(self, value):
        return float(value)
""",
            relpath="core/pipeline.py",
        )
        assert "TL-BLOCK" not in _rules_of(kept)

    def test_host_scalar_cast_passes(self):
        # int() on a host constant is not a readback even on the hot path
        kept, _ = _check(
            """
def update_async(self, preds):
    depth = int(2)
    self._queue.put((depth, preds))
""",
            relpath="core/pipeline.py",
        )
        assert "TL-BLOCK" not in _rules_of(kept)

    def test_worker_outside_pipeline_not_scoped(self):
        # the worker/enqueue name tokens only bind inside core/pipeline.py
        kept, _ = _check(
            """
class Exporter:
    def _worker(self):
        return self._value.item()
""",
            relpath="observability/exporters.py",
        )
        assert "TL-BLOCK" not in _rules_of(kept)

    def test_pragma_suppresses_block(self):
        kept, suppressed = _check(
            """
def update_async(self, preds):
    return preds.item()  # tracelint: disable=TL-BLOCK — documented cold path
""",
            relpath="core/pipeline.py",
        )
        assert "TL-BLOCK" not in _rules_of(kept)
        assert "TL-BLOCK" in _rules_of(suppressed)


# ---------------------------------------------------------------------------
# suppression pragmas
# ---------------------------------------------------------------------------

class TestSuppression:
    def test_pragma_parses(self):
        assert suppressed_rules("x = 1  # tracelint: disable=TL-TRACE") == {"TL-TRACE"}
        assert suppressed_rules("x = 1  # tracelint: disable=tl-trace, TL-STATE") == {
            "TL-TRACE",
            "TL-STATE",
        }
        assert suppressed_rules("x = 1  # tracelint: disable=all") == {"ALL"}
        assert suppressed_rules("x = 1  # a normal comment") == set()

    def test_pragma_suppresses_on_violation_line(self):
        kept, suppressed = _check(
            """
def f():
    print("hello")  # tracelint: disable=TL-PRINT — CLI surface
"""
        )
        assert "TL-PRINT" not in _rules_of(kept)
        assert "TL-PRINT" in _rules_of(suppressed)

    def test_pragma_for_other_rule_does_not_suppress(self):
        kept, suppressed = _check(
            """
def f():
    print("hello")  # tracelint: disable=TL-TRACE
"""
        )
        assert "TL-PRINT" in _rules_of(kept)

    def test_disable_all_suppresses_everything(self):
        kept, suppressed = _check(
            """
def f(x):
    print(jax.lax.psum(x, "rank"))  # tracelint: disable=all
"""
        )
        assert kept == []
        assert {"TL-PRINT", "TL-COLLECTIVE"} <= _rules_of(suppressed)


# ---------------------------------------------------------------------------
# baseline round-trip
# ---------------------------------------------------------------------------

class TestBaseline:
    def _violations(self):
        kept, _ = _check(
            """
def f():
    print("a")
    print("a")
    print("b")
"""
        )
        return [v for v in kept if v.rule == "TL-PRINT"]

    def test_round_trip_is_clean(self, tmp_path):
        violations = self._violations()
        baseline_file = tmp_path / "baseline.json"
        save_baseline(baseline_file, violations)
        loaded = load_baseline(baseline_file)
        new, grandfathered, stale = split_by_baseline(violations, loaded)
        assert new == []
        assert len(grandfathered) == len(violations)
        assert not stale

    def test_duplicate_lines_tracked_by_count(self, tmp_path):
        violations = self._violations()
        assert len(violations) == 3  # two identical `print("a")` lines + one "b"
        baseline_file = tmp_path / "baseline.json"
        save_baseline(baseline_file, violations)
        loaded = load_baseline(baseline_file)
        assert sum(loaded.values()) == 3
        # dropping one duplicate from the baseline surfaces exactly one NEW
        short = Counter(loaded)
        key = next(k for k in short if 'print("a")' in k[2])
        short[key] -= 1
        new, grandfathered, _ = split_by_baseline(violations, short)
        assert len(new) == 1

    def test_new_violation_not_masked(self, tmp_path):
        violations = self._violations()
        baseline_file = tmp_path / "baseline.json"
        save_baseline(baseline_file, violations[:1])
        loaded = load_baseline(baseline_file)
        new, _, _ = split_by_baseline(violations, loaded)
        assert len(new) == len(violations) - 1

    def test_fixed_violation_reported_stale(self, tmp_path):
        violations = self._violations()
        baseline_file = tmp_path / "baseline.json"
        save_baseline(baseline_file, violations)
        loaded = load_baseline(baseline_file)
        _, _, stale = split_by_baseline(violations[:1], loaded)
        assert sum(stale.values()) == 2

    def test_missing_baseline_is_empty(self, tmp_path):
        assert load_baseline(tmp_path / "nope.json") == Counter()

    def test_version_mismatch_raises(self, tmp_path):
        bad = tmp_path / "baseline.json"
        bad.write_text(json.dumps({"version": 99, "entries": []}))
        with pytest.raises(ValueError, match="version"):
            load_baseline(bad)


# ---------------------------------------------------------------------------
# JSON reporter schema
# ---------------------------------------------------------------------------

class TestJsonReporter:
    def test_schema(self):
        kept, suppressed = _check(
            """
def f():
    print("a")
"""
        )
        payload = json.loads(
            render_json(kept, [], suppressed_count=len(suppressed), n_files=1, rules=["TL-PRINT"])
        )
        assert payload["version"] == 2
        assert payload["tool"] == "tracelint"
        assert isinstance(payload["violations"], list) and payload["violations"]
        entry = payload["violations"][0]
        # v2 adds the repo-relative "file" key; every v1 field survives so
        # consumers keyed on path/line/rule are unaffected
        for field in ("rule", "path", "file", "line", "col", "message", "snippet", "baselined"):
            assert field in entry
        assert entry["file"] == "metrics_tpu/" + entry["path"]
        assert entry["baselined"] is False
        summary = payload["summary"]
        for field in ("files", "new", "baselined", "suppressed", "rules"):
            assert field in summary
        assert summary["new"] == len(kept)

    def test_cli_json_mode(self, tmp_path, capsys):
        src = tmp_path / "mod.py"
        src.write_text("print('x')\n")
        rc = cli_main([str(src), "--json", "--no-baseline"])
        out = capsys.readouterr().out
        payload = json.loads(out)
        assert rc == 1
        assert payload["summary"]["new"] == 1


# ---------------------------------------------------------------------------
# CLI baseline scoping: partial-path runs must not clobber or mis-report
# entries belonging to files outside the analyzed set
# ---------------------------------------------------------------------------

class TestCliBaselineScoping:
    def _two_files(self, tmp_path):
        dirty_a = tmp_path / "a.py"
        dirty_a.write_text("print('a')\n")
        dirty_b = tmp_path / "b.py"
        dirty_b.write_text("print('b')\n")
        return dirty_a, dirty_b

    def test_partial_baseline_update_carries_other_files(self, tmp_path, capsys):
        dirty_a, dirty_b = self._two_files(tmp_path)
        baseline = tmp_path / "baseline.json"
        # baseline both files, then re-update from only a.py
        assert cli_main([str(dirty_a), str(dirty_b), "--baseline", str(baseline), "--baseline-update"]) == 0
        assert cli_main([str(dirty_a), "--baseline", str(baseline), "--baseline-update"]) == 0
        capsys.readouterr()
        loaded = load_baseline(baseline)
        # b.py's grandfathered entry survived the a.py-only rewrite
        assert any(path == "b.py" for (_, path, _) in loaded)
        assert cli_main([str(dirty_a), str(dirty_b), "--baseline", str(baseline), "--check"]) == 0
        capsys.readouterr()

    def test_partial_check_ignores_other_files_staleness(self, tmp_path, capsys):
        dirty_a, dirty_b = self._two_files(tmp_path)
        baseline = tmp_path / "baseline.json"
        assert cli_main([str(dirty_a), str(dirty_b), "--baseline", str(baseline), "--baseline-update"]) == 0
        capsys.readouterr()
        # checking only a.py: b.py's unconsumed entry is NOT stale
        assert cli_main([str(dirty_a), "--baseline", str(baseline), "--check"]) == 0
        out = capsys.readouterr().out
        assert "stale" not in out
        # but a genuinely fixed violation in an ANALYZED file still is
        dirty_a.write_text("x = 1\n")
        assert cli_main([str(dirty_a), "--baseline", str(baseline), "--check"]) == 1
        assert "stale" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# package gate (tier-1): the whole library must be clean vs the baseline
# ---------------------------------------------------------------------------

class TestPackageGate:
    def test_package_has_no_new_violations(self):
        result = analyze_paths([default_package_root()])
        baseline = load_baseline(REPO_ROOT / DEFAULT_BASELINE)
        new, grandfathered, _ = split_by_baseline(result.violations, baseline)
        assert not result.parse_errors
        details = "\n".join(v.render() for v in new)
        assert new == [], f"new tracelint violations in metrics_tpu/:\n{details}"

    def test_baseline_is_small(self):
        """Acceptance gate: at most 15 grandfathered entries, every one
        carrying the auditable (rule, path, snippet) key."""
        baseline = load_baseline(REPO_ROOT / DEFAULT_BASELINE)
        assert sum(baseline.values()) <= 15

    def test_every_rule_registered(self):
        assert set(RULE_REGISTRY) == {
            "TL-TRACE",
            "TL-RECOMPILE",
            "TL-STATE",
            "TL-COLLECTIVE",
            "TL-PRINT",
            "TL-DECL",
            "TL-FLOW",
            "TL-BLOCK",
            "TL-SHARD",
            "TL-MERGE",
            "TL-WIRE",
            "TL-LOCK",
        }

    def test_cli_script_exits_zero_on_package(self):
        result = subprocess.run(
            [sys.executable, str(REPO_ROOT / "scripts" / "tracelint.py"), "--check"],
            capture_output=True,
            text=True,
        )
        assert result.returncode == 0, result.stdout + result.stderr

    def test_manifest_check_covers_both_manifests_without_jax(self, tmp_path):
        """The CI freshness gate (`--manifest --check`) must regenerate and
        verify BOTH manifests on a machine with no accelerator stack: run
        it in a subprocess where importing jax is a hard error."""
        blocker = tmp_path / "sitecustomize.py"
        blocker.write_text(
            "import sys\n"
            "class _Block:\n"
            "    def find_spec(self, name, path=None, target=None):\n"
            "        if name == 'jax' or name.startswith('jax.'):\n"
            "            raise ImportError('jax import blocked by test')\n"
            "        return None\n"
            "sys.meta_path.insert(0, _Block())\n"
        )
        env = dict(os.environ, PYTHONPATH=str(tmp_path))
        result = subprocess.run(
            [
                sys.executable,
                str(REPO_ROOT / "scripts" / "tracelint.py"),
                "--manifest",
                "--check",
            ],
            capture_output=True,
            text=True,
            env=env,
            cwd=str(REPO_ROOT),
        )
        assert result.returncode == 0, result.stdout + result.stderr
        out = result.stdout
        assert "fusibility" in out and "layout" in out, out


# ---------------------------------------------------------------------------
# engine satellites: alias rebindings, direct member imports, file pragmas
# ---------------------------------------------------------------------------

class TestAliasRebinding:
    def test_jnp_rebinding_tracks_taint(self):
        """`np = jnp` makes np.* calls traced producers for TL-TRACE."""
        kept, _ = _check(
            """
np2 = jnp
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds):
        x = np2.cumsum(preds)
        if x[-1] > 0:
            preds = preds / x[-1]
        self.total = self.total + jnp.sum(preds)
    def _compute(self):
        return self.total
"""
        )
        assert "TL-TRACE" in _rules_of(kept)

    def test_rebound_numpy_asarray_not_flagged_as_host(self):
        """`np = jnp` must NOT flag np.asarray as a host pull."""
        kept, _ = _check(
            """
import numpy
np3 = jnp
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds):
        self.total = self.total + np3.asarray(preds).sum()
    def _compute(self):
        return self.total
"""
        )
        assert "TL-TRACE" not in _rules_of(kept)

    def test_direct_jnp_member_import_tracks_taint(self):
        kept, _ = _check(
            """
from jax.numpy import concatenate
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds):
        both = concatenate([preds, preds])
        if both[0] > 0:
            preds = preds * 2
        self.total = self.total + jnp.sum(preds)
    def _compute(self):
        return self.total
"""
        )
        assert "TL-TRACE" in _rules_of(kept)

    def test_direct_numpy_member_import_flags_host_pull(self):
        kept, _ = _check(
            """
from numpy import asarray
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds):
        host = asarray(preds)
        self.total = self.total + host.sum()
    def _compute(self):
        return self.total
"""
        )
        assert "TL-TRACE" in _rules_of(kept)

    def test_lax_rebinding_collective_flags(self):
        kept, _ = _check(
            """
mylax = jax.lax
def my_sync(x):
    return mylax.psum(x, "rank")
"""
        )
        assert "TL-COLLECTIVE" in _rules_of(kept)

    def test_unrebound_name_still_clean(self):
        kept, _ = _check(
            """
import numpy
def helper(meta):
    return numpy.prod(meta)
"""
        )
        assert "TL-TRACE" not in _rules_of(kept)


class TestFilePragma:
    def test_docstring_pragma_suppresses_rule_file_wide(self):
        kept, suppressed = analyze_source(
            '"""Fixture module.\n\n# tracelint: disable-file=TL-PRINT — CLI surface\n"""\n'
            "def f():\n    print('a')\n    print('b')\n",
            "classification/fixture.py",
        )
        assert "TL-PRINT" not in _rules_of(kept)

    def test_leading_comment_pragma_counts(self):
        kept, _ = analyze_source(
            "# tracelint: disable-file=TL-PRINT\nimport sys\n\ndef f():\n    print('a')\n",
            "classification/fixture.py",
        )
        assert "TL-PRINT" not in _rules_of(kept)

    def test_disable_file_all(self):
        kept, _ = analyze_source(
            '"""Doc.\n\n# tracelint: disable-file=all\n"""\nimport jax\n\n'
            "def f(x):\n    print(jax.lax.psum(x, 'r'))\n",
            "classification/fixture.py",
        )
        assert kept == []

    def test_pragma_after_docstring_region_ignored(self):
        """A disable-file pragma buried mid-module must NOT waive the rule."""
        kept, _ = analyze_source(
            '"""Doc."""\n\ndef f():\n    # tracelint: disable-file=TL-PRINT\n    print("a")\n',
            "classification/fixture.py",
        )
        assert "TL-PRINT" in _rules_of(kept)

    def test_other_rules_unaffected(self):
        kept, _ = analyze_source(
            '"""Doc.\n\n# tracelint: disable-file=TL-COLLECTIVE\n"""\n'
            "def f():\n    print('a')\n",
            "classification/fixture.py",
        )
        assert "TL-PRINT" in _rules_of(kept)


# ---------------------------------------------------------------------------
# TL-DECL: declarations vs the abstract interpreter's verdict
# ---------------------------------------------------------------------------

class TestDeclRule:
    def test_stale_true_declaration_flags(self):
        """Seeded mutant (acceptance): declared True, statically fusible."""
        kept, _ = _check(
            """
class M(Metric):
    __jit_unsafe__ = True
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds):
        self.total = self.total + jnp.sum(preds)
    def _compute(self):
        return self.total
"""
        )
        assert "TL-DECL" in _rules_of(kept)

    def test_contradicted_false_declaration_flags(self):
        """Seeded mutant (acceptance, reverse direction): declared False,
        host-sync in the update."""
        kept, _ = _check(
            """
class M(Metric):
    __jit_unsafe__ = False
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds):
        self.total = self.total + float(jnp.sum(preds))
    def _compute(self):
        return self.total
"""
        )
        assert "TL-DECL" in _rules_of(kept)

    def test_false_with_data_dependent_shape_flags(self):
        kept, _ = _check(
            """
class M(Metric):
    __jit_unsafe__ = False
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds):
        kept_vals = preds[preds > 0]
        self.total = self.total + jnp.sum(kept_vals)
    def _compute(self):
        return self.total
"""
        )
        assert "TL-DECL" in _rules_of(kept)

    def test_true_with_genuine_host_sync_passes(self):
        kept, _ = _check(
            """
class M(Metric):
    __jit_unsafe__ = True
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds):
        self.total = self.total + float(np.asarray(preds).sum())
    def _compute(self):
        return self.total
"""
        )
        assert "TL-DECL" not in _rules_of(kept)

    def test_false_with_cat_state_passes(self):
        """cat-growth never contradicts False: list states are excluded
        from fusion by the runtime list check, not the declaration."""
        kept, _ = _check(
            """
class M(Metric):
    __jit_unsafe__ = False
    def __init__(self):
        super().__init__()
        self.add_state("preds", default=[], dist_reduce_fx="cat")
    def _update(self, preds):
        self.preds.append(preds)
    def _compute(self):
        return jnp.concatenate(self.preds)
"""
        )
        assert "TL-DECL" not in _rules_of(kept)

    def test_unknown_verdict_never_fires(self):
        """An unresolved helper call blocks the fusible verdict, so a True
        declaration cannot be proven stale."""
        kept, _ = _check(
            """
from somewhere_external import mystery_kernel
class M(Metric):
    __jit_unsafe__ = True
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds):
        self.total = self.total + mystery_kernel(preds)
    def _compute(self):
        return self.total
"""
        )
        assert "TL-DECL" not in _rules_of(kept)

    def test_undeclared_never_fires(self):
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds):
        self.total = self.total + jnp.sum(preds)
    def _compute(self):
        return self.total
"""
        )
        assert "TL-DECL" not in _rules_of(kept)


# ---------------------------------------------------------------------------
# TL-FLOW: state-lifecycle dataflow
# ---------------------------------------------------------------------------

class TestFlowRule:
    def test_sum_state_overwrite_flags(self):
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds):
        self.total = jnp.sum(preds)
    def _compute(self):
        return self.total
"""
        )
        assert "TL-FLOW" in _rules_of(kept)

    def test_sum_state_extremum_update_flags(self):
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds):
        self.total = jnp.maximum(self.total, jnp.max(preds))
    def _compute(self):
        return self.total
"""
        )
        assert "TL-FLOW" in _rules_of(kept)

    def test_sum_state_imul_flags(self):
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(1.0), dist_reduce_fx="sum")
    def _update(self, preds):
        self.total *= jnp.prod(preds)
    def _compute(self):
        return self.total
"""
        )
        assert "TL-FLOW" in _rules_of(kept)

    def test_max_state_additive_update_flags(self):
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("peak", default=jnp.asarray(0.0), dist_reduce_fx="max")
    def _update(self, preds):
        self.peak = self.peak + jnp.max(preds)
    def _compute(self):
        return self.peak
"""
        )
        assert "TL-FLOW" in _rules_of(kept)

    def test_reset_override_missing_leaf_flags(self):
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("a", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("b", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds):
        self.a = self.a + jnp.sum(preds)
        self.b = self.b + jnp.max(preds)
    def reset(self):
        self.a = jnp.asarray(0.0)
    def _compute(self):
        return self.a / self.b
"""
        )
        assert "TL-FLOW" in _rules_of(kept)

    def test_dead_leaf_flags(self):
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("ghost", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds):
        self.total = self.total + jnp.sum(preds)
    def _compute(self):
        return self.total
"""
        )
        assert "TL-FLOW" in _rules_of(kept)

    def test_clean_lifecycle_passes(self):
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("peak", default=jnp.asarray(0.0), dist_reduce_fx="max")
    def _update(self, preds):
        self.total = self.total + jnp.sum(preds)
        self.peak = jnp.maximum(self.peak, jnp.max(preds))
    def reset(self):
        super().reset()
    def _compute(self):
        return self.total / self.peak
"""
        )
        assert "TL-FLOW" not in _rules_of(kept)

    # -- windowed reducers (ISSUE 12): decayed-sum and ring-rotation writes

    def test_decayed_write_into_decay_state_passes(self):
        """The decay idiom: prior value SCALED before the delta lands."""
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="decay")
    def _update(self, preds):
        self.total = 0.99 * self.total + jnp.sum(preds)
    def _compute(self):
        return self.total
"""
        )
        assert "TL-FLOW" not in _rules_of(kept)

    def test_plain_additive_write_into_decay_state_flags(self):
        """An unscaled addition never decays — the leaf silently degrades
        to an all-of-time sum while consumers read it as a window."""
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="decay")
    def _update(self, preds):
        self.total = self.total + jnp.sum(preds)
    def _compute(self):
        return self.total
"""
        )
        assert "TL-FLOW" in _rules_of(kept)

    def test_augassign_into_decay_state_flags(self):
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="decay")
    def _update(self, preds):
        self.total += jnp.sum(preds)
    def _compute(self):
        return self.total
"""
        )
        assert "TL-FLOW" in _rules_of(kept)

    def test_decay_state_overwrite_flags(self):
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="decay")
    def _update(self, preds):
        self.total = jnp.sum(preds)
    def _compute(self):
        return self.total
"""
        )
        assert "TL-FLOW" in _rules_of(kept)

    def test_ring_rotation_set_into_ring_state_passes(self):
        """The ring idiom: one slot read, combined, written back with
        `.at[slot].set` — reducer-consistent rotation."""
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("rows", default=jnp.zeros((8, 4)), dist_reduce_fx="ring")
        self.add_state("clock", default=jnp.asarray(0), dist_reduce_fx="max")
    def _update(self, preds):
        slot = self.clock % 8
        self.rows = self.rows.at[slot].set(self.rows[slot] + preds)
        self.clock = jnp.maximum(self.clock, self.clock + 1)
    def _compute(self):
        return jnp.sum(self.rows, axis=0)
"""
        )
        assert "TL-FLOW" not in _rules_of(kept)

    def test_whole_leaf_additive_into_ring_state_flags(self):
        """Pouring the batch into every bucket's row ignores rotation:
        expired buckets never evict and every window over-counts."""
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("rows", default=jnp.zeros((8, 4)), dist_reduce_fx="ring")
    def _update(self, preds):
        self.rows = self.rows + preds
    def _compute(self):
        return jnp.sum(self.rows, axis=0)
"""
        )
        assert "TL-FLOW" in _rules_of(kept)

    def test_augassign_into_ring_state_flags(self):
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("rows", default=jnp.zeros((8, 4)), dist_reduce_fx="ring")
    def _update(self, preds):
        self.rows += preds
    def _compute(self):
        return jnp.sum(self.rows, axis=0)
"""
        )
        assert "TL-FLOW" in _rules_of(kept)

    def test_ring_state_overwrite_flags(self):
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("rows", default=jnp.zeros((8, 4)), dist_reduce_fx="ring")
    def _update(self, preds):
        self.rows = jnp.broadcast_to(preds, (8, 4))
    def _compute(self):
        return jnp.sum(self.rows, axis=0)
"""
        )
        assert "TL-FLOW" in _rules_of(kept)

    def test_where_guarded_sum_write_passes(self):
        """RHS mentioning the leaf (jnp.where blend) is accumulation the
        rule cannot refute — no finding."""
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds, mask):
        self.total = jnp.where(jnp.any(mask), self.total + jnp.sum(preds), self.total)
    def _compute(self):
        return self.total
"""
        )
        assert "TL-FLOW" not in _rules_of(kept)

    def test_conditional_reducer_skipped(self):
        """StatScores idiom: a variable reducer has no checkable contract."""
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self, samplewise):
        super().__init__()
        fx = "cat" if samplewise else "sum"
        self.add_state("tp", default=jnp.zeros(3), dist_reduce_fx=fx)
    def _update(self, preds):
        self.tp = jnp.sum(preds)
    def _compute(self):
        return self.tp
"""
        )
        assert "TL-FLOW" not in _rules_of(kept)

    def test_segment_sum_scatter_into_sum_state_passes(self):
        """The sliced subsystem's canonical write: per-row deltas
        segment-summed into a slice axis, combined additively — reducer-
        consistent, no finding (and no pragma in metrics_tpu/sliced/)."""
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("totals", default=jnp.zeros(16), dist_reduce_fx="sum")
    def _update(self, slice_ids, vals):
        self.totals = self.totals + jax.ops.segment_sum(vals, slice_ids, num_segments=16)
    def _compute(self):
        return self.totals
"""
        )
        assert "TL-FLOW" not in _rules_of(kept)

    def test_segment_max_folded_into_max_state_passes(self):
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("peaks", default=jnp.zeros(16), dist_reduce_fx="max")
    def _update(self, slice_ids, vals):
        self.peaks = jnp.maximum(self.peaks, jax.ops.segment_max(vals, slice_ids, num_segments=16))
    def _compute(self):
        return self.peaks
"""
        )
        assert "TL-FLOW" not in _rules_of(kept)

    def test_scatter_extremum_into_sum_state_flags(self):
        """`.at[ids].max(...)` reads the prior value syntactically, so the
        plain overwrite check cannot see it — the scatter-extremum check
        must: scattered extrema are not additive across ranks."""
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("totals", default=jnp.zeros(16), dist_reduce_fx="sum")
    def _update(self, slice_ids, vals):
        self.totals = self.totals.at[slice_ids].max(vals)
    def _compute(self):
        return self.totals
"""
        )
        assert "TL-FLOW" in _rules_of(kept)

    def test_scatter_extremum_into_matching_state_passes(self):
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("peaks", default=jnp.zeros(16), dist_reduce_fx="max")
    def _update(self, slice_ids, vals):
        self.peaks = self.peaks.at[slice_ids].max(vals)
    def _compute(self):
        return self.peaks
"""
        )
        assert "TL-FLOW" not in _rules_of(kept)

    def test_scatter_extremum_mismatched_direction_flags(self):
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("peaks", default=jnp.zeros(16), dist_reduce_fx="max")
    def _update(self, slice_ids, vals):
        self.peaks = self.peaks.at[slice_ids].min(vals)
    def _compute(self):
        return self.peaks
"""
        )
        assert "TL-FLOW" in _rules_of(kept)

    def test_scatter_add_into_max_state_flags(self):
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("peaks", default=jnp.zeros(16), dist_reduce_fx="max")
    def _update(self, slice_ids, vals):
        self.peaks = self.peaks.at[slice_ids].add(vals)
    def _compute(self):
        return self.peaks
"""
        )
        assert "TL-FLOW" in _rules_of(kept)

    def test_summed_segment_extremum_into_sum_state_flags(self):
        """`self.x + segment_max(...)` reads the prior value, so the plain
        overwrite check passes it — but the accumulated quantity is an
        extremum, not additive across ranks."""
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("totals", default=jnp.zeros(16), dist_reduce_fx="sum")
    def _update(self, slice_ids, vals):
        self.totals = self.totals + jax.ops.segment_max(vals, slice_ids, num_segments=16)
    def _compute(self):
        return self.totals
"""
        )
        assert "TL-FLOW" in _rules_of(kept)


# ---------------------------------------------------------------------------
# the abstract interpreter's verdicts (interp.py) — fixture-level checks
# ---------------------------------------------------------------------------

class TestInterpVerdicts:
    def _verdict(self, source, relpath="classification/fixture.py"):
        import ast as _ast

        from metrics_tpu.analysis.engine import FileContext
        from metrics_tpu.analysis.interp import Project, classify

        ctx = FileContext(None, relpath, _METRIC_PREAMBLE + source)
        project = Project()
        node = next(n for n in ctx.tree.body if isinstance(n, _ast.ClassDef))
        verdict, _ = classify(project, ctx, node)
        return verdict

    def test_pure_additive_update_is_fusible(self):
        v = self._verdict(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds):
        self.total = self.total + jnp.sum(preds)
    def _compute(self):
        return self.total
"""
        )
        assert v.status == "fusible"

    def test_list_state_is_cat_growth(self):
        v = self._verdict(
            """
class M(Metric):
    __jit_unsafe__ = True
    def __init__(self):
        super().__init__()
        self.add_state("preds", default=[], dist_reduce_fx="cat")
    def _update(self, preds):
        self.preds.append(preds)
    def _compute(self):
        return jnp.concatenate(self.preds)
"""
        )
        assert (v.status, v.reason) == ("unsafe", "cat-growth")

    def test_item_call_is_host_sync(self):
        v = self._verdict(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds):
        self.total = self.total + jnp.sum(preds).item()
    def _compute(self):
        return self.total
"""
        )
        assert (v.status, v.reason) == ("unsafe", "host-sync")

    def test_jnp_unique_is_data_dependent_shape(self):
        v = self._verdict(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds):
        classes = jnp.unique(preds)
        self.total = self.total + classes.shape[0]
    def _compute(self):
        return self.total
"""
        )
        assert (v.status, v.reason) == ("unsafe", "data-dependent-shape")

    def test_string_annotation_is_host_sync(self):
        v = self._verdict(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds: str):
        self.total = self.total + len(preds)
    def _compute(self):
        return self.total
"""
        )
        assert (v.status, v.reason) == ("unsafe", "host-sync")

    def test_unresolved_call_is_unknown(self):
        v = self._verdict(
            """
from nowhere import helper
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds):
        self.total = self.total + helper(preds)
    def _compute(self):
        return self.total
"""
        )
        assert v.status == "unknown"

    def test_cross_file_functional_resolution(self):
        """The real interprocedural case: an update calling into
        metrics_tpu/functional/ resolves and stays fusible."""
        v = self._verdict(
            """
from metrics_tpu.functional.classification.confusion_matrix import _confusion_matrix_update
class M(Metric):
    def __init__(self, num_classes: int):
        super().__init__()
        self.num_classes = num_classes
        self.add_state("confmat", default=jnp.zeros((3, 3), dtype=jnp.int32), dist_reduce_fx="sum")
    def _update(self, preds, target):
        self.confmat = self.confmat + _confusion_matrix_update(preds, target, self.num_classes)
    def _compute(self):
        return self.confmat
"""
        )
        assert v.status == "fusible"

    def test_concrete_guard_exempts(self):
        v = self._verdict(
            """
from metrics_tpu.utils.checks import _is_concrete
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds):
        if _is_concrete(preds):
            if float(jnp.max(preds)) > 1e6:
                raise ValueError("suspicious magnitude")
        self.total = self.total + jnp.sum(preds)
    def _compute(self):
        return self.total
"""
        )
        assert v.status == "fusible"

    def test_state_shape_symbols_recorded(self):
        import ast as _ast

        from metrics_tpu.analysis.engine import FileContext
        from metrics_tpu.analysis.interp import Project, classify

        ctx = FileContext(
            None,
            "classification/fixture.py",
            _METRIC_PREAMBLE
            + """
class M(Metric):
    def __init__(self, num_classes: int):
        super().__init__()
        self.add_state("confmat", default=jnp.zeros((num_classes, num_classes), dtype=jnp.int32), dist_reduce_fx="sum")
    def _update(self, preds):
        self.confmat = self.confmat + preds
    def _compute(self):
        return self.confmat
""",
        )
        node = next(n for n in ctx.tree.body if isinstance(n, _ast.ClassDef))
        _, facts = classify(Project(), ctx, node)
        entry = next(e for e in facts.entries if e.name == "confmat")
        assert entry.container == "array"
        assert entry.shape == ["num_classes", "num_classes"]
        assert entry.dtype == "int32"
        assert entry.dist_reduce_fx == "sum"


# ---------------------------------------------------------------------------
# review fixes: scope-sensitivity, two-step accumulation, child resets
# ---------------------------------------------------------------------------

class TestReviewRegressions:
    def test_function_local_rebind_does_not_exempt_module_numpy(self):
        """A local `np = jnp` shadow inside one helper must not re-alias
        np file-wide and suppress host-pull detection elsewhere."""
        kept, _ = _check(
            """
def unrelated_helper(x):
    np = jnp  # local shadow
    return np.sum(x)

class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds):
        host = np.asarray(preds)
        self.total = self.total + host.sum()
    def _compute(self):
        return self.total
"""
        )
        assert "TL-TRACE" in _rules_of(kept)

    def test_two_step_additive_accumulation_passes(self):
        """`new = self.total + x; self.total = new` reads the prior value —
        not an overwrite."""
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds):
        new_total = self.total + jnp.sum(preds)
        self.total = new_total
    def _compute(self):
        return self.total
"""
        )
        assert "TL-FLOW" not in _rules_of(kept)

    def test_child_only_reset_still_flags_missing_leaves(self):
        """`child.reset()` is not `super().reset()`: own leaves must still
        be restored."""
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self, child):
        super().__init__()
        self.child = child
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds):
        self.total = self.total + jnp.sum(preds)
    def reset(self):
        self.child.reset()
    def _compute(self):
        return self.total
"""
        )
        assert "TL-FLOW" in _rules_of(kept)

    def test_base_class_reset_with_self_counts(self):
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds):
        self.total = self.total + jnp.sum(preds)
    def reset(self):
        Metric.reset(self)
    def _compute(self):
        return self.total
"""
        )
        assert "TL-FLOW" not in _rules_of(kept)


# ---------------------------------------------------------------------------
# sketch-state teaching (ISSUE 10): "merge" reducers, exact-mode split,
# fixed-size nonzero, tuple-return taint
# ---------------------------------------------------------------------------


class TestMergeReducerFlow:
    _SKETCH_PREAMBLE = """
from metrics_tpu.sketches.quantile import qsketch_init, qsketch_insert, sketch_merge_fx
"""

    def test_merge_string_reducer_is_known(self):
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("sk", default=jnp.zeros((64, 3)), dist_reduce_fx="merge")
    def _update(self, preds):
        self.sk = self.sk.at[0, 0].add(jnp.sum(preds) * 0 + 1)
    def _compute(self):
        return jnp.sum(self.sk)
"""
        )
        # no "unknown dist_reduce_fx" complaint for the merge string
        assert not any("unknown dist_reduce_fx" in v.message for v in kept)

    def test_merge_leaf_insert_transform_passes(self):
        kept, _ = _check(
            self._SKETCH_PREAMBLE
            + """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("sk", default=qsketch_init(64, payload_cols=0), dist_reduce_fx=sketch_merge_fx())
    def _update(self, preds):
        self.sk = qsketch_insert(self.sk, preds)
    def _compute(self):
        return jnp.sum(self.sk)
"""
        )
        assert "TL-FLOW" not in _rules_of(kept)

    def test_merge_leaf_additive_write_flags(self):
        kept, _ = _check(
            self._SKETCH_PREAMBLE
            + """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("sk", default=qsketch_init(64, payload_cols=0), dist_reduce_fx=sketch_merge_fx())
    def _update(self, preds):
        self.sk = self.sk + jnp.sum(preds)
    def _compute(self):
        return jnp.sum(self.sk)
"""
        )
        assert "TL-FLOW" in _rules_of(kept)
        assert any("not element-wise summable" in v.message for v in kept)

    def test_merge_leaf_overwrite_flags(self):
        kept, _ = _check(
            self._SKETCH_PREAMBLE
            + """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("sk", default=qsketch_init(64, payload_cols=0), dist_reduce_fx=sketch_merge_fx())
    def _update(self, preds):
        self.sk = qsketch_insert(qsketch_init(64, payload_cols=0), preds)
    def _compute(self):
        return jnp.sum(self.sk)
"""
        )
        assert "TL-FLOW" in _rules_of(kept)
        assert any("without reading its prior value" in v.message for v in kept)


class TestSketchInterpTeaching:
    def _verdict(self, source, relpath="classification/fixture.py"):
        import ast as _ast

        from metrics_tpu.analysis.engine import FileContext
        from metrics_tpu.analysis.interp import Project, classify

        ctx = FileContext(None, relpath, _METRIC_PREAMBLE + source)
        project = Project()
        node = next(
            n for n in ctx.tree.body if isinstance(n, _ast.ClassDef) and n.name == "M"
        )
        verdict, _ = classify(project, ctx, node)
        return verdict

    def test_exact_mode_split_default_mode_is_fusible(self):
        """The __exact_mode_attr__ contract: the exact branch's list appends
        belong to the runtime-guarded opt-in mode, so the class verdict
        describes the (fusible) sketch default."""
        v = self._verdict(
            """
from metrics_tpu.sketches.quantile import qsketch_init, qsketch_insert, sketch_merge_fx

class M(Metric):
    __exact_mode_attr__ = "_exact"
    def __init__(self, exact=False):
        super().__init__()
        self._exact = exact
        self.add_state("sk", default=qsketch_init(64, payload_cols=0), dist_reduce_fx=sketch_merge_fx())
    def _update(self, preds):
        if self._exact:
            self.preds.append(preds)
        else:
            self.sk = qsketch_insert(self.sk, preds)
    def _compute(self):
        return jnp.sum(self.sk)
"""
        )
        assert v.status == "fusible", (v.status, v.reason, v.detail)

    def test_same_split_without_declaration_is_not_fusible(self):
        v = self._verdict(
            """
from metrics_tpu.sketches.quantile import qsketch_init, qsketch_insert, sketch_merge_fx

class M(Metric):
    def __init__(self, exact=False):
        super().__init__()
        self._exact = exact
        self.add_state("sk", default=qsketch_init(64, payload_cols=0), dist_reduce_fx=sketch_merge_fx())
    def _update(self, preds):
        if self._exact:
            self.preds.append(preds)
        else:
            self.sk = qsketch_insert(self.sk, preds)
    def _compute(self):
        return jnp.sum(self.sk)
"""
        )
        assert v.status != "fusible", v.status

    def test_fixed_size_nonzero_is_fusible(self):
        v = self._verdict(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("buf", default=jnp.zeros((64,)), dist_reduce_fx="sum")
    def _update(self, preds):
        idx = jnp.nonzero(preds > 0, size=8, fill_value=64)[0]
        self.buf = self.buf.at[idx].add(1.0)
    def _compute(self):
        return jnp.sum(self.buf)
"""
        )
        assert v.status == "fusible", (v.status, v.reason, v.detail)

    def test_dynamic_nonzero_still_unsafe(self):
        v = self._verdict(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("buf", default=jnp.zeros((64,)), dist_reduce_fx="sum")
    def _update(self, preds):
        idx = jnp.nonzero(preds > 0)[0]
        self.buf = self.buf.at[idx].add(1.0)
    def _compute(self):
        return jnp.sum(self.buf)
"""
        )
        assert v.status == "unsafe" and v.reason == "data-dependent-shape", v

    def test_tuple_return_keeps_host_mode_element_untainted(self):
        """Element-wise tuple taint: a canonicalizer returning
        (traced, traced, host_enum) must not taint the mode its caller
        branches on."""
        v = self._verdict(
            """
def _canon(preds, target):
    mode = "binary" if preds.ndim == 1 else "cols"
    return preds, target, mode

class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds, target):
        preds, target, mode = _canon(preds, target)
        if mode == "binary":
            self.total = self.total + jnp.sum(preds)
        else:
            self.total = self.total + jnp.sum(preds[:, 0])
    def _compute(self):
        return self.total
"""
        )
        assert v.status == "fusible", (v.status, v.reason, v.detail)

    def test_converted_curve_metrics_are_fusible_in_manifest(self):
        """The acceptance pin: the sketch-converted classes carry fusible
        verdicts in the COMMITTED manifest (KID stays unsafe: its feature
        extractor is an arbitrary host callable)."""
        import json
        from pathlib import Path

        manifest = json.loads(Path("scripts/fusibility_manifest.json").read_text())
        metrics = manifest["metrics"]
        fusible = {
            "classification/auroc.py::AUROC",
            "classification/roc.py::ROC",
            "classification/precision_recall_curve.py::PrecisionRecallCurve",
            "classification/avg_precision.py::AveragePrecision",
            "classification/calibration_error.py::CalibrationError",
            "regression/spearman.py::SpearmanCorrCoef",
            "regression/cosine_similarity.py::CosineSimilarity",
        }
        for key in fusible:
            assert metrics[key]["verdict"] == "fusible", (key, metrics[key]["verdict"])
        kid = metrics["image/kid.py::KernelInceptionDistance"]
        # since the lazy-reservoir refactor (round 19) the interpreter stops
        # at the unresolved `add_state`-inside-`_update` call before reaching
        # the host-sync evidence; the declared __jit_unsafe__=True keeps KID
        # off the fused path either way
        assert kid["verdict"] == "unknown" and kid["reason"] is None
        # sketch leaves serialize their merge reducer
        assert metrics["classification/auroc.py::AUROC"]["states"]["csketch"]["dist_reduce_fx"] == "merge"


# ---------------------------------------------------------------------------
# retrieval-table teaching (ISSUE 15): the scatter-into-table write shape
# ---------------------------------------------------------------------------


class TestRetrievalTableFlow:
    """TL-FLOW fixtures for the new scatter-into-table write shape: the
    table leaf is a ``"merge"`` (tagged ``retrieval_table_merge_fx``)
    packed structure, so the ONLY consistent accumulation is the
    insert-into-prior transform — exactly the qsketch contract, pinned
    here for the retrieval spelling."""

    _PREAMBLE = """
from metrics_tpu.retrieval.table import (
    retrieval_table_init, retrieval_table_insert, retrieval_table_merge_fx,
)
"""

    def test_table_insert_into_prior_passes(self):
        kept, _ = _check(
            self._PREAMBLE
            + """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("qtable", default=retrieval_table_init(64, 16), dist_reduce_fx=retrieval_table_merge_fx())
    def _update(self, preds, target, indexes):
        self.qtable = retrieval_table_insert(self.qtable, indexes, preds, target)
    def _compute(self):
        return jnp.sum(self.qtable)
"""
        )
        assert "TL-FLOW" not in _rules_of(kept)

    def test_table_additive_write_flags(self):
        kept, _ = _check(
            self._PREAMBLE
            + """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("qtable", default=retrieval_table_init(64, 16), dist_reduce_fx=retrieval_table_merge_fx())
    def _update(self, preds, target, indexes):
        self.qtable = self.qtable + jnp.sum(preds)
    def _compute(self):
        return jnp.sum(self.qtable)
"""
        )
        assert "TL-FLOW" in _rules_of(kept)
        assert any("not element-wise summable" in v.message for v in kept)

    def test_table_overwrite_without_prior_flags(self):
        kept, _ = _check(
            self._PREAMBLE
            + """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("qtable", default=retrieval_table_init(64, 16), dist_reduce_fx=retrieval_table_merge_fx())
    def _update(self, preds, target, indexes):
        self.qtable = retrieval_table_insert(retrieval_table_init(64, 16), indexes, preds, target)
    def _compute(self):
        return jnp.sum(self.qtable)
"""
        )
        assert "TL-FLOW" in _rules_of(kept)
        assert any("without reading its prior value" in v.message for v in kept)


class TestRetrievalTableInterpTeaching:
    def test_retrieval_family_fusible_in_committed_manifest(self):
        """The ISSUE 15 acceptance pin: all 9 retrieval classes carry
        fusible verdicts in the COMMITTED manifest, with the table leaf's
        merge reducer serialized per leaf (fusible count 23 -> >= 32)."""
        import json
        from pathlib import Path

        manifest = json.loads(Path("scripts/fusibility_manifest.json").read_text())
        metrics = manifest["metrics"]
        family = [k for k in metrics if k.startswith("retrieval/")]
        assert len(family) == 9
        for key in family:
            assert metrics[key]["verdict"] == "fusible", (key, metrics[key]["verdict"])
            assert metrics[key]["states"]["qtable"]["dist_reduce_fx"] == "merge", key
        fusible_count = sum(1 for v in metrics.values() if v["verdict"] == "fusible")
        assert fusible_count >= 32, fusible_count


class TestMomentsFlow:
    """TL-FLOW fixtures for the streaming-moment reducer
    (``moments_merge_fx``): the leaves are element-wise summable
    sufficient statistics, so the full ``"sum"`` write contract applies —
    additive accumulation passes, overwrites and extrema flag."""

    _PREAMBLE = """
from metrics_tpu.sketches.moments import moments_merge_fx
"""

    def test_moments_additive_write_passes(self):
        kept, _ = _check(
            self._PREAMBLE
            + """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("prob_sum", default=jnp.zeros((10, 8)), dist_reduce_fx=moments_merge_fx())
    def _update(self, preds):
        self.prob_sum = self.prob_sum + jnp.sum(preds, axis=0)
    def _compute(self):
        return jnp.sum(self.prob_sum)
"""
        )
        assert "TL-FLOW" not in _rules_of(kept)

    def test_moments_overwrite_without_prior_flags(self):
        kept, _ = _check(
            self._PREAMBLE
            + """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("prob_sum", default=jnp.zeros((10, 8)), dist_reduce_fx=moments_merge_fx())
    def _update(self, preds):
        self.prob_sum = jnp.sum(preds, axis=0)
    def _compute(self):
        return jnp.sum(self.prob_sum)
"""
        )
        assert "TL-FLOW" in _rules_of(kept)
        assert any("without reading its prior value" in v.message for v in kept)

    def test_moments_extremum_write_flags(self):
        kept, _ = _check(
            self._PREAMBLE
            + """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("prob_sum", default=jnp.zeros((10, 8)), dist_reduce_fx=moments_merge_fx())
    def _update(self, preds):
        self.prob_sum = jnp.maximum(self.prob_sum, jnp.sum(preds, axis=0))
    def _compute(self):
        return jnp.sum(self.prob_sum)
"""
        )
        assert "TL-FLOW" in _rules_of(kept)
        assert any("extremum" in v.message for v in kept)


class TestImageDetectionInterpTeaching:
    """Interp fixtures for the ISSUE 19 teachings: declared traced-callable
    attributes (``__traced_callable_attrs__``), bare ``bool``/``int``
    static-parameter annotations, the ``detection_table_init`` packed-state
    ctor, and the ``moments`` reducer."""

    def _verdict(self, source, relpath="classification/fixture.py"):
        import ast as _ast

        from metrics_tpu.analysis.engine import FileContext
        from metrics_tpu.analysis.interp import Project, classify

        ctx = FileContext(None, relpath, _METRIC_PREAMBLE + source)
        project = Project()
        node = next(
            n for n in ctx.tree.body if isinstance(n, _ast.ClassDef) and n.name == "M"
        )
        verdict, _ = classify(project, ctx, node)
        return verdict

    def test_declared_traced_callable_attr_is_fusible(self):
        v = self._verdict(
            """
class M(Metric):
    __traced_callable_attrs__ = ("inception",)
    def __init__(self, feature_extractor):
        super().__init__()
        self.inception = feature_extractor
        self.add_state("feat_sum", default=jnp.zeros((16,)), dist_reduce_fx="sum")
    def _update(self, imgs):
        feats = self.inception(imgs)
        self.feat_sum = self.feat_sum + jnp.sum(feats, axis=0)
    def _compute(self):
        return jnp.sum(self.feat_sum)
"""
        )
        assert v.status == "fusible", (v.status, v.reason, v.detail)

    def test_undeclared_callable_attr_is_unknown(self):
        v = self._verdict(
            """
class M(Metric):
    def __init__(self, feature_extractor):
        super().__init__()
        self.inception = feature_extractor
        self.add_state("feat_sum", default=jnp.zeros((16,)), dist_reduce_fx="sum")
    def _update(self, imgs):
        feats = self.inception(imgs)
        self.feat_sum = self.feat_sum + jnp.sum(feats, axis=0)
    def _compute(self):
        return jnp.sum(self.feat_sum)
"""
        )
        assert v.status == "unknown", (v.status, v.reason, v.detail)

    def test_bool_annotated_param_branch_is_fusible(self):
        """A bare ``bool`` annotation declares a Python-static knob: under
        the fused dispatcher non-array leaves never become tracers, so
        branching on it is shape selection, not a traced-value host sync."""
        v = self._verdict(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("real_sum", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("fake_sum", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, imgs, real: bool):
        if real:
            self.real_sum = self.real_sum + jnp.sum(imgs)
        else:
            self.fake_sum = self.fake_sum + jnp.sum(imgs)
    def _compute(self):
        return self.real_sum - self.fake_sum
"""
        )
        assert v.status == "fusible", (v.status, v.reason, v.detail)

    def test_unannotated_flag_branch_is_host_sync(self):
        """Without the annotation the flag is a traced input and branching
        on it is a concretization host sync."""
        v = self._verdict(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("real_sum", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("fake_sum", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, imgs, real):
        if real:
            self.real_sum = self.real_sum + jnp.sum(imgs)
        else:
            self.fake_sum = self.fake_sum + jnp.sum(imgs)
    def _compute(self):
        return self.real_sum - self.fake_sum
"""
        )
        assert v.status != "fusible", (v.status, v.reason, v.detail)

    def test_optional_int_annotation_stays_traced(self):
        """Only the BARE annotation opts out: ``Optional[int]`` keeps the
        parameter traced (it may arrive as an array)."""
        from metrics_tpu.analysis.interp import _static_annotated_params
        import ast as _ast

        fn = _ast.parse(
            "def _update(self, a: bool, b: int, c: Optional[int], d: str, e): pass"
        ).body[0]
        assert _static_annotated_params(fn) == {"a", "b"}

    def test_detection_table_insert_is_fusible(self):
        v = self._verdict(
            """
from metrics_tpu.sketches.reservoir import (
    detection_table_init, reservoir_insert, reservoir_merge_fx,
)

class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("table", default=detection_table_init(64, 32), dist_reduce_fx=reservoir_merge_fx())
        self.add_state("images_seen", default=jnp.asarray(0, dtype=jnp.int32), dist_reduce_fx="sum")
    def _update(self, rows):
        self.table = reservoir_insert(self.table, rows, seen=self.images_seen, seed=7)
        self.images_seen = self.images_seen + rows.shape[0]
    def _compute(self):
        return jnp.sum(self.table)
"""
        )
        assert v.status == "fusible", (v.status, v.reason, v.detail)

    def test_image_detection_families_fusible_in_committed_manifest(self):
        """The ISSUE 19 acceptance pin: FID, IS, and mAP carry fusible
        verdicts in the COMMITTED manifest (fusible count 32 -> >= 35),
        with the new reducer kinds serialized per leaf."""
        import json
        from pathlib import Path

        manifest = json.loads(Path("scripts/fusibility_manifest.json").read_text())
        metrics = manifest["metrics"]
        for key, leaf, reducer in (
            ("image/fid.py::FrechetInceptionDistance", "real_feat_sum", "sum"),
            ("image/inception.py::InceptionScore", "prob_sum", "moments"),
            ("detection/mean_ap.py::MeanAveragePrecision", "table", "merge"),
        ):
            assert metrics[key]["verdict"] == "fusible", (key, metrics[key]["verdict"])
            assert metrics[key]["states"][leaf]["dist_reduce_fx"] == reducer, key
        # KID deliberately stays off the fused path: the lazy width-discovery
        # `add_state` inside `_update` is an unresolved call the interpreter
        # refuses to bless (verdict unknown), and the class declares
        # __jit_unsafe__=True on top (docs/differences.md)
        kid = metrics["image/kid.py::KernelInceptionDistance"]
        assert kid["verdict"] == "unknown" and kid["declared_jit_unsafe"] is True
        fusible_count = sum(1 for v in metrics.values() if v["verdict"] == "fusible")
        assert fusible_count >= 35, fusible_count


# ---------------------------------------------------------------------------
# GitHub reporter (--format=github workflow commands)
# ---------------------------------------------------------------------------

class TestGithubReporter:
    def test_error_annotation_shape(self):
        kept, _ = _check(
            """
def f():
    print("a")
"""
        )
        out = render_github(kept, [])
        line = out.splitlines()[0]
        assert line.startswith("::error file=metrics_tpu/classification/fixture.py,line=")
        assert ",col=" in line and ",title=tracelint TL-PRINT::" in line

    def test_baselined_become_warnings_and_newlines_escape(self):
        kept, _ = _check(
            """
def f():
    print("a")
"""
        )
        out = render_github([], kept)
        assert out.splitlines()[0].startswith("::warning file=")
        # messages must be %0A-escaped, never raw newlines after `::`
        assert "\n" not in out.splitlines()[0]

    def test_empty_renders_empty(self):
        assert render_github([], []) == ""

    def test_cli_format_github(self, tmp_path, capsys):
        src = tmp_path / "mod.py"
        src.write_text("print('x')\n")
        rc = cli_main([str(src), "--format=github", "--no-baseline"])
        out = capsys.readouterr().out
        assert rc == 1
        assert "::error file=" in out


# ---------------------------------------------------------------------------
# TL-SHARD
# ---------------------------------------------------------------------------

_SPEC_PREAMBLE = """
from jax.sharding import NamedSharding, PartitionSpec
"""


def _shard_check(source, relpath="sliced/fixture.py"):
    kept, suppressed = analyze_source(
        _SPEC_PREAMBLE + source, relpath, rules=get_rules(["TL-SHARD"])
    )
    return kept, suppressed


class TestShardRule:
    def test_unconditional_dictcomp_over_defaults_flags(self):
        """The PR 8 mutant: every leaf claimed sharded with no divisibility
        guard — the leaves the fallback leaves replicated would silently
        skip their required reduction."""
        kept, _ = _shard_check(
            """
def sliced_partition_specs(m, axis_name):
    return {name: PartitionSpec(axis_name) for name in m._defaults}
"""
        )
        assert _rules_of(kept) == {"TL-SHARD"}
        assert "unconditionally" in kept[0].message

    def test_guarded_dictcomp_passes(self):
        kept, _ = _shard_check(
            """
def sliced_partition_specs(m, axis_name, shardable):
    return {
        name: (PartitionSpec(axis_name) if shardable(name) else PartitionSpec())
        for name in m._defaults
    }
"""
        )
        assert not kept

    def test_helper_routed_dictcomp_passes(self):
        """Routing through a helper call keeps the divisibility authority
        with the helper — no static claim to audit."""
        kept, _ = _shard_check(
            """
def shard_sliced_states(m, mesh):
    return {name: get_naive_slice_sharding(v, mesh) for name, v in m._defaults.items()}
"""
        )
        assert not kept

    def test_spec_dict_claiming_replicated_leaf_flags(self):
        kept, _ = _shard_check(
            """
SPECS = {"total": PartitionSpec("slices")}
"""
        )
        assert _rules_of(kept) == {"TL-SHARD"}
        assert "`total`" in kept[0].message

    def test_spec_dict_on_slice_rows_passes(self):
        kept, _ = _shard_check(
            """
SPECS = {"_slice_rows": PartitionSpec("slices"), "total": PartitionSpec()}
"""
        )
        assert not kept

    def test_rule_set_missing_catchall_flags(self):
        kept, _ = _shard_check(
            """
import re
RULES = (
    (f"{re.escape(SLICE_ROWS)}$", PartitionSpec("slices")),
)
"""
        )
        assert _rules_of(kept) == {"TL-SHARD"}
        assert "unmatched" in kept[0].message

    def test_named_axis_catchall_flags_replicated_first_match(self):
        kept, _ = _shard_check(
            """
RULES = (
    (".*", PartitionSpec("slices")),
)
"""
        )
        assert any("cross-rank reduction" in v.message for v in kept)

    def test_scoped_rule_set_with_replicate_catchall_passes(self):
        kept, _ = _shard_check(
            """
import re
RULES = (
    (f"{re.escape(SLICE_ROWS)}$", PartitionSpec("slices")),
    (".*", PartitionSpec()),
)
"""
        )
        assert not kept


# ---------------------------------------------------------------------------
# TL-MERGE
# ---------------------------------------------------------------------------

def _merge_check(source, relpath="windowed/fixture.py"):
    kept, suppressed = analyze_source(
        _METRIC_PREAMBLE + source, relpath, rules=get_rules(["TL-MERGE"])
    )
    return kept, suppressed


class TestMergeRuleStatic:
    def test_noncommutative_fold_step_flags(self):
        kept, _ = _merge_check(
            """
class TopKMerge:
    merge_like = True
    def __call__(self, stacked):
        out = stacked[0]
        for i in range(1, 4):
            out = out - stacked[i]
        return out
"""
        )
        assert _rules_of(kept) == {"TL-MERGE"}
        assert "non-commutative" in kept[0].message

    def test_commutative_fold_passes(self):
        kept, _ = _merge_check(
            """
class SumMerge:
    merge_like = True
    def __call__(self, stacked):
        out = stacked[0]
        for i in range(1, 4):
            out = out + stacked[i]
        return out
"""
        )
        assert not kept

    def test_untagged_class_is_out_of_scope(self):
        """Plain callables (not merge_like-tagged) may do whatever they
        like — the collector never folds through them."""
        kept, _ = _merge_check(
            """
class PlainDelta:
    def __call__(self, stacked):
        return stacked[0] - stacked[1]
"""
        )
        assert not kept

    def test_host_state_and_instance_mutation_flag(self):
        kept, _ = _merge_check(
            """
import time
class StampMerge:
    merge_like = True
    def __call__(self, stacked):
        self.last = time.time()
        return jnp.sum(stacked, axis=0)
"""
        )
        assert len(kept) == 2 and _rules_of(kept) == {"TL-MERGE"}
        messages = " ".join(v.message for v in kept)
        assert "host state" in messages and "mutates" in messages

    def test_ring_full_reduce_flags(self):
        kept, _ = _merge_check(
            """
class RingMerge:
    merge_like = True
    windowed_kind = "ring"
    def __call__(self, stacked):
        return jnp.sum(stacked)
"""
        )
        assert _rules_of(kept) == {"TL-MERGE"}
        assert "slot-aligned" in kept[0].message

    def test_ring_slot_aligned_reduce_passes(self):
        kept, _ = _merge_check(
            """
class RingMerge:
    merge_like = True
    windowed_kind = "ring"
    def __call__(self, stacked):
        return jnp.sum(stacked, axis=0)
"""
        )
        assert not kept

    def test_ring_flatten_flags(self):
        kept, _ = _merge_check(
            """
class RingMerge:
    merge_like = True
    windowed_kind = "ring"
    def __call__(self, stacked):
        rows = stacked.ravel()
        return jnp.sort(rows)
"""
        )
        assert any("time-bucket" in v.message for v in kept)

    def test_shipped_merge_reducers_are_clean(self):
        """Every merge_like reducer actually shipped must satisfy its own
        rule — the arrival-order contract is pinned dynamically in
        test_fleet_collector, statically here."""
        root = default_package_root()
        for rel in (
            "sketches/reservoir.py",
            "sketches/quantile.py",
            "windowed/reducers.py",
            "retrieval/table.py",
        ):
            kept, _ = analyze_source(
                (root / rel).read_text(), rel, rules=get_rules(["TL-MERGE"])
            )
            assert not kept, (rel, [v.message for v in kept])


# ---------------------------------------------------------------------------
# TL-WIRE
# ---------------------------------------------------------------------------

def _wire_check(source, relpath="classification/fixture.py"):
    kept, suppressed = analyze_source(
        _METRIC_PREAMBLE + source, relpath, rules=get_rules(["TL-WIRE"])
    )
    return kept, suppressed


class TestWireRule:
    def test_untagged_callable_reducer_flags(self):
        kept, _ = _wire_check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("acc", jnp.zeros(()), lambda a, b: a + b)
"""
        )
        assert _rules_of(kept) == {"TL-WIRE"}
        assert "untagged callable reducer" in kept[0].message

    def test_constructor_parameterized_reducer_passes(self):
        """BaseAggregator's pattern: the caller picks the fold, add_state
        validates it at registration — runtime keeps authority."""
        kept, _ = _wire_check(
            """
class M(Metric):
    def __init__(self, fn):
        super().__init__()
        self.add_state("acc", jnp.zeros(()), fn)
"""
        )
        assert not kept

    def test_string_reducer_passes(self):
        kept, _ = _wire_check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("acc", jnp.zeros(()), "sum")
"""
        )
        assert not kept

    def test_wire_opaque_default_flags(self):
        kept, _ = _wire_check(
            """
OPAQUE = object()
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("blob", OPAQUE, "sum")
"""
        )
        assert _rules_of(kept) == {"TL-WIRE"}
        assert "wire-opaque" in kept[0].message

    def test_locally_derived_default_passes(self):
        kept, _ = _wire_check(
            """
class M(Metric):
    def __init__(self, exact):
        super().__init__()
        default = jnp.zeros((4,)) if exact else jnp.zeros((2,))
        self.add_state("v", default, "sum")
"""
        )
        assert not kept

    def test_mixed_modes_without_escape_hatch_flag(self):
        kept, _ = _wire_check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("count", jnp.zeros(()), "sum")
        self.add_state("items", [], "cat")
"""
        )
        assert any("__exact_mode_attr__" in v.message for v in kept)

    def test_mixed_modes_with_exact_attr_pass(self):
        kept, _ = _wire_check(
            """
class M(Metric):
    __exact_mode_attr__ = "exact"
    def __init__(self, exact=False):
        super().__init__()
        self.exact = exact
        self.add_state("count", jnp.zeros(()), "sum")
        if exact:
            self.add_state("items", [], "cat")
"""
        )
        assert not any("__exact_mode_attr__" in v.message for v in kept)


# ---------------------------------------------------------------------------
# TL-LOCK
# ---------------------------------------------------------------------------

def _lock_check(source, relpath="core/pipeline.py"):
    kept, suppressed = analyze_source(source, relpath, rules=get_rules(["TL-LOCK"]))
    return kept, suppressed


class TestLockRule:
    BAD = """
class AsyncUpdateHandle:
    def stats(self):
        return self._pending
"""

    def test_unlocked_read_flags(self):
        kept, _ = _lock_check(self.BAD)
        assert _rules_of(kept) == {"TL-LOCK"}
        assert "_pending" in kept[0].message and "_cond" in kept[0].message

    def test_locked_read_and_exempt_contexts_pass(self):
        kept, _ = _lock_check(
            """
class AsyncUpdateHandle:
    def __init__(self):
        self._pending = 0
    def stats(self):
        with self._cond:
            return self._pending
    def _drain_locked(self):
        return self._pending
"""
        )
        assert not kept

    def test_closure_inherits_lexical_lock_scope(self):
        kept, _ = _lock_check(
            """
class AsyncUpdateHandle:
    def stats(self):
        with self._cond:
            def read():
                return self._pending
            return read()
"""
        )
        assert not kept

    def test_registry_is_path_scoped(self):
        """The same access pattern outside the registered files is not the
        rule's business — unregistered classes own their own discipline."""
        kept, _ = _lock_check(self.BAD, relpath="classification/fixture.py")
        assert not kept

    def test_collector_registry_fields(self):
        kept, _ = _lock_check(
            """
class FleetCollector:
    def errors(self):
        return self.fold_errors
""",
            relpath="observability/collector.py",
        )
        assert _rules_of(kept) == {"TL-LOCK"}
        assert "_lock" in kept[0].message
