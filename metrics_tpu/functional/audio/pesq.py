"""Functional PESQ.

Parity surface with /root/reference/torchmetrics/functional/audio/pesq.py
(which validates fs/mode and loops the external ``pesq`` binding over the
batch); here the scorer is the in-repo P.862 engine
(:mod:`metrics_tpu.functional.audio._pesq_engine`) and no external package is
required. A custom ``pesq_fn(ref, deg, fs, mode) -> float`` can still be
injected (e.g. the ``pesq`` C binding for bit-exact ITU conformance).
"""
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.functional.audio._pesq_engine import pesq as _engine_pesq
from metrics_tpu.utils.imports import _PESQ_AVAILABLE

Array = jax.Array

__all__ = ["perceptual_evaluation_speech_quality"]


def _default_pesq_fn() -> Callable:
    """Scorer used when no ``pesq_fn`` is injected: the external ``pesq`` C
    binding when installed (bit-exact ITU-T conformance, and what the
    reference wraps — torchmetrics/functional/audio/pesq.py), otherwise the
    in-repo P.862 engine so the metric computes with zero dependencies."""
    if _PESQ_AVAILABLE:
        from pesq import pesq as pesq_backend

        return lambda ref, deg, fs, mode: pesq_backend(fs, ref, deg, mode)
    return _engine_pesq


def perceptual_evaluation_speech_quality(
    preds: Array,
    target: Array,
    fs: int,
    mode: str,
    pesq_fn: Optional[Callable] = None,
) -> Array:
    """PESQ MOS-LQO per utterance (host-side P.862 DSP, batch preserved).

    Args:
        preds: degraded speech ``[..., time]``.
        target: clean reference speech, same shape.
        fs: sampling frequency — 8000 (narrow-band) or 16000.
        mode: ``"nb"`` or ``"wb"`` (wide-band requires fs=16000).
        pesq_fn: optional scorer override ``(ref, deg, fs, mode) -> float``.

    Returns:
        Array of MOS-LQO scores with shape ``preds.shape[:-1]``.
    """
    # validate unconditionally (the default engine re-checks, but a custom
    # scorer must not silently receive an invalid fs/mode combination)
    if fs not in (8000, 16000):
        raise ValueError(f"Expected argument `fs` to either be 8000 or 16000 but got {fs}")
    if mode not in ("nb", "wb"):
        raise ValueError(f"Expected argument `mode` to either be 'wb' or 'nb' but got {mode}")
    if mode == "wb" and fs == 8000:
        raise ValueError("Wide-band PESQ ('wb') requires fs=16000")
    scorer = pesq_fn or _default_pesq_fn()
    preds_np = np.asarray(preds, np.float64)
    target_np = np.asarray(target, np.float64)
    if preds_np.shape != target_np.shape:
        raise ValueError(
            f"preds and target must have the same shape, got {preds_np.shape} and {target_np.shape}"
        )
    batch_shape = preds_np.shape[:-1]
    preds_np = preds_np.reshape(-1, preds_np.shape[-1])
    target_np = target_np.reshape(-1, target_np.shape[-1])
    scores = np.array(
        [scorer(ref, deg, fs, mode) for ref, deg in zip(target_np, preds_np)], np.float32
    )
    return jnp.asarray(scores.reshape(batch_shape) if batch_shape else scores[0])
