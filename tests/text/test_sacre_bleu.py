"""SacreBLEUScore parity vs the sacrebleu package (the reference's own
oracle, /root/reference/tests/text/test_sacre_bleu.py:25-39)."""
from functools import partial

import pytest

sacrebleu_metrics = pytest.importorskip("sacrebleu.metrics")

from metrics_tpu.functional.text.sacre_bleu import sacre_bleu_score
from metrics_tpu.text.sacre_bleu import SacreBLEUScore
from tests.text.helpers import TextTester
from tests.text.inputs import _inputs_multiple_references

TOKENIZERS = ("none", "13a", "zh", "intl", "char")


def _sacrebleu_oracle(preds, targets, tokenize, lowercase):
    oracle = sacrebleu_metrics.BLEU(tokenize=tokenize, lowercase=lowercase)
    # sacrebleu wants targets transposed: one stream per reference position
    targets_t = [[target[i] for target in targets] for i in range(len(targets[0]))]
    return oracle.corpus_score(preds, targets_t).score / 100


@pytest.mark.parametrize("lowercase", [False, True])
@pytest.mark.parametrize("tokenize", TOKENIZERS)
class TestSacreBLEUScore(TextTester):
    def test_sacre_bleu_class(self, tokenize, lowercase):
        self.run_class_metric_test(
            preds=_inputs_multiple_references.preds,
            targets=_inputs_multiple_references.targets,
            metric_class=SacreBLEUScore,
            sk_metric=partial(_sacrebleu_oracle, tokenize=tokenize, lowercase=lowercase),
            metric_args={"tokenize": tokenize, "lowercase": lowercase},
        )

    def test_sacre_bleu_functional(self, tokenize, lowercase):
        self.run_functional_metric_test(
            preds=_inputs_multiple_references.preds,
            targets=_inputs_multiple_references.targets,
            metric_functional=sacre_bleu_score,
            sk_metric=partial(_sacrebleu_oracle, tokenize=tokenize, lowercase=lowercase),
            metric_args={"tokenize": tokenize, "lowercase": lowercase},
        )


def test_unknown_tokenizer_raises():
    with pytest.raises(ValueError, match="Argument `tokenize`"):
        SacreBLEUScore(tokenize="not-a-tokenizer")
