"""Retrieval mean reciprocal rank.

Behavior parity with /root/reference/torchmetrics/functional/retrieval/
reciprocal_rank.py:20-52.
"""
import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _check_retrieval_functional_inputs

Array = jax.Array


def retrieval_reciprocal_rank(preds: Array, target: Array) -> Array:
    """Reciprocal rank of the first relevant document.

    Example:
        >>> import jax.numpy as jnp
        >>> retrieval_reciprocal_rank(jnp.array([0.2, 0.3, 0.5]), jnp.array([False, True, False]))
        Array(0.5, dtype=float32)
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)
    if not jnp.sum(target):
        return jnp.asarray(0.0, dtype=preds.dtype)

    target = target[jnp.argsort(-preds, axis=-1)]
    position = jnp.nonzero(target)[0]
    return jnp.asarray(1.0 / (position[0] + 1.0), dtype=preds.dtype)
