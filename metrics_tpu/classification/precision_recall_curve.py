"""Modular PrecisionRecallCurve (cat-state, exact sorted mode).

Behavior parity with /root/reference/torchmetrics/classification/
precision_recall_curve.py:28-145.
"""
from typing import Any, List, Optional, Tuple, Union

import jax

from metrics_tpu.classification._capacity import CapacityCurveMixin
from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.exact_curve import (
    binary_precision_recall_curve_fixed,
    multiclass_precision_recall_curve_fixed,
)
from metrics_tpu.functional.classification.precision_recall_curve import (
    _precision_recall_curve_compute,
    _precision_recall_curve_update,
)
from metrics_tpu.utils.data import dim_zero_cat

Array = jax.Array


class PrecisionRecallCurve(CapacityCurveMixin, Metric):
    """Computes precision-recall pairs for different thresholds.

    Example:
        >>> import jax.numpy as jnp
        >>> pred = jnp.array([0., 1., 2., 3.])
        >>> target = jnp.array([0, 1, 1, 0])
        >>> pr_curve = PrecisionRecallCurve(pos_label=1)
        >>> precision, recall, thresholds = pr_curve(pred, target)
        >>> precision
        Array([0.6666667, 0.5      , 0.       , 1.       ], dtype=float32)
    """

    __jit_unsafe__ = True  # exact curve mode has data-dependent output shapes
    is_differentiable = False

    def __init__(
        self,
        num_classes: Optional[int] = None,
        pos_label: Optional[int] = None,
        capacity: Optional[int] = None,
        multilabel: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.pos_label = pos_label
        # TPU-native exact mode: static [capacity] buffers, fully jit-safe.
        # Binary keeps the flat triple; num_classes >= 2 keeps [capacity, C]
        # score rows (one-vs-rest curves per class); `multilabel=True`
        # additionally stores [capacity, C] indicator targets.
        self._init_capacity_case(capacity, num_classes, multilabel)
        if capacity is None:
            self.add_state("preds", default=[], dist_reduce_fx="cat")
            self.add_state("target", default=[], dist_reduce_fx="cat")

    def _update(self, preds: Array, target: Array) -> None:
        if self._capacity is not None:
            self._capacity_update(preds, target, pos_label=self.pos_label)
            return
        preds, target, num_classes, pos_label = _precision_recall_curve_update(
            preds, target, self.num_classes, self.pos_label
        )
        self.preds.append(preds)
        self.target.append(target)
        self.num_classes = num_classes
        self.pos_label = pos_label

    def _compute(
        self,
    ) -> Union[
        Tuple[Array, Array, Array],
        Tuple[List[Array], List[Array], List[Array]],
        # capacity mode: (precision, recall, thresholds, point_mask, last_point)
        Tuple[Array, Array, Array, Array, Array],
    ]:
        if self._capacity is not None:
            # static-shape output: (precision, recall, thresholds, point_mask,
            # last_point); see exact_curve.binary_precision_recall_curve_fixed.
            # Multiclass/multilabel rows are per-class one-vs-rest curves.
            if self._capacity_cols is not None:
                return multiclass_precision_recall_curve_fixed(
                    *self._capacity_buffers_2d(),
                    self.num_classes,
                    multilabel=self._capacity_multilabel,
                )
            return binary_precision_recall_curve_fixed(*self._capacity_buffers())
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _precision_recall_curve_compute(preds, target, self.num_classes, self.pos_label)
