"""Reference-parity sweep for the audio domain's deterministic metrics.

Breadth parity with /root/reference/tests/audio/test_{snr,sdr,si_sdr,
si_snr,pit}.py: SNR / SI-SNR / SDR / SI-SDR / PIT against the reference
implementation (deterministic DSP — unlike the resampled BootStrapper,
exact value parity is expected) over multi-speaker batches, argument axes
(zero_mean, use_cg_iter, PIT eval functions), and shape/validation edges.
STOI has its own independent numpy oracle (test_stoi_pesq.py) and PESQ its
P.862 engine tests (test_pesq_engine.py).
"""
import numpy as np
import pytest

import jax.numpy as jnp

from metrics_tpu.audio import (
    PermutationInvariantTraining,
    ScaleInvariantSignalDistortionRatio,
    ScaleInvariantSignalNoiseRatio,
    SignalDistortionRatio,
    SignalNoiseRatio,
)
from metrics_tpu.functional.audio import (
    permutation_invariant_training,
    scale_invariant_signal_distortion_ratio,
    scale_invariant_signal_noise_ratio,
    signal_distortion_ratio,
    signal_noise_ratio,
)
from tests.helpers.reference import load_reference_module

torch = pytest.importorskip("torch")

_rng = np.random.default_rng(23)
T = 1000
BATCHES = 3
# degraded = scaled clean + noise, so the ratios are non-degenerate
CLEAN = _rng.standard_normal((BATCHES, 4, T)).astype(np.float32)
DEG = (0.8 * CLEAN + 0.2 * _rng.standard_normal((BATCHES, 4, T))).astype(np.float32)


def _ref_audio(attr, *args, **kwargs):
    mod = load_reference_module("torchmetrics.audio")
    return getattr(mod, attr)(*args, **kwargs)


def _ref_fn(name):
    return getattr(load_reference_module("torchmetrics.functional"), name)


@pytest.mark.parametrize(
    "cls, name, kwargs",
    [
        (SignalNoiseRatio, "SignalNoiseRatio", {"zero_mean": False}),
        (SignalNoiseRatio, "SignalNoiseRatio", {"zero_mean": True}),
        (ScaleInvariantSignalNoiseRatio, "ScaleInvariantSignalNoiseRatio", {}),
    ],
    ids=["snr", "snr-zero_mean", "si_snr"],
)
def test_snr_family_reference_parity(cls, name, kwargs):
    ours = cls(**kwargs)
    ref = _ref_audio(name, **kwargs)
    for i in range(BATCHES):
        ours.update(jnp.asarray(DEG[i]), jnp.asarray(CLEAN[i]))
        ref.update(torch.as_tensor(DEG[i]), torch.as_tensor(CLEAN[i]))
    np.testing.assert_allclose(float(ours.compute()), float(ref.compute()), rtol=1e-4)


@pytest.mark.parametrize("zero_mean", [False, True])
def test_si_sdr_reference_parity(zero_mean):
    ours = ScaleInvariantSignalDistortionRatio(zero_mean=zero_mean)
    ref = _ref_audio("ScaleInvariantSignalDistortionRatio", zero_mean=zero_mean)
    for i in range(BATCHES):
        ours.update(jnp.asarray(DEG[i]), jnp.asarray(CLEAN[i]))
        ref.update(torch.as_tensor(DEG[i]), torch.as_tensor(CLEAN[i]))
    np.testing.assert_allclose(float(ours.compute()), float(ref.compute()), rtol=1e-4)


@pytest.mark.parametrize("use_cg_iter", [None, 10])
def test_sdr_reference_parity(use_cg_iter):
    """Full BSS-eval SDR (Toeplitz distortion-filter solve) vs the reference
    (which delegates to fast_bss_eval); the direct-solve and CG paths must
    agree with it to DSP tolerance."""
    pytest.importorskip("fast_bss_eval")
    ours = SignalDistortionRatio(use_cg_iter=use_cg_iter)
    ref = _ref_audio("SignalDistortionRatio", use_cg_iter=use_cg_iter)
    for i in range(BATCHES):
        ours.update(jnp.asarray(DEG[i]), jnp.asarray(CLEAN[i]))
        ref.update(torch.as_tensor(DEG[i]), torch.as_tensor(CLEAN[i]))
    np.testing.assert_allclose(float(ours.compute()), float(ref.compute()), rtol=1e-3)


def test_sdr_functional_self_consistency():
    """Functional SDR on identical signals is near the clean ceiling, and
    degradation strictly lowers it (oracle-free invariants that hold even
    where fast_bss_eval is absent)."""
    clean = jnp.asarray(CLEAN[0])
    same = float(jnp.mean(signal_distortion_ratio(clean, clean)))
    worse = float(jnp.mean(signal_distortion_ratio(jnp.asarray(DEG[0]), clean)))
    assert same > 30.0
    assert worse < same


@pytest.mark.parametrize("eval_func", ["max", "min"])
@pytest.mark.parametrize("n_spk", [2, 3])
def test_pit_reference_parity(eval_func, n_spk):
    """PIT over permuted speakers matches the reference exactly (same metric
    function on both sides: SI-SDR; the permutation search is exhaustive on
    both for small speaker counts)."""
    ref_tm_fn = _ref_fn("scale_invariant_signal_distortion_ratio")
    perm = _rng.permutation(n_spk)
    clean = CLEAN[0][:n_spk]
    est = DEG[0][perm]  # speaker-permuted estimates

    ours = PermutationInvariantTraining(
        scale_invariant_signal_distortion_ratio, eval_func=eval_func
    )
    ref = _ref_audio(
        "PermutationInvariantTraining", ref_tm_fn, eval_func=eval_func
    )
    ours.update(jnp.asarray(est)[None], jnp.asarray(clean)[None])
    ref.update(torch.as_tensor(est)[None], torch.as_tensor(clean)[None])
    np.testing.assert_allclose(float(ours.compute()), float(ref.compute()), rtol=1e-4)

    # the functional also returns the best permutation — same one the
    # reference functional finds
    if eval_func == "max":
        vals, best = permutation_invariant_training(
            jnp.asarray(est)[None], jnp.asarray(clean)[None],
            scale_invariant_signal_noise_ratio, eval_func="max",
        )
        ref_pit = _ref_fn("permutation_invariant_training")
        _, ref_best = ref_pit(
            torch.as_tensor(est)[None], torch.as_tensor(clean)[None],
            _ref_fn("scale_invariant_signal_noise_ratio"), eval_func="max",
        )
        np.testing.assert_array_equal(np.asarray(best)[0], ref_best[0].numpy())


def test_pit_validation_matches_reference():
    m = PermutationInvariantTraining(scale_invariant_signal_noise_ratio)
    with pytest.raises(RuntimeError, match="speaker"):
        m.update(jnp.zeros((2, 3, 10)), jnp.zeros((2, 4, 10)))  # speaker mismatch
    with pytest.raises(ValueError):
        PermutationInvariantTraining(scale_invariant_signal_noise_ratio, eval_func="bad")


def test_snr_functional_batch_shape_preserved():
    out = signal_noise_ratio(jnp.asarray(DEG[0]), jnp.asarray(CLEAN[0]))
    assert out.shape == (4,)
    out_si = scale_invariant_signal_noise_ratio(jnp.asarray(DEG[0]), jnp.asarray(CLEAN[0]))
    assert out_si.shape == (4,)


def test_si_sdr_known_value_reference_pair():
    """The reference docstring's canonical SI-SDR example value."""
    target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
    preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
    val = float(scale_invariant_signal_distortion_ratio(preds, target))
    ref_fn = _ref_fn("scale_invariant_signal_distortion_ratio")
    want = float(ref_fn(torch.tensor([2.5, 0.0, 2.0, 8.0]), torch.tensor([3.0, -0.5, 2.0, 7.0])))
    np.testing.assert_allclose(val, want, rtol=1e-5)
