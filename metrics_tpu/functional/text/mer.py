"""Match Error Rate (parity: /root/reference/torchmetrics/functional/text/mer.py)."""
from typing import List, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.helper import _edit_distance

Array = jax.Array


def _mer_update(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Tuple[Array, Array]:
    """Sum edit ops and max(len(ref), len(pred)) word counts (mer.py:23-49)."""
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [target]
    errors = 0
    total = 0
    for pred, tgt in zip(preds, target):
        pred_tokens = pred.split()
        tgt_tokens = tgt.split()
        errors += _edit_distance(pred_tokens, tgt_tokens)
        total += max(len(tgt_tokens), len(pred_tokens))
    return jnp.asarray(errors, jnp.float32), jnp.asarray(total, jnp.float32)


def _mer_compute(errors: Array, total: Array) -> Array:
    return errors / total


def match_error_rate(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Array:
    """Match error rate of transcription(s); 0 is perfect.

    Example:
        >>> preds = ["this is the prediction", "there is an other sample"]
        >>> target = ["this is the reference", "there is another one"]
        >>> match_error_rate(preds=preds, target=target)
        Array(0.44444445, dtype=float32)
    """
    errors, total = _mer_update(preds, target)
    return _mer_compute(errors, total)
