"""Modular MeanAbsoluteError.

Behavior parity with /root/reference/torchmetrics/regression/mae.py:23-80.
"""
from typing import Any

import jax
import jax.numpy as jnp

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.regression.mae import _mean_absolute_error_compute, _mean_absolute_error_update

Array = jax.Array


class MeanAbsoluteError(Metric):
    """Computes mean absolute error.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> mean_absolute_error = MeanAbsoluteError()
        >>> mean_absolute_error(preds, target)
        Array(0.5, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_abs_error", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")

    def _update(self, preds: Array, target: Array) -> None:
        sum_abs_error, n_obs = _mean_absolute_error_update(preds, target)
        self.sum_abs_error = self.sum_abs_error + sum_abs_error
        self.total = self.total + n_obs

    def _compute(self) -> Array:
        return _mean_absolute_error_compute(self.sum_abs_error, self.total)
