"""Extended Edit Distance (EED).

Behavior parity with /root/reference/torchmetrics/functional/text/eed.py
(436 LoC; itself following rwth-i6/ExtendedEditDistance): a character-level
CDER-grid DP with an extra "long jump" operation at blanks, a coverage
penalty for re-visited positions, language-specific preprocessing (en/ja),
and per-sentence best-reference selection averaged over the corpus.

The DP deliberately uses plain Python floats in the reference's evaluation
order: the relaxation accumulates ``+ deletion`` sequentially, and 0.2 is not
exactly representable, so a re-associated vectorized form could flip argmin
ties and diverge from the reference on edge cases.

Host-side string processing feeding scalar device states (SURVEY §2.7).
"""
import re
import unicodedata
from math import inf
from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.helper import _validate_inputs

Array = jax.Array


def _eed_function(
    hyp: str,
    ref: str,
    alpha: float = 2.0,
    rho: float = 0.3,
    deletion: float = 0.2,
    insertion: float = 1.0,
) -> float:
    """Sentence-level EED via the CDER alignment grid with long jumps.

    ``alpha`` is the jump penalty, ``rho`` the coverage cost for re-visited
    hypothesis positions, ``deletion``/``insertion`` the character edit
    costs (substitution shares the 0/1 word-distance with identity).
    """
    width = len(hyp) + 1
    visit_count = [-1] * width

    row = [1.0] * width
    row[0] = 0.0  # CDER initialisation: (0, 0) = 0, rest 1
    for w in range(1, len(ref) + 1):
        ref_char = ref[w - 1]
        next_row = [inf] * width
        next_row[0] = row[0] + 1.0
        for i in range(1, width):
            next_row[i] = min(
                next_row[i - 1] + deletion,
                row[i - 1] + (0 if hyp[i - 1] == ref_char else 1),
                row[i] + insertion,
            )

        min_index = next_row.index(min(next_row))
        visit_count[min_index] += 1

        if ref_char == " ":  # long jump from the best position
            jump = alpha + next_row[min_index]
            next_row = [min(x, jump) for x in next_row]

        row = next_row

    coverage = rho * sum(x if x >= 0 else 1 for x in visit_count)
    return min(1, (row[-1] + coverage) / (float(len(ref)) + coverage))


_ABBREVIATION_RE = re.compile(r"(Dr|Jr|Prof|Rev|Gen|Mr|Mt|Mrs|Ms) .")
_NUMBER_RE = re.compile(r"(\d) ([.,]) (\d)")
_SPACES_RE = re.compile(r"\s+")


def _preprocess_en(sentence: str) -> str:
    """English preprocessing (rwth-i6 EED util.py recipe): space out
    punctuation, then re-join numbers and known abbreviations, pad ends."""
    if not isinstance(sentence, str):
        raise ValueError(f"Only strings allowed during preprocessing step, found {type(sentence)} instead")
    sentence = sentence.rstrip()
    for mark in (".", "!", "?", ","):
        sentence = sentence.replace(mark, f" {mark}")
    sentence = _SPACES_RE.sub(" ", sentence)
    sentence = _NUMBER_RE.sub(r"\1\2\3", sentence)
    sentence = _ABBREVIATION_RE.sub(r"\1.", sentence)
    for spaced, joined in (("e . g .", "e.g."), ("i . e .", "i.e."), ("U . S .", "U.S.")):
        sentence = sentence.replace(spaced, joined)
    return f" {sentence} "


def _preprocess_ja(sentence: str) -> str:
    """Japanese preprocessing: NFKC normalization only."""
    if not isinstance(sentence, str):
        raise ValueError(f"Only strings allowed during preprocessing step, found {type(sentence)} instead")
    return unicodedata.normalize("NFKC", sentence.rstrip())


def _eed_update(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    language: str = "en",
    alpha: float = 2.0,
    rho: float = 0.3,
    deletion: float = 0.2,
    insertion: float = 1.0,
) -> List[float]:
    """Sentence-level best-reference EED scores for a batch."""
    target, preds = _validate_inputs(target, preds)

    if language == "en":
        preprocess = _preprocess_en
    elif language == "ja":
        preprocess = _preprocess_ja
    else:
        raise ValueError(f"Expected argument `language` to either be `en` or `ja` but got {language}")

    preds = [preprocess(pred) for pred in preds]
    target = [[preprocess(ref) for ref in refs] for refs in target]

    if 0 in (len(preds), len(target[0])):
        return []

    scores: List[float] = []
    for hypothesis, references in zip(preds, target):
        best = inf
        for reference in references:
            score = _eed_function(hypothesis, reference, alpha, rho, deletion, insertion)
            if score < best:
                best = score
        scores.append(best)
    return scores


def _eed_compute(sentence_level_scores: List[float]) -> Array:
    if not sentence_level_scores:
        return jnp.asarray(0.0, jnp.float32)
    return jnp.asarray(sum(sentence_level_scores) / len(sentence_level_scores), jnp.float32)


def extended_edit_distance(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    language: str = "en",
    return_sentence_level_score: bool = False,
    alpha: float = 2.0,
    rho: float = 0.3,
    deletion: float = 0.2,
    insertion: float = 1.0,
) -> Union[Array, Tuple[Array, Array]]:
    """Corpus-level Extended Edit Distance.

    Example:
        >>> preds = ["this is the prediction", "here is an other sample"]
        >>> target = ["this is the reference", "here is another one"]
        >>> float(extended_edit_distance(preds=preds, target=target))  # doctest: +ELLIPSIS
        0.3077...
    """
    for param_name, param in zip(["alpha", "rho", "deletion", "insertion"], [alpha, rho, deletion, insertion]):
        if not isinstance(param, float) or param < 0:
            raise ValueError(f"Parameter `{param_name}` is expected to be a non-negative float.")

    sentence_level_scores = _eed_update(preds, target, language, alpha, rho, deletion, insertion)
    average = _eed_compute(sentence_level_scores)
    if return_sentence_level_score:
        return average, jnp.asarray(sentence_level_scores, jnp.float32)
    return average
