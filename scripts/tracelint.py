#!/usr/bin/env python
"""tracelint CLI — static analysis of metrics_tpu's trace-safety, state,
recompile, collective, and print conventions.

Thin launcher over ``metrics_tpu/analysis/`` that loads the (stdlib-only)
analysis package WITHOUT importing the jax-heavy parent package, so a lint
run starts instantly and works on machines with no accelerator stack.
``python -m metrics_tpu.analysis`` is the equivalent in-package entry point.

    python scripts/tracelint.py                  # lint the package vs baseline
    python scripts/tracelint.py --check          # CI mode (stale baseline fails)
    python scripts/tracelint.py --baseline-update
    python scripts/tracelint.py --format=json path/to/file.py
    python scripts/tracelint.py --format=github  # ::error annotations for PR diffs
    python scripts/tracelint.py --list-rules
    python scripts/tracelint.py --manifest           # regenerate BOTH manifests
                                                     # (fusibility + layout)
    python scripts/tracelint.py --manifest --check   # CI freshness gate (both)
"""
import importlib.util
import pathlib
import sys

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
_PKG_DIR = REPO_ROOT / "metrics_tpu" / "analysis"
_PKG_NAME = "metrics_tpu.analysis"


def load_analysis():
    """Import ``metrics_tpu.analysis`` standalone (no parent-package import).

    Registers a stub ``metrics_tpu`` package entry so the analysis
    package's relative imports resolve without executing the real
    ``metrics_tpu/__init__.py`` (which imports jax and every metric).
    """
    if _PKG_NAME in sys.modules:
        return sys.modules[_PKG_NAME]
    if "metrics_tpu" not in sys.modules:
        import types

        stub = types.ModuleType("metrics_tpu")
        stub.__path__ = [str(_PKG_DIR.parent)]
        sys.modules["metrics_tpu"] = stub
    spec = importlib.util.spec_from_file_location(
        _PKG_NAME,
        _PKG_DIR / "__init__.py",
        submodule_search_locations=[str(_PKG_DIR)],
    )
    module = importlib.util.module_from_spec(spec)
    sys.modules[_PKG_NAME] = module
    spec.loader.exec_module(module)
    return module


if __name__ == "__main__":
    load_analysis()
    from metrics_tpu.analysis.cli import main

    sys.exit(main())
