"""Fused per-row top-k + gather kernel vs the jnp path, plus the new
segment-extremum kernels filling the formerly jnp-only dispatch slots.

Interpret mode runs the REAL kernel bodies on CPU (the ``tests/ops/``
convention). Selection and permutation are value-exact operations, so —
unlike segment-sum — EVERY case here pins BIT-identical agreement, ties
and invalid slots included.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from metrics_tpu import ops
from metrics_tpu.ops.dispatch import choose_backend
from metrics_tpu.ops.scatter_pallas import segment_extremum_tiled
from metrics_tpu.ops.topk_pallas import _row_topk_jnp, row_topk_tiled

_rng = np.random.RandomState(0)


# ---------------------------------------------------------------------------
# row_topk
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "r,n,k",
    [(1, 2, 1), (8, 100, 5), (20, 300, 7), (65, 257, 32), (3, 16, 16), (5, 9, 20)],
)
def test_row_topk_interpret_bit_identical(r, n, k):
    """Ragged row/col counts off the tile multiples, k above and below the
    column count, heavy ties (quantized scores) — all bit-identical."""
    preds = (_rng.randint(0, 16, (r, n)) / 4.0).astype(np.float32)
    valid = (_rng.rand(r, n) < 0.7).astype(np.float32)
    payload = _rng.randint(0, 2, (r, n)).astype(np.float32)
    got = row_topk_tiled(preds, payload, valid, k, interpret=True)
    want = _row_topk_jnp(jnp.asarray(preds), jnp.asarray(payload), jnp.asarray(valid), k)
    for g, w, name in zip(got, want, ("keys", "payload", "valid")):
        assert jnp.array_equal(g, w, equal_nan=True), name


def test_row_topk_tie_break_is_stable():
    """Equal keys keep the LOWER column index first — the stable descending
    sort order — on both backends, so kernel-vs-fallback agreement holds
    even when the selection boundary lands inside a tie run."""
    preds = jnp.asarray([[1.0, 2.0, 2.0, 2.0, 0.5]], jnp.float32)
    payload = jnp.asarray([[10.0, 11.0, 12.0, 13.0, 14.0]], jnp.float32)
    valid = jnp.ones((1, 5), jnp.float32)
    for backend in ("interpret", "jnp"):
        with ops.forced_backend(backend):
            keys, pay, val = ops.row_topk_dispatch(preds, payload, valid, 2)
        assert keys.tolist() == [[2.0, 2.0]]
        assert pay.tolist() == [[11.0, 12.0]]  # first-occurrence order


def test_row_topk_invalid_slots_sort_last():
    preds = jnp.asarray([[5.0, 4.0, 3.0]], jnp.float32)
    valid = jnp.asarray([[0.0, 1.0, 1.0]], jnp.float32)  # best score invalid
    payload = jnp.asarray([[1.0, 2.0, 3.0]], jnp.float32)
    for backend in ("interpret", "jnp"):
        with ops.forced_backend(backend):
            keys, pay, val = ops.row_topk_dispatch(preds, payload, valid, 3)
        assert pay.tolist() == [[2.0, 3.0, 1.0]]
        assert val.tolist() == [[1.0, 1.0, 0.0]]
        assert keys[0, 2] == -jnp.inf


def test_row_topk_k_validation():
    with pytest.raises(ValueError, match="positive static int"):
        ops.row_topk_dispatch(jnp.ones((2, 4)), jnp.ones((2, 4)), jnp.ones((2, 4)), 0)
    with pytest.raises(ValueError, match="rows, cols"):
        ops.row_topk_dispatch(jnp.ones(4), jnp.ones(4), jnp.ones(4), 2)


def test_row_topk_route_floors(monkeypatch):
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    spec = ops.get_kernel("row_topk")
    big = (jnp.ones((256, 512), jnp.float32), jnp.ones((256, 512)), jnp.ones((256, 512)), 8)
    tiny = (jnp.ones((4, 16), jnp.float32), jnp.ones((4, 16)), jnp.ones((4, 16)), 4)
    wide = (jnp.ones((64, 1 << 12), jnp.float32),) * 3 + (8,)
    bf16 = (jnp.ones((256, 512), jnp.bfloat16), jnp.ones((256, 512)), jnp.ones((256, 512)), 8)
    assert choose_backend(spec, *big) == "pallas"
    assert choose_backend(spec, *tiny) == "jnp"  # below the size floors
    assert choose_backend(spec, *wide) == "jnp"  # past the network width cap
    assert choose_backend(spec, *bf16) == "jnp"  # f32-only route


# ---------------------------------------------------------------------------
# segment extremum kernels
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("is_max", [True, False], ids=["max", "min"])
@pytest.mark.parametrize(
    "b,d,s", [(1, 1, 1), (300, 3, 40), (512, 1, 128), (1000, 5, 257)]
)
def test_segment_extremum_interpret_bit_identical(is_max, b, d, s):
    """Extremum folds never round: parity is bit-exact for arbitrary float
    data (not just the integer window), ragged tails included."""
    ids = _rng.randint(-2, s + 3, b)  # OOB and negative ids drop
    vals = _rng.randn(b, d).astype(np.float32)
    got = segment_extremum_tiled(vals, ids, s, is_max=is_max, interpret=True)
    ref = (jax.ops.segment_max if is_max else jax.ops.segment_min)(
        jnp.asarray(vals), jnp.asarray(ids), num_segments=s
    )
    assert jnp.array_equal(got, ref)


def test_segment_extremum_1d_and_empty_segment_identity():
    vals = jnp.asarray([1.0, 5.0, -3.0], jnp.float32)
    ids = jnp.asarray([0, 0, 2])
    mx = segment_extremum_tiled(vals, ids, 4, is_max=True, interpret=True)
    assert mx.shape == (4,)
    assert mx[0] == 5.0 and mx[2] == -3.0
    assert mx[1] == -jnp.inf and mx[3] == -jnp.inf  # empty = identity
    mn = segment_extremum_tiled(vals, ids, 4, is_max=False, interpret=True)
    assert mn[0] == 1.0 and mn[1] == jnp.inf


def test_segment_extremum_nd_values_flatten_and_route_guard(monkeypatch):
    """ND max/min leaves (a SlicedMetric wrapping a 2-D extremum state)
    flatten through the 2-D kernel and restore; the route itself refuses
    ND so a direct dispatch caller can never crash the kernel on TPU."""
    vals = _rng.randn(512, 4, 4).astype(np.float32)
    ids = _rng.randint(0, 128, 512)
    want = jax.ops.segment_max(jnp.asarray(vals), jnp.asarray(ids), num_segments=128)
    with ops.forced_backend("interpret"):
        got = ops.segment_max_dispatch(vals, ids, 128)
    assert got.shape == (128, 4, 4)
    assert jnp.array_equal(got, want)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    spec = ops.get_kernel("segment_max")
    nd = (jnp.ones((512, 4, 4), jnp.float32), jnp.zeros(512, jnp.int32), 128)
    assert choose_backend(spec, *nd) == "jnp"


def test_segment_extremum_dispatch_interpret_parity():
    ids = _rng.randint(0, 50, 400)
    vals = _rng.randn(400).astype(np.float32)
    want_max = jax.ops.segment_max(jnp.asarray(vals), jnp.asarray(ids), num_segments=50)
    want_min = jax.ops.segment_min(jnp.asarray(vals), jnp.asarray(ids), num_segments=50)
    with ops.forced_backend("interpret"):
        got_max = ops.segment_max_dispatch(vals, ids, 50)
        got_min = ops.segment_min_dispatch(vals, ids, 50)
    assert jnp.array_equal(got_max, want_max)
    assert jnp.array_equal(got_min, want_min)


def test_segment_extremum_route_mirrors_sum_floors(monkeypatch):
    """ISSUE 15 satellite: the extremum kernels route behind the SAME f32
    / batch / segment floors as segment-sum (minus the 2**24 exactness cap
    an extremum doesn't need), with a tighter feature bound."""
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    for name in ("segment_max", "segment_min"):
        spec = ops.get_kernel(name)
        big = (jnp.ones((2048, 4), jnp.float32), jnp.zeros(2048, jnp.int32), 256)
        small = (jnp.ones((8, 4), jnp.float32), jnp.zeros(8, jnp.int32), 4)
        ints = (jnp.ones((2048, 4), jnp.int32), jnp.zeros(2048, jnp.int32), 256)
        bf16 = (jnp.ones((2048, 4), jnp.bfloat16), jnp.zeros(2048, jnp.int32), 256)
        wide = (jnp.ones((2048, 512), jnp.float32), jnp.zeros(2048, jnp.int32), 256)
        assert choose_backend(spec, *big) == "pallas", name
        assert choose_backend(spec, *small) == "jnp", name
        assert choose_backend(spec, *ints) == "jnp", name
        assert choose_backend(spec, *bf16) == "jnp", name
        assert choose_backend(spec, *wide) == "jnp", name  # feature bound


# ---------------------------------------------------------------------------
# composition: the retrieval table's hot paths through the kernels
# ---------------------------------------------------------------------------


def test_retrieval_table_compaction_through_interpret_kernels():
    """Doc-overflow compaction and a cross-rank merge, with every dispatch
    forced through the real kernel bodies: final tables bit-identical to
    the jnp-path run."""
    from metrics_tpu.retrieval.table import (
        retrieval_table_init,
        retrieval_table_insert,
        retrieval_table_merge,
    )

    rng = np.random.RandomState(3)
    idx = np.repeat(np.arange(6), 40)  # 40 docs into max_docs=16 -> compacts
    preds = rng.rand(240).astype(np.float32)
    target = (rng.rand(240) < 0.5).astype(np.int32)

    def run():
        t = retrieval_table_insert(retrieval_table_init(16, 16), idx, preds, target)
        other = retrieval_table_insert(
            retrieval_table_init(16, 16), idx + 3, preds[::-1].copy(), target[::-1].copy()
        )
        return retrieval_table_merge(t, other)

    plain = run()
    with ops.forced_backend("interpret"):
        kernel = run()
    assert jnp.array_equal(plain, kernel)


def test_ops_dispatch_counters_cover_new_ops():
    from metrics_tpu.observability import get_recorder

    rec = get_recorder()
    rec.enable()
    try:
        with ops.forced_backend("jnp"):
            ops.row_topk_dispatch(jnp.ones((4, 8)), jnp.ones((4, 8)), jnp.ones((4, 8)), 2)
            ops.segment_max_dispatch(jnp.ones(8), jnp.zeros(8, jnp.int32), 4)
        totals = rec.ops_dispatch_totals()
        assert totals.get("row_topk|jnp", 0) >= 1
        assert totals.get("segment_max|jnp", 0) >= 1
    finally:
        rec.disable()
        rec.reset()
