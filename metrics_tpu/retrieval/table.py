"""Fixed-capacity per-query retrieval state table (packed single leaf).

The retrieval family's cat-states (``indexes/preds/target`` lists) are the
largest remaining jit-unsafe surface: unbounded memory and permanent
exclusion from ``FusedUpdate``/``compile_update_async``. This module is the
replacement — the retrieval analog of the quantile sketch: one packed

    ``[max_queries, 7 + 2 * max_docs]`` float32

leaf where each ROW owns one query's documents and exact per-query
counters, with three pure, fixed-shape, jit-safe transforms:

* ``retrieval_table_init(max_queries, max_docs) -> leaf``
* ``retrieval_table_insert(leaf, indexes, preds, target, ...) -> leaf``
* ``retrieval_table_merge(a, b) -> leaf``  (``dist_reduce_fx`` material)

Row layout (columns)::

    0: KEY   deterministic reservoir key in (0, 1] hashed from the query
             id (0 = empty row)
    1: QHI   query id bits 24..31   (uint32 split, exact in f32)
    2: QLO   query id bits 0..23
    3: NSEEN total documents seen for this query (exact counter)
    4: POS   sum of target over ALL seen documents (exact; drives the
             empty-query policy even past doc capacity)
    5: NEG   count of ``target == 0`` documents seen (exact; FallOut's
             inverted empty policy)
    6: FILL  documents currently stored in the slot region
    7            .. 7+max_docs-1:   stored preds
    7+max_docs   .. 7+2*max_docs-1: stored targets

**Row policy — deterministic bottom-k reservoir.** Every query id hashes
to a fixed KEY; the table maintains the invariant *rows == the
``max_queries`` largest ``(KEY, -qid)`` priorities among every query ever
seen*. Because priorities are a pure function of the id, the sampled query
SET is independent of arrival order and batch chunking, a query that will
survive is admitted at first sight and never evicted (the table minimum
only rises once full), and two ranks inserting the same query agree on its
fate without sharing RNG state — ``merge`` is a pure top-``Q`` of the row
union. While distinct queries fit in ``max_queries`` nothing is sampled at
all.

**Doc policy — top-``max_docs`` truncation.** Documents append into free
slots in arrival order (the segment-scatter shape: one flat
``.at[row * cap + fill + col].set`` per leaf region). When a row's slots
would overflow, the stored + incoming documents compact to the top
``max_docs // 2`` by score through the fused top-k + gather kernel
(:mod:`metrics_tpu.ops.topk_pallas`) under a ``lax.cond`` — in-window
streams never pay the sort, mirroring the qsketch absorb contract. Beyond
capacity a query's metrics become their depth-truncated (top-``k``-pooled)
variants while NSEEN/POS/NEG stay exact, so the empty-query policy and
positive mass never degrade.

**Lossless window.** While every query holds at most ``max_docs``
documents and distinct queries fit in ``max_queries``, the table stores
the exact stream in arrival order: unpacking (:func:`retrieval_table_layout`)
reproduces ``pack_queries``'s padded layout and the compute results are
bit-identical to the cat-state path on integer-exact data. Cross-rank
merges concatenate same-query documents in rank order — the gather-concat
order — so the window extends across a mesh sync.

Everything is plain ``jnp`` (sorts, ``searchsorted`` joins, scatters,
``lax.cond``) — no host syncs, no data-dependent shapes — so retrieval
updates fuse, bucket (``n_valid`` pad masking), and mesh-sync like any
sketch-state metric.
"""
import functools
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array

#: column layout (see module docstring)
COL_KEY, COL_QHI, COL_QLO, COL_NSEEN, COL_POS, COL_NEG, COL_FILL = range(7)
#: number of metadata columns before the preds/targets slot regions
META_COLS = 7

#: finite stand-in for +/-inf so stored scores always beat the -inf empty
#: sentinel in top-k selection (real f32 data is unaffected by the clip)
_FMAX = jnp.float32(3.4e38)
_I32_MAX = jnp.int32(2**31 - 1)

#: docs absorbed per fixed-shape chunk. Chunk size does NOT affect
#: in-window results (appends are order-preserving whatever the split;
#: the overflow branch widens over the WHOLE chunk) — it only bounds the
#: transient ``[max_queries, chunk]`` overflow scratch and amortizes the
#: per-chunk join sort over more documents.
_INSERT_CHUNK = 2048


def table_capacity(table: Array) -> Tuple[int, int]:
    """``(max_queries, max_docs)`` encoded in the leaf's static shape."""
    q, c = table.shape
    if c < META_COLS + 2 or (c - META_COLS) % 2:
        raise ValueError(f"not a retrieval table leaf: shape {table.shape}")
    return q, (c - META_COLS) // 2


def retrieval_table_init(max_queries: int, max_docs: int) -> Array:
    """Fresh empty table leaf ``[max_queries, 7 + 2 * max_docs]``."""
    if not (isinstance(max_queries, int) and max_queries > 0):
        raise ValueError(f"`max_queries` must be a positive int, got {max_queries!r}")
    if not (isinstance(max_docs, int) and max_docs >= 2):
        raise ValueError(f"`max_docs` must be an int >= 2, got {max_docs!r}")
    return jnp.zeros((max_queries, META_COLS + 2 * max_docs), jnp.float32)


def _retain(max_docs: int) -> int:
    """Docs kept per row by an overflow compaction (top-k by score)."""
    return max(1, max_docs // 2)


def _qid_key(qid: Array) -> Array:
    """Deterministic per-query reservoir key in ``(0, 1]`` (24-bit
    granularity — exact in f32; hash collisions tie-break on the id).
    A pure function of the id so every rank, every replay, and every
    chunking of the stream draws the same priority for the same query."""
    x = qid.astype(jnp.uint32)
    x = (x ^ (x >> 16)) * jnp.uint32(0x7FEB352D)
    x = (x ^ (x >> 15)) * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    return ((x >> 8).astype(jnp.float32) + 1.0) / jnp.float32(1 << 24)


def _split_qid(qid: Array) -> Tuple[Array, Array]:
    """int32 id -> (hi, lo) f32 lanes, each exact below 2**24."""
    u = qid.astype(jnp.uint32)
    return (u >> 24).astype(jnp.float32), (u & jnp.uint32(0xFFFFFF)).astype(jnp.float32)


def _join_qid(qhi: Array, qlo: Array) -> Array:
    """(hi, lo) f32 lanes -> the original int32 id (two's complement)."""
    u = (qhi.astype(jnp.uint32) << 24) | qlo.astype(jnp.uint32)
    return u.astype(jnp.int32)


def _unpack(table: Array):
    q, cap = table_capacity(table)
    return (
        table[:, COL_KEY],
        _join_qid(table[:, COL_QHI], table[:, COL_QLO]),
        table[:, COL_NSEEN],
        table[:, COL_POS],
        table[:, COL_NEG],
        table[:, COL_FILL],
        table[:, META_COLS : META_COLS + cap],
        table[:, META_COLS + cap :],
    )


def _pack(key, qid, nseen, pos, neg, fill, preds, target) -> Array:
    qhi, qlo = _split_qid(qid)
    return jnp.concatenate(
        [
            key[:, None],
            qhi[:, None],
            qlo[:, None],
            nseen[:, None],
            pos[:, None],
            neg[:, None],
            fill[:, None],
            preds,
            target,
        ],
        axis=1,
    ).astype(jnp.float32)


# ---------------------------------------------------------------------------
# insert
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("_mode",))
def _chunk_insert(table: Array, qid: Array, preds: Array, target: Array, valid: Array, _mode: Any = None) -> Array:
    """One fixed-shape chunk (``<= _INSERT_CHUNK`` docs) into the table:
    searchsorted join of batch query ids against the resident rows, a
    greedy sorted pairing for reservoir admission/eviction, a flat
    segment-scatter append of documents into free slots, and a
    ``lax.cond``-gated top-k compaction when any row would overflow.
    Jitted on its own so eager updates pay one cached dispatch; ``_mode``
    is the ops-dispatch routing state folded into the cache key (the
    compaction backend is a trace-time decision)."""
    from metrics_tpu.ops import row_topk_dispatch, segment_sum_dispatch

    num_q, cap = table_capacity(table)
    keep = _retain(cap)
    b = qid.shape[0]
    key_t, qid_t, nseen, pos_m, neg_c, fill, pt, tt = _unpack(table)
    occ = key_t > 0

    # ---- batch segment layout: stable sort by query id, invalid rows last
    skey = jnp.where(valid, qid, _I32_MAX)
    order = jnp.lexsort((jnp.arange(b, dtype=jnp.int32), skey))
    sq = skey[order]
    sv = valid[order]
    sp = jnp.clip(preds[order].astype(jnp.float32), -_FMAX, _FMAX)
    st = target[order].astype(jnp.float32)
    change = jnp.concatenate([jnp.ones(1, bool), sq[1:] != sq[:-1]])
    pos_i = jnp.arange(b, dtype=jnp.int32)
    seg_start = jax.lax.cummax(jnp.where(change, pos_i, 0))
    col = pos_i - seg_start

    # ---- join: which resident row owns each batch doc's query?
    qkey_t = jnp.where(occ, qid_t, _I32_MAX)
    torder = jnp.lexsort(((~occ).astype(jnp.int32), qkey_t))
    tq_sorted = qkey_t[torder]
    occ_sorted = occ[torder]
    loc = jnp.clip(jnp.searchsorted(tq_sorted, sq, side="left"), 0, num_q - 1)
    matched = (tq_sorted[loc] == sq) & occ_sorted[loc] & sv
    match_row = jnp.where(matched, torder[loc], -1)

    # ---- reservoir admission: distinct unmatched queries vs resident rows
    is_cand = change & sv & ~matched
    ckey = jnp.where(is_cand, _qid_key(sq), 0.0)
    cand_order = jnp.lexsort((sq, -ckey))  # priority desc: key desc, qid asc
    cq = sq[cand_order]
    ck = ckey[cand_order]
    # resident rows ascending by priority (KEY, -qid): free rows (KEY 0)
    # first, then occupied rows from the smallest key upward; qid DESC
    # breaks key ties (larger id = lower priority, the strict total order)
    neg_qid = jnp.invert(qid_t)  # ~x = -x-1: monotone signed flip, no overflow
    row_order = jnp.lexsort((neg_qid, key_t))
    n_pair = min(b, num_q)
    rslots = row_order[:n_pair]
    rkey = key_t[rslots]
    rqid = qid_t[rslots]
    ckp, cqp = ck[:n_pair], cq[:n_pair]
    beats = (ckp > rkey) | ((ckp == rkey) & (cqp < rqid))
    accept = (ckp > 0) & ((rkey <= 0) | beats)
    target_row = jnp.where(accept, rslots, num_q)  # num_q = dropped scatter

    # evicted/admitted rows restart fresh with the new query's identity
    key_t = key_t.at[target_row].set(ckp, mode="drop")
    qhi_new, qlo_new = _split_qid(cqp)
    qhi_t, qlo_t = _split_qid(qid_t)
    qhi_t = qhi_t.at[target_row].set(qhi_new, mode="drop")
    qlo_t = qlo_t.at[target_row].set(qlo_new, mode="drop")
    qid_t = _join_qid(qhi_t, qlo_t)
    zeros_pair = jnp.zeros(n_pair, jnp.float32)
    nseen = nseen.at[target_row].set(zeros_pair, mode="drop")
    pos_m = pos_m.at[target_row].set(zeros_pair, mode="drop")
    neg_c = neg_c.at[target_row].set(zeros_pair, mode="drop")
    fill = fill.at[target_row].set(zeros_pair, mode="drop")

    # map admissions back to the sorted batch: the accepted candidate at
    # sorted position p carries its row to every doc of its group
    admit_row = jnp.full(b, -1, jnp.int32).at[cand_order[:n_pair]].set(
        jnp.where(accept, rslots, -1), mode="drop"
    )
    # a row evicted THIS chunk belongs to its new query now: docs of the
    # evicted (matched-before-eviction) query must drop, not scatter into
    # the new owner's slots
    evicted = jnp.zeros(num_q, bool).at[target_row].set(accept, mode="drop")
    still_owned = matched & ~evicted[jnp.clip(match_row, 0, num_q - 1)]
    row_doc = jnp.where(still_owned, match_row, admit_row[seg_start])
    row_doc = jnp.where(sv & (row_doc >= 0), row_doc, num_q)  # num_q drops

    # ---- exact per-query counters (the scatter the sliced metric shares)
    live = row_doc < num_q
    ones = jnp.where(live, 1.0, 0.0).astype(jnp.float32)
    n_inc = segment_sum_dispatch(ones, row_doc, num_q)
    nseen = nseen + n_inc
    pos_m = pos_m + segment_sum_dispatch(jnp.where(live, st, 0.0), row_doc, num_q)
    neg_c = neg_c + segment_sum_dispatch(
        jnp.where(live & (st == 0), 1.0, 0.0), row_doc, num_q
    )

    # ---- document append: flat segment-scatter into each row's free slots
    row_c = jnp.clip(row_doc, 0, num_q - 1)
    slot = fill[row_c].astype(jnp.int32) + col
    flat = jnp.where(live & (slot < cap), row_c * cap + slot, num_q * cap)
    p_app = pt.reshape(-1).at[flat].set(sp, mode="drop").reshape(num_q, cap)
    t_app = tt.reshape(-1).at[flat].set(st, mode="drop").reshape(num_q, cap)
    fill_app = jnp.minimum(fill + n_inc, float(cap))

    over = fill + n_inc > cap

    def no_overflow(operands):
        p_a, t_a, f_a = operands[:3]
        return p_a, t_a, f_a

    def with_overflow(operands):
        p_a, t_a, f_a, p_old, t_old, f_old = operands
        # widen: stored slots + this chunk's docs scattered into scratch
        # columns (within-group col < chunk size by construction), then the
        # fused top-k + gather kernel keeps the best `keep` per row
        scratch_p = jnp.zeros((num_q, b), jnp.float32)
        scratch_t = jnp.zeros((num_q, b), jnp.float32)
        sflat = jnp.where(live, row_c * b + col, num_q * b)
        scratch_p = scratch_p.reshape(-1).at[sflat].set(sp, mode="drop").reshape(num_q, b)
        scratch_t = scratch_t.reshape(-1).at[sflat].set(st, mode="drop").reshape(num_q, b)
        scratch_v = (
            jnp.zeros((num_q, b), jnp.float32)
            .reshape(-1)
            .at[sflat]
            .set(ones, mode="drop")
            .reshape(num_q, b)
        )
        wide_p = jnp.concatenate([p_old, scratch_p], axis=1)
        wide_t = jnp.concatenate([t_old, scratch_t], axis=1)
        iota = jnp.arange(cap, dtype=jnp.float32)[None, :]
        wide_v = jnp.concatenate([(iota < f_old[:, None]).astype(jnp.float32), scratch_v], axis=1)
        top_p, top_t, _ = row_topk_dispatch(wide_p, wide_t, wide_v, keep)
        p_k = jnp.zeros((num_q, cap), jnp.float32).at[:, :keep].set(top_p)
        t_k = jnp.zeros((num_q, cap), jnp.float32).at[:, :keep].set(top_t)
        f_k = jnp.minimum(f_old + n_inc, float(keep))
        sel = over[:, None]
        return (
            jnp.where(sel, p_k, p_a),
            jnp.where(sel, t_k, t_a),
            jnp.where(over, f_k, f_a),
        )

    p_new, t_new, fill_new = jax.lax.cond(
        jnp.any(over),
        with_overflow,
        no_overflow,
        (p_app, t_app, fill_app, pt, tt, fill),
    )
    return _pack(key_t, qid_t, nseen, pos_m, neg_c, fill_new, p_new, t_new)


def retrieval_table_insert(
    table: Array,
    indexes: Array,
    preds: Array,
    target: Array,
    valid: Optional[Array] = None,
    n_valid: Optional[Array] = None,
) -> Array:
    """Insert a batch of ``(query id, pred, target)`` documents; pure and
    jit-safe. ``valid`` masks rows out entirely (the ``ignore_index``
    contract); ``n_valid`` masks trailing pad rows (the fused bucketing
    pad-and-mask contract). Batches larger than one chunk are absorbed in
    fixed chunks (host loop over static slices)."""
    from metrics_tpu.ops.dispatch import dispatch_mode

    indexes = jnp.asarray(indexes, jnp.int32).reshape(-1)
    preds = jnp.asarray(preds, jnp.float32).reshape(-1)
    target = jnp.asarray(target).astype(jnp.float32).reshape(-1)
    b = indexes.shape[0]
    v = jnp.ones(b, bool) if valid is None else jnp.asarray(valid, bool).reshape(-1)
    if n_valid is not None:
        v = v & (jnp.arange(b) < n_valid)
    mode = dispatch_mode()
    step = _INSERT_CHUNK
    for lo in range(0, b, step):
        table = _chunk_insert(
            table,
            indexes[lo : lo + step],
            preds[lo : lo + step],
            target[lo : lo + step],
            v[lo : lo + step],
            _mode=mode,
        )
    return table


# ---------------------------------------------------------------------------
# merge (dist_reduce_fx)
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("_mode",))
def _merge_impl(a: Array, b: Array, _mode: Any = None) -> Array:
    num_q, cap = table_capacity(a)
    rows = jnp.concatenate([a, b], axis=0)  # rank order: a's rows first
    key, qid, nseen, pos_m, neg_c, fill, pt, tt = _unpack(rows)
    occ = key > 0
    n2 = 2 * num_q

    # sort by query id (occupied first, original order as tiebreak) so
    # duplicate queries — present on both sides — become adjacent pairs,
    # with the a-side row first (stable: each side holds unique qids)
    qkey = jnp.where(occ, qid, _I32_MAX)
    order = jnp.lexsort((jnp.arange(n2, dtype=jnp.int32), qkey, (~occ).astype(jnp.int32)))
    key, qid, nseen, pos_m, neg_c, fill = (
        x[order] for x in (key, qid, nseen, pos_m, neg_c, fill)
    )
    pt, tt = pt[order], tt[order]
    occ = key > 0
    dup_next = jnp.concatenate([occ[1:] & occ[:-1] & (qid[1:] == qid[:-1]), jnp.zeros(1, bool)])
    is_dup = jnp.concatenate([jnp.zeros(1, bool), dup_next[:-1]])

    # fold the duplicate partner into its primary: docs concatenate in
    # rank (a-then-b) order — the gather-concat order the cat path syncs in
    nxt = jnp.minimum(jnp.arange(n2) + 1, n2 - 1)
    part_fill = jnp.where(dup_next, fill[nxt], 0.0)
    wide_p = jnp.concatenate([pt, jnp.where(dup_next[:, None], pt[nxt], 0.0)], axis=1)
    wide_t = jnp.concatenate([tt, jnp.where(dup_next[:, None], tt[nxt], 0.0)], axis=1)
    iota = jnp.arange(cap, dtype=jnp.float32)[None, :]
    wide_v = jnp.concatenate(
        [
            (iota < fill[:, None]).astype(jnp.float32),
            jnp.where(dup_next[:, None], (iota < part_fill[:, None]).astype(jnp.float32), 0.0),
        ],
        axis=1,
    )
    f_comb = fill + part_fill

    # arrival-order repack (valid slots first, a-side columns before
    # b-side) — exact while the combined docs fit
    arr_key = jnp.where(wide_v > 0, jnp.arange(2 * cap, dtype=jnp.float32)[None, :], jnp.float32(4 * cap))
    arr_order = jnp.argsort(arr_key, axis=1)
    packed_p = jnp.take_along_axis(wide_p, arr_order, axis=1)[:, :cap]
    packed_t = jnp.take_along_axis(wide_t, arr_order, axis=1)[:, :cap]

    def no_overflow(ops):
        pp, ptg = ops[:2]
        return pp, ptg, jnp.minimum(f_comb, float(cap))

    def with_overflow(ops):
        pp, ptg, wp, wt, wv = ops
        from metrics_tpu.ops import row_topk_dispatch

        top_p, top_t, _ = row_topk_dispatch(wp, wt, wv, cap)
        sel = (f_comb > cap)[:, None]
        return (
            jnp.where(sel, top_p, pp),
            jnp.where(sel, top_t, ptg),
            jnp.minimum(f_comb, float(cap)),
        )

    packed_p, packed_t, fill = jax.lax.cond(
        jnp.any(f_comb > cap),
        with_overflow,
        no_overflow,
        (packed_p, packed_t, wide_p, wide_t, wide_v),
    )
    nseen = nseen + jnp.where(dup_next, nseen[nxt], 0.0)
    pos_m = pos_m + jnp.where(dup_next, pos_m[nxt], 0.0)
    neg_c = neg_c + jnp.where(dup_next, neg_c[nxt], 0.0)
    # absorbed partners leave the row set
    key = jnp.where(is_dup, 0.0, key)

    # reservoir: keep the top-num_q (KEY, -qid) priorities of the union
    # (key descending, qid ascending on ties — the insert path's order)
    keep_order = jnp.lexsort((qid, -key))[:num_q]
    return _pack(
        key[keep_order],
        qid[keep_order],
        nseen[keep_order],
        pos_m[keep_order],
        neg_c[keep_order],
        fill[keep_order],
        packed_p[keep_order],
        packed_t[keep_order],
    )


def retrieval_table_merge(a: Array, b: Array) -> Array:
    """Merge two tables of identical geometry (``dist_reduce_fx``
    material): same-query rows fold doc-wise in rank order (top-``cap`` by
    score past capacity), distinct queries compete through the
    deterministic key reservoir. Exact — and bit-identical to the
    cat-state gather — while the union fits both capacities."""
    if a.shape != b.shape:
        raise ValueError(f"cannot merge retrieval tables with layouts {a.shape} and {b.shape}")
    from metrics_tpu.ops.dispatch import dispatch_mode

    return _merge_impl(a, b, _mode=dispatch_mode())


class _RetrievalTableReduce:
    """``dist_reduce_fx`` for retrieval-table leaves: folds
    :func:`retrieval_table_merge` over the stacked per-rank leaves
    ``[world, Q, C]`` in rank order — inside the lossless window this
    reproduces the cat-state gather's concatenation order bit-for-bit. A
    module-level class (picklable/deepcopy-able) tagged ``merge_like`` /
    ``sketch_kind`` so ``merge_states``, ``sync_pytree_in_mesh``'s fused
    gather round, TL-FLOW, and the footprint accounting all treat the
    table like the other fixed-capacity sketch kinds."""

    merge_like = True
    sketch_kind = "retrieval_table"
    __name__ = "retrieval_table_reduce"

    def __call__(self, stacked: Array) -> Array:
        stacked = jnp.asarray(stacked)
        if stacked.ndim == 2:  # single-rank passthrough
            return stacked
        out = stacked[0]
        for i in range(1, stacked.shape[0]):
            out = retrieval_table_merge(out, stacked[i])
        return out


_TABLE_REDUCE = _RetrievalTableReduce()


def retrieval_table_merge_fx() -> _RetrievalTableReduce:
    """The shared retrieval-table ``dist_reduce_fx`` (see
    :class:`_RetrievalTableReduce`)."""
    return _TABLE_REDUCE


# ---------------------------------------------------------------------------
# queries (pure unless noted)
# ---------------------------------------------------------------------------


def retrieval_table_fill(table: Array) -> Array:
    """Occupied query rows (int32 scalar)."""
    return jnp.sum(table[:, COL_KEY] > 0).astype(jnp.int32)


def retrieval_table_layout(table: Array):
    """Unpack to the padded compute layout, rows ordered by ascending
    query id (the ``pack_queries`` order, so in-window results match the
    cat-state path bit-for-bit on integer-exact data):

    ``(padded_preds [Q, cap], padded_target [Q, cap], mask [Q, cap],
    row_valid [Q], pos_mass [Q], neg_count [Q], n_seen [Q])``

    Padding slots carry ``preds=-inf``/``target=0``/``mask=False`` —
    the row kernels' contract.
    """
    key, qid, nseen, pos_m, neg_c, fill, pt, tt = _unpack(table)
    occ = key > 0
    order = jnp.lexsort((qid, (~occ).astype(jnp.int32)))
    occ, fill = occ[order], fill[order]
    mask = (jnp.arange(pt.shape[1], dtype=jnp.float32)[None, :] < fill[:, None]) & occ[:, None]
    padded_preds = jnp.where(mask, pt[order], -jnp.inf)
    padded_target = jnp.where(mask, tt[order], 0.0)
    return (
        padded_preds,
        padded_target,
        mask,
        occ,
        pos_m[order],
        neg_c[order],
        nseen[order],
    )


def retrieval_table_layout_rows(table: Array, rows: Array):
    """Subset unpack: the padded compute layout of just ``table[rows]``,
    in CALLER order — no cross-row qid sort, so row ``i`` of every output
    is the requested table row ``rows[i]``, and the per-row values are
    bit-identical to the same row of :func:`retrieval_table_layout` (the
    sort only reorders rows, never changes one). Returns the layout tuple
    plus a trailing ``qid [n]`` so callers know which query each row
    holds:

    ``(padded_preds [n, cap], padded_target [n, cap], mask [n, cap],
    row_valid [n], pos_mass [n], neg_count [n], n_seen [n], qid [n])``
    """
    rows = jnp.asarray(rows, jnp.int32)
    key, qid, nseen, pos_m, neg_c, fill, pt, tt = _unpack(table[rows])
    occ = key > 0
    mask = (jnp.arange(pt.shape[1], dtype=jnp.float32)[None, :] < fill[:, None]) & occ[:, None]
    padded_preds = jnp.where(mask, pt, -jnp.inf)
    padded_target = jnp.where(mask, tt, 0.0)
    return (padded_preds, padded_target, mask, occ, pos_m, neg_c, nseen, qid)
