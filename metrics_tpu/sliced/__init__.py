"""Sliced metric state: one metric tracked across many shardable slices.

The reference library's answer to "the same metric over many groups" is
``ClasswiseWrapper``-style object fan-out — N metric objects, N states, N
dispatches — which caps out at tens of groups. :class:`SlicedMetric` gives
any fusible metric a leading ``[S]`` slice dimension on every state leaf
instead: one state pytree, one segment-scatter update per batch (inside the
fused single-dispatch kernel), one vmapped compute — per-tenant /
per-cohort / per-model-version metrics at 10^5–10^6 slices on one pod, with
the slice axis shardable across a device mesh via the partition rules in
:mod:`metrics_tpu.sliced.sharding`.
"""
from metrics_tpu.sliced.metric import SLICED_FOOTPRINT_PREFIX, SlicedMetric
from metrics_tpu.sliced.sharding import (
    get_naive_slice_sharding,
    match_partition_rules,
    shard_sliced_states,
    slice_partition_rules,
    sliced_partition_specs,
)

__all__ = [
    "SLICED_FOOTPRINT_PREFIX",
    "SlicedMetric",
    "get_naive_slice_sharding",
    "match_partition_rules",
    "shard_sliced_states",
    "slice_partition_rules",
    "sliced_partition_specs",
]
