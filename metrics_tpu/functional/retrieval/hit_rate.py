"""Retrieval hit rate.

Behavior parity with /root/reference/torchmetrics/functional/retrieval/
hit_rate.py:20-58.
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _check_retrieval_functional_inputs, _check_retrieval_k

Array = jax.Array


def retrieval_hit_rate(preds: Array, target: Array, k: Optional[int] = None) -> Array:
    """1.0 if any relevant document is in the top k, else 0.0.

    Example:
        >>> import jax.numpy as jnp
        >>> retrieval_hit_rate(jnp.array([0.2, 0.3, 0.5]), jnp.array([True, False, True]), k=2)
        Array(1., dtype=float32)
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)
    if k is None:
        k = preds.shape[-1]
    _check_retrieval_k(k)

    relevant = jnp.sum(target[jnp.argsort(-preds, axis=-1)][:k])
    return (relevant > 0).astype(jnp.float32)
