"""Import helper for using the reference implementation as a test oracle.

The reference tree at /root/reference is pure Python over torch (CPU build
available in this environment), so domains whose usual PyPI oracle is absent
(e.g. jiwer for the WER family) can be checked against the reference itself
— the same pattern tests/detection/test_map.py uses for mAP.
"""
import sys
import types

import pytest


def load_reference_module(dotted: str):
    """Import ``torchmetrics...`` submodule from /root/reference, or skip."""
    pytest.importorskip("torch")
    if "/root/reference" not in sys.path:
        sys.path.insert(0, "/root/reference")
    if "pkg_resources" not in sys.modules:
        # this env's setuptools no longer ships pkg_resources; the reference
        # only needs these two names for optional-dependency probing
        stub = types.ModuleType("pkg_resources")

        class DistributionNotFound(Exception):
            pass

        def get_distribution(name):
            raise DistributionNotFound(name)

        stub.DistributionNotFound = DistributionNotFound
        stub.get_distribution = get_distribution
        sys.modules["pkg_resources"] = stub
    try:
        __import__(dotted)
    except Exception as err:  # pragma: no cover
        pytest.skip(f"reference torchmetrics unavailable: {err}")
    return sys.modules[dotted]


def ref_oracle(name: str, **ref_kwargs):
    """Oracle adapter: numpy batch -> reference torchmetrics functional.

    Handles list outputs (curve metrics return per-class lists) by mapping
    the tensor->numpy conversion over them.
    """
    import numpy as np
    import torch

    fn = getattr(load_reference_module("torchmetrics.functional"), name)

    def _to_np(out):
        if isinstance(out, (list, tuple)):
            return [_to_np(o) for o in out]
        return out.numpy()

    def oracle(preds, target, **_):
        return _to_np(
            fn(torch.as_tensor(np.asarray(preds)), torch.as_tensor(np.asarray(target)), **ref_kwargs)
        )

    return oracle


def assert_accumulated_parity(metric, fixture, oracle, atol=1e-6):
    """Update per batch, then compare the accumulated compute against the
    oracle on the batch-flattened data (the shared shape of the targeted
    argument-corner tests in the reference grids)."""
    import jax.numpy as jnp
    import numpy as np

    for i in range(fixture.preds.shape[0]):
        metric.update(jnp.asarray(fixture.preds[i]), jnp.asarray(fixture.target[i]))
    flat_p = fixture.preds.reshape(-1, *fixture.preds.shape[2:])
    flat_t = fixture.target.reshape(-1, *fixture.target.shape[2:])
    want = oracle(flat_p, flat_t)
    got = metric.compute()
    if isinstance(got, (list, tuple)):
        assert len(got) == len(want), f"length mismatch: {len(got)} vs {len(want)}"
        for g, w in zip(got, want):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w), atol=atol)
    else:
        np.testing.assert_allclose(np.asarray(got), want, atol=atol)
