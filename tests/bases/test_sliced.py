"""Sliced metric state (ISSUE 8 tentpole).

Parity suite: a ``SlicedMetric(m, S)`` fed interleaved slice batches must be
BIT-identical to S independent metric objects — across sum/max/min-reduced
(and mean-style sum/sum) metrics, eager and fused, through reset / merge /
``state_dict`` round-trips and ``compile_update_async``; the slice axis must
shard over a multi-device CPU mesh and sync traffic-free through the
generalized ``sync_pytree_in_mesh(partition_specs=...)``; and non-sliceable
metrics must be rejected with a clear error instead of mis-scattering.

Parity data uses integer-valued floats on purpose: every partial sum is
exact in float32, so any accumulation ORDER produces identical bits and the
bit-equality assertions test the scatter arithmetic, not summation
bracketing.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from metrics_tpu import (
    Accuracy,
    CatMetric,
    MaxMetric,
    MeanMetric,
    MeanSquaredError,
    MetricCollection,
    MinMetric,
    SumMetric,
)
from metrics_tpu.core.metric import Metric
from metrics_tpu.observability import get_recorder
from metrics_tpu.parallel.distributed import sync_pytree_in_mesh
from metrics_tpu.sliced import (
    SlicedMetric,
    get_naive_slice_sharding,
    match_partition_rules,
    shard_sliced_states,
    slice_partition_rules,
    sliced_partition_specs,
)
from metrics_tpu.utils.compat import shard_map
from metrics_tpu.utils.exceptions import MetricsUserError
from metrics_tpu.wrappers import ClasswiseWrapper


@pytest.fixture
def recorder():
    rec = get_recorder()
    rec.reset()
    rec.enable()
    try:
        yield rec
    finally:
        rec.disable()
        rec.reset()


def _state_of(m: Metric):
    return {k: getattr(m, k) for k in m._defaults}


def _assert_states_bit_identical(a: Metric, b: Metric):
    for k in a._defaults:
        va, vb = getattr(a, k), getattr(b, k)
        assert bool(jnp.array_equal(jnp.asarray(va), jnp.asarray(vb))), (
            f"state {k!r} diverged"
        )


# ---------------------------------------------------------------------------
# interleaved-batch generators (integer-valued -> exact float arithmetic)
# ---------------------------------------------------------------------------

def _reg_batches(rng, S, n_batches, rows_per_batch):
    """(ids, preds, target) regression batches, integer-valued floats."""
    out = []
    for _ in range(n_batches):
        ids = rng.randint(0, S, rows_per_batch)
        preds = rng.randint(0, 8, rows_per_batch).astype(np.float32)
        target = rng.randint(0, 8, rows_per_batch).astype(np.float32)
        out.append((jnp.asarray(ids), jnp.asarray(preds), jnp.asarray(target)))
    return out


def _cls_batches(rng, S, n_batches, rows_per_batch, n_classes=4):
    out = []
    for _ in range(n_batches):
        ids = rng.randint(0, S, rows_per_batch)
        preds = rng.rand(rows_per_batch, n_classes).astype(np.float32)
        preds /= preds.sum(-1, keepdims=True)
        target = rng.randint(0, n_classes, rows_per_batch)
        out.append((jnp.asarray(ids), jnp.asarray(preds), jnp.asarray(target)))
    return out


def _fanout_apply(objs, ids, *args):
    """Feed S independent objects the same rows, ONE ROW AT A TIME in row
    order — the accumulation order the per-row segment scatter reproduces."""
    ids = np.asarray(ids)
    for r, i in enumerate(ids):
        objs[int(i)].update(*(jnp.asarray(a)[r : r + 1] for a in args))


# ---------------------------------------------------------------------------
# parity: sliced vs S independent objects
# ---------------------------------------------------------------------------

@pytest.mark.parametrize(
    "make,batches",
    [
        (lambda: MeanSquaredError(), "reg"),
        (lambda: SumMetric(nan_strategy="ignore"), "agg"),
        (lambda: MaxMetric(nan_strategy="ignore"), "agg"),
        (lambda: MinMetric(nan_strategy="ignore"), "agg"),
        (lambda: MeanMetric(nan_strategy="ignore"), "agg"),
    ],
    ids=["mse-sum", "sum", "max", "min", "mean"],
)
def test_small_s_parity_across_reducers(make, batches):
    """sum / max / min / mean-style reducers, multiple rows per slice per
    batch, eager path."""
    S = 8
    rng = np.random.RandomState(3)
    sliced = SlicedMetric(make(), num_slices=S)
    objs = [make() for _ in range(S)]
    expected_counts = np.zeros(S, np.int64)
    for _ in range(4):
        ids = rng.randint(0, S, 32)
        vals = rng.randint(0, 9, 32).astype(np.float32)
        expected_counts += np.bincount(ids, minlength=S)
        if batches == "reg":
            target = rng.randint(0, 9, 32).astype(np.float32)
            sliced.update(jnp.asarray(ids), jnp.asarray(vals), jnp.asarray(target))
            _fanout_apply(objs, ids, vals, target)
        else:
            sliced.update(jnp.asarray(ids), jnp.asarray(vals))
            _fanout_apply(objs, ids, vals)
    per_slice = sliced.compute()
    ref = jnp.stack([o.compute() for o in objs])
    assert bool(jnp.array_equal(per_slice, ref))
    # per-slice row counts match the rows each object saw
    assert np.array_equal(np.asarray(sliced.slice_counts), expected_counts)


def test_mean_metric_weighted_parity():
    """MeanMetric's weight kwarg rides the row alignment too."""
    S = 4
    rng = np.random.RandomState(5)
    sliced = SlicedMetric(MeanMetric(), num_slices=S)
    objs = [MeanMetric() for _ in range(S)]
    for _ in range(3):
        ids = rng.randint(0, S, 16)
        vals = rng.randint(0, 9, 16).astype(np.float32)
        w = rng.randint(1, 4, 16).astype(np.float32)
        sliced.update(jnp.asarray(ids), jnp.asarray(vals), weight=jnp.asarray(w))
        ids_np = np.asarray(ids)
        for r, i in enumerate(ids_np):
            objs[int(i)].update(jnp.asarray(vals)[r : r + 1], weight=jnp.asarray(w)[r : r + 1])
    assert bool(jnp.array_equal(sliced.compute(), jnp.stack([o.compute() for o in objs])))


@pytest.mark.parametrize("fused", [False, True], ids=["eager", "fused"])
def test_s1000_parity_classification_regression_aggregation(fused):
    """The acceptance-criterion parity: S=1000 slices, one classification +
    one regression + one aggregation metric, bit-identical to 1000
    independent objects, eager AND fused, with the slice states synced
    through the generalized ``sync_pytree_in_mesh`` on the 8-device CPU
    mesh (slice-sharded leaves pass through traffic-free, bit-identically).
    """
    S = 1000
    rng = np.random.RandomState(11)
    n_classes = 4
    makes = {
        "acc": lambda: Accuracy(),
        "mse": lambda: MeanSquaredError(),
        "sum": lambda: SumMetric(nan_strategy="ignore"),
    }
    sliced = {k: SlicedMetric(mk(), num_slices=S) for k, mk in makes.items()}
    objs = {k: [mk() for _ in range(S)] for k, mk in makes.items()}

    # 2 interleaved batches of 1000 rows: every slice sees exactly one row
    # per batch (a permutation), so the object side gets one single-row
    # update per batch — same accumulation order as the segment scatter
    cols = {}
    if fused:
        for k in makes:
            cols[k] = MetricCollection({k: sliced[k]})
    for _ in range(2):
        ids = rng.permutation(S)
        preds_c = rng.rand(S, n_classes).astype(np.float32)
        preds_c /= preds_c.sum(-1, keepdims=True)
        target_c = rng.randint(0, n_classes, S)
        preds_r = rng.randint(0, 8, S).astype(np.float32)
        target_r = rng.randint(0, 8, S).astype(np.float32)
        batch = {
            "acc": (jnp.asarray(preds_c), jnp.asarray(target_c)),
            "mse": (jnp.asarray(preds_r), jnp.asarray(target_r)),
            "sum": (jnp.asarray(preds_r),),
        }
        for k in makes:
            if fused:
                cols[k].update(jnp.asarray(ids), *batch[k])
                if cols[k].fused_update is None:
                    cols[k].compile_update()
            else:
                sliced[k].update(jnp.asarray(ids), *batch[k])
        for k in makes:
            for r, i in enumerate(ids):
                objs[k][int(i)].update(*(a[r : r + 1] for a in batch[k]))

    # mesh round-trip: shard the slice axis over the 8 CPU devices and run
    # the generalized sync — slice-sharded leaves are identity (zero
    # cross-host traffic for the sharded dimension)
    mesh = Mesh(np.asarray(jax.devices()[:8]), ("slices",))
    for k in makes:
        m = sliced[k]
        shard_sliced_states(m, mesh)
        state = _state_of(m)
        reductions = m.state_reductions()
        specs = sliced_partition_specs(m, mesh=mesh)
        leaves = sorted(state)
        body = lambda *vals: tuple(  # noqa: E731
            sync_pytree_in_mesh(
                dict(zip(leaves, vals)), reductions, "slices", partition_specs=specs
            )[n]
            for n in leaves
        )
        synced = jax.jit(
            shard_map(
                body,
                mesh=mesh,
                in_specs=tuple(P("slices") for _ in leaves),
                out_specs=tuple(P("slices") for _ in leaves),
            )
        )(*(state[n] for n in leaves))
        for name, out in zip(leaves, synced):
            assert bool(jnp.array_equal(out, state[name])), (k, name)
            object.__setattr__(m, name, out)

    for k in makes:
        per_slice = sliced[k].compute()
        ref = jnp.stack([o.compute() for o in objs[k]])
        assert bool(jnp.array_equal(per_slice, ref)), k
        assert np.asarray(sliced[k].slice_counts).sum() == 2 * S


# ---------------------------------------------------------------------------
# fused path: single dispatch, bucketing, async
# ---------------------------------------------------------------------------

def test_fused_parity_and_bucketed_single_compile(recorder):
    """Ragged batch sizes share ONE compilation through pad-and-mask
    bucketing, and the fused states stay bit-identical to an eager twin fed
    the same (unpadded) batches — the pad rows' scatter contribution is
    subtracted exactly, slice ids included."""
    S = 64
    rng = np.random.RandomState(7)
    eager = SlicedMetric(MeanSquaredError(), num_slices=S)
    col = MetricCollection({"m": SlicedMetric(MeanSquaredError(), num_slices=S)})

    sizes = (96, 112, 128)
    batches = []
    for n in (128, *sizes * 3):
        ids = rng.randint(0, S, n)
        preds = rng.randint(0, 8, n).astype(np.float32)
        target = rng.randint(0, 8, n).astype(np.float32)
        batches.append((jnp.asarray(ids), jnp.asarray(preds), jnp.asarray(target)))

    col.update(*batches[0])  # discovery batch
    eager.update(*batches[0])
    handle = col.compile_update(buckets=(128,))
    for b in batches[1:]:
        col.update(*b)
        eager.update(*b)
    assert handle.n_compiles == 1, "bucketed ragged shapes must share one compile"
    _assert_states_bit_identical(col["m"], eager)
    ev = [e for e in recorder.events() if e["type"] == "fused_update"]
    assert len(ev) == len(batches) - 1
    assert all(e["n_sliced"] == 1 for e in ev)


def test_async_parity(recorder):
    """compile_update_async ingests sliced batches bit-identically to the
    blocking eager path."""
    S = 16
    rng = np.random.RandomState(9)
    eager = SlicedMetric(MeanSquaredError(), num_slices=S)
    col = MetricCollection({"m": SlicedMetric(MeanSquaredError(), num_slices=S)})
    batches = _reg_batches(rng, S, 8, 32)
    col.update(*batches[0])
    eager.update(*batches[0])
    handle = col.compile_update_async(queue_depth=2)
    try:
        for b in batches[1:]:
            col.update_async(*b)
            eager.update(*b)
        handle.flush()
        _assert_states_bit_identical(col["m"], eager)
        assert bool(jnp.array_equal(col.compute()["m"], eager.compute()))
    finally:
        handle.close()


# ---------------------------------------------------------------------------
# lifecycle round-trips
# ---------------------------------------------------------------------------

def test_reset_merge_state_dict_round_trips():
    S = 8
    rng = np.random.RandomState(13)
    batches = _reg_batches(rng, S, 6, 24)

    # one metric over all batches == merge of two halves
    whole = SlicedMetric(MeanSquaredError(), num_slices=S)
    for b in batches:
        whole.update(*b)
    ha = SlicedMetric(MeanSquaredError(), num_slices=S)
    hb = SlicedMetric(MeanSquaredError(), num_slices=S)
    for b in batches[:3]:
        ha.update(*b)
    for b in batches[3:]:
        hb.update(*b)
    merged = ha.merge_states(_state_of(ha), _state_of(hb))
    for k, v in merged.items():
        assert bool(jnp.array_equal(v, getattr(whole, k))), k

    # state_dict round-trip preserves bits
    sd = whole.state_dict()
    restored = SlicedMetric(MeanSquaredError(), num_slices=S)
    restored.load_state_dict(sd)
    _assert_states_bit_identical(whole, restored)
    assert bool(jnp.array_equal(restored.compute(), whole.compute()))

    # reset restores defaults (incl. the row counter) and re-accumulates
    # identically
    whole.reset()
    assert int(np.asarray(whole.slice_counts).sum()) == 0
    for b in batches:
        whole.update(*b)
    _assert_states_bit_identical(whole, restored)


def test_forward_returns_batch_value_and_keeps_accumulation():
    S = 4
    m = SlicedMetric(SumMetric(nan_strategy="ignore"), num_slices=S)
    m.update(jnp.array([0, 1]), jnp.array([1.0, 2.0]))
    batch_val = m(jnp.array([0, 3]), jnp.array([5.0, 7.0]))
    assert bool(jnp.array_equal(batch_val, jnp.array([5.0, 0.0, 0.0, 7.0])))
    assert bool(jnp.array_equal(m.compute(), jnp.array([6.0, 2.0, 0.0, 7.0])))


def test_clone_is_independent():
    m = SlicedMetric(SumMetric(nan_strategy="ignore"), num_slices=2)
    m.update(jnp.array([0]), jnp.array([1.0]))
    c = m.clone()
    c.update(jnp.array([1]), jnp.array([5.0]))
    assert bool(jnp.array_equal(m.compute(), jnp.array([1.0, 0.0])))
    assert bool(jnp.array_equal(c.compute(), jnp.array([1.0, 5.0])))


# ---------------------------------------------------------------------------
# compute subsetting / top-k
# ---------------------------------------------------------------------------

def test_compute_subset_and_top_k():
    S = 16
    rng = np.random.RandomState(17)
    m = SlicedMetric(MeanSquaredError(), num_slices=S)
    for b in _reg_batches(rng, S, 4, 32):
        m.update(*b)
    full = m.compute()
    ids = jnp.array([3, 0, 11])
    assert bool(jnp.array_equal(m.compute(slice_ids=ids), full[ids]))

    k = 4
    top_ids, top_vals = m.compute(top_k=k)
    counts = np.asarray(m.slice_counts)
    assert len(top_ids) == k
    # the selected slices carry the k largest row counts
    assert counts[np.asarray(top_ids)].min() >= np.sort(counts)[::-1][k - 1]
    assert bool(jnp.array_equal(top_vals, full[top_ids]))

    with pytest.raises(MetricsUserError, match="not both"):
        m.compute(slice_ids=ids, top_k=2)
    with pytest.raises(MetricsUserError, match="positive int"):
        m.compute(top_k=0)
    # gathers CLAMP out-of-range indices (unlike update's scatter, which
    # drops them) — an off-by-one must raise, not return slice S-1's value
    with pytest.raises(MetricsUserError, match="out of range"):
        m.compute(slice_ids=jnp.array([S]))
    with pytest.raises(MetricsUserError, match="out of range"):
        m.compute(slice_ids=jnp.array([-1]))


# ---------------------------------------------------------------------------
# construction-time rejection of non-sliceable metrics
# ---------------------------------------------------------------------------

class _RunningMean(Metric):
    """A genuinely mean-REDUCED leaf: no exact per-slice scatter exists."""

    def __init__(self):
        super().__init__()
        self.add_state("avg", default=jnp.asarray(0.0), dist_reduce_fx="mean")

    def _update(self, v):
        self.avg = (self.avg + jnp.mean(v)) / 2

    def _compute(self):
        return self.avg


def test_rejects_non_sliceable_metrics():
    with pytest.raises(MetricsUserError, match="list \\('cat'\\) state"):
        SlicedMetric(CatMetric(), num_slices=4)
    with pytest.raises(MetricsUserError, match="only sum/max/min"):
        SlicedMetric(_RunningMean(), num_slices=4)
    with pytest.raises(MetricsUserError, match="__jit_unsafe__"):
        SlicedMetric(ClasswiseWrapper(Accuracy(num_classes=3, average="none")), num_slices=4)
    with pytest.raises(MetricsUserError, match="cannot wrap another"):
        SlicedMetric(SlicedMetric(MeanSquaredError(), num_slices=2), num_slices=2)
    with pytest.raises(MetricsUserError, match="positive int"):
        SlicedMetric(MeanSquaredError(), num_slices=0)


def test_update_validates_slice_ids():
    m = SlicedMetric(MeanSquaredError(), num_slices=4)
    with pytest.raises(MetricsUserError, match="1-D integer"):
        m.update(jnp.zeros((2, 2), jnp.int32), jnp.zeros(2), jnp.zeros(2))
    with pytest.raises(MetricsUserError, match="integer-typed"):
        m.update(jnp.array([0.0, 1.0]), jnp.zeros(2), jnp.zeros(2))
    with pytest.raises(MetricsUserError, match="row-aligned"):
        m.update(jnp.array([0, 1]), jnp.zeros(3), jnp.zeros(3))


def test_out_of_range_ids_are_dropped():
    """XLA scatter semantics: ids outside [0, S) contribute nothing."""
    m = SlicedMetric(SumMetric(nan_strategy="ignore"), num_slices=2)
    m.update(jnp.array([0, 5, -1]), jnp.array([1.0, 100.0, 100.0]))
    assert bool(jnp.array_equal(m.compute(), jnp.array([1.0, 0.0])))
    assert np.array_equal(np.asarray(m.slice_counts), [1, 0])


# ---------------------------------------------------------------------------
# compute groups: differently-configured inner metrics must not merge
# ---------------------------------------------------------------------------

def test_compute_groups_respect_template_config():
    a = SlicedMetric(Accuracy(threshold=0.3), num_slices=4)
    b = SlicedMetric(Accuracy(threshold=0.7), num_slices=4)
    assert not MetricCollection._equal_metric_states(a, b)
    c = SlicedMetric(Accuracy(threshold=0.3), num_slices=4)
    assert MetricCollection._equal_update_attrs(a, c)


# ---------------------------------------------------------------------------
# sharding helpers + generalized mesh sync
# ---------------------------------------------------------------------------

def test_match_partition_rules_paths():
    tree = {
        "m": {"sliced/total": jnp.zeros(16), "scalar": jnp.asarray(0.0), "plain": jnp.zeros(3)}
    }
    specs = match_partition_rules(slice_partition_rules("slices"), tree)
    assert specs["m"]["sliced/total"] == P("slices")
    assert specs["m"]["scalar"] == P()  # scalars never partition
    assert specs["m"]["plain"] == P()  # catch-all replicates
    with pytest.raises(MetricsUserError, match="no partition rule"):
        match_partition_rules(((r"^only-this$", P()),), {"other": jnp.zeros(4)})


def test_naive_slice_sharding_divisibility():
    mesh = Mesh(np.asarray(jax.devices()[:8]), ("slices",))
    sharded = get_naive_slice_sharding(jnp.zeros(16), mesh)
    assert sharded.spec == P("slices")
    replicated = get_naive_slice_sharding(jnp.zeros(10), mesh)  # 10 % 8 != 0
    assert replicated.spec == P()


def test_partition_specs_follow_replication_fallback():
    """When num_slices does not divide the mesh axis, shard_sliced_states
    replicates — and the mesh-aware spec tree must say replicated TOO, or
    sync_pytree_in_mesh would pass the leaves through as disjointly owned
    and silently skip the cross-rank reduction replication requires."""
    mesh = Mesh(np.asarray(jax.devices()[:8]), ("slices",))
    m = SlicedMetric(MeanSquaredError(), num_slices=10)  # 10 % 8 != 0
    shardings = shard_sliced_states(m, mesh)
    assert all(s.spec == P() for s in shardings.values())
    specs = sliced_partition_specs(m, mesh)
    assert all(s == P() for s in specs.values())
    # and a divisible metric claims sharded under the same mesh
    ok = SlicedMetric(MeanSquaredError(), num_slices=16)
    assert all(s == P("slices") for s in sliced_partition_specs(ok, mesh).values())


def test_shard_sliced_states_survives_update_and_reset():
    mesh = Mesh(np.asarray(jax.devices()[:8]), ("slices",))
    m = SlicedMetric(MeanSquaredError(), num_slices=16)
    shardings = shard_sliced_states(m, mesh)
    assert all(s.spec == P("slices") for s in shardings.values())
    m.update(jnp.arange(16), jnp.arange(16, dtype=jnp.float32), jnp.zeros(16))
    assert m.sum_squared_error.sharding.spec == P("slices")
    m.reset()
    assert m.sum_squared_error.sharding.spec == P("slices")


def test_sync_pytree_partition_specs_mixed_tree():
    """Slice-sharded leaves pass through untouched while replicated leaves
    in the SAME pytree still reduce across the axis."""
    n_dev = 8
    mesh = Mesh(np.asarray(jax.devices()[:n_dev]), ("slices",))
    S = 16
    sliced_leaf = jnp.arange(S, dtype=jnp.float32)
    per_rank = jnp.arange(n_dev, dtype=jnp.float32)[:, None]

    def body(sl, scalar):
        out = sync_pytree_in_mesh(
            {"m": {"sl": sl, "scalar": scalar[0]}},
            {"m": {"sl": "sum", "scalar": "sum"}},
            "slices",
            partition_specs={"m": {"sl": P("slices"), "scalar": P()}},
        )
        return out["m"]["sl"], out["m"]["scalar"]

    out_sl, out_scalar = jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=(P("slices"), P("slices")),
            out_specs=(P("slices"), P()),
        )
    )(sliced_leaf, per_rank)
    assert bool(jnp.array_equal(out_sl, sliced_leaf))  # identity: disjoint owners
    assert float(np.asarray(out_scalar).reshape(-1)[0]) == float(per_rank.sum())


# ---------------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------------

def test_footprint_sliced_label_and_per_slice_average(recorder):
    recorder.enable(footprint_warn_bytes=10**9)
    S = 100
    m = SlicedMetric(MeanSquaredError(), num_slices=S)
    m.update(jnp.array([0, 1]), jnp.array([1.0, 2.0]), jnp.zeros(2))
    hwm = recorder.footprint_high_water_marks()
    assert "SlicedMetric[sliced]" in hwm
    assert "SlicedMetric" not in hwm  # no base-state bytes to misattribute
    assert hwm["SlicedMetric[sliced]"] == sum(m.state_footprint().values())
    assert recorder.footprint_slice_counts()["SlicedMetric[sliced]"] == S
    summary = recorder.summary()
    assert "B/slice over 100 slices" in summary
    ev = [e for e in recorder.events() if e["type"] == "footprint"]
    assert ev and ev[-1]["sliced_bytes"] == hwm["SlicedMetric[sliced]"]
    assert ev[-1]["n_slices"] == S


def test_scatter_events_and_prometheus(recorder):
    m = SlicedMetric(SumMetric(nan_strategy="ignore"), num_slices=32)
    m.update(jnp.array([0, 1, 2]), jnp.array([1.0, 2.0, 3.0]))
    m.update(jnp.array([4, 5]), jnp.array([1.0, 2.0]))
    totals = recorder.sliced_totals()
    assert totals["scatter_events"] == 2
    assert totals["rows"] == 5
    assert totals["max_slices"] == 32
    page = recorder.render_prometheus()
    assert "metrics_tpu_sliced_scatter_total 2" in page
    assert "metrics_tpu_sliced_rows_total 5" in page
    assert "metrics_tpu_sliced_slices 32" in page
    from metrics_tpu.observability.aggregate import aggregate_across_hosts

    agg = aggregate_across_hosts()
    assert agg["sliced_totals"]["scatter_events"] == 2
