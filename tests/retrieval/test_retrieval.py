"""Retrieval metrics vs sklearn / hand-rolled oracles.

Mirrors /root/reference/tests/retrieval/ in spirit: grouped queries with
random lengths, all empty_target_action modes, argument validation.
"""
import numpy as np
import pytest
from sklearn.metrics import average_precision_score as sk_ap, ndcg_score as sk_ndcg

import jax.numpy as jnp

from metrics_tpu import (
    RetrievalFallOut,
    RetrievalHitRate,
    RetrievalMAP,
    RetrievalMRR,
    RetrievalNormalizedDCG,
    RetrievalPrecision,
    RetrievalRPrecision,
    RetrievalRecall,
)
from metrics_tpu.functional import (
    retrieval_average_precision,
    retrieval_fall_out,
    retrieval_hit_rate,
    retrieval_normalized_dcg,
    retrieval_precision,
    retrieval_r_precision,
    retrieval_recall,
    retrieval_reciprocal_rank,
)

_rng = np.random.RandomState(42)
N_QUERIES = 10
# each query has 4-12 documents, with at least one positive and one negative
_indexes, _preds, _target = [], [], []
for q in range(N_QUERIES):
    n = _rng.randint(4, 13)
    t = np.zeros(n, dtype=np.int64)
    t[_rng.choice(n, _rng.randint(1, n), replace=False)] = 1
    if t.all():
        t[0] = 0
    _indexes.append(np.full(n, q))
    _preds.append(_rng.rand(n).astype(np.float32))
    _target.append(t)
INDEXES = jnp.asarray(np.concatenate(_indexes))
PREDS = jnp.asarray(np.concatenate(_preds))
TARGET = jnp.asarray(np.concatenate(_target))


def _per_query_mean(fn):
    return np.mean([fn(p, t) for p, t in zip(_preds, _target)])


def _sk_mrr(p, t):
    order = np.argsort(-p)
    pos = np.nonzero(t[order])[0]
    return 1.0 / (pos[0] + 1)


def _sk_precision_at(k):
    def fn(p, t):
        order = np.argsort(-p)[:k]
        return t[order].sum() / k
    return fn


def _sk_recall_at(k):
    def fn(p, t):
        order = np.argsort(-p)[:k]
        return t[order].sum() / t.sum()
    return fn


def _sk_hit_at(k):
    def fn(p, t):
        return float(t[np.argsort(-p)[:k]].sum() > 0)
    return fn


def _sk_fallout_at(k):
    def fn(p, t):
        neg = 1 - t
        return neg[np.argsort(-p)[:k]].sum() / neg.sum()
    return fn


def _sk_rprec(p, t):
    r = int(t.sum())
    return t[np.argsort(-p)[:r]].sum() / r


@pytest.mark.parametrize(
    "metric_class, metric_args, oracle",
    [
        (RetrievalMAP, {}, lambda: _per_query_mean(lambda p, t: sk_ap(t, p))),
        (RetrievalMRR, {}, lambda: _per_query_mean(_sk_mrr)),
        (RetrievalPrecision, {"k": 2}, lambda: _per_query_mean(_sk_precision_at(2))),
        (RetrievalRecall, {"k": 2}, lambda: _per_query_mean(_sk_recall_at(2))),
        (RetrievalHitRate, {"k": 2}, lambda: _per_query_mean(_sk_hit_at(2))),
        (RetrievalFallOut, {"k": 2}, lambda: _per_query_mean(_sk_fallout_at(2))),
        (RetrievalRPrecision, {}, lambda: _per_query_mean(_sk_rprec)),
        (
            RetrievalNormalizedDCG,
            {},
            lambda: _per_query_mean(lambda p, t: sk_ndcg(t[None, :], p[None, :])),
        ),
        (
            RetrievalNormalizedDCG,
            {"k": 3},
            lambda: _per_query_mean(lambda p, t: sk_ndcg(t[None, :], p[None, :], k=3)),
        ),
    ],
)
def test_retrieval_metric_parity(metric_class, metric_args, oracle):
    metric = metric_class(**metric_args)
    # batched updates split mid-query to exercise cross-batch grouping
    half = len(PREDS) // 2
    metric.update(PREDS[:half], TARGET[:half], indexes=INDEXES[:half])
    metric.update(PREDS[half:], TARGET[half:], indexes=INDEXES[half:])
    np.testing.assert_allclose(np.asarray(metric.compute()), oracle(), atol=1e-5)


def test_empty_target_actions():
    indexes = jnp.asarray([0, 0, 1, 1])
    preds = jnp.asarray([0.3, 0.7, 0.2, 0.8], dtype=jnp.float32)
    target = jnp.asarray([0, 1, 0, 0])  # query 1 has no positives

    for action, expected in [("neg", (1.0 + 0.0) / 2), ("pos", (1.0 + 1.0) / 2), ("skip", 1.0)]:
        m = RetrievalMAP(empty_target_action=action)
        m.update(preds, target, indexes=indexes)
        assert float(m.compute()) == pytest.approx(expected), action

    m = RetrievalMAP(empty_target_action="error")
    m.update(preds, target, indexes=indexes)
    with pytest.raises(ValueError, match="no positive"):
        m.compute()


def test_fall_out_inverted_empty_handling():
    indexes = jnp.asarray([0, 0, 1, 1])
    preds = jnp.asarray([0.3, 0.7, 0.2, 0.8], dtype=jnp.float32)
    target = jnp.asarray([0, 1, 1, 1])  # query 1 has no negatives

    m = RetrievalFallOut(empty_target_action="error")
    m.update(preds, target, indexes=indexes)
    with pytest.raises(ValueError, match="no negative"):
        m.compute()


def test_ignore_index():
    indexes = jnp.asarray([0, 0, 0])
    preds = jnp.asarray([0.3, 0.7, 0.5], dtype=jnp.float32)
    target = jnp.asarray([0, 1, -100])
    m = RetrievalMAP(ignore_index=-100)
    m.update(preds, target, indexes=indexes)
    assert float(m.compute()) == pytest.approx(1.0)


def test_invalid_args():
    with pytest.raises(ValueError):
        RetrievalMAP(empty_target_action="bad")
    with pytest.raises(ValueError):
        RetrievalMAP(ignore_index="bad")
    with pytest.raises(ValueError):
        RetrievalPrecision(k=-1)
    m = RetrievalMAP()
    with pytest.raises(ValueError):
        m.update(PREDS, TARGET, indexes=None)


def test_functional_kernels():
    p = jnp.asarray([0.2, 0.3, 0.5], dtype=jnp.float32)
    t = jnp.asarray([True, False, True])
    assert float(retrieval_average_precision(p, t)) == pytest.approx((1 / 1 + 2 / 3) / 2)
    assert float(retrieval_reciprocal_rank(p, t)) == pytest.approx(1.0)
    assert float(retrieval_precision(p, t, k=2)) == pytest.approx(0.5)
    assert float(retrieval_recall(p, t, k=2)) == pytest.approx(0.5)
    assert float(retrieval_hit_rate(p, t, k=2)) == pytest.approx(1.0)
    assert float(retrieval_fall_out(p, t, k=2)) == pytest.approx(1.0)
    assert float(retrieval_r_precision(p, t)) == pytest.approx(0.5)
    nd = retrieval_normalized_dcg(jnp.asarray([0.1, 0.2, 0.3, 4.0, 70.0]), jnp.asarray([10, 0, 0, 1, 5]))
    expected = sk_ndcg(np.asarray([[10, 0, 0, 1, 5]]), np.asarray([[0.1, 0.2, 0.3, 4.0, 70.0]]))
    np.testing.assert_allclose(np.asarray(nd), expected, atol=1e-5)

    # no-positive queries return 0
    t0 = jnp.asarray([False, False, False])
    assert float(retrieval_average_precision(p, t0)) == 0.0
    assert float(retrieval_reciprocal_rank(p, t0)) == 0.0


# ---------------------------------------------------------------------------
# padded single-jit compute path vs host group-loop (exact-parity fallback)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "metric_class, metric_args",
    [
        (RetrievalMAP, {}),
        (RetrievalMRR, {}),
        (RetrievalPrecision, {"k": 3}),
        (RetrievalPrecision, {}),
        (RetrievalRecall, {"k": 3}),
        (RetrievalRecall, {}),
        (RetrievalHitRate, {"k": 3}),
        (RetrievalFallOut, {"k": 3}),
        (RetrievalRPrecision, {}),
        (RetrievalNormalizedDCG, {}),
        (RetrievalNormalizedDCG, {"k": 4}),
    ],
)
@pytest.mark.parametrize("action", ["neg", "pos", "skip"])
def test_padded_compute_equals_host_loop(metric_class, metric_args, action):
    """The single-jit padded path must agree with the per-group host loop on
    uneven group sizes, queries with no positives, and all-positive queries."""
    rng = np.random.default_rng(5)
    idx_list, preds_list, target_list = [], [], []
    for q in range(30):
        n = int(rng.integers(1, 13))
        idx_list.append(np.full(n, q))
        preds_list.append(rng.random(n).astype(np.float32))
        if q % 7 == 0:
            t = np.zeros(n)  # no positives: exercises empty action
        elif q % 7 == 1:
            t = np.ones(n)  # no negatives: exercises fall-out empty action
        else:
            t = rng.integers(0, 2, n)
        target_list.append(t.astype(np.int32))
    indexes = jnp.asarray(np.concatenate(idx_list))
    preds = jnp.asarray(np.concatenate(preds_list))
    target = jnp.asarray(np.concatenate(target_list))

    m = metric_class(empty_target_action=action, exact=True, **metric_args)
    assert type(m)._padded_metric is not None  # library classes all have kernels
    m.update(preds, target, indexes=indexes)
    padded_val = np.asarray(m._compute())
    host_val = np.asarray(m._compute_host_loop())
    np.testing.assert_allclose(padded_val, host_val, atol=1e-6)


def test_padded_graded_ndcg_equals_host_loop():
    rng = np.random.default_rng(9)
    n_per = [3, 8, 5, 12, 1]
    indexes = jnp.asarray(np.concatenate([np.full(n, q) for q, n in enumerate(n_per)]))
    preds = jnp.asarray(rng.random(sum(n_per)).astype(np.float32))
    target = jnp.asarray(rng.integers(0, 6, sum(n_per)).astype(np.int32))  # graded
    m = RetrievalNormalizedDCG(k=4, exact=True)
    m.update(preds, target, indexes=indexes)
    np.testing.assert_allclose(np.asarray(m._compute()), np.asarray(m._compute_host_loop()), atol=1e-6)


def test_padded_error_action_raises():
    m = RetrievalMAP(empty_target_action="error")
    m.update(jnp.asarray([0.1, 0.2]), jnp.asarray([0, 0]), indexes=jnp.asarray([0, 0]))
    with pytest.raises(ValueError, match="no positive"):
        m.compute()


def test_custom_subclass_falls_back_to_host_loop():
    from metrics_tpu.retrieval.base import RetrievalMetric

    class MyMetric(RetrievalMetric):
        def _metric(self, preds, target):
            return jnp.max(preds * target)

    m = MyMetric()
    assert m._padded_metric is None
    m.update(jnp.asarray([0.2, 0.9]), jnp.asarray([1, 0]), indexes=jnp.asarray([0, 0]))
    np.testing.assert_allclose(np.asarray(m.compute()), 0.2, atol=1e-6)


def test_skewed_groups_fall_back_to_host_loop():
    """One huge query among many tiny ones must not densify into a huge pad."""
    from metrics_tpu.functional.retrieval.padded import pack_queries

    rng = np.random.default_rng(3)
    # 200 single-doc queries + 1 query with 400 docs: Q*Dmax = 201*400 >> 16*600
    idx = np.concatenate([np.arange(200), np.full(400, 200)])
    n = len(idx)
    preds = rng.random(n).astype(np.float32)
    target = rng.integers(0, 2, n).astype(np.int32)

    assert pack_queries(jnp.asarray(idx), jnp.asarray(preds), jnp.asarray(target), max_expand=16) is None

    m = RetrievalMAP(exact=True)
    m.update(jnp.asarray(preds), jnp.asarray(target), indexes=jnp.asarray(idx))
    np.testing.assert_allclose(np.asarray(m._compute()), np.asarray(m._compute_host_loop()), atol=1e-6)


def test_pack_queries_empty_raises_descriptive():
    """compute-before-update must raise a clear message, not an IndexError
    (functional/retrieval/padded.py pack_queries zero-length guard)."""
    from metrics_tpu.functional.retrieval.padded import pack_queries

    with pytest.raises(ValueError, match="no accumulated samples"):
        pack_queries(
            jnp.zeros((0,), jnp.int32), jnp.zeros((0,), jnp.float32), jnp.zeros((0,), jnp.float32)
        )


def test_collection_shares_one_pack_across_metrics(monkeypatch):
    """An NDCG+MAP MetricCollection forms one compute group (identical
    states), and the padded path packs the ragged layout ONCE for both
    metrics (pack_queries_cached keyed on the shared state arrays)."""
    import metrics_tpu.functional.retrieval.padded as padded
    from metrics_tpu import MetricCollection
    from metrics_tpu.retrieval import RetrievalNormalizedDCG

    calls = {"n": 0}
    orig = padded.pack_queries

    def counting(*args, **kwargs):
        calls["n"] += 1
        return orig(*args, **kwargs)

    monkeypatch.setattr(padded, "pack_queries", counting)

    rng = np.random.default_rng(9)
    idx = np.repeat(np.arange(40), 10)
    preds = rng.random(400).astype(np.float32)
    target = rng.integers(0, 2, 400).astype(np.int32)

    col = MetricCollection([RetrievalNormalizedDCG(exact=True), RetrievalMAP(exact=True)])
    col.update(jnp.asarray(preds), jnp.asarray(target), indexes=jnp.asarray(idx))
    out = col.compute()
    assert calls["n"] == 1  # one pack for both metrics

    # further updates change the state arrays -> cache miss, ONE repack
    col.update(jnp.asarray(preds), jnp.asarray(target), indexes=jnp.asarray(idx))
    col.compute()
    assert calls["n"] == 2

    # parity vs an independent metric (its own state -> its own pack)
    solo = RetrievalMAP(exact=True)
    solo.update(jnp.asarray(preds), jnp.asarray(target), indexes=jnp.asarray(idx))
    np.testing.assert_allclose(
        np.asarray(out["RetrievalMAP"]), np.asarray(solo.compute()), atol=1e-6
    )
    assert calls["n"] == 3


def test_pack_cache_entry_freed_with_its_arrays():
    """The pack cache must not keep state (or packed) buffers alive after the
    owning metric is gone — weakref finalizers purge the entry."""
    import gc

    import metrics_tpu.functional.retrieval.padded as padded

    padded._PACK_CACHE.clear()
    m = RetrievalMAP(exact=True)
    m.update(
        jnp.asarray([0.3, 0.7, 0.2, 0.9]), jnp.asarray([0, 1, 1, 0]), indexes=jnp.asarray([0, 0, 1, 1])
    )
    m.compute()
    assert len(padded._PACK_CACHE) == 1
    m.compute()  # second compute on unchanged state hits the cache
    assert len(padded._PACK_CACHE) == 1
    del m
    gc.collect()
    assert len(padded._PACK_CACHE) == 0


def test_collection_shares_one_row_sort(monkeypatch):
    """Metrics over the same pack share ONE per-row argsort
    (sorted_row_layout memoized per pack) and still match the host loop."""
    import metrics_tpu.functional.retrieval.padded as padded
    from metrics_tpu import MetricCollection
    from metrics_tpu.retrieval import RetrievalNormalizedDCG

    calls = {"n": 0}
    orig = padded._sorted_layout

    def counting(*args):
        calls["n"] += 1
        return orig(*args)

    monkeypatch.setattr(padded, "_sorted_layout", counting)

    rng = np.random.default_rng(11)
    idx = np.repeat(np.arange(30), 8)
    preds = rng.random(240).astype(np.float32)
    target = rng.integers(0, 2, 240).astype(np.int32)

    col = MetricCollection([RetrievalNormalizedDCG(exact=True), RetrievalMAP(exact=True)])
    col.update(jnp.asarray(preds), jnp.asarray(target), indexes=jnp.asarray(idx))
    out = col.compute()
    assert calls["n"] == 1  # one argsort for both metrics

    solo = RetrievalMAP(exact=True)
    solo.update(jnp.asarray(preds), jnp.asarray(target), indexes=jnp.asarray(idx))
    np.testing.assert_allclose(
        np.asarray(out["RetrievalMAP"]), np.asarray(solo._compute_host_loop()), atol=1e-6
    )


def test_custom_padded_kernel_without_sorted_variant_still_works():
    """User-supplied row kernels (no sorted_fn attribute) run through the
    legacy raw path."""
    from metrics_tpu.retrieval.base import RetrievalMetric

    def max_pos_score_row(preds, target, mask, k=None):
        return jnp.max(jnp.where((target > 0) & mask, preds, -jnp.inf))

    class MaxPosScore(RetrievalMetric):
        _padded_metric = staticmethod(max_pos_score_row)

        def _metric(self, preds, target):
            return jnp.max(jnp.where(target > 0, preds, -jnp.inf))

    m = MaxPosScore()
    m.update(
        jnp.asarray([0.2, 0.9, 0.5, 0.4]), jnp.asarray([1, 0, 1, 1]), indexes=jnp.asarray([0, 0, 1, 1])
    )
    np.testing.assert_allclose(float(m.compute()), (0.2 + 0.5) / 2, atol=1e-6)
