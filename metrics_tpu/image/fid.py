"""Frechet Inception Distance.

Behavior parity with /root/reference/torchmetrics/image/fid.py:26-280: list
states of extracted features, float64 statistics ("extremely sensitive",
fid.py:261-264), sqrtm of the covariance product with the singularity
eps-offset retry.

TPU-native departures: ``feature`` accepts any callable ``imgs -> [N, d]``
(JAX or host function; the reference takes an ``nn.Module``) or an int
depth which builds the bundled Flax InceptionV3 (weights must be provided —
this environment has no network access to fetch the FID-compat weights).
The matrix square root uses the symmetric-eigendecomposition identity
``Tr sqrtm(S1 S2) = sum sqrt eig(S1^1/2 S2 S1^1/2)`` in numpy float64 on
host (replacing scipy's general sqrtm — the FID value only needs the
trace, and the symmetrized form is PSD so eigh is exact and stable).
"""
from typing import Any, Callable, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.core.metric import Metric
from metrics_tpu.utils.data import dim_zero_cat
from metrics_tpu.utils.prints import rank_zero_info, rank_zero_warn

Array = jax.Array


def _sqrtm_eigh(mat: np.ndarray) -> np.ndarray:
    """Symmetric PSD square root via eigendecomposition (float64 host)."""
    vals, vecs = np.linalg.eigh(mat)
    vals = np.clip(vals, 0.0, None)
    return (vecs * np.sqrt(vals)) @ vecs.T


def _trace_sqrtm_product(sigma1: np.ndarray, sigma2: np.ndarray) -> float:
    """Tr[(sigma1 @ sigma2)^(1/2)] for symmetric PSD sigma1, sigma2."""
    s1_half = _sqrtm_eigh(sigma1)
    m = s1_half @ sigma2 @ s1_half
    vals = np.linalg.eigvalsh((m + m.T) / 2)
    return float(np.sqrt(np.clip(vals, 0.0, None)).sum())


def _compute_fid(
    mu1: np.ndarray, sigma1: np.ndarray, mu2: np.ndarray, sigma2: np.ndarray, eps: float = 1e-6
) -> float:
    """d^2 = ||mu1 - mu2||^2 + Tr(s1 + s2 - 2 sqrtm(s1 s2)). Reference fid.py:95-122."""
    diff = mu1 - mu2

    # eigvalsh raises LinAlgError (rather than returning NaN the way scipy's
    # sqrtm does) when the product is numerically degenerate — map both
    # failure shapes onto the reference's add-eps-and-retry path (fid.py:95-122)
    try:
        tr_covmean = _trace_sqrtm_product(sigma1, sigma2)
    except np.linalg.LinAlgError:
        tr_covmean = float("nan")
    if not np.isfinite(tr_covmean):
        rank_zero_info(f"FID calculation produces singular product; adding {eps} to diagonal of covariance estimates")
        offset = np.eye(sigma1.shape[0]) * eps
        try:
            tr_covmean = _trace_sqrtm_product(sigma1 + offset, sigma2 + offset)
        except np.linalg.LinAlgError as err:
            raise ValueError(
                "FID covariance square root failed even after adding eps to the diagonals —"
                " the feature matrices likely contain NaN/Inf (broken or overflowing extractor)."
            ) from err

    return float(diff @ diff + np.trace(sigma1) + np.trace(sigma2) - 2 * tr_covmean)


class FrechetInceptionDistance(Metric):
    """Computes the FID between real and generated image distributions.

    Args:
        feature: a callable mapping an image batch to ``[N, d]`` features, or
            an int in (64, 192, 768, 2048) selecting the bundled Flax
            InceptionV3 depth (requires local weights).
        feature_extractor_weights_path: npz checkpoint for the bundled
            InceptionV3 (int ``feature`` only).
    """

    __jit_unsafe__ = True
    is_differentiable = False
    higher_is_better = False

    def __init__(
        self,
        feature: Union[int, Callable] = 2048,
        feature_extractor_weights_path: str = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)

        rank_zero_warn(
            "Metric `FrechetInceptionDistance` will save all extracted features in buffer."
            " For large datasets this may lead to large memory footprint.",
            UserWarning,
        )

        if isinstance(feature, int):
            valid_int_input = (64, 192, 768, 2048)
            if feature not in valid_int_input:
                raise ValueError(
                    f"Integer input to argument `feature` must be one of {valid_int_input}, but got {feature}."
                )
            from metrics_tpu.models.inception import build_fid_inception

            self.inception = build_fid_inception(feature, feature_extractor_weights_path)
        elif callable(feature):
            self.inception = feature
        else:
            raise TypeError("Got unknown input to argument `feature`")

        self.add_state("real_features", [], dist_reduce_fx=None)
        self.add_state("fake_features", [], dist_reduce_fx=None)

    def _update(self, imgs: Array, real: bool) -> None:
        features = self.inception(imgs)
        if real:
            self.real_features.append(features)
        else:
            self.fake_features.append(features)

    def _compute(self) -> Array:
        getattr(self.inception, "finalize", lambda: None)()  # flush async range check of the last batch
        real_features = dim_zero_cat(self.real_features)
        fake_features = dim_zero_cat(self.fake_features)
        orig_dtype = real_features.dtype

        # float64 statistics on host — the computation is extremely sensitive
        real = np.asarray(real_features, dtype=np.float64)
        fake = np.asarray(fake_features, dtype=np.float64)

        n = real.shape[0]
        mean1 = real.mean(axis=0)
        mean2 = fake.mean(axis=0)
        diff1 = real - mean1
        diff2 = fake - mean2
        cov1 = diff1.T @ diff1 / (n - 1)
        cov2 = diff2.T @ diff2 / (fake.shape[0] - 1)

        return jnp.asarray(_compute_fid(mean1, cov1, mean2, cov2), dtype=orig_dtype)
