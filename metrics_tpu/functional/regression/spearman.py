"""Spearman rank correlation.

Behavior parity with /root/reference/torchmetrics/functional/regression/
spearman.py:22-120. The reference's tie-averaging is a Python loop over
repeated values (spearman.py:49-52); here ranks are tie-averaged fully
vectorized with a sort + segment-sum — jit-safe and O(n log n) on device.
"""
from typing import Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _check_same_shape

Array = jax.Array


def _rank_data(data: Array) -> Array:
    """Ranks (1-based); ties get the mean of their ranks. Fully vectorized."""
    n = data.size
    idx = jnp.argsort(data)
    sorted_x = data[idx]
    ranks = jnp.arange(1, n + 1, dtype=jnp.float32)

    is_start = jnp.concatenate([jnp.array([True]), sorted_x[1:] != sorted_x[:-1]])
    group_id = jnp.cumsum(is_start) - 1
    group_sum = jax.ops.segment_sum(ranks, group_id, num_segments=n)
    group_cnt = jax.ops.segment_sum(jnp.ones_like(ranks), group_id, num_segments=n)
    mean_rank_sorted = (group_sum / jnp.maximum(group_cnt, 1))[group_id]

    return jnp.zeros(n, dtype=data.dtype).at[idx].set(mean_rank_sorted.astype(data.dtype))


def _spearman_corrcoef_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    if preds.dtype != target.dtype:
        raise TypeError(
            "Expected `preds` and `target` to have the same data type."
            f" Got preds: {preds.dtype} and target: {target.dtype}."
        )
    _check_same_shape(preds, target)
    preds = jnp.squeeze(preds)
    target = jnp.squeeze(target)
    if preds.ndim > 1 or target.ndim > 1:
        raise ValueError("Expected both predictions and target to be 1 dimensional tensors.")
    return preds, target


def _spearman_corrcoef_compute(preds: Array, target: Array, eps: float = 1e-6) -> Array:
    rank_preds = _rank_data(preds)
    rank_target = _rank_data(target)

    preds_diff = rank_preds - jnp.mean(rank_preds)
    target_diff = rank_target - jnp.mean(rank_target)

    cov = jnp.mean(preds_diff * target_diff)
    preds_std = jnp.sqrt(jnp.mean(preds_diff * preds_diff))
    target_std = jnp.sqrt(jnp.mean(target_diff * target_diff))

    corrcoef = cov / (preds_std * target_std + eps)
    return jnp.clip(corrcoef, -1.0, 1.0)


def spearman_corrcoef(preds: Array, target: Array) -> Array:
    """Computes the Spearman rank correlation coefficient.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([3., -0.5, 2., 7.])
        >>> preds = jnp.array([2.5, 0.0, 2., 8.])
        >>> spearman_corrcoef(preds, target)
        Array(0.9999992, dtype=float32)
    """
    preds, target = _spearman_corrcoef_update(preds, target)
    return _spearman_corrcoef_compute(preds, target)
