"""Reference-parity sweep for the retrieval domain.

Breadth parity with /root/reference/tests/retrieval/ (the
RetrievalMetricTester parametrization, helpers.py:410-530): every metric x
k x empty_target_action over a shared ragged fixture that contains
empty-target queries, graded targets for NDCG, single-doc queries, and an
argument-validation sweep — with the reference implementation as oracle so
the empty-query policies and @k edge rules are pinned behaviorally.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from metrics_tpu.retrieval import (
    RetrievalFallOut,
    RetrievalHitRate,
    RetrievalMAP,
    RetrievalMRR,
    RetrievalNormalizedDCG,
    RetrievalPrecision,
    RetrievalRecall,
    RetrievalRPrecision,
)
from tests.helpers.reference import load_reference_module

torch = pytest.importorskip("torch")


# ragged fixture: 24 queries, 1-15 docs each, ~1/4 with no positive target,
# one single-doc query, one all-positive query
_rng = np.random.default_rng(55)
_idx_parts, _preds_parts, _target_parts = [], [], []
for q in range(24):
    n = int(_rng.integers(1, 16)) if q != 3 else 1
    t = (_rng.random(n) < 0.35).astype(np.int64)
    if q % 4 == 0:
        t[:] = 0  # empty-target query
    if q == 7:
        t[:] = 1  # all-positive query (FallOut's empty case)
    _idx_parts.append(np.full(n, q))
    _preds_parts.append(_rng.random(n).astype(np.float32))
    _target_parts.append(t)
IDX = np.concatenate(_idx_parts)
PREDS = np.concatenate(_preds_parts)
TARGET = np.concatenate(_target_parts)

# graded-relevance variant for NDCG
TARGET_GRADED = np.where(TARGET > 0, _rng.integers(1, 5, len(TARGET)), 0).astype(np.int64)


METRICS = [
    ("RetrievalMAP", RetrievalMAP, {}, False),
    ("RetrievalMRR", RetrievalMRR, {}, False),
    ("RetrievalRPrecision", RetrievalRPrecision, {}, False),
    ("RetrievalPrecision", RetrievalPrecision, {"k": 1}, False),
    ("RetrievalPrecision", RetrievalPrecision, {"k": 3}, False),
    ("RetrievalPrecision", RetrievalPrecision, {}, False),
    ("RetrievalRecall", RetrievalRecall, {"k": 1}, False),
    ("RetrievalRecall", RetrievalRecall, {"k": 3}, False),
    ("RetrievalHitRate", RetrievalHitRate, {"k": 1}, False),
    ("RetrievalHitRate", RetrievalHitRate, {"k": 3}, False),
    ("RetrievalFallOut", RetrievalFallOut, {"k": 3}, False),
    ("RetrievalNormalizedDCG", RetrievalNormalizedDCG, {"k": 3}, False),
    ("RetrievalNormalizedDCG", RetrievalNormalizedDCG, {}, True),
]
METRIC_IDS = [
    f"{name}{'-k' + str(args['k']) if 'k' in args else ''}{'-graded' if graded else ''}"
    for name, _, args, graded in METRICS
]


def _ref_retrieval(name, **kwargs):
    mod = load_reference_module("torchmetrics.retrieval")
    return getattr(mod, name)(**kwargs)


@pytest.mark.parametrize("action", ["neg", "pos", "skip"])
@pytest.mark.parametrize("name, cls, args, graded", METRICS, ids=METRIC_IDS)
def test_retrieval_reference_parity(name, cls, args, graded, action):
    """Accumulated value matches the reference metric with identical
    arguments, across every empty-query policy, fed in two uneven batches
    that split mid-query."""
    target = TARGET_GRADED if graded else TARGET
    ours = cls(empty_target_action=action, **args)
    ref = _ref_retrieval(name, empty_target_action=action, **args)

    half = len(PREDS) // 2
    for lo, hi in ((0, half), (half, len(PREDS))):
        ours.update(
            jnp.asarray(PREDS[lo:hi]), jnp.asarray(target[lo:hi]), indexes=jnp.asarray(IDX[lo:hi])
        )
        ref.update(
            torch.as_tensor(PREDS[lo:hi]),
            torch.as_tensor(target[lo:hi]),
            indexes=torch.as_tensor(IDX[lo:hi]),
        )
    np.testing.assert_allclose(
        float(ours.compute()), float(ref.compute()), atol=1e-5, err_msg=f"{name} {args} {action}"
    )


@pytest.mark.parametrize("name, cls, args, graded", METRICS, ids=METRIC_IDS)
def test_retrieval_error_action_raises_like_reference(name, cls, args, graded):
    """`empty_target_action='error'` raises on both sides with the SAME
    message (reference helpers.py `_errors_test_class_metric_parameters_no_
    pos_target` / `_no_neg_target`): 'no positive target' for the standard
    metrics, 'no negative target' for FallOut (its empty case is inverted —
    the fixture's all-positive query 7 triggers it)."""
    expected = (
        "no negative target" if cls is RetrievalFallOut else "no positive target"
    )
    ours = cls(empty_target_action="error", **args)
    ours.update(jnp.asarray(PREDS), jnp.asarray(TARGET), indexes=jnp.asarray(IDX))
    with pytest.raises(ValueError, match=expected):
        ours.compute()

    ref = _ref_retrieval(name, empty_target_action="error", **args)
    ref.update(torch.as_tensor(PREDS), torch.as_tensor(TARGET), indexes=torch.as_tensor(IDX))
    with pytest.raises(ValueError, match=expected):
        ref.compute()


@pytest.mark.parametrize("action", ["skip", "neg", "pos"])
@pytest.mark.parametrize("name, cls, args, graded", METRICS, ids=METRIC_IDS)
def test_retrieval_ignore_index_action_k_parity(name, cls, args, graded, action):
    """The full empty_target_action x ignore_index x k cross-product the
    reference's RetrievalMetricTester sweeps (tests/retrieval/test_*.py
    `test_class_metric_ignore_index`): every metric (incl. each k variant)
    with ignore_index=-100 over a fixture where ignored positions erase
    ENTIRE queries (so the policy actually fires on post-filter-empty
    queries), against the reference with identical arguments."""
    target = (TARGET_GRADED if graded else TARGET).copy()
    target[::7] = -100  # sprinkle ignored positions...
    target[IDX == 5] = -100  # ...and erase one whole query
    ours = cls(ignore_index=-100, empty_target_action=action, **args)
    ref = _ref_retrieval(name, ignore_index=-100, empty_target_action=action, **args)
    ours.update(jnp.asarray(PREDS), jnp.asarray(target), indexes=jnp.asarray(IDX))
    ref.update(torch.as_tensor(PREDS), torch.as_tensor(target), indexes=torch.as_tensor(IDX))
    np.testing.assert_allclose(
        float(ours.compute()), float(ref.compute()), atol=1e-5, err_msg=f"{name} {args} {action}"
    )


@pytest.mark.parametrize("ignore_index", [-100, 0])
def test_retrieval_ignore_index_parity(ignore_index):
    target = TARGET.copy()
    target[::7] = ignore_index  # sprinkle ignored positions
    ours = RetrievalMAP(ignore_index=ignore_index, empty_target_action="skip")
    ref = _ref_retrieval("RetrievalMAP", ignore_index=ignore_index, empty_target_action="skip")
    ours.update(jnp.asarray(PREDS), jnp.asarray(target), indexes=jnp.asarray(IDX))
    ref.update(torch.as_tensor(PREDS), torch.as_tensor(target), indexes=torch.as_tensor(IDX))
    np.testing.assert_allclose(float(ours.compute()), float(ref.compute()), atol=1e-5)


# ---------------------------------------------------------------------------
# argument-validation sweep (RetrievalMetricTester's "arguments" checks)
# ---------------------------------------------------------------------------

ALL_CLASSES = sorted(
    {cls for _, cls, _, _ in METRICS}, key=lambda c: c.__name__
)


@pytest.mark.parametrize("cls", ALL_CLASSES, ids=[c.__name__ for c in ALL_CLASSES])
def test_retrieval_argument_validation(cls):
    with pytest.raises(ValueError, match="empty_target_action"):
        cls(empty_target_action="casual_argument")
    with pytest.raises(ValueError, match="ignore_index"):
        cls(ignore_index="not an int")

    m = cls()
    # indexes are required
    with pytest.raises(ValueError, match="`indexes`"):
        m.update(jnp.asarray([0.1, 0.2]), jnp.asarray([0, 1]), indexes=None)
    # shape mismatch
    with pytest.raises(ValueError, match="same shape"):
        m.update(jnp.asarray([0.1, 0.2]), jnp.asarray([0, 1, 1]), indexes=jnp.asarray([0, 0, 0]))
    # float indexes rejected
    with pytest.raises(ValueError, match="long integers"):
        m.update(jnp.asarray([0.1, 0.2]), jnp.asarray([0, 1]), indexes=jnp.asarray([0.0, 0.0]))
    # integer preds rejected
    with pytest.raises(ValueError, match="float"):
        m.update(jnp.asarray([1, 0]), jnp.asarray([0, 1]), indexes=jnp.asarray([0, 0]))
    # empty tensors rejected (reference: "must be non-empty and non-scalar")
    with pytest.raises(ValueError, match="non-empty"):
        m.update(
            jnp.zeros((0,), jnp.float32),
            jnp.zeros((0,), jnp.int32),
            indexes=jnp.zeros((0,), jnp.int32),
        )
    # ignore_index erasing EVERYTHING leaves empty tensors -> same error
    with pytest.raises(ValueError, match="non-empty"):
        me = cls(ignore_index=-100)
        me.update(
            jnp.asarray([0.1, 0.2]), jnp.asarray([-100, -100]), indexes=jnp.asarray([0, 0])
        )


FUNCTIONALS = [
    ("retrieval_average_precision", False, False),
    ("retrieval_reciprocal_rank", False, False),
    ("retrieval_r_precision", False, False),
    ("retrieval_precision", True, False),
    ("retrieval_recall", True, False),
    ("retrieval_hit_rate", True, False),
    ("retrieval_fall_out", True, False),
    ("retrieval_normalized_dcg", True, True),
]


@pytest.mark.parametrize("fname, has_k, graded_ok", FUNCTIONALS, ids=[f[0] for f in FUNCTIONALS])
def test_retrieval_functional_error_matrix(fname, has_k, graded_ok):
    """The reference's `_errors_test_functional_metric_parameters_default` /
    `_with_nonbinary` / `_k` matrices (tests/retrieval/helpers.py:131-163)
    across all 8 functional kernels: shape mismatch, empty input, non-float
    preds, non-binary target (where disallowed), and invalid k (where
    accepted) — with the reference's error messages."""
    import metrics_tpu.functional.retrieval as F

    fn = getattr(F, fname)
    good_p, good_t = jnp.asarray([0.2, 0.7, 0.4]), jnp.asarray([0, 1, 1])

    with pytest.raises(ValueError, match="same shape"):
        fn(good_p, jnp.asarray([0, 1]))
    with pytest.raises(ValueError, match="non-empty and non-scalar"):
        fn(jnp.zeros((0,), jnp.float32), jnp.zeros((0,), jnp.int32))
    with pytest.raises(ValueError, match="`preds` must be a tensor of floats"):
        fn(jnp.asarray([True, False, True]), good_t)
    if not graded_ok:
        with pytest.raises(ValueError, match="binary"):
            fn(good_p, jnp.asarray([0, 3, 1]))
    if has_k:
        with pytest.raises(ValueError, match="positive integer or None"):
            fn(good_p, good_t, k=-10)
        with pytest.raises(ValueError, match="positive integer or None"):
            fn(good_p, good_t, k=4.0)


@pytest.mark.parametrize(
    "cls", [RetrievalPrecision, RetrievalRecall, RetrievalHitRate, RetrievalFallOut, RetrievalNormalizedDCG]
)
def test_retrieval_k_validation(cls):
    with pytest.raises(ValueError, match="`k`"):
        cls(k=-1)
    with pytest.raises(ValueError, match="`k`"):
        cls(k=0)
    with pytest.raises(ValueError, match="`k`"):
        cls(k=1.5)


def test_retrieval_non_binary_target_rejected_where_disallowed():
    m = RetrievalMAP()
    with pytest.raises(ValueError, match="binary"):
        m.update(jnp.asarray([0.1, 0.2]), jnp.asarray([0, 3]), indexes=jnp.asarray([0, 0]))
    # NDCG allows graded targets
    ndcg = RetrievalNormalizedDCG()
    ndcg.update(jnp.asarray([0.1, 0.2]), jnp.asarray([0, 3]), indexes=jnp.asarray([0, 0]))
    assert float(ndcg.compute()) >= 0.0


def test_retrieval_single_query_single_doc():
    """Degenerate layouts: one query, one doc (positive and negative)."""
    pos = RetrievalMAP()
    pos.update(jnp.asarray([0.5]), jnp.asarray([1]), indexes=jnp.asarray([0]))
    assert float(pos.compute()) == 1.0
    neg = RetrievalMAP(empty_target_action="neg")
    neg.update(jnp.asarray([0.5]), jnp.asarray([0]), indexes=jnp.asarray([0]))
    assert float(neg.compute()) == 0.0


def test_retrieval_nonconsecutive_query_ids():
    """Query ids need not be dense/consecutive (reference get_group_indexes
    contract): sparse ids give the same result as densified ones."""
    sparse = jnp.asarray([100, 100, 7, 7, 9000])
    dense = jnp.asarray([0, 0, 1, 1, 2])
    preds = jnp.asarray([0.9, 0.1, 0.8, 0.3, 0.7])
    target = jnp.asarray([1, 0, 0, 1, 1])
    a, b = RetrievalMAP(), RetrievalMAP()
    a.update(preds, target, indexes=sparse)
    b.update(preds, target, indexes=dense)
    np.testing.assert_allclose(float(a.compute()), float(b.compute()), atol=1e-6)
