from metrics_tpu.functional.classification.accuracy import accuracy  # noqa: F401
from metrics_tpu.functional.classification.f_beta import f1_score, fbeta_score  # noqa: F401
from metrics_tpu.functional.classification.hamming import hamming_distance  # noqa: F401
from metrics_tpu.functional.classification.precision_recall import precision, precision_recall, recall  # noqa: F401
from metrics_tpu.functional.classification.specificity import specificity  # noqa: F401
from metrics_tpu.functional.classification.stat_scores import stat_scores  # noqa: F401
from metrics_tpu.functional.regression import (  # noqa: F401
    cosine_similarity,
    explained_variance,
    mean_absolute_error,
    mean_absolute_percentage_error,
    mean_squared_error,
    mean_squared_log_error,
    pearson_corrcoef,
    r2_score,
    spearman_corrcoef,
    symmetric_mean_absolute_percentage_error,
    tweedie_deviance_score,
)
from metrics_tpu.functional.classification.auc import auc  # noqa: F401
from metrics_tpu.functional.classification.auroc import auroc  # noqa: F401
from metrics_tpu.functional.classification.average_precision import average_precision  # noqa: F401
from metrics_tpu.functional.classification.precision_recall_curve import precision_recall_curve  # noqa: F401
from metrics_tpu.functional.classification.roc import roc  # noqa: F401
from metrics_tpu.functional.classification.calibration_error import calibration_error  # noqa: F401
from metrics_tpu.functional.classification.cohen_kappa import cohen_kappa  # noqa: F401
from metrics_tpu.functional.classification.confusion_matrix import confusion_matrix  # noqa: F401
from metrics_tpu.functional.classification.dice import dice_score  # noqa: F401
from metrics_tpu.functional.classification.hinge import hinge_loss  # noqa: F401
from metrics_tpu.functional.classification.jaccard import jaccard_index  # noqa: F401
from metrics_tpu.functional.classification.kl_divergence import kl_divergence  # noqa: F401
from metrics_tpu.functional.classification.matthews_corrcoef import matthews_corrcoef  # noqa: F401
from metrics_tpu.functional.retrieval import (  # noqa: F401
    retrieval_average_precision,
    retrieval_fall_out,
    retrieval_hit_rate,
    retrieval_normalized_dcg,
    retrieval_precision,
    retrieval_r_precision,
    retrieval_recall,
    retrieval_reciprocal_rank,
)
from metrics_tpu.functional.pairwise import (  # noqa: F401
    pairwise_cosine_similarity,
    pairwise_euclidean_distance,
    pairwise_linear_similarity,
    pairwise_manhattan_distance,
)
from metrics_tpu.functional.image import (  # noqa: F401
    image_gradients,
    multiscale_structural_similarity_index_measure,
    peak_signal_noise_ratio,
    structural_similarity_index_measure,
    universal_image_quality_index,
)
from metrics_tpu.functional.audio import (  # noqa: F401
    permutation_invariant_training,
    pit_permutate,
    scale_invariant_signal_distortion_ratio,
    scale_invariant_signal_noise_ratio,
    signal_distortion_ratio,
    signal_noise_ratio,
)
from metrics_tpu.functional.text import (  # noqa: F401
    bert_score,
    bleu_score,
    char_error_rate,
    chrf_score,
    extended_edit_distance,
    match_error_rate,
    rouge_score,
    sacre_bleu_score,
    squad,
    translation_edit_rate,
    word_error_rate,
    word_information_lost,
    word_information_preserved,
)
