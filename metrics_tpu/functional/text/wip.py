"""Word Information Preserved (parity: /root/reference/torchmetrics/functional/text/wip.py)."""
from typing import List, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.helper import _edit_distance

Array = jax.Array


def _wip_update(
    preds: Union[str, List[str]], target: Union[str, List[str]]
) -> Tuple[Array, Array, Array]:
    """Accumulate negative hit counts and word totals (wip.py:21-51); see wil.py."""
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [target]
    errors = 0
    target_total = 0
    preds_total = 0
    total = 0
    for pred, tgt in zip(preds, target):
        pred_tokens = pred.split()
        tgt_tokens = tgt.split()
        errors += _edit_distance(pred_tokens, tgt_tokens)
        target_total += len(tgt_tokens)
        preds_total += len(pred_tokens)
        total += max(len(tgt_tokens), len(pred_tokens))
    return (
        jnp.asarray(errors - total, jnp.float32),
        jnp.asarray(target_total, jnp.float32),
        jnp.asarray(preds_total, jnp.float32),
    )


def _wip_compute(errors: Array, target_total: Array, preds_total: Array) -> Array:
    return (errors / target_total) * (errors / preds_total)


def word_information_preserved(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Array:
    """Word information preserved of transcription(s); 1 is perfect.

    Example:
        >>> preds = ["this is the prediction", "there is an other sample"]
        >>> target = ["this is the reference", "there is another one"]
        >>> word_information_preserved(preds=preds, target=target)
        Array(0.34722224, dtype=float32)
    """
    errors, target_total, preds_total = _wip_update(preds, target)
    return _wip_compute(errors, target_total, preds_total)
