"""Modular HingeLoss.

Behavior parity with /root/reference/torchmetrics/classification/hinge.py:22-120.
"""
from typing import Any, Optional, Union

import jax
import jax.numpy as jnp

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.hinge import (
    MulticlassMode,
    _hinge_compute,
    _hinge_update,
)

Array = jax.Array


class HingeLoss(Metric):
    """Computes the mean hinge loss.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([0, 1, 1])
        >>> preds = jnp.array([-2.2, 2.4, 0.1])
        >>> hinge = HingeLoss()
        >>> hinge(preds, target)
        Array(0.29999998, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = False

    def __init__(
        self,
        squared: bool = False,
        multiclass_mode: Optional[Union[str, MulticlassMode]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.add_state("measure", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")

        if multiclass_mode not in (None, MulticlassMode.CRAMMER_SINGER, MulticlassMode.ONE_VS_ALL):
            raise ValueError(
                "The `multiclass_mode` should be either None / 'crammer-singer' / MulticlassMode.CRAMMER_SINGER"
                "(default) or 'one-vs-all' / MulticlassMode.ONE_VS_ALL,"
                f" got {multiclass_mode}."
            )

        self.squared = squared
        self.multiclass_mode = multiclass_mode

    def _update(self, preds: Array, target: Array) -> None:
        measure, total = _hinge_update(preds, target, squared=self.squared, multiclass_mode=self.multiclass_mode)
        self.measure = measure + self.measure
        self.total = total + self.total

    def _compute(self) -> Array:
        return _hinge_compute(self.measure, self.total)
