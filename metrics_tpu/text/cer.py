"""Modular CharErrorRate.

Behavior parity with /root/reference/torchmetrics/text/cer.py:24-99.
"""
from typing import Any, List, Union

import jax

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.text.cer import _cer_compute, _cer_update

Array = jax.Array


class CharErrorRate(Metric):
    """Character error rate of transcriptions vs references; 0 is perfect.

    Example:
        >>> preds = ["this is the prediction", "there is an other sample"]
        >>> target = ["this is the reference", "there is another one"]
        >>> metric = CharErrorRate()
        >>> metric(preds, target)
        Array(0.34146342, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = False
    __jit_unsafe__ = True  # update consumes Python strings

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", default=0.0, dist_reduce_fx="sum")
        self.add_state("total", default=0.0, dist_reduce_fx="sum")

    def _update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        errors, total = _cer_update(preds, target)
        self.errors = self.errors + errors
        self.total = self.total + total

    def _compute(self) -> Array:
        return _cer_compute(self.errors, self.total)
