"""Permutation-invariant training (parity: /root/reference/torchmetrics/functional/audio/pit.py:28-194).

The reference picks between an exhaustive permutation search (spk < 3) and
scipy ``linear_sum_assignment`` on host (spk >= 3). Here the exhaustive
search is a fully vectorized device kernel — the metric matrix is gathered
along all P = spk! permutations in one ``take_along_axis`` and reduced on
device, which stays jittable and beats a host round-trip up to the default
``max_exhaustive_spk=6`` (720 perms). Beyond that our own C++ batched
Hungarian solver takes over (metrics_tpu/native/, compiled on demand;
scipy fallback) — host-side by nature, data-dependent — SURVEY §2.9.
"""
from itertools import permutations
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

_MAX_EXHAUSTIVE_SPK = 6


def _find_best_perm_exhaustive(metric_mtx: Array, eval_max: bool) -> Tuple[Array, Array]:
    """Score every permutation on device; mtx is [batch, spk, spk] with
    [b, target_i, pred_j] entries."""
    spk_num = metric_mtx.shape[-1]
    perms = jnp.asarray(list(permutations(range(spk_num))))  # [P, spk]
    # score[b, p] = mean_i mtx[b, i, perms[p, i]]
    gathered = jnp.take_along_axis(
        metric_mtx[:, None, :, :], perms[None, :, :, None], axis=-1
    )[..., 0]  # [batch, P, spk]
    scores = jnp.mean(gathered, axis=-1)  # [batch, P]
    best_idx = jnp.argmax(scores, axis=-1) if eval_max else jnp.argmin(scores, axis=-1)
    best_metric = jnp.take_along_axis(scores, best_idx[:, None], axis=-1)[..., 0]
    best_perm = perms[best_idx]
    return best_metric, best_perm


def _find_best_perm_lsa(metric_mtx: Array, eval_max: bool) -> Tuple[Array, Array]:
    """Hungarian assignment on host for large speaker counts — the in-repo
    C++ batched solver (metrics_tpu/native/lsap.cpp, compiled on demand),
    with scipy as the no-toolchain fallback."""
    from metrics_tpu.native import lsap

    mtx = np.asarray(metric_mtx)
    best_perm = lsap(mtx, maximize=eval_max).astype(np.int64)
    best_metric = np.take_along_axis(mtx, best_perm[:, :, None], axis=2).mean(axis=(-1, -2))
    return jnp.asarray(best_metric), jnp.asarray(best_perm)


def permutation_invariant_training(
    preds: Array, target: Array, metric_func: Callable, eval_func: str = "max", **kwargs: Any
) -> Tuple[Array, Array]:
    """Evaluate ``metric_func`` under the best speaker permutation (pit.py:103-181).

    Args:
        preds: estimates, shape ``[batch, spk, ...]``.
        target: references, shape ``[batch, spk, ...]``.
        metric_func: batched pairwise metric,
            ``metric_func(preds[:, j], target[:, i], **kwargs) -> [batch]``.
        eval_func: ``"max"`` (higher better) or ``"min"``.

    Returns:
        ``(best_metric [batch], best_perm [batch, spk])``.

    Example:
        >>> from metrics_tpu.functional.audio.sdr import scale_invariant_signal_distortion_ratio
        >>> preds = jnp.array([[[-0.0579,  0.3560, -0.9604], [-0.1719,  0.3205,  0.2951]]])
        >>> target = jnp.array([[[ 1.0958, -0.1648,  0.5228], [-0.4100,  1.1942, -0.5103]]])
        >>> best_metric, best_perm = permutation_invariant_training(
        ...     preds, target, scale_invariant_signal_distortion_ratio, 'max')
        >>> best_metric
        Array([-5.1091003], dtype=float32)
        >>> best_perm
        Array([[0, 1]], dtype=int32)
    """
    if preds.shape[0:2] != target.shape[0:2]:
        raise RuntimeError(
            "Predictions and targets are expected to have the same shape at the batch and speaker dimensions"
        )
    if eval_func not in ("max", "min"):
        raise ValueError(f'eval_func can only be "max" or "min" but got {eval_func}')
    if target.ndim < 2:
        raise ValueError(f"Inputs must be of shape [batch, spk, ...], got {target.shape} and {preds.shape} instead")

    spk_num = target.shape[1]
    rows = []
    for target_idx in range(spk_num):
        row = [
            metric_func(preds[:, preds_idx, ...], target[:, target_idx, ...], **kwargs)
            for preds_idx in range(spk_num)
        ]
        rows.append(jnp.stack(row, axis=-1))
    metric_mtx = jnp.stack(rows, axis=-2)  # [batch, target_spk, pred_spk]

    if spk_num <= _MAX_EXHAUSTIVE_SPK:
        return _find_best_perm_exhaustive(metric_mtx, eval_func == "max")
    return _find_best_perm_lsa(metric_mtx, eval_func == "max")


def pit_permutate(preds: Array, perm: Array) -> Array:
    """Reorder ``preds`` along the speaker axis by ``perm`` (pit.py:184-194)."""
    return jnp.take_along_axis(preds, perm[(...,) + (None,) * (preds.ndim - 2)], axis=1)
