"""PIT parity vs the reference implementation (pure torch + scipy, imported
from /root/reference) over both solver paths: the vectorized on-device
exhaustive search (spk <= 6) and the scipy Hungarian host path (spk > 6;
reference switches at spk >= 3 — both find the same optimum)."""
import numpy as np
import pytest

from metrics_tpu.audio import PermutationInvariantTraining
from metrics_tpu.functional.audio import (
    permutation_invariant_training,
    pit_permutate,
    scale_invariant_signal_distortion_ratio,
    signal_noise_ratio,
)
from tests.helpers.reference import load_reference_module


def _reference_pit(preds, target, metric, eval_func):
    import torch

    ref_pit = load_reference_module("torchmetrics.functional.audio.pit")
    ref_metric = load_reference_module("torchmetrics.functional.audio.snr")
    fns = {
        "si_sdr": load_reference_module("torchmetrics.functional.audio.sdr").scale_invariant_signal_distortion_ratio,
        "snr": ref_metric.signal_noise_ratio,
    }
    best_metric, best_perm = ref_pit.permutation_invariant_training(
        torch.tensor(np.asarray(preds)), torch.tensor(np.asarray(target)), fns[metric], eval_func
    )
    return best_metric.numpy(), best_perm.numpy()


@pytest.mark.parametrize("spk", [2, 3, 4])
@pytest.mark.parametrize(
    ["metric", "metric_fn", "eval_func"],
    [
        ("si_sdr", scale_invariant_signal_distortion_ratio, "max"),
        ("snr", signal_noise_ratio, "max"),
        ("snr", signal_noise_ratio, "min"),
    ],
)
def test_pit_parity(spk, metric, metric_fn, eval_func):
    rng = np.random.RandomState(spk)
    preds = rng.randn(3, spk, 200).astype(np.float32)
    target = rng.randn(3, spk, 200).astype(np.float32)
    best_metric, best_perm = permutation_invariant_training(preds, target, metric_fn, eval_func)
    ref_metric, ref_perm = _reference_pit(preds, target, metric, eval_func)
    np.testing.assert_allclose(np.asarray(best_metric), ref_metric, atol=1e-4, rtol=1e-4)
    np.testing.assert_array_equal(np.asarray(best_perm), ref_perm)


def test_pit_large_spk_hungarian_path():
    """spk=8 exceeds the exhaustive cap -> scipy Hungarian host path; the
    optimum must match brute force over all 40320 permutations."""
    from itertools import permutations as iperm

    rng = np.random.RandomState(0)
    spk = 8
    preds = rng.randn(2, spk, 50).astype(np.float32)
    target = rng.randn(2, spk, 50).astype(np.float32)
    best_metric, best_perm = permutation_invariant_training(
        preds, target, signal_noise_ratio, "max"
    )
    # brute-force oracle on the raw metric matrix
    mtx = np.stack(
        [
            np.stack(
                [
                    [float(signal_noise_ratio(preds[b, j], target[b, i])) for j in range(spk)]
                    for i in range(spk)
                ]
            )
            for b in range(2)
        ]
    )
    for b in range(2):
        brute = max(np.mean(mtx[b, range(spk), list(p)]) for p in iperm(range(spk)))
        assert float(best_metric[b]) == pytest.approx(brute, abs=1e-5)


def test_pit_permutate():
    rng = np.random.RandomState(1)
    preds = rng.randn(2, 3, 10).astype(np.float32)
    perm = np.array([[2, 0, 1], [1, 2, 0]])
    out = np.asarray(pit_permutate(preds, perm))
    for b in range(2):
        for i in range(3):
            np.testing.assert_array_equal(out[b, i], preds[b, perm[b, i]])


def test_pit_class_lifecycle():
    rng = np.random.RandomState(2)
    preds = rng.randn(4, 2, 100).astype(np.float32)
    target = rng.randn(4, 2, 100).astype(np.float32)
    metric = PermutationInvariantTraining(scale_invariant_signal_distortion_ratio, "max")
    v1 = metric(preds[:2], target[:2])
    metric.update(preds[2:], target[2:])
    acc = metric.compute()
    full_metric, _ = permutation_invariant_training(preds, target, scale_invariant_signal_distortion_ratio, "max")
    assert float(acc) == pytest.approx(float(np.mean(np.asarray(full_metric))), abs=1e-5)
    assert np.asarray(v1).shape == ()
    metric.reset()
    assert float(metric.total) == 0


def test_pit_error_paths():
    with pytest.raises(ValueError, match="eval_func"):
        permutation_invariant_training(np.zeros((1, 2, 5)), np.zeros((1, 2, 5)), signal_noise_ratio, "sum")
    with pytest.raises(RuntimeError, match="same shape"):
        permutation_invariant_training(np.zeros((1, 2, 5)), np.zeros((1, 3, 5)), signal_noise_ratio)
    with pytest.raises(ValueError, match="Inputs must be of shape"):
        permutation_invariant_training(np.zeros(5), np.zeros(5), signal_noise_ratio)
