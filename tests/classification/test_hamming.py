"""HammingDistance vs sklearn hamming_loss."""
import numpy as np
import pytest
from sklearn.metrics import hamming_loss as sk_hamming_loss

from metrics_tpu.classification import HammingDistance
from metrics_tpu.functional import hamming_distance
from tests.classification.inputs import (
    _input_binary,
    _input_binary_prob,
    _input_multilabel,
    _input_multilabel_prob,
)
from tests.helpers.testers import THRESHOLD, MetricTester


def _sk_hamming(preds, target):
    preds, target = np.asarray(preds), np.asarray(target)
    if np.issubdtype(preds.dtype, np.floating):
        preds = (preds >= THRESHOLD).astype(int)
    return sk_hamming_loss(target.reshape(-1), preds.reshape(-1))


@pytest.mark.parametrize(
    "preds, target",
    [
        (_input_binary.preds, _input_binary.target),
        (_input_binary_prob.preds, _input_binary_prob.target),
        (_input_multilabel.preds, _input_multilabel.target),
        (_input_multilabel_prob.preds, _input_multilabel_prob.target),
    ],
)
class TestHammingDistance(MetricTester):
    atol = 1e-6

    def test_hamming_class(self, preds, target):
        self.run_class_metric_test(
            preds=preds,
            target=target,
            metric_class=HammingDistance,
            sk_metric=_sk_hamming,
            metric_args={"threshold": THRESHOLD},
        )

    def test_hamming_fn(self, preds, target):
        self.run_functional_metric_test(
            preds,
            target,
            metric_functional=hamming_distance,
            sk_metric=_sk_hamming,
            metric_args={"threshold": THRESHOLD},
        )
