"""Job-wide telemetry aggregation: merge every rank's counters onto one view.

PR 1's recorder is process-local; on a multi-host job every rank keeps a
private recorder and the rank-zero export silently reports 1/Nth of the
job. :func:`aggregate_across_hosts` fixes the accounting: each process
serializes its counter totals (call counts/times, signature counts, sync
totals, footprint high-water marks, compile bills) to a JSON payload, a
process allgather moves the payloads (padded to the max length — they are
uneven), and the merge runs on every rank so rank zero exports the whole
job while other ranks stay consistent.

Merge semantics per counter family:

* call counts / call times / sync totals / compile counts+times / dropped —
  **summed** (extensive quantities; the job total is the sum of ranks)
* distinct signature counts — **max** across ranks (each rank counts its
  own distinct set; identical pipelines see identical signatures, so the
  max is the best under-approximation of the job-wide distinct count that
  needs no signature exchange — a rank whose count *differs* is itself a
  data-skew signal, visible in the per-process detail)
* footprint high-water marks — **max** (a high-water mark is a max)

In a single-process run the allgather is skipped entirely and the local
payload is returned as a world-size-1 aggregate — a no-op by construction.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from metrics_tpu.observability.recorder import _DEFAULT_RECORDER

__all__ = ["aggregate_across_hosts", "counter_payload", "merge_payloads"]

#: separator for (metric, phase) keys in the JSON payload; class and phase
#: names are identifiers, so "|" cannot collide
_KEY_SEP = "|"


def counter_payload(recorder: Optional[Any] = None) -> Dict[str, Any]:
    """One process's aggregate counters as a flat JSON-safe dict (the unit
    the cross-host allgather serializes — and the fleet wire format ships).

    Every payload is stamped with snapshot provenance beyond the bare
    process index: the ``host`` name, the wall-clock ``t`` it was taken,
    and a monotonic per-process ``seq`` (survives recorder resets) — what
    a fleet collector's per-host labelling, lag tracking, and duplicate
    detection key on. All three merge as identity defaults: a payload
    from an older build simply lacks them (``merge_payloads`` reads every
    family with ``.get``), so mixed-fleet merges keep working."""
    rec = recorder if recorder is not None else _DEFAULT_RECORDER
    import socket
    import time as _time

    from metrics_tpu.parallel.distributed import process_index

    registry = getattr(rec, "timeseries", None)
    next_seq = getattr(rec, "next_snapshot_seq", None)
    return {
        "process": process_index(),
        "host": socket.gethostname(),
        "t": _time.time(),
        "seq": next_seq() if callable(next_seq) else 0,
        "call_counts": {_KEY_SEP.join(k): v for k, v in rec.call_counts().items()},
        "call_times": {_KEY_SEP.join(k): v for k, v in rec.call_times().items()},
        "signature_counts": dict(rec.signature_counts()),
        "sync_totals": dict(rec.sync_totals()),
        "footprint_hwm": dict(rec.footprint_high_water_marks()),
        "compile_counts": dict(rec.compile_counts()),
        "compile_times": dict(rec.compile_times()),
        "fused_update_totals": dict(rec.fused_update_totals()),
        "async_totals": dict(rec.async_totals()),
        "sliced_totals": dict(rec.sliced_totals()),
        "sliced_slice_counts": dict(rec.footprint_slice_counts()),
        "sketch_totals": dict(rec.sketch_totals()),
        "drift_scores": dict(rec.drift_scores()),
        "fleet_totals": dict(rec.fleet_totals()),
        "ops_dispatch_totals": dict(rec.ops_dispatch_totals()),
        "read_totals": dict(rec.read_totals()),
        "memory": dict(rec.memory_totals()),
        "freshness": dict(rec.freshness_totals()),
        "export_errors": rec.export_errors(),
        # windowed time series ride the same payload path: per-bucket
        # sketches serialize JSON-safe and merge by qsketch_merge, so a
        # fleet-wide windowed p99 is the same fold as every other family
        "timeseries": registry.payload() if registry is not None else {},
        "dropped_events": rec.dropped_events(),
    }


def _merge_sum(maps: List[Dict[str, Any]]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for m in maps:
        for k, v in m.items():
            out[k] = out.get(k, 0) + v
    return out


def _merge_max(maps: List[Dict[str, Any]]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for m in maps:
        for k, v in m.items():
            out[k] = max(out.get(k, v), v)
    return out


def merge_payloads(payloads: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge per-process counter payloads into one job-wide aggregate.

    Returns tuple-keyed counters matching the recorder's accessors, plus
    the raw per-process payloads under ``"processes"`` (per-rank detail for
    the ``process``-labelled Prometheus series and straggler triage).

    Every counter family is read with ``.get`` and an identity default: a
    heterogeneous fleet (a rank on an older build missing a family, a rank
    whose workload never touched a subsystem) merges as zero/identity —
    absent keys are data about that rank, never an error.
    """
    return {
        "world_size": len(payloads),
        "call_counts": {
            tuple(k.split(_KEY_SEP)): v
            for k, v in _merge_sum([p.get("call_counts", {}) for p in payloads]).items()
        },
        "call_times": {
            tuple(k.split(_KEY_SEP)): v
            for k, v in _merge_sum([p.get("call_times", {}) for p in payloads]).items()
        },
        "signature_counts": _merge_max([p.get("signature_counts", {}) for p in payloads]),
        "sync_totals": _merge_sum([p.get("sync_totals", {}) for p in payloads]),
        "footprint_hwm": _merge_max([p.get("footprint_hwm", {}) for p in payloads]),
        "compile_counts": _merge_sum([p.get("compile_counts", {}) for p in payloads]),
        "compile_times": _merge_sum([p.get("compile_times", {}) for p in payloads]),
        # extensive, like the call counts they mirror (older payloads from
        # pre-fused ranks simply contribute nothing)
        "fused_update_totals": _merge_sum([p.get("fused_update_totals", {}) for p in payloads]),
        "async_totals": _merge_async([p.get("async_totals", {}) for p in payloads]),
        "sliced_totals": _merge_sliced([p.get("sliced_totals", {}) for p in payloads]),
        # slice counts are a structural property (same SlicedMetric config
        # on every rank) — max is the safe reconciliation if they skew
        "sliced_slice_counts": _merge_max([p.get("sliced_slice_counts", {}) for p in payloads]),
        "sketch_totals": _merge_sketch([p.get("sketch_totals", {}) for p in payloads]),
        # drift scores are last-seen gauges; the worst (max) rank's score is
        # the fleet's headline — a rank without the drift layer contributes
        # nothing, like every other family
        "drift_scores": _merge_max([p.get("drift_scores", {}) for p in payloads]),
        "fleet_totals": _merge_fleet([p.get("fleet_totals", {}) for p in payloads]),
        # dispatch counts are extensive; the per-backend split surviving the
        # merge is the point — a fleet where one host's TPU traffic all
        # lands on the jnp fallback is exactly what this view must show
        "ops_dispatch_totals": _merge_sum(
            [p.get("ops_dispatch_totals", {}) for p in payloads]
        ),
        "read_totals": _merge_reads([p.get("read_totals", {}) for p in payloads]),
        "memory": _merge_memory([p.get("memory", {}) for p in payloads]),
        "freshness": _merge_freshness([p.get("freshness", {}) for p in payloads]),
        "export_errors": sum(p.get("export_errors", 0) for p in payloads),
        "timeseries": _merge_timeseries([p.get("timeseries", {}) for p in payloads]),
        "dropped_events": sum(p.get("dropped_events", 0) for p in payloads),
        "processes": list(payloads),
    }


def _merge_timeseries(maps: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Windowed-series fan-in: same-name series merge bucket-by-bucket
    (counts summed, sketches ``qsketch_merge``d — see
    ``timeseries.merge_registry_payloads``); a rank without the live layer
    contributes nothing. Lazy import: payload merging must stay cheap for
    the (common) case where no rank attached a registry."""
    maps = [m for m in maps if m]
    if not maps:
        return {}
    from metrics_tpu.observability.timeseries import merge_registry_payloads

    return merge_registry_payloads(maps)


#: async-pipeline counter keys that are extensive batch counts (summed);
#: every other key in the payload is a gauge/high-water mark (maxed)
_ASYNC_SUM_KEYS = ("enqueued", "applied", "dropped", "flushes")


def _merge_async(maps: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Async totals mix extensive counts (batches moved — summed) with
    gauges and high-water marks (queue depth, staleness, in-flight bytes —
    maxed, same semantics as the footprint HWMs)."""
    sums = _merge_sum([{k: v for k, v in m.items() if k in _ASYNC_SUM_KEYS} for m in maps])
    maxes = _merge_max([{k: v for k, v in m.items() if k not in _ASYNC_SUM_KEYS} for m in maps])
    return {**maxes, **sums}


#: sliced-scatter counter keys that are extensive (summed); max_slices is
#: a high-water mark
_SLICED_SUM_KEYS = ("scatter_events", "rows")


def _merge_sliced(maps: List[Dict[str, Any]]) -> Dict[str, Any]:
    sums = _merge_sum([{k: v for k, v in m.items() if k in _SLICED_SUM_KEYS} for m in maps])
    maxes = _merge_max([{k: v for k, v in m.items() if k not in _SLICED_SUM_KEYS} for m in maps])
    return {**maxes, **sums}


#: fleet-collector counter keys that are extensive (summed); backlog and
#: publisher-lag gauges/high-water marks (and the publisher count) max
_FLEET_SUM_KEYS = ("absorbed", "duplicates", "late_dropped", "fold_errors")


def _merge_fleet(maps: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fleet-collector totals: snapshot outcome counts sum; the backlog /
    worst-lag gauges and the publisher count max — a rank that runs no
    collector contributes nothing, like every other family."""
    sums = _merge_sum([{k: v for k, v in m.items() if k in _FLEET_SUM_KEYS} for m in maps])
    maxes = _merge_max([{k: v for k, v in m.items() if k not in _FLEET_SUM_KEYS} for m in maps])
    return {**maxes, **sums}


#: read-path counter keys that are extensive (summed); the per-read maxima
#: are high-water marks (maxed)
_READ_SUM_KEYS = (
    "reads", "cache_hits", "leaves_folded", "ring_buckets_folded",
    "table_rows_unpacked", "fanin", "read_s_total",
)


def _merge_reads(maps: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Read-path totals: read/fold counts sum across ranks; the worst
    single read (latency, fan-in) maxes — a rank that never computes
    contributes nothing, like every other family."""
    sums = _merge_sum([{k: v for k, v in m.items() if k in _READ_SUM_KEYS} for m in maps])
    maxes = _merge_max([{k: v for k, v in m.items() if k not in _READ_SUM_KEYS} for m in maps])
    return {**maxes, **sums}


#: memory-plane counter keys that are extensive (summed); the byte gauges
#: and their ``max_*`` high-water marks max — a fleet's ledger bytes are
#: per-host numbers, and the merged view keeps the worst host's figure
#: (per-host detail stays in the ``processes`` list)
_MEMORY_SUM_KEYS = (
    "events", "update_boundaries", "compute_boundaries", "reset_boundaries",
    "observations", "cache_plane_events", "plane_evictions", "plane_evicted_bytes",
)


def _merge_memory(maps: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Memory-observatory totals: boundary/observation counts sum across
    ranks; the ledger / cache-plane / device / unaccounted byte gauges (and
    their high-water marks) max — a rank without the memory plane
    contributes nothing, like every other family."""
    sums = _merge_sum([{k: v for k, v in m.items() if k in _MEMORY_SUM_KEYS} for m in maps])
    maxes = _merge_max([{k: v for k, v in m.items() if k not in _MEMORY_SUM_KEYS} for m in maps])
    return {**maxes, **sums}


def _merge_freshness(maps: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Freshness totals merge like the stamps they summarize: min of the
    mins, max of the maxes (``None`` is the identity for the event-time
    bounds, matching :class:`~metrics_tpu.observability.freshness.
    FreshnessStamp`'s monoid), stamp counts sum. A payload from a rank
    without the freshness layer contributes the identity."""
    maps = [m for m in maps if m]
    out: Dict[str, Any] = {
        "stamps": 0, "min_event_t": None, "max_event_t": None,
        "max_staleness_s": 0.0, "max_async_age_s": 0.0,
        "max_ring_span_s": 0.0, "max_watermark_lag_s": 0.0,
    }
    if not maps:
        return out
    for m in maps:
        out["stamps"] += int(m.get("stamps", 0) or 0)
        lo = m.get("min_event_t")
        if lo is not None:
            out["min_event_t"] = lo if out["min_event_t"] is None else min(out["min_event_t"], lo)
        hi = m.get("max_event_t")
        if hi is not None:
            out["max_event_t"] = hi if out["max_event_t"] is None else max(out["max_event_t"], hi)
        for key in ("max_staleness_s", "max_async_age_s", "max_ring_span_s", "max_watermark_lag_s"):
            out[key] = max(out[key], float(m.get(key, 0.0) or 0.0))
    return out


#: sketch counter keys that are extensive (summed); the fill ratios are
#: gauges/high-water marks (maxed)
_SKETCH_SUM_KEYS = ("merges",)


def _merge_sketch(maps: List[Dict[str, Any]]) -> Dict[str, Any]:
    sums = _merge_sum([{k: v for k, v in m.items() if k in _SKETCH_SUM_KEYS} for m in maps])
    maxes = _merge_max([{k: v for k, v in m.items() if k not in _SKETCH_SUM_KEYS} for m in maps])
    return {**maxes, **sums}


def aggregate_across_hosts(recorder: Optional[Any] = None) -> Dict[str, Any]:
    """Merge this recorder's counters with every other process's.

    Single-process: returns the local totals as a world-size-1 aggregate
    without touching any collective. Multi-process: one
    ``process_allgather`` of the JSON-serialized payloads (padded uint8 —
    payload lengths are uneven across ranks) and a deterministic merge on
    every rank. Call it at export time, then hand the result to
    ``render_prometheus(aggregate=...)`` or read the merged counters
    directly.
    """
    local = counter_payload(recorder)
    from metrics_tpu.parallel.distributed import distributed_available

    if not distributed_available():
        return merge_payloads([local])

    import numpy as np
    from jax.experimental import multihost_utils

    raw = json.dumps(local).encode("utf-8")
    # lengths are uneven (different metric sets / signature tables per
    # rank); exchange them first, pad to max, gather, trim per rank
    lengths = np.asarray(
        multihost_utils.process_allgather(np.asarray([len(raw)], np.int64), tiled=False)
    ).reshape(-1)
    max_len = int(lengths.max())
    padded = np.zeros((max_len,), np.uint8)
    padded[: len(raw)] = np.frombuffer(raw, np.uint8)
    gathered = np.asarray(multihost_utils.process_allgather(padded, tiled=False))
    payloads = [
        json.loads(gathered[i, : int(lengths[i])].tobytes().decode("utf-8"))
        for i in range(gathered.shape[0])
    ]
    payloads.sort(key=lambda p: p.get("process", 0))
    return merge_payloads(payloads)
