"""Partition rules for sliced metric state: state-leaf paths -> ``PartitionSpec``.

The slice axis is the natural partition axis for ``[S]``-leading state: at
10^5–10^6 slices the state pytree no longer fits (or no longer belongs)
replicated on one chip. This module maps state-leaf *paths* to
``PartitionSpec``s with regex rules (the ``match_partition_rules`` /
``get_naive_sharding`` patterns from large-model parameter sharding, applied
to metric state) and places the arrays under ``NamedSharding``s on a mesh:

    mesh = Mesh(np.array(jax.devices()).reshape(-1), ("slices",))
    shard_sliced_states(metric, mesh)          # [S] leaves split over "slices"

A leaf sharded along the mesh axis is owned disjointly by each mesh
position, so :func:`metrics_tpu.parallel.distributed.sync_pytree_in_mesh`
with ``partition_specs=`` skips the collective for it entirely — slice-
sharded leaves sync with zero cross-host traffic for their sharded
dimension, while replicated (non-slice) leaves keep the ordinary fused
all-reduce.
"""
from __future__ import annotations

import re
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from metrics_tpu.observability.recorder import SLICED_FOOTPRINT_PREFIX
from metrics_tpu.utils.exceptions import MetricsUserError

Array = jax.Array

#: default name of the mesh axis the slice dimension shards over
SLICE_AXIS = "slices"


def slice_partition_rules(axis_name: str = SLICE_AXIS) -> Tuple[Tuple[str, PartitionSpec], ...]:
    """Default rules for sliced metric state: every leaf registered by a
    ``SlicedMetric`` (its ``state_footprint`` paths carry the
    ``SLICED_FOOTPRINT_PREFIX``, and plain state names match the
    catch-all) shards its leading ``[S]`` dimension over ``axis_name``;
    anything else replicates."""
    return (
        (rf"(^|/){re.escape(SLICED_FOOTPRINT_PREFIX)}", PartitionSpec(axis_name)),
        (r"(^|/)_slice_rows$", PartitionSpec(axis_name)),
        (r".*", PartitionSpec()),
    )


def _iter_paths(tree: Any, path: str = "", sep: str = "/"):
    if isinstance(tree, dict):
        for key, value in tree.items():
            sub = f"{path}{sep}{key}" if path else str(key)
            yield from _iter_paths(value, sub, sep)
    else:
        yield path, tree


def _rebuild(tree: Any, flat: Dict[str, Any], path: str = "", sep: str = "/") -> Any:
    if isinstance(tree, dict):
        return {
            key: _rebuild(value, flat, f"{path}{sep}{key}" if path else str(key), sep)
            for key, value in tree.items()
        }
    return flat[path]


def match_partition_rules(
    rules: Sequence[Tuple[str, PartitionSpec]],
    tree: Dict[str, Any],
    sep: str = "/",
) -> Dict[str, Any]:
    """A pytree of ``PartitionSpec`` matching ``tree`` (nested string-keyed
    dicts of arrays — the shape ``Metric.state_dict()`` /
    ``MetricCollection.state_reductions()`` produce), chosen by the first
    rule whose regex searches the ``sep``-joined leaf path. Scalars (and
    one-element arrays) never partition. Raises when no rule matches — a
    silent replicate-by-default would hide a typo'd rule."""
    flat: Dict[str, Any] = {}
    for path, leaf in _iter_paths(tree, sep=sep):
        shape = getattr(leaf, "shape", ())
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            flat[path] = PartitionSpec()
            continue
        for pattern, spec in rules:
            if re.search(pattern, path) is not None:
                flat[path] = spec
                break
        else:
            raise MetricsUserError(f"no partition rule matched state leaf {path!r}")
    return _rebuild(tree, flat, sep=sep)


def get_naive_slice_sharding(
    x: Array, mesh: Mesh, axis_name: str = SLICE_AXIS
) -> NamedSharding:
    """Shard ``x``'s leading dimension over ``axis_name`` when it divides
    evenly, else replicate — the ``get_naive_sharding`` pattern specialized
    to the slice axis."""
    axis_size = mesh.shape[axis_name]
    shape = getattr(x, "shape", ())
    if len(shape) >= 1 and shape[0] % axis_size == 0 and shape[0] >= axis_size:
        return NamedSharding(mesh, PartitionSpec(axis_name))
    return NamedSharding(mesh, PartitionSpec())


def sliced_partition_specs(
    metric: Any,
    mesh: Mesh,
    axis_name: str = SLICE_AXIS,
) -> Dict[str, Any]:
    """Partition specs for a metric's (or collection's) state pytree:
    ``{leaf: PartitionSpec}`` nested like ``state_reductions()`` — the
    shape ``sync_pytree_in_mesh(partition_specs=...)`` consumes.

    ``mesh`` is REQUIRED and must be the mesh you shard over (the one
    given to :func:`shard_sliced_states`): a ``SlicedMetric`` leaf is
    claimed slice-sharded only when the naive-sharding divisibility rule
    actually shards it; leaves the fallback left replicated get
    ``PartitionSpec()``, so the sync still reduces them across the axis.
    An unconditional claim on a replicated leaf would make the sync pass
    it through untouched and silently drop the cross-rank reduction its
    replication requires — exactly the wrong-answer mode this signature
    exists to prevent. Non-sliced metrics replicate everywhere."""
    from metrics_tpu.sliced.metric import SlicedMetric

    def spec_for(m: Any) -> Dict[str, Any]:
        if isinstance(m, SlicedMetric):
            return {
                name: get_naive_slice_sharding(
                    jnp.asarray(getattr(m, name)), mesh, axis_name=axis_name
                ).spec
                for name in m._defaults
            }
        return {name: PartitionSpec() for name in m._defaults}

    if hasattr(metric, "_metrics"):  # MetricCollection duck-type
        return {name: spec_for(m) for name, m in metric._metrics.items()}
    return spec_for(metric)


def shard_sliced_states(
    metric: Any,
    mesh: Mesh,
    axis_name: str = SLICE_AXIS,
    rules: Optional[Sequence[Tuple[str, PartitionSpec]]] = None,
) -> Dict[str, Any]:
    """Place a metric's (or collection's) array states under mesh shardings
    derived from ``rules`` (default: :func:`slice_partition_rules`) and
    return the applied ``{state: NamedSharding}`` pytree.

    Uses ``Metric.shard_states`` underneath, so reset defaults are re-placed
    too and ``reset()`` preserves the layout. Leaves whose leading dimension
    does not divide the mesh axis stay replicated rather than erroring —
    pad ``num_slices`` up to a multiple of the mesh axis to shard evenly.
    A rule's spec names the mesh axis for the LEADING (slice) dimension;
    specs without a named axis replicate, and other placements are out of
    scope here (use ``Metric.shard_states`` directly for exotic layouts).
    """
    rules = tuple(rules) if rules is not None else slice_partition_rules(axis_name)

    def place(m: Any) -> Dict[str, Any]:
        state = {
            name: getattr(m, name)
            for name in m._defaults
            if not isinstance(m._defaults[name], list)
        }
        # footprint keys carry the SLICED_FOOTPRINT_PREFIX for SlicedMetric
        # leaves; rule-match against those paths with the SAME matcher (and
        # the same raise-on-no-match contract) as match_partition_rules,
        # then strip back to state names
        by_path = {
            key: jnp.asarray(state[name])
            for key in m.state_footprint(include_children=False)
            if (name := key.split("/", 1)[1] if key.startswith(SLICED_FOOTPRINT_PREFIX) else key)
            in state
        }
        spec_by_path = match_partition_rules(rules, by_path)
        shardings: Dict[str, NamedSharding] = {}
        for key, spec in spec_by_path.items():
            name = key.split("/", 1)[1] if key.startswith(SLICED_FOOTPRINT_PREFIX) else key
            # a rule spec names at most one mesh axis for the leading dim;
            # anything without a named leading axis replicates
            axis = next((a for a in tuple(spec) if isinstance(a, str)), None)
            if axis is None:
                shardings[name] = NamedSharding(mesh, PartitionSpec())
                continue
            shardings[name] = get_naive_slice_sharding(by_path[key], mesh, axis_name=axis)
        m.shard_states(shardings)
        return shardings

    if hasattr(metric, "_metrics"):  # MetricCollection duck-type
        return {name: place(m) for name, m in metric._metrics.items()}
    return place(metric)
