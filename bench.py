"""Benchmark driver: prints ONE JSON line with the headline metric.

Headline (BASELINE.md config 2): ImageNet-1k-shaped AUROC + ConfusionMatrix
pipeline — per batch, one jitted step updates both metric states AND computes
exact macro AUROC (Mann-Whitney rank kernel) + the confusion matrix, on the
available accelerator. Baseline: the reference TorchMetrics AUROC +
ConfusionMatrix on torch-CPU doing the same work (the reference publishes no
numbers of its own — BASELINE.md — so it is measured live from
/root/reference).
"""
import json
import os
import sys
import time
import warnings

import numpy as np

BATCH = 4096
NUM_CLASSES = 1000
WARMUP = 3
# The timed region necessarily ends with ONE scalar device->host readback
# whose tunnel round trip is ~100 ms regardless of work (round-5
# measurement: a no-op scan epoch costs ~103 ms end to end). 50 steps
# amortize that fixed measurement overhead to ~2 ms/step — the shape of a
# real eval epoch — where 10 steps buried the device time under it
# (13.7 ms/step apparent vs ~3 ms/step device).
ITERS = 50


#: env channel for the ``--cost-analysis`` flag: the full-emission driver
#: runs each config in a subprocess, so the flag must survive the hop
COST_ENV_VAR = "METRICS_TPU_BENCH_COST"


def _compiled_cost_payload(fn, *args, **kwargs):
    """Compiler-estimated cost of a benched jitted entry point, for the
    ``--cost-analysis`` flag: FLOPs / bytes accessed plus the
    trace/lower/compile wall breakdown. Returns ``None`` when the flag is
    off or the backend reports no estimate — the bench line then simply
    carries no ``cost_analysis`` key (older artifacts stay comparable)."""
    if not os.environ.get(COST_ENV_VAR):
        return None
    try:
        from metrics_tpu.observability.profiling import compiled_cost

        report = compiled_cost(fn, *args, **kwargs)
        if report["flops"] is None and report["bytes_accessed"] is None:
            return None
        return {
            "flops": report["flops"],
            "bytes_accessed": report["bytes_accessed"],
            "trace_s": report["trace_s"],
            "lower_s": report["lower_s"],
            "compile_s": report["compile_s"],
        }
    except Exception:
        return None


def _with_cost(record, cost):
    if cost is not None:
        record["cost_analysis"] = cost
    return record


def _make_data(n_batches=None):
    """Seed-42 softmax fixture; ``n_batches`` stacks independent batches
    (the TPU scan epoch) — one flat batch otherwise (the torch reference),
    both from the ONE generator so the two sides measure the same
    distribution."""
    rng = np.random.RandomState(42)
    shape = (BATCH, NUM_CLASSES) if n_batches is None else (n_batches, BATCH, NUM_CLASSES)
    logits = rng.rand(*shape).astype(np.float32) * 4
    preds = np.exp(logits - logits.max(axis=-1, keepdims=True))
    preds /= preds.sum(axis=-1, keepdims=True)
    target = rng.randint(0, NUM_CLASSES, size=shape[:-1]).astype(np.int64)
    return preds, target


def bench_tpu() -> tuple:
    """(Samples/sec, cost payload or None) through a jitted
    AUROC+ConfusionMatrix epoch on device.

    ITERS update+AUROC steps run inside ONE jitted lax.scan — the shape a
    real jitted TPU training loop has — so the measurement captures device
    execution rather than per-step host dispatch (which, over the tunneled
    accelerator transport used here, costs ~200 ms per launch and
    block_until_ready does not wait; the timed region ends with a scalar
    device->host readback instead). The scan consumes ITERS PRE-STACKED
    INDEPENDENT batches: the earlier rolled-view variant let XLA share work
    between steps once the rank kernel moved to a class-major fused sort
    (rolls permute the sort axis, so per-step sorts are recombinable) and
    measured ~29% too fast vs independent data — caught and corrected in
    round 5.
    """
    import jax
    import jax.numpy as jnp
    from metrics_tpu.classification import ConfusionMatrix
    from metrics_tpu.functional.classification.auroc import auroc_rank_multiclass

    preds_np, target_np = _make_data(n_batches=ITERS)
    preds_all = jnp.asarray(preds_np)
    target_all = jnp.asarray(target_np, dtype=jnp.int32)

    confmat = ConfusionMatrix(num_classes=NUM_CLASSES)

    @jax.jit
    def epoch(state, preds_all, target_all):
        def step(state, xs):
            preds_i, target_i = xs
            new_state = confmat.update_state(state, preds_i, target_i)
            auc = auroc_rank_multiclass(preds_i, target_i, NUM_CLASSES, average="macro")
            return new_state, auc
        state, aucs = jax.lax.scan(step, state, (preds_all, target_all))
        return state, aucs[-1]

    state, auc = epoch(confmat.init_state(), preds_all, target_all)  # compile
    float(auc)
    for _ in range(WARMUP):
        state, auc = epoch(confmat.init_state(), preds_all, target_all)
    float(auc)

    t0 = time.perf_counter()
    state, auc = epoch(confmat.init_state(), preds_all, target_all)
    float(auc)
    dt = time.perf_counter() - t0
    cost = _compiled_cost_payload(epoch, confmat.init_state(), preds_all, target_all)
    return BATCH * ITERS / dt, cost


def _stub_pkg_resources() -> None:
    """Modern setuptools dropped pkg_resources; the reference needs a stub."""
    if "pkg_resources" not in sys.modules:
        import types

        stub = types.ModuleType("pkg_resources")

        class DistributionNotFound(Exception):
            pass

        def get_distribution(name):
            raise DistributionNotFound(name)

        stub.DistributionNotFound = DistributionNotFound
        stub.get_distribution = get_distribution
        sys.modules["pkg_resources"] = stub


def bench_reference() -> float:
    """Samples/sec through reference TorchMetrics AUROC+ConfusionMatrix on torch-CPU."""
    _stub_pkg_resources()

    sys.path.insert(0, "/root/reference")
    try:
        import torch
        from torchmetrics import AUROC as TorchAUROC, ConfusionMatrix as TorchConfusionMatrix

        preds_np, target_np = _make_data()
        preds = torch.from_numpy(preds_np)
        target = torch.from_numpy(target_np)

        auroc = TorchAUROC(num_classes=NUM_CLASSES, average="macro")
        confmat = TorchConfusionMatrix(num_classes=NUM_CLASSES)

        def step():
            confmat.update(preds, target)
            auroc.reset()
            auroc.update(preds, target)
            return auroc.compute()

        step()  # warmup
        t0 = time.perf_counter()
        iters = 2
        for _ in range(iters):
            step()
        dt = time.perf_counter() - t0
        return BATCH * iters / dt
    finally:
        sys.path.pop(0)


def _make_detection_data(n_imgs=1000, n_classes=91, seed=3):
    """COCO-shaped fixture: 91 classes, 10-100 detections and 1-30 ground
    truths per image, so the chunked matching-kernel path actually executes
    at the unit counts COCO val produces (~10^4-10^5 (image,class) units)."""
    rng = np.random.default_rng(seed)
    preds, target = [], []
    for _ in range(n_imgs):
        nd = int(rng.integers(10, 101))
        ng = int(rng.integers(1, 31))

        def boxes(n):
            x1 = rng.uniform(0, 500, n)
            y1 = rng.uniform(0, 500, n)
            w = rng.uniform(4, 150, n)
            h = rng.uniform(4, 150, n)
            return np.stack([x1, y1, x1 + w, y1 + h], 1).astype(np.float32)

        preds.append(
            dict(
                boxes=boxes(nd),
                scores=rng.uniform(0, 1, nd).astype(np.float32),
                labels=rng.integers(0, n_classes, nd).astype(np.int32),
            )
        )
        target.append(dict(boxes=boxes(ng), labels=rng.integers(0, n_classes, ng).astype(np.int32)))
    return preds, target


def bench_map() -> None:
    """images/sec through COCO mAP update+compute (BASELINE config 3)."""
    import jax.numpy as jnp
    from metrics_tpu.detection import MeanAveragePrecision

    preds, target = _make_detection_data()
    n_imgs = len(preds)

    def run_once():
        # host numpy inputs, same as the torch-CPU reference is fed
        m = MeanAveragePrecision(class_metrics=True)
        m.update(preds, target)
        return m.compute()

    run_once()  # compile
    iters = 2
    t0 = time.perf_counter()
    for _ in range(iters):
        run_once()
    ours = n_imgs * iters / (time.perf_counter() - t0)

    ref_ips = None
    try:
        import torch

        sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tests"))
        from detection.test_map import _load_reference_map

        RefMAP = _load_reference_map()
        t_preds = [{k: torch.as_tensor(v) for k, v in p.items()} for p in preds]
        t_target = [{k: torch.as_tensor(v) for k, v in t.items()} for t in target]

        def ref_once():
            m = RefMAP(class_metrics=True)
            m.update(t_preds, t_target)
            return m.compute()

        ref_once()
        t0 = time.perf_counter()
        ref_once()
        ref_ips = n_imgs / (time.perf_counter() - t0)
    except Exception:
        pass
    except BaseException as err:
        # _load_reference_map raises pytest's Skipped — a BaseException —
        # when the reference checkout is absent; that must degrade to
        # vs_baseline=None like every other config, not kill the bench
        if type(err).__name__ != "Skipped":
            raise

    print(
        json.dumps(
            {
                "metric": "coco_map_update_compute_throughput",
                "value": round(ours, 2),
                "unit": "images/sec",
                "vs_baseline": round(ours / ref_ips, 3) if ref_ips else None,
            }
        )
    )


def bench_retrieval() -> None:
    """Retrieval throughput, two records.

    1. ``mslr_shaped_ndcg_map_throughput`` — the historical config-4 record
       (MSLR-WEB30K-shaped, ``exact=True`` cat-state + the packed device
       compute path), kept on the exact mode so the number stays
       comparable across rounds.
    2. ``fused_retrieval_throughput`` — the ISSUE 15 gate record: the
       fixed-capacity table default through ``compile_update`` at 10k
       queries across 3 ragged shapes, against the eager per-query group
       loop (the reference's dict-loop shape). AUX fields gate the >= 5x
       acceptance floor and the one-compile anchor; BOOLs pin in-window
       bit parity (dyadic-valued metric: exact by construction) and the
       top-k / segment-extremum kernels' interpret-mode parity.
    """
    import jax.numpy as jnp
    from metrics_tpu import MetricCollection
    from metrics_tpu.retrieval import RetrievalMAP, RetrievalNormalizedDCG

    rng = np.random.RandomState(7)
    n_queries = 5000
    counts = rng.randint(40, 200, n_queries)
    idx = np.repeat(np.arange(n_queries), counts)
    n = len(idx)
    preds = rng.rand(n).astype(np.float32)
    target = (rng.rand(n) < 0.08).astype(np.int32)

    # every iteration gets FRESH device arrays (a real epoch's tensors are
    # new objects), so the id-keyed pack cache can never carry packing work
    # across timed iterations — each run_once packs, computes, and reads back
    iters = 3
    epochs = [
        (
            jnp.asarray(idx),
            jnp.asarray(preds + np.float32(1e-7) * e),
            jnp.asarray(target),
        )
        for e in range(iters + 1)
    ]

    def run_once(j_idx, j_preds, j_target):
        import warnings

        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            col = MetricCollection(
                [RetrievalNormalizedDCG(exact=True), RetrievalMAP(exact=True)]
            )
        col.update(j_preds, j_target, indexes=j_idx)
        # scalar readbacks so the timed region includes kernel completion
        return {k: float(v) for k, v in col.compute().items()}

    run_once(*epochs[-1])  # compile
    t0 = time.perf_counter()
    for e in range(iters):
        run_once(*epochs[e])
    ours = n_queries * iters / (time.perf_counter() - t0)

    ref_qps = None
    try:
        import torch

        _stub_pkg_resources()
        sys.path.insert(0, "/root/reference")
        from torchmetrics import MetricCollection as TRefCollection
        from torchmetrics.retrieval import RetrievalMAP as TRefMAP
        from torchmetrics.retrieval import RetrievalNormalizedDCG as TRefNDCG

        t_idx = torch.as_tensor(idx)
        t_preds = torch.as_tensor(preds)
        t_target = torch.as_tensor(target)

        def ref_once():
            col = TRefCollection([TRefNDCG(), TRefMAP()])
            col.update(t_preds, t_target, indexes=t_idx)
            return col.compute()

        ref_once()
        t0 = time.perf_counter()
        ref_once()
        ref_qps = n_queries / (time.perf_counter() - t0)
    except Exception:
        pass

    print(
        json.dumps(
            {
                "metric": "mslr_shaped_ndcg_map_throughput",
                "value": round(ours, 1),
                "unit": "queries/sec",
                "vs_baseline": round(ours / ref_qps, 3) if ref_qps else None,
            }
        ),
        flush=True,
    )
    _bench_fused_retrieval()


def _bench_fused_retrieval() -> None:
    """The ISSUE 15 acceptance record (see :func:`bench_retrieval`)."""
    import warnings

    import jax
    import jax.numpy as jnp
    from metrics_tpu import MetricCollection
    from metrics_tpu.retrieval import RetrievalPrecision

    rng = np.random.RandomState(15)
    n_queries = 10_000
    # 3 ragged shapes cycling through the stream (bucketing must absorb
    # them in ONE compile); ~12 docs/query keeps the table in-window so
    # the parity BOOL is exact
    kw = dict(max_queries=1 << 14, max_docs=16, k=4)
    counts = rng.randint(8, 16, n_queries)
    idx = np.repeat(np.arange(n_queries), counts)
    order = np.arange(len(idx))
    preds = (rng.randint(0, 4096, len(idx)) / 4096.0).astype(np.float32)
    target = (rng.rand(len(idx)) < 0.3).astype(np.int32)
    shapes = (4096, 6144, 8192)
    batches = []
    lo = 0
    si = 0
    while lo < len(idx):
        hi = min(lo + shapes[si % 3], len(idx))
        batches.append(
            (
                jnp.asarray(preds[lo:hi]),
                jnp.asarray(target[lo:hi]),
                jnp.asarray(idx[lo:hi]),
            )
        )
        si += 1
        lo = hi

    # --- fused table side: update stream + compute, min-of-2 epochs ------
    # ONE bucket absorbs all three ragged shapes -> exactly one compile
    table_handle = {}

    def fused_epoch():
        m = MetricCollection([RetrievalPrecision(**kw)])
        handle = m.compile_update(buckets=[max(shapes)])
        for p, t, i in batches:
            m.update(p, t, indexes=i)
        val = float(m.compute()["RetrievalPrecision"])
        table_handle["qtable"] = m["RetrievalPrecision"].qtable
        return val, len(handle._cache)

    fused_epoch()  # compile epoch (the cache is per-collection, rebuilt)
    t0 = time.perf_counter()
    fused_val, n_compiles = fused_epoch()
    fused_wall = time.perf_counter() - t0
    best = fused_wall
    t0 = time.perf_counter()
    fused_epoch()
    best = min(best, time.perf_counter() - t0)
    fused_qps = n_queries / best

    # --- eager per-query group loop (the reference dict-loop shape) ------
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        loop = RetrievalPrecision(exact=True, k=4)
    for p, t, i in batches:
        loop.update(p, t, indexes=i)
    t0 = time.perf_counter()
    loop_val = float(loop._compute_host_loop())
    loop_wall = time.perf_counter() - t0
    loop_qps = n_queries / loop_wall

    # in-window bit parity at the STATE level: the table's unpacked padded
    # layout must reproduce the exact path's device pack bit-for-bit
    # (query order, doc order, masks, values). The final scalar is gated
    # within a few f32 ulp — XLA lowers the one mean division differently
    # per array shape (reciprocal-multiply vs true divide), which is the
    # only tolerated divergence.
    from metrics_tpu.functional.retrieval.padded import pack_queries
    from metrics_tpu.retrieval.table import retrieval_table_layout

    ep, et, em = pack_queries(
        jnp.asarray(idx), jnp.asarray(preds), jnp.asarray(target)
    )
    tp_, tt_, tm_, trv, *_ = retrieval_table_layout(table_handle["qtable"])
    rows = np.flatnonzero(np.asarray(trv))
    dmax = ep.shape[1]
    sl_p, sl_t, sl_m = (np.asarray(x)[rows][:, :dmax] for x in (tp_, tt_, tm_))
    window_bit_exact = bool(
        len(rows) == ep.shape[0]
        and bool(np.array_equal(sl_m, np.asarray(em)))
        and bool(np.array_equal(sl_p, np.asarray(ep), equal_nan=True))
        and bool(np.array_equal(sl_t, np.asarray(et)))
        and not np.asarray(tm_)[rows][:, dmax:].any()
        and abs(fused_val - loop_val) <= 4 * np.finfo(np.float32).eps
    )

    # --- kernel parity BOOLs (real bodies, interpret mode) ---------------
    tp = jnp.asarray(rng.randint(0, 64, (64, 256)).astype(np.float32) / 16.0)
    tv = jnp.asarray((rng.rand(64, 256) < 0.8).astype(np.float32))
    tt = jnp.asarray(rng.randint(0, 2, (64, 256)).astype(np.float32))
    from metrics_tpu.ops.topk_pallas import _row_topk_jnp, row_topk_tiled

    want = _row_topk_jnp(tp, tt, tv, 16)
    got = row_topk_tiled(tp, tt, tv, 16, interpret=True)
    topk_parity = all(bool(jnp.array_equal(a, b, equal_nan=True)) for a, b in zip(got, want))
    from metrics_tpu.ops.scatter_pallas import segment_extremum_tiled

    sv = jnp.asarray(rng.randn(1024, 4).astype(np.float32))
    si_ = jnp.asarray(rng.randint(0, 200, 1024), jnp.int32)
    smax_parity = bool(
        jnp.array_equal(
            segment_extremum_tiled(sv, si_, 200, is_max=True, interpret=True),
            jax.ops.segment_max(sv, si_, num_segments=200),
        )
    )
    smin_parity = bool(
        jnp.array_equal(
            segment_extremum_tiled(sv, si_, 200, is_max=False, interpret=True),
            jax.ops.segment_min(sv, si_, num_segments=200),
        )
    )

    print(
        json.dumps(
            {
                "metric": "fused_retrieval_throughput",
                "value": round(fused_qps, 1),
                "unit": "queries/sec",
                "eager_group_loop_qps": round(loop_qps, 1),
                "retrieval_fused_vs_eager": round(fused_qps / loop_qps, 2),
                "retrieval_fused_compiles": n_compiles,
                "bucketed_shapes": 3,
                "retrieval_window_bit_exact": window_bit_exact,
                "ops_row_topk_parity": topk_parity,
                "ops_segment_max_parity": smax_parity,
                "ops_segment_min_parity": smin_parity,
            }
        ),
        flush=True,
    )


def bench_image() -> None:
    """images/sec through SSIM update+compute (BASELINE config 5's measurable
    half; FID throughput needs pretrained Inception weights absent here)."""
    import jax
    import jax.numpy as jnp
    from metrics_tpu.functional.image.ssim import structural_similarity_index_measure

    rng = np.random.RandomState(5)
    n, hw = 64, 192
    a = rng.rand(n, 3, hw, hw).astype(np.float32)
    b = np.clip(a + 0.05 * rng.randn(n, 3, hw, hw).astype(np.float32), 0, 1)
    ja, jb = jnp.asarray(a), jnp.asarray(b)

    fn = jax.jit(lambda x, y: structural_similarity_index_measure(x, y, data_range=1.0))
    float(fn(ja, jb))
    iters = 5
    t0 = time.perf_counter()
    for _ in range(iters):
        v = fn(ja, jb)
    float(v)
    ours = n * iters / (time.perf_counter() - t0)
    cost = _compiled_cost_payload(fn, ja, jb)

    ref_ips = None
    try:
        import torch

        _stub_pkg_resources()
        sys.path.insert(0, "/root/reference")
        from torchmetrics.functional import structural_similarity_index_measure as ref_ssim

        ta, tb = torch.from_numpy(a), torch.from_numpy(b)
        ref_ssim(ta, tb, data_range=1.0)
        t0 = time.perf_counter()
        ref_ssim(ta, tb, data_range=1.0)
        ref_ips = n / (time.perf_counter() - t0)
    except Exception:
        pass

    print(
        json.dumps(
            _with_cost(
                {
                    "metric": "ssim_update_compute_throughput",
                    "value": round(ours, 1),
                    "unit": "images/sec",
                    "vs_baseline": round(ours / ref_ips, 3) if ref_ips else None,
                },
                cost,
            )
        )
    )


def _ref_sync_worker(rank: int, world: int, port: int, warmup: int, iters: int, queue) -> None:
    """torch.distributed gloo worker: times the reference gather_all_tensors
    over the same state bundle the mesh bench syncs."""
    import torch
    import torch.distributed as dist

    _stub_pkg_resources()
    sys.path.insert(0, "/root/reference")
    from torchmetrics.utilities.distributed import gather_all_tensors

    dist.init_process_group(
        "gloo", init_method=f"tcp://127.0.0.1:{port}", rank=rank, world_size=world
    )
    try:
        g = torch.Generator().manual_seed(rank)
        states = [
            torch.rand((NUM_CLASSES, NUM_CLASSES), generator=g),  # confmat sum state
            torch.rand((65536,), generator=g),                    # capacity preds
            torch.randint(0, 2, (65536,), generator=g),           # capacity target
            torch.zeros((65536,), dtype=torch.bool),              # capacity valid
        ]
        times = []
        for i in range(warmup + iters):
            t0 = time.perf_counter()
            gathered = [gather_all_tensors(s) for s in states]
            # same post-gather reduction work the Metric sync applies
            total = torch.stack([t.float().sum() for gs in gathered for t in gs]).sum()
            float(total)
            if i >= warmup:
                times.append(time.perf_counter() - t0)
        if rank == 0:
            queue.put(times)
    finally:
        dist.destroy_process_group()


def bench_sync() -> None:
    """p50/p95 latency of a FULL in-jit mesh state sync — the 'DDP-sync p50
    latency' metric BASELINE.md declares. One jitted shard_map over an
    8-device mesh syncs a representative state bundle (ConfusionMatrix
    [1000,1000] sum state + a 64k-sample exact-curve capacity buffer triple
    via the VMA-clean all-gather + overflow tally) and reduces it to one
    scalar. Baseline: the reference's gather_all_tensors over the same bundle
    on an 8-process gloo group (same world size; gloo is its CPU backend,
    testers.py:59). Multi-chip TPU hardware is unavailable here, so the mesh
    is 8 virtual CPU devices — this measures the sync machinery, not ICI
    wire time."""
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from metrics_tpu.parallel.distributed import sync_in_mesh
    from metrics_tpu.utils.compat import shard_map

    n_dev = 8
    cap = 65536
    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("rank",))
    rng = np.random.RandomState(11)

    confmat = jnp.asarray(rng.rand(n_dev, NUM_CLASSES, NUM_CLASSES).astype(np.float32))
    preds = jnp.asarray(rng.rand(n_dev, cap).astype(np.float32))
    target = jnp.asarray(rng.randint(0, 2, (n_dev, cap)).astype(np.int32))
    valid = jnp.asarray(np.ones((n_dev, cap), bool))
    overflow = jnp.zeros((n_dev,), jnp.int32)

    reductions = {"confmat": "sum", "preds": "cat", "target": "cat", "valid": "cat", "overflow": "sum"}

    def step(confmat, preds, target, valid, overflow):
        state = {
            "confmat": confmat[0],
            "preds": preds[0],
            "target": target[0],
            "valid": valid[0],
            "overflow": overflow[0],
        }
        synced = sync_in_mesh(state, reductions, "rank")
        total = sum(jnp.sum(v.astype(jnp.float32)) for v in synced.values())
        return total[None]

    fn = jax.jit(
        shard_map(
            step,
            mesh=mesh,
            in_specs=(P("rank"), P("rank"), P("rank"), P("rank"), P("rank")),
            out_specs=P("rank"),
        )
    )

    args = (confmat, preds, target, valid, overflow)
    cost = _compiled_cost_payload(fn, *args)
    float(fn(*args)[0])  # compile
    warmup, iters = 3, 50
    for _ in range(warmup):
        float(fn(*args)[0])
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        float(fn(*args)[0])  # scalar readback bounds the timed region
        times.append(time.perf_counter() - t0)
    p50 = float(np.percentile(times, 50) * 1e3)
    p95 = float(np.percentile(times, 95) * 1e3)

    ref_p50 = None
    procs = []
    try:
        import multiprocessing as mp

        ctx = mp.get_context("spawn")
        queue = ctx.Queue()
        port = 29571
        world = n_dev
        procs = [
            ctx.Process(target=_ref_sync_worker, args=(r, world, port, 3, 20, queue))
            for r in range(world)
        ]
        for p in procs:
            p.start()
        ref_times = queue.get(timeout=300)
        for p in procs:
            p.join(timeout=60)
        ref_p50 = float(np.percentile(ref_times, 50) * 1e3)
    except Exception:
        for p in procs:
            if p.is_alive():
                p.terminate()

    print(
        json.dumps(
            _with_cost(
                {
                    "metric": "mesh_state_sync_latency_p50",
                    "value": round(p50, 3),
                    "unit": "ms",
                    "p95_ms": round(p95, 3),
                    "ranks": n_dev,
                    "ref_gloo_p50_ms": round(ref_p50, 3) if ref_p50 else None,
                    "vs_baseline": round(ref_p50 / p50, 3) if ref_p50 else None,
                },
                cost,
            )
        )
    )


def bench_inference() -> None:
    """Inference-metric extractor throughput (BASELINE config 5b): the Flax
    InceptionV3 FID feature path and the BERTScore Flax encoder, on the
    accelerator, vs the torch-CPU mirrors of the same architectures.

    Random weights — THROUGHPUT only (numeric parity is pinned separately by
    tests/image + the gated real-weight tests). Device work mirrors what the
    metrics run per update: Inception forward + FID's running feature-sum /
    Gram accumulation; BERT forward + bert_score's L2-normalize + greedy
    cosine matching. Both run as one jitted lax.scan epoch over distinct
    batches (the jitted-eval-loop shape; see bench_tpu's rationale) ending in
    a scalar readback."""
    import jax
    import jax.numpy as jnp
    from metrics_tpu.models.inception import InceptionV3FID

    rng = np.random.RandomState(0)

    # --- FID extractor: uint8 COCO/ImageNet-shaped batches ---
    model = InceptionV3FID()
    # 24 steps amortize the fixed ~100 ms readback RTT (see ITERS note)
    fb, fnb = 64, 24
    imgs = jnp.asarray(rng.randint(0, 256, (fnb, fb, 3, 299, 299), dtype=np.uint8))
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 3, 299, 299), jnp.float32))

    @jax.jit
    def fid_epoch(variables, imgs):
        def step(carry, batch):
            feats = model.apply(variables, batch)  # [fb, 2048]
            return (carry[0] + feats.sum(0), carry[1] + feats.T @ feats), ()

        init = (jnp.zeros((2048,)), jnp.zeros((2048, 2048)))
        (s, g), _ = jax.lax.scan(step, init, imgs)
        return s.sum() + g.sum()

    float(fid_epoch(variables, imgs))  # compile
    for _ in range(2):
        float(fid_epoch(variables, imgs))
    t0 = time.perf_counter()
    float(fid_epoch(variables, imgs))
    fid_ips = fb * fnb / (time.perf_counter() - t0)
    fid_cost = _compiled_cost_payload(fid_epoch, variables, imgs)

    fid_ref_ips = None
    try:
        import torch

        sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tests"))
        from image.test_fid_kid_is import TorchFIDInception

        rb = 32
        t_imgs = (
            torch.from_numpy(rng.randint(0, 256, (rb, 3, 299, 299), dtype=np.uint8)).float() / 255.0
        )
        net = TorchFIDInception().eval()
        with torch.no_grad():
            net(t_imgs[:2])  # warmup
            t0 = time.perf_counter()
            feats = net(t_imgs)
            s = feats.sum(0)
            g = feats.T @ feats
            float(s.sum() + g.sum())
            fid_ref_ips = rb / (time.perf_counter() - t0)
    except Exception:
        pass

    print(
        json.dumps(
            _with_cost(
                {
                    "metric": "fid_inception_extractor_throughput",
                    "value": round(fid_ips, 1),
                    "unit": "images/sec",
                    "vs_baseline": round(fid_ips / fid_ref_ips, 3) if fid_ref_ips else None,
                },
                fid_cost,
            )
        )
    )

    # --- BERTScore encoder: BERT-base-shaped, seq len 128 ---
    from transformers import BertConfig, FlaxBertModel

    cfg = BertConfig()
    bmodel = FlaxBertModel(cfg, seed=0, dtype=jnp.float32)
    sb, sl, snb = 64, 128, 24
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (snb, sb, sl)).astype(np.int32))
    mask = jnp.ones((snb, sb, sl), jnp.int32)
    params = bmodel.params

    @jax.jit
    def bert_epoch(params, ids, mask):
        def step(carry, xs):
            i, m = xs
            h = bmodel.module.apply(
                {"params": params}, input_ids=i, attention_mask=m
            ).last_hidden_state
            h = h / jnp.linalg.norm(h, axis=-1, keepdims=True)
            sim = jnp.einsum("bld,bmd->blm", h, h)
            return carry + sim.max(-1).mean(), ()

        tot, _ = jax.lax.scan(step, jnp.asarray(0.0), (ids, mask))
        return tot

    float(bert_epoch(params, ids, mask))  # compile
    for _ in range(2):
        float(bert_epoch(params, ids, mask))
    t0 = time.perf_counter()
    float(bert_epoch(params, ids, mask))
    bert_sps = sb * snb / (time.perf_counter() - t0)
    bert_cost = _compiled_cost_payload(bert_epoch, params, ids, mask)

    bert_ref_sps = None
    try:
        import torch
        from transformers import BertModel

        bm = BertModel(cfg).eval()
        t_ids = torch.from_numpy(rng.randint(0, cfg.vocab_size, (sb, sl)).astype(np.int64))
        t_mask = torch.ones(sb, sl, dtype=torch.int64)
        with torch.no_grad():
            bm(input_ids=t_ids[:4], attention_mask=t_mask[:4])  # warmup
            t0 = time.perf_counter()
            h = bm(input_ids=t_ids, attention_mask=t_mask).last_hidden_state
            h = h / h.norm(dim=-1, keepdim=True)
            sim = torch.einsum("bld,bmd->blm", h, h)
            float(sim.max(-1).values.mean())
            bert_ref_sps = sb / (time.perf_counter() - t0)
    except Exception:
        pass

    print(
        json.dumps(
            _with_cost(
                {
                    "metric": "bertscore_encoder_throughput",
                    "value": round(bert_sps, 1),
                    "unit": "sentences/sec",
                    "vs_baseline": round(bert_sps / bert_ref_sps, 3) if bert_ref_sps else None,
                },
                bert_cost,
            )
        )
    )


def bench_fused() -> None:
    """Fused vs eager MetricCollection update throughput (ISSUE 4 tentpole).

    A 6-metric classification collection (Accuracy / Precision / Recall /
    F1Score / ConfusionMatrix / CohenKappa) is updated over batches cycling
    THREE ragged shapes. The eager side pays one XLA dispatch per metric per
    batch; the fused side runs ``compile_update(buckets=...)`` — one jitted
    dispatch per batch with pad-and-mask bucketing, so the three shapes
    share ONE compilation. Both sides get one untimed discovery batch first
    (compute groups settle), and the timed region ends with a
    block-until-ready over every state so kernel completion is inside it.

    Also pins ``fused_first_batch_ms`` (ISSUE 6): wall time of the FIRST
    fused batch on a fresh handle — the per-(metric, signature)
    ``eval_shape`` fusibility probes plus the kernel compile — measured with
    the tracelint fusibility manifest consulted (statically-proven-fusible
    members skip their probes) and, as the reference column, with the
    manifest disabled. The delta is the probe cost the static manifest
    removes from every cold start.
    """
    import jax
    import jax.numpy as jnp
    from metrics_tpu import MetricCollection
    from metrics_tpu.classification import (
        Accuracy,
        CohenKappa,
        ConfusionMatrix,
        F1Score,
        Precision,
        Recall,
    )

    rng = np.random.RandomState(7)
    n_classes = 10
    shapes = (1900, 2000, 2048)

    def make_batch(n):
        p = rng.rand(n, n_classes).astype(np.float32)
        p /= p.sum(-1, keepdims=True)
        return jnp.asarray(p), jnp.asarray(rng.randint(0, n_classes, n))

    def make_collection():
        return MetricCollection(
            [
                Accuracy(),
                Precision(num_classes=n_classes, average="macro"),
                Recall(num_classes=n_classes, average="macro"),
                F1Score(num_classes=n_classes, average="macro"),
                ConfusionMatrix(num_classes=n_classes),
                CohenKappa(num_classes=n_classes),
            ]
        )

    batches = [make_batch(n) for n in shapes]
    epoch = batches * 10  # 30 timed updates, 3 ragged shapes interleaved

    def block(col):
        jax.block_until_ready(
            [
                getattr(m, s)
                for m in col.values()
                for s in m._defaults
                if not isinstance(getattr(m, s), (list, int))
            ]
        )

    eager, fused = make_collection(), make_collection()
    # untimed discovery batch: compute groups settle before either side is
    # measured, so the fused cache sees ONE stable metric structure
    eager.update(*batches[0])
    fused.update(*batches[0])
    handle = fused.compile_update(buckets=(2048,))
    for b in batches:  # warmup: compiles (fused) and caches (eager) per shape
        eager.update(*b)
        fused.update(*b)
    block(eager)
    block(fused)

    t0 = time.perf_counter()
    for b in epoch:
        eager.update(*b)
    block(eager)
    eager_ups = len(epoch) / (time.perf_counter() - t0)

    t0 = time.perf_counter()
    for b in epoch:
        fused.update(*b)
    block(fused)
    fused_ups = len(epoch) / (time.perf_counter() - t0)

    # first-batch setup cost: fresh handle, one discovery update, then the
    # timed first fused batch (fusibility probes + kernel compile). Measured
    # with and without the static manifest so the probe-skip win is pinned.
    def first_batch_ms(use_manifest):
        col = make_collection()
        col.update(*batches[0])
        handle = col.compile_update(buckets=(2048,), use_manifest=use_manifest)
        t0 = time.perf_counter()
        col.update(*batches[0])
        block(col)
        return (time.perf_counter() - t0) * 1e3, handle.manifest_probe_skips

    # min-of-2 per side: first-batch cost is XLA-compile-dominated and the
    # compile time itself is noisy; the min is the stable floor
    first_no_manifest_ms = min(first_batch_ms(False)[0] for _ in range(2))
    runs = [first_batch_ms(True) for _ in range(2)]
    first_manifest_ms = min(ms for ms, _ in runs)
    probe_skips = runs[0][1]

    print(
        json.dumps(
            {
                "metric": "collection_fused_update_throughput",
                "value": round(fused_ups, 1),
                "unit": "updates/sec",
                "eager_updates_per_sec": round(eager_ups, 1),
                "fused_vs_eager": round(fused_ups / eager_ups, 3),
                "bucketed_compiles": handle.n_compiles,
                "bucketed_shapes": len(shapes),
                "n_metrics": len(fused),
                "fused_first_batch_ms": round(first_manifest_ms, 2),
                "fused_first_batch_no_manifest_ms": round(first_no_manifest_ms, 2),
                "manifest_probe_skips": probe_skips,
            }
        )
    )


def bench_async() -> None:
    """Async vs blocking fused ingest under a producer/consumer serving loop
    (ISSUE 7 tentpole).

    The same 6-metric classification collection as ``bench_fused``, updated
    at a fixed batch shape. Each serving-loop step first *handles a
    request* — modeled as an I/O-bound wait calibrated to ~1x the blocking
    fused update's wall cost, because a real serving loop spends the gap
    between metric updates blocked on the next request batch / model
    forward, not burning host CPU (a CPU-bound gap on the 2-vCPU CI box
    would measure core contention, not pipeline design) — then accounts
    the batch:

    * **blocking** — ``compile_update()``; the step pays request-wait +
      the fused update's host dispatch serially.
    * **async** — ``compile_update_async(queue_depth=2)``; the step pays
      request-wait + a microseconds ``update_async`` enqueue, and the
      worker thread overlaps the fused dispatch (and any eager fallbacks)
      with the next request's wait.

    Emits ``async_vs_blocking`` (steady-state throughput ratio, each side's
    best of 5 alternating epochs; the acceptance floor is 1.3x) and the p99
    ``update_async`` call latency within that best epoch —
    both gated as AUX_FIELDS by scripts/check_cost_regression.py — plus a
    ``states_bit_identical`` parity bit: both sides consume the identical
    batch sequence and must land byte-equal final states.
    """
    import jax
    import jax.numpy as jnp
    from metrics_tpu import MetricCollection
    from metrics_tpu.classification import (
        Accuracy,
        CohenKappa,
        ConfusionMatrix,
        F1Score,
        Precision,
        Recall,
    )

    rng = np.random.RandomState(7)
    n_classes = 10
    n = 2048
    steps = 100

    def make_batch():
        p = rng.rand(n, n_classes).astype(np.float32)
        p /= p.sum(-1, keepdims=True)
        return jnp.asarray(p), jnp.asarray(rng.randint(0, n_classes, n))

    def make_collection():
        return MetricCollection(
            [
                Accuracy(),
                Precision(num_classes=n_classes, average="macro"),
                Recall(num_classes=n_classes, average="macro"),
                F1Score(num_classes=n_classes, average="macro"),
                ConfusionMatrix(num_classes=n_classes),
                CohenKappa(num_classes=n_classes),
            ]
        )

    def block(col):
        jax.block_until_ready(
            [
                getattr(m, s)
                for m in col.values()
                for s in m._defaults
                if not isinstance(getattr(m, s), (list, int))
            ]
        )

    pool = [make_batch() for _ in range(8)]
    warmup = pool[:4]
    epoch = [pool[i % len(pool)] for i in range(steps)]

    # --- blocking side: discovery, compile, warmup, calibrate update cost ---
    blocking = make_collection()
    blocking.update(*pool[0])
    blocking.compile_update()
    for b in warmup:
        blocking.update(*b)
    block(blocking)
    # calibrate with the min over 3 groups: the wait models the request gap
    # and sets the overlap regime, so a single GC pause or scheduler stall
    # in the calibration pass must not inflate it (an overshot wait dilutes
    # the measurable overlap toward 1x regardless of pipeline quality)
    per_group = []
    for _ in range(3):
        t0 = time.perf_counter()
        for b in warmup:
            blocking.update(*b)
        block(blocking)
        per_group.append((time.perf_counter() - t0) / len(warmup))
    update_ms = min(per_group) * 1e3

    # per-step request handling, calibrated to ~1x the update cost — the
    # overlap-matters-most regime (request rate ~= metric accounting rate)
    work_s = update_ms / 1e3

    def produce():
        time.sleep(work_s)

    # --- async side: identical batch sequence, ingest via the queue ---
    asynchronous = make_collection()
    asynchronous.update(*pool[0])
    handle = asynchronous.compile_update_async(queue_depth=2)
    for b in warmup * 4:  # mirror the blocking side's warmup + calibration
        handle.update_async(*b)
    handle.flush()
    block(asynchronous)

    # --- timed epochs: best-of-5 per side, alternating so clock drift and
    # background load hit both sides alike; each side's best epoch is its
    # steady-state throughput (standard min-of-N wall-time practice — on
    # shared-infra vCPUs single epochs swing tens of percent) ---
    latencies = []  # enqueue latencies of the BEST async epoch: p99 must
    # characterize the pipeline's steady state, not whichever epochs a
    # noisy-neighbor stall happened to hit (a starved scheduler inflates
    # the pooled tail by 10-100x with zero code change)
    blocking_ups = 0.0
    async_ups = 0.0
    for _rep in range(5):
        t0 = time.perf_counter()
        for b in epoch:
            produce()
            blocking.update(*b)
        block(blocking)
        blocking_ups = max(blocking_ups, steps / (time.perf_counter() - t0))

        lat_rep = []
        t0 = time.perf_counter()
        for b in epoch:
            produce()
            t_call = time.perf_counter()
            handle.update_async(*b)
            lat_rep.append(time.perf_counter() - t_call)
        handle.flush()  # the tail drain is part of the measured epoch
        block(asynchronous)
        ups = steps / (time.perf_counter() - t0)
        if ups > async_ups:
            async_ups, latencies = ups, lat_rep
    p99_ms = float(np.percentile(latencies, 99) * 1e3)
    dropped = handle.dropped
    handle.close()

    # parity: both sides consumed the identical sequence — every state
    # leaf must match byte for byte
    identical = True
    for name, m_async in asynchronous.items(keep_base=True):
        m_block = blocking[name]
        for sname in m_async._defaults:
            va, vb = np.asarray(getattr(m_async, sname)), np.asarray(getattr(m_block, sname))
            if not np.array_equal(va, vb):
                identical = False

    print(
        json.dumps(
            {
                "metric": "collection_async_update_throughput",
                "value": round(async_ups, 1),
                "unit": "updates/sec",
                "blocking_updates_per_sec": round(blocking_ups, 1),
                "async_vs_blocking": round(async_ups / blocking_ups, 3),
                "update_async_p99_ms": round(p99_ms, 3),
                "request_wait_ms": round(work_s * 1e3, 3),
                "blocking_update_ms": round(update_ms, 3),
                "queue_depth": 2,
                "dropped_batches": dropped,
                "n_metrics": len(asynchronous),
                "states_bit_identical": identical,
            }
        )
    )


def bench_sliced() -> None:
    """Sliced single-dispatch update vs object fan-out (ISSUE 8 tentpole).

    One ``SlicedMetric(MeanSquaredError, S)`` ingests batches whose rows
    span S slices through the fused single-dispatch kernel (ragged batch
    sizes bucketed so ALL slice batches share ONE compilation); the
    reference is the ``ClasswiseWrapper``-style fan-out — S independent
    metric objects, each fed its slice's sub-batch, S Python dispatches per
    batch. Measured at S ∈ {16, 1k, 100k}; the fan-out side is only run
    where it terminates in sane time (at 100k slices a single fan-out batch
    is ~10^5 eager updates — the architecture being replaced).

    The committed gate (BENCH_r08.json) rides the AUX fields:
    ``sliced_vs_fanout`` (row throughput ratio at S=1k, ISSUE 8 acceptance
    floor 5x) and ``sliced_scatter_compiles`` (exactly 1 compile across the
    bucketed ragged shapes). ``states_bit_identical`` is the parity bit —
    integer-valued data makes every partial sum exact, so the sliced state
    must match the fan-out accumulation bit for bit.
    """
    import jax
    import jax.numpy as jnp

    from metrics_tpu import MetricCollection
    from metrics_tpu.regression import MeanSquaredError
    from metrics_tpu.sliced import SlicedMetric

    rng = np.random.RandomState(8)
    sizes = (3072, 3584, 4096)
    bucket = 4096

    def make_batches(S, n):
        out = []
        for i in range(n):
            b = sizes[i % len(sizes)]
            ids = rng.randint(0, S, b)
            preds = rng.randint(0, 8, b).astype(np.float32)
            target = rng.randint(0, 8, b).astype(np.float32)
            out.append((jnp.asarray(ids), jnp.asarray(preds), jnp.asarray(target)))
        return out

    def block(col):
        jax.block_until_ready(
            [getattr(m, s) for m in col.values() for s in m._defaults]
        )

    def sliced_rows_per_sec(S, batches):
        col = MetricCollection({"m": SlicedMetric(MeanSquaredError(), num_slices=S)})
        col.update(*batches[0])  # discovery
        handle = col.compile_update(buckets=(bucket,))
        for b in batches[: len(sizes)]:  # warm every bucketed shape
            col.update(*b)
        block(col)
        # best-of-3: this box's noisy-neighbor CPU steal swings wall clock
        # ~3x; the best epoch is the stable floor (BENCH_r07 precedent)
        best = 0.0
        for _ in range(3):
            t0 = time.perf_counter()
            for b in batches:
                col.update(*b)
            block(col)
            rows = sum(int(b[0].shape[0]) for b in batches)
            best = max(best, rows / (time.perf_counter() - t0))
        return best, handle, col

    def fanout_rows_per_sec(S, batches, timed):
        objs = [MeanSquaredError() for _ in range(S)]

        def apply(batch):
            ids, preds, target = (np.asarray(x) for x in batch)
            order = np.argsort(ids, kind="stable")
            ids, preds, target = ids[order], preds[order], target[order]
            bounds = np.flatnonzero(np.diff(ids)) + 1
            for chunk_ids, chunk_p, chunk_t in zip(
                np.split(ids, bounds), np.split(preds, bounds), np.split(target, bounds)
            ):
                objs[int(chunk_ids[0])].update(jnp.asarray(chunk_p), jnp.asarray(chunk_t))

        apply(batches[0])  # warm the per-shape jit caches
        t0 = time.perf_counter()
        for b in batches[1 : 1 + timed]:
            apply(b)
        jax.block_until_ready([o.sum_squared_error for o in objs])
        rows = sum(int(b[0].shape[0]) for b in batches[1 : 1 + timed])
        return rows / (time.perf_counter() - t0), objs

    per_s = {}
    # S=1k: the headline ratio + parity bit
    S = 1000
    batches_1k = make_batches(S, 12)
    sliced_ups, handle, col = sliced_rows_per_sec(S, batches_1k)
    fanout_ups, objs = fanout_rows_per_sec(S, batches_1k, timed=2)
    # parity bit on a FRESH pair over one short epoch (the timed handles
    # above saw different batch counts): sliced state must equal the
    # per-object sub-batch accumulation bit for bit
    parity_sliced = SlicedMetric(MeanSquaredError(), num_slices=S)
    parity_objs = [MeanSquaredError() for _ in range(S)]
    for ids, preds, target in batches_1k[:4]:
        parity_sliced.update(ids, preds, target)
        ids_np = np.asarray(ids)
        for i in np.unique(ids_np):
            mask = ids_np == i
            parity_objs[int(i)].update(preds[mask], target[mask])
    # one stacked comparison per leaf (2 readbacks), not one per slice
    identical = all(
        bool(
            jnp.array_equal(
                getattr(parity_sliced, k),
                jnp.stack([jnp.asarray(getattr(o, k)) for o in parity_objs]),
            )
        )
        for k in ("sum_squared_error", "total")
    )
    per_s["1000"] = {
        "sliced_rows_per_sec": round(sliced_ups, 1),
        "fanout_rows_per_sec": round(fanout_ups, 1),
    }

    # S=16: fan-out's best case (few objects) — the ratio floor context
    batches_16 = make_batches(16, 12)
    s16, _, _ = sliced_rows_per_sec(16, batches_16)
    f16, _ = fanout_rows_per_sec(16, batches_16, timed=3)
    per_s["16"] = {"sliced_rows_per_sec": round(s16, 1), "fanout_rows_per_sec": round(f16, 1)}

    # S=100k: sliced only — the scale the object fan-out cannot reach
    batches_100k = make_batches(100_000, 6)
    s100k, handle_100k, _ = sliced_rows_per_sec(100_000, batches_100k)
    per_s["100000"] = {"sliced_rows_per_sec": round(s100k, 1), "fanout_rows_per_sec": None}

    print(
        json.dumps(
            {
                "metric": "sliced_update_throughput",
                "value": round(sliced_ups, 1),
                "unit": "rows/sec",
                "sliced_vs_fanout": round(sliced_ups / fanout_ups, 2),
                "sliced_scatter_compiles": handle.n_compiles,
                "bucketed_shapes": len(sizes),
                "states_bit_identical": identical,
                "per_slice_count": per_s,
            }
        )
    )


def bench_sketch() -> None:
    """Sketch-backed streaming states vs the exact cat-state path (ISSUE 10).

    One million binary samples stream through a sketched AUROC (the new
    default state mode, quantile sketch at the default capacity) and
    through ``exact=True`` (yesterday's unbounded cat-list default). The
    tentpole claims being gated:

    * **O(capacity) memory** — ``sketch_state_bytes_frac`` is the sketched
      state's bytes as a fraction of the exact path's O(N) bytes at 10^6
      samples (~1.3% at the 8192 default; anchor gates it from growing).
    * **Bounded accuracy** — ``sketch_auroc_abs_err`` is the |sketched −
      exact| AUROC gap at 10^6 samples, the end-to-end realization of the
      quantile sketch's advertised rank-error envelope.
    * **Fusion intact** — a sketched AUROC inside a fused collection with
      pad-and-mask bucketing must compile EXACTLY once across three ragged
      batch shapes (``sketch_fused_compiles``, anchor 1): the n_valid
      pad-mask contract is what keeps merge-leaf states bucketable.
    * **Lossless window** — ``sketch_window_bit_exact`` (BOOL_FIELDS) pins
      the bit-for-bit equality of sketch-default and exact compute while
      the stream fits the capacity.
    """
    import jax
    import jax.numpy as jnp

    from metrics_tpu import AUROC, Accuracy, MetricCollection

    rng = np.random.RandomState(10)
    n_total, bs = 1_000_000, 4096
    batches = []
    for lo in range(0, n_total, bs):
        preds = rng.rand(bs).astype(np.float32)
        target = (rng.rand(bs) < 0.35).astype(np.int32)
        batches.append((jnp.asarray(preds), jnp.asarray(target)))

    def run(metric):
        metric.update(*batches[0])  # warm the insert kernel cache
        jax.block_until_ready(metric.csketch if hasattr(metric, "csketch") else metric.preds[-1])
        t0 = time.perf_counter()
        for b in batches[1:]:
            metric.update(*b)
        if hasattr(metric, "csketch"):
            jax.block_until_ready(metric.csketch)
        dur = time.perf_counter() - t0
        return (len(batches) - 1) * bs / dur, metric

    sketched_ups, sketched = run(AUROC())
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        exact_ups, exact = run(AUROC(exact=True))
    sketch_bytes = sketched.total_state_bytes()
    exact_bytes = exact.total_state_bytes()
    err = abs(float(sketched.compute()) - float(exact.compute()))

    # lossless-window parity bit: a short stream must be BIT-equal
    small = AUROC()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        small_exact = AUROC(exact=True)
    for b in batches[:2]:
        small.update(*b)
        small_exact.update(*b)
    window_bit_exact = float(small.compute()) == float(small_exact.compute())

    # fused + bucketed: sketched metric rides the single-dispatch kernel —
    # one compile across three ragged shapes via the n_valid pad mask
    col = MetricCollection([Accuracy(), AUROC()])
    handle = col.compile_update(buckets=(bs,))
    for n in (bs - 512, bs, bs - 100):
        p = rng.rand(n).astype(np.float32)
        t = (rng.rand(n) < 0.35).astype(np.int32)
        col.update(jnp.asarray(p), jnp.asarray(t))

    print(
        json.dumps(
            {
                "metric": "sketched_auroc_throughput",
                "value": round(sketched_ups, 1),
                "unit": "samples/sec",
                "exact_samples_per_sec": round(exact_ups, 1),
                "sketch_state_bytes": int(sketch_bytes),
                "exact_state_bytes_at_1m": int(exact_bytes),
                "sketch_state_bytes_frac": round(sketch_bytes / exact_bytes, 5),
                "sketch_auroc_abs_err": round(err, 6),
                "sketch_fused_compiles": handle.n_compiles,
                "bucketed_shapes": 3,
                "sketch_window_bit_exact": bool(window_bit_exact),
            }
        )
    )


def bench_windowed() -> None:
    """Windowed metric state vs plain all-of-time state (ISSUE 12).

    A fused collection of ``WindowedMetric``-wrapped Accuracy+MSE streams
    bucketed ragged batches next to the identical unwrapped collection.
    The tentpole claims being gated:

    * **Fusion intact** — the windowed collection compiles EXACTLY once
      across three ragged bucketed batch shapes (``windowed_compiles``,
      anchor 1): the ring rotation is a fixed-shape ``.at[slot].set`` and
      the wrapper's slot-aware pad correction keeps bucketing exact.
    * **Affordable window** — ``windowed_vs_plain`` is the fused
      throughput ratio of the windowed collection over the plain one
      (the R-fold state plus the rotation costs something; the anchor
      gates it from collapsing).
    * **Ring-fold exactness** — ``windowed_ring_fold_exact``
      (BOOL_FIELDS) pins that a ring-window ``compute()`` on
      integer-exact data is BIT-identical to recomputing the same
      window's batches from scratch — the sliding window is the real
      metric, not an approximation of it.
    """
    import jax
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, MeanSquaredError, MetricCollection
    from metrics_tpu.windowed import WindowedMetric

    rng = np.random.RandomState(12)
    bs = 2048
    shapes = (bs - 512, bs, bs - 100)

    def make_batches(n_batches):
        out = []
        for i in range(n_batches):
            n = shapes[i % len(shapes)]
            preds = rng.randint(0, 2, n).astype(np.int32)
            target = rng.randint(0, 2, n).astype(np.int32)
            out.append((jnp.asarray(preds), jnp.asarray(target)))
        return out

    def make_collection(windowed):
        # num_classes keeps Accuracy's canonicalizer traceable so both
        # members genuinely ride the fused kernel on both sides of the ratio
        if windowed:
            return MetricCollection(
                {
                    "acc": WindowedMetric(Accuracy(num_classes=2), window=8, updates_per_bucket=4),
                    "mse": WindowedMetric(MeanSquaredError(), window=8, updates_per_bucket=4),
                }
            )
        return MetricCollection({"acc": Accuracy(num_classes=2), "mse": MeanSquaredError()})

    def block(col):
        for m in col.values():
            for name in m._defaults:
                val = getattr(m, name)
                if isinstance(val, jnp.ndarray):
                    jax.block_until_ready(val)

    n_measure = 120
    batches = make_batches(n_measure)

    def rows_per_sec(windowed):
        col = make_collection(windowed)
        handle = col.compile_update(buckets=(bs,))
        for b in batches[:6]:  # warm every bucket entry + group discovery
            col.update(*b)
        block(col)
        best = 0.0
        for _ in range(3):  # min-of-3: this box's CPU steal is noisy
            t0 = time.perf_counter()
            rows = 0
            for b in batches[6:]:
                col.update(*b)
                rows += int(b[0].shape[0])
            block(col)
            best = max(best, rows / (time.perf_counter() - t0))
        return best, handle

    windowed_ups, whandle = rows_per_sec(True)
    plain_ups, _ = rows_per_sec(False)

    # ring-fold exactness on integer data: compute() over the ring must be
    # bit-identical to recomputing the in-window batches from scratch
    wm = WindowedMetric(MeanSquaredError(), window=4, updates_per_bucket=2)
    parity_batches = [
        (
            jnp.asarray(rng.randint(0, 7, 256).astype(np.float32)),
            jnp.asarray(rng.randint(0, 7, 256).astype(np.float32)),
        )
        for _ in range(11)
    ]
    for b in parity_batches:
        wm.update(*b)
    # 11 updates, 2/bucket -> buckets 0..5; ring of 4 holds buckets 2..5 =
    # updates 4..10
    fresh = MeanSquaredError()
    for b in parity_batches[4:]:
        fresh.update(*b)
    ring_fold_exact = float(wm.compute()) == float(fresh.compute())

    print(
        json.dumps(
            {
                "metric": "windowed_update_throughput",
                "value": round(windowed_ups, 1),
                "unit": "rows/sec",
                "plain_rows_per_sec": round(plain_ups, 1),
                "windowed_vs_plain": round(windowed_ups / plain_ups, 4),
                "windowed_compiles": whandle.n_compiles,
                "windowed_fused": whandle.n_compiles == 1,
                "bucketed_shapes": len(shapes),
                "windowed_ring_fold_exact": bool(ring_fold_exact),
            }
        )
    )


def bench_collector() -> None:
    """Fleet-observatory collector bench (ISSUE 13).

    Pre-encodes a fleet's worth of cumulative snapshots (8 publishers x
    ~150 sequence numbers, each carrying the metric-state pytree of an
    Accuracy+MSE collection plus a telemetry counter payload) and measures
    the two tentpole numbers:

    * **fold throughput** — ``collector_fold_per_sec``: snapshots ingested
      (decode + validate + dedup + absorb) per second through
      ``FleetCollector.ingest`` plus the final global fold; the
      "thousands of snapshots per second" claim, AUX-gated.
    * **wire cost** — ``wire_bytes_per_snapshot``: mean encoded snapshot
      size for this template; growth means the wire format regressed
      (e.g. lost the raw-buffer encoding), AUX-gated lower-is-better.
    * **determinism** — ``collector_fold_deterministic`` (BOOL_FIELDS):
      the same snapshot multiset ingested in two different arrival orders
      (including a duplicate) must produce bit-identical folded state
      leaves and a byte-identical fold-side Prometheus page; a false bit
      fails the gate regardless of throughput.
    """
    import jax.numpy as jnp

    from metrics_tpu import MeanSquaredError, MetricCollection
    from metrics_tpu.classification import Accuracy
    from metrics_tpu.observability import counter_payload, encode_snapshot, snapshot_states
    from metrics_tpu.observability.collector import FleetCollector

    def make_collection():
        return MetricCollection({"acc": Accuracy(num_classes=2), "mse": MeanSquaredError()})

    rng = np.random.RandomState(13)
    n_pubs, n_seqs, bs = 8, 150, 64
    payload = counter_payload()
    blobs = []
    t_base = 1_000_000.0
    for p in range(n_pubs):
        col = make_collection()
        for seq in range(n_seqs):
            preds = jnp.asarray(rng.randint(0, 2, bs), jnp.int32)
            target = jnp.asarray(rng.randint(0, 2, bs), jnp.int32)
            col.update(preds, target)
            blobs.append(
                encode_snapshot(
                    publisher=f"pub{p}",
                    seq=seq,
                    t=t_base + seq,
                    host=f"host{p % 4}",
                    process=p,
                    states=snapshot_states(col),
                    states_template=col,
                    telemetry=payload,
                )
            )
    wire_bytes = sum(len(b) for b in blobs) / len(blobs)

    def fold_all(order):
        coll = FleetCollector(template=make_collection(), late_window_s=1e9, stale_after_s=60.0)
        t0 = time.perf_counter()
        for i in order:
            coll.ingest(blobs[i], now=t_base + n_seqs)
        states = coll.fold_states()
        dur = time.perf_counter() - t0
        return coll, states, len(order) / dur

    base_order = list(range(len(blobs)))
    coll, states_a, per_sec = fold_all(base_order)

    # determinism probe: reversed arrival plus one duplicate — identical
    # folded leaves, identical fold-side exposition bytes
    perm = list(reversed(base_order)) + [0]
    coll_b, states_b, _ = fold_all(perm)
    det = coll_b.totals()["duplicates"] == 1
    for name in states_a:
        for leaf in states_a[name]:
            det = det and bool(
                np.array_equal(np.asarray(states_a[name][leaf]), np.asarray(states_b[name][leaf]))
            )
    det = det and (
        coll.render_prometheus(include_collector_families=False, include_fold_values=True)
        == coll_b.render_prometheus(include_collector_families=False, include_fold_values=True)
    )

    print(
        json.dumps(
            {
                "metric": "collector_fold_throughput",
                "value": None,
                "unit": "snapshots/sec",
                "collector_fold_per_sec": round(per_sec, 1),
                "wire_bytes_per_snapshot": round(wire_bytes, 1),
                "n_snapshots": len(blobs),
                "n_publishers": n_pubs,
                "collector_fold_deterministic": bool(det),
            }
        )
    )


def bench_ops() -> None:
    """Ops kernel suite: dispatched-vs-direct throughput per op + parity bits.

    For each registered hot op (bincount, segment_sum, qsketch_compact) at
    2-3 sizes, times the registry-dispatched path against a direct call of
    the jnp implementation. On this CPU box both resolve to the same jnp
    kernel, so the ratio isolates the DISPATCH LAYER's overhead (registry
    lookup + routing predicate + counter check) — the ``ops_dispatch_overhead``
    AUX gate pins it near 1.0 so the shared layer can never quietly tax
    every confusion-matrix update. On TPU the same bench doubles as the
    kernel-vs-jnp A/B (the dispatched side routes to Pallas above the
    density floors).

    The parity BOOLs run the REAL Pallas kernel bodies in interpret mode
    on integer-exact data, where the f32 MXU accumulation is exact: a
    false bit means a kernel diverged from its fallback — data corruption
    regardless of speed — and fails CI via BOOL_FIELDS even without a
    baseline anchor.
    """
    import jax
    import jax.numpy as jnp

    from metrics_tpu import ops
    from metrics_tpu.ops.qsketch_pallas import _qsketch_compact_pallas
    from metrics_tpu.sketches.quantile import _compact_rows_jnp

    rng = np.random.RandomState(14)

    def best_of(fn, *args, reps=5, inner=4):
        fn(*args)  # warm caches / jit
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(inner):
                out = fn(*args)
            jax.block_until_ready(out)
            best = min(best, (time.perf_counter() - t0) / inner)
        return best

    per_op = {}

    # --- bincount: the confusion-matrix inner loop shape -------------------
    bincount_elems_per_sec = 0.0
    for n, c in ((1 << 16, 10_000), (1 << 20, 1_000_000)):
        x = jnp.asarray(rng.randint(0, c, n), jnp.int32)
        t_disp = best_of(lambda a: ops.bincount_dispatch(a, c), x)
        t_jnp = best_of(lambda a: jnp.bincount(a, length=c), x)
        per_op[f"bincount_{n}x{c}"] = {
            "dispatched_elems_per_sec": round(n / t_disp, 1),
            "jnp_elems_per_sec": round(n / t_jnp, 1),
            "overhead_ratio": round(t_disp / t_jnp, 4),
        }
        bincount_elems_per_sec = n / t_disp

    # --- segment_sum: the sliced-scatter shape -----------------------------
    for b, d, s in ((1 << 16, 8, 1_000), (1 << 18, 8, 100_000)):
        vals = jnp.asarray(rng.randint(0, 7, (b, d)).astype(np.float32))
        ids = jnp.asarray(rng.randint(0, s, b), jnp.int32)
        t_disp = best_of(lambda v, i: ops.segment_sum_dispatch(v, i, s), vals, ids)
        t_jnp = best_of(lambda v, i: jax.ops.segment_sum(v, i, num_segments=s), vals, ids)
        per_op[f"segment_sum_{b}x{d}_s{s}"] = {
            "dispatched_rows_per_sec": round(b / t_disp, 1),
            "jnp_rows_per_sec": round(b / t_jnp, 1),
            "overhead_ratio": round(t_disp / t_jnp, 4),
        }

    # --- qsketch_compact: the sketched-metric overflow pass ----------------
    for cap in (1024, 8192):
        n = cap * 2
        rows = np.zeros((n, 3), np.float32)
        rows[:, 0] = 1.0
        rows[:, 1] = rng.randint(0, 100_000, n)
        rows[:, 2] = rng.randint(0, 2, n)
        rows = jnp.asarray(rows)
        t_disp = best_of(lambda r: ops.qsketch_compact_dispatch(r, cap), rows, reps=3, inner=2)
        t_jnp = best_of(lambda r: _compact_rows_jnp(r, cap), rows, reps=3, inner=2)
        per_op[f"qsketch_compact_{n}_cap{cap}"] = {
            "dispatched_rows_per_sec": round(n / t_disp, 1),
            "jnp_rows_per_sec": round(n / t_jnp, 1),
            "overhead_ratio": round(t_disp / t_jnp, 4),
        }

    # the gated overhead headline: the WORST dispatched/direct ratio across
    # ops and sizes (lower is better; ~1.0 when routing resolves to jnp)
    overhead = max(v["overhead_ratio"] for v in per_op.values())

    # --- parity bits: real kernel bodies, interpret mode, integer data ----
    xp = jnp.asarray(rng.randint(0, 500, 4096), jnp.int32)
    with ops.forced_backend("interpret"):
        bc_parity = bool(jnp.array_equal(ops.bincount_dispatch(xp, 500), jnp.bincount(xp, length=500)))
    sv = jnp.asarray(rng.randint(-9, 9, (2048, 4)).astype(np.float32))
    si = jnp.asarray(rng.randint(0, 300, 2048), jnp.int32)
    with ops.forced_backend("interpret"):
        ss_parity = bool(
            jnp.array_equal(
                ops.segment_sum_dispatch(sv, si, 300),
                jax.ops.segment_sum(sv, si, num_segments=300),
            )
        )
    prows = np.zeros((512, 3), np.float32)
    prows[:, 0] = rng.randint(1, 4, 512)
    prows[:, 1] = rng.randint(-500, 500, 512)
    prows[:, 2] = rng.randint(0, 3, 512)
    prows = jnp.asarray(prows)
    qc_parity = bool(
        jnp.array_equal(_qsketch_compact_pallas(prows, 256, interpret=True), _compact_rows_jnp(prows, 256))
    )

    # compiled-cost bill for the headline dispatched op (--cost-analysis)
    c = 1_000_000
    xbill = jnp.asarray(rng.randint(0, c, 1 << 20), jnp.int32)
    cost = _compiled_cost_payload(jax.jit(lambda a: ops.bincount_dispatch(a, c)), xbill)

    print(
        json.dumps(
            _with_cost(
                {
                    "metric": "ops_kernel_dispatch_throughput",
                    "value": round(bincount_elems_per_sec, 1),
                    "unit": "elems/sec",
                    "backend": jax.default_backend(),
                    "ops_dispatch_overhead": round(overhead, 4),
                    "ops_bincount_parity": bc_parity,
                    "ops_segment_sum_parity": ss_parity,
                    "ops_qsketch_compact_parity": qc_parity,
                    "per_op": per_op,
                },
                cost,
            )
        )
    )


def bench_ops_ab() -> None:
    """Route-floor A/B sweep for bincount / qsketch_compact (ROADMAP item
    1's open tuning note; the BASELINE.md "bincount/qsketch A/B" table).

    For a grid of sizes straddling each op's route floors, emits per cell:
    the ROUTE DECISION a TPU backend would take (the host-static
    predicate, evaluated directly — no hardware needed), the measured jnp
    fallback wall, and the dispatched wall on THIS backend. On the CPU CI
    box both walls resolve to the same jnp kernel, so their ratio
    isolates the dispatch-layer tax per size; on a TPU box the same
    sweep's dispatched column becomes the Pallas side and the table is
    the floor-tuning instrument. One JSON record; the human-readable
    table lands in BASELINE.md.
    """
    import jax
    import jax.numpy as jnp

    from metrics_tpu import ops
    from metrics_tpu.ops.scatter_pallas import _bincount_route
    from metrics_tpu.ops.qsketch_pallas import _qsketch_route
    from metrics_tpu.sketches.quantile import _compact_rows_jnp

    rng = np.random.RandomState(16)

    def best_of(fn, *args, reps=5, inner=4):
        fn(*args)
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(inner):
                out = fn(*args)
            jax.block_until_ready(out)
            best = min(best, (time.perf_counter() - t0) / inner)
        return best

    cells = []
    # --- bincount: sweep batch across the b >= 256 floor and the segment
    # count across the num_segments >= 64 floor
    for n in (128, 256, 1 << 12, 1 << 16, 1 << 20):
        for c in (32, 64, 4096, 1_000_000):
            x = jnp.asarray(rng.randint(0, c, n), jnp.int32)
            t_disp = best_of(lambda a: ops.bincount_dispatch(a, c), x)
            t_jnp = best_of(lambda a: jnp.bincount(a, length=c), x)
            cells.append(
                {
                    "op": "bincount",
                    "n": n,
                    "segments": c,
                    "tpu_route": "pallas" if _bincount_route(x, c) else "jnp",
                    "jnp_us": round(t_jnp * 1e6, 1),
                    "dispatched_us": round(t_disp * 1e6, 1),
                    "overhead_ratio": round(t_disp / t_jnp, 3),
                }
            )
    # --- qsketch_compact: sweep row count across the 2**10..2**15 window
    for cap in (256, 1024, 8192, 1 << 15):
        n = cap * 2
        rows = np.zeros((n, 3), np.float32)
        rows[:, 0] = 1.0
        rows[:, 1] = rng.randint(0, 100_000, n)
        rows[:, 2] = rng.randint(0, 2, n)
        rows = jnp.asarray(rows)
        t_disp = best_of(lambda r: ops.qsketch_compact_dispatch(r, cap), rows, reps=3, inner=2)
        t_jnp = best_of(lambda r: _compact_rows_jnp(r, cap), rows, reps=3, inner=2)
        cells.append(
            {
                "op": "qsketch_compact",
                "n": n,
                "segments": cap,
                "tpu_route": "pallas" if _qsketch_route(rows, cap) else "jnp",
                "jnp_us": round(t_jnp * 1e6, 1),
                "dispatched_us": round(t_disp * 1e6, 1),
                "overhead_ratio": round(t_disp / t_jnp, 3),
            }
        )

    worst = max(c["overhead_ratio"] for c in cells)
    print(
        json.dumps(
            {
                "metric": "ops_route_floor_ab",
                "value": round(worst, 3),
                "unit": "ratio",
                "backend": jax.default_backend(),
                "cells": cells,
            }
        )
    )


def bench_telemetry() -> None:
    """Micro-bench for the telemetry zero-overhead-when-disabled contract:
    per-call wall cost of ``Metric.update`` with the recorder disabled vs
    enabled. The disabled path must be indistinguishable from no telemetry
    at all (its only cost is one bool check, no event allocation); the
    enabled figure is the price of turning collection on."""
    import jax.numpy as jnp

    from metrics_tpu.aggregation import SumMetric
    from metrics_tpu.observability import get_recorder

    m = SumMetric()
    x = jnp.asarray(1.0)
    m.update(x)  # warm caches / first dispatch
    rec = get_recorder()
    n = 3000

    def time_updates() -> float:
        t0 = time.perf_counter()
        for _ in range(n):
            m.update(x)
        return (time.perf_counter() - t0) / n * 1e9

    was_enabled = rec.enabled
    rec.disable()
    disabled_ns = time_updates()
    rec.enable()
    enabled_ns = time_updates()
    rec.disable()
    # drop the synthetic events so an env-driven artifact isn't flooded
    rec.reset()
    if was_enabled:
        rec.enable()

    # pin the `_coerce_foreign` all-native fast path (ISSUE 4 satellite):
    # jax-array inputs must pass the update()-boundary coercion with a few
    # isinstance checks, no recursion, no allocation
    from metrics_tpu.core.metric import _coerce_foreign

    native_args = (x, x)
    t0 = time.perf_counter()
    for _ in range(n):
        _coerce_foreign(native_args)
    coerce_ns = (time.perf_counter() - t0) / n * 1e9

    # telemetry-overhead regression gate (ISSUE 11 satellite): fused-update
    # throughput with recorder + windowed time-series ON vs OFF. The live
    # health layer's whole enablement story is "affordable when on, one bool
    # check when off" — the ratio (ON/OFF throughput, higher is better) is
    # AUX-gated vs BENCH_r11.json so a regression in the enabled feed path
    # (or a leak of cost into the disabled path, caught by the ns/call wall
    # value above) fails CI rather than silently taxing every serving loop.
    from metrics_tpu import MeanSquaredError, MetricCollection
    from metrics_tpu.aggregation import MeanMetric

    col = MetricCollection({"mse": MeanSquaredError(), "mean": MeanMetric()})
    col.compile_update()
    rng = np.random.default_rng(0)
    preds = jnp.asarray(rng.random(256, dtype=np.float32))
    target = jnp.asarray(rng.random(256, dtype=np.float32))
    col.update(preds, target)  # warm: compile + group discovery
    n_fused = 300

    def fused_updates_per_sec() -> float:
        best = 0.0
        for _ in range(3):  # min-of-3: this box's CPU steal is noisy
            t0 = time.perf_counter()
            for _ in range(n_fused):
                col.update(preds, target)
            best = max(best, n_fused / (time.perf_counter() - t0))
        return best

    rec.disable()
    off_ups = fused_updates_per_sec()
    rec.enable()
    rec.attach_timeseries(bucket_seconds=1.0, n_buckets=60, sketch_capacity=128)
    col.update(preds, target)  # warm the series get-or-create path
    on_ups = fused_updates_per_sec()
    rec.disable()
    rec.detach_timeseries()
    rec.reset()
    if was_enabled:
        rec.enable()

    print(
        json.dumps(
            {
                "metric": "telemetry_disabled_update_overhead",
                "value": round(disabled_ns, 1),
                "unit": "ns/call",
                "enabled_ns_per_call": round(enabled_ns, 1),
                "coerce_fastpath_ns_per_call": round(coerce_ns, 1),
                "fused_telemetry_on_ratio": round(on_ups / off_ups, 4),
                "fused_updates_per_sec_off": round(off_ups, 1),
                "fused_updates_per_sec_on": round(on_ups, 1),
            }
        )
    )


def bench_reads() -> None:
    """Read-plane bench (ISSUE 16 satellite): subset-read throughput of a
    ``SlicedMetric`` at S=100k while a background thread keeps the async
    ingest queue busy — the serving regime the read telemetry instruments.

    Gated figures ride the committed BENCH_r17.json anchor:

    * ``read_event_overhead_ratio`` (AUX, higher is better) — reads/sec
      with the recorder + windowed time-series ON divided by reads/sec with
      the recorder OFF, measured with ingest paused so the ratio isolates
      the per-read tax (typed ``read`` event + freshness stamp) instead of
      re-measuring the ingest-side telemetry price other gates bound.
    * ``freshness_stamp_exact`` (BOOL) — inject a known-age stream: ingest
      at a recorded wall time, sleep a known delta, take the collection's
      :meth:`freshness` stamp, and record a stamped probe read. The
      event's ``staleness_s`` must land within ONE telemetry bucket
      (``bucket_seconds=1.0``) of the ground-truth age, proving the stamp
      is threaded causally (ingest wall clock -> stamp -> read event),
      not re-derived from queue-depth heuristics.
    * the headline reads/sec value itself (instrumented side).
    * ``incremental_vs_full`` (AUX, higher is better) — median cold full
      fold wall time over median incremental ``compute(slice_ids=)`` wall
      time on lockstep S=100k twins with <=0.5% of slices dirtied between
      reads: the dirty-fold + per-slice-cache win of the incremental read
      plane (ISSUE 17 floor: >= 5x).
    * ``incremental_read_bit_exact`` (BOOL) — every incremental subset read
      in that loop byte-equal to the cold full fold's values at the same
      ids; the plane's exactness contract, gated alongside its speed.
    """
    import threading

    import jax
    import jax.numpy as jnp

    from metrics_tpu import MetricCollection
    from metrics_tpu.observability import get_recorder
    from metrics_tpu.regression import MeanSquaredError
    from metrics_tpu.sliced import SlicedMetric

    rng = np.random.RandomState(16)
    S = 100_000
    batch = 4096

    col = MetricCollection({"m": SlicedMetric(MeanSquaredError(), num_slices=S)})
    ids = jnp.asarray(rng.randint(0, S, batch))
    preds = jnp.asarray(rng.randint(0, 8, batch).astype(np.float32))
    target = jnp.asarray(rng.randint(0, 8, batch).astype(np.float32))
    col.update(ids, preds, target)  # discovery
    handle = col.compile_update_async(queue_depth=2, policy="drop")
    handle.update_async(ids, preds, target)
    handle.flush()

    sliced = col["m"]
    query = jnp.asarray(rng.randint(0, S, 256))
    jax.block_until_ready(sliced.compute(slice_ids=query))  # warm the subset path

    rec = get_recorder()
    was_enabled = rec.enabled

    # background ingest: keep the async queue non-empty for the whole
    # measured window so reads race real in-flight writes (the regime the
    # freshness plane exists for), throttled so the 2-vCPU box's reader
    # thread still gets scheduled
    stop = threading.Event()

    def ingest() -> None:
        while not stop.is_set():
            handle.update_async(ids, preds, target)
            time.sleep(0.002)

    n_reads = 150

    def reads_per_sec() -> float:
        t0 = time.perf_counter()
        for _ in range(n_reads):
            jax.block_until_ready(sliced.compute(slice_ids=query))
        return n_reads / (time.perf_counter() - t0)

    worker = threading.Thread(target=ingest, daemon=True)
    worker.start()
    try:
        rec.enable()
        rec.attach_timeseries(bucket_seconds=1.0, n_buckets=60, sketch_capacity=128)
        jax.block_until_ready(sliced.compute(slice_ids=query))  # warm series path
        on_rps = max(reads_per_sec() for _ in range(3))  # headline: under ingest
    finally:
        stop.set()
        worker.join(timeout=10)
    handle.flush()

    # the overhead ratio A/B times reads with the ingest WORKER paused and
    # an untimed synchronous update dirtying slices before every timed
    # read. Two reasons: (a) with the recorder on the worker's own ingest
    # telemetry also grows, so an under-ingest off-side would race a
    # cheaper worker and the ratio would conflate the ingest-side
    # telemetry price (gated by fused_telemetry_on_ratio and the async
    # bench) with the read-event tax this anchor bounds; (b) without any
    # writes the reads collapse to pure cache hits — the cheapest read the
    # incremental plane can serve — and the ratio would gate the tax
    # against an unrealistically tiny denominator instead of the real
    # dirty-fold read a serving loop pays between ingest batches.
    n_ab = 60

    def median_read_s() -> float:
        # per-read MEDIAN, not the window total: the attached time-series
        # rotates its buckets about once a second, and one rotation's
        # sketch compaction (several ms of host work) landing inside a
        # ~40ms timed window would swing the whole ratio — it's periodic
        # maintenance amortized across thousands of reads, not the
        # per-read tax this anchor bounds
        ts = []
        for _ in range(n_ab):
            sliced.update(ids, preds, target)  # untimed: re-dirty the slices
            rec.tick()  # untimed: fold pending telemetry so bucket
            # compaction never lands inside a timed read — the same call a
            # latency-sensitive serving loop makes between probe reads
            t0 = time.perf_counter()
            jax.block_until_ready(sliced.compute(slice_ids=query))
            ts.append(time.perf_counter() - t0)
        return float(np.median(ts))

    off_t = on_t = float("inf")
    for _ in range(3):
        rec.disable()
        off_t = min(off_t, median_read_s())
        rec.enable()
        on_t = min(on_t, median_read_s())
    off_rps = 1.0 / off_t
    on_rps_solo = 1.0 / on_t

    # --- freshness exactness on an injected known-age stream (recorder ON) ---
    probe_col = MetricCollection({"mse": MeanSquaredError()})
    t_ingest = time.time()
    probe_col.update(preds, target)
    time.sleep(0.25)  # the known age
    stamp = probe_col.freshness()
    rec.record_read("probe", duration_s=0.0, freshness=stamp)
    probe_events = [
        e for e in rec.events() if e.get("type") == "read" and e.get("kind") == "probe"
    ]
    measured = float(probe_events[-1].get("staleness_s", float("nan"))) if probe_events else float("nan")
    truth = time.time() - t_ingest
    exact = bool(probe_events) and abs(measured - truth) <= 1.0  # one bucket

    handle.close()
    rec.disable()
    rec.detach_timeseries()
    rec.reset()

    # --- incremental read plane (ISSUE 17): dirty-slice subset reads vs the
    # cold full fold the pre-plane API required for the same answer ---
    # lockstep twins at S=100k, each step dirtying <=512 distinct slices
    # (<=0.5%); the incremental side serves `compute(slice_ids=)` from the
    # dirty fold + per-slice value cache, the cold side is degraded via
    # `_mark_state_written()` (all-dirty) before every full `compute()`.
    # Medians, not means: a bucket-transition compile lands in exactly one
    # iteration and would otherwise dominate the incremental side.
    inc = SlicedMetric(MeanSquaredError(), num_slices=S)
    full = SlicedMetric(MeanSquaredError(), num_slices=S)
    for m in (inc, full):
        m.update(ids, preds, target)
    jax.block_until_ready(jax.tree_util.tree_leaves(inc.compute(slice_ids=query)))
    jax.block_until_ready(jnp.asarray(full.compute()))  # warm both programs
    t_inc: list = []
    t_full: list = []
    bit_exact = True
    host_query = np.asarray(query)
    for i in range(30):
        step_ids = jnp.asarray(rng.randint(0, S, batch))
        inc.update(step_ids, preds, target)
        full.update(step_ids, preds, target)
        t0 = time.perf_counter()
        v_inc = inc.compute(slice_ids=query)
        jax.block_until_ready(jax.tree_util.tree_leaves(v_inc))
        t_inc.append(time.perf_counter() - t0)
        full._mark_state_written()
        t0 = time.perf_counter()
        v_full = full.compute()
        jax.block_until_ready(jnp.asarray(v_full))
        t_full.append(time.perf_counter() - t0)
        bit_exact = bit_exact and (
            np.asarray(v_inc).tobytes() == np.asarray(v_full)[host_query].tobytes()
        )
    inc_ms = float(np.median(t_inc) * 1e3)
    full_ms = float(np.median(t_full) * 1e3)

    if was_enabled:
        rec.enable()

    print(
        json.dumps(
            {
                "metric": "read_plane_throughput",
                "value": round(on_rps, 1),
                "unit": "reads/sec",
                "num_slices": S,
                "reads_per_sec_off": round(off_rps, 1),
                "read_event_overhead_ratio": round(on_rps_solo / off_rps, 4),
                "freshness_stamp_exact": exact,
                "freshness_measured_s": round(measured, 3) if measured == measured else None,
                "freshness_truth_s": round(truth, 3),
                "incremental_vs_full": round(full_ms / inc_ms, 2),
                "incremental_read_ms": round(inc_ms, 3),
                "full_fold_ms": round(full_ms, 3),
                "incremental_read_bit_exact": bit_exact,
                "note": "S=100k subset reads; headline reads/sec races"
                " concurrent async ingest, the overhead ratio A/B runs with"
                " ingest paused (instrumented/off reads per sec, higher is"
                " better); stamp exactness = staleness_s within one 1s"
                " telemetry bucket of the injected ground-truth age;"
                " incremental_vs_full is the median cold full fold over the"
                " median dirty-subset incremental read at <=0.5% dirty,"
                " gated bit-exact against the full fold's values",
            }
        )
    )


def bench_memory() -> None:
    """Memory-observatory bench (ISSUE 18): the device-memory plane's cost
    and its accounting honesty at serving scale.

    Gated figures ride the committed BENCH_r18.json anchor:

    * ``memory_plane_on_ratio`` (AUX, higher is better) — S=100k sliced
      async ingest throughput with the memory plane armed (per-update
      boundary hooks + observatory polls at the serving probe cadence)
      over throughput with the plane disarmed (boundary hook stubbed to a
      no-op, no polls). BOTH sides run with the recorder + windowed
      time-series enabled, so the ratio isolates the plane's marginal tax
      instead of re-measuring the baseline telemetry price other anchors
      gate (fused_telemetry_on_ratio, read_event_overhead_ratio). The
      acceptance ceiling is a <=5% tax, i.e. a 0.95 floor on the ratio.
      (The disabled-telemetry hot path pays exactly one bool check per
      boundary — that contract is unit-tested, not benched.)
    * ``bytes_per_tenant`` (AUX, lower is better) — the ledger's sliced
      state bytes divided across tenants; the figure MemoryBudget gates.
    * ``ledger_matches_backend`` (BOOL) — where the backend reports
      ``memory_stats()``, the unaccounted residue (bytes_in_use - ledger -
      cache planes) must be non-negative within allocator slack: the
      ledger never claims MORE live state than the device holds.
      Vacuously true on CPU (no backend stats; noted in the record).
    * ``unaccounted_non_growing`` (BOOL) — the residue after each of 3
      update/compute/reset cycles stays within slack of the post-warmup
      baseline: reset returns the process to its accounting baseline
      instead of leaking per-epoch state the ledger cannot see.
    """
    import jax
    import jax.numpy as jnp

    from metrics_tpu import MetricCollection
    from metrics_tpu.observability import MemoryObservatory, get_recorder
    from metrics_tpu.observability.memory import backend_memory_stats
    from metrics_tpu.regression import MeanSquaredError
    from metrics_tpu.sliced import SlicedMetric

    rng = np.random.RandomState(18)
    S = 100_000
    batch = 4096
    # ~0.8s of enqueue+drain per timed window: long enough that the 4 Hz
    # observatory poll amortizes the way it does in a serving loop, and a
    # single scheduler stall cannot swing the ratio double digits
    steps = 600

    col = MetricCollection({"m": SlicedMetric(MeanSquaredError(), num_slices=S)})
    ids = jnp.asarray(rng.randint(0, S, batch))
    preds = jnp.asarray(rng.rand(batch).astype(np.float32))
    target = jnp.asarray(rng.rand(batch).astype(np.float32))
    col.update(ids, preds, target)  # discovery
    handle = col.compile_update_async(queue_depth=2)
    handle.update_async(ids, preds, target)
    handle.flush()

    rec = get_recorder()
    was_enabled = rec.enabled
    rec.reset()
    rec.enable()
    rec.attach_timeseries(bucket_seconds=1.0, n_buckets=60, sketch_capacity=128)
    obs = MemoryObservatory(recorder=rec)
    obs.observe()  # warm the poll path (first /proc read, plane callbacks)

    def updates_per_sec(armed: bool) -> float:
        # timed window = n enqueues + the drain; the observatory poll rides
        # INSIDE it at the serving probe cadence because the ledger walk +
        # plane inventory + RSS read are the plane's steady-state cost
        last_poll = time.perf_counter()
        t0 = time.perf_counter()
        for _ in range(steps):
            handle.update_async(ids, preds, target)
            if armed and time.perf_counter() - last_poll >= 0.25:
                obs.observe()
                last_poll = time.perf_counter()
        handle.flush()
        return steps / (time.perf_counter() - t0)

    # alternating best-of-3 per side, same clock-drift hygiene as the other
    # A/B benches; the disarmed side keeps the recorder + time-series ON and
    # stubs ONLY the memory boundary hook, so the ratio is the plane's
    # marginal price, not the whole telemetry stack's
    real_boundary = rec.record_memory_boundary
    off_ups = on_ups = 0.0
    for _ in range(3):
        rec.record_memory_boundary = lambda *a, **k: None
        try:
            off_ups = max(off_ups, updates_per_sec(False))
        finally:
            rec.record_memory_boundary = real_boundary
        on_ups = max(on_ups, updates_per_sec(True))

    # --- accounting honesty (telemetry on) ---
    report = obs.observe()
    stats = backend_memory_stats()
    slack = 48 * 1024 * 1024  # allocator + host-runtime slop
    if stats and report["device_bytes_in_use"] is not None:
        ledger_matches_backend = report["unaccounted_bytes"] >= -slack
        backend_note = "backend memory_stats"
    else:
        ledger_matches_backend = True
        backend_note = "no backend memory_stats on this platform: vacuously true"

    # 3 full epochs: ingest, publish, reset — the residue vs the post-warmup
    # baseline is the leak signal; reset must return to baseline
    base_unaccounted = report["unaccounted_bytes"]
    base_ledger = int(report["total_bytes"])
    deltas = []
    ledger_cycle_bytes = []
    for _ in range(3):
        for _ in range(20):
            handle.update_async(ids, preds, target)
        handle.flush()
        col.compute()
        col.reset()
        handle = col.compile_update_async(queue_depth=2)  # warm cache reuse
        cycle = obs.observe()
        ledger_cycle_bytes.append(int(cycle["total_bytes"]))
        if cycle["unaccounted_bytes"] is None or base_unaccounted is None:
            deltas.append(None)
        else:
            deltas.append(int(cycle["unaccounted_bytes"]) - int(base_unaccounted))
    unaccounted_non_growing = all(d is None or d <= slack for d in deltas)

    handle.close()
    rec.disable()
    rec.detach_timeseries()
    rec.reset()
    if was_enabled:
        rec.enable()

    print(
        json.dumps(
            {
                "metric": "memory_plane_throughput",
                "value": round(on_ups, 1),
                "unit": "updates/sec",
                "num_slices": S,
                "updates_per_sec_off": round(off_ups, 1),
                "memory_plane_on_ratio": round(on_ups / off_ups, 4),
                "bytes_per_tenant": round(float(report["bytes_per_tenant"]), 2),
                "ledger_bytes": base_ledger,
                "ledger_cycle_bytes": ledger_cycle_bytes,
                "cache_plane_bytes": int(report["cache_plane_bytes"]),
                "memory_source": report["source"],
                "ledger_matches_backend": bool(ledger_matches_backend),
                "backend_note": backend_note,
                "unaccounted_non_growing": bool(unaccounted_non_growing),
                "unaccounted_cycle_deltas": deltas,
                "note": "S=100k sliced async ingest; on_ratio = armed"
                " (boundary hooks + observatory polls at probe cadence) over"
                " disarmed (hook stubbed, no polls) with the recorder +"
                " time-series ON both sides, floor 0.95 == the <=5% tax"
                " ceiling; honesty = unaccounted residue (in_use - ledger -"
                " planes) non-negative vs the backend and non-growing across"
                " 3 update/compute/reset cycles within 48MB slack",
            }
        )
    )


def bench_image_detection() -> None:
    """Streaming image/detection state bench (ISSUE 19): the two biggest
    eager families — FID/IS moment states and the fixed-capacity mAP
    table — measured at the serving boundary they were rebuilt for.

    Gated figures ride the committed BENCH_r19.json anchor:

    * ``map_fused_vs_eager`` (AUX, higher is better) — end-to-end wall
      (raw per-image numpy stream -> computed result dict) for the eager
      list-state path over the fused table path on N=2048 images in
      batches of 64. Both sides start from the SAME raw host data: the
      eager side pays per-image jnp dict construction + the per-image
      python update loop, the fused side pays host padding + one bucketed
      device dispatch per batch. The acceptance floor is 5x.
    * ``fid_state_bytes_frac`` (AUX, lower is better) — the streaming FID
      metric's full state footprint at feature_dim=2048 over the cat-state
      bytes of a 10^5-feature stream (1e5 x 2048 float32). The moment
      state is O(d^2) however long the stream; ceiling 0.05.
    * ``newton_schulz_abs_err`` (AUX, lower is better) — |device f32
      Newton-Schulz trace-sqrtm - host f64 eigh oracle| on a seeded
      covariance pair from unit-scale features.
    * ``states_bit_identical`` (BOOL) — the fused run's table and
      images_seen leaves are bit-identical to the eager list-API run's.
    * ``map_window_bit_exact`` (BOOL) — streaming compute() equals the
      ``exact=True`` list path on every result key for an in-window
      substream.
    * ``fid_identity_bit_exact`` (BOOL) — streaming FID moment leaves are
      bit-identical to float64 oracle sums cast to f32 on dyadic features
      (exactly representable sums: any deviation is an update-path bug).
    """
    import jax
    import jax.numpy as jnp

    from metrics_tpu import MetricCollection
    from metrics_tpu.detection import MeanAveragePrecision
    from metrics_tpu.image.fid import FrechetInceptionDistance, _trace_sqrtm_product
    from metrics_tpu.ops.sqrtm import trace_sqrtm_dispatch

    rng = np.random.RandomState(19)
    N, B, D, G = 2048, 64, 8, 8
    kw = dict(max_images=4096, det_slots=D, gt_slots=G, max_detection_thresholds=[1, D])

    # grid-jittered boxes so detections genuinely overlap ground truths and
    # the PR grids are non-trivial (same generator family as the table tests)
    def _boxes(k):
        xy = rng.randint(0, 4, (k, 2)).astype(np.float64) * 6.0 + rng.rand(k, 2)
        wh = 4.0 + rng.rand(k, 2) * 4.0
        return np.concatenate([xy, xy + wh], axis=1).astype(np.float32)

    images = []
    for _ in range(N):
        nd, ng = int(rng.randint(0, D + 1)), int(rng.randint(1, G + 1))
        images.append(
            (
                dict(boxes=_boxes(nd), scores=rng.rand(nd).astype(np.float32), labels=rng.randint(0, 3, nd).astype(np.int32)),
                dict(boxes=_boxes(ng), labels=rng.randint(0, 3, ng).astype(np.int32)),
            )
        )

    def pad_batch(chunk):
        n = len(chunk)
        pb = np.zeros((n, D, 4), np.float32)
        ps = np.zeros((n, D), np.float32)
        pl = np.zeros((n, D), np.int32)
        pn = np.zeros((n,), np.int32)
        gb = np.zeros((n, G, 4), np.float32)
        gl = np.zeros((n, G), np.int32)
        gn = np.zeros((n,), np.int32)
        for i, (p, t) in enumerate(chunk):
            nd, ng = len(p["scores"]), len(t["labels"])
            pb[i, :nd], ps[i, :nd], pl[i, :nd], pn[i] = p["boxes"], p["scores"], p["labels"], nd
            gb[i, :ng], gl[i, :ng], gn[i] = t["boxes"], t["labels"], ng
        return (
            dict(boxes=jnp.asarray(pb), scores=jnp.asarray(ps), labels=jnp.asarray(pl), n=jnp.asarray(pn)),
            dict(boxes=jnp.asarray(gb), labels=jnp.asarray(gl), n=jnp.asarray(gn)),
        )

    def eager_pass():
        m = MeanAveragePrecision(**kw)
        t0 = time.perf_counter()
        for lo in range(0, N, B):
            chunk = images[lo : lo + B]
            m.update(
                [{k: jnp.asarray(v) for k, v in p.items()} for p, _ in chunk],
                [{k: jnp.asarray(v) for k, v in t.items()} for _, t in chunk],
            )
        jax.block_until_ready(m.table)
        t_up = time.perf_counter() - t0
        res = m.compute()
        return m, res, t_up, time.perf_counter() - t0

    # fused: warm pass compiles the single bucketed executable, reset clears
    # the states but not the shape-keyed compile cache, timed pass measures
    # the steady-state ingest the serving loop actually runs
    col = MetricCollection([MeanAveragePrecision(**kw)])
    handle = col.compile_update(buckets=[B])

    def fused_pass():
        t0 = time.perf_counter()
        for lo in range(0, N, B):
            col.update(*pad_batch(images[lo : lo + B]))
        fm = col["MeanAveragePrecision"]
        jax.block_until_ready(fm.table)
        t_up = time.perf_counter() - t0
        res = col.compute()
        return fm, res, t_up, time.perf_counter() - t0

    fused_pass()
    col.reset()
    # eager warm pass too: the per-image jnp ops hit the global jit caches,
    # and both sides deserve the same steady-state treatment
    eager_pass()
    fm, fused_res, fused_up, fused_tot = fused_pass()
    em, eager_res, eager_up, eager_tot = eager_pass()

    states_bit_identical = bool(jnp.array_equal(fm.table, em.table)) and bool(
        jnp.array_equal(fm.images_seen, em.images_seen)
    )
    results_equal = set(fused_res) == set(eager_res) and all(
        np.array_equal(np.asarray(fused_res[k]).ravel(), np.asarray(eager_res[k]).ravel())
        for k in eager_res
    )
    states_bit_identical = states_bit_identical and results_equal

    # in-window streaming-vs-exact parity on a substream (the full exact run
    # would re-measure the eager price, not the contract)
    sub = images[:256]
    win = MeanAveragePrecision(**kw)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        ex = MeanAveragePrecision(exact=True, **kw)
    for m in (win, ex):
        m.update(
            [{k: jnp.asarray(v) for k, v in p.items()} for p, _ in sub],
            [{k: jnp.asarray(v) for k, v in t.items()} for _, t in sub],
        )
    wr, xr = win.compute(), ex.compute()
    map_window_bit_exact = set(wr) == set(xr) and all(
        np.array_equal(np.asarray(wr[k]).ravel(), np.asarray(xr[k]).ravel()) for k in xr
    )

    # --- FID: state footprint + moment exactness + sqrtm oracle ---
    d_full = 2048
    fid = FrechetInceptionDistance(feature=lambda x: x, feature_dim=d_full)
    fid_streaming_bytes = sum(fid.state_footprint().values())
    fid_cat_bytes = 100_000 * d_full * 4  # 1e5 extracted float32 features
    fid_state_bytes_frac = fid_streaming_bytes / fid_cat_bytes

    # dyadic features: every moment sum is exactly representable in f32, so
    # the streaming leaves must be BIT-identical to the f64 oracle sums
    feats = rng.randint(0, 16, (64, 8)).astype(np.float64) / 2.0
    small = FrechetInceptionDistance(feature=lambda x: x, feature_dim=8)
    for i, (lo, hi) in enumerate(((0, 24), (24, 40), (40, 64))):
        small.update(jnp.asarray(feats[lo:hi], jnp.float32), real=i % 2 == 0)
    merged_sum = np.asarray(small.real_feat_sum) + np.asarray(small.fake_feat_sum)
    merged_outer = np.asarray(small.real_outer_sum) + np.asarray(small.fake_outer_sum)
    fid_identity_bit_exact = (
        np.array_equal(merged_sum, feats.sum(0).astype(np.float32))
        and np.array_equal(merged_outer, (feats.T @ feats).astype(np.float32))
        and float(np.asarray(small.real_count) + np.asarray(small.fake_count)) == 64.0
    )

    # seeded covariance pair from unit-scale features: device f32
    # Newton-Schulz trace-sqrtm vs the host f64 eigh oracle
    d_ns, n_ns = 256, 512
    fa = rng.randn(n_ns, d_ns)
    fb = rng.randn(n_ns, d_ns) * 0.9 + 0.1
    cov_a = np.cov(fa, rowvar=False)
    cov_b = np.cov(fb, rowvar=False)
    ns = float(trace_sqrtm_dispatch(jnp.asarray(cov_a, jnp.float32), jnp.asarray(cov_b, jnp.float32)))
    oracle = _trace_sqrtm_product(cov_a, cov_b)
    newton_schulz_abs_err = abs(ns - oracle)

    print(
        json.dumps(
            {
                "metric": "image_detection_throughput",
                "value": round(N / fused_tot, 1),
                "unit": "images/sec",
                "images": N,
                "batch": B,
                "eager_update_s": round(eager_up, 4),
                "eager_total_s": round(eager_tot, 4),
                "fused_update_s": round(fused_up, 4),
                "fused_total_s": round(fused_tot, 4),
                "map_fused_vs_eager": round(eager_tot / fused_tot, 2),
                "map_update_ratio": round(eager_up / fused_up, 2),
                "fused_compiles": len(handle._cache),
                "fid_streaming_bytes": int(fid_streaming_bytes),
                "fid_cat_bytes": int(fid_cat_bytes),
                "fid_state_bytes_frac": round(fid_state_bytes_frac, 5),
                "newton_schulz_abs_err": round(newton_schulz_abs_err, 6),
                "newton_schulz_trace": round(ns, 4),
                "oracle_trace": round(oracle, 4),
                "states_bit_identical": states_bit_identical,
                "map_window_bit_exact": bool(map_window_bit_exact),
                "fid_identity_bit_exact": bool(fid_identity_bit_exact),
                "note": "N=2048 images, det/gt slots 8, batch 64, one fused"
                " bucket; ratio = eager list-state end-to-end wall (per-image"
                " jnp dicts + python update loop + compute) over fused table"
                " wall (host pad + single bucketed dispatch + compute), both"
                " from the same raw numpy stream after a warm pass, floor 5x;"
                " fid frac = full streaming metric footprint at d=2048 over a"
                " 1e5-feature cat state, ceiling 0.05; parity bits are"
                " fused-vs-eager state/result equality, in-window streaming-"
                "vs-exact result equality, and dyadic-feature moment bit-"
                "exactness",
            }
        )
    )


SUBCOMMANDS = {
    "map": bench_map,
    "retrieval": bench_retrieval,
    "image": bench_image,
    "sync": bench_sync,
    "inference": bench_inference,
    "telemetry": bench_telemetry,
    "fused": bench_fused,
    "async": bench_async,
    "sliced": bench_sliced,
    "sketch": bench_sketch,
    "windowed": bench_windowed,
    "collector": bench_collector,
    "ops": bench_ops,
    "ops_ab": bench_ops_ab,
    "reads": bench_reads,
    "memory": bench_memory,
    "image_detection": bench_image_detection,
}


def _check_against_baseline(records, baseline_path) -> None:
    """The ``--baseline`` flag: diff this run's emitted records against a
    committed bench artifact via scripts/check_cost_regression.py and emit
    the verdict as one JSON line. Report-only here — the standalone script
    is the exiting CI gate — so a perf regression cannot mask the bench
    numbers themselves."""
    import importlib.util

    script = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "scripts", "check_cost_regression.py"
    )
    spec = importlib.util.spec_from_file_location("check_cost_regression", script)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    current = {r["metric"]: r for r in records if r.get("metric")}
    regressions, _ = mod.compare(current, mod.load_records(baseline_path))
    print(
        json.dumps(
            {
                "metric": "cost_regression_check",
                "ok": not regressions,
                "baseline": baseline_path,
                "regressions": regressions,
            }
        ),
        flush=True,
    )


def main() -> None:
    argv = sys.argv[1:]
    baseline_path = None
    rest = []
    for arg in argv:
        if arg == "--cost-analysis":
            # env channel: the per-config subprocesses must inherit the flag
            os.environ[COST_ENV_VAR] = "1"
        elif arg.startswith("--baseline="):
            baseline_path = arg.split("=", 1)[1]
        else:
            rest.append(arg)
    argv = rest
    has_flag = any(arg.split("=", 1)[0] == "--telemetry" for arg in argv)
    telemetry_active = has_flag or bool(os.environ.get("METRICS_TPU_TELEMETRY"))
    if telemetry_active:
        # only a telemetry run pays the metrics_tpu import in the driver
        # parent; the plain full-emission driver stays stdlib-only until its
        # subprocesses do the work
        from metrics_tpu.observability import activate_telemetry, maybe_export_env

        _, argv = activate_telemetry(argv, default_path="BENCH_telemetry.jsonl")

    if argv:
        if baseline_path:
            # the baseline diff needs the full emitted record set, which
            # only the no-args full-emission run collects; silently
            # skipping the check would let CI believe the gate ran
            raise SystemExit(
                "--baseline requires the full bench run (no subcommand);"
                " for a single config, diff artifacts with"
                " scripts/check_cost_regression.py directly"
            )
        fn = SUBCOMMANDS.get(argv[0])
        if fn is None:
            raise SystemExit(f"unknown bench subcommand {argv[0]!r}; one of {sorted(SUBCOMMANDS)}")
        fn()
        if telemetry_active:
            maybe_export_env()
        return

    # No args (the driver's invocation): emit EVERY measured BASELINE config
    # as its own JSON line so per-round regressions in any path are visible,
    # with the headline config LAST (the driver parses the final line). Each
    # config runs in a subprocess: bench_sync must force an 8-virtual-device
    # CPU platform, which would poison the TPU benches if run in-process, and
    # a crash in one config must not take down the rest.
    import subprocess

    records = []  # every emitted JSON object, for the --baseline check
    for name in ("map", "retrieval", "image", "inference", "sync", "fused", "async", "sliced", "sketch", "windowed", "telemetry", "ops", "ops_ab", "reads", "memory", "image_detection"):
        try:
            out = subprocess.run(
                [sys.executable, os.path.abspath(__file__), name],
                capture_output=True,
                text=True,
                timeout=1200,
            )
            emitted = 0
            for line in out.stdout.splitlines():
                if line.startswith("{"):
                    print(line, flush=True)
                    emitted += 1
                    try:
                        records.append(json.loads(line))
                    except json.JSONDecodeError:
                        pass
            # a crashed or silent config must surface as an error line, not
            # silently vanish from the round record
            if out.returncode != 0 or not emitted:
                print(
                    json.dumps(
                        {
                            "metric": f"bench_{name}",
                            "error": f"rc={out.returncode}: {out.stderr.strip()[-200:]}",
                        }
                    ),
                    flush=True,
                )
        except Exception as err:  # noqa: BLE001 — a failed config is reported, not fatal
            print(json.dumps({"metric": f"bench_{name}", "error": str(err)[:200]}), flush=True)

    tpu_sps, tpu_cost = bench_tpu()
    try:
        ref_sps = bench_reference()
    except Exception:
        ref_sps = None

    # the parent's own events (headline config) land in the same artifact the
    # per-config subprocesses appended to
    if telemetry_active:
        maybe_export_env()

    headline = _with_cost(
        {
            "metric": "imagenet1k_auroc_confmat_throughput",
            "value": round(tpu_sps, 1),
            "unit": "samples/sec",
            "vs_baseline": round(tpu_sps / ref_sps, 3) if ref_sps else None,
        },
        tpu_cost,
    )
    records.append(headline)

    # the regression verdict prints BEFORE the headline: the driver parses
    # the final stdout line as the headline metric
    if baseline_path:
        try:
            _check_against_baseline(records, baseline_path)
        except Exception as err:  # noqa: BLE001 — a broken baseline must not kill the bench
            print(json.dumps({"metric": "cost_regression_check", "error": str(err)[:200]}), flush=True)

    print(json.dumps(headline))


if __name__ == "__main__":
    main()
