"""Pairwise cosine similarity.

Behavior parity with /root/reference/torchmetrics/functional/pairwise/cosine.py:20-90.
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.pairwise.helpers import _check_input, _reduce_distance_matrix, _zero_diagonal

Array = jax.Array


def _pairwise_cosine_similarity_update(
    x: Array, y: Optional[Array] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    x = x / jnp.linalg.norm(x, axis=1, keepdims=True)
    y = y / jnp.linalg.norm(y, axis=1, keepdims=True)
    distance = jnp.matmul(x, y.T, precision=jax.lax.Precision.HIGHEST)
    return _zero_diagonal(distance, zero_diagonal)


def pairwise_cosine_similarity(
    x: Array, y: Optional[Array] = None, reduction: Optional[str] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    """Pairwise cosine similarity between rows of x (and y).

    Example:
        >>> import jax.numpy as jnp
        >>> x = jnp.array([[2., 3.], [3., 5.], [5., 8.]])
        >>> y = jnp.array([[1., 0.], [2., 1.]])
        >>> pairwise_cosine_similarity(x, y)
        Array([[0.5547002 , 0.86824316],
               [0.5144958 , 0.84366155],
               [0.52999896, 0.85328186]], dtype=float32)
    """
    distance = _pairwise_cosine_similarity_update(x, y, zero_diagonal)
    return _reduce_distance_matrix(distance, reduction)
