"""Image gradients (dy, dx).

Behavior parity with /root/reference/torchmetrics/functional/image/gradients.py:20-85.
"""
from typing import Tuple

import jax
import jax.numpy as jnp

Array = jax.Array


def _image_gradients_validate(img: Array) -> None:
    if not isinstance(img, jnp.ndarray):
        raise TypeError(f"The `img` expects a value of <Array> type but got {type(img)}")
    if img.ndim != 4:
        raise RuntimeError(f"The `img` expects a 4D tensor but got {img.ndim}D tensor")


def _compute_image_gradients(img: Array) -> Tuple[Array, Array]:
    dy = img[..., 1:, :] - img[..., :-1, :]
    dx = img[..., :, 1:] - img[..., :, :-1]

    dy = jnp.pad(dy, ((0, 0), (0, 0), (0, 1), (0, 0)))
    dx = jnp.pad(dx, ((0, 0), (0, 0), (0, 0), (0, 1)))

    return dy, dx


def image_gradients(img: Array) -> Tuple[Array, Array]:
    """Computes (dy, dx) of an ``(N, C, H, W)`` image tensor.

    Example:
        >>> import jax.numpy as jnp
        >>> img = jnp.arange(16, dtype=jnp.float32).reshape(1, 1, 4, 4)
        >>> dy, dx = image_gradients(img)
        >>> dy[0, 0, :, :]
        Array([[4., 4., 4., 4.],
               [4., 4., 4., 4.],
               [4., 4., 4., 4.],
               [0., 0., 0., 0.]], dtype=float32)
    """
    _image_gradients_validate(img)
    return _compute_image_gradients(img)
