"""tracelint v2: interprocedural abstract interpreter over metric updates.

tracelint v1 rules are single-file and single-function; the framework's
central contract — "a metric whose update is pure and fixed-shape fuses
into the one-dispatch kernel" — is interprocedural: metric ``_update``
bodies immediately call into ``metrics_tpu/functional/`` kernels, which call
into ``metrics_tpu/utils/`` input formatters. This module resolves those
calls across files and runs an abstract interpretation that classifies
every metric class into one of three **verdicts**:

* ``fusible`` — the update provably stays on device with fixed shapes: every
  reachable operation is a jnp/lax op, a resolved in-package helper that is
  itself clean, a static builtin, or a method on a traced array. The fused
  path (``core/fused.py``) may skip its runtime ``eval_shape`` probe.
* ``unsafe`` — a definitive violation was found on an unconditional path,
  with a machine-derived **reason**:
  - ``cat-growth`` — unbounded list-state concatenation (``default=[]``
    states, ``self.<state>.append`` in update);
  - ``host-sync`` — a device->host round-trip (``float()``/``.item()``/
    ``np.*`` on a traced value) or Python control flow on traced data;
  - ``data-dependent-shape`` — an output shape that depends on data values
    (``jnp.unique``/``nonzero``, boolean-mask indexing, traced slice
    bounds, length-less ``bincount``).
* ``unknown`` — the analysis hit something it cannot bound (an unresolved
  call receiving traced values, a config-dependent state container, or an
  unsafe signal on a *conditional* path that a concrete config may never
  take). The runtime probe remains the authority.

The **value lattice** tracks, per local name: *taintedness* (does it carry a
traced array), *None-ness* (``none`` / ``notnone`` / ``maybe`` — used to
kill statically-dead ``if x is None`` branches, the idiom every input
formatter uses to gate its host-side fallbacks), and *bool-ness* (is it a
comparison result, i.e. a potential boolean mask). Function summaries
``(signals, return taint, return None-ness)`` are memoized per
``(function, argument binding)`` so the interprocedural walk stays linear.

Sanctioned host escapes are honored: any ``if`` mentioning the
``_is_concrete`` eager-only guard skips its guarded side, and the
``if not _is_concrete(...): raise`` idiom marks the remainder of the block
eager-only.

Everything here is stdlib-only (ast) — the CLI never imports jax.
"""
from __future__ import annotations

import ast
import pathlib
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterator, List, Optional, Sequence, Set, Tuple

from .engine import FileContext, PACKAGE_NAME, default_package_root

# ---------------------------------------------------------------------------
# verdict vocabulary (stable — serialized into the fusibility manifest)
# ---------------------------------------------------------------------------

VERDICT_FUSIBLE = "fusible"
VERDICT_UNSAFE = "unsafe"
VERDICT_UNKNOWN = "unknown"

REASON_CAT_GROWTH = "cat-growth"
REASON_HOST_SYNC = "host-sync"
REASON_DATA_SHAPE = "data-dependent-shape"

#: signal kinds an update scan can raise; "unknown" and "trace-raise" never
#: make a metric unsafe, they only block the fusible verdict ("trace-raise"
#: marks a reachable, UNCAUGHT `if not _is_concrete(...): raise` — an input
#: configuration that fails under tracing; a caller that wraps the call in
#: try/except has handled it, and the signal is dropped at that call site)
_SIGNAL_KINDS = (REASON_HOST_SYNC, REASON_DATA_SHAPE, REASON_CAT_GROWTH, "unknown", "trace-raise")

# None-ness lattice
_NONE = "none"
_NOT_NONE = "notnone"
_MAYBE = "maybe"

#: jnp/lax members whose OUTPUT shape depends on data values — poison for
#: the fixed-shape contract (jnp.where is handled separately: only its
#: single-argument form is dynamic)
_DATA_DEP_MEMBERS = {
    "unique",
    "unique_values",
    "unique_counts",
    "unique_all",
    "unique_inverse",
    "nonzero",
    "flatnonzero",
    "argwhere",
    "compress",
    "extract",
    "setdiff1d",
    "union1d",
    "intersect1d",
    "trim_zeros",
}

#: jnp members returning HOST values (dtype predicates and metadata) — their
#: results never taint, so `if jnp.issubdtype(x.dtype, ...)` stays static
_HOST_RESULT_MEMBERS = {
    "issubdtype",
    "result_type",
    "promote_types",
    "iinfo",
    "finfo",
    "dtype",
    "ndim",
    "shape",
    "size",
    "isdtype",
}

#: jnp members whose result is a boolean mask when fed traced data
_BOOLISH_MEMBERS = {
    "isnan",
    "isinf",
    "isfinite",
    "isneginf",
    "isposinf",
    "logical_and",
    "logical_or",
    "logical_not",
    "logical_xor",
    "greater",
    "greater_equal",
    "less",
    "less_equal",
    "equal",
    "not_equal",
    "isclose",
    "isin",
}

#: array-method names that force a host sync / dynamic shape
_HOST_SYNC_METHODS = {"item", "tolist", "block_until_ready", "__array__"}
_DATA_DEP_METHODS = {"nonzero"}

#: methods of the `.at[...]` functional-update namespace (pure traced
#: scatter ops; receiver taint is irrelevant)
_AT_UPDATE_METHODS = {
    "set", "add", "subtract", "multiply", "divide", "power", "max", "min", "get", "apply",
}

#: registry-dispatched array ops (metrics_tpu/ops/): the routing decision
#: is host-static — backend identity, the METRICS_TPU_NO_PALLAS env hatch,
#: and shape/dtype route predicates, all resolved in host Python at trace
#: time — and every backend lowers a pure fixed-shape array program, so a
#: dispatched call is modeled exactly like a jnp op: traced result, no
#: descent (the value validation inside the boundary is `_is_concrete`-
#: guarded, the same exemption pattern that function gets). Descending
#: instead would misread the host-side routing `if`s as trace-value
#: concretization and flip every bincount/scatter consumer to unsafe.
_DISPATCHED_OPS = {
    "bincount_dispatch",
    "segment_sum_dispatch",
    "segment_max_dispatch",
    "segment_min_dispatch",
    "qsketch_compact_dispatch",
    "row_topk_dispatch",
    "box_iou_dispatch",
}

#: builtins whose results are host/static values (superset of the rule-side
#: set: pure readers plus shape-free constructors)
_SAFE_HOST_BUILTINS = {
    "isinstance",
    "len",
    "getattr",
    "hasattr",
    "type",
    "range",
    "enumerate",
    "zip",
    "max",
    "min",
    "abs",
    "sum",
    "sorted",
    "reversed",
    "list",
    "tuple",
    "dict",
    "set",
    "str",
    "repr",
    "format",
    "print",
    "id",
    "round",
    "all",
    "any",
    "map",
    "filter",
    "super",
    "ValueError",
    "TypeError",
    "RuntimeError",
    "KeyError",
    "NotImplementedError",
}

_CAST_BUILTINS = {"float", "int", "bool", "complex"}

#: attributes that are static under tracing
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "weak_type", "sharding"}

#: resolution depth budget — deep enough for the longest real chain
#: (metric update -> functional kernel -> input formatter -> per-case
#: checker -> validator -> leaf predicate) with headroom
_DEPTH_BUDGET = 8


def _last_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _attr_chain(node: ast.AST) -> List[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return []


def _mentions_concrete_guard(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and _last_name(sub.func) == "_is_concrete":
            return True
    return False


def _is_not_concrete_test(node: ast.AST) -> bool:
    """``not _is_concrete(...)``-shaped test: the negated eager guard whose
    raising body makes the REST of the block eager-only."""
    return (
        isinstance(node, ast.UnaryOp)
        and isinstance(node.op, ast.Not)
        and _mentions_concrete_guard(node.operand)
    )


def _always_raises(stmts: Sequence[ast.stmt]) -> bool:
    """Every terminal path of ``stmts`` ends in raise/return (a guard body)."""
    if not stmts:
        return False
    last = stmts[-1]
    if isinstance(last, (ast.Raise, ast.Return)):
        return True
    if isinstance(last, ast.If) and last.orelse:
        return _always_raises(last.body) and _always_raises(last.orelse)
    return False


# ---------------------------------------------------------------------------
# signals and verdicts
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Signal:
    """One abstract-interpretation finding inside an update's call graph."""

    kind: str  # one of _SIGNAL_KINDS
    detail: str
    conditional: bool  # found under a host-config branch that may be dead
    line: int = 0


@dataclass(frozen=True)
class Verdict:
    """Static fusibility classification of one metric class."""

    status: str  # fusible | unsafe | unknown
    reason: Optional[str] = None  # unsafe reason (cat-growth | host-sync | data-dependent-shape)
    detail: Optional[str] = None  # human-readable context for the verdict

    def to_dict(self) -> Dict[str, Optional[str]]:
        return {"status": self.status, "reason": self.reason, "detail": self.detail}


def verdict_from_signals(signals: Sequence[Signal]) -> Verdict:
    """Definitive (unconditional) unsafe signals decide; anything weaker —
    conditional unsafety, unresolved calls, uncaught trace-time raises —
    degrades to ``unknown`` so the runtime probe stays the authority; a
    silent scan is ``fusible``."""
    for sig in signals:
        if sig.kind not in ("unknown", "trace-raise") and not sig.conditional:
            return Verdict(VERDICT_UNSAFE, sig.kind, sig.detail)
    if signals:
        first = signals[0]
        return Verdict(
            VERDICT_UNKNOWN,
            None,
            f"{first.kind}: {first.detail}" if first.kind != "unknown" else first.detail,
        )
    return Verdict(VERDICT_FUSIBLE)


# ---------------------------------------------------------------------------
# abstract values
# ---------------------------------------------------------------------------

@dataclass
class _Value:
    tainted: bool = False
    noneness: str = _MAYBE
    boolish: bool = False
    #: element-wise values when this abstracts a tuple (a canonicalizer's
    #: `(preds, target, mode)` return) — lets tuple unpacking keep a host
    #: element (the mode enum) untainted beside traced arrays
    elts: Optional[List["_Value"]] = None


_HOST = _Value(tainted=False, noneness=_NOT_NONE)


@dataclass
class _Env:
    """Per-function abstract store."""

    traced: Set[str] = field(default_factory=set)
    boolmask: Set[str] = field(default_factory=set)
    noneness: Dict[str, str] = field(default_factory=dict)
    states: Set[str] = field(default_factory=set)  # traced self.<attr> names
    list_states: Set[str] = field(default_factory=set)  # may-be-list self attrs

    def value_of(self, name: str) -> _Value:
        return _Value(
            tainted=name in self.traced,
            noneness=self.noneness.get(name, _MAYBE),
            boolish=name in self.boolmask,
        )

    def bind(self, name: str, value: _Value) -> None:
        if value.tainted:
            self.traced.add(name)
        else:
            self.traced.discard(name)
        if value.boolish:
            self.boolmask.add(name)
        else:
            self.boolmask.discard(name)
        self.noneness[name] = value.noneness

    def snapshot(self) -> "_Env":
        return _Env(
            traced=set(self.traced),
            boolmask=set(self.boolmask),
            noneness=dict(self.noneness),
            states=self.states,  # shared: never mutated during a scan
            list_states=self.list_states,
        )

    def absorb_branches(self, a: "_Env", b: "_Env") -> None:
        """Join two branch environments back into this one: taint unions
        (conservative), None-ness meets (agreement survives, disagreement
        decays to maybe) — so a binding in ONE branch can never mask the
        other branch's path (`num_classes = preds.shape[1]` in the float
        branch must not kill the label branch's None check)."""
        self.traced.clear()
        self.traced.update(a.traced | b.traced)
        self.boolmask.clear()
        self.boolmask.update(a.boolmask | b.boolmask)
        merged: Dict[str, str] = {}
        for key in set(a.noneness) | set(b.noneness):
            va = a.noneness.get(key, _MAYBE)
            vb = b.noneness.get(key, _MAYBE)
            merged[key] = va if va == vb else _MAYBE
        self.noneness.clear()
        self.noneness.update(merged)


# ---------------------------------------------------------------------------
# cross-file resolution
# ---------------------------------------------------------------------------

class Project:
    """Parse-once view of the package for cross-file symbol resolution.

    Modules are addressed package-relative (``functional/classification/
    accuracy.py``); ``from metrics_tpu.x.y import f`` (or the relative
    equivalent) resolves ``f`` to its def in ``x/y.py``, following one
    ``__init__.py`` re-export hop.
    """

    def __init__(self, root: Optional[pathlib.Path] = None) -> None:
        self.root = pathlib.Path(root) if root is not None else default_package_root()
        self._ctx_cache: Dict[str, Optional[FileContext]] = {}
        self._import_cache: Dict[int, Dict[str, Tuple[str, str]]] = {}
        self._summary_cache: Dict[Tuple, Tuple[List[Signal], bool, str]] = {}
        self._in_progress: Set[Tuple] = set()

    # -- file / module access ------------------------------------------
    def ctx(self, relpath: str) -> Optional[FileContext]:
        cached = self._ctx_cache.get(relpath, _MISSING)
        if cached is not _MISSING:
            return cached
        path = self.root / relpath
        ctx: Optional[FileContext] = None
        if path.is_file():
            try:
                ctx = FileContext(path, relpath, path.read_text())
            except (SyntaxError, UnicodeDecodeError):
                ctx = None
        self._ctx_cache[relpath] = ctx
        return ctx

    def module_relpath(self, module: str) -> Optional[str]:
        """``metrics_tpu.functional.x`` -> ``functional/x.py`` (or the
        package ``__init__.py``); None for out-of-package modules."""
        if module == PACKAGE_NAME:
            return "__init__.py"
        prefix = PACKAGE_NAME + "."
        if not module.startswith(prefix):
            return None
        tail = module[len(prefix):].replace(".", "/")
        if (self.root / (tail + ".py")).is_file():
            return tail + ".py"
        if (self.root / tail / "__init__.py").is_file():
            return tail + "/__init__.py"
        return None

    def imports_of(self, ctx: FileContext) -> Dict[str, Tuple[str, str]]:
        """bound name -> (absolute module, original name) for every
        ``from <in-package module> import name [as bound]`` in ``ctx``."""
        cached = self._import_cache.get(id(ctx))
        if cached is not None:
            return cached
        out: Dict[str, Tuple[str, str]] = {}
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ImportFrom):
                continue
            module = node.module or ""
            if node.level:
                # relative import: resolve against the file's package path
                parts = ctx.relpath.split("/")[:-1]
                if node.level - 1:
                    parts = parts[: -(node.level - 1)] if node.level - 1 <= len(parts) else []
                base = ".".join([PACKAGE_NAME] + parts)
                module = f"{base}.{module}" if module else base
            if not (module == PACKAGE_NAME or module.startswith(PACKAGE_NAME + ".")):
                continue
            for alias in node.names:
                out[alias.asname or alias.name] = (module, alias.name)
        self._import_cache[id(ctx)] = out
        return out

    def _find_def(self, ctx: FileContext, name: str, kind) -> Optional[Tuple[FileContext, ast.AST]]:
        for node in ctx.tree.body:
            if isinstance(node, kind) and node.name == name:
                return ctx, node
        return None

    def resolve_function(
        self, ctx: FileContext, name: str, _hops: int = 4
    ) -> Optional[Tuple[FileContext, ast.FunctionDef]]:
        """Find the def of ``name`` visible from ``ctx``: same module first,
        then module-level rebindings (``_kappa_update = _confmat_update``),
        then in-package ``from`` imports (one ``__init__`` hop)."""
        found = self._find_def(ctx, name, ast.FunctionDef)
        if found is not None:
            return found  # type: ignore[return-value]
        if _hops > 0:
            for node in ctx.tree.body:
                if (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == name
                    and isinstance(node.value, ast.Name)
                ):
                    return self.resolve_function(ctx, node.value.id, _hops - 1)
        target = self.imports_of(ctx).get(name)
        if target is None or _hops <= 0:
            return None
        relpath = self.module_relpath(target[0])
        if relpath is None:
            return None
        tctx = self.ctx(relpath)
        if tctx is None or tctx is ctx:
            return None
        return self.resolve_function(tctx, target[1], _hops - 1)

    def resolve_class(
        self, ctx: FileContext, name: str, _hops: int = 4
    ) -> Optional[Tuple[FileContext, ast.ClassDef]]:
        found = self._find_def(ctx, name, ast.ClassDef)
        if found is not None:
            return found  # type: ignore[return-value]
        target = self.imports_of(ctx).get(name)
        if target is None or _hops <= 0:
            return None
        relpath = self.module_relpath(target[0])
        if relpath is None:
            return None
        tctx = self.ctx(relpath)
        if tctx is None or tctx is ctx:
            return None
        return self.resolve_class(tctx, target[1], _hops - 1)


class _Missing:
    pass


_MISSING = _Missing()


# ---------------------------------------------------------------------------
# the abstract interpreter
# ---------------------------------------------------------------------------

class _Scanner:
    """Walks one function body collecting :class:`Signal`s, tracking the
    taint / None-ness / bool-ness lattice, resolving in-package calls."""

    def __init__(self, project: Project, ctx: FileContext, depth: int) -> None:
        self.project = project
        self.ctx = ctx
        self.depth = depth
        self.signals: List[Signal] = []
        self.return_value = _Value(tainted=False, noneness=_NOT_NONE)
        self._saw_return = False
        self._returned_once = False
        #: >0 while scanning a `try` body that has except handlers: callees'
        #: trace-time raises are caught here, so their "trace-raise" signals
        #: are dropped at this call site
        self._shielded = 0

    # -- entry points --------------------------------------------------
    def scan(self, fn: ast.FunctionDef, env: _Env) -> None:
        self._scan_stmts(fn.body, env, conditional=False)

    def _emit(self, kind: str, detail: str, conditional: bool, node: ast.AST) -> None:
        self.signals.append(
            Signal(kind=kind, detail=detail, conditional=conditional, line=getattr(node, "lineno", 0))
        )

    # -- statements ----------------------------------------------------
    def _scan_stmts(self, stmts: Sequence[ast.stmt], env: _Env, conditional: bool) -> None:
        for i, stmt in enumerate(stmts):
            if isinstance(stmt, ast.If):
                stop = self._scan_if(stmt, env, conditional)
                if stop:
                    return  # remainder is eager-only (guarded-raise idiom)
            elif isinstance(stmt, ast.While):
                test = self._eval(stmt.test, env, conditional)
                if test.tainted:
                    self._emit(
                        REASON_HOST_SYNC,
                        "Python `while` on a traced value concretizes under jit",
                        conditional,
                        stmt,
                    )
                self._scan_stmts(stmt.body, env, True)
                self._scan_stmts(stmt.orelse, env, True)
            elif isinstance(stmt, ast.For):
                it = self._eval(stmt.iter, env, conditional)
                self._bind_target(stmt.target, _Value(tainted=it.tainted, noneness=_NOT_NONE), env)
                self._scan_stmts(stmt.body, env, conditional)
                self._scan_stmts(stmt.orelse, env, conditional)
            elif isinstance(stmt, ast.Try):
                if stmt.handlers:
                    self._shielded += 1
                try:
                    self._scan_stmts(stmt.body, env, conditional)
                finally:
                    if stmt.handlers:
                        self._shielded -= 1
                for handler in stmt.handlers:
                    self._scan_stmts(handler.body, env, True)
                self._scan_stmts(stmt.orelse, env, conditional)
                self._scan_stmts(stmt.finalbody, env, conditional)
            elif isinstance(stmt, ast.With):
                for item in stmt.items:
                    self._eval(item.context_expr, env, conditional)
                self._scan_stmts(stmt.body, env, conditional)
            elif isinstance(stmt, ast.Assign):
                value = self._eval(stmt.value, env, conditional)
                for tgt in stmt.targets:
                    self._scan_state_write(tgt, stmt.value, env, conditional)
                    self._bind_target(tgt, value, env)
            elif isinstance(stmt, ast.AugAssign):
                value = self._eval(stmt.value, env, conditional)
                if isinstance(stmt.target, ast.Name):
                    prev = env.value_of(stmt.target.id)
                    env.bind(
                        stmt.target.id,
                        _Value(tainted=prev.tainted or value.tainted, noneness=_NOT_NONE),
                    )
            elif isinstance(stmt, ast.AnnAssign):
                if stmt.value is not None:
                    value = self._eval(stmt.value, env, conditional)
                    self._scan_state_write(stmt.target, stmt.value, env, conditional)
                    self._bind_target(stmt.target, value, env)
            elif isinstance(stmt, ast.Return):
                self._saw_return = True
                if stmt.value is not None:
                    value = self._eval(stmt.value, env, conditional)
                    if not self._returned_once:
                        merged_elts = value.elts
                    elif (
                        self.return_value.elts is not None
                        and value.elts is not None
                        and len(self.return_value.elts) == len(value.elts)
                    ):
                        merged_elts = [
                            _Value(
                                tainted=a.tainted or b.tainted,
                                noneness=a.noneness if a.noneness == b.noneness else _MAYBE,
                            )
                            for a, b in zip(self.return_value.elts, value.elts)
                        ]
                    else:
                        merged_elts = None  # mixed return shapes: whole-tuple taint
                    self.return_value = _Value(
                        tainted=self.return_value.tainted or value.tainted,
                        noneness=value.noneness if not self._saw_return else _MAYBE
                        if self.return_value.noneness != value.noneness
                        else value.noneness,
                        elts=merged_elts,
                    )
                    self._returned_once = True
            elif isinstance(stmt, ast.Expr):
                self._eval(stmt.value, env, conditional)
            elif isinstance(stmt, ast.Assert):
                test = self._eval(stmt.test, env, conditional)
                if test.tainted:
                    self._emit(
                        REASON_HOST_SYNC,
                        "`assert` on a traced value concretizes under jit",
                        conditional,
                        stmt,
                    )
            elif isinstance(stmt, ast.Raise):
                if stmt.exc is not None:
                    self._eval(stmt.exc, env, conditional)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue  # nested defs: out of scope for the update surface
            else:
                continue

    #: when set (the class's __exact_mode_attr__), branches testing
    #: `self.<attr>` are the opt-in exact mode: runtime-guarded, excluded
    #: from the default-mode verdict this scan produces
    exact_attr: Optional[str] = None

    #: attribute names from __traced_callable_attrs__: `self.<attr>(...)`
    #: is modeled as a traced-pure array program (the ctor installs a
    #: traceable callable there by contract; a violating user install is
    #: caught at runtime by the fused dispatcher's stale-manifest demotion)
    traced_callable_attrs: FrozenSet[str] = frozenset()

    def _exact_branch_side(self, test: ast.AST) -> Optional[str]:
        """\"body\" when `if self.<exact_attr>:` selects the exact mode in
        its body, \"orelse\" for the negated spelling, None otherwise."""
        attr = self.exact_attr
        if attr is None:
            return None

        def is_exact_ref(node: ast.AST) -> bool:
            if isinstance(node, ast.Attribute) and node.attr == attr:
                return isinstance(node.value, ast.Name) and node.value.id == "self"
            return isinstance(node, ast.Name) and node.id == attr

        if is_exact_ref(test):
            return "body"
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not) and is_exact_ref(test.operand):
            return "orelse"
        return None

    def _scan_if(self, stmt: ast.If, env: _Env, conditional: bool) -> bool:
        """Returns True when the remainder of the enclosing block is
        eager-only (the ``if not _is_concrete(...): raise`` idiom)."""
        exact_side = self._exact_branch_side(stmt.test)
        if exact_side is not None:
            # declared mode split: only the default (sketch) side counts
            # toward the class verdict; the exact side is runtime-guarded
            self._scan_stmts(
                stmt.orelse if exact_side == "body" else stmt.body, env, conditional
            )
            return False
        if _mentions_concrete_guard(stmt.test):
            # guarded side is host-only by contract; the else side traces
            self._scan_stmts(stmt.orelse, env, conditional)
            if _is_not_concrete_test(stmt.test) and _always_raises(stmt.body):
                # `if not _is_concrete(...): raise` — this code path FAILS
                # under tracing. An enclosing try/except owns the failure;
                # otherwise the fusible verdict is blocked (probe decides)
                if not self._shielded:
                    self._emit(
                        "trace-raise",
                        "reachable `if not _is_concrete(...): raise` fails under tracing "
                        "for some input configurations",
                        conditional,
                        stmt,
                    )
                return True
            return False

        # statically-dead branch elimination on None-ness
        live = self._liveness(stmt.test, env)
        if live == "body":
            self._scan_stmts(stmt.body, env, conditional)
            return False
        if live == "orelse":
            self._scan_stmts(stmt.orelse, env, conditional)
            return False

        test = self._eval(stmt.test, env, conditional)
        is_type_dispatch = any(
            isinstance(sub, ast.Call) and _last_name(sub.func) == "isinstance"
            for sub in ast.walk(stmt.test)
        )
        if test.tainted and not is_type_dispatch:
            self._emit(
                REASON_HOST_SYNC,
                "Python `if` on a traced value concretizes under jit",
                conditional,
                stmt,
            )
        # isolated branch environments, joined on exit — bindings from one
        # branch must not leak into (and mask) the other
        env_body = env.snapshot()
        env_orelse = env.snapshot()
        self._scan_stmts(stmt.body, env_body, True)
        self._scan_stmts(stmt.orelse, env_orelse, True)
        env.absorb_branches(env_body, env_orelse)
        return False

    def _liveness(self, test: ast.AST, env: _Env) -> Optional[str]:
        """Which branch of ``if test`` is statically live, when decidable
        from None-ness: `x is None` / `x is not None` / bare `x` / `not x`
        with x's None-ness known."""
        def name_noneness(node: ast.AST) -> Optional[str]:
            if isinstance(node, ast.Name):
                return env.noneness.get(node.id, _MAYBE)
            return None

        if isinstance(test, ast.Compare) and len(test.ops) == 1 and len(test.comparators) == 1:
            left, right = test.left, test.comparators[0]
            is_none_cmp = isinstance(right, ast.Constant) and right.value is None
            if is_none_cmp:
                nn = name_noneness(left)
                if isinstance(test.ops[0], ast.Is):
                    if nn == _NONE:
                        return "body"
                    if nn == _NOT_NONE:
                        return "orelse"
                elif isinstance(test.ops[0], ast.IsNot):
                    if nn == _NONE:
                        return "orelse"
                    if nn == _NOT_NONE:
                        return "body"
        if isinstance(test, ast.Name) and env.noneness.get(test.id) == _NONE:
            return "orelse"  # `if x:` with x known-None is statically false
        if (
            isinstance(test, ast.UnaryOp)
            and isinstance(test.op, ast.Not)
            and isinstance(test.operand, ast.Name)
            and env.noneness.get(test.operand.id) == _NONE
        ):
            return "body"  # `if not x:` with x known-None
        return None

    def _bind_target(self, tgt: ast.AST, value: _Value, env: _Env) -> None:
        if isinstance(tgt, ast.Name):
            env.bind(tgt.id, value)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            if value.elts is not None and len(value.elts) == len(tgt.elts):
                # element-wise tuple taint (a resolved callee returning
                # `(traced, traced, host_mode)` must not taint the mode)
                for el, ev in zip(tgt.elts, value.elts):
                    self._bind_target(el, ev, env)
                return
            for el in tgt.elts:
                self._bind_target(el, _Value(tainted=value.tainted, noneness=_MAYBE), env)
        elif isinstance(tgt, ast.Starred):
            self._bind_target(tgt.value, value, env)
        # attribute/subscript targets carry no local binding

    def _scan_state_write(self, tgt: ast.AST, rhs: ast.AST, env: _Env, conditional: bool) -> None:
        """Assignment to a registered state: growing the array (concatenate
        with itself) is the array-state spelling of cat-growth."""
        if not (
            isinstance(tgt, ast.Attribute)
            and isinstance(tgt.value, ast.Name)
            and tgt.value.id == "self"
            and tgt.attr in env.states
        ):
            return
        for sub in ast.walk(rhs):
            if isinstance(sub, ast.Call) and _last_name(sub.func) in {
                "concatenate",
                "append",
                "hstack",
                "vstack",
            }:
                mentions_state = any(
                    isinstance(n, ast.Attribute)
                    and n.attr == tgt.attr
                    and isinstance(n.value, ast.Name)
                    and n.value.id == "self"
                    for a in list(sub.args) + [kw.value for kw in sub.keywords]
                    for n in ast.walk(a)
                )
                if mentions_state:
                    self._emit(
                        REASON_CAT_GROWTH,
                        f"state `{tgt.attr}` grows by concatenation each update",
                        conditional,
                        sub,
                    )

    # -- expressions ---------------------------------------------------
    def _eval(self, node: ast.AST, env: _Env, conditional: bool) -> _Value:
        if isinstance(node, ast.Constant):
            return _Value(tainted=False, noneness=_NONE if node.value is None else _NOT_NONE)
        if isinstance(node, ast.Name):
            return env.value_of(node.id)
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                self._eval(node.value, env, conditional)  # still visit for signals
                return _Value(tainted=False, noneness=_NOT_NONE)
            if isinstance(node.value, ast.Name) and node.value.id == "self":
                return _Value(tainted=node.attr in env.states, noneness=_MAYBE)
            base = self._eval(node.value, env, conditional)
            return _Value(tainted=base.tainted, noneness=_MAYBE)
        if isinstance(node, ast.Call):
            return self._eval_call(node, env, conditional)
        if isinstance(node, (ast.Tuple, ast.List)) and not isinstance(node.ctx, ast.Store):
            elts = [self._eval(e, env, conditional) for e in node.elts]
            return _Value(
                tainted=any(v.tainted for v in elts),
                noneness=_NOT_NONE,
                elts=elts if isinstance(node, ast.Tuple) else None,
            )
        if isinstance(node, ast.Compare):
            values = [self._eval(node.left, env, conditional)] + [
                self._eval(c, env, conditional) for c in node.comparators
            ]
            if all(isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn)) for op in node.ops):
                return _Value(tainted=False, noneness=_NOT_NONE)
            tainted = any(v.tainted for v in values)
            return _Value(tainted=tainted, noneness=_NOT_NONE, boolish=tainted)
        if isinstance(node, (ast.BinOp,)):
            left = self._eval(node.left, env, conditional)
            right = self._eval(node.right, env, conditional)
            boolish = (left.boolish or right.boolish) and isinstance(
                node.op, (ast.BitAnd, ast.BitOr, ast.BitXor)
            )
            return _Value(tainted=left.tainted or right.tainted, noneness=_NOT_NONE, boolish=boolish)
        if isinstance(node, ast.UnaryOp):
            operand = self._eval(node.operand, env, conditional)
            return _Value(tainted=operand.tainted, noneness=_NOT_NONE, boolish=operand.boolish)
        if isinstance(node, ast.BoolOp):
            values = [self._eval(v, env, conditional) for v in node.values]
            return _Value(
                tainted=any(v.tainted for v in values),
                noneness=_MAYBE,
                boolish=any(v.boolish for v in values),
            )
        if isinstance(node, ast.IfExp):
            test = self._eval(node.test, env, conditional)
            if test.tainted:
                self._emit(
                    REASON_HOST_SYNC,
                    "conditional expression on a traced value concretizes under jit",
                    conditional,
                    node,
                )
            body = self._eval(node.body, env, conditional)
            orelse = self._eval(node.orelse, env, conditional)
            return _Value(
                tainted=body.tainted or orelse.tainted,
                noneness=body.noneness if body.noneness == orelse.noneness else _MAYBE,
            )
        if isinstance(node, ast.Subscript):
            base = self._eval(node.value, env, conditional)
            self._scan_subscript(node, base, env, conditional)
            # `x.shape[i]` yields an int, never None; general subscripts
            # (dict lookups) stay maybe-None
            shape_like = (
                isinstance(node.value, ast.Attribute) and node.value.attr in _STATIC_ATTRS
            )
            return _Value(
                tainted=base.tainted, noneness=_NOT_NONE if shape_like else _MAYBE
            )
        if isinstance(node, (ast.List, ast.Tuple, ast.Set)):
            values = [self._eval(el, env, conditional) for el in node.elts]
            return _Value(tainted=any(v.tainted for v in values), noneness=_NOT_NONE)
        if isinstance(node, ast.Dict):
            tainted = False
            for k, v in zip(node.keys, node.values):
                if k is not None:
                    tainted |= self._eval(k, env, conditional).tainted
                tainted |= self._eval(v, env, conditional).tainted
            return _Value(tainted=tainted, noneness=_NOT_NONE)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)):
            tainted = False
            for gen in node.generators:
                it = self._eval(gen.iter, env, conditional)
                self._bind_target(gen.target, _Value(tainted=it.tainted, noneness=_NOT_NONE), env)
                tainted |= it.tainted
                for cond in gen.ifs:
                    cv = self._eval(cond, env, conditional)
                    if cv.tainted:
                        self._emit(
                            REASON_DATA_SHAPE,
                            "comprehension filtered on a traced value has a data-dependent length",
                            conditional,
                            cond,
                        )
            if isinstance(node, ast.DictComp):
                tainted |= self._eval(node.key, env, conditional).tainted
                tainted |= self._eval(node.value, env, conditional).tainted
            else:
                tainted |= self._eval(node.elt, env, conditional).tainted
            return _Value(tainted=tainted, noneness=_NOT_NONE)
        if isinstance(node, ast.JoinedStr):
            for v in node.values:
                if isinstance(v, ast.FormattedValue):
                    fv = self._eval(v.value, env, conditional)
                    if fv.tainted:
                        self._emit(
                            REASON_HOST_SYNC,
                            "f-string interpolation of a traced value reads it on host",
                            conditional,
                            v,
                        )
            return _Value(tainted=False, noneness=_NOT_NONE)
        if isinstance(node, ast.NamedExpr):
            value = self._eval(node.value, env, conditional)
            self._bind_target(node.target, value, env)
            return value
        if isinstance(node, ast.Starred):
            return self._eval(node.value, env, conditional)
        if isinstance(node, ast.Lambda):
            return _Value(tainted=False, noneness=_NOT_NONE)
        if isinstance(node, ast.Slice):
            for part in (node.lower, node.upper, node.step):
                if part is not None:
                    self._eval(part, env, conditional)
            return _Value(tainted=False, noneness=_NOT_NONE)
        # unhandled expression kinds: visit children conservatively
        tainted = False
        for child in ast.iter_child_nodes(node):
            tainted |= self._eval(child, env, conditional).tainted
        return _Value(tainted=tainted, noneness=_MAYBE)

    def _scan_subscript(self, node: ast.Subscript, base: _Value, env: _Env, conditional: bool) -> None:
        sl = node.slice
        parts: List[ast.AST]
        if isinstance(sl, ast.Tuple):
            parts = list(sl.elts)
        else:
            parts = [sl]
        for part in parts:
            if isinstance(part, ast.Slice):
                for bound in (part.lower, part.upper, part.step):
                    if bound is None:
                        continue
                    bv = self._eval(bound, env, conditional)
                    if bv.tainted and base.tainted:
                        self._emit(
                            REASON_DATA_SHAPE,
                            "slice bound derived from traced data gives a data-dependent shape",
                            conditional,
                            part,
                        )
            else:
                pv = self._eval(part, env, conditional)
                if base.tainted and pv.tainted and pv.boolish:
                    self._emit(
                        REASON_DATA_SHAPE,
                        "boolean-mask indexing selects a data-dependent number of elements",
                        conditional,
                        part,
                    )

    # -- calls ----------------------------------------------------------
    def _eval_call(self, node: ast.Call, env: _Env, conditional: bool) -> _Value:
        func = node.func
        arg_values = [self._eval(a, env, conditional) for a in node.args]
        kw_values = {kw.arg: self._eval(kw.value, env, conditional) for kw in node.keywords}
        any_taint = any(v.tainted for v in arg_values) or any(
            v.tainted for v in kw_values.values()
        )

        if isinstance(func, ast.Name):
            name = func.id
            if name == "_is_concrete":
                return _Value(tainted=False, noneness=_NOT_NONE)
            if name in _DISPATCHED_OPS:
                return _Value(tainted=True, noneness=_NOT_NONE)
            if name in _CAST_BUILTINS:
                if any_taint:
                    self._emit(
                        REASON_HOST_SYNC,
                        f"`{name}()` on a traced value forces a device->host round-trip",
                        conditional,
                        node,
                    )
                return _Value(tainted=False, noneness=_NOT_NONE)
            if name in _SAFE_HOST_BUILTINS:
                # container/iteration builtins preserve taint of their input
                keeps = name in {"sum", "max", "min", "abs", "list", "tuple", "sorted", "reversed"}
                return _Value(tainted=any_taint and keeps, noneness=_NOT_NONE)
            if name in self.ctx.jnp_member_imports:
                return self._jnp_call(self.ctx.jnp_member_imports[name], node, arg_values, kw_values, env, conditional)
            if name in self.ctx.numpy_member_imports:
                if any_taint:
                    self._emit(
                        REASON_HOST_SYNC,
                        f"numpy `{name}` on a traced value pulls it to host",
                        conditional,
                        node,
                    )
                return _Value(tainted=False, noneness=_NOT_NONE)
            resolved = self.project.resolve_function(self.ctx, name)
            if resolved is not None:
                return self._resolved_call(resolved, node, arg_values, kw_values, conditional)
            if any_taint:
                # an "unknown" signal already blocks the fusible verdict, so
                # the result is modeled untainted: propagating taint out of a
                # hole would cascade into FALSE unconditional unsafe signals
                # downstream (`if` on the artifact), turning unknown into a
                # wrong unsafe verdict
                self._emit(
                    "unknown",
                    f"unresolved call `{name}` receives traced values",
                    conditional,
                    node,
                )
            return _Value(tainted=False, noneness=_MAYBE)

        if isinstance(func, ast.Attribute):
            chain = _attr_chain(func)
            root = chain[0] if chain else None
            member = func.attr
            # module-rooted calls
            if root is not None and len(chain) >= 2:
                if root in self.ctx.jnp_aliases and len(chain) == 2:
                    return self._jnp_call(member, node, arg_values, kw_values, env, conditional)
                if root in self.ctx.jnp_aliases and len(chain) > 2:
                    # jnp submodule ops (jnp.linalg.norm, jnp.fft.*): ordinary
                    # traced-pure array programs, like their top-level kin
                    return _Value(tainted=True, noneness=_NOT_NONE)
                if root in self.ctx.lax_aliases or (
                    len(chain) >= 3 and root in self.ctx.jax_aliases and chain[1] == "lax"
                ):
                    return _Value(tainted=True, noneness=_NOT_NONE)
                if root in self.ctx.jax_aliases:
                    if member == "device_get":
                        self._emit(
                            REASON_HOST_SYNC,
                            "`jax.device_get` blocks on a host transfer",
                            conditional,
                            node,
                        )
                        return _Value(tainted=False, noneness=_NOT_NONE)
                    if len(chain) >= 3 and chain[1] == "numpy":
                        return self._jnp_call(member, node, arg_values, kw_values, env, conditional)
                    return _Value(tainted=True, noneness=_NOT_NONE)
                if root in self.ctx.numpy_aliases and root not in self.ctx.jnp_aliases:
                    if any_taint:
                        self._emit(
                            REASON_HOST_SYNC,
                            f"`{root}.{member}` on a traced value pulls it to host",
                            conditional,
                            node,
                        )
                    return _Value(tainted=False, noneness=_NOT_NONE)
            # self.<method>(...) — resolve within the class chain if bound
            # (resolved BEFORE the dispatched-ops name check: a class's own
            # method shadowing one of those names must still be descended)
            if (
                isinstance(func.value, ast.Name)
                and func.value.id == "self"
                and self._method_resolver is not None
            ):
                resolved = self._method_resolver(member)
                if resolved is not None:
                    return self._resolved_call(resolved, node, arg_values, kw_values, conditional, skip_self=True)
                if member == "add_state":
                    return _Value(tainted=False, noneness=_NOT_NONE)
                if member in self.traced_callable_attrs:
                    # declared traced callable attribute (e.g. a Flax
                    # feature extractor): a pure array → array program
                    return _Value(tainted=True, noneness=_NOT_NONE)
                if any_taint:
                    self._emit(
                        "unknown",
                        f"unresolved method `self.{member}` receives traced values",
                        conditional,
                        node,
                    )
                return _Value(tainted=False, noneness=_MAYBE)
            # module-attribute form of the dispatched ops (ops.bincount_dispatch);
            # after self-method resolution so a class's own same-named method
            # is still descended; the names are distinctive (`*_dispatch`)
            if member in _DISPATCHED_OPS and not (
                isinstance(func.value, ast.Name) and func.value.id == "self"
            ):
                self._eval(func.value, env, conditional)
                return _Value(tainted=True, noneness=_NOT_NONE)
            # `x.at[idx].set/add/...` — jax's pure functional scatter-update
            # namespace: a traced array op whatever the receiver's taint
            if (
                member in _AT_UPDATE_METHODS
                and isinstance(func.value, ast.Subscript)
                and isinstance(func.value.value, ast.Attribute)
                and func.value.value.attr == "at"
            ):
                self._eval(func.value, env, conditional)
                return _Value(tainted=True, noneness=_NOT_NONE)
            # method on an evaluated receiver
            receiver = self._eval(func.value, env, conditional)
            if (
                member == "append"
                and isinstance(func.value, ast.Attribute)
                and isinstance(func.value.value, ast.Name)
                and func.value.value.id == "self"
                and func.value.attr in (env.states | env.list_states)
            ):
                self._emit(
                    REASON_CAT_GROWTH,
                    f"state `{func.value.attr}` accumulates by append (unbounded concatenation)",
                    conditional,
                    node,
                )
                return _Value(tainted=False, noneness=_NOT_NONE)
            if receiver.tainted:
                if member in _HOST_SYNC_METHODS:
                    self._emit(
                        REASON_HOST_SYNC,
                        f"`.{member}()` forces a device->host sync",
                        conditional,
                        node,
                    )
                    return _Value(tainted=False, noneness=_NOT_NONE)
                if member in _DATA_DEP_METHODS:
                    self._emit(
                        REASON_DATA_SHAPE,
                        f"`.{member}()` has a data-dependent output shape",
                        conditional,
                        node,
                    )
                    return _Value(tainted=True, noneness=_NOT_NONE)
                return _Value(
                    tainted=True, noneness=_NOT_NONE, boolish=member in _BOOLISH_MEMBERS
                )
            if any_taint:
                self._emit(
                    "unknown",
                    f"unresolved call `{'.'.join(chain) or member}` receives traced values",
                    conditional,
                    node,
                )
            return _Value(tainted=False, noneness=_MAYBE)

        # call on an arbitrary expression (rare)
        self._eval(func, env, conditional)
        if any_taint:
            self._emit("unknown", "unresolved indirect call receives traced values", conditional, node)
        return _Value(tainted=False, noneness=_MAYBE)

    #: set by classify_* so `self.<method>()` resolves along the class chain
    _method_resolver = None

    def _jnp_call(
        self,
        member: str,
        node: ast.Call,
        arg_values: List[_Value],
        kw_values: Dict[Optional[str], _Value],
        env: _Env,
        conditional: bool,
    ) -> _Value:
        if member in _DATA_DEP_MEMBERS:
            if member in ("nonzero", "flatnonzero") and "size" in kw_values:
                # `size=` pads/truncates to a STATIC length — the fixed-shape
                # scatter-index idiom the capacity buffers and sketches use
                return _Value(tainted=True, noneness=_NOT_NONE)
            self._emit(
                REASON_DATA_SHAPE,
                f"`jnp.{member}` has a data-dependent output shape",
                conditional,
                node,
            )
            return _Value(tainted=True, noneness=_NOT_NONE)
        if member == "where" and len(node.args) == 1:
            self._emit(
                REASON_DATA_SHAPE,
                "single-argument `jnp.where` is `nonzero` — data-dependent output shape",
                conditional,
                node,
            )
            return _Value(tainted=True, noneness=_NOT_NONE)
        if member == "bincount" and "length" not in kw_values:
            self._emit(
                REASON_DATA_SHAPE,
                "`jnp.bincount` without `length=` has a data-dependent output shape",
                conditional,
                node,
            )
            return _Value(tainted=True, noneness=_NOT_NONE)
        if member == "repeat" and "total_repeat_length" not in kw_values:
            repeats_tainted = (len(arg_values) >= 2 and arg_values[1].tainted) or kw_values.get(
                "repeats", _HOST
            ).tainted
            if repeats_tainted:
                self._emit(
                    REASON_DATA_SHAPE,
                    "`jnp.repeat` with traced repeats and no `total_repeat_length` has a data-dependent shape",
                    conditional,
                    node,
                )
                return _Value(tainted=True, noneness=_NOT_NONE)
        if member in _HOST_RESULT_MEMBERS:
            return _Value(tainted=False, noneness=_NOT_NONE)
        return _Value(tainted=True, noneness=_NOT_NONE, boolish=member in _BOOLISH_MEMBERS)

    def _resolved_call(
        self,
        resolved: Tuple[FileContext, ast.FunctionDef],
        node: ast.Call,
        arg_values: List[_Value],
        kw_values: Dict[Optional[str], _Value],
        conditional: bool,
        skip_self: bool = False,
    ) -> _Value:
        tctx, fn = resolved
        if self.depth <= 0:
            if any(v.tainted for v in arg_values) or any(v.tainted for v in kw_values.values()):
                self._emit(
                    "unknown",
                    f"call depth budget exhausted at `{fn.name}`",
                    conditional,
                    node,
                )
            # untainted result for the same reason as unresolved calls: the
            # unknown signal is already recorded, and an artificial taint
            # would fabricate unconditional unsafe signals downstream
            return _Value(tainted=False, noneness=_MAYBE)

        signals, ret = summarize_function(
            self.project,
            tctx,
            fn,
            arg_values,
            kw_values,
            depth=self.depth - 1,
            skip_self=skip_self,
        )
        for sig in signals:
            if sig.kind == "trace-raise" and self._shielded:
                continue  # an enclosing try/except owns the trace-time raise
            self.signals.append(
                Signal(sig.kind, f"{sig.detail} (via `{fn.name}`)", sig.conditional or conditional, sig.line)
            )
        return ret


def _bind_params(
    fn: ast.FunctionDef,
    arg_values: List[_Value],
    kw_values: Dict[Optional[str], _Value],
    skip_self: bool,
) -> Tuple[Set[str], Dict[str, str]]:
    """Map a concrete call's abstract arguments onto the callee's params;
    returns (tainted param names, param None-ness)."""
    params = [a.arg for a in list(fn.args.posonlyargs) + list(fn.args.args)]
    if skip_self and params and params[0] == "self":
        params = params[1:]
    defaults = list(fn.args.defaults)
    default_map: Dict[str, ast.AST] = {}
    for pname, dflt in zip(params[len(params) - len(defaults):], defaults):
        default_map[pname] = dflt
    for kwarg, dflt in zip(fn.args.kwonlyargs, fn.args.kw_defaults):
        if dflt is not None:
            default_map[kwarg.arg] = dflt
    kw_params = [a.arg for a in fn.args.kwonlyargs]

    tainted: Set[str] = set()
    noneness: Dict[str, str] = {}

    def note(pname: str, value: _Value) -> None:
        if value.tainted:
            tainted.add(pname)
        noneness[pname] = value.noneness

    consumed = 0
    for i, value in enumerate(arg_values):
        if i < len(params):
            note(params[i], value)
            consumed = i + 1
        elif fn.args.vararg is not None:
            note(fn.args.vararg.arg, value)
    for kwname, value in kw_values.items():
        if kwname is None:  # **kwargs expansion at the call site
            for pname in params[consumed:] + kw_params:
                if value.tainted:
                    tainted.add(pname)
                noneness.setdefault(pname, _MAYBE)
            if fn.args.kwarg is not None:
                note(fn.args.kwarg.arg, value)
        elif kwname in params or kwname in kw_params:
            note(kwname, value)
        elif fn.args.kwarg is not None:
            note(fn.args.kwarg.arg, value)
    # unbound params take their declared default's None-ness
    for pname in params + kw_params:
        if pname in noneness:
            continue
        dflt = default_map.get(pname)
        if isinstance(dflt, ast.Constant):
            noneness[pname] = _NONE if dflt.value is None else _NOT_NONE
        else:
            noneness[pname] = _MAYBE
    # a MAYBE binding upgrades to notnone when the parameter's annotation
    # excludes None (`num_classes: int`): passing None there is already a
    # type error, so dead-branch elimination may trust the annotation
    ann_by_name = {
        a.arg: a.annotation
        for a in list(fn.args.posonlyargs) + list(fn.args.args) + list(fn.args.kwonlyargs)
    }
    for pname, nn in list(noneness.items()):
        if nn == _MAYBE and _annotation_excludes_none(ann_by_name.get(pname)):
            noneness[pname] = _NOT_NONE
    return tainted, noneness


def _annotation_excludes_none(ann: Optional[ast.AST]) -> bool:
    """True for annotations that rule out None (``int``, ``Array``,
    ``Union[str, List[str]]``); False for Optional/None/Any/strings."""
    if ann is None:
        return False
    for sub in ast.walk(ann):
        if isinstance(sub, ast.Constant) and (sub.value is None or isinstance(sub.value, str)):
            return False  # explicit None, or a quoted annotation we won't parse
        name = None
        if isinstance(sub, ast.Name):
            name = sub.id
        elif isinstance(sub, ast.Attribute):
            name = sub.attr
        if name in ("Optional", "Any", "object", "None"):
            return False
    return True


def summarize_function(
    project: Project,
    ctx: FileContext,
    fn: ast.FunctionDef,
    arg_values: List[_Value],
    kw_values: Dict[Optional[str], _Value],
    depth: int,
    skip_self: bool = False,
) -> Tuple[List[Signal], _Value]:
    """Memoized abstract scan of ``fn`` under one argument binding."""
    tainted, noneness = _bind_params(fn, arg_values, kw_values, skip_self)
    key = (
        ctx.relpath,
        fn.name,
        fn.lineno,
        frozenset(tainted),
        tuple(sorted(noneness.items())),
    )
    cached = project._summary_cache.get(key)
    if cached is not None:
        return list(cached[0]), _Value(tainted=cached[1], noneness=cached[2], elts=cached[3])
    if key in project._in_progress:
        return [], _Value(tainted=True, noneness=_MAYBE)  # recursion: optimistic
    project._in_progress.add(key)
    try:
        scanner = _Scanner(project, ctx, depth)
        env = _Env(traced=set(tainted), noneness=dict(noneness))
        scanner.scan(fn, env)
        ret = scanner.return_value
        # element values survive memoization WITHOUT nested elts (one level
        # is what tuple unpacking at the call site consumes)
        elts = (
            [_Value(tainted=e.tainted, noneness=e.noneness) for e in ret.elts]
            if ret.elts is not None
            else None
        )
        project._summary_cache[key] = (list(scanner.signals), ret.tainted, ret.noneness, elts)
        return list(scanner.signals), _Value(tainted=ret.tainted, noneness=ret.noneness, elts=elts)
    finally:
        project._in_progress.discard(key)


# ---------------------------------------------------------------------------
# class-level classification
# ---------------------------------------------------------------------------

#: add_state default-expression container classification
_CONTAINER_ARRAY = "array"
_CONTAINER_LIST = "list"
_CONTAINER_UNKNOWN = "unknown"

#: jnp constructors whose first argument is the shape
_SHAPED_CTORS = {"zeros", "ones", "empty", "full"}

#: metrics_tpu/sketches/ (and retrieval-table) state initializers:
#: fixed-shape float32 leaves with the capacity as the leading dim
_SKETCH_INIT_CTORS = {
    "qsketch_init",
    "ranksketch_init",
    "reservoir_init",
    "hist_init",
    "retrieval_table_init",
    "detection_table_init",
}

_DTYPE_DEFAULTS = {"zeros": "float32", "ones": "float32", "empty": "float32", "full": None}


def _dim_of(node: ast.AST) -> object:
    """One abstract dimension: a concrete int, a symbol (parameter name),
    or "?" when the expression is beyond the lattice."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return node.value
    if isinstance(node, ast.Name):
        return node.id
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return "?"


def _shape_of(node: ast.AST) -> Optional[List[object]]:
    if isinstance(node, (ast.Tuple, ast.List)):
        return [_dim_of(el) for el in node.elts]
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, ast.Name) or isinstance(node, ast.Attribute):
        return None  # a shape variable: rank unknown
    return None


def _dtype_name(node: Optional[ast.AST]) -> Optional[str]:
    if node is None:
        return None
    name = _last_name(node)
    if name and (
        name.startswith(("int", "uint", "float", "bfloat", "complex"))
        or name in ("bool_", "bool")
    ):
        return "bool" if name in ("bool_", "bool") else name
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


@dataclass
class StateEntry:
    """Abstract description of one registered state leaf."""

    name: str
    container: str  # array | list | unknown
    shape: Optional[List[object]]  # dims: int | symbol str | "?" ; None = unknown
    dtype: Optional[str]
    dist_reduce_fx: Optional[str]  # "sum"/"mean"/... | "custom" | None

    @property
    def sliceable(self) -> bool:
        """Whether the leaf admits an exact slice-axis scatter: a
        ``sum``/``max``/``min`` reducer over an array state maps onto
        ``segment_sum`` / scatter-max / scatter-min along a leading ``[S]``
        dimension (``metrics_tpu/sliced/``); mean/cat/custom/None reducers
        and list states have no per-slice decomposition, and an unknown
        container is conservatively not sliceable."""
        return self.container == _CONTAINER_ARRAY and self.dist_reduce_fx in _SLICEABLE_REDUCERS

    def to_dict(self) -> Dict[str, object]:
        return {
            "container": self.container,
            "shape": self.shape,
            "dtype": self.dtype,
            "dist_reduce_fx": self.dist_reduce_fx,
            "sliceable": self.sliceable,
        }


def _infer_default(
    expr: Optional[ast.AST],
    bindings: Optional[Dict[str, List[ast.AST]]] = None,
    _depth: int = 3,
) -> Tuple[str, Optional[List[object]], Optional[str]]:
    """(container, shape, dtype) of an ``add_state`` default expression.

    ``bindings`` maps local names to every expression assigned to them in
    the class body: a name bound exactly once resolves through (the
    ``default = jnp.zeros(...) if multilabel else ...`` idiom); multiple
    bindings are genuinely config-dependent and stay unknown.
    """
    if expr is None or _depth <= 0:
        return _CONTAINER_UNKNOWN, None, None
    if isinstance(expr, ast.Name) and bindings is not None:
        bound = bindings.get(expr.id)
        if bound is not None and len(bound) == 1:
            return _infer_default(bound[0], bindings, _depth - 1)
        return _CONTAINER_UNKNOWN, None, None
    if (
        isinstance(expr, ast.Call)
        and isinstance(expr.func, ast.Name)
        and not expr.args
        and not expr.keywords
        and bindings is not None
    ):
        # `default()` thunk idiom: resolve the zero-arg callable's body
        bound = bindings.get(expr.func.id)
        if bound is not None and len(bound) == 1:
            target = bound[0]
            if isinstance(target, ast.Lambda):
                return _infer_default(target.body, bindings, _depth - 1)
            if isinstance(target, ast.Name) and target.id == "list":
                return _CONTAINER_LIST, None, None
        if expr.func.id == "list":
            return _CONTAINER_LIST, None, None
        if bound is not None:
            return _CONTAINER_UNKNOWN, None, None
    if isinstance(expr, ast.List):
        return _CONTAINER_LIST, None, None
    if isinstance(expr, ast.Constant) and isinstance(expr.value, (int, float, bool)):
        dtype = "bool" if isinstance(expr.value, bool) else (
            "int32" if isinstance(expr.value, int) else "float32"
        )
        return _CONTAINER_ARRAY, [], dtype
    if isinstance(expr, ast.UnaryOp) and isinstance(expr.operand, ast.Constant):
        return _infer_default(expr.operand, bindings, _depth - 1)
    if isinstance(expr, ast.IfExp):
        c1, s1, d1 = _infer_default(expr.body, bindings, _depth - 1)
        c2, s2, d2 = _infer_default(expr.orelse, bindings, _depth - 1)
        container = c1 if c1 == c2 else _CONTAINER_UNKNOWN
        return container, s1 if s1 == s2 else None, d1 if d1 == d2 else None
    if isinstance(expr, ast.Call):
        member = _last_name(expr.func)
        dtype_kw = next((kw.value for kw in expr.keywords if kw.arg == "dtype"), None)
        if member in _SKETCH_INIT_CTORS:
            # the sketches/ initializers return fixed float32 arrays whose
            # leading dim is the capacity argument (metrics register their
            # defaults through them; column count is layout-derived)
            dim0 = _dim_of(expr.args[0]) if expr.args else "?"
            return _CONTAINER_ARRAY, [dim0, "?"], "float32"
        if member in _SHAPED_CTORS:
            shape = _shape_of(expr.args[0]) if expr.args else None
            dtype = _dtype_name(dtype_kw) or (
                _dtype_name(expr.args[2]) if member == "full" and len(expr.args) >= 3 else None
            ) or _DTYPE_DEFAULTS.get(member)
            if member == "full" and dtype is None and len(expr.args) >= 2:
                _, _, dtype = _infer_default(expr.args[1], bindings, _depth - 1)
            return _CONTAINER_ARRAY, shape, dtype
        if member == "eye" and expr.args:
            dim = _dim_of(expr.args[0])
            return _CONTAINER_ARRAY, [dim, dim], _dtype_name(dtype_kw) or "float32"
        if member in {"asarray", "array"} and expr.args:
            container, shape, dtype = _infer_default(expr.args[0], bindings, _depth - 1)
            if container == _CONTAINER_LIST:
                # jnp.asarray([...]) is an ARRAY literal
                inner = expr.args[0]
                shape = [len(inner.elts)] if isinstance(inner, ast.List) else None
                container, dtype = _CONTAINER_ARRAY, dtype
            explicit = _dtype_name(dtype_kw) or (
                _dtype_name(expr.args[1]) if len(expr.args) >= 2 else None
            )
            return _CONTAINER_ARRAY, shape, explicit or dtype
        return _CONTAINER_UNKNOWN, None, _dtype_name(dtype_kw)
    return _CONTAINER_UNKNOWN, None, None


_STRING_REDUCERS = {"sum", "mean", "max", "min", "cat", "merge", "ring", "decay"}

#: reducers with an exact slice-axis scatter (see StateEntry.sliceable)
_SLICEABLE_REDUCERS = {"sum", "max", "min"}


def _reducer_of(call: ast.Call) -> Optional[str]:
    """The dist_reduce_fx of an add_state call: a known string, None (no
    reduction), or "custom" for callables/unrecognized expressions."""
    fx: Optional[ast.AST] = None
    if len(call.args) >= 3:
        fx = call.args[2]
    for kw in call.keywords:
        if kw.arg == "dist_reduce_fx":
            fx = kw.value
    if fx is None:
        return None
    if isinstance(fx, ast.Constant):
        if fx.value is None:
            return None
        if isinstance(fx.value, str) and fx.value in _STRING_REDUCERS:
            return fx.value
    if isinstance(fx, ast.Call):
        name = _last_name(fx.func)
        # the windowed module's tagged reducers (`ring_sum_fx()`,
        # `ring_merge_fx(...)`, `decay_sum_fx()`) serialize as their window
        # semantics — checked BEFORE the merge_fx suffix so a ring-of-
        # sketches leaf reads "ring", not "merge"
        if name in ("ring_sum_fx", "ring_merge_fx"):
            return "ring"
        if name == "decay_sum_fx":
            return "decay"
        # streaming-moment leaves (`moments_merge_fx()`): element-wise
        # summable sufficient statistics whose cross-rank merge IS addition
        # — checked BEFORE the merge_fx suffix so the write-contract rules
        # (additive, not insert-transform) apply to them
        if name == "moments_merge_fx":
            return "moments"
        # the sketch modules' tagged merge reducers (`sketch_merge_fx()`,
        # `reservoir_merge_fx()`, `ranksketch_merge_fx()`): a self-merging
        # leaf, distinct from an arbitrary custom callable
        if name is not None and name.endswith("merge_fx"):
            return "merge"
    return "custom"


def state_entries_of(class_node: ast.ClassDef) -> List[StateEntry]:
    """Every ``self.add_state(...)`` in the class body, abstracted."""
    entries: List[StateEntry] = []
    seen: Set[str] = set()
    # local constant propagation for the `default = <expr>; add_state(...,
    # default=default)` idiom: single-binding names resolve through
    bindings: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(class_node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and isinstance(node.targets[0], ast.Name):
            bindings.setdefault(node.targets[0].id, []).append(node.value)
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name) and node.value is not None:
            bindings.setdefault(node.target.id, []).append(node.value)
    for node in ast.walk(class_node):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not (
            isinstance(func, ast.Attribute)
            and func.attr == "add_state"
            and isinstance(func.value, ast.Name)
            and func.value.id == "self"
        ):
            continue
        name = None
        if node.args and isinstance(node.args[0], ast.Constant) and isinstance(node.args[0].value, str):
            name = node.args[0].value
        default: Optional[ast.AST] = node.args[1] if len(node.args) >= 2 else None
        for kw in node.keywords:
            if kw.arg == "default":
                default = kw.value
        container, shape, dtype = _infer_default(default, bindings)
        if name is None:
            continue  # dynamically-named state: recorded via the unknown-container path
        if name in seen:
            # registered twice (config branches): containers must agree
            prev = next(e for e in entries if e.name == name)
            if prev.container != container:
                prev.container = _CONTAINER_UNKNOWN
                prev.shape = None
            continue
        seen.add(name)
        entries.append(StateEntry(name, container, shape, dtype, _reducer_of(node)))
    return entries


@dataclass
class ClassFacts:
    """Merged cross-file view of a metric class and its in-package bases."""

    name: str
    relpath: str
    node: ast.ClassDef
    entries: List[StateEntry]
    declared: Optional[bool]  # explicit __jit_unsafe__ (None = undeclared)
    declared_here: Optional[bool]  # declaration in THIS class body only
    declared_computed: bool
    update: Optional[Tuple[FileContext, ast.FunctionDef]]
    chain: List[Tuple[FileContext, ast.ClassDef]]
    is_metric: bool
    exact_attr: Optional[str] = None  # __exact_mode_attr__ declaration
    traced_callable_attrs: FrozenSet[str] = frozenset()  # __traced_callable_attrs__


def _traced_callable_attrs(class_node: ast.ClassDef) -> FrozenSet[str]:
    """The ``__traced_callable_attrs__ = ("<attr>", ...)`` declaration.

    A metric whose constructor installs a *traceable* callable on an
    instance attribute (e.g. a Flax feature extractor bound via
    ``self.inception = build_fid_inception(...)``) declares those attribute
    names here: ``self.<attr>(...)`` calls in the update are modeled as
    traced-pure array programs instead of emitting the unresolved-method
    "unknown" signal. The declaration is a CONTRACT on the default
    configuration — a user who installs a host-only callable on such an
    attribute is caught at runtime by the fused dispatcher's stale-manifest
    safety net (the trace fails, the member is re-probed and demoted to the
    eager path), so a wrong declaration degrades performance, never
    correctness.
    """
    for stmt in class_node.body:
        if (
            isinstance(stmt, ast.Assign)
            and len(stmt.targets) == 1
            and isinstance(stmt.targets[0], ast.Name)
            and stmt.targets[0].id == "__traced_callable_attrs__"
            and isinstance(stmt.value, (ast.Tuple, ast.List))
        ):
            names = [
                el.value
                for el in stmt.value.elts
                if isinstance(el, ast.Constant) and isinstance(el.value, str)
            ]
            return frozenset(names)
    return frozenset()


def _exact_mode_attr(class_node: ast.ClassDef) -> Optional[str]:
    """The ``__exact_mode_attr__ = "<attr>"`` declaration, if present.

    The mode-split contract for sketch-converted metrics: branches testing
    ``self.<attr>`` (and states registered only there) belong to the opt-in
    exact mode, which is runtime-guarded (live list states + instance-level
    ``__jit_unsafe__``) — the class-level verdict describes the DEFAULT
    (sketch) mode, so the scanner skips the declared exact branches.
    """
    for stmt in class_node.body:
        if (
            isinstance(stmt, ast.Assign)
            and len(stmt.targets) == 1
            and isinstance(stmt.targets[0], ast.Name)
            and stmt.targets[0].id == "__exact_mode_attr__"
            and isinstance(stmt.value, ast.Constant)
            and isinstance(stmt.value.value, str)
        ):
            return stmt.value.value
    return None


def _own_declaration(class_node: ast.ClassDef) -> Tuple[Optional[bool], bool]:
    """(declared value, computed?) for a __jit_unsafe__ declaration in this
    class body — class-level assignment or the instance-dict idiom."""
    declared: Optional[bool] = None
    computed = False

    def record(value: Optional[ast.AST]) -> None:
        nonlocal declared, computed
        if isinstance(value, ast.Constant):
            declared = bool(value.value) if declared is None else (declared or bool(value.value))
        else:
            computed = True
            declared = True if declared is None else declared

    for stmt in class_node.body:
        target = None
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and isinstance(stmt.targets[0], ast.Name):
            target = stmt.targets[0].id
        elif isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
            target = stmt.target.id
        if target == "__jit_unsafe__":
            record(getattr(stmt, "value", None))
    for node in ast.walk(class_node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if (
                isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"
                and tgt.attr == "__jit_unsafe__"
            ):
                record(node.value)
            if (
                isinstance(tgt, ast.Subscript)
                and isinstance(tgt.value, ast.Attribute)
                and isinstance(tgt.value.value, ast.Name)
                and tgt.value.value.id == "self"
                and tgt.value.attr == "__dict__"
                and isinstance(tgt.slice, ast.Constant)
                and tgt.slice.value == "__jit_unsafe__"
            ):
                record(node.value)
    return declared, computed


def class_facts(project: Project, ctx: FileContext, class_node: ast.ClassDef) -> ClassFacts:
    """Resolve the class chain across files and merge state registrations,
    declarations, and the effective update method."""
    chain: List[Tuple[FileContext, ast.ClassDef]] = []
    seen: Set[Tuple[str, str]] = set()
    queue: List[Tuple[FileContext, ast.ClassDef]] = [(ctx, class_node)]
    is_metric = False
    while queue:
        cur_ctx, cur_node = queue.pop(0)
        key = (cur_ctx.relpath, cur_node.name)
        if key in seen:
            continue
        seen.add(key)
        chain.append((cur_ctx, cur_node))
        for base in cur_node.bases:
            base_name = _last_name(base)
            if base_name is None:
                continue
            if base_name == "Metric" or base_name.endswith("Metric") or base_name == "ABC":
                if base_name != "ABC":
                    is_metric = True
                resolved = project.resolve_class(cur_ctx, base_name)
                if resolved is not None and base_name != "ABC":
                    queue.append(resolved)
                continue
            resolved = project.resolve_class(cur_ctx, base_name)
            if resolved is not None:
                queue.append(resolved)

    entries: List[StateEntry] = []
    names: Set[str] = set()
    declared: Optional[bool] = None
    computed = False
    for cur_ctx, cur_node in chain:
        for entry in state_entries_of(cur_node):
            if entry.name not in names:
                names.add(entry.name)
                entries.append(entry)
        if entries and not is_metric:
            is_metric = True  # registers state: metric-like regardless of name
        if declared is None and not (
            cur_node.name == "Metric" and cur_ctx.relpath == "core/metric.py"
        ):
            # the base Metric's `__jit_unsafe__ = False` is the inherited
            # DEFAULT, not an explicit per-metric declaration
            d, c = _own_declaration(cur_node)
            if d is not None:
                declared, computed = d, c

    update: Optional[Tuple[FileContext, ast.FunctionDef]] = None
    for method_name in ("_update", "update"):
        for cur_ctx, cur_node in chain:
            for stmt in cur_node.body:
                if isinstance(stmt, ast.FunctionDef) and stmt.name == method_name:
                    update = (cur_ctx, stmt)
                    break
            if update is not None:
                break
        if update is not None:
            break

    declared_here, computed_here = _own_declaration(class_node)
    exact_attr = None
    for cur_ctx, cur_node in chain:
        exact_attr = _exact_mode_attr(cur_node)
        if exact_attr is not None:
            break
    traced_attrs: FrozenSet[str] = frozenset()
    for cur_ctx, cur_node in chain:
        traced_attrs = traced_attrs | _traced_callable_attrs(cur_node)
    return ClassFacts(
        name=class_node.name,
        relpath=ctx.relpath,
        node=class_node,
        entries=entries,
        declared=declared,
        declared_here=declared_here,
        declared_computed=computed or computed_here,
        update=update,
        chain=chain,
        is_metric=is_metric,
        exact_attr=exact_attr,
        traced_callable_attrs=traced_attrs,
    )


def _string_annotated_params(fn: ast.FunctionDef) -> Set[str]:
    """Update parameters whose type annotation mentions ``str`` — a declared
    host-text input that can never trace."""
    out: Set[str] = set()
    for arg in list(fn.args.posonlyargs) + list(fn.args.args) + list(fn.args.kwonlyargs):
        if arg.arg == "self" or arg.annotation is None:
            continue
        for sub in ast.walk(arg.annotation):
            if (isinstance(sub, ast.Name) and sub.id == "str") or (
                isinstance(sub, ast.Constant) and sub.value == "str"
            ):
                out.add(arg.arg)
                break
    return out


def _static_annotated_params(fn: ast.FunctionDef) -> Set[str]:
    """Update parameters annotated as BARE ``bool`` or ``int`` — declared
    Python-static configuration knobs, not traced array inputs. Under the
    fused dispatcher these are static (non-array leaves never become
    tracers), so branching on them is shape selection, not a host sync.
    Only the bare annotation qualifies: ``Optional[int]``, ``Tensor``-like
    wrappers, and unions stay traced."""
    out: Set[str] = set()
    for arg in list(fn.args.posonlyargs) + list(fn.args.args) + list(fn.args.kwonlyargs):
        ann = arg.annotation
        if arg.arg == "self" or ann is None:
            continue
        if (isinstance(ann, ast.Name) and ann.id in ("bool", "int")) or (
            isinstance(ann, ast.Constant) and ann.value in ("bool", "int")
        ):
            out.add(arg.arg)
    return out


def _method_resolver_for(project: Project, facts: ClassFacts):
    """Resolve ``self.<name>(...)`` along the class chain (in-package only)."""

    def resolve(name: str) -> Optional[Tuple[FileContext, ast.FunctionDef]]:
        for cur_ctx, cur_node in facts.chain:
            for stmt in cur_node.body:
                if isinstance(stmt, ast.FunctionDef) and stmt.name == name:
                    return cur_ctx, stmt
        return None

    return resolve


def classify(project: Project, ctx: FileContext, class_node: ast.ClassDef) -> Tuple[Verdict, ClassFacts]:
    """The per-class verdict and the facts it was derived from."""
    facts = class_facts(project, ctx, class_node)

    definite_lists = [e.name for e in facts.entries if e.container == _CONTAINER_LIST]
    if definite_lists:
        return (
            Verdict(
                VERDICT_UNSAFE,
                REASON_CAT_GROWTH,
                f"list state{'s' if len(definite_lists) > 1 else ''} "
                f"{', '.join(sorted(definite_lists))} accumulate by unbounded concatenation",
            ),
            facts,
        )

    if facts.update is None:
        return Verdict(VERDICT_UNKNOWN, None, "no update method found in the class chain"), facts

    unknown_containers = [e.name for e in facts.entries if e.container == _CONTAINER_UNKNOWN]

    up_ctx, up_fn = facts.update
    text_params = _string_annotated_params(up_fn)
    if text_params:
        # declared host-text inputs: jax cannot trace Python strings, so the
        # update is host-side by type contract, whatever its body does
        return (
            Verdict(
                VERDICT_UNSAFE,
                REASON_HOST_SYNC,
                "update consumes Python strings (host text processing): "
                + ", ".join(sorted(text_params)),
            ),
            facts,
        )
    scanner = _Scanner(project, up_ctx, _DEPTH_BUDGET)
    scanner._method_resolver = _method_resolver_for(project, facts)
    scanner.exact_attr = facts.exact_attr
    scanner.traced_callable_attrs = facts.traced_callable_attrs
    params = {a.arg for a in list(up_fn.args.posonlyargs) + list(up_fn.args.args) if a.arg != "self"}
    params.update(a.arg for a in up_fn.args.kwonlyargs)
    if up_fn.args.vararg:
        params.add(up_fn.args.vararg.arg)
    if up_fn.args.kwarg:
        params.add(up_fn.args.kwarg.arg)
    env = _Env(
        traced=set(params) - _static_annotated_params(up_fn),
        noneness={p: _NOT_NONE for p in params},
        states={e.name for e in facts.entries if e.container != _CONTAINER_LIST},
        list_states=set(unknown_containers),
    )
    scanner.scan(up_fn, env)
    signals = list(scanner.signals)
    if unknown_containers:
        signals.append(
            Signal(
                "unknown",
                "state container depends on constructor configuration: "
                + ", ".join(sorted(unknown_containers)),
                conditional=True,
                line=class_node.lineno,
            )
        )
    return verdict_from_signals(signals), facts


def iter_metric_classes(ctx: FileContext) -> Iterator[ast.ClassDef]:
    """Top-level classes in ``ctx`` worth classifying (named like metrics,
    based on an in-package metric, or registering state)."""
    for node in ctx.tree.body:
        if isinstance(node, ast.ClassDef):
            yield node
