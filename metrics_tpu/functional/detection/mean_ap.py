"""COCO mean-average-precision kernels (TPU-first re-design).

Behavior parity target: /root/reference/torchmetrics/detection/map.py:335-672
(itself a torch re-expression of pycocotools).  The reference evaluates a
Python loop of per-(image, class, area) calls with sequential greedy matching
per detection (map.py:423-430) — the heaviest CPU-bound path in the library
(SURVEY §3.4).

TPU-first architecture (SURVEY §7 stage 4):

1. **Host packing** — ragged per-image detections/ground-truths are packed
   into ``(image, class)`` *evaluation units* padded to power-of-two buckets
   ``[U, D]`` / ``[U, G]`` (static shapes; a handful of bucket combos →
   bounded recompiles).  Detections are pre-sorted by score (descending)
   per unit so the device loop is a pure prefix scan.
2. **Device matching** — ONE jitted kernel computes the full IoU buffer
   ``[U, D, G]`` and runs the greedy COCO matching as a ``lax.fori_loop``
   over detection rank (sequential dependence is inherent to COCO
   semantics), vectorized over all units × area ranges × IoU thresholds at
   once — replacing |imgs|×|classes|×4×10 Python iterations with D fused
   steps.
3. **Host PR reduction** — exact float64 cumsum/searchsorted reduction
   reproducing reference map.py:608-672 bit-for-bit semantics (mergesort
   score ordering, right-to-left precision envelope, first-out-of-bounds
   recall truncation).
"""
from functools import partial
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.functional.detection.box_ops import box_area
from metrics_tpu.ops.box_iou_pallas import box_iou_dispatch

Array = jax.Array



# ---------------------------------------------------------------------------
# device kernel
# ---------------------------------------------------------------------------
@partial(jax.jit, static_argnames=())
def _match_units_kernel(
    det_boxes: Array,  # [U, D, 4] xyxy, sorted by score desc per unit, zero-padded
    det_valid: Array,  # [U, D] bool
    gt_boxes: Array,  # [U, G, 4] xyxy, zero-padded
    gt_valid: Array,  # [U, G] bool
    iou_thresholds: Array,  # [T] f32
    area_ranges: Array,  # [A, 2] f32 (lo, hi)
) -> Tuple[Array, Array, Array]:
    """Greedy COCO matching for all units × area ranges × IoU thresholds.

    Returns ``det_matches [U, A, T, D]`` (detection matched an unignored gt),
    ``det_area_out [U, A, D]`` (detection box outside the area range — an
    unmatched such detection is ignored, reference map.py:432-438) and
    ``npig [U, A]`` (number of unignored ground truths, map.py:640).

    Matching semantics follow reference ``_find_best_gt_match``
    (map.py:447-476): per IoU threshold, each detection (score-descending)
    takes the argmax-IoU ground truth among those not yet matched and not
    ignored, iff that IoU strictly exceeds the threshold.  Ignored ground
    truths (area outside range) are never matchable, and a matched detection
    therefore never inherits an ignore flag.
    """
    U, D, _ = det_boxes.shape
    G = gt_boxes.shape[1]
    A = area_ranges.shape[0]
    T = iou_thresholds.shape[0]

    gt_areas = box_area(gt_boxes)  # [U, G]
    lo = area_ranges[None, :, 0, None]  # [1, A, 1]
    hi = area_ranges[None, :, 1, None]
    gt_area_out = (gt_areas[:, None, :] < lo) | (gt_areas[:, None, :] > hi)  # [U, A, G]
    gt_ignore = gt_area_out | ~gt_valid[:, None, :]
    npig = jnp.sum(gt_valid[:, None, :] & ~gt_area_out, axis=-1).astype(jnp.int32)  # [U, A]

    det_areas = box_area(det_boxes)  # [U, D]
    det_area_out = (det_areas[:, None, :] < lo) | (det_areas[:, None, :] > hi)  # [U, A, D]

    # measured dispatch (ops/box_iou_pallas.py): the batched Pallas unit-tile
    # kernel when unit density earns it on TPU, the XLA broadcast otherwise
    ious = box_iou_dispatch(det_boxes, gt_boxes)  # [U, D, G]
    ious = ious * (det_valid[:, :, None] & gt_valid[:, None, :])

    def body(d: int, carry: Tuple[Array, Array]) -> Tuple[Array, Array]:
        gt_matched, det_matches = carry  # [U, A, T, G], [U, A, T, D]
        iou_d = jax.lax.dynamic_index_in_dim(ious, d, axis=1, keepdims=False)  # [U, G]
        blocked = gt_matched | gt_ignore[:, :, None, :]  # [U, A, T, G]
        cand = iou_d[:, None, None, :] * (~blocked)
        best = jnp.max(cand, axis=-1)  # [U, A, T]
        m = jnp.argmax(cand, axis=-1)
        ok = best > iou_thresholds[None, None, :]
        gt_matched = gt_matched | (jax.nn.one_hot(m, G, dtype=bool) & ok[..., None])
        det_matches = det_matches.at[:, :, :, d].set(ok)
        return gt_matched, det_matches

    init = (
        jnp.zeros((U, A, T, G), dtype=bool),
        jnp.zeros((U, A, T, D), dtype=bool),
    )
    _, det_matches = jax.lax.fori_loop(0, D, body, init)
    return det_matches, det_area_out, npig


def _pack_bool_bits(x: Array) -> Array:
    """Pack a trailing bool axis into little-endian uint8 bytes (in-jit)."""
    d = x.shape[-1]
    padded = -(-d // 8) * 8
    x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, padded - d)])
    x = x.reshape(x.shape[:-1] + (padded // 8, 8))
    weights = (2 ** jnp.arange(8, dtype=jnp.uint32)).astype(jnp.uint32)
    return jnp.sum(x.astype(jnp.uint32) * weights, axis=-1).astype(jnp.uint8)


def _unpack_bool_bits(packed: np.ndarray, d: int) -> np.ndarray:
    bits = np.unpackbits(np.asarray(packed), axis=-1, bitorder="little")
    return bits[..., :d].astype(bool)


@jax.jit
def _match_units_kernel_packed(
    det_boxes: Array,
    det_valid: Array,
    gt_boxes: Array,
    gt_valid: Array,
    iou_thresholds: Array,
    area_ranges: Array,
) -> Tuple[Array, Array, Array]:
    """Matching kernel with bit-packed boolean outputs: the ``[U, A, T, D]``
    match matrix dominates the device->host transfer (8x smaller as bytes,
    which matters on hosts where the accelerator link is the bottleneck)."""
    det_matches, det_area_out, npig = _match_units_kernel(
        det_boxes, det_valid, gt_boxes, gt_valid, iou_thresholds, area_ranges
    )
    return _pack_bool_bits(det_matches), _pack_bool_bits(det_area_out), npig


# ---------------------------------------------------------------------------
# host packing
# ---------------------------------------------------------------------------
class _PackedUnits(NamedTuple):
    """Static-shape evaluation units plus per-unit host metadata."""

    det_boxes: np.ndarray  # [U, D, 4]
    det_valid: np.ndarray  # [U, D]
    gt_boxes: np.ndarray  # [U, G, 4]
    gt_valid: np.ndarray  # [U, G]
    scores: np.ndarray  # [U, D] score-descending, padding = -inf
    unit_class: np.ndarray  # [U] index into the classes list
    n_det: np.ndarray  # [U]


def _bucket(n: int) -> int:
    """Round up to a power of two (min 1) to bound jit recompilations."""
    return 1 << max(int(n) - 1, 0).bit_length()


def _pack_units_loop(
    det_boxes: Sequence[np.ndarray],
    det_scores: Sequence[np.ndarray],
    det_labels: Sequence[np.ndarray],
    gt_boxes: Sequence[np.ndarray],
    gt_labels: Sequence[np.ndarray],
    classes: Sequence[int],
    max_det: int,
) -> Optional[_PackedUnits]:
    """Build padded ``(image, class)`` evaluation units.

    A unit exists for image *i*, class *c* iff the image has at least one
    detection AND at least one ground truth overall, and at least one of
    them is of class *c* — the exact skip conditions of reference
    ``_evaluate_image`` (map.py:391-396).
    """
    units = []  # (img, class_idx, det_idx_sorted, gt_idx)
    for i in range(len(gt_boxes)):
        dl = det_labels[i]
        gl = gt_labels[i]
        if len(dl) == 0 or len(gl) == 0:
            # reference map.py:391-392: images with no detections at all or
            # no ground truths at all contribute nothing for any class
            continue
        for k, c in enumerate(classes):
            det_idx = np.flatnonzero(dl == c)
            gt_idx = np.flatnonzero(gl == c)
            if len(det_idx) == 0 and len(gt_idx) == 0:
                continue
            if len(det_idx):
                order = np.argsort(-det_scores[i][det_idx], kind="stable")
                det_idx = det_idx[order][:max_det]
            units.append((i, k, det_idx, gt_idx))

    if not units:
        return None

    D = _bucket(max((len(u[2]) for u in units), default=1) or 1)
    G = _bucket(max((len(u[3]) for u in units), default=1) or 1)
    U = len(units)

    p_det = np.zeros((U, D, 4), np.float32)
    p_det_valid = np.zeros((U, D), bool)
    p_gt = np.zeros((U, G, 4), np.float32)
    p_gt_valid = np.zeros((U, G), bool)
    p_scores = np.full((U, D), -np.inf, np.float64)
    p_class = np.zeros((U,), np.int64)
    p_ndet = np.zeros((U,), np.int64)

    for u, (i, k, det_idx, gt_idx) in enumerate(units):
        nd, ng = len(det_idx), len(gt_idx)
        if nd:
            p_det[u, :nd] = det_boxes[i][det_idx]
            p_det_valid[u, :nd] = True
            p_scores[u, :nd] = det_scores[i][det_idx]
        if ng:
            p_gt[u, :ng] = gt_boxes[i][gt_idx]
            p_gt_valid[u, :ng] = True
        p_class[u] = k
        p_ndet[u] = nd

    return _PackedUnits(p_det, p_det_valid, p_gt, p_gt_valid, p_scores, p_class, p_ndet)



def _pack_units(
    det_boxes: Sequence[np.ndarray],
    det_scores: Sequence[np.ndarray],
    det_labels: Sequence[np.ndarray],
    gt_boxes: Sequence[np.ndarray],
    gt_labels: Sequence[np.ndarray],
    classes: Sequence[int],
    max_det: int,
) -> Optional[_PackedUnits]:
    """Vectorized unit packing (same output as ``_pack_units_loop``).

    One global lexsort of all detections by (image, class, -score) and one of
    all ground truths by (image, class) replace the per-image/per-class
    Python loops; unit order (image-major, class-minor) and within-unit
    tie order are preserved exactly, which matters because the PR
    reduction's mergesort tie-breaking follows unit order.
    """
    n_imgs = len(gt_boxes)
    class_arr = np.asarray(list(classes), dtype=np.int64)
    num_classes = len(class_arr)
    if n_imgs == 0 or num_classes == 0:
        return None

    # images contributing anything: >=1 detection AND >=1 ground truth
    has_det = np.array([len(l) > 0 for l in det_labels], bool)
    has_gt = np.array([len(l) > 0 for l in gt_labels], bool)
    keep_img = has_det & has_gt
    if not keep_img.any():
        return None

    def _flatten(boxes_seq, labels_seq, scores_seq=None):
        imgs, boxes, labels, scores = [], [], [], []
        for i in np.flatnonzero(keep_img):
            n = len(labels_seq[i])
            imgs.append(np.full(n, i, np.int64))
            boxes.append(np.asarray(boxes_seq[i], np.float32).reshape(n, 4))
            labels.append(np.asarray(labels_seq[i], np.int64).reshape(n))
            if scores_seq is not None:
                scores.append(np.asarray(scores_seq[i], np.float64).reshape(n))
        return (
            np.concatenate(imgs),
            np.concatenate(boxes),
            np.concatenate(labels),
            np.concatenate(scores) if scores_seq is not None else None,
        )

    d_img, d_box, d_label, d_score = _flatten(det_boxes, det_labels, det_scores)
    g_img, g_box, g_label, _ = _flatten(gt_boxes, gt_labels)

    d_cls = np.searchsorted(class_arr, d_label)
    g_cls = np.searchsorted(class_arr, g_label)

    # stable global sorts: detections by (img, class, -score), gts by (img, class)
    d_order = np.lexsort((-d_score, d_cls, d_img))
    d_img, d_box, d_cls, d_score = d_img[d_order], d_box[d_order], d_cls[d_order], d_score[d_order]
    g_order = np.lexsort((g_cls, g_img))
    g_img, g_box, g_cls = g_img[g_order], g_box[g_order], g_cls[g_order]

    # unit ids: unique (img, class) keys over BOTH sides, image-major order
    d_key = d_img * num_classes + d_cls
    g_key = g_img * num_classes + g_cls
    unit_keys = np.unique(np.concatenate([d_key, g_key]))
    U = len(unit_keys)
    d_unit = np.searchsorted(unit_keys, d_key)
    g_unit = np.searchsorted(unit_keys, g_key)

    def _ranks(unit_ids):
        """Position of each element within its (sorted, contiguous) unit run."""
        n = len(unit_ids)
        if n == 0:
            return np.zeros(0, np.int64)
        pos = np.arange(n)
        start = np.zeros(n, np.int64)
        new_run = np.flatnonzero(np.diff(unit_ids)) + 1
        start[new_run] = new_run
        return pos - np.maximum.accumulate(start)

    d_rank = _ranks(d_unit)
    keep = d_rank < max_det  # per-unit detection cap, score-descending
    d_unit_k, d_rank_k = d_unit[keep], d_rank[keep]
    g_rank = _ranks(g_unit)

    n_det = np.bincount(d_unit_k, minlength=U).astype(np.int64)
    n_gt = np.bincount(g_unit, minlength=U).astype(np.int64)
    D = max(_bucket(max(int(n_det.max()), 1)), 1)
    G = max(_bucket(max(int(n_gt.max()), 1)), 1)

    p_det = np.zeros((U, D, 4), np.float32)
    p_det_valid = np.zeros((U, D), bool)
    p_scores = np.full((U, D), -np.inf, np.float64)
    p_det[d_unit_k, d_rank_k] = d_box[keep]
    p_det_valid[d_unit_k, d_rank_k] = True
    p_scores[d_unit_k, d_rank_k] = d_score[keep]

    p_gt = np.zeros((U, G, 4), np.float32)
    p_gt_valid = np.zeros((U, G), bool)
    p_gt[g_unit, g_rank] = g_box
    p_gt_valid[g_unit, g_rank] = True

    p_class = (unit_keys % num_classes).astype(np.int64)
    return _PackedUnits(p_det, p_det_valid, p_gt, p_gt_valid, p_scores, p_class, n_det)


# ---------------------------------------------------------------------------
# host PR reduction (exact float64, reference map.py:608-672 semantics)
# ---------------------------------------------------------------------------
def _calculate_precision_recall(
    packed: _PackedUnits,
    det_matches: np.ndarray,  # [U, A, T, D] bool
    det_area_out: np.ndarray,  # [U, A, D] bool
    npig_units: np.ndarray,  # [U, A] int
    num_classes: int,
    num_areas: int,
    iou_thresholds: Sequence[float],
    rec_thresholds: Sequence[float],
    max_detection_thresholds: Sequence[int],
) -> Tuple[np.ndarray, np.ndarray]:
    """Integrate matches into the COCO precision/recall tables.

    Returns ``precision [T, R, K, A, M]`` and ``recall [T, K, A, M]``
    initialized to -1 (reference map.py:553-554). The per-cell reduction
    (sort, cumulate, zigzag, recall-grid projection) is the shared
    :func:`~metrics_tpu.functional.classification.sketch_curve.coco_precision_recall_grid`;
    this function only assembles each cell's scores/matches/ignore views.
    """
    from metrics_tpu.functional.classification.sketch_curve import (
        coco_precision_recall_grid,
    )

    T = len(iou_thresholds)
    R = len(rec_thresholds)
    M = len(max_detection_thresholds)
    rec_thrs = np.asarray(rec_thresholds, np.float64)

    precision = -np.ones((T, R, num_classes, num_areas, M))
    recall = -np.ones((T, num_classes, num_areas, M))

    # per-max_det validity masks over the padded det axis: element (u, d) is
    # live iff d < min(n_det[u], max_det). Boolean row-major indexing with
    # these masks reproduces the reference's per-unit concatenation order
    # (units ascending, then detection rank) without per-unit Python slicing.
    D = packed.scores.shape[1]
    det_rank = np.arange(D)[None, :]
    live_masks = [
        det_rank < np.minimum(packed.n_det, max_det)[:, None]
        for max_det in max_detection_thresholds
    ]

    for k in range(num_classes):
        sel = np.flatnonzero(packed.unit_class == k)
        if len(sel) == 0:
            continue
        scores_k = packed.scores[sel]  # [S, D]
        matches_k = det_matches[sel]  # [S, A, T, D]
        area_out_k = det_area_out[sel]  # [S, A, D]
        for a in range(num_areas):
            npig = int(npig_units[sel, a].sum())
            if npig == 0:
                continue  # reference map.py:641-642
            for mi, max_det in enumerate(max_detection_thresholds):
                live = live_masks[mi][sel]  # [S, D]
                scores = scores_k[live]  # [nd], unit-major order
                matches = np.moveaxis(matches_k[:, a], 1, 0)[:, live]  # [T, nd]
                ignore = (~matches) & area_out_k[:, a][live][None, :]
                prec_cell, rec_cell = coco_precision_recall_grid(
                    scores, matches, ignore, npig, rec_thrs
                )
                precision[:, :, k, a, mi] = prec_cell
                recall[:, k, a, mi] = rec_cell
    return precision, recall


def _summarize(
    precision: np.ndarray,  # [T, R, K, A, M]
    recall: np.ndarray,  # [T, K, A, M]
    avg_prec: bool,
    iou_thresholds: Sequence[float],
    iou_threshold: Optional[float] = None,
    area_idx: int = 0,
    mdet_idx: int = -1,
) -> float:
    """Mean of table entries > -1 for one (iou, area, maxdet) selection.

    Parity with reference ``_summarize`` (map.py:478-521).
    """
    vals = precision if avg_prec else recall
    if iou_threshold is not None:
        t = list(iou_thresholds).index(iou_threshold)
        vals = vals[t : t + 1]
    vals = vals[..., area_idx, mdet_idx]
    found = vals[vals > -1]
    return float(found.mean()) if found.size else -1.0
