"""RetrievalMetric base: grouped-by-query mean of a per-query metric.

Behavior parity with /root/reference/torchmetrics/retrieval/base.py:27-150:
cat-states ``indexes/preds/target``; compute = concat -> group by query id ->
per-group ``_metric`` -> mean; ``empty_target_action`` in neg/pos/skip/error.

The reference groups with a Python dict loop (utilities/data.py:244-253, a
known hot spot — SURVEY.md §3.6); here ``get_group_indexes`` sorts by query
id and splits segments (O(N log N) on device), and per-group evaluation
walks the segments host-side (exact-parity mode — data-dependent group
sizes are inherently host work; the subclass kernels themselves are
device ops).
"""
from abc import ABC, abstractmethod
from typing import Any, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.core.metric import Metric
from metrics_tpu.utils.checks import _check_retrieval_inputs
from metrics_tpu.utils.data import dim_zero_cat, get_group_indexes

Array = jax.Array


class RetrievalMetric(Metric, ABC):
    """Base class for retrieval metrics over (indexes, preds, target) triples."""

    higher_is_better = True
    __jit_unsafe__ = True  # grouping by query id has data-dependent shapes

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.allow_non_binary_target = False

        empty_target_action_options = ("error", "skip", "neg", "pos")
        if empty_target_action not in empty_target_action_options:
            raise ValueError(f"Argument `empty_target_action` received a wrong value `{empty_target_action}`.")
        self.empty_target_action = empty_target_action

        if ignore_index is not None and not isinstance(ignore_index, int):
            raise ValueError("Argument `ignore_index` must be an integer or None.")
        self.ignore_index = ignore_index

        self.add_state("indexes", default=[], dist_reduce_fx=None)
        self.add_state("preds", default=[], dist_reduce_fx=None)
        self.add_state("target", default=[], dist_reduce_fx=None)

    def _update(self, preds: Array, target: Array, indexes: Array) -> None:
        if indexes is None:
            raise ValueError("Argument `indexes` cannot be None")

        indexes, preds, target = _check_retrieval_inputs(
            indexes,
            preds,
            target,
            allow_non_binary_target=self.allow_non_binary_target,
            ignore_index=self.ignore_index,
        )

        self.indexes.append(indexes)
        self.preds.append(preds)
        self.target.append(target)

    def _group_empty(self, mini_target: Array) -> bool:
        """True if this query has no positive target (override to invert)."""
        return not bool(jnp.sum(mini_target))

    def _empty_error_message(self) -> str:
        return "`compute` method was provided with a query with no positive target."

    def _compute(self) -> Array:
        indexes = dim_zero_cat(self.indexes)
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)

        res = []
        groups = get_group_indexes(indexes)

        for group in groups:
            mini_preds = preds[group]
            mini_target = target[group]

            if self._group_empty(mini_target):
                if self.empty_target_action == "error":
                    raise ValueError(self._empty_error_message())
                if self.empty_target_action == "pos":
                    res.append(jnp.asarray(1.0))
                elif self.empty_target_action == "neg":
                    res.append(jnp.asarray(0.0))
            else:
                res.append(self._metric(mini_preds, mini_target))

        if res:
            return jnp.mean(jnp.stack([jnp.asarray(x, dtype=preds.dtype) for x in res]))
        return jnp.asarray(0.0, dtype=preds.dtype)

    @abstractmethod
    def _metric(self, preds: Array, target: Array) -> Array:
        """Compute the metric for a single query's documents."""
