"""Modular R2Score.

Behavior parity with /root/reference/torchmetrics/regression/r2.py:23-127.
"""
from typing import Any

import jax
import jax.numpy as jnp

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.regression.r2 import _r2_score_compute, _r2_score_update

Array = jax.Array


class R2Score(Metric):
    """Computes the R² score.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([3., -0.5, 2., 7.])
        >>> preds = jnp.array([2.5, 0.0, 2., 8.])
        >>> r2score = R2Score()
        >>> r2score(preds, target)
        Array(0.94860816, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = True

    def __init__(
        self,
        num_outputs: int = 1,
        adjusted: int = 0,
        multioutput: str = "uniform_average",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_outputs = num_outputs
        if adjusted < 0 or not isinstance(adjusted, int):
            raise ValueError("`adjusted` parameter should be an integer larger or equal to 0.")
        self.adjusted = adjusted
        allowed_multioutput = ("raw_values", "uniform_average", "variance_weighted")
        if multioutput not in allowed_multioutput:
            raise ValueError(
                f"Invalid input to argument `multioutput`. Choose one of the following: {allowed_multioutput}"
            )
        self.multioutput = multioutput

        zeros_shape = [] if num_outputs == 1 else [num_outputs]
        self.add_state("sum_squared_error", default=jnp.zeros(zeros_shape), dist_reduce_fx="sum")
        self.add_state("sum_error", default=jnp.zeros(zeros_shape), dist_reduce_fx="sum")
        self.add_state("residual", default=jnp.zeros(zeros_shape), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")

    def _update(self, preds: Array, target: Array) -> None:
        sum_squared_obs, sum_obs, rss, n_obs = _r2_score_update(preds, target)
        self.sum_squared_error = self.sum_squared_error + sum_squared_obs
        self.sum_error = self.sum_error + sum_obs
        self.residual = self.residual + rss
        self.total = self.total + n_obs

    def _compute(self) -> Array:
        return _r2_score_compute(
            self.sum_squared_error, self.sum_error, self.residual, self.total, self.adjusted, self.multioutput
        )
