"""SacreBLEU score (parity: /root/reference/torchmetrics/functional/text/sacre_bleu.py).

Tokenizer rules follow the sacrebleu project (mjpost/sacrebleu tokenizers):
13a (mteval-v13a), zh (CJK split + 13a), intl (mteval-v14 international),
char, none.  The ``intl`` tokenizer needs the third-party ``regex`` module
for unicode property classes (same optional gate as the reference).
"""
import re
from typing import Sequence, Union

import jax

from metrics_tpu.functional.text.bleu import _bleu_score_compute, _bleu_score_update
from metrics_tpu.utils.imports import _package_available

Array = jax.Array

_REGEX_AVAILABLE = _package_available("regex")

AVAILABLE_TOKENIZERS = ("none", "13a", "zh", "intl", "char")

_UCODE_RANGES = (
    ("\u3400", "\u4db5"),  # CJK Unified Ideographs Extension A
    ("\u4e00", "\u9fa5"),  # CJK Unified Ideographs
    ("\u9fa6", "\u9fbb"),
    ("\uf900", "\ufa2d"),  # CJK Compatibility Ideographs
    ("\ufa30", "\ufa6a"),
    ("\ufa70", "\ufad9"),
    # CJK Extension B / Compatibility Supplement — written with the 4-digit
    # \u escape exactly as sacrebleu (and the reference) write them, which
    # Python parses as TWO-char strings (' ' + '0', ...): the
    # lexicographic comparison then ALSO classifies single chars in
    # (U+2000, U+2A6D] and (U+2F80, U+2FA1] — general punctuation and
    # currency symbols like '€' — as Chinese in zh mode. Deliberately
    # reproduced for observable tokenizer parity with sacrebleu/the
    # reference (the unicode-correct \U00020000 form diverges from both;
    # pinned by tests/text/test_stored_oracle.py's zh grid rows).
    ("\u2000" "0", "\u2a6d" "6"),
    ("\u2f80" "0", "\u2fa1" "d"),
    ("\uff00", "\uffef"),  # full-width ASCII / half-width kana
    ("\u2e80", "\u2eff"),  # CJK Radicals Supplement
    ("\u3000", "\u303f"),  # CJK punctuation
    ("\u31c0", "\u31ef"),  # CJK strokes
    ("\u2f00", "\u2fdf"),  # Kangxi Radicals
    ("\u2ff0", "\u2fff"),  # character structure
    ("\u3100", "\u312f"),  # phonetic symbols
    ("\u31a0", "\u31bf"),
    ("\ufe10", "\ufe1f"),
    ("\ufe30", "\ufe4f"),
    ("\u2600", "\u26ff"),
    ("\u2700", "\u27bf"),
    ("\u3200", "\u32ff"),
    ("\u3300", "\u33ff"),
)


class _SacreBLEUTokenizer:
    """Line tokenizers for SacreBLEU (reference sacre_bleu.py:80-276)."""

    _REGEX = (
        (re.compile(r"([\{-\~\[-\` -\&\(-\+\:-\@\/])"), r" \1 "),
        (re.compile(r"([^0-9])([\.,])"), r"\1 \2 "),
        (re.compile(r"([\.,])([^0-9])"), r" \1 \2"),
        (re.compile(r"([0-9])(-)"), r"\1 \2 "),
    )

    if _REGEX_AVAILABLE:
        import regex

        _INT_REGEX = (
            (regex.compile(r"(\P{N})(\p{P})"), r"\1 \2 "),
            (regex.compile(r"(\p{P})(\P{N})"), r" \1 \2"),
            (regex.compile(r"(\p{S})"), r" \1 "),
        )

    _TOKENIZE_FN = {
        "none": "_tokenize_base",
        "13a": "_tokenize_13a",
        "zh": "_tokenize_zh",
        "intl": "_tokenize_international",
        "char": "_tokenize_char",
    }

    def __init__(self, tokenize: str, lowercase: bool = False) -> None:
        self.tokenize_fn = getattr(self, self._TOKENIZE_FN[tokenize])
        self.lowercase = lowercase

    def __call__(self, line: str) -> Sequence[str]:
        tokenized_line = self.tokenize_fn(line)
        return self._lower(tokenized_line, self.lowercase).split()

    @classmethod
    def tokenize(cls, line: str, tokenize: str, lowercase: bool = False) -> Sequence[str]:
        tokenize_fn = getattr(cls, cls._TOKENIZE_FN[tokenize])
        return cls._lower(tokenize_fn(line), lowercase).split()

    @classmethod
    def _tokenize_regex(cls, line: str) -> str:
        for _re, repl in cls._REGEX:
            line = _re.sub(repl, line)
        return " ".join(line.split())

    @staticmethod
    def _is_chinese_char(uchar: str) -> bool:
        return any(start <= uchar <= end for start, end in _UCODE_RANGES)

    @classmethod
    def _tokenize_base(cls, line: str) -> str:
        return line

    @classmethod
    def _tokenize_13a(cls, line: str) -> str:
        line = line.replace("<skipped>", "")
        line = line.replace("-\n", "")
        line = line.replace("\n", " ")
        if "&" in line:
            line = line.replace("&quot;", '"')
            line = line.replace("&amp;", "&")
            line = line.replace("&lt;", "<")
            line = line.replace("&gt;", ">")
        # mteval v13a applies the punctuation regexes to the SPACE-PADDED
        # line (sacrebleu Tokenizer13a: `self._post_tokenizer(f' {line} ')`),
        # so a sentence-final period after a digit still splits: '04.' ->
        # '04 .'. The zh tokenizer shares the regexes but does NOT pad.
        return cls._tokenize_regex(f" {line} ")

    @classmethod
    def _tokenize_zh(cls, line: str) -> str:
        line = line.strip()
        line_in_chars = ""
        for char in line:
            if cls._is_chinese_char(char):
                line_in_chars += " " + char + " "
            else:
                line_in_chars += char
        return cls._tokenize_regex(line_in_chars)

    @classmethod
    def _tokenize_international(cls, line: str) -> str:
        for _re, repl in cls._INT_REGEX:
            line = _re.sub(repl, line)
        return " ".join(line.split())

    @classmethod
    def _tokenize_char(cls, line: str) -> str:
        return " ".join(char for char in line)

    @staticmethod
    def _lower(line: str, lowercase: bool) -> str:
        return line.lower() if lowercase else line


def sacre_bleu_score(
    preds: Sequence[str],
    target: Sequence[Sequence[str]],
    n_gram: int = 4,
    smooth: bool = False,
    tokenize: str = "13a",
    lowercase: bool = False,
) -> Array:
    """Calculate BLEU with sacrebleu-compatible tokenization.

    Example:
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
        >>> sacre_bleu_score(preds, target)  # doctest: +ELLIPSIS
        Array(0.7598..., dtype=float32)
    """
    if tokenize not in AVAILABLE_TOKENIZERS:
        raise ValueError(f"Argument `tokenize` expected to be one of {AVAILABLE_TOKENIZERS} but got {tokenize}.")
    if tokenize == "intl" and not _REGEX_AVAILABLE:
        raise ModuleNotFoundError(
            "`'intl'` tokenization requires that `regex` is installed. Use `pip install regex`."
        )
    if len(preds) != len(target):
        raise ValueError(f"Corpus has different size {len(preds)} != {len(target)}")

    import numpy as np

    numerator = np.zeros(n_gram)
    denominator = np.zeros(n_gram)
    tokenize_fn = _SacreBLEUTokenizer(tokenize, lowercase)
    preds_len, target_len = _bleu_score_update(
        preds, target, numerator, denominator, 0.0, 0.0, n_gram, tokenize_fn
    )
    return _bleu_score_compute(preds_len, target_len, numerator, denominator, n_gram, smooth)
