"""Declarative health/SLO engine over the windowed telemetry series.

The time-series layer (:mod:`metrics_tpu.observability.timeseries`) answers
"what is the p99 / rate / max over the last N seconds"; this module turns
those answers into an operational verdict: a rule set is evaluated against
the registry and produces a typed :class:`HealthSnapshot` —
``ok``/``warn``/``critical`` plus the exact alarms firing — exported as
Prometheus families, appended to a JSONL alarm log on every transition,
and renderable as a terminal summary (:func:`render_health`).

Three rule shapes cover the standard serving-loop failure modes:

* :class:`ThresholdRule` — a windowed statistic (``p50``/``p95``/``p99``/
  ``mean``/``max``/``min``/``rate``/``count``) of one series compared
  against a bound. Backs the queue-saturation, staleness, recompile-storm,
  sketch-fill-ceiling, and hot-slice-skew alarms.
* :class:`BurnRateRule` — multiwindow SLO burn: the ratio of a "bad"
  counter to a "total" counter (e.g. dropped / offered batches) is
  compared to an error budget over a short AND a long window; the alarm
  fires only when both burn rates exceed the threshold, the standard
  fast-burn page condition (short window catches the spike, long window
  filters blips). Backs the drop-rate alarm.
* :class:`DriftRule` — a reference-vs-live distribution comparison: a
  window of a distribution series is frozen as the reference, and later
  windows histogram over the same static edges and score against it
  (PSI / KL / JS / TV — :mod:`metrics_tpu.observability.drift`). Backs
  the score-drift alarm, the "is the MODEL healthy" complement to the
  pipeline alarms above.

:func:`default_rules` wires the thirteen standard alarm classes — seven
serving-loop classes, the three fleet-collector classes
(``publisher_stale``/``snapshot_backlog``/``fold_error``), the
read-path freshness class (``freshness_slo``, with its ``read_latency``
companion), and the two memory-observatory classes
(:class:`MemoryBudget`/:class:`MemoryLeak`) — over the standard series
names the recorder feeds
(``SERIES_*`` in ``recorder.py``); every threshold is a keyword so
deployments tune rather than reimplement. ``examples/serving_loop.py`` drives the serving layer
and ``examples/fleet_collector.py`` the fleet layer under fault
injection. See docs/observability.md for the rule reference.
"""
from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from metrics_tpu.observability.recorder import (
    _DEFAULT_RECORDER,
    SERIES_ASYNC_DROPPED,
    SERIES_ASYNC_ENQUEUED,
    SERIES_ASYNC_QUEUE_DEPTH,
    SERIES_ASYNC_STALENESS,
    SERIES_COLLECTOR_BACKLOG,
    SERIES_FOLD_ERRORS,
    SERIES_FRESHNESS_AGE_S,
    SERIES_HOT_SLICE_SHARE,
    SERIES_MEM_BYTES_PER_TENANT,
    SERIES_MEM_UNACCOUNTED,
    SERIES_PUBLISHER_LAG,
    SERIES_READ_MS,
    SERIES_RECOMPILES,
    SERIES_SCORES,
    SERIES_SKETCH_FILL,
)

__all__ = [
    "AlarmState",
    "BurnRateRule",
    "DriftRule",
    "HealthMonitor",
    "HealthSnapshot",
    "MemoryBudget",
    "MemoryLeak",
    "Rule",
    "ThresholdRule",
    "default_rules",
    "render_health",
]

#: snapshot statuses in escalation order
STATUSES = ("ok", "warn", "critical")

#: accepted rule severities (a firing critical rule makes the snapshot
#: critical; warn rules cap at warn)
SEVERITIES = ("warn", "critical")

#: windowed statistics ThresholdRule understands; pNN spellings map onto
#: the sketch quantile query
_STATS = ("p50", "p90", "p95", "p99", "mean", "max", "min", "rate", "count", "total")

_OPS = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
}


class Rule:
    """One health rule: a name, a severity, and an ``evaluate`` returning
    ``(firing, observed_value, detail)``. Subclass to add shapes beyond
    threshold/burn-rate; the monitor only needs this interface."""

    def __init__(self, name: str, severity: str = "warn", description: str = "") -> None:
        if severity not in SEVERITIES:
            raise ValueError(f"severity must be one of {SEVERITIES}, got {severity!r}")
        self.name = name
        self.severity = severity
        self.description = description

    def evaluate(self, registry: Any, now: Optional[float] = None) -> Tuple[bool, Optional[float], str]:
        raise NotImplementedError

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.name!r}, severity={self.severity!r})"


class ThresholdRule(Rule):
    """Fire when a windowed statistic of one series crosses a bound.

    ``stat`` is one of ``p50/p90/p95/p99`` (sketch quantiles), ``mean``/
    ``max``/``min`` (scalar aggregates), ``rate`` (summed values per
    second), ``count``, or ``total``. An empty window (or an absent
    series) never fires — silence is not an alarm; pair with a liveness
    rule if silence should page. ``min_count`` suppresses firing until
    the window holds at least that many observations (quantiles of three
    points are noise, not signal)."""

    def __init__(
        self,
        name: str,
        series: str,
        stat: str,
        threshold: float,
        window_s: float = 30.0,
        op: str = ">",
        severity: str = "warn",
        min_count: int = 1,
        description: str = "",
    ) -> None:
        super().__init__(name, severity=severity, description=description)
        if stat not in _STATS:
            raise ValueError(f"stat must be one of {_STATS}, got {stat!r}")
        if op not in _OPS:
            raise ValueError(f"op must be one of {sorted(_OPS)}, got {op!r}")
        if window_s <= 0:
            raise ValueError(f"window_s must be positive, got {window_s}")
        self.series = series
        self.stat = stat
        self.threshold = float(threshold)
        self.window_s = float(window_s)
        self.op = op
        self.min_count = int(min_count)

    def evaluate(self, registry: Any, now: Optional[float] = None) -> Tuple[bool, Optional[float], str]:
        s = registry.get(self.series) if registry is not None else None
        if s is None:
            return False, None, f"series `{self.series}` absent"
        n = s.count(self.window_s, now=now)
        if n < self.min_count:
            return False, None, f"only {n} observation(s) in window"
        if self.stat.startswith("p"):
            value = s.quantile(int(self.stat[1:]) / 100.0, window_s=self.window_s, now=now)
        elif self.stat == "mean":
            value = s.mean(self.window_s, now=now)
        elif self.stat == "max":
            value = s.value_max(self.window_s, now=now)
        elif self.stat == "min":
            value = s.value_min(self.window_s, now=now)
        elif self.stat == "rate":
            value = s.rate(self.window_s, now=now)
        elif self.stat == "total":
            value = s.total(self.window_s, now=now)
        else:  # count
            value = float(n)
        if value is None:
            return False, None, "empty window"
        firing = _OPS[self.op](value, self.threshold)
        return (
            bool(firing),
            float(value),
            f"{self.stat}({self.series}, {self.window_s:g}s) = {value:.4g} {self.op} {self.threshold:g}",
        )


class BurnRateRule(Rule):
    """Multiwindow SLO burn-rate alarm over counter series.

    The error ratio ``sum(bad) / sum(total)`` is measured over a short and
    a long window; each is divided by the error ``budget`` (the SLO's
    allowed ratio) to get a burn rate, and the alarm fires when BOTH
    exceed ``burn_threshold`` — the standard fast-burn condition: the
    short window reacts within seconds, the long window keeps a single
    bad bucket from paging. ``denominator`` may be several series (their
    totals add), e.g. offered batches = accepted + dropped."""

    def __init__(
        self,
        name: str,
        numerator: str,
        denominator: Union[str, Sequence[str]],
        budget: float,
        short_window_s: float = 10.0,
        long_window_s: float = 60.0,
        burn_threshold: float = 1.0,
        severity: str = "critical",
        min_total: int = 1,
        description: str = "",
    ) -> None:
        super().__init__(name, severity=severity, description=description)
        if not (0 < budget < 1):
            raise ValueError(f"budget must be a ratio in (0, 1), got {budget}")
        if short_window_s >= long_window_s:
            raise ValueError("short_window_s must be smaller than long_window_s")
        self.numerator = numerator
        self.denominator = (denominator,) if isinstance(denominator, str) else tuple(denominator)
        self.budget = float(budget)
        self.short_window_s = float(short_window_s)
        self.long_window_s = float(long_window_s)
        self.burn_threshold = float(burn_threshold)
        self.min_total = int(min_total)

    def _burn(self, registry: Any, window_s: float, now: Optional[float]) -> Optional[float]:
        num_series = registry.get(self.numerator)
        bad = num_series.total(window_s, now=now) if num_series is not None else 0.0
        total = bad
        for name in self.denominator:
            s = registry.get(name)
            if s is not None and s is not num_series:
                total += s.total(window_s, now=now)
        if total < self.min_total:
            return None
        return (bad / total) / self.budget

    def evaluate(self, registry: Any, now: Optional[float] = None) -> Tuple[bool, Optional[float], str]:
        if registry is None:
            return False, None, "no registry"
        short = self._burn(registry, self.short_window_s, now)
        long_ = self._burn(registry, self.long_window_s, now)
        if short is None or long_ is None:
            return False, None, "no traffic in window"
        firing = short >= self.burn_threshold and long_ >= self.burn_threshold
        return (
            bool(firing),
            float(short),
            f"burn {self.short_window_s:g}s={short:.2f}x, {self.long_window_s:g}s={long_:.2f}x"
            f" of budget {self.budget:g} (threshold {self.burn_threshold:g}x)",
        )


class DriftRule(Rule):
    """Fire when a distribution series drifts from its frozen reference
    window (the seventh standard alarm class).

    The rule watches a ``"distribution"`` series (by default the sampled
    model scores serving loops feed via ``record_scores``). Evaluation has
    two phases:

    1. **Reference capture** — until the series has accumulated
       ``freeze_after`` observations inside ``reference_window_s``, the
       rule never fires (detail: "collecting reference"). At that point
       the window's merged sketch is FROZEN as the reference: static
       histogram edges are derived from it once
       (:func:`~metrics_tpu.observability.drift.reference_edges`, unless
       explicit ``edges`` were passed) and its binned histogram is kept.
    2. **Live comparison** — every later evaluation histograms the
       trailing ``window_s`` sketch over the SAME edges and scores it
       against the reference with ``stat`` (``psi``/``kl``/``js``/``tv``
       — see :mod:`metrics_tpu.observability.drift`), firing when the
       score crosses ``threshold``. Scores also land on the default
       recorder as ``metrics_tpu_drift_score{metric,stat}`` gauges.

    The reference stays frozen until :meth:`reset_reference` (or a new
    rule) — drift is measured against *then*, not against a sliding
    yesterday that would normalize a slow regression away. An absent
    series never fires, like every other rule.
    """

    def __init__(
        self,
        name: str,
        series: str = SERIES_SCORES,
        stat: str = "psi",
        threshold: float = 0.25,
        window_s: float = 30.0,
        reference_window_s: Optional[float] = None,
        freeze_after: int = 200,
        n_bins: int = 10,
        min_count: int = 20,
        edges: Optional[Any] = None,
        severity: str = "warn",
        description: str = "",
        recorder: Optional[Any] = None,
    ) -> None:
        super().__init__(name, severity=severity, description=description)
        #: recorder the drift-score gauges land on; None = inherit the
        #: monitor's recorder (HealthMonitor injects its override at
        #: construction, like every other health family), falling back to
        #: the process default
        self.recorder = recorder
        from metrics_tpu.observability.drift import DRIFT_STATS

        if stat not in DRIFT_STATS:
            raise ValueError(f"stat must be one of {DRIFT_STATS}, got {stat!r}")
        if window_s <= 0:
            raise ValueError(f"window_s must be positive, got {window_s}")
        if not isinstance(freeze_after, int) or freeze_after < 1:
            raise ValueError(f"freeze_after must be a positive int, got {freeze_after!r}")
        if not isinstance(n_bins, int) or n_bins < 2:
            raise ValueError(f"n_bins must be an int >= 2, got {n_bins!r}")
        self.series = series
        self.stat = stat
        self.threshold = float(threshold)
        self.window_s = float(window_s)
        self.reference_window_s = float(
            reference_window_s if reference_window_s is not None else window_s
        )
        self.freeze_after = int(freeze_after)
        self.n_bins = int(n_bins)
        self.min_count = int(min_count)
        self._edges = edges
        self._ref_hist: Optional[Any] = None
        #: serializes reference mutation: the monitor lock covers evaluate(),
        #: but freeze_reference() is a direct caller API (the serving loop's
        #: phase boundary) racing the exporter tick's auto-freeze — without
        #: this, two concurrent freezes can bin the reference over one
        #: thread's edges and keep the OTHER thread's edges for live
        #: comparisons, a permanently wrong score with no error
        self._freeze_lock = threading.Lock()

    def reset_reference(self) -> None:
        """Drop the frozen reference; the next evaluations re-capture it
        (an intentional re-baseline after a model push)."""
        with self._freeze_lock:
            self._ref_hist = None
            # edges re-derive with the new reference unless explicit
            if getattr(self, "_edges_derived", False):
                self._edges = None

    def freeze_reference(self, registry: Any, now: Optional[float] = None) -> bool:
        """Freeze the reference from the CURRENT reference window,
        bypassing the ``freeze_after`` count gate — for callers that know
        their own phase boundaries (a serving loop freezing at the end of
        a known-healthy warmup) instead of trusting traffic-rate timing:
        the count-gated auto-freeze can land inside a fault window when
        early traffic crawls through cold caches, silently baselining on
        the very distribution the rule exists to catch. Returns True when
        a reference was (already or newly) frozen; no-op on an absent
        series or an empty window (the auto path remains)."""
        if self._ref_hist is not None:
            return True
        s = registry.get(self.series) if registry is not None else None
        if s is None:
            return False
        sketch = s.window_sketch(self.reference_window_s, now=now)
        if sketch is None:
            return False
        self._freeze(sketch)
        return True

    def _freeze(self, sketch: Any) -> None:
        import jax.numpy as jnp

        from metrics_tpu.observability.drift import reference_edges
        from metrics_tpu.sketches.quantile import qsketch_histogram

        with self._freeze_lock:
            if self._ref_hist is not None:
                return  # another thread froze first: first freeze wins whole
            if self._edges is None:
                self._edges = reference_edges(sketch, n_bins=self.n_bins)
                self._edges_derived = True
            self._ref_hist = qsketch_histogram(
                jnp.asarray(sketch), jnp.asarray(self._edges, jnp.float32)
            )

    def evaluate(self, registry: Any, now: Optional[float] = None) -> Tuple[bool, Optional[float], str]:
        s = registry.get(self.series) if registry is not None else None
        if s is None:
            return False, None, f"series `{self.series}` absent"
        with self._freeze_lock:
            ref_hist, edges = self._ref_hist, self._edges
        if ref_hist is None:
            n_ref = s.count(self.reference_window_s, now=now)
            if n_ref < self.freeze_after:
                return False, None, f"collecting reference ({n_ref}/{self.freeze_after})"
            sketch = s.window_sketch(self.reference_window_s, now=now)
            if sketch is None:
                return False, None, "reference window holds no mass yet"
            self._freeze(sketch)
            return False, 0.0, f"reference frozen over {self.reference_window_s:g}s"
        n_live = s.count(self.window_s, now=now)
        if n_live < self.min_count:
            return False, None, f"only {n_live} live observation(s) in window"
        live = s.window_sketch(self.window_s, now=now)
        if live is None:
            return False, None, "empty live window"
        import jax.numpy as jnp

        from metrics_tpu.observability.drift import histogram_drift
        from metrics_tpu.sketches.quantile import qsketch_histogram

        # score against the SNAPSHOT pair read under the lock above — a
        # concurrent re-baseline cannot mix one reference's edges with
        # another's histogram mid-evaluation
        live_hist = qsketch_histogram(jnp.asarray(live), jnp.asarray(edges, jnp.float32))
        score = histogram_drift(ref_hist, live_hist)[self.stat]
        rec = self.recorder if self.recorder is not None else _DEFAULT_RECORDER
        if rec.enabled:
            rec.record_drift_score(self.series, self.stat, score)
        firing = score >= self.threshold
        return (
            bool(firing),
            float(score),
            f"{self.stat}({self.series}: frozen ref vs live {self.window_s:g}s)"
            f" = {score:.4g} >= {self.threshold:g}",
        )


class MemoryBudget(ThresholdRule):
    """Bytes/tenant ceiling on sliced (per-tenant) metric state — the
    twelfth standard alarm class.

    Watches the ``mem_bytes_per_tenant`` series the memory observatory
    (:class:`~metrics_tpu.observability.memory.MemoryObservatory`) feeds:
    the ledger's live SlicedMetric state bytes divided by the total slice
    (tenant) count. Firing means each tenant's state grew past the budget
    the deployment provisioned — the ROADMAP item-3 headline number going
    out of bounds, e.g. a window/sketch capacity misconfiguration
    multiplying per-tenant bytes. The threshold is a plain attribute, so
    capacity tooling can tighten it live (``rule.threshold = ...``)."""

    def __init__(
        self,
        limit_bytes_per_tenant: float,
        name: str = "memory_budget",
        window_s: float = 30.0,
        severity: str = "warn",
        min_count: int = 1,
        description: str = "per-tenant sliced state bytes exceeded the provisioned budget",
    ) -> None:
        super().__init__(
            name,
            SERIES_MEM_BYTES_PER_TENANT,
            stat="max",
            threshold=float(limit_bytes_per_tenant),
            window_s=window_s,
            op=">",
            severity=severity,
            min_count=min_count,
            description=description,
        )


class MemoryLeak(Rule):
    """Monotone unaccounted-bytes growth — the thirteenth standard alarm
    class, the "where did my HBM go" page.

    Watches the ``mem_unaccounted_bytes`` residue series
    (``device_in_use − ledger − cache planes``, fed by the memory
    observatory). Bytes the ledger and the cache planes can both explain
    are healthy; a residue that keeps GROWING is memory nobody accounts
    for — a pinned compute cache, a leaked buffer reference, a foreign
    allocation riding the device.

    The monotone test splits the window in half and fires when the
    *minimum* of the recent half exceeds the *maximum* of the prior half
    by more than ``growth_bytes`` — every recent sample above every older
    sample, so a noisy-but-flat residue (host-RSS jitter on CPU, allocator
    fragmentation) never fires, while steady growth of any shape does.
    An absent series (observatory not polling) never fires."""

    def __init__(
        self,
        growth_bytes: float = 128 * 1024 * 1024,
        name: str = "memory_leak",
        series: str = SERIES_MEM_UNACCOUNTED,
        window_s: float = 30.0,
        min_count: int = 4,
        severity: str = "warn",
        description: str = "unaccounted device bytes growing monotonically — likely leak",
    ) -> None:
        super().__init__(name, severity=severity, description=description)
        if window_s <= 0:
            raise ValueError(f"window_s must be positive, got {window_s}")
        self.series = series
        self.growth_bytes = float(growth_bytes)
        self.window_s = float(window_s)
        self.min_count = int(min_count)

    def evaluate(self, registry: Any, now: Optional[float] = None) -> Tuple[bool, Optional[float], str]:
        s = registry.get(self.series) if registry is not None else None
        if s is None:
            return False, None, f"series `{self.series}` absent"
        t = time.time() if now is None else float(now)
        n = s.count(self.window_s, now=t)
        if n < self.min_count:
            return False, None, f"only {n} observation(s) in window"
        half = self.window_s / 2.0
        prior_max = s.value_max(half, now=t - half)
        recent_min = s.value_min(half, now=t)
        if prior_max is None or recent_min is None:
            return False, None, "both window halves not yet populated"
        growth = float(recent_min) - float(prior_max)
        firing = growth > self.growth_bytes
        return (
            bool(firing),
            growth,
            f"min(recent {half:g}s) - max(prior {half:g}s) of {self.series}"
            f" = {growth:.4g} B (threshold {self.growth_bytes:g})",
        )


@dataclass(frozen=True)
class AlarmState:
    """One rule's state inside a snapshot."""

    name: str
    severity: str
    firing: bool
    value: Optional[float]
    detail: str
    fired_at: Optional[float] = None  # wall time the CURRENT firing episode began


@dataclass(frozen=True)
class HealthSnapshot:
    """Typed verdict of one health evaluation: overall status, every
    rule's state, and the exporter-error count (a stale-artifact signal is
    itself a health fact)."""

    status: str
    t: float
    alarms: Tuple[AlarmState, ...] = ()
    export_errors: int = 0

    @property
    def firing(self) -> Tuple[AlarmState, ...]:
        return tuple(a for a in self.alarms if a.firing)

    def to_json(self) -> Dict[str, Any]:
        return {
            "status": self.status,
            "t": self.t,
            "export_errors": self.export_errors,
            "alarms": [
                {
                    "name": a.name,
                    "severity": a.severity,
                    "firing": a.firing,
                    "value": a.value,
                    "detail": a.detail,
                    "fired_at": a.fired_at,
                }
                for a in self.alarms
            ],
        }


class HealthMonitor:
    """Evaluates a rule set against a time-series registry and tracks alarm
    transitions.

    ``evaluate()`` returns a :class:`HealthSnapshot`; each rule's
    fired/cleared transition is appended to the JSONL alarm log (when
    configured) and remembered in :meth:`transitions` — so
    "did every alarm class fire AND clear during this run" is a direct
    query (:meth:`fired_and_cleared`), which is exactly what the
    serving-loop fault-injection smoke asserts. Thread-safe: the
    :class:`~metrics_tpu.observability.exporters.PeriodicExporter` calls
    ``evaluate()`` from its tick thread while the serving loop polls."""

    #: transition-history cap — health evaluation must stay fixed-memory
    #: like everything else in the live layer
    MAX_TRANSITIONS = 10_000

    def __init__(
        self,
        rules: Sequence[Rule],
        registry: Optional[Any] = None,
        recorder: Optional[Any] = None,
        alarm_log_path: Optional[str] = None,
    ) -> None:
        names = [r.name for r in rules]
        dup = {n for n in names if names.count(n) > 1}
        if dup:
            raise ValueError(f"duplicate rule names: {sorted(dup)}")
        self.rules = list(rules)
        self._registry = registry
        self._recorder = recorder
        if recorder is not None:
            # recorder-aware rules (DriftRule's score gauges) inherit the
            # monitor's override unless they carry their own — the same
            # routing every other health family gets via _resolve
            for r in self.rules:
                if getattr(r, "recorder", "__absent__") is None:
                    r.recorder = recorder
        self.alarm_log_path = alarm_log_path
        self._lock = threading.Lock()
        #: serializes alarm-log appends — O_APPEND writes interleave at
        #: line granularity, but the rows of ONE evaluation must land as a
        #: contiguous block so concurrent evaluates (exporter tick thread +
        #: the serving loop's probe) read as coherent transitions
        self._log_lock = threading.Lock()
        self._fired_at: Dict[str, float] = {}
        self._transitions: List[Dict[str, Any]] = []
        self._last: Optional[HealthSnapshot] = None

    def _resolve_registry(self) -> Optional[Any]:
        if self._registry is not None:
            return self._registry
        rec = self._recorder if self._recorder is not None else _DEFAULT_RECORDER
        return rec.timeseries

    # ------------------------------------------------------------------
    # evaluation
    # ------------------------------------------------------------------
    def evaluate(self, now: Optional[float] = None) -> HealthSnapshot:
        registry = self._resolve_registry()
        rec = self._recorder if self._recorder is not None else _DEFAULT_RECORDER
        t = time.time() if now is None else float(now)
        alarms: List[AlarmState] = []
        new_transitions: List[Dict[str, Any]] = []
        with self._lock:
            for rule in self.rules:
                try:
                    firing, value, detail = rule.evaluate(registry, now=now)
                except Exception as err:  # noqa: BLE001 — one bad rule must not kill the sweep
                    firing, value, detail = False, None, f"rule evaluation failed: {err!r}"
                was = rule.name in self._fired_at
                if firing and not was:
                    self._fired_at[rule.name] = t
                    new_transitions.append(
                        {
                            "event": "fired",
                            "alarm": rule.name,
                            "severity": rule.severity,
                            "value": value,
                            "detail": detail,
                            "t": t,
                        }
                    )
                elif not firing and was:
                    fired_at = self._fired_at.pop(rule.name)
                    new_transitions.append(
                        {
                            "event": "cleared",
                            "alarm": rule.name,
                            "severity": rule.severity,
                            "value": value,
                            "duration_s": round(t - fired_at, 3),
                            "t": t,
                        }
                    )
                alarms.append(
                    AlarmState(
                        name=rule.name,
                        severity=rule.severity,
                        firing=firing,
                        value=value,
                        detail=detail,
                        fired_at=self._fired_at.get(rule.name),
                    )
                )
            self._transitions.extend(new_transitions)
            if len(self._transitions) > self.MAX_TRANSITIONS:
                self._transitions = self._transitions[-self.MAX_TRANSITIONS :]
            status = "ok"
            for a in alarms:
                if a.firing:
                    if a.severity == "critical":
                        status = "critical"
                        break
                    status = "warn"
            snap = HealthSnapshot(
                status=status,
                t=t,
                alarms=tuple(alarms),
                export_errors=rec.export_errors(),
            )
            self._last = snap
        if new_transitions and self.alarm_log_path:
            from metrics_tpu.observability.exporters import _atomic_append
            from metrics_tpu.utils.prints import _process_index

            if _process_index() == 0:
                try:
                    with self._log_lock:
                        _atomic_append(
                            self.alarm_log_path,
                            "".join(json.dumps(row) + "\n" for row in new_transitions),
                        )
                except Exception:  # noqa: BLE001 — the log is an artifact, not the source of truth
                    pass
        return snap

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------
    @property
    def last_snapshot(self) -> Optional[HealthSnapshot]:
        with self._lock:
            return self._last

    def transitions(self) -> List[Dict[str, Any]]:
        """Every fired/cleared transition observed so far (capped)."""
        with self._lock:
            return list(self._transitions)

    def fired_ever(self) -> List[str]:
        with self._lock:
            return sorted({r["alarm"] for r in self._transitions if r["event"] == "fired"})

    def fired_and_cleared(self) -> List[str]:
        """Alarm names that have both fired and subsequently cleared — the
        fault-injection smoke's acceptance query."""
        with self._lock:
            fired = {r["alarm"] for r in self._transitions if r["event"] == "fired"}
            cleared = {r["alarm"] for r in self._transitions if r["event"] == "cleared"}
        return sorted(fired & cleared)

    # ------------------------------------------------------------------
    # exports
    # ------------------------------------------------------------------
    def prometheus_lines(self, snapshot: Optional[HealthSnapshot] = None) -> List[str]:
        """The health families for the Prometheus page (appended by
        ``PeriodicExporter``/``render_prometheus`` when a monitor rides
        along): overall status as 0/1/2, one 0/1 firing gauge and one
        observed-value gauge per alarm."""
        snap = snapshot if snapshot is not None else self.last_snapshot
        if snap is None:
            return []
        from metrics_tpu.observability.exporters import _labels

        lines = [
            "# HELP metrics_tpu_health_status Overall health verdict (0=ok, 1=warn, 2=critical).",
            "# TYPE metrics_tpu_health_status gauge",
            f"metrics_tpu_health_status {STATUSES.index(snap.status)}",
            "# HELP metrics_tpu_alarm_firing Whether the alarm rule is currently firing.",
            "# TYPE metrics_tpu_alarm_firing gauge",
        ]
        for a in snap.alarms:
            lines.append(
                f"metrics_tpu_alarm_firing{_labels(alarm=a.name, severity=a.severity)}"
                f" {1 if a.firing else 0}"
            )
        lines.append("# HELP metrics_tpu_alarm_value Last observed value of the alarm rule's statistic.")
        lines.append("# TYPE metrics_tpu_alarm_value gauge")
        for a in snap.alarms:
            if a.value is not None:
                lines.append(f"metrics_tpu_alarm_value{_labels(alarm=a.name)} {a.value:g}")
        return lines


def render_health(snapshot: HealthSnapshot) -> str:
    """Terminal one-glance rendering of a snapshot: the status line, then
    one row per alarm (firing rows first)."""
    lines = [
        f"health: {snapshot.status.upper()}"
        f" ({len(snapshot.firing)}/{len(snapshot.alarms)} alarms firing,"
        f" {snapshot.export_errors} export errors)"
    ]
    for a in sorted(snapshot.alarms, key=lambda a: (not a.firing, a.name)):
        mark = "FIRING" if a.firing else "ok"
        lines.append(f"  [{mark:>6}] {a.name} ({a.severity}): {a.detail}")
    return "\n".join(lines)


def default_rules(
    queue_depth_limit: float = 4,
    staleness_limit_steps: float = 4,
    drop_budget: float = 0.01,
    drop_burn_threshold: float = 2.0,
    recompiles_per_window: float = 4,
    fill_ceiling: float = 0.9,
    hot_share_limit: float = 0.5,
    window_s: float = 30.0,
    short_window_s: Optional[float] = None,
    critical_queue_factor: float = 2.0,
    drift_threshold: float = 0.25,
    drift_freeze_after: int = 128,
    drift_stat: str = "psi",
    publisher_lag_limit_s: float = 30.0,
    backlog_limit: float = 64,
    fold_errors_per_window: float = 1,
    freshness_bound_s: float = 10.0,
    read_latency_limit_ms: float = 250.0,
    tenant_bytes_limit: float = 16 * 1024,
    unaccounted_growth_bytes: float = 128 * 1024 * 1024,
) -> List[Rule]:
    """The thirteen standard alarm classes — seven serving-loop classes,
    the three fleet-collector classes, the read-path freshness class
    (plus its ``read_latency`` companion), and the two memory-observatory
    classes — over the standard recorder-fed series, every threshold
    tunable:

    * ``queue_saturation`` (warn) / ``queue_saturation_critical`` — p95 /
      max of the async queue depth against the configured limit.
    * ``staleness`` — max compute-snapshot staleness in batches.
    * ``drop_rate`` — multiwindow burn of dropped vs offered batches
      against the ``drop_budget`` SLO.
    * ``recompile_storm`` — new-signature count per window.
    * ``sketch_fill`` — max sketch capacity-fill ratio against the
      ceiling (past it, compactions are imminent/ongoing and accuracy is
      being spent).
    * ``hot_slice_skew`` — p95 of the per-batch hottest-slice row share.
    * ``score_drift`` — PSI (by default) of the live score distribution
      against its frozen reference window (``record_scores`` feeds the
      series; absent when the loop never records scores — the rule then
      never fires, like any absent series).
    * ``publisher_stale`` — worst per-publisher snapshot lag seen at a
      fleet-collector poll against the staleness bound (a silent
      publisher's lag grows every poll; the collector feeds the series).
    * ``snapshot_backlog`` — unfolded snapshots at the collector (queued
      files + in-window pending deltas) against the backlog limit.
    * ``fold_error`` (critical) — ANY fold error in the window: a
      snapshot the collector could not decode, validate, or merge is
      fleet data loss.
    * ``freshness_slo`` — p95 ingest-to-visible staleness (the
      ``freshness_age_s`` series every stamped read feeds: wall-clock age
      of the newest event visible in the answer, see
      :mod:`metrics_tpu.observability.freshness`) against
      ``freshness_bound_s`` — the "is the dashboard showing old data"
      alarm, distinct from ``staleness`` (queued batches) and
      ``score_drift`` (distribution shape).
    * ``read_latency`` — p95 read wall time (``read_ms``, fed by every
      ``compute``/``window_state``/sliced/fleet read) against
      ``read_latency_limit_ms``.
    * ``memory_budget`` — the ledger's sliced state bytes per tenant
      (``mem_bytes_per_tenant``, fed by memory-observatory polls) against
      ``tenant_bytes_limit`` — the ROADMAP item-3 capacity headline as an
      alarm.
    * ``memory_leak`` — monotone growth of the unaccounted residue
      (``mem_unaccounted_bytes`` = device in-use − ledger − cache planes)
      beyond ``unaccounted_growth_bytes`` across the window: memory
      nothing in the inventory explains, and it keeps growing.

    The three fleet classes watch series only a
    :class:`~metrics_tpu.observability.collector.FleetCollector` feeds —
    in a job without a collector they never fire, like any absent series;
    the two read-path classes likewise stay silent until something reads,
    and the two memory classes until a
    :class:`~metrics_tpu.observability.memory.MemoryObservatory` polls.
    """
    short = short_window_s if short_window_s is not None else max(window_s / 3.0, 1.0)
    return [
        ThresholdRule(
            "queue_saturation",
            SERIES_ASYNC_QUEUE_DEPTH,
            stat="p95",
            threshold=queue_depth_limit,
            window_s=window_s,
            op=">=",
            severity="warn",
            min_count=3,
            description="async ingest queue persistently near capacity",
        ),
        ThresholdRule(
            "queue_saturation_critical",
            SERIES_ASYNC_QUEUE_DEPTH,
            stat="p95",
            threshold=queue_depth_limit * critical_queue_factor,
            window_s=window_s,
            op=">=",
            severity="critical",
            min_count=3,
            description="async ingest queue saturated well past its limit",
        ),
        ThresholdRule(
            "staleness",
            SERIES_ASYNC_STALENESS,
            stat="max",
            threshold=staleness_limit_steps,
            window_s=window_s,
            op=">=",
            severity="warn",
            description="compute snapshots are further behind ingest than the bound",
        ),
        BurnRateRule(
            "drop_rate",
            numerator=SERIES_ASYNC_DROPPED,
            denominator=(SERIES_ASYNC_ENQUEUED, SERIES_ASYNC_DROPPED),
            budget=drop_budget,
            short_window_s=short,
            long_window_s=window_s,
            burn_threshold=drop_burn_threshold,
            severity="critical",
            description="batch drop ratio is burning the SLO error budget",
        ),
        ThresholdRule(
            "recompile_storm",
            SERIES_RECOMPILES,
            stat="total",
            threshold=recompiles_per_window,
            window_s=window_s,
            op=">=",
            severity="warn",
            description="new call signatures keep triggering XLA compilation",
        ),
        ThresholdRule(
            "sketch_fill",
            SERIES_SKETCH_FILL,
            stat="max",
            threshold=fill_ceiling,
            window_s=window_s,
            op=">=",
            severity="warn",
            description="sketch states near/at capacity — accuracy budget being spent",
        ),
        ThresholdRule(
            "hot_slice_skew",
            SERIES_HOT_SLICE_SHARE,
            stat="p95",
            threshold=hot_share_limit,
            window_s=window_s,
            op=">=",
            severity="warn",
            min_count=3,
            description="one slice is receiving an outsized share of batch rows",
        ),
        DriftRule(
            "score_drift",
            SERIES_SCORES,
            stat=drift_stat,
            threshold=drift_threshold,
            window_s=window_s,
            reference_window_s=window_s,
            freeze_after=drift_freeze_after,
            min_count=16,
            severity="warn",
            description="live score distribution drifted from the frozen reference window",
        ),
        ThresholdRule(
            "publisher_stale",
            SERIES_PUBLISHER_LAG,
            stat="max",
            threshold=publisher_lag_limit_s,
            window_s=window_s,
            op=">=",
            severity="warn",
            description="a fleet publisher has not shipped a snapshot within the staleness bound",
        ),
        ThresholdRule(
            "snapshot_backlog",
            SERIES_COLLECTOR_BACKLOG,
            stat="max",
            threshold=backlog_limit,
            window_s=window_s,
            op=">=",
            severity="warn",
            description="the fleet collector is falling behind the publishers' snapshot rate",
        ),
        ThresholdRule(
            "fold_error",
            SERIES_FOLD_ERRORS,
            stat="total",
            threshold=fold_errors_per_window,
            window_s=window_s,
            op=">=",
            severity="critical",
            description="snapshots failed to decode/validate/fold — fleet data loss",
        ),
        ThresholdRule(
            "freshness_slo",
            SERIES_FRESHNESS_AGE_S,
            stat="p95",
            threshold=freshness_bound_s,
            window_s=window_s,
            op=">",
            severity="warn",
            min_count=3,
            description="ingest-to-visible staleness past the freshness bound — readers are seeing old data",
        ),
        ThresholdRule(
            "read_latency",
            SERIES_READ_MS,
            stat="p95",
            threshold=read_latency_limit_ms,
            window_s=window_s,
            op=">",
            severity="warn",
            min_count=3,
            description="metric reads (compute/window/fleet fold) persistently slow",
        ),
        MemoryBudget(
            tenant_bytes_limit,
            window_s=window_s,
        ),
        MemoryLeak(
            unaccounted_growth_bytes,
            window_s=window_s,
        ),
    ]
