"""Classification input fixture grid.

Parity with /root/reference/tests/classification/inputs.py:25-75: seeded
random fixtures for every input case, shaped (NUM_BATCHES, BATCH_SIZE, ...).
"""
from collections import namedtuple

import numpy as np

from tests.helpers import seed_all
from tests.helpers.testers import BATCH_SIZE, EXTRA_DIM, NUM_BATCHES, NUM_CLASSES

seed_all(1)

Input = namedtuple("Input", ["preds", "target"])

_rng = np.random.default_rng(1)

_input_binary_prob = Input(
    preds=_rng.random((NUM_BATCHES, BATCH_SIZE)).astype(np.float32),
    target=_rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE)),
)

_input_binary = Input(
    preds=_rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE)),
    target=_rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE)),
)

_input_multilabel_prob = Input(
    preds=_rng.random((NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)).astype(np.float32),
    target=_rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)),
)

_input_multilabel = Input(
    preds=_rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)),
    target=_rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)),
)


def _softmax(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


_input_multiclass_prob = Input(
    preds=_softmax(_rng.random((NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)).astype(np.float32), axis=-1),
    target=_rng.integers(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE)),
)

_input_multiclass = Input(
    preds=_rng.integers(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE)),
    target=_rng.integers(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE)),
)

_input_multidim_multiclass_prob = Input(
    preds=_softmax(_rng.random((NUM_BATCHES, BATCH_SIZE, NUM_CLASSES, EXTRA_DIM)).astype(np.float32), axis=2),
    target=_rng.integers(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE, EXTRA_DIM)),
)

_input_multidim_multiclass = Input(
    preds=_rng.integers(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE, EXTRA_DIM)),
    target=_rng.integers(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE, EXTRA_DIM)),
)

_input_multilabel_multidim_prob = Input(
    preds=_rng.random((NUM_BATCHES, BATCH_SIZE, NUM_CLASSES, EXTRA_DIM)).astype(np.float32),
    target=_rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES, EXTRA_DIM)),
)

_input_multilabel_multidim = Input(
    preds=_rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES, EXTRA_DIM)),
    target=_rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES, EXTRA_DIM)),
)

# logits variants (reference inputs.py: _input_binary_logits etc.)
_input_binary_logits = Input(
    preds=_rng.normal(size=(NUM_BATCHES, BATCH_SIZE)).astype(np.float32),
    target=_rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE)),
)

_input_multilabel_logits = Input(
    preds=_rng.normal(size=(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)).astype(np.float32),
    target=_rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)),
)

_input_multiclass_logits = Input(
    preds=(10 * _rng.normal(size=(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES))).astype(np.float32),
    target=_rng.integers(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE)),
)

# multilabel edge case where nothing matches (scores are undefined)
_nm_preds = _rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES))
_input_multilabel_no_match = Input(preds=_nm_preds, target=np.abs(_nm_preds - 1))


def generate_plausible_inputs_multilabel(num_classes=NUM_CLASSES, num_batches=NUM_BATCHES, batch_size=BATCH_SIZE):
    """Targets one-hot of a sampled class; preds biased toward the target
    (reference inputs.py:100-113)."""
    correct = _rng.integers(0, num_classes, (num_batches, batch_size))
    preds = _rng.random((num_batches, batch_size, num_classes)).astype(np.float32)
    targets = np.zeros_like(preds, dtype=np.int64)
    np.put_along_axis(targets, correct[..., None], 1, axis=2)
    preds = preds + _rng.random(preds.shape).astype(np.float32) * targets / 3
    preds = preds / preds.sum(axis=2, keepdims=True)
    return Input(preds=preds.astype(np.float32), target=targets)


def generate_plausible_inputs_binary(num_batches=NUM_BATCHES, batch_size=BATCH_SIZE):
    targets = _rng.integers(0, 2, (num_batches, batch_size))
    preds = _rng.random((num_batches, batch_size)) + _rng.random((num_batches, batch_size)) * targets / 3
    return Input(preds=(preds / (preds.max() + 0.01)).astype(np.float32), target=targets)


_input_multilabel_prob_plausible = generate_plausible_inputs_multilabel()
_input_binary_prob_plausible = generate_plausible_inputs_binary()

# randomly remove one class from the input (reference inputs.py:121-127)
_mc_missing = _rng.integers(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE))
_cls_remove, _cls_replace = _rng.choice(NUM_CLASSES, size=2, replace=False)
_mc_missing[_mc_missing == _cls_remove] = _cls_replace
_input_multiclass_with_missing_class = Input(_mc_missing.copy(), _mc_missing.copy())
