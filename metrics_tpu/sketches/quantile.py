"""Mergeable weighted quantile/stream sketch with FIXED-shape state.

The cat-state metrics (exact curves, Spearman, calibration) accumulate
unbounded ``[N]`` arrays — O(dataset) memory and permanent exclusion from
``FusedUpdate``/``compile_update_async`` because list-state is dynamic
shape. This module is the replacement: a **packed single-leaf sketch**,

    ``[capacity, 2 + payload_cols]`` float32
    column 0: weight  (``> 0`` ⇒ occupied slot)
    column 1: key     (the value the sketch orders/quantiles by)
    columns 2..: payload riding with each key (labels, one-hot rows, ...)

with three pure, jit-safe, fixed-shape transforms:

* ``qsketch_init(capacity, payload_cols) -> leaf``
* ``qsketch_insert(leaf, key, payload, weights, n_valid) -> leaf``
* ``qsketch_merge(a, b) -> leaf``   (``dist_reduce_fx`` material)

**Lossless window.** Inserts append into the first free slots (stable
pack: insertion order is preserved), so while the total inserted row
count fits in ``capacity`` the sketch holds the exact stream — weights
all 1, rows in arrival order. Converted metrics exploit this: inside the
window they reconstruct the original arrays and run the exact unbounded
kernels bit-for-bit; only past capacity do the weighted approximate
kernels engage.

**Compaction.** On overflow the occupied rows compact by a fully
vectorized merging-t-digest pass: rows sort by key, map through the
tail-adaptive quantile scale ``k1(q) = (capacity / 2π) · asin(2q − 1)``,
and rows sharing a scale bucket merge into one weighted centroid
(``weight`` summed, key/payload weighted-MEAN). Weighted means preserve
every first moment exactly (``sum(w * payload)`` is invariant), so curve
statistics built from linear functionals of the payload (weighted TP/FP
masses, rank co-moments) lose accuracy only through key displacement
inside a bucket — narrowest at the tails, and bounded by the rank-error
envelope :func:`rank_error_bound` advertises and the property tests pin
across adversarial orderings.

**Merge.** ``merge(a, b)`` concatenates rows and runs the same
pack-or-compact step. Below combined capacity it is exact; above, both
orders produce the same key-sorted collapsed rows for distinct keys
(commutativity is pinned in tests as multiset equality of rows).

Everything is a plain ``jnp`` program — no host syncs, no data-dependent
shapes — so metrics whose update is one ``qsketch_insert`` fuse, bucket
(via ``n_valid`` pad masking), vmap, and mesh-sync like any sum-state
metric.
"""
import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.observability.memory import register_cache_plane

Array = jax.Array

#: empirical compaction constant for :func:`rank_error_bound` — the
#: adversarial-ordering property tests (tests/sketches/) pin measured rank
#: error under this envelope
QSKETCH_RANK_EPS = 4.0


def rank_error_bound(n: int, capacity: int) -> float:
    """Advertised ABSOLUTE rank-error bound after ``n`` unit-weight inserts.

    Zero inside the lossless window (``n <= capacity``); beyond it, pair
    collapse displaces a query rank by at most the collapsed pair weights
    crossing it — empirically bounded by ``QSKETCH_RANK_EPS * n /
    capacity`` across adversarial orderings (sorted, reversed, organ-pipe,
    interleaved; see the property tests). Relative rank error is therefore
    ``QSKETCH_RANK_EPS / capacity`` — capacity IS the accuracy knob.
    """
    if n <= capacity:
        return 0.0
    return QSKETCH_RANK_EPS * float(n) / float(capacity) + 2.0


def qsketch_init(capacity: int, payload_cols: int = 0) -> Array:
    """Fresh empty sketch leaf ``[capacity, 2 + payload_cols]``."""
    if not (isinstance(capacity, int) and capacity > 0):
        raise ValueError(f"sketch `capacity` must be a positive int, got {capacity}")
    if not (isinstance(payload_cols, int) and payload_cols >= 0):
        raise ValueError(f"`payload_cols` must be a non-negative int, got {payload_cols}")
    return jnp.zeros((capacity, 2 + payload_cols), jnp.float32)


def _pack_rows(rows: Array) -> Array:
    """Occupied rows first, preserving their relative order (stable)."""
    n = rows.shape[0]
    occ = rows[:, 0] > 0
    # composite integer key makes the pack order-stable without relying on
    # argsort's stability kwarg across jax versions
    order = jnp.argsort(jnp.where(occ, 0, 1) * n + jnp.arange(n, dtype=jnp.int32))
    return rows[order]


def _finalize_compact(seg_w: Array, seg_vals: Array, rows: Array) -> Array:
    """Compaction epilogue shared by the jnp and Pallas paths: divide the
    segment-summed weighted values back to centroids, embed them at their
    (key-ordered) bucket positions in a ``rows``-shaped buffer, and pack
    occupied rows first. ``seg_vals`` carries the WEIGHTED sums."""
    n_seg = seg_w.shape[0]
    seg_vals = seg_vals / jnp.clip(seg_w[:, None], 1e-30, None)
    merged = jnp.concatenate([seg_w[:, None], seg_vals], axis=1)
    out = jnp.zeros_like(rows)
    out = out.at[:n_seg].set(merged.astype(rows.dtype))
    return _pack_rows(out)


def _compact_rows_jnp(rows: Array, capacity: int) -> Array:
    """One merging-t-digest compaction pass, fully vectorized (the jnp
    reference path; ``_compact_rows`` routes here off-TPU).

    Occupied rows (weighted centroids) are sorted by key; each row's
    mid-quantile position ``q`` maps through the tail-adaptive scale
    ``k1(q) = (capacity / 2π) · asin(2q − 1)`` to an integer bucket, and
    rows sharing a bucket merge into one centroid (``segment_sum``: weight
    summed, key/payload weighted-mean — every first moment preserved
    exactly). The scale allots bucket widths ∝ ``sqrt(q(1−q))``, so tail
    quantiles (where threshold curves live) get the narrowest buckets and
    the post-pass centroid count is ≤ capacity/2 + 4 whatever the input.
    One sort + one segment-sum — no data-dependent shapes, no host reads.
    """
    n = rows.shape[0]
    w = rows[:, 0]
    occ = w > 0
    key = jnp.where(occ, rows[:, 1], jnp.inf)
    order = jnp.lexsort((jnp.arange(n, dtype=jnp.int32), key))
    srt = rows[order]
    sw = srt[:, 0]
    total = jnp.clip(jnp.sum(sw), 1e-30, None)
    cum = jnp.cumsum(sw)
    q = jnp.clip((cum - sw / 2.0) / total, 0.0, 1.0)
    scale = capacity / (2.0 * jnp.pi)
    k = scale * jnp.arcsin(2.0 * q - 1.0)  # in [-capacity/4, capacity/4]
    n_seg = capacity // 2 + 4
    bucket = jnp.clip(
        jnp.floor(k).astype(jnp.int32) + capacity // 4 + 1, 0, n_seg - 1
    )
    seg_w = jax.ops.segment_sum(sw, bucket, num_segments=n_seg)
    seg_vals = jax.ops.segment_sum(sw[:, None] * srt[:, 1:], bucket, num_segments=n_seg)
    return _finalize_compact(seg_w, seg_vals, rows)


def _compact_rows(rows: Array, capacity: int) -> Array:
    """The compaction pass, routed through the ops kernel registry: the
    fused Pallas sort→bucket→segment-merge chain on TPU
    (:mod:`metrics_tpu.ops.qsketch_pallas`), :func:`_compact_rows_jnp`
    everywhere else. Lazy import — ``ops`` imports this module's jnp body
    as its fallback."""
    from metrics_tpu.ops import qsketch_compact_dispatch

    return qsketch_compact_dispatch(rows, capacity)


@functools.partial(jax.jit, static_argnames=("_mode",))
def _absorb_impl(sketch: Array, new_rows: Array, _mode: Any = None) -> Array:
    """Shared insert/merge core: concatenate, pack, compact iff the
    occupied rows overflow capacity (``lax.cond`` — the compaction branch
    only runs on overflow, so in-window streams never pay the sort).
    Jitted on its own so EAGER metric updates pay one cached dispatch per
    (capacity, batch) signature instead of tens of small op dispatches; the
    raises below are host-static shape checks that fire at trace time.
    ``_mode`` is the ops-dispatch routing state (see
    ``ops.dispatch.dispatch_mode``) folded into the jit cache key — the
    compaction backend is chosen at trace time, so a flipped
    ``METRICS_TPU_NO_PALLAS`` or a forced interpret test must not be
    shadowed by a stale trace."""
    capacity = sketch.shape[0]
    if new_rows.shape[0] > capacity:
        raise ValueError(
            f"cannot absorb {new_rows.shape[0]} rows into a capacity-{capacity} sketch in one"
            " pass; chunk the batch to at most `capacity` rows"
        )
    if capacity < 8:
        raise ValueError(f"sketch capacity must be at least 8, got {capacity}")
    rows = jnp.concatenate([sketch, new_rows.astype(sketch.dtype)], axis=0)
    packed = _pack_rows(rows)
    n_occ = jnp.sum(packed[:, 0] > 0)
    return jax.lax.cond(
        n_occ > capacity,
        lambda r: _compact_rows(r, capacity),
        lambda r: r,
        packed,
    )[:capacity]


def _absorb(sketch: Array, new_rows: Array) -> Array:
    """:func:`_absorb_impl` with the current ops-dispatch routing state as
    the trace-cache discriminator."""
    from metrics_tpu.ops.dispatch import dispatch_mode

    return _absorb_impl(sketch, new_rows, _mode=dispatch_mode())


def sketch_scratch_entries() -> int:
    """Executables cached for the absorb core — one per (capacity,
    batch-shape, dispatch-mode) signature in jax's own jit cache."""
    try:
        return int(_absorb_impl._cache_size())
    except Exception:
        return 0


def _sketch_scratch_nbytes() -> int:
    """The ``sketch_scratch`` memory plane. The absorb core's executables
    live in jax's internal jit cache, which exposes an entry count
    (:func:`sketch_scratch_entries`) but not per-entry device bytes — so
    the plane reports the honest measurable number (0) rather than an
    estimate; their code-size bytes land in the backend ``bytes_in_use``
    poll and therefore in the *unaccounted* residue, which docs/memory.md
    calls out as the expected baseline offset. Registered anyway so the
    inventory enumerates every byte-holding cache by name."""
    return 0


register_cache_plane("sketch_scratch", _sketch_scratch_nbytes)


def qsketch_insert(
    sketch: Array,
    key: Array,
    payload: Optional[Array] = None,
    weights: Optional[Array] = None,
    n_valid: Optional[Array] = None,
) -> Array:
    """Insert a batch of keyed rows; pure and jit-safe.

    ``key`` is ``[B]``; ``payload`` is ``[B, payload_cols]`` (or None for a
    payload-less sketch); ``weights`` default to 1. ``n_valid`` masks
    trailing rows to weight 0 — the pad-and-mask contract of the fused
    bucketed dispatch (``__fused_mask_valid__``): edge-pad rows beyond
    ``n_valid`` are dropped instead of inserted. Batches larger than
    ``capacity`` are absorbed in capacity-sized chunks (host loop over
    static slices).
    """
    key = jnp.asarray(key, jnp.float32).reshape(-1)
    b = key.shape[0]
    w = jnp.ones((b,), jnp.float32) if weights is None else jnp.asarray(weights, jnp.float32).reshape(-1)
    if n_valid is not None:
        w = w * (jnp.arange(b) < n_valid)
    expect = sketch.shape[1] - 2
    if payload is None:
        if expect != 0:
            raise ValueError(
                f"payload has 0 column(s) but the sketch was initialized with {expect}"
            )
        rows = jnp.concatenate([w[:, None], key[:, None]], axis=1)
    else:
        payload = jnp.asarray(payload, jnp.float32).reshape(b, -1)
        if payload.shape[1] != expect:
            raise ValueError(
                f"payload has {payload.shape[1]} column(s) but the sketch was initialized"
                f" with {expect}"
            )
        rows = jnp.concatenate([w[:, None], key[:, None], payload], axis=1)
    capacity = sketch.shape[0]
    for lo in range(0, b, capacity):
        sketch = _absorb(sketch, rows[lo : lo + capacity])
    return sketch


def qsketch_merge(a: Array, b: Array) -> Array:
    """Merge two sketches into one of ``a``'s capacity (``dist_reduce_fx``
    material: pure, associative up to collapse rounding, commutative as a
    row multiset). Exact while the combined occupancy fits."""
    if a.ndim != 2 or a.shape[1:] != b.shape[1:]:
        raise ValueError(f"cannot merge sketches with layouts {a.shape} and {b.shape}")
    out = a
    for lo in range(0, b.shape[0], a.shape[0]):
        out = _absorb(out, b[lo : lo + a.shape[0]])
    return out


def qsketch_merge_into(dst: Array, *others: Array) -> Array:
    """Fold any number of sketches into ``dst``'s capacity (left fold of
    :func:`qsketch_merge`) and return the result. The convenience shape the
    fan-in consumers use — telemetry time-series window queries merge a run
    of per-bucket sketches, and cross-host aggregation merges one sketch
    per rank — without each spelling the fold loop."""
    for other in others:
        dst = qsketch_merge(dst, other)
    return dst


def qsketch_absorb_rows(sketch: Array, rows: Any) -> Array:
    """Fold serialized occupied rows (a ``[n, cols]`` host array/list —
    the shape telemetry payloads and fleet snapshots ship sketches as)
    into ``sketch``. ``n`` may exceed the sketch's capacity (a payload
    from a larger-capacity peer); the merge chunks it down. The one
    payload-fan-in fold shared by the time-series registry merge and the
    fleet collector, so wire-level sketch semantics cannot drift from the
    in-memory merge contract."""
    rows = jnp.asarray(rows, sketch.dtype)
    if rows.ndim != 2 or rows.shape[1] != sketch.shape[1]:
        raise ValueError(
            f"serialized rows layout {rows.shape} does not match sketch layout {sketch.shape}"
        )
    incoming = jnp.zeros((max(sketch.shape[0], rows.shape[0]), sketch.shape[1]), sketch.dtype)
    incoming = incoming.at[: rows.shape[0]].set(rows)
    return qsketch_merge(sketch, incoming)


class _QSketchReduce:
    """``dist_reduce_fx`` for quantile-sketch leaves: takes the stacked
    per-rank leaves ``[world, capacity, cols]`` (the contract both
    ``Metric._sync_dist`` and the callable-reducer leg of ``sync_in_mesh``
    deliver) and folds :func:`qsketch_merge` across ranks in rank order —
    inside the lossless window this reproduces the cat-state gather's
    concatenation order bit-for-bit.

    A module-level class (not a closure) so metric instances carrying it
    pickle/deepcopy; tagged ``merge_like`` / ``sketch_kind`` so
    ``merge_states``, ``sync_pytree_in_mesh``'s fused gather round,
    tracelint's TL-FLOW, and the footprint accounting all recognize sketch
    leaves without importing this module.
    """

    merge_like = True
    sketch_kind = "quantile"
    __name__ = "qsketch_reduce"

    def __call__(self, stacked: Array) -> Array:
        stacked = jnp.asarray(stacked)
        if stacked.ndim == 2:  # single-rank passthrough
            return stacked
        out = stacked[0]
        for i in range(1, stacked.shape[0]):
            out = qsketch_merge(out, stacked[i])
        return out


_QSKETCH_REDUCE = _QSketchReduce()


def sketch_merge_fx() -> _QSketchReduce:
    """The shared quantile-sketch ``dist_reduce_fx`` (see
    :class:`_QSketchReduce`)."""
    return _QSKETCH_REDUCE


# ---------------------------------------------------------------------------
# queries (pure; fixed-shape unless noted)
# ---------------------------------------------------------------------------


def qsketch_fill(sketch: Array) -> Array:
    """Number of occupied slots (int32 scalar)."""
    return jnp.sum(sketch[:, 0] > 0).astype(jnp.int32)


def qsketch_total_weight(sketch: Array) -> Array:
    """Total inserted weight surviving in the sketch."""
    return jnp.sum(sketch[:, 0])


def qsketch_rank(sketch: Array, xs: Array) -> Array:
    """Estimated rank (weighted count of keys ``<= x``) per query point."""
    w, key = sketch[:, 0], sketch[:, 1]
    xs = jnp.asarray(xs, jnp.float32).reshape(-1)
    return jnp.sum(w[None, :] * (key[None, :] <= xs[:, None]), axis=1)


def qsketch_cdf(sketch: Array, xs: Array) -> Array:
    """Estimated CDF at each query point (rank / total weight).

    An EMPTY sketch (total weight 0) has no distribution to query: every
    result is the explicit ``NaN`` sentinel rather than a confident-looking
    0 from a guarded division — callers that can see an empty window
    should skip the query instead (``TelemetrySeries`` does)."""
    total = qsketch_total_weight(sketch)
    cdf = qsketch_rank(sketch, xs) / jnp.clip(total, 1e-12, None)
    return jnp.where(total > 0, cdf, jnp.nan)


def qsketch_quantile(sketch: Array, q: Array) -> Array:
    """Estimated quantile(s): smallest key whose cumulative weight reaches
    ``q`` of the total.

    Empty-sketch contract (total weight 0): returns ``NaN`` per query —
    the un-guarded arithmetic would otherwise return key 0.0, a silently
    wrong *value* where the windowed stats need a recognizable *absence*.
    """
    w, key = sketch[:, 0], sketch[:, 1]
    order = jnp.argsort(jnp.where(w > 0, key, jnp.inf))
    sk, sw = key[order], w[order]
    cum = jnp.cumsum(sw)
    total = cum[-1]
    q = jnp.asarray(q, jnp.float32).reshape(-1)
    idx = jnp.clip(
        jnp.searchsorted(cum / jnp.clip(total, 1e-12, None), q, side="left"),
        0,
        sk.shape[0] - 1,
    )
    return jnp.where(total > 0, sk[idx], jnp.nan)


def qsketch_histogram(sketch: Array, edges: Array) -> Array:
    """Weighted histogram of the keys over ``len(edges) - 1`` bins, using
    the same ``searchsorted(side='left')`` convention as the calibration
    binning kernel."""
    w, key = sketch[:, 0], sketch[:, 1]
    n_bins = edges.shape[0] - 1
    idx = jnp.clip(jnp.searchsorted(edges, key, side="left") - 1, 0, n_bins - 1)
    return jnp.zeros(n_bins, jnp.float32).at[idx].add(w)
