"""Sketch-backed telemetry time series: fixed-capacity ring-of-buckets
windows over every hot-path signal the recorder emits.

PR 1/2's recorder answers *what happened since reset* — monotone counters
and one-shot exports with no notion of time. A live serving job needs
*windowed* answers ("p99 update latency over the last minute", "drop rate
right now", "is the async queue saturating"), which means per-interval
state that expires. This module is that layer:

* A :class:`TelemetrySeries` is a **ring of time buckets**. Each bucket
  covers ``bucket_seconds`` of wall time, keyed by the absolute bucket
  index ``int(t / bucket_seconds)`` — so buckets align across processes
  and the ring self-expires (a slot whose index has fallen out of the
  ring's span is reset on the next write or ignored on read). Memory is
  fixed: ``n_buckets`` buckets, never more.
* A ``"distribution"`` series backs each bucket with a ``qsketch`` state
  (:mod:`metrics_tpu.sketches.quantile`) — the SAME fixed-capacity
  mergeable quantile sketch the metric states use — so windowed
  p50/p95/p99 queries are a fold of :func:`qsketch_merge_into` over the
  window's buckets and one :func:`qsketch_quantile`, with the sketch's
  advertised :func:`rank_error_bound` as the accuracy contract. A
  ``"counter"`` series skips the sketch and tracks windowed sums/rates.
* **Hot-path cost is host-only**: ``record()`` appends to a per-bucket
  pending list and updates count/sum/min/max — no jax dispatch. Pending
  values are folded into the bucket's sketch in fixed-shape batches
  (padded to ``sketch_capacity`` with weight-0 rows, so every flush hits
  the same cached ``_absorb`` compilation) only at query/export time or
  when the pending list crosses its bound.
* **Cross-host aggregation reuses the merge contract**: a series
  serializes to a JSON-safe payload (occupied sketch rows only) that
  ``aggregate_across_hosts`` ships over the existing padded-uint8
  allgather; same-index buckets merge by summing counts and
  ``qsketch_merge``-ing sketches, so a fleet-wide windowed p99 is a fold
  — the seed of the ROADMAP's merge-tree collector.

The registry is wired into the default recorder via
``get_recorder().attach_timeseries()``; the recorder then feeds the
standard series (named by the ``SERIES_*`` constants in ``recorder.py``)
from its existing hooks at zero extra cost when detached. The health/SLO
engine (:mod:`metrics_tpu.observability.health`) evaluates its alarm
rules over these windows. See docs/observability.md.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "TelemetrySeries",
    "TimeSeriesRegistry",
    "merge_registry_payloads",
    "registry_from_payload",
    "series_from_payload",
]

#: accepted series kinds — "distribution" buckets carry a quantile sketch,
#: "counter" buckets only the count/sum/min/max scalars
KINDS = ("distribution", "counter")


class _Bucket:
    """One ring slot: scalar aggregates + (distribution series) a pending
    host-value list and the qsketch leaf it folds into."""

    __slots__ = ("index", "count", "total", "vmin", "vmax", "pending", "sketch")

    def __init__(self, index: int) -> None:
        self.index = index
        self.count = 0
        self.total = 0.0
        self.vmin = float("inf")
        self.vmax = float("-inf")
        self.pending: List[float] = []
        self.sketch: Any = None


class TelemetrySeries:
    """Windowed telemetry over one signal.

    ``record(value)`` is the host-only hot path; ``rate``/``mean``/
    ``value_max``/``quantile`` answer windowed queries; ``to_payload`` /
    :func:`merge_series_payloads` / :func:`series_from_payload` carry the
    series across hosts. All methods are thread-safe (worker threads and
    the serving loop record concurrently; exporters query concurrently).

    ``clock`` defaults to wall time (``time.time``) so bucket indexes
    align across processes; tests and simulations may inject their own.
    """

    def __init__(
        self,
        name: str,
        kind: str = "distribution",
        bucket_seconds: float = 1.0,
        n_buckets: int = 60,
        sketch_capacity: int = 128,
        clock: Optional[Callable[[], float]] = None,
    ) -> None:
        if kind not in KINDS:
            raise ValueError(f"series kind must be one of {KINDS}, got {kind!r}")
        if bucket_seconds <= 0:
            raise ValueError(f"bucket_seconds must be positive, got {bucket_seconds}")
        if n_buckets < 2:
            raise ValueError(f"n_buckets must be >= 2, got {n_buckets}")
        if sketch_capacity < 8:
            raise ValueError(f"sketch_capacity must be >= 8, got {sketch_capacity}")
        self.name = name
        self.kind = kind
        self.bucket_seconds = float(bucket_seconds)
        self.n_buckets = int(n_buckets)
        self.sketch_capacity = int(sketch_capacity)
        self.clock = clock if clock is not None else time.time
        self._lock = threading.Lock()
        self._ring: List[Optional[_Bucket]] = [None] * self.n_buckets
        #: pending-list bound before an inline sketch flush — bounds worst-
        #: case host memory per bucket without a per-record jax dispatch
        self._flush_at = max(4 * self.sketch_capacity, 512)

    # ------------------------------------------------------------------
    # hot path
    # ------------------------------------------------------------------
    def record(self, value: float, t: Optional[float] = None) -> None:
        """Add one observation (distribution) or increment (counter) at
        time ``t`` (default: now). O(1) host work; the only jax dispatch
        this can trigger is the bounded inline flush of an overfull
        pending list."""
        t = self.clock() if t is None else float(t)
        idx = int(t // self.bucket_seconds)
        value = float(value)
        with self._lock:
            b = self._slot(idx)
            b.count += 1
            b.total += value
            if value < b.vmin:
                b.vmin = value
            if value > b.vmax:
                b.vmax = value
            if self.kind == "distribution":
                b.pending.append(value)
                if len(b.pending) >= self._flush_at:
                    self._flush(b)

    def housekeep(self) -> int:
        """Fold every bucket's pending observations into its sketch NOW,
        returning the number of values folded.

        The hot path bounds its own worst case with the inline flush at
        ``_flush_at`` pending values — but that flush (a few ms of sketch
        compaction) then lands inside whichever :meth:`record` crosses
        the threshold, i.e. inside somebody's timed read. A
        latency-sensitive caller (a serving loop between probe reads)
        calls this at a moment of its own choosing so the compaction
        never rides a measured path."""
        folded = 0
        with self._lock:
            for b in self._ring:
                if b is not None and b.pending:
                    folded += len(b.pending)
                    self._flush(b)
        return folded

    def _slot(self, idx: int) -> _Bucket:
        """The live bucket for absolute index ``idx`` — resetting the slot
        if its previous occupant has expired out of the ring's span.
        Caller holds the lock."""
        pos = idx % self.n_buckets
        b = self._ring[pos]
        if b is None or b.index != idx:
            b = _Bucket(idx)
            self._ring[pos] = b
        return b

    # ------------------------------------------------------------------
    # sketch materialization
    # ------------------------------------------------------------------
    def _flush(self, b: _Bucket) -> None:
        """Fold the bucket's pending values into its sketch. Pads each
        chunk to the fixed ``sketch_capacity`` shape with weight-0 rows
        (the ``n_valid`` mask contract), so every flush — whatever the
        pending length — reuses ONE cached compilation of the absorb
        kernel instead of compiling per ragged length. Caller holds the
        lock."""
        if not b.pending:
            return
        import jax.numpy as jnp
        import numpy as np

        from metrics_tpu.sketches.quantile import qsketch_init, qsketch_insert

        vals = b.pending
        b.pending = []
        if b.sketch is None:
            b.sketch = qsketch_init(self.sketch_capacity)
        cap = self.sketch_capacity
        buf = np.zeros((cap,), np.float32)
        for lo in range(0, len(vals), cap):
            chunk = vals[lo : lo + cap]
            buf[: len(chunk)] = chunk
            buf[len(chunk) :] = 0.0
            b.sketch = qsketch_insert(
                b.sketch, jnp.asarray(buf), n_valid=jnp.asarray(len(chunk), jnp.int32)
            )

    # ------------------------------------------------------------------
    # windowed queries
    # ------------------------------------------------------------------
    def _window(self, window_s: Optional[float], now: Optional[float]) -> List[_Bucket]:
        """Live buckets inside ``[now - window_s, now]`` (whole ring span
        when ``window_s`` is None). Caller holds the lock."""
        now = self.clock() if now is None else float(now)
        hi = int(now // self.bucket_seconds)
        if window_s is None:
            lo = hi - self.n_buckets + 1
        else:
            lo = int((now - float(window_s)) // self.bucket_seconds) + 1
            # a window narrower than one bucket still covers the CURRENT
            # bucket (else sub-bucket windows read empty and a rule over
            # them can never fire)
            lo = min(lo, hi)
            lo = max(lo, hi - self.n_buckets + 1)
        out = []
        for idx in range(lo, hi + 1):
            b = self._ring[idx % self.n_buckets]
            if b is not None and b.index == idx and b.count:
                out.append(b)
        return out

    def count(self, window_s: Optional[float] = None, now: Optional[float] = None) -> int:
        """Observations recorded inside the window."""
        with self._lock:
            return sum(b.count for b in self._window(window_s, now))

    def total(self, window_s: Optional[float] = None, now: Optional[float] = None) -> float:
        """Sum of recorded values inside the window (a counter's windowed
        increment total)."""
        with self._lock:
            return float(sum(b.total for b in self._window(window_s, now)))

    def rate(self, window_s: float, now: Optional[float] = None) -> float:
        """Windowed rate: summed values per second over ``window_s``."""
        return self.total(window_s, now) / float(window_s)

    def mean(self, window_s: Optional[float] = None, now: Optional[float] = None) -> Optional[float]:
        with self._lock:
            buckets = self._window(window_s, now)
            n = sum(b.count for b in buckets)
            if not n:
                return None
            return float(sum(b.total for b in buckets)) / n

    def value_min(self, window_s: Optional[float] = None, now: Optional[float] = None) -> Optional[float]:
        with self._lock:
            buckets = self._window(window_s, now)
            if not buckets:
                return None
            return float(min(b.vmin for b in buckets))

    def value_max(self, window_s: Optional[float] = None, now: Optional[float] = None) -> Optional[float]:
        with self._lock:
            buckets = self._window(window_s, now)
            if not buckets:
                return None
            return float(max(b.vmax for b in buckets))

    def quantile(
        self,
        q: float,
        window_s: Optional[float] = None,
        now: Optional[float] = None,
    ) -> Optional[float]:
        """Windowed quantile from the merged per-bucket sketches
        (``None`` when the window is empty; distribution series only).
        Accuracy follows :func:`metrics_tpu.sketches.quantile.
        rank_error_bound` for the window's observation count — exact
        inside the lossless window, capacity-bounded rank error past it."""
        out = self.quantiles((q,), window_s=window_s, now=now)
        return out[0] if out is not None else None

    def window_sketch(self, window_s: Optional[float] = None, now: Optional[float] = None):
        """The window's per-bucket sketches merged into ONE qsketch leaf
        (``None`` when the window holds no mass) — what the quantile
        queries fold and what the drift comparator
        (:mod:`metrics_tpu.observability.drift`) histograms. Empty buckets
        are skipped rather than folded: an all-zero sketch would poison
        every downstream query with the empty-sketch ``NaN`` sentinel."""
        if self.kind != "distribution":
            raise ValueError(
                f"series `{self.name}` is a counter; sketch queries need a distribution series"
            )
        from metrics_tpu.sketches.quantile import qsketch_merge_into, qsketch_total_weight

        # flush + collect sketch REFS under the lock, but run the merge
        # fold (jax dispatches, first call compiles) OUTSIDE it — holding
        # the lock through device work would block every record() feeding
        # this series for the whole export tick
        with self._lock:
            buckets = self._window(window_s, now)
            for b in buckets:
                self._flush(b)
            # a bucket with observations always holds mass (unit-weight
            # inserts), but payload-merged buckets can arrive sketchless or
            # weightless — skip them instead of folding an empty leaf
            sketches = [b.sketch for b in buckets if b.sketch is not None and b.count]
        if not sketches:
            return None
        # sketch leaves are immutable jnp arrays: a concurrent record()
        # swaps the bucket's ref, never mutates ours
        merged = qsketch_merge_into(sketches[0], *sketches[1:])
        if float(qsketch_total_weight(merged)) <= 0:
            return None
        return merged

    def quantiles(
        self,
        qs: Sequence[float],
        window_s: Optional[float] = None,
        now: Optional[float] = None,
    ) -> Optional[List[float]]:
        """Several windowed quantiles from ONE merged sketch (one merge
        fold + one query, however many quantiles). ``None`` — never the
        empty-sketch ``NaN`` sentinel — when the window holds no mass."""
        merged = self.window_sketch(window_s=window_s, now=now)
        if merged is None:
            return None
        import jax.numpy as jnp

        from metrics_tpu.sketches.quantile import qsketch_quantile

        vals = qsketch_quantile(merged, jnp.asarray(list(qs), jnp.float32))
        return [float(v) for v in vals]

    def _live_buckets(self) -> List[_Bucket]:
        """Every non-empty slot in the ring, oldest first — by construction
        within the ring's span of the newest write, with NO clock involved
        (a snapshot must capture whatever was recorded, even when the data
        carried explicit timestamps far from this host's wall clock).
        Caller holds the lock."""
        return sorted(
            (b for b in self._ring if b is not None and b.count), key=lambda b: b.index
        )

    def window_count(self) -> int:
        """Non-empty buckets currently in the ring."""
        with self._lock:
            return len(self._live_buckets())

    # ------------------------------------------------------------------
    # serialization / merge
    # ------------------------------------------------------------------
    def to_payload(self) -> Dict[str, Any]:
        """JSON-safe snapshot of the live ring (the unit the cross-host
        allgather ships). Sketches serialize occupied rows only, so a
        mostly-empty window stays small on the wire."""
        # flush + snapshot scalars/sketch refs under the lock; the host
        # readback (np.asarray syncs the device) runs outside it so the
        # record() hot path never waits on serialization
        with self._lock:
            snap = []
            for b in self._live_buckets():
                self._flush(b)
                snap.append((b.index, b.count, b.total, b.vmin, b.vmax, b.sketch))
        buckets = []
        for index, count, total, vmin, vmax, sketch in snap:
            row: Dict[str, Any] = {"i": index, "c": count, "s": total, "mn": vmin, "mx": vmax}
            if sketch is not None:
                import numpy as np

                arr = np.asarray(sketch)
                occ = arr[arr[:, 0] > 0]
                row["sk"] = [[float(x) for x in r] for r in occ]
            buckets.append(row)
        return {
            "name": self.name,
            "kind": self.kind,
            "bucket_seconds": self.bucket_seconds,
            "n_buckets": self.n_buckets,
            "sketch_capacity": self.sketch_capacity,
            "buckets": buckets,
        }

    def load_payload(self, payload: Dict[str, Any]) -> "TelemetrySeries":
        """Install a payload's buckets into this (expected empty) series —
        the read side of :func:`series_from_payload`."""
        from metrics_tpu.sketches.quantile import qsketch_absorb_rows, qsketch_init

        with self._lock:
            for row in payload.get("buckets", []):
                idx = int(row["i"])
                existing = self._ring[idx % self.n_buckets]
                if existing is not None and existing.index > idx:
                    # the slot holds FRESHER data (a straggler host shipped
                    # buckets older than the ring span) — installing the
                    # stale bucket via _slot would evict the newer one; the
                    # stale bucket is outside every live window anyway
                    continue
                b = self._slot(idx)
                b.count += int(row["c"])
                b.total += float(row["s"])
                b.vmin = min(b.vmin, float(row["mn"]))
                b.vmax = max(b.vmax, float(row["mx"]))
                rows = row.get("sk")
                if rows:
                    self._flush(b)
                    if b.sketch is None:
                        b.sketch = qsketch_init(self.sketch_capacity)
                    # the shared payload-fan-in fold (larger-capacity peers
                    # chunk down inside the merge)
                    b.sketch = qsketch_absorb_rows(b.sketch, rows)
        return self

    def reset(self) -> "TelemetrySeries":
        with self._lock:
            self._ring = [None] * self.n_buckets
        return self


def series_from_payload(
    payload: Dict[str, Any], clock: Optional[Callable[[], float]] = None
) -> TelemetrySeries:
    """Reconstruct a queryable series from one (possibly merged) payload."""
    s = TelemetrySeries(
        payload["name"],
        kind=payload.get("kind", "distribution"),
        bucket_seconds=payload.get("bucket_seconds", 1.0),
        n_buckets=payload.get("n_buckets", 60),
        sketch_capacity=payload.get("sketch_capacity", 128),
        clock=clock,
    )
    return s.load_payload(payload)


def merge_series_payloads(payloads: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge same-series payloads from several hosts into one.

    Buckets align on their absolute index (wall-clock bucketing makes
    same-index buckets the same time interval on every host): counts and
    sums add, min/max fold, and sketches merge through
    :func:`qsketch_merge_into` — so a quantile over the merged payload is
    within the sketch's advertised rank-error bound of the same quantile
    over the pooled raw observations (pinned by test). Payloads may
    disagree on capacity/layout across a mixed-version fleet; the first
    payload's geometry wins and the rest fold into it."""
    if not payloads:
        return {}
    base = series_from_payload(payloads[0])
    for p in payloads[1:]:
        base.load_payload(p)
    return base.to_payload()


class TimeSeriesRegistry:
    """Named-series registry with one shared geometry (bucket width, ring
    length, sketch capacity) and one clock.

    ``observe(name, value, kind=...)`` is the get-or-create hot path the
    recorder's feed hooks call. ``payload()`` snapshots every series for
    ``aggregate_across_hosts``; :func:`merge_registry_payloads` folds the
    per-host snapshots."""

    def __init__(
        self,
        bucket_seconds: float = 1.0,
        n_buckets: int = 60,
        sketch_capacity: int = 128,
        clock: Optional[Callable[[], float]] = None,
    ) -> None:
        self.bucket_seconds = float(bucket_seconds)
        self.n_buckets = int(n_buckets)
        self.sketch_capacity = int(sketch_capacity)
        self.clock = clock if clock is not None else time.time
        self._lock = threading.Lock()
        self._series: Dict[str, TelemetrySeries] = {}

    def series(self, name: str, kind: str = "distribution") -> TelemetrySeries:
        """Get-or-create the named series (first caller's ``kind`` wins)."""
        s = self._series.get(name)
        if s is None:
            with self._lock:
                s = self._series.get(name)
                if s is None:
                    s = self._series[name] = TelemetrySeries(
                        name,
                        kind=kind,
                        bucket_seconds=self.bucket_seconds,
                        n_buckets=self.n_buckets,
                        sketch_capacity=self.sketch_capacity,
                        clock=self.clock,
                    )
        return s

    def observe(
        self, name: str, value: float, kind: str = "distribution", t: Optional[float] = None
    ) -> None:
        self.series(name, kind=kind).record(value, t=t)

    def get(self, name: str) -> Optional[TelemetrySeries]:
        return self._series.get(name)

    def housekeep(self) -> int:
        """Run :meth:`TelemetrySeries.housekeep` on every series; returns
        the total number of pending values folded."""
        with self._lock:
            series = list(self._series.values())
        return sum(s.housekeep() for s in series)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def payload(self) -> Dict[str, Any]:
        """``{series name: series payload}`` for every registered series."""
        with self._lock:
            series = list(self._series.values())
        return {s.name: s.to_payload() for s in series}

    def reset(self) -> "TimeSeriesRegistry":
        """Clear every series' data (registrations and geometry stay)."""
        with self._lock:
            series = list(self._series.values())
        for s in series:
            s.reset()
        return self


def merge_registry_payloads(payloads: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge per-host registry payloads: series align by name, and a host
    missing a series (mixed-version fleet, workload skew) simply
    contributes nothing — absent keys are identity, never an error."""
    names: Dict[str, List[Dict[str, Any]]] = {}
    for p in payloads:
        if not isinstance(p, dict):
            continue
        for name, sp in p.items():
            names.setdefault(name, []).append(sp)
    return {name: merge_series_payloads(sps) for name, sps in sorted(names.items())}


def registry_from_payload(
    payload: Dict[str, Any], clock: Optional[Callable[[], float]] = None
) -> TimeSeriesRegistry:
    """Reconstruct a queryable registry from a (possibly merged) registry
    payload — how an aggregator queries fleet-wide windowed quantiles."""
    reg = TimeSeriesRegistry(clock=clock)
    for name, sp in payload.items():
        reg._series[name] = series_from_payload(sp, clock=clock)
    return reg
