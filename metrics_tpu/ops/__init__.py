"""Pallas TPU kernels for hot ops (SURVEY §2.9 native-equivalents plan).

Kernels dispatch through shape/backend heuristics with jnp fallbacks, so
every entry point works on CPU (interpret mode in tests) and TPU alike.
"""
from metrics_tpu.ops.box_iou_pallas import box_iou_dispatch, box_iou_tiled  # noqa: F401
