"""Frechet Inception Distance.

Behavior parity with /root/reference/torchmetrics/image/fid.py:26-280:
float64 statistics ("extremely sensitive", fid.py:261-264) and the sqrtm
singularity eps-offset retry on the ``exact=True`` path.

State modes: by DEFAULT features stream into exact fixed-capacity moment
leaves per distribution — ``Σx [d]``, ``Σxxᵀ [d, d]``, and a count, all
``"sum"``-reduced (``metrics_tpu/sketches/moments.py``). The Gaussian
fit depends on the features only through those sufficient statistics, so
the streaming state is EXACT forever (no window, no admission policy):
the cat-state comparison is a covariance-identity check to float32 ulp,
not a capacity bound. ``compute()`` stays on device — the covariance
identity feeds the Newton–Schulz ``trace_sqrtm`` dispatch op
(``ops/sqrtm.py``) instead of hopping to the host for a float64
eigendecomposition. ``exact=True`` restores the reference's unbounded
feature lists and host float64 statistics bit-for-bit (and its
large-memory warning — fired only on that path).

TPU-native departures: ``feature`` accepts any callable ``imgs -> [N, d]``
(JAX or host function; the reference takes an ``nn.Module``) or an int
depth which builds the bundled Flax InceptionV3 (weights must be provided —
this environment has no network access to fetch the FID-compat weights).
Callable extractors declare their width via ``feature_dim`` (default
2048, the InceptionV3 pool head). The bundled extractor is a traced-pure
array program, declared via ``__traced_callable_attrs__`` so the
fusibility scan models ``self.inception(imgs)`` as device work; a user
who installs a host-only callable is demoted to the eager path at
runtime by the fused dispatcher's stale-manifest safety net.
"""
from typing import Any, Callable, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.core.metric import Metric
from metrics_tpu.ops.sqrtm import trace_sqrtm_dispatch
from metrics_tpu.sketches.compat import register_exact_list_states, warn_exact_buffer
from metrics_tpu.sketches.moments import mean_cov_from_moments
from metrics_tpu.utils.data import dim_zero_cat
from metrics_tpu.utils.prints import rank_zero_info

Array = jax.Array


def _sqrtm_eigh(mat: np.ndarray) -> np.ndarray:
    """Symmetric PSD square root via eigendecomposition (float64 host)."""
    vals, vecs = np.linalg.eigh(mat)
    vals = np.clip(vals, 0.0, None)
    return (vecs * np.sqrt(vals)) @ vecs.T


def _trace_sqrtm_product(sigma1: np.ndarray, sigma2: np.ndarray) -> float:
    """Tr[(sigma1 @ sigma2)^(1/2)] for symmetric PSD sigma1, sigma2."""
    s1_half = _sqrtm_eigh(sigma1)
    m = s1_half @ sigma2 @ s1_half
    vals = np.linalg.eigvalsh((m + m.T) / 2)
    return float(np.sqrt(np.clip(vals, 0.0, None)).sum())


def _compute_fid(
    mu1: np.ndarray, sigma1: np.ndarray, mu2: np.ndarray, sigma2: np.ndarray, eps: float = 1e-6
) -> float:
    """d^2 = ||mu1 - mu2||^2 + Tr(s1 + s2 - 2 sqrtm(s1 s2)). Reference fid.py:95-122."""
    diff = mu1 - mu2

    # eigvalsh raises LinAlgError (rather than returning NaN the way scipy's
    # sqrtm does) when the product is numerically degenerate — map both
    # failure shapes onto the reference's add-eps-and-retry path (fid.py:95-122)
    try:
        tr_covmean = _trace_sqrtm_product(sigma1, sigma2)
    except np.linalg.LinAlgError:
        tr_covmean = float("nan")
    if not np.isfinite(tr_covmean):
        rank_zero_info(f"FID calculation produces singular product; adding {eps} to diagonal of covariance estimates")
        offset = np.eye(sigma1.shape[0]) * eps
        try:
            tr_covmean = _trace_sqrtm_product(sigma1 + offset, sigma2 + offset)
        except np.linalg.LinAlgError as err:
            raise ValueError(
                "FID covariance square root failed even after adding eps to the diagonals —"
                " the feature matrices likely contain NaN/Inf (broken or overflowing extractor)."
            ) from err

    return float(diff @ diff + np.trace(sigma1) + np.trace(sigma2) - 2 * tr_covmean)


class FrechetInceptionDistance(Metric):
    """Computes the FID between real and generated image distributions.

    Args:
        feature: a callable mapping an image batch to ``[N, d]`` features, or
            an int in (64, 192, 768, 2048) selecting the bundled Flax
            InceptionV3 depth (requires local weights).
        feature_extractor_weights_path: npz checkpoint for the bundled
            InceptionV3 (int ``feature`` only).
        feature_dim: feature width ``d`` for callable extractors (ignored
            for int ``feature``, whose depth fixes it); default 2048.
        exact: restore the reference's unbounded feature lists and host
            float64 statistics (bit-for-bit legacy behavior).
    """

    __exact_mode_attr__ = "_exact"
    __traced_callable_attrs__ = ("inception",)
    is_differentiable = False
    higher_is_better = False

    def __init__(
        self,
        feature: Union[int, Callable] = 2048,
        feature_extractor_weights_path: str = None,
        feature_dim: Optional[int] = None,
        exact: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)

        if isinstance(feature, int):
            valid_int_input = (64, 192, 768, 2048)
            if feature not in valid_int_input:
                raise ValueError(
                    f"Integer input to argument `feature` must be one of {valid_int_input}, but got {feature}."
                )
            from metrics_tpu.models.inception import build_fid_inception

            self.inception = build_fid_inception(feature, feature_extractor_weights_path)
            feature_dim = feature  # the bundled heads emit [N, depth] features
        elif callable(feature):
            self.inception = feature
            feature_dim = 2048 if feature_dim is None else feature_dim
        else:
            raise TypeError("Got unknown input to argument `feature`")
        if not (isinstance(feature_dim, int) and feature_dim > 0):
            raise ValueError(f"Argument `feature_dim` expected to be a positive int, got {feature_dim}")
        self._feature_dim = feature_dim

        self._exact = bool(exact)
        if self._exact:
            register_exact_list_states(self, ("real_features", "fake_features"), dist_reduce_fx=None)
            warn_exact_buffer("FrechetInceptionDistance", "extracted features")
        else:
            # the moments_init layout (sketches/moments.py), spelled as
            # literal zeros so the fusibility scan sees the leaf shapes
            d = feature_dim
            self.add_state("real_feat_sum", default=jnp.zeros((d,), jnp.float32), dist_reduce_fx="sum")
            self.add_state("real_outer_sum", default=jnp.zeros((d, d), jnp.float32), dist_reduce_fx="sum")
            self.add_state("real_count", default=jnp.zeros((), jnp.float32), dist_reduce_fx="sum")
            self.add_state("fake_feat_sum", default=jnp.zeros((d,), jnp.float32), dist_reduce_fx="sum")
            self.add_state("fake_outer_sum", default=jnp.zeros((d, d), jnp.float32), dist_reduce_fx="sum")
            self.add_state("fake_count", default=jnp.zeros((), jnp.float32), dist_reduce_fx="sum")

    def _update(self, imgs: Array, real: bool) -> None:
        features = self.inception(imgs)
        if self._exact:
            if real:  # tracelint: disable=TL-TRACE — static dispatch flag: the fused cache keys on `real`, it is always a concrete bool
                self.real_features.append(features)
            else:
                self.fake_features.append(features)
            return
        features = jnp.asarray(features, jnp.float32)
        if features.shape[-1] != self._feature_dim:
            raise ValueError(
                f"Extractor emitted features of width {features.shape[-1]} but the streaming"
                f" moment state was sized for feature_dim={self._feature_dim} — pass the"
                " extractor's true width via `feature_dim` (or use `exact=True`)."
            )
        outer = jnp.matmul(features.T, features, precision=jax.lax.Precision.HIGHEST)
        if real:  # tracelint: disable=TL-TRACE — static dispatch flag: the fused cache keys on `real`, it is always a concrete bool
            self.real_feat_sum = self.real_feat_sum + jnp.sum(features, axis=0)
            self.real_outer_sum = self.real_outer_sum + outer
            self.real_count = self.real_count + features.shape[0]
        else:
            self.fake_feat_sum = self.fake_feat_sum + jnp.sum(features, axis=0)
            self.fake_outer_sum = self.fake_outer_sum + outer
            self.fake_count = self.fake_count + features.shape[0]

    def _compute_exact(self) -> Array:
        real_features = dim_zero_cat(self.real_features)
        fake_features = dim_zero_cat(self.fake_features)
        orig_dtype = real_features.dtype

        # float64 statistics on host — the computation is extremely sensitive
        real = np.asarray(real_features, dtype=np.float64)
        fake = np.asarray(fake_features, dtype=np.float64)

        n = real.shape[0]
        mean1 = real.mean(axis=0)
        mean2 = fake.mean(axis=0)
        diff1 = real - mean1
        diff2 = fake - mean2
        cov1 = diff1.T @ diff1 / (n - 1)
        cov2 = diff2.T @ diff2 / (fake.shape[0] - 1)

        return jnp.asarray(_compute_fid(mean1, cov1, mean2, cov2), dtype=orig_dtype)

    def _compute(self) -> Array:
        getattr(self.inception, "finalize", lambda: None)()  # flush async range check of the last batch
        if self._exact:
            return self._compute_exact()

        mean1, cov1 = mean_cov_from_moments(self.real_feat_sum, self.real_outer_sum, self.real_count)
        mean2, cov2 = mean_cov_from_moments(self.fake_feat_sum, self.fake_outer_sum, self.fake_count)
        diff = mean1 - mean2
        base = diff @ diff + jnp.trace(cov1) + jnp.trace(cov2)
        fid = base - 2.0 * trace_sqrtm_dispatch(cov1, cov2)
        if not bool(jnp.isfinite(fid)):  # tracelint: disable=TL-TRACE — host compute(): the reference's singular-retry check, never traced
            # the reference's singular-product retry (fid.py:95-122): offset
            # the diagonals and rerun the square root. The finiteness check
            # is a host sync, but only on the already-failed path.
            eps = 1e-6
            rank_zero_info(
                f"FID calculation produces singular product; adding {eps} to diagonal of covariance estimates"
            )
            offset = jnp.eye(cov1.shape[0], dtype=jnp.float32) * eps
            fid = base - 2.0 * trace_sqrtm_dispatch(cov1 + offset, cov2 + offset)
        return fid
