"""Modular AUC (generic area under an (x, y) curve).

Behavior parity with /root/reference/torchmetrics/classification/auc.py:24-97.
"""
from typing import Any

import jax

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.auc import _auc_compute, _auc_update
from metrics_tpu.utils.data import dim_zero_cat

Array = jax.Array


class AUC(Metric):
    """Computes the area under a curve given (x, y) points.

    Example:
        >>> import jax.numpy as jnp
        >>> auc = AUC()
        >>> auc(jnp.array([0., 1., 2., 3.]), jnp.array([0., 1., 2., 2.]))
        Array(4., dtype=float32)
    """

    __jit_unsafe__ = True
    is_differentiable = False

    def __init__(self, reorder: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.reorder = reorder
        self.add_state("x", default=[], dist_reduce_fx="cat")
        self.add_state("y", default=[], dist_reduce_fx="cat")

    def _update(self, x: Array, y: Array) -> None:
        x, y = _auc_update(x, y)
        self.x.append(x)
        self.y.append(y)

    def _compute(self) -> Array:
        x = dim_zero_cat(self.x)
        y = dim_zero_cat(self.y)
        return _auc_compute(x, y, reorder=self.reorder)
