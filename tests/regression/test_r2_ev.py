"""R2Score and ExplainedVariance vs sklearn oracles."""
import numpy as np
import pytest
from sklearn.metrics import explained_variance_score as sk_ev, r2_score as sk_r2

from metrics_tpu.functional import explained_variance, r2_score
from metrics_tpu.regression import ExplainedVariance, R2Score
from tests.helpers.testers import BATCH_SIZE, NUM_BATCHES, MetricTester

_rng = np.random.RandomState(7)
_preds = _rng.rand(NUM_BATCHES, BATCH_SIZE).astype(np.float32)
_target = (_rng.rand(NUM_BATCHES, BATCH_SIZE) * 2).astype(np.float32)
NUM_OUTPUTS = 2
_preds_mo = _rng.rand(NUM_BATCHES, BATCH_SIZE, NUM_OUTPUTS).astype(np.float32)
_target_mo = (_rng.rand(NUM_BATCHES, BATCH_SIZE, NUM_OUTPUTS) * 2).astype(np.float32)


@pytest.mark.parametrize("multioutput", ["raw_values", "uniform_average", "variance_weighted"])
@pytest.mark.parametrize(
    "preds, target, num_outputs",
    [(_preds, _target, 1), (_preds_mo, _target_mo, NUM_OUTPUTS)],
)
class TestR2Score(MetricTester):
    atol = 1e-4

    def test_r2_class(self, multioutput, preds, target, num_outputs):
        def sk_wrapped(p, t):
            return sk_r2(np.asarray(t, np.float64), np.asarray(p, np.float64), multioutput=multioutput)

        self.run_class_metric_test(
            preds=preds,
            target=target,
            metric_class=R2Score,
            sk_metric=sk_wrapped,
            metric_args={"multioutput": multioutput, "num_outputs": num_outputs},
        )

    def test_r2_functional(self, multioutput, preds, target, num_outputs):
        self.run_functional_metric_test(
            preds,
            target,
            metric_functional=r2_score,
            sk_metric=lambda p, t: sk_r2(
                np.asarray(t, np.float64), np.asarray(p, np.float64), multioutput=multioutput
            ),
            metric_args={"multioutput": multioutput},
        )


@pytest.mark.parametrize("multioutput", ["raw_values", "uniform_average", "variance_weighted"])
@pytest.mark.parametrize("preds, target", [(_preds, _target), (_preds_mo, _target_mo)])
class TestExplainedVariance(MetricTester):
    atol = 1e-4

    def test_ev_class(self, multioutput, preds, target):
        def sk_wrapped(p, t):
            return sk_ev(np.asarray(t, np.float64), np.asarray(p, np.float64), multioutput=multioutput)

        self.run_class_metric_test(
            preds=preds,
            target=target,
            metric_class=ExplainedVariance,
            sk_metric=sk_wrapped,
            metric_args={"multioutput": multioutput},
        )

    def test_ev_functional(self, multioutput, preds, target):
        self.run_functional_metric_test(
            preds,
            target,
            metric_functional=explained_variance,
            sk_metric=lambda p, t: sk_ev(
                np.asarray(t, np.float64), np.asarray(p, np.float64), multioutput=multioutput
            ),
            metric_args={"multioutput": multioutput},
        )


def test_r2_needs_two_samples():
    import jax.numpy as jnp

    with pytest.raises(ValueError):
        r2_score(jnp.array([1.0]), jnp.array([1.0]))


def test_invalid_multioutput():
    with pytest.raises(ValueError):
        R2Score(multioutput="invalid")
    with pytest.raises(ValueError):
        ExplainedVariance(multioutput="invalid")
