#!/usr/bin/env python
"""Gate CI on bench regressions: wall-time AND compiled-cost drift.

Diffs two bench artifacts (JSON-lines files as emitted by ``bench.py`` —
one object per config, e.g. the committed ``BENCH_rNN.json`` rounds)::

    python scripts/check_cost_regression.py BENCH_new.json --baseline BENCH_r05.json
    python scripts/check_cost_regression.py BENCH_new.json --baseline BENCH_r05.json \
        --tolerance 0.10 --cost-tolerance 0.02

Two independent checks per metric present in BOTH artifacts:

* **wall time** — the ``value`` field, direction-aware by unit: ``ms``
  units are latencies (higher = regression), every other unit is a
  throughput (lower = regression). Fails when the current value is worse
  than baseline by more than ``--tolerance`` (relative, default 10% — wall
  clock is noisy).
* **compiled cost** — the ``cost_analysis.flops`` / ``.bytes_accessed``
  fields that ``bench.py --cost-analysis`` embeds. The compiler's estimate
  is deterministic for a fixed graph, so the default ``--cost-tolerance``
  is tight (1%): any real growth in compiled flops/bytes is a code change,
  not noise. Missing cost fields on either side skip the check (older
  artifacts predate ``--cost-analysis``).

Exit status 0 when clean, 1 with a per-metric listing otherwise; entries
with an ``error`` field and metrics present on only one side are reported
but never fail the gate (configs come and go between rounds).
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional, Tuple


def load_records(path: str) -> Dict[str, Dict[str, Any]]:
    """Parse a JSON-lines bench artifact into {metric_name: record}; later
    lines win (bench re-runs append)."""
    records: Dict[str, Dict[str, Any]] = {}
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            name = obj.get("metric")
            if name:
                records[name] = obj
    return records


#: extra per-record fields gated beyond value/cost_analysis — the fused
#: MetricCollection bench (``collection_fused_update_throughput``) carries
#: its speedup ratio, its compilation count, and its manifest-seeded
#: first-batch setup latency in-line; losing any of them (fused drops under
#: eager, bucketed shapes stop sharing a compile, or the fusibility
#: manifest stops pre-seeding the probes and cold starts regress) is a
#: regression even when raw wall throughput still passes
#: the async ingest bench (``collection_async_update_throughput``) likewise
#: carries its speedup over the blocking fused loop and the p99 enqueue
#: latency; async dropping below blocking, or the hot-path enqueue growing a
#: blocking wait, is a regression even when raw throughput still passes
#: the sliced bench (``sliced_update_throughput``) carries its speedup over
#: the S-object fan-out and its compile count across bucketed ragged shapes;
#: sliced dropping toward object-fan-out territory, or the scatter kernel
#: recompiling per batch shape, is a regression even when raw wall
#: throughput still passes
AUX_FIELDS: Dict[str, str] = {
    "fused_vs_eager": "higher",
    "bucketed_compiles": "lower",
    "fused_first_batch_ms": "lower",
    "async_vs_blocking": "higher",
    "update_async_p99_ms": "lower",
    "sliced_vs_fanout": "higher",
    "sliced_scatter_compiles": "lower",
    "sketch_state_bytes_frac": "lower",
    "sketch_auroc_abs_err": "lower",
    "sketch_fused_compiles": "lower",
    "fused_telemetry_on_ratio": "higher",
    "windowed_vs_plain": "higher",
    "windowed_compiles": "lower",
    "collector_fold_per_sec": "higher",
    "wire_bytes_per_snapshot": "lower",
    # the ops kernel-suite bench (``ops_kernel_dispatch_throughput``)
    # carries the worst dispatched-vs-direct wall ratio across ops: the
    # shared dispatch layer growing a per-call tax on every bincount /
    # segment-scatter / compaction is a regression even when the headline
    # throughput still passes
    "ops_dispatch_overhead": "lower",
    # fused table-state retrieval (``fused_retrieval_throughput``): the
    # ISSUE 15 acceptance floor (>= 5x over the eager per-query group
    # loop at 10k queries) and the one-compile-across-ragged-shapes anchor
    "retrieval_fused_vs_eager": "higher",
    "retrieval_fused_compiles": "lower",
    # the read-plane bench (``read_plane_throughput``): instrumented-vs-off
    # subset-read throughput under concurrent async ingest — the typed read
    # event + freshness stamp growing a per-read tax is a regression even
    # when the absolute reads/sec still passes
    "read_event_overhead_ratio": "higher",
    # the incremental read plane (ISSUE 17 acceptance floor): the median
    # cold full fold over the median dirty-subset incremental read at
    # <=0.5% dirty slices of S=100k must stay >= 5x — the dirty bitmap,
    # per-slice value cache, and bucketed AOT subset readers losing their
    # edge over a whole-axis refold is the regression this PR exists to
    # prevent
    "incremental_vs_full": "higher",
    # the memory-plane bench (``memory_plane_throughput``): armed-vs-disarmed
    # S=100k async ingest throughput — the boundary hooks + observatory
    # polls growing a per-update tax past the <=5% acceptance ceiling is a
    # regression even when the absolute updates/sec still passes — and the
    # ledger's per-tenant attribution, whose growth means sliced state the
    # budget rule meters got silently heavier
    "memory_plane_on_ratio": "higher",
    "bytes_per_tenant": "lower",
    # the image/detection state bench (``image_detection_throughput``,
    # ISSUE 19): the end-to-end fused-table-over-eager-list mAP wall ratio
    # (acceptance floor 5x — the anchor is set so the 10% tolerance lands
    # the gate there), the streaming-FID-over-cat-state footprint fraction
    # at a 1e5-feature stream (acceptance ceiling 0.05 — the moment state
    # is O(d^2) forever, growth means a state leaf regressed to O(N)), and
    # the device Newton-Schulz trace-sqrtm's absolute error vs the host
    # f64 eigh oracle (a broken iteration errs at O(1), not O(1e-3))
    "map_fused_vs_eager": "higher",
    "fid_state_bytes_frac": "lower",
    "newton_schulz_abs_err": "lower",
}

#: boolean invariants gated whenever the CURRENT record carries them — a
#: bench that reports a false parity bit (async final states diverged from
#: the blocking path) is broken no matter how fast it ran, and the
#: ratio/wall checks above would pass it silently
BOOL_FIELDS: Tuple[str, ...] = (
    "states_bit_identical",
    "sketch_window_bit_exact",
    "windowed_ring_fold_exact",
    # exactly-one-compile as a BOOL: the "lower"-direction AUX gate on
    # windowed_compiles would pass n_compiles == 0 — a total eager
    # demotion, the very regression the anchor exists to catch
    "windowed_fused",
    # arrival-order independence of the fleet collector fold (bit-identical
    # leaves + byte-identical exposition) — broken determinism is data
    # corruption however fast the fold runs
    "collector_fold_deterministic",
    # ops kernel-vs-fallback parity on integer-exact data (interpret mode
    # runs the real kernel bodies): a kernel diverging from its jnp
    # fallback is data corruption on every metric built on it, however
    # fast it dispatches
    "ops_bincount_parity",
    "ops_segment_sum_parity",
    "ops_qsketch_compact_parity",
    # retrieval table-state window parity (state-level reconstruction
    # bit-equality + value within f32 ulp of the exact path) and the new
    # kernels' interpret-mode parity — a false bit is data corruption on
    # every retrieval metric regardless of the throughput ratio
    "retrieval_window_bit_exact",
    "ops_row_topk_parity",
    "ops_segment_max_parity",
    "ops_segment_min_parity",
    # freshness-stamp exactness on an injected known-age stream: the read
    # event's staleness must land within one telemetry bucket of ground
    # truth — a stamp that drifts from the ingest wall clock is a lying
    # dashboard however cheap the read plane is
    "freshness_stamp_exact",
    # incremental-read parity: every gated incremental read's values must
    # be bit-identical (tobytes equality) to a cold full fold at the same
    # ids — the incremental plane changes WHEN folds run, never WHAT they
    # compute, and a fast-but-wrong cached read is data corruption however
    # large the speedup ratio
    "incremental_read_bit_exact",
    # memory accounting honesty: the ledger must never claim more live
    # state than the backend reports (unaccounted residue non-negative
    # within allocator slack; vacuously true where the backend exposes no
    # memory_stats), and the residue must return to its post-warmup
    # baseline across update/compute/reset cycles — a growing residue is
    # the leak signal the observatory exists to expose, and a lying ledger
    # breaks every budget/leak alarm built on it
    "ledger_matches_backend",
    "unaccounted_non_growing",
    # image/detection streaming-state parity (ISSUE 19): streaming mAP
    # compute() must equal the exact=True list path on every result key
    # inside the capacity window, and the streaming FID moment leaves must
    # be bit-identical to f64 oracle sums cast to f32 on dyadic features
    # (every sum exactly representable — a false bit is an update-path
    # bug, not float noise); fused-vs-eager state equality rides the
    # existing states_bit_identical field
    "map_window_bit_exact",
    "fid_identity_bit_exact",
)


def _lower_is_better(record: Dict[str, Any]) -> bool:
    """Latency-style units (ms, ns/call, ...) regress upward; rate units
    (x/sec) regress downward. Anything that is not a per-second rate is
    treated as a latency/cost — the conservative default for unknown
    units, since passing a real regression is worse than flagging a win."""
    unit = str(record.get("unit", "")).lower()
    return not ("/sec" in unit or unit.endswith("/s"))


def compare(
    current: Dict[str, Dict[str, Any]],
    baseline: Dict[str, Dict[str, Any]],
    tolerance: float = 0.10,
    cost_tolerance: float = 0.01,
) -> Tuple[List[str], List[str]]:
    """Returns (regressions, notes) — human-readable lines. A non-empty
    regressions list means the gate fails."""
    regressions: List[str] = []
    notes: List[str] = []
    for name in sorted(set(current) | set(baseline)):
        cur, base = current.get(name), baseline.get(name)
        # boolean invariants gate on the CURRENT record alone, BEFORE the
        # both-sides requirement: a brand-new bench (no baseline anchor
        # committed yet) must still fail on a false parity bit
        if cur is not None and "error" not in cur:
            for field in BOOL_FIELDS:
                flag = cur.get(field)
                if flag is False:
                    regressions.append(f"{name}: {field} is false — invariant broken")
                elif flag is True:
                    notes.append(f"{name}: {field} ok")
        if cur is None or base is None:
            notes.append(f"{name}: only in {'baseline' if cur is None else 'current'} — skipped")
            continue
        if "error" in cur or "error" in base:
            notes.append(f"{name}: carries an error field — skipped")
            continue

        cv, bv = cur.get("value"), base.get("value")
        if isinstance(cv, (int, float)) and isinstance(bv, (int, float)) and bv:
            lower_better = _lower_is_better(base)
            ratio = cv / bv
            worse = ratio > 1 + tolerance if lower_better else ratio < 1 - tolerance
            arrow = f"{bv:g} -> {cv:g} {base.get('unit', '')}".strip()
            if worse:
                regressions.append(
                    f"{name}: wall-time regression {arrow}"
                    f" ({abs(ratio - 1) * 100:.1f}% worse, tolerance {tolerance * 100:.0f}%)"
                )
            else:
                notes.append(f"{name}: wall ok ({arrow})")

        for field in ("flops", "bytes_accessed"):
            cc = _cost_field(cur, field)
            bc = _cost_field(base, field)
            if cc is None or bc is None or not bc:
                continue
            ratio = cc / bc
            if ratio > 1 + cost_tolerance:
                regressions.append(
                    f"{name}: compiled {field} regression {bc:g} -> {cc:g}"
                    f" (+{(ratio - 1) * 100:.2f}%, tolerance {cost_tolerance * 100:.0f}%)"
                )
            elif ratio < 1 - cost_tolerance:
                notes.append(f"{name}: compiled {field} improved {bc:g} -> {cc:g}")

        for field, direction in AUX_FIELDS.items():
            cv, bv = cur.get(field), base.get(field)
            if not (isinstance(cv, (int, float)) and isinstance(bv, (int, float))) or not bv:
                continue
            ratio = cv / bv
            worse = ratio < 1 - tolerance if direction == "higher" else ratio > 1 + tolerance
            if worse:
                regressions.append(
                    f"{name}: {field} regression {bv:g} -> {cv:g}"
                    f" ({abs(ratio - 1) * 100:.1f}% worse, tolerance {tolerance * 100:.0f}%)"
                )
            else:
                notes.append(f"{name}: {field} ok ({bv:g} -> {cv:g})")
    return regressions, notes


def _cost_field(record: Dict[str, Any], field: str) -> Optional[float]:
    cost = record.get("cost_analysis")
    if not isinstance(cost, dict):
        return None
    value = cost.get(field)
    return float(value) if isinstance(value, (int, float)) else None


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("current", help="bench JSON-lines artifact to check")
    parser.add_argument("--baseline", required=True, help="bench artifact to compare against")
    parser.add_argument("--tolerance", type=float, default=0.10, help="relative wall-time slack (default 0.10)")
    parser.add_argument(
        "--cost-tolerance", type=float, default=0.01, help="relative compiled-cost slack (default 0.01)"
    )
    args = parser.parse_args(argv)

    regressions, notes = compare(
        load_records(args.current),
        load_records(args.baseline),
        tolerance=args.tolerance,
        cost_tolerance=args.cost_tolerance,
    )
    for line in notes:
        print(f"  note: {line}")
    if regressions:
        print(f"FAIL: {len(regressions)} regression(s) vs {args.baseline}")
        for line in regressions:
            print(f"  {line}")
        return 1
    print(f"OK: no regressions vs {args.baseline}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
