"""Modular PerceptualEvaluationSpeechQuality.

The reference wraps the external `pesq` C library
(/root/reference/torchmetrics/audio/pesq.py:25-118) — ITU-T P.862 is ~5k LoC
of licensed DSP C that is inherently host-side per-utterance (SURVEY §2.9).
DECISION: rather than re-implementing P.862, this class keeps the reference's
exact metric surface (fs/mode validation, sum/count states, per-utterance
averaging) and takes the scorer as an injectable host callable ``pesq_fn(ref,
deg, fs, mode) -> float`` — the `pesq` package's ``pesq`` function slots in
unchanged where it is installed. Constructing without a scorer raises the
same ModuleNotFoundError shape as the reference does without the package.
"""
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.core.metric import Metric

Array = jax.Array


class PerceptualEvaluationSpeechQuality(Metric):
    """Average PESQ over accumulated utterances (scorer injected host-side).

    Args:
        fs: sampling frequency (8000 for narrow-band, 16000 for wide-band).
        mode: 'nb' (narrow-band) or 'wb' (wide-band; requires fs=16000).
        pesq_fn: host callable ``(ref, deg, fs, mode) -> float`` implementing
            ITU-T P.862 (e.g. ``pesq.pesq`` reordered); required.
    """

    is_differentiable = False
    higher_is_better = True
    __jit_unsafe__ = True  # per-utterance host DSP

    def __init__(self, fs: int, mode: str, pesq_fn: Optional[Callable] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if fs not in (8000, 16000):
            raise ValueError(f"Expected argument `fs` to either be 8000 or 16000 but got {fs}")
        self.fs = fs
        if mode not in ("wb", "nb"):
            raise ValueError(f"Expected argument `mode` to either be 'wb' or 'nb' but got {mode}")
        if mode == "wb" and fs == 8000:
            raise ValueError("Wide-band PESQ ('wb') requires fs=16000")
        self.mode = mode

        if pesq_fn is None:
            try:  # use the C-library binding when present (reference behavior)
                from pesq import pesq as _pesq

                pesq_fn = lambda ref, deg, fs_, mode_: _pesq(fs_, ref, deg, mode_)
            except ImportError:
                raise ModuleNotFoundError(
                    "PESQ metric requires an ITU-T P.862 scorer: install the `pesq` package"
                    " or pass `pesq_fn(ref, deg, fs, mode) -> float` explicitly."
                )
        self.pesq_fn = pesq_fn

        self.add_state("sum_pesq", default=jnp.asarray(0.0, jnp.float32), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0, jnp.int32), dist_reduce_fx="sum")

    def _update(self, preds: Array, target: Array) -> None:
        preds_np = np.asarray(preds, np.float64)
        target_np = np.asarray(target, np.float64)
        if preds_np.shape != target_np.shape:
            raise ValueError("preds and target must have the same shape")
        preds_np = preds_np.reshape(-1, preds_np.shape[-1])
        target_np = target_np.reshape(-1, target_np.shape[-1])
        for deg, ref in zip(preds_np, target_np):
            self.sum_pesq = self.sum_pesq + float(self.pesq_fn(ref, deg, self.fs, self.mode))
            self.total = self.total + 1

    def _compute(self) -> Array:
        return self.sum_pesq / self.total
