"""Core metric runtime: the ``Metric`` base class and compositional algebra.

Behavior parity with /root/reference/torchmetrics/metric.py (902 LoC): state
registry (``add_state``), double-update ``forward`` semantics (:264-300),
sync/unsync state machine (:329-419), compute caching (:430-489), reset,
persistence, kwarg filtering, and the 30+ operator compositional algebra
(:685-902).

TPU-first design departures from the reference:

* Metric state is an explicit **pytree** (dict of ``jax.Array`` leaves or
  lists thereof); ``update``/``compute`` numerics live in pure functions
  (``metrics_tpu.functional``) that are jit-compiled, so the class here is a
  thin host-side wrapper holding the pytree.
* A **pure-functional state API** (``init_state`` / ``update_state`` /
  ``compute_state`` / ``merge_states``) exposes every metric as pure
  ``(state, batch) -> state`` transforms usable *inside* a jitted train step
  or a ``shard_map`` over a device mesh — something the torch reference
  cannot do (its update mutates module buffers eagerly).
* Cross-process sync maps ``dist_reduce_fx`` onto XLA collectives
  (see metrics_tpu/parallel/distributed.py) instead of
  gather-then-reduce over NCCL/Gloo.
"""
from __future__ import annotations

import functools
import inspect
import operator
import time
from abc import ABC, abstractmethod
import contextlib
from contextlib import contextmanager
from copy import deepcopy
from typing import Any, Callable, Dict, Generator, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.utils.data import (
    _flatten,
    _squeeze_if_scalar,
    apply_to_collection,
    dim_zero_cat,
    dim_zero_max,
    dim_zero_mean,
    dim_zero_min,
    dim_zero_sum,
    torch_to_numpy,
)
from metrics_tpu.utils.exceptions import MetricsUserError
from metrics_tpu.utils.prints import rank_zero_warn
from metrics_tpu.observability.freshness import FreshnessStamp
from metrics_tpu.observability.memory import _track_metric
from metrics_tpu.observability.recorder import _DEFAULT_RECORDER as _TELEMETRY
from metrics_tpu.observability.recorder import SKETCH_FOOTPRINT_PREFIX, _nbytes
from metrics_tpu.observability.trace import span as _span
from metrics_tpu.parallel.distributed import distributed_available as _dist_available
from metrics_tpu.parallel.distributed import gather_all_arrays
from metrics_tpu.parallel.distributed import world_size as _world_size

Array = jax.Array
StateValue = Union[Array, List[Array]]

#: auto-registered update counter accompanying any mean-reduced state — the
#: default weights for `merge_states` on uneven accumulations (sum-reduced
#: with negative-sentinel propagation, so cross-rank syncs and pairwise
#: merges compose)
_AUTO_COUNT = "_n_updates"


def _sentinel_count_sum(x: "Array") -> "Array":
    """Dim-zero sum of per-rank `_n_updates` counters that PROPAGATES the
    pre-counter-checkpoint sentinel: if any rank's counter is negative
    ("history unknown", see ``load_state_dict``), the reduced counter is -1
    instead of a confident wrong sum — a plain sum would launder the
    sentinel into a positive count missing that rank's accumulation, and
    ``merge_states`` would then trust it as a weight. Used by both the
    host-level ``_sync_dist`` gather-reduce and (as the callable-reducer
    path of ``sync_in_mesh``) in-jit mesh syncs."""
    x = jnp.asarray(x)
    return jnp.where(jnp.all(x >= 0), jnp.sum(x, axis=0), jnp.asarray(-1, x.dtype))


#: concrete types known to pass through coercion unchanged — `isinstance`
#: against the abstract ``jax.Array`` costs more than the recursion it
#: guards, so the fast path keys on exact types, learning each concrete
#: jax array/tracer type the first time the slow path clears it
_NATIVE_LEAF_TYPES = {np.ndarray}


def _coerce_foreign(obj: Any) -> Any:
    """Convert foreign array types (torch tensors — the reference's native
    inputs) to jax arrays, recursing through lists/tuples/dicts; everything
    else (jax/numpy arrays, strings, scalars) passes through unchanged.

    The common hot-path case — every top-level leaf already a jax/numpy
    array — returns the input object untouched (same identity) without
    recursing: one exact-type set lookup per leaf. ``bench.py telemetry``
    pins the cost."""
    t = type(obj)
    if t in _NATIVE_LEAF_TYPES:
        return obj
    if (t is tuple or t is list) and all(type(o) in _NATIVE_LEAF_TYPES for o in obj):
        return obj
    if hasattr(obj, "detach") and hasattr(obj, "cpu") and hasattr(obj, "numpy"):
        return jnp.asarray(torch_to_numpy(obj))
    if isinstance(obj, tuple):
        return tuple(_coerce_foreign(o) for o in obj)
    if isinstance(obj, list):
        return [_coerce_foreign(o) for o in obj]
    if isinstance(obj, dict):
        return {k: _coerce_foreign(v) for k, v in obj.items()}
    if isinstance(obj, jnp.ndarray):
        _NATIVE_LEAF_TYPES.add(t)
    return obj


class Metric(ABC):
    """Base class for all metrics.

    Subclasses implement ``_update(self, ...)`` (reading and assigning the
    registered state attributes) and ``_compute(self)`` returning the value.
    The public ``update``/``compute``/``forward``/``reset`` lifecycle,
    caching, and distributed synchronization are provided here.
    """

    __jit_unsafe__: bool = False  # set True on metrics whose update cannot be traced
    is_differentiable: Optional[bool] = None
    higher_is_better: Optional[bool] = None

    def __init__(
        self,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
        compute_on_step: Optional[bool] = None,
    ) -> None:
        self._device = None
        self._dtype = jnp.float32

        if compute_on_step is not None:
            rank_zero_warn(
                "Argument `compute_on_step` is deprecated and has no effect; `forward` always"
                " returns the batch value.",
                DeprecationWarning,
            )
        # constructor-kwarg validation parity with reference metric.py:137-147
        if not isinstance(dist_sync_on_step, bool):
            raise ValueError(
                f"Expected keyword argument `dist_sync_on_step` to be an `bool` but got {dist_sync_on_step}"
            )
        if dist_sync_fn is not None and not callable(dist_sync_fn):
            raise ValueError(
                f"Expected keyword argument `dist_sync_fn` to be an callable function but got {dist_sync_fn}"
            )
        self.dist_sync_on_step = dist_sync_on_step
        self.process_group = process_group
        self.dist_sync_fn = dist_sync_fn

        self._update_called = False
        self._to_sync = True
        self._should_unsync = True
        self._forward_cache: Any = None
        self._computed: Any = None
        # --- write-epoch clock (incremental read plane) ---------------
        # `_write_epoch` is a host-side monotonic counter bumped on EVERY
        # state mutation (update, fused/async apply, reset, restore,
        # checkpoint load, collection group-borrow); `_computed_epoch` is
        # the epoch the cached `_computed` value was folded at. The pair
        # replaces the blunt `_computed = None` wipe as the cache-validity
        # test (`_computed` is still nulled on writes for back-compat with
        # callers that poke it), and gives subclasses a single clock to key
        # their own incremental caches on: SlicedMetric's per-slice value
        # cache, WindowedMetric's partial ring folds, and RetrievalMetric's
        # table-layout memo are all epoch-keyed. Plain Python ints — never
        # traced, never device-resident — so tracelint's TL-STATE rule
        # whitelists them as legal non-leaf writes.
        self._write_epoch: int = 0
        self._computed_epoch: int = -1
        # wall clock of the first/last ingested batch (telemetry-enabled
        # updates only — freshness stamping is part of the telemetry plane
        # and the disabled hot path must stay one bool check)
        self._ingest_first_t: Optional[float] = None
        self._ingest_last_t: Optional[float] = None
        self._defaults: Dict[str, StateValue] = {}
        self._persistent: Dict[str, bool] = {}
        self._reductions: Dict[str, Union[str, Callable, None]] = {}
        self._cat_states: Dict[str, bool] = {}
        self._children: Dict[str, "Metric"] = {}

        self._is_synced = False
        self._cache: Optional[Dict[str, StateValue]] = None

        # weak registration with the memory observatory (observability/
        # memory.py): the default MemoryLedger walks every live metric's
        # state pytree without the user threading instances around. Weak —
        # never extends the metric's lifetime — and never fails construction.
        _track_metric(self)

    # ------------------------------------------------------------------
    # child-metric registry (minimal nn.Module-style nesting for wrappers)
    # ------------------------------------------------------------------
    def __setattr__(self, name: str, value: Any) -> None:
        children = self.__dict__.get("_children")
        if children is not None and name != "_children":
            if isinstance(value, Metric):
                children[name] = value
            elif isinstance(value, list) and value and all(isinstance(v, Metric) for v in value):
                # lists of child metrics (BootStrapper/MultioutputWrapper copies)
                children[name] = value
            elif name in children:
                del children[name]
        if name in ("higher_is_better", "is_differentiable") and self.__dict__.get("_defaults") is not None:
            raise RuntimeError(f"Can't change const `{name}`.")
        object.__setattr__(self, name, value)

    def _iter_child_metrics(self) -> "Generator[tuple, None, None]":
        """Yield (name, metric) for every registered child, flattening lists."""
        for name, child in self._children.items():
            if isinstance(child, list):
                for i, c in enumerate(child):
                    yield f"{name}.{i}", c
            else:
                yield name, child

    def _snapshot_state(self) -> Dict[str, Any]:
        """Recursive snapshot of own + child states (used by ``forward`` so the
        batch-value cycle cannot wipe wrapped metrics' accumulation)."""
        return {
            "own": {attr: getattr(self, attr) for attr in self._defaults},
            "children": {n: c._snapshot_state() for n, c in self._iter_child_metrics()},
            "update_called": self._update_called,
        }

    def _restore_state(self, snap: Dict[str, Any]) -> None:
        for attr, val in snap["own"].items():
            object.__setattr__(self, attr, val)
        for n, c in self._iter_child_metrics():
            if n in snap["children"]:
                c._restore_state(snap["children"][n])
        self._update_called = snap["update_called"]
        self._mark_state_written()
        self._is_synced = False

    # ------------------------------------------------------------------
    # state registry
    # ------------------------------------------------------------------
    def add_state(
        self,
        name: str,
        default: StateValue,
        dist_reduce_fx: Optional[Union[str, Callable]] = None,
        persistent: bool = False,
    ) -> None:
        """Register a metric state variable.

        ``default`` must be an array (reduced across processes via
        ``dist_reduce_fx``) or an empty list (all-gathered and flattened).
        String reducers ``"sum"/"mean"/"max"/"min"/"cat"`` map to the
        dim-zero functions; parity with reference metric.py:194-261.
        """
        if isinstance(default, list):
            if default:
                raise ValueError("state variable must be an array or an empty list (where you can append arrays)")
        else:
            try:
                default = jnp.asarray(default)
            except (TypeError, ValueError):
                raise ValueError("state variable must be an array or an empty list (where you can append arrays)")

        if dist_reduce_fx == "sum":
            dist_reduce_fx = dim_zero_sum
        elif dist_reduce_fx == "mean":
            dist_reduce_fx = dim_zero_mean
        elif dist_reduce_fx == "max":
            dist_reduce_fx = dim_zero_max
        elif dist_reduce_fx == "min":
            dist_reduce_fx = dim_zero_min
        elif dist_reduce_fx == "cat":
            dist_reduce_fx = dim_zero_cat
        elif dist_reduce_fx == "merge":
            # sketch-leaf states (metrics_tpu/sketches/): the leaf carries its
            # own cross-rank merge. The string form covers the standard packed
            # quantile-sketch layout; other kinds pass their tagged
            # ``*_merge_fx()`` callable directly.
            from metrics_tpu.sketches.quantile import sketch_merge_fx

            dist_reduce_fx = sketch_merge_fx()
        elif dist_reduce_fx == "ring":
            # windowed ring-of-sums leaf (metrics_tpu/windowed/): same-bucket
            # rows add elementwise across ranks, but the leaf must stay
            # distinct from dim_zero_sum so the fused pad correction defers
            # to the wrapper's slot-aware one (see windowed/reducers.py)
            from metrics_tpu.windowed.reducers import ring_sum_fx

            dist_reduce_fx = ring_sum_fx()
        elif dist_reduce_fx == "decay":
            # exponentially-decayed sum leaf: lock-stepped decayed streams
            # stay additive across ranks — sum fold, windowed-tagged
            from metrics_tpu.windowed.reducers import decay_sum_fx

            dist_reduce_fx = decay_sum_fx()
        elif dist_reduce_fx is not None and not callable(dist_reduce_fx):
            raise ValueError(
                "`dist_reduce_fx` must be callable or one of"
                " ['mean', 'sum', 'cat', 'min', 'max', 'merge', 'ring', 'decay', None]"
            )

        if isinstance(default, list):
            setattr(self, name, [])
        else:
            setattr(self, name, default)
        self._defaults[name] = deepcopy(default)
        self._persistent[name] = persistent
        self._reductions[name] = dist_reduce_fx
        # Explicit concat-semantics flag instead of reducer-identity checks:
        # a custom reducer opts in by carrying a truthy ``cat_like`` attribute.
        # (List states with a None reducer — gathered, NOT reduced, e.g.
        # detection's per-image boxes — keep element identity and are not
        # cat-like.)
        self._cat_states[name] = dist_reduce_fx is dim_zero_cat or bool(
            getattr(dist_reduce_fx, "cat_like", False)
        )
        # Mean-reduced states have no information-preserving pairwise merge
        # without knowing how many updates each side absorbed, so the first
        # mean state auto-registers a sum-reduced update counter that
        # `merge_states` uses as the default weights (see merge_states).
        if dist_reduce_fx is dim_zero_mean and _AUTO_COUNT not in self._defaults:
            self.add_state(_AUTO_COUNT, default=jnp.asarray(0, jnp.int32), dist_reduce_fx=_sentinel_count_sum)

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    @abstractmethod
    def _update(self, *args: Any, **kwargs: Any) -> None:
        """Accumulate batch statistics into the registered states."""

    @abstractmethod
    def _compute(self) -> Any:
        """Compute the final value from the accumulated states."""

    #: set True (class- or instance-level) to wrap update/compute in named
    #: jax.profiler traces so metric cost shows up in TPU profiles (SURVEY §5:
    #: the reference has no tracing; this is a new opt-in capability)
    enable_profiling: bool = False

    def _profiler_annotation(self, phase: str):
        if self.enable_profiling:
            return jax.profiler.TraceAnnotation(f"{self.__class__.__name__}.{phase}")
        return contextlib.nullcontext()

    def _trace_annotation(self, phase: str):
        """The per-phase tracing context: a ``jax.profiler.TraceAnnotation``
        when ``enable_profiling`` is set (device profiles), a telemetry
        :func:`~metrics_tpu.observability.span` when the recorder is enabled
        (host-side nesting), both when both are on, and a no-op otherwise."""
        prof = self._profiler_annotation(phase) if self.enable_profiling else None
        if _TELEMETRY.enabled:
            sp = _span(f"{self.__class__.__name__}.{phase}")
            if prof is None:
                return sp
            stack = contextlib.ExitStack()
            stack.enter_context(prof)
            stack.enter_context(sp)
            return stack
        return prof if prof is not None else contextlib.nullcontext()

    def _bump_auto_count(self) -> None:
        """Increment the auto-registered mean-merge update counter (a no-op
        for metrics without mean-reduced states); jit-safe. A negative
        counter is the pre-counter-checkpoint sentinel (see
        ``load_state_dict``) and must STAY negative: updates after such a
        restore would otherwise rebuild a small positive count that misses
        the restored accumulation history, and ``merge_states`` would trust
        it as a confident underweight.

        Eager fast path: outside jit the counter stays a plain Python int —
        the first bump after a reset/restore pays one host readback to
        concretize it, and every later bump is host arithmetic instead of a
        ``jnp.where`` device dispatch per update. Inside jit (tracer
        counter, e.g. via ``update_state``) the jit-safe ``where`` form is
        kept. Sync/checkpoint boundaries re-materialize the int as an int32
        array, so the functional/distributed contracts are unchanged."""
        if _AUTO_COUNT not in self._defaults:
            return
        count = getattr(self, _AUTO_COUNT)
        if isinstance(count, int):
            if count >= 0:
                object.__setattr__(self, _AUTO_COUNT, count + 1)
            return
        if (
            isinstance(count, jnp.ndarray)
            and not isinstance(count, jax.core.Tracer)
            # a multi-host global array (shard_states over a mesh) cannot be
            # concretized on one host; it keeps the device-side bump
            and getattr(count, "is_fully_addressable", True)
        ):
            c = int(count)
            object.__setattr__(self, _AUTO_COUNT, c + 1 if c >= 0 else c)
            return
        object.__setattr__(self, _AUTO_COUNT, jnp.where(count < 0, count, count + 1))

    def _mark_state_written(self) -> None:
        """Record an OUT-OF-BAND state mutation on the write-epoch clock:
        reset, snapshot restore, checkpoint load, distributed install, and
        collection group-borrow all route here. Bumps ``_write_epoch`` and
        nulls the cached value; subclasses with incremental read caches
        override to additionally degrade them to cold (all-dirty /
        fold-memo drop) — external writers cannot say WHAT changed, so the
        only never-wrong answer is "everything". ``update()`` does NOT call
        this: its own inline bump lets ``_update`` implementations keep
        fine-grained dirty information (e.g. SlicedMetric marking only the
        scattered slice ids)."""
        self._write_epoch += 1
        self._computed = None

    def _mark_fused_written(self) -> None:
        """Install hook for the fused single-dispatch apply path
        (``FusedUpdate``/async drain): the kernel just wrote this metric's
        states, so advance the epoch clock and mark the update observed.
        The fused trace saw only tracers, so the base behavior is the
        all-dirty degrade of :meth:`_mark_state_written`; subclasses whose
        fused kernel performs exactly their normal state transform (e.g.
        WindowedMetric's ring rotation) override to keep their incremental
        caches warm instead."""
        self._update_called = True
        self._mark_state_written()

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Accumulate into global state. Parity with reference metric.py:421-428,460-463.

        Inputs are coerced at this boundary: torch tensors (the reference's
        native input type) convert to jax arrays host-side, recursively
        through lists/tuples/dicts (detection-style structured inputs), so
        reference users can switch frameworks without touching their data
        pipeline. Strings and other non-array leaves pass through untouched.
        """
        self._write_epoch += 1
        self._computed = None
        self._update_called = True
        if not _TELEMETRY.enabled:  # disabled telemetry costs this ONE check
            with self._trace_annotation("update"):
                self._update(*_coerce_foreign(args), **_coerce_foreign(kwargs))
            self._bump_auto_count()
            return
        t0 = time.perf_counter()
        now = time.time()
        if self._ingest_first_t is None:
            self._ingest_first_t = now
        self._ingest_last_t = now
        coerced_args = _coerce_foreign(args)
        coerced_kwargs = _coerce_foreign(kwargs)
        with self._trace_annotation("update"):  # annotation + telemetry span
            self._update(*coerced_args, **coerced_kwargs)
            self._bump_auto_count()
            # recorded INSIDE the span so the update event carries its id
            is_new_sig = _TELEMETRY.record_call(
                "update", self, time.perf_counter() - t0, args, kwargs
            )
        if is_new_sig and _TELEMETRY.profile_compiles:
            # a NEW signature at this entry point = an XLA recompile of the
            # metric's jitted kernels; bill it via the compiler's own cost
            # analysis (observability/profiling.py) — opt-in, cold path only.
            # The COERCED arguments are billed: jax cannot trace raw torch
            # tensors, and they are what the jitted kernels actually see
            from metrics_tpu.observability.profiling import metric_compile_cost

            metric_compile_cost(self, coerced_args, coerced_kwargs, phase="update")
        if _TELEMETRY.footprint_warn_bytes is not None:
            fp = self.state_footprint()
            _TELEMETRY.record_footprint(
                self,
                fp,
                theoretical_bytes=int(self.theoretical_state_bytes()),
                live_bytes=int(sum(fp.values())),
            )
        # boundary counter is exact; the typed event row (with a live state
        # walk) is throttled inside the recorder, so eager loops stay cheap
        _TELEMETRY.record_memory_boundary("update", self, live_bytes=self.total_state_bytes)

    def compute(self) -> Any:
        """Compute (and cache) the metric from accumulated state, syncing across
        processes first when distributed. Parity with reference metric.py:430-489."""
        if not self._update_called:
            rank_zero_warn(
                f"The ``compute`` method of metric {self.__class__.__name__} was called before"
                " the ``update`` method which may lead to errors, as metric states have not yet been updated.",
                UserWarning,
            )
        # epoch-keyed cache hit: the cached value must exist AND have been
        # folded at the current write epoch — a concurrent async apply (or
        # any out-of-band install) that bumped the clock mid-/post-compute
        # makes the pair unequal and forces a cold fold, so a stale value is
        # never served even when `_computed` survived the wipe
        if self._computed is not None and self._computed_epoch == self._write_epoch:
            if _TELEMETRY.enabled:  # disabled read path stays ONE bool check
                _TELEMETRY.record_read(
                    "compute",
                    self,
                    cache_hit=True,
                    leaves=len(self._defaults),
                    freshness=self.freshness_stamp(),
                )
            return self._computed

        # stamp the epoch BEFORE the fold: writes that land while _compute
        # runs (async ingest) bump past the stamp and invalidate the result
        epoch0 = self._write_epoch
        # capture the gate once: a recorder enabled mid-call must not record
        # a duration measured against the 0.0 placeholder
        rec = _TELEMETRY if _TELEMETRY.enabled else None
        t0 = time.perf_counter() if rec is not None else 0.0
        # the compute span wraps the WHOLE cycle including the distributed
        # sync, so `<Metric>.sync` (and its transport spans) nest under it
        span_ctx = (
            _span(f"{type(self).__name__}.compute")
            if rec is not None
            else contextlib.nullcontext()
        )
        with span_ctx:
            with self.sync_context(
                dist_sync_fn=self.dist_sync_fn,
                should_sync=self._to_sync,
                should_unsync=self._should_unsync,
            ):
                with self._profiler_annotation("compute"):
                    value = self._compute()
                self._computed = _squeeze_if_scalar(value)
                self._computed_epoch = epoch0
            if rec is not None:
                dt = time.perf_counter() - t0
                rec.record_call("compute", self, dt)
                rec.record_read(
                    "compute",
                    self,
                    duration_s=dt,
                    leaves=len(self._defaults),
                    freshness=self.freshness_stamp(),
                    **self._read_extras(),
                )
                # sketch occupancy is read on the cold compute path only
                # (it syncs the leaf); no-op for metrics without sketch leaves
                ratios = self.sketch_fill_ratios()
                if ratios:
                    rec.record_sketch_fill(self, ratios)
                rec.record_memory_boundary(
                    "compute", self, live_bytes=self.total_state_bytes
                )
        return self._computed

    def freshness_stamp(self, now: Optional[float] = None) -> "FreshnessStamp":
        """The :class:`~metrics_tpu.observability.freshness.FreshnessStamp`
        of this metric's accumulated state: wall clock of the first/last
        ingested batch. Identity until a telemetry-enabled ``update`` runs
        (ingest times are stamped only while the recorder is on)."""
        return FreshnessStamp(
            min_event_t=self._ingest_first_t, max_event_t=self._ingest_last_t
        )

    def _read_extras(self) -> Dict[str, Any]:
        """Extra ``record_read`` fields a subclass' ``_compute`` wants on
        the read event (e.g. RetrievalMetric's table rows unpacked)."""
        return {}

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Update global state AND return the metric for just this batch.

        Double-update semantics, parity with reference metric.py:264-300:
        accumulate into global state; then cache state, reset, update on the
        batch alone, compute the batch value, and restore the global state.
        """
        if self._is_synced:
            raise MetricsUserError(
                "The Metric shouldn't be synced when performing ``update``. HINT: Did you forget to call ``unsync``?."
            )
        rec = _TELEMETRY if _TELEMETRY.enabled else None
        t0 = time.perf_counter() if rec is not None else 0.0
        # the forward span contains both inner update spans and the batch
        # compute span, so the double-update cycle nests under one parent
        span_ctx = (
            _span(f"{type(self).__name__}.forward")
            if rec is not None
            else contextlib.nullcontext()
        )
        with span_ctx:
            self.update(*args, **kwargs)

            self._to_sync = self.dist_sync_on_step
            self._should_unsync = False

            cache = self._snapshot_state()

            self.reset()
            self.update(*args, **kwargs)
            self._forward_cache = self.compute()

            self._restore_state(cache)

            self._should_unsync = True
            self._to_sync = True
            self._update_called = True

            if rec is not None:
                # the forward event's duration covers the WHOLE double-update
                # cycle; the two inner update events it contains are also in
                # the stream, making the double-update overhead directly
                # visible
                rec.record_call("forward", self, time.perf_counter() - t0, args, kwargs)
        return self._forward_cache

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        # coerce torch inputs ONCE here so forward's double update (and any
        # wrapper forward that slices raw args) sees jax arrays; update()'s
        # own coercion then finds nothing left to convert
        return self.forward(*_coerce_foreign(args), **_coerce_foreign(kwargs))

    def reset(self) -> None:
        """Restore every state to its default. Parity with reference metric.py:491-506."""
        self._update_called = False
        self._forward_cache = None
        self._mark_state_written()
        for attr, default in self._defaults.items():
            if isinstance(default, list):
                object.__setattr__(self, attr, [])
            else:
                object.__setattr__(self, attr, jnp.array(default))
        self._cache = None
        self._is_synced = False
        self._ingest_first_t = None
        self._ingest_last_t = None
        if _TELEMETRY.enabled:  # disabled reset path stays ONE bool check
            _TELEMETRY.record_memory_boundary(
                "reset", self, live_bytes=self.total_state_bytes
            )

    # ------------------------------------------------------------------
    # distributed sync state machine
    # ------------------------------------------------------------------
    def _sync_dist(self, dist_sync_fn: Callable = gather_all_arrays, process_group: Optional[Any] = None) -> None:
        # the eager-path counter fast path keeps `_n_updates` as a Python
        # int; the gather contract below only moves arrays
        input_dict = {
            attr: jnp.asarray(v, jnp.int32) if isinstance(v, int) else v
            for attr, v in ((a, getattr(self, a)) for a in self._reductions)
        }

        for attr in self._reductions:
            if self._cat_states.get(attr) and isinstance(input_dict[attr], list) and len(input_dict[attr]) > 1:
                input_dict[attr] = [dim_zero_cat(input_dict[attr])]

        import numpy as _np

        output_dict = apply_to_collection(
            input_dict,
            (jnp.ndarray, _np.ndarray),  # host-resident states (e.g. detection) gather too
            dist_sync_fn,
            group=process_group or self.process_group,
        )

        for attr, reduction_fn in self._reductions.items():
            if isinstance(output_dict[attr], list) and output_dict[attr] and isinstance(output_dict[attr][0], list):
                output_dict[attr] = _flatten(output_dict[attr])
            if isinstance(output_dict[attr], list) and output_dict[attr] and isinstance(output_dict[attr][0], jnp.ndarray):
                output_dict[attr] = jnp.stack(output_dict[attr]) if not isinstance(getattr(self, attr), list) else output_dict[attr]
            if not (callable(reduction_fn) or reduction_fn is None):
                raise TypeError("reduction_fn must be callable or None")
            reduced = reduction_fn(output_dict[attr]) if reduction_fn is not None else output_dict[attr]
            if getattr(reduction_fn, "merge_like", False) and _TELEMETRY.enabled:
                n_ranks = (
                    output_dict[attr].shape[0]
                    if isinstance(output_dict[attr], jnp.ndarray) and output_dict[attr].ndim >= 3
                    else 1
                )
                _TELEMETRY.record_sketch_merge(max(n_ranks - 1, 1))
            object.__setattr__(self, attr, reduced)

    def sync(
        self,
        dist_sync_fn: Optional[Callable] = None,
        process_group: Optional[Any] = None,
        should_sync: bool = True,
        distributed_available: Optional[Callable] = _dist_available,
    ) -> None:
        """Manually sync states across processes. Parity with reference metric.py:329-363."""
        if self._is_synced and should_sync:
            raise MetricsUserError("The Metric has already been synced.")

        is_distributed = distributed_available() if callable(distributed_available) else None
        # a custom dist_sync_fn implies a simulated/virtual world even without multi-process jax
        if dist_sync_fn is None:
            dist_sync_fn = self.dist_sync_fn
        if not should_sync or not (is_distributed or dist_sync_fn is not None):
            return

        if dist_sync_fn is None:
            dist_sync_fn = gather_all_arrays

        self._cache = {attr: getattr(self, attr) for attr in self._defaults}
        if not _TELEMETRY.enabled:
            self._sync_dist(dist_sync_fn, process_group=process_group)
            self._is_synced = True
            return
        t0 = time.perf_counter()
        state_bytes = sum(self.state_footprint(include_children=False).values())
        with _span(f"{type(self).__name__}.sync"):
            self._sync_dist(dist_sync_fn, process_group=process_group)
            self._is_synced = True
            # lifecycle-level event: metric attribution + duration + LOCAL
            # state bytes, under its OWN type tag — "sync" events are the
            # transport's (gather_all_arrays / sync_in_mesh), which own the
            # gather-byte and pad-waste accounting, so totals are never
            # double-counted and type=="sync" consumers always find the
            # gather_bytes schema
            _TELEMETRY.record_event(
                "metric_sync",
                metric=type(self).__name__,
                local_state_bytes=state_bytes,
                world_size=_world_size(process_group or self.process_group),
                dur_ms=round((time.perf_counter() - t0) * 1e3, 4),
            )

    def unsync(self, should_unsync: bool = True) -> None:
        """Restore pre-sync local states. Parity with reference metric.py:365-385."""
        if not should_unsync:
            return
        if not self._is_synced:
            raise MetricsUserError("The Metric has already been un-synced.")
        if self._cache is None:
            raise MetricsUserError("The internal cache should exist to unsync the Metric.")
        for attr, val in self._cache.items():
            object.__setattr__(self, attr, val)
        self._is_synced = False
        self._cache = None

    @contextmanager
    def sync_context(
        self,
        dist_sync_fn: Optional[Callable] = None,
        process_group: Optional[Any] = None,
        should_sync: bool = True,
        should_unsync: bool = True,
        distributed_available: Optional[Callable] = _dist_available,
    ) -> Generator:
        """Sync on entry, restore local state on exit. Parity with metric.py:388-419."""
        self.sync(
            dist_sync_fn=dist_sync_fn,
            process_group=process_group,
            should_sync=should_sync,
            distributed_available=distributed_available,
        )
        yield
        self.unsync(should_unsync=self._is_synced and should_unsync)

    # ------------------------------------------------------------------
    # pure-functional state API (TPU-native extension; no reference analog)
    # ------------------------------------------------------------------
    def shard_states(self, shardings: Any) -> None:
        """Place array states (and their reset defaults) under mesh shardings.

        SURVEY §5 long-context analog as a library feature: large per-class /
        per-threshold accumulator states (confusion matrices, binned curve
        TPs/FPs/FNs, capacity-mode buffers) can live SHARDED over a
        ``jax.sharding.Mesh`` so full-dataset state scales with the mesh
        instead of one chip's HBM. ``shardings`` is a single
        ``jax.sharding.Sharding`` applied to every array state, or a dict
        mapping state names to shardings (missing names stay as they are).
        List states (ragged host-side accumulators) are not shardable and are
        skipped. Reset defaults are re-placed too, so ``reset()`` preserves
        the layout.
        """
        for name in list(self._defaults):
            sharding = shardings.get(name) if isinstance(shardings, dict) else shardings
            if sharding is None:
                continue
            value = getattr(self, name)
            if isinstance(value, list) or isinstance(self._defaults[name], list):
                continue
            object.__setattr__(self, name, jax.device_put(jnp.asarray(value), sharding))
            self._defaults[name] = jax.device_put(jnp.asarray(self._defaults[name]), sharding)
        # wrappers/compositions keep their states in children (same recursion
        # every other state-wide operation performs)
        if not isinstance(shardings, dict):
            for _, child in self._iter_child_metrics():
                child.shard_states(shardings)

    def state_reductions(self) -> Dict[str, Union[str, Callable, None]]:
        """Reducer spec per state ("sum"/"mean"/"max"/"min"/"cat", a custom
        callable, or None) — exactly what
        :func:`metrics_tpu.parallel.distributed.sync_in_mesh` takes, so metric
        states sync inside shard_map with one call:
        ``sync_in_mesh(state, metric.state_reductions(), axis)``."""
        names = {
            dim_zero_sum: "sum",
            dim_zero_mean: "mean",
            dim_zero_max: "max",
            dim_zero_min: "min",
            dim_zero_cat: "cat",
        }
        return {k: names.get(fn, fn) for k, fn in self._reductions.items()}

    def init_state(self) -> Dict[str, StateValue]:
        """Fresh state pytree (defaults)."""
        return {
            k: ([] if isinstance(v, list) else jnp.array(v)) for k, v in self._defaults.items()
        }

    def _bind(self, state: Dict[str, StateValue]) -> Dict[str, StateValue]:
        old = {k: getattr(self, k) for k in self._defaults}
        for k, v in state.items():
            object.__setattr__(self, k, v)
        return old

    def update_state(self, state: Dict[str, StateValue], *args: Any, **kwargs: Any) -> Dict[str, StateValue]:
        """Pure functional update: ``(state, batch) -> state``. Jit-compatible for
        metrics with array states (list states grow the pytree structure)."""
        old = self._bind(state)
        try:
            self._update(*args, **kwargs)
            # bump the mean-merge counter only when the INPUT state carries it:
            # a pre-counter state (old checkpoint, hand-built dict) must stay
            # counter-less so merge_states keeps its documented unweighted
            # fallback instead of trusting a counter that missed its history
            if _AUTO_COUNT in state:
                self._bump_auto_count()
            out = {
                k: getattr(self, k)
                for k in self._defaults
                if k != _AUTO_COUNT or k in state
            }
            # the eager counter fast path leaves a Python int behind; the
            # functional contract returns array leaves
            if isinstance(out.get(_AUTO_COUNT), int):
                out[_AUTO_COUNT] = jnp.asarray(out[_AUTO_COUNT], jnp.int32)
            return out
        finally:
            for k, v in old.items():
                object.__setattr__(self, k, v)

    def compute_state(self, state: Dict[str, StateValue]) -> Any:
        """Pure functional compute: ``state -> value``."""
        old = self._bind(state)
        try:
            return self._compute()
        finally:
            for k, v in old.items():
                object.__setattr__(self, k, v)

    def merge_states(
        self,
        a: Dict[str, StateValue],
        b: Dict[str, StateValue],
        counts: Optional[Sequence[Union[int, float, Array]]] = None,
    ) -> Dict[str, StateValue]:
        """Merge two independently-accumulated states via each state's reducer.

        ``counts`` — optional ``(n_a, n_b)`` update (or sample) counts for the
        two states. Mean-reduced states are merged as the count-weighted
        average ``(n_a*a + n_b*b) / (n_a + n_b)``. Without ``counts``, the
        weights default to the auto-registered per-state update counters
        (every metric with a mean-reduced state tracks one; see
        ``add_state``), so uneven accumulations merge correctly out of the
        box; the unweighted ``(a + b) / 2`` — the reference's stack-then-mean
        sync convention — is only the last resort for states that predate the
        counter (e.g. restored from an old checkpoint), since it silently
        mis-averages uneven sides.

        A NEGATIVE count on either side is the "history unknown" sentinel
        (``load_state_dict`` sets ``-1`` when restoring a pre-counter
        checkpoint): the merge falls back to the unweighted mean, and the
        merged counter stays ``-1`` so the uncertainty propagates through
        chained merges instead of resetting to a small confident count.
        """
        if counts is not None and len(counts) != 2:
            raise ValueError(f"`counts` must be a pair (n_a, n_b), got {len(counts)} entries")
        if counts is None and _AUTO_COUNT in a and _AUTO_COUNT in b:
            counts = (a[_AUTO_COUNT], b[_AUTO_COUNT])
        out: Dict[str, StateValue] = {}
        for name, red in self._reductions.items():
            if name == _AUTO_COUNT and (name not in a or name not in b):
                continue  # hand-built / pre-counter states; weights fell back above
            va, vb = a[name], b[name]
            if name == _AUTO_COUNT:
                # sentinel propagation: merging an unknown-history side keeps
                # the result's counter unknown
                out[name] = jnp.where((va >= 0) & (vb >= 0), va + vb, -1)
            elif isinstance(va, list) or isinstance(vb, list) or self._cat_states.get(name):
                la = va if isinstance(va, list) else [va]
                lb = vb if isinstance(vb, list) else [vb]
                out[name] = la + lb
            elif red == dim_zero_sum or red == dim_zero_mean:
                if red == dim_zero_sum:
                    out[name] = va + vb
                elif counts is not None:
                    na, nb = (jnp.asarray(c, jnp.float32) for c in counts)
                    total = na + nb
                    # never-updated pairs (both counters 0) fall back to the
                    # unweighted mean of the defaults instead of 0/0, and a
                    # negative (sentinel) counter on either side means the
                    # weights are unknown — unweighted fallback, never a
                    # zero/negative weight that discards a side's data
                    weighted_ok = (na >= 0) & (nb >= 0) & (total > 0)
                    out[name] = jnp.where(
                        weighted_ok,
                        (na * va + nb * vb) / jnp.maximum(total, 1.0),
                        (va + vb) / 2,
                    )
                else:
                    out[name] = (va + vb) / 2
            elif red == dim_zero_max:
                out[name] = jnp.maximum(va, vb)
            elif red == dim_zero_min:
                out[name] = jnp.minimum(va, vb)
            elif getattr(red, "merge_like", False):
                # sketch leaves merge through their own reducer (the same
                # stacked-leaves contract the distributed sync delivers)
                out[name] = red(jnp.stack([jnp.asarray(va), jnp.asarray(vb)]))
                if _TELEMETRY.enabled:
                    _TELEMETRY.record_sketch_merge(1)
            elif getattr(red, "inner_reduce", None) == "sum":
                # windowed ring/decay sum leaves (metrics_tpu/windowed/):
                # same-bucket rows and decayed sums add pairwise
                out[name] = va + vb
            elif red is None:
                raise MetricsUserError(
                    f"Cannot merge tensor state {name!r} with reduction None (gathered-not-reduced"
                    " states have no well-defined pairwise merge); use a list state instead"
                )
            else:
                raise MetricsUserError(f"Cannot merge state {name!r} with custom reduction")
        return out

    # ------------------------------------------------------------------
    # state memory accounting (observability; no reference analog)
    # ------------------------------------------------------------------
    def state_footprint(self, include_children: bool = True) -> Dict[str, int]:
        """Per-state device-memory footprint in bytes.

        Keys are state names (child metrics' states under dotted prefixes);
        list states report the sum over their elements — the number that
        grows without bound for cat-accumulating curve metrics (AUROC/ROC/
        PRC), which is exactly what the telemetry high-water-mark warning
        watches. ``sum(m.state_footprint().values())`` (or
        :meth:`total_state_bytes`) is the metric's total state memory.
        """
        out: Dict[str, int] = {}
        for name in self._defaults:
            val = getattr(self, name)
            if isinstance(val, list):
                out[name] = int(sum(_nbytes(v) for v in val))
            elif isinstance(val, int):
                out[name] = 4  # host-resident int32 counter (eager fast path)
            else:
                # sketch leaves (merge-like reducer) report under their own
                # prefix: their bytes are the FIXED O(capacity) budget, not a
                # growing accumulation, and the telemetry HWM labelling keys
                # on the prefix (see observability/recorder.py)
                key = (
                    f"{SKETCH_FOOTPRINT_PREFIX}{name}"
                    if getattr(self._reductions.get(name), "merge_like", False)
                    else name
                )
                out[key] = _nbytes(val)
        if include_children:
            for cname, child in self._iter_child_metrics():
                for key, nb in child.state_footprint().items():
                    out[f"{cname}.{key}"] = nb
        return out

    def total_state_bytes(self) -> int:
        """Total bytes held by this metric's (and its children's) states."""
        return sum(self.state_footprint().values())

    def theoretical_state_bytes(self) -> int:
        """Bytes the registered state *defaults* predict at their current
        dtypes — shape × itemsize over ``_defaults``, recursing children
        (list states predict 0: their growth is data-dependent). For
        fixed-shape metrics this equals the live :meth:`total_state_bytes`;
        divergence means either a cat-accumulating state (expected) or a
        leaf whose dtype drifted from its default's — the staleness the
        ``footprint`` event's theoretical/live byte pair exists to catch
        (``set_dtype`` must cast states AND defaults in lockstep)."""
        total = 0
        for default in self._defaults.values():
            if isinstance(default, list):
                continue
            total += _nbytes(default)
        for _, child in self._iter_child_metrics():
            total += child.theoretical_state_bytes()
        return total

    def sketch_fill_ratios(self) -> Dict[str, float]:
        """Occupancy per sketch-leaf state (``occupied slots / capacity``)
        — the number that says whether a sketch is still inside its
        lossless window (< 1.0 with no compactions) or how aggressively the
        capacity budget is being spent. Empty for metrics without sketch
        leaves. Host-syncing (reads the leaf); telemetry calls it from the
        cold compute path only."""
        out: Dict[str, float] = {}
        for name, red in self._reductions.items():
            if not getattr(red, "merge_like", False):
                continue
            val = getattr(self, name)
            if not isinstance(val, jnp.ndarray) or isinstance(val, jax.core.Tracer) or val.ndim < 2:
                continue
            # leading-ellipsis form covers both the flat [capacity, cols]
            # sketch layout and the windowed ring-of-sketches [R, capacity,
            # cols]. Per-SKETCH occupancy, worst slot reported: averaging
            # over all ring slots would let one at-capacity live bucket
            # (compactions imminent — exactly what the fill alarm watches)
            # hide behind R-1 empty ones for the whole first ring lap.
            occupied = (
                val[..., 0] > -jnp.inf
                if getattr(red, "sketch_kind", "") == "reservoir"
                else val[..., 0] > 0
            )
            out[name] = float(jnp.max(jnp.mean(occupied.astype(jnp.float32), axis=-1)))
        return out

    # ------------------------------------------------------------------
    # persistence
    # ------------------------------------------------------------------
    def persistent(self, mode: bool = False) -> None:
        for name in self._persistent:
            self._persistent[name] = mode
        for _, child in self._iter_child_metrics():
            child.persistent(mode)

    def state_dict(self, destination: Optional[Dict] = None, prefix: str = "") -> Dict[str, Any]:
        """Flat dict of all states (a checkpointable pytree; orbax-compatible).
        Parity with reference metric.py:604-622."""
        destination = {} if destination is None else destination
        for name in self._defaults:
            current = getattr(self, name)
            if isinstance(current, list):
                destination[prefix + name] = [jnp.array(v) for v in current]
            else:
                destination[prefix + name] = jnp.array(current)
        for cname, child in self._iter_child_metrics():
            child.state_dict(destination, prefix=f"{prefix}{cname}.")
        return destination

    def load_state_dict(self, state_dict: Dict[str, Any], prefix: str = "") -> None:
        """Restore states saved by ``state_dict``. Parity with metric.py:624-642.

        Pre-counter checkpoints: when real states are restored but the
        auto-registered ``_n_updates`` counter is absent (an old, pre-0.5
        snapshot), the counter is set to the sentinel ``-1`` instead of
        staying at its default ``0`` — a 0 would weight this side's
        accumulated mean to ZERO in the next count-weighted
        ``merge_states``, silently discarding its data. A negative counter
        makes ``merge_states`` fall back to the unweighted mean and
        survives both further updates (``_bump_auto_count``) and
        re-snapshotting, so the "history unknown" mark cannot be laundered
        into a confident wrong weight.
        """
        restored_real_state = False
        for name in self._defaults:
            key = prefix + name
            if key in state_dict:
                val = state_dict[key]
                if isinstance(val, list):
                    object.__setattr__(self, name, [jnp.asarray(v) for v in val])
                else:
                    object.__setattr__(self, name, jnp.asarray(val))
                if name != _AUTO_COUNT:
                    restored_real_state = True
        if (
            restored_real_state
            and _AUTO_COUNT in self._defaults
            and prefix + _AUTO_COUNT not in state_dict
        ):
            object.__setattr__(self, _AUTO_COUNT, jnp.asarray(-1, jnp.int32))
        if restored_real_state:
            self._mark_state_written()
        for cname, child in self._iter_child_metrics():
            child.load_state_dict(state_dict, prefix=f"{prefix}{cname}.")

    # ------------------------------------------------------------------
    # dtype / device
    # ------------------------------------------------------------------
    @property
    def dtype(self):
        return self._dtype

    @property
    def device(self):
        """Device of the first placed state array; ``None`` only when the
        metric has no array states or they are tracers (inside jit, where
        placement is undecided). Any OTHER failure to resolve placement
        propagates — masking it would hide real multi-device placement bugs."""
        from jax.errors import ConcretizationTypeError, TracerArrayConversionError

        for name in self._defaults:
            val = getattr(self, name)
            if isinstance(val, list):
                if val:
                    val = val[0]
                else:
                    continue
            if isinstance(val, jnp.ndarray):
                try:
                    return next(iter(val.devices()))
                except (ConcretizationTypeError, TracerArrayConversionError):
                    return None
        return None

    def set_dtype(self, dst_type) -> "Metric":
        """Cast floating-point states (and defaults) to ``dst_type``.
        Parity with reference metric.py:559-564 (`.float()/.half()` are no-ops)."""
        self._dtype = dst_type

        def _cast(v):
            if isinstance(v, jnp.ndarray) and jnp.issubdtype(v.dtype, jnp.floating):
                return v.astype(dst_type)
            return v

        for name in self._defaults:
            val = getattr(self, name)
            if isinstance(val, list):
                object.__setattr__(self, name, [_cast(v) for v in val])
            else:
                object.__setattr__(self, name, _cast(val))
            self._defaults[name] = (
                [_cast(v) for v in self._defaults[name]]
                if isinstance(self._defaults[name], list)
                else _cast(self._defaults[name])
            )
        computed = self._computed
        # the cast rewrote every floating leaf in place: route through the
        # out-of-band write hook so the epoch clock advances and subclass
        # incremental read caches (per-slice value cache, window fold memos)
        # degrade to cold instead of serving values folded at the old dtype
        self._mark_state_written()
        if computed is not None:
            # the cached value itself is cast too and stays correct —
            # reinstall it stamped at the post-cast epoch
            self._computed = apply_to_collection(computed, jnp.ndarray, _cast)
            self._computed_epoch = self._write_epoch
        for _, child in self._iter_child_metrics():
            child.set_dtype(dst_type)
        if _TELEMETRY.enabled:
            # footprint events straddling a dtype flip must reflect the NEW
            # leaf dtypes; states and defaults were cast in lockstep above,
            # so theoretical (default-predicted) and live bytes agree for
            # fixed-shape metrics — the event carries both so a stale cast
            # shows up as a theoretical/live mismatch in telemetry
            fp = self.state_footprint()
            _TELEMETRY.record_footprint(
                self,
                fp,
                theoretical_bytes=int(self.theoretical_state_bytes()),
                live_bytes=int(sum(fp.values())),
                cast_to=str(jnp.dtype(dst_type)),
            )
        return self

    def to_device(self, device) -> "Metric":
        """Move all states to ``device`` (TPU/CPU)."""
        for name in self._defaults:
            val = getattr(self, name)
            if isinstance(val, list):
                object.__setattr__(self, name, [jax.device_put(v, device) for v in val])
            else:
                object.__setattr__(self, name, jax.device_put(val, device))
        for _, child in self._iter_child_metrics():
            child.to_device(device)
        return self

    # ------------------------------------------------------------------
    # static analysis (tracelint v2 manifest; no reference analog)
    # ------------------------------------------------------------------
    @classmethod
    def static_fusibility(cls) -> Optional[Dict[str, Any]]:
        """This class's entry in the tracelint fusibility manifest, or None.

        The manifest (``scripts/fusibility_manifest.json``, regenerated by
        ``python scripts/tracelint.py --manifest``) carries the abstract
        interpreter's verdict — ``fusible`` / ``unsafe`` (with a
        machine-derived reason: ``cat-growth`` / ``host-sync`` /
        ``data-dependent-shape``) / ``unknown`` — plus the abstract
        shape/dtype/reduction of every registered state leaf.
        ``FusedUpdate`` consults the same entry to skip its ``eval_shape``
        probe for ``fusible`` classes; exposing it here lets users (and the
        package gate test) ask a metric *why* it does or does not fuse.
        Classes outside ``metrics_tpu`` (user subclasses) have no entry.
        """
        from metrics_tpu.analysis.manifest import lookup_class

        return lookup_class(cls)

    def static_sliceability(self) -> Optional[Dict[str, bool]]:
        """Per-leaf ``sliceable`` verdicts from the tracelint manifest, or
        None when the class has no entry (user subclasses).

        A leaf is statically sliceable when the abstract interpreter
        extracted a ``sum``/``max``/``min`` reducer over an array state —
        exactly the leaves :class:`metrics_tpu.sliced.SlicedMetric` can
        segment-scatter along a leading ``[S]`` slice axis.
        ``SlicedMetric`` consults this at construction to put the
        machine-derived reason in its rejection error; the runtime
        ``_reductions`` registry stays the authority (an instance method,
        not a classmethod, because reducers can be config-dependent —
        StatScores' ``"cat"``-or-``"sum"`` idiom).
        """
        entry = type(self).static_fusibility()
        if not entry:
            return None
        states = entry.get("states")
        if not isinstance(states, dict):
            return None
        out: Dict[str, bool] = {}
        for name, leaf in states.items():
            out[name] = bool(isinstance(leaf, dict) and leaf.get("sliceable"))
        return out

    # ------------------------------------------------------------------
    # misc
    # ------------------------------------------------------------------
    def clone(self) -> "Metric":
        """Deep copy of the metric. Parity with metric.py:508-510."""
        return deepcopy(self)

    def __getstate__(self) -> Dict[str, Any]:
        return {k: v for k, v in self.__dict__.items()}

    def __setstate__(self, state: Dict[str, Any]) -> None:
        self.__dict__.update(state)

    def _filter_kwargs(self, **kwargs: Any) -> Dict[str, Any]:
        """Filter kwargs by the signature of ``self._update``. Parity with metric.py:644-664."""
        sig = inspect.signature(self._update)
        params = sig.parameters
        has_var_kw = any(p.kind == p.VAR_KEYWORD for p in params.values())
        if has_var_kw:
            return kwargs
        _params = (inspect.Parameter.VAR_POSITIONAL, inspect.Parameter.VAR_KEYWORD)
        return {
            k: v
            for k, v in kwargs.items()
            if k in params and params[k].kind not in _params
        }

    def __hash__(self) -> int:
        hash_vals: List[Any] = [self.__class__.__name__, id(self)]
        for key in self._defaults:
            val = getattr(self, key)
            if isinstance(val, list):
                hash_vals.extend(id(v) for v in val)
            else:
                hash_vals.append(id(val))
        return hash(tuple(hash_vals))

    def __repr__(self) -> str:
        return f"{self.__class__.__name__}()"

    # ------------------------------------------------------------------
    # operator algebra (parity with reference metric.py:685-788)
    # ------------------------------------------------------------------
    def __add__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.add, self, other)

    def __radd__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.add, other, self)

    def __sub__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.sub, self, other)

    def __rsub__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.sub, other, self)

    def __mul__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.mul, self, other)

    def __rmul__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.mul, other, self)

    def __truediv__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.truediv, self, other)

    def __rtruediv__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.truediv, other, self)

    def __floordiv__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.floordiv, self, other)

    def __rfloordiv__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.floordiv, other, self)

    def __mod__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.mod, self, other)

    def __rmod__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.mod, other, self)

    def __pow__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.pow, self, other)

    def __rpow__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.pow, other, self)

    def __matmul__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.matmul, self, other)

    def __rmatmul__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.matmul, other, self)

    def __and__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.and_, self, other)

    def __rand__(self, other: Any) -> "CompositionalMetric":
        # bitwise_and is commutative
        return CompositionalMetric(operator.and_, self, other)

    def __or__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.or_, self, other)

    def __ror__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.or_, other, self)

    def __xor__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.xor, self, other)

    def __rxor__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.xor, other, self)

    def __eq__(self, other: Any) -> "CompositionalMetric":  # type: ignore[override]
        return CompositionalMetric(operator.eq, self, other)

    def __ne__(self, other: Any) -> "CompositionalMetric":  # type: ignore[override]
        return CompositionalMetric(operator.ne, self, other)

    def __lt__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.lt, self, other)

    def __le__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.le, self, other)

    def __gt__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.gt, self, other)

    def __ge__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.ge, self, other)

    def __abs__(self) -> "CompositionalMetric":
        return CompositionalMetric(operator.abs, self, None)

    def __neg__(self) -> "CompositionalMetric":
        return CompositionalMetric(_neg, self, None)

    def __pos__(self) -> "CompositionalMetric":
        return CompositionalMetric(operator.abs, self, None)

    def __invert__(self) -> "CompositionalMetric":
        return CompositionalMetric(operator.invert, self, None)

    def __getitem__(self, idx: Any) -> "CompositionalMetric":
        return CompositionalMetric(lambda x: x[idx], self, None)


def _neg(x: Array) -> Array:
    return -jnp.abs(x)


class CompositionalMetric(Metric):
    """Composition of two metrics (or a metric and a constant) via a binary op.

    Parity with reference metric.py:795-902: ``update`` fans out to both
    children with kwarg filtering; ``compute`` applies the operator on the
    children's computed values; own ``_sync_dist`` is a no-op (children sync
    themselves).
    """

    def __init__(
        self,
        operator: Callable,
        metric_a: Union[Metric, float, int, Array],
        metric_b: Union[Metric, float, int, Array, None],
    ) -> None:
        super().__init__()
        self.op = operator
        self.metric_a = jnp.asarray(metric_a) if isinstance(metric_a, (int, float)) else metric_a
        self.metric_b = jnp.asarray(metric_b) if isinstance(metric_b, (int, float)) else metric_b

    def _sync_dist(self, dist_sync_fn: Optional[Callable] = None, process_group: Optional[Any] = None) -> None:
        pass  # children sync themselves

    def _update(self, *args: Any, **kwargs: Any) -> None:
        if isinstance(self.metric_a, Metric):
            self.metric_a.update(*args, **self.metric_a._filter_kwargs(**kwargs))
        if isinstance(self.metric_b, Metric):
            self.metric_b.update(*args, **self.metric_b._filter_kwargs(**kwargs))

    def _compute(self) -> Any:
        return self.compute()

    def compute(self) -> Any:
        val_a = self.metric_a.compute() if isinstance(self.metric_a, Metric) else self.metric_a
        val_b = self.metric_b.compute() if isinstance(self.metric_b, Metric) else self.metric_b
        if val_b is None:
            return self.op(val_a)
        return self.op(val_a, val_b)

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        val_a = (
            self.metric_a(*args, **self.metric_a._filter_kwargs(**kwargs))
            if isinstance(self.metric_a, Metric)
            else self.metric_a
        )
        val_b = (
            self.metric_b(*args, **self.metric_b._filter_kwargs(**kwargs))
            if isinstance(self.metric_b, Metric)
            else self.metric_b
        )
        if val_a is None:
            self._forward_cache = None
        elif val_b is None:
            if isinstance(self.metric_b, Metric):
                self._forward_cache = None
            else:
                self._forward_cache = self.op(val_a)
        else:
            self._forward_cache = self.op(val_a, val_b)
        return self._forward_cache

    def reset(self) -> None:
        if isinstance(self.metric_a, Metric):
            self.metric_a.reset()
        if isinstance(self.metric_b, Metric):
            self.metric_b.reset()
        self._update_called = False
        self._forward_cache = None
        self._computed = None

    def __repr__(self) -> str:
        _op_name = getattr(self.op, "__name__", str(self.op))
        repr_str = self.__class__.__name__ + f"(\n  {_op_name}(\n    {repr(self.metric_a)},\n    {repr(self.metric_b)}\n  )\n)"
        return repr_str

    def __hash__(self) -> int:
        return hash((self.__class__.__name__, id(self)))
