"""Cross-domain bf16-precision and differentiability sweep.

The reference runs fp16 precision checks and autograd gradcheck through its
MetricTester per metric (tests/helpers/testers.py:297-326,530-564); here the
bf16 + jax.grad analogs sweep EVERY major exported class from one table
instead of per-file opt-ins (round-2 verdict weak #9: the checks covered
only 2 of 16 files).
"""
import numpy as np
import pytest

import jax.numpy as jnp

import metrics_tpu as mt
import metrics_tpu.functional as F
from tests.helpers.testers import MetricTester

_rng = np.random.default_rng(0)

_N, _C = 40, 5
_probs = _rng.random((_N, _C)).astype(np.float32)
_probs /= _probs.sum(1, keepdims=True)
_bin_preds = _rng.random(_N).astype(np.float32)
_bin_target = _rng.integers(0, 2, _N)
_mc_target = _rng.integers(0, _C, _N)
_reg_preds = _rng.standard_normal(_N).astype(np.float32)
_reg_target = (_reg_preds + 0.3 * _rng.standard_normal(_N)).astype(np.float32)
_img_a = _rng.random((2, 3, 24, 24)).astype(np.float32)
_img_b = np.clip(_img_a + 0.05 * _rng.standard_normal((2, 3, 24, 24)), 0, 1).astype(np.float32)
_wave_a = _rng.standard_normal((2, 800)).astype(np.float32)
_wave_b = (_wave_a + 0.3 * _rng.standard_normal((2, 800))).astype(np.float32)

# (class, functional or None, init args, preds fixture, target fixture)
_SWEEP = [
    # classification
    (mt.Accuracy, F.accuracy, {}, _probs, _mc_target),
    (mt.Precision, F.precision, {}, _probs, _mc_target),
    (mt.Recall, F.recall, {}, _probs, _mc_target),
    (mt.F1Score, F.f1_score, {}, _probs, _mc_target),
    (mt.Specificity, F.specificity, {}, _probs, _mc_target),
    (mt.StatScores, F.stat_scores, {}, _probs, _mc_target),
    (mt.ConfusionMatrix, F.confusion_matrix, {"num_classes": _C}, _probs, _mc_target),
    (mt.JaccardIndex, F.jaccard_index, {"num_classes": _C}, _probs, _mc_target),
    (mt.CohenKappa, F.cohen_kappa, {"num_classes": _C}, _probs, _mc_target),
    (mt.MatthewsCorrCoef, F.matthews_corrcoef, {"num_classes": _C}, _probs, _mc_target),
    (mt.HammingDistance, F.hamming_distance, {}, _probs, _mc_target),
    (mt.AUROC, F.auroc, {"pos_label": 1}, _bin_preds, _bin_target),
    (mt.AveragePrecision, F.average_precision, {"pos_label": 1}, _bin_preds, _bin_target),
    (mt.PrecisionRecallCurve, F.precision_recall_curve, {"pos_label": 1}, _bin_preds, _bin_target),
    (mt.ROC, F.roc, {"pos_label": 1}, _bin_preds, _bin_target),
    (mt.HingeLoss, F.hinge_loss, {}, _rng.standard_normal((_N, _C)).astype(np.float32), _mc_target),
    (mt.KLDivergence, F.kl_divergence, {}, _probs, _probs[::-1].copy()),
    (mt.CalibrationError, F.calibration_error, {}, _bin_preds, _bin_target),
    # regression
    (mt.MeanSquaredError, F.mean_squared_error, {}, _reg_preds, _reg_target),
    (mt.MeanAbsoluteError, F.mean_absolute_error, {}, _reg_preds, _reg_target),
    (mt.MeanSquaredLogError, F.mean_squared_log_error, {}, np.abs(_reg_preds), np.abs(_reg_target)),
    (mt.MeanAbsolutePercentageError, F.mean_absolute_percentage_error, {}, _reg_preds, _reg_target + 1.5),
    (
        mt.SymmetricMeanAbsolutePercentageError,
        F.symmetric_mean_absolute_percentage_error,
        {},
        np.abs(_reg_preds) + 0.5,
        np.abs(_reg_target) + 0.5,
    ),
    (mt.TweedieDevianceScore, F.tweedie_deviance_score, {}, np.abs(_reg_preds) + 0.5, np.abs(_reg_target) + 0.5),
    (mt.CosineSimilarity, F.cosine_similarity, {}, _rng.random((8, 6)).astype(np.float32), _rng.random((8, 6)).astype(np.float32)),
    (mt.ExplainedVariance, F.explained_variance, {}, _reg_preds, _reg_target),
    (mt.R2Score, F.r2_score, {}, _reg_preds, _reg_target),
    (mt.PearsonCorrCoef, F.pearson_corrcoef, {}, _reg_preds, _reg_target),
    (mt.SpearmanCorrCoef, F.spearman_corrcoef, {}, _reg_preds, _reg_target),
    # image
    (mt.PeakSignalNoiseRatio, F.peak_signal_noise_ratio, {}, _img_a, _img_b),
    (mt.StructuralSimilarityIndexMeasure, F.structural_similarity_index_measure, {}, _img_a, _img_b),
    (mt.UniversalImageQualityIndex, F.universal_image_quality_index, {}, _img_a, _img_b),
    # audio
    (mt.SignalNoiseRatio, F.signal_noise_ratio, {}, _wave_a, _wave_b),
    (mt.ScaleInvariantSignalNoiseRatio, F.scale_invariant_signal_noise_ratio, {}, _wave_a, _wave_b),
    (mt.SignalDistortionRatio, F.signal_distortion_ratio, {}, _wave_a, _wave_b),
    (mt.ScaleInvariantSignalDistortionRatio, F.scale_invariant_signal_distortion_ratio, {}, _wave_a, _wave_b),
    # aggregation
    (mt.MeanMetric, None, {}, _reg_preds, None),
    (mt.SumMetric, None, {}, _reg_preds, None),
    (mt.MaxMetric, None, {}, _reg_preds, None),
    (mt.MinMetric, None, {}, _reg_preds, None),
]

_IDS = [entry[0].__name__ for entry in _SWEEP]


def _wrap(preds, target):
    """MetricTester expects batched fixtures; wrap as a single batch."""
    return [preds], [target]


@pytest.mark.parametrize("cls, functional, args, preds, target", _SWEEP, ids=_IDS)
def test_bf16_precision(cls, functional, args, preds, target):
    metric = cls(**args)
    metric.set_dtype(jnp.bfloat16)
    p = jnp.asarray(preds)
    if jnp.issubdtype(p.dtype, jnp.floating):
        p = p.astype(jnp.bfloat16)
    if target is None:
        metric.update(p)
    else:
        t = jnp.asarray(target)
        if jnp.issubdtype(t.dtype, jnp.floating):
            t = t.astype(jnp.bfloat16)
        metric.update(p, t)
    result = metric.compute()
    leaves = result.values() if isinstance(result, dict) else (
        result if isinstance(result, (tuple, list)) else [result]
    )
    for leaf in leaves:
        if isinstance(leaf, (list, tuple)):
            continue
        assert not bool(jnp.any(jnp.isnan(jnp.asarray(leaf, jnp.float32)))), f"NaN in bf16 {cls.__name__}"


@pytest.mark.parametrize("cls, functional, args, preds, target", _SWEEP, ids=_IDS)
def test_differentiability(cls, functional, args, preds, target):
    if functional is None or target is None:
        pytest.skip("aggregation metrics have no functional form")
    metric = cls(**args)
    if not metric.is_differentiable:
        pytest.skip(f"{cls.__name__} declares is_differentiable=False")
    MetricTester().run_differentiability_test(
        *_wrap(preds, target), metric_class=cls, metric_functional=functional, metric_args=args
    )
