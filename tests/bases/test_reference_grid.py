"""Reference value-parity for the composition layer (L6).

The behavior tests (test_collections/test_aggregation/test_composition)
pin semantics; this grid pins VALUES against the reference implementation
for MetricCollection (grouped metrics, prefix/postfix naming), the
aggregation metrics (including nan strategies and weighted-mean
broadcasting), and the compositional operator algebra over real metrics.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from metrics_tpu import (
    Accuracy,
    CatMetric,
    MaxMetric,
    MeanMetric,
    MetricCollection,
    MinMetric,
    Precision,
    Recall,
    SumMetric,
)
from tests.helpers.reference import load_reference_module

torch = pytest.importorskip("torch")

_rng = np.random.default_rng(41)
STEPS = 4
PREDS = _rng.integers(0, 2, (STEPS, 32))
TARGET = _rng.integers(0, 2, (STEPS, 32))


# the reference snapshot's compute-group state borrowing getattrs members by
# their DECORATED name and crashes whenever a prefix/postfix is set (its own
# bug — ours decorates only the output keys); its groups are disabled for
# the oracle, which does not change values
@pytest.mark.parametrize("naming", [{"prefix": "val_"}, {"postfix": "_epoch"}], ids=["prefix", "postfix"])
def test_collection_values_and_naming_parity(naming):
    ref_tm = load_reference_module("torchmetrics")
    ours = MetricCollection([Accuracy(), Precision(), Recall()], **naming)
    ref = ref_tm.MetricCollection(
        [ref_tm.Accuracy(), ref_tm.Precision(), ref_tm.Recall()],
        compute_groups=False,
        **naming,
    )
    for i in range(STEPS):
        ours.update(jnp.asarray(PREDS[i]), jnp.asarray(TARGET[i]))
        ref.update(torch.as_tensor(PREDS[i]), torch.as_tensor(TARGET[i]))
    got, want = ours.compute(), ref.compute()
    assert set(got) == set(want)  # identical decorated names
    for k in want:
        np.testing.assert_allclose(float(got[k]), float(want[k]), atol=1e-6, err_msg=k)


def test_collection_compute_groups_values_match_ungrouped_reference():
    """Our grouped collection equals the reference's grouped collection AND
    its own ungrouped evaluation (groups are an optimization, never a
    semantic change)."""
    ref_tm = load_reference_module("torchmetrics")
    ours = MetricCollection([Precision(), Recall()])
    ours_ungrouped = MetricCollection([Precision(), Recall()], compute_groups=False)
    ref = ref_tm.MetricCollection([ref_tm.Precision(), ref_tm.Recall()])
    for i in range(STEPS):
        ours.update(jnp.asarray(PREDS[i]), jnp.asarray(TARGET[i]))
        ours_ungrouped.update(jnp.asarray(PREDS[i]), jnp.asarray(TARGET[i]))
        ref.update(torch.as_tensor(PREDS[i]), torch.as_tensor(TARGET[i]))
    got, got_u, want = ours.compute(), ours_ungrouped.compute(), ref.compute()
    for k in want:
        np.testing.assert_allclose(float(got[k]), float(want[k]), atol=1e-6, err_msg=k)
        np.testing.assert_allclose(float(got[k]), float(got_u[k]), atol=1e-6, err_msg=k)


VALUES = _rng.random((STEPS, 8)).astype(np.float32) * 10


@pytest.mark.parametrize(
    "ours_cls, ref_name",
    [
        (MaxMetric, "MaxMetric"),
        (MinMetric, "MinMetric"),
        (SumMetric, "SumMetric"),
        (MeanMetric, "MeanMetric"),
        (CatMetric, "CatMetric"),
    ],
    ids=["max", "min", "sum", "mean", "cat"],
)
def test_aggregation_value_parity(ours_cls, ref_name):
    ref_tm = load_reference_module("torchmetrics")
    ours, ref = ours_cls(), getattr(ref_tm, ref_name)()
    for i in range(STEPS):
        ours.update(jnp.asarray(VALUES[i]))
        ref.update(torch.as_tensor(VALUES[i]))
    got, want = np.asarray(ours.compute()), ref.compute()
    if isinstance(want, list):  # reference CatMetric may return list pre-cat
        want = torch.cat([torch.atleast_1d(w) for w in want])
    np.testing.assert_allclose(got.ravel(), want.numpy().ravel(), rtol=1e-6)


@pytest.mark.parametrize("nan_strategy", ["ignore", 42.0])
def test_aggregation_nan_strategy_value_parity(nan_strategy):
    ref_tm = load_reference_module("torchmetrics")
    vals = np.asarray([1.0, np.nan, 3.0, np.nan, 5.0], np.float32)
    ours, ref = (
        MeanMetric(nan_strategy=nan_strategy),
        ref_tm.MeanMetric(nan_strategy=nan_strategy),
    )
    ours.update(jnp.asarray(vals))
    ref.update(torch.as_tensor(vals))
    np.testing.assert_allclose(float(ours.compute()), float(ref.compute()), atol=1e-6)


def test_weighted_mean_broadcasting_parity():
    ref_tm = load_reference_module("torchmetrics")
    vals = np.asarray([1.0, 2.0, 3.0], np.float32)
    for weight in (np.asarray([1.0, 2.0, 3.0], np.float32), 2.0):
        ours, ref = MeanMetric(), ref_tm.MeanMetric()
        w_ours = jnp.asarray(weight) if isinstance(weight, np.ndarray) else weight
        w_ref = torch.as_tensor(weight) if isinstance(weight, np.ndarray) else weight
        ours.update(jnp.asarray(vals), w_ours)
        ref.update(torch.as_tensor(vals), w_ref)
        np.testing.assert_allclose(float(ours.compute()), float(ref.compute()), atol=1e-6)


def test_compositional_algebra_value_parity():
    """Operator algebra over REAL metrics matches the reference end-to-end
    (the dummy-metric sweeps in test_composition.py pin each operator; this
    pins a realistic F-measure-style composition)."""
    ref_tm = load_reference_module("torchmetrics")
    ours_p, ours_r = Precision(), Recall()
    ref_p, ref_r = ref_tm.Precision(), ref_tm.Recall()
    ours_f = 2 * (ours_p * ours_r) / (ours_p + ours_r)
    ref_f = 2 * (ref_p * ref_r) / (ref_p + ref_r)
    for i in range(STEPS):
        ours_f.update(jnp.asarray(PREDS[i]), jnp.asarray(TARGET[i]))
        ref_f.update(torch.as_tensor(PREDS[i]), torch.as_tensor(TARGET[i]))
    np.testing.assert_allclose(float(ours_f.compute()), float(ref_f.compute()), atol=1e-6)
