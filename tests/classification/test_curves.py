"""Curve metrics (ROC / AUROC / PR-curve / AveragePrecision / AUC) vs sklearn,
plus the binned fixed-threshold family.

Mirrors the reference tests/classification/test_{roc,auroc,
precision_recall_curve,average_precision}.py in spirit.
"""
import numpy as np
import pytest
from sklearn.metrics import (
    auc as sk_auc,
    average_precision_score as sk_average_precision,
    precision_recall_curve as sk_precision_recall_curve,
    roc_auc_score as sk_roc_auc,
    roc_curve as sk_roc_curve,
)

import jax.numpy as jnp

from metrics_tpu import (
    AUC,
    AUROC,
    AveragePrecision,
    BinnedAveragePrecision,
    BinnedPrecisionRecallCurve,
    BinnedRecallAtFixedPrecision,
    PrecisionRecallCurve,
    ROC,
)
from metrics_tpu.functional import auc, auroc, average_precision, precision_recall_curve, roc
from tests.helpers.testers import BATCH_SIZE, NUM_BATCHES, NUM_CLASSES, MetricTester

_rng = np.random.RandomState(42)
_preds_binary = _rng.rand(NUM_BATCHES, BATCH_SIZE).astype(np.float32)
_target_binary = _rng.randint(0, 2, (NUM_BATCHES, BATCH_SIZE))


def _softmax(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


_preds_mc = _softmax(_rng.rand(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES).astype(np.float32) * 3)
_target_mc = _rng.randint(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE))


class TestROC(MetricTester):
    atol = 1e-5

    def _sk_roc(self, preds, target):
        fpr, tpr, thresholds = sk_roc_curve(np.asarray(target), np.asarray(preds), drop_intermediate=False)
        # newer sklearn uses +inf as the first threshold; the reference (and
        # this framework) use thresholds[1] + 1
        thresholds = thresholds.copy()
        if np.isinf(thresholds[0]):
            thresholds[0] = thresholds[1] + 1
        return fpr, tpr, thresholds

    def test_roc_binary(self):
        self.run_class_metric_test(
            preds=_preds_binary,
            target=_target_binary,
            metric_class=ROC,
            sk_metric=self._sk_roc,
            metric_args={"pos_label": 1},
            check_merge=False,
            check_jit=False,
        )

    def test_roc_functional(self):
        self.run_functional_metric_test(
            _preds_binary, _target_binary, metric_functional=roc, sk_metric=self._sk_roc,
            metric_args={"pos_label": 1},
        )


class TestPrecisionRecallCurve(MetricTester):
    atol = 1e-5

    def _sk_prc(self, preds, target):
        precision, recall, thresholds = sk_precision_recall_curve(np.asarray(target), np.asarray(preds))
        # sklearn >= 1.1 keeps the full curve; the reference truncates at the
        # first attainment of full recall — drop the leading duplicated-recall
        # run (all but its last element) to match
        m = int(np.max(np.nonzero(recall == recall[0])[0]))
        return precision[m:], recall[m:], thresholds[m:]

    def test_prc_binary(self):
        self.run_class_metric_test(
            preds=_preds_binary,
            target=_target_binary,
            metric_class=PrecisionRecallCurve,
            sk_metric=self._sk_prc,
            metric_args={"pos_label": 1},
            check_merge=False,
            check_jit=False,
        )

    def test_prc_functional(self):
        self.run_functional_metric_test(
            _preds_binary, _target_binary, metric_functional=precision_recall_curve, sk_metric=self._sk_prc,
            metric_args={"pos_label": 1},
        )


@pytest.mark.parametrize("average", ["macro", "weighted"])
class TestAUROCMulticlass(MetricTester):
    atol = 1e-5

    def test_auroc_multiclass(self, average):
        def sk_metric(preds, target):
            return sk_roc_auc(
                np.asarray(target), np.asarray(preds), multi_class="ovr", average="macro" if average == "macro" else "weighted",
                labels=list(range(NUM_CLASSES)),
            )

        self.run_class_metric_test(
            preds=_preds_mc,
            target=_target_mc,
            metric_class=AUROC,
            sk_metric=sk_metric,
            metric_args={"num_classes": NUM_CLASSES, "average": average},
            check_merge=False,
            check_jit=False,
        )

    def test_auroc_functional(self, average):
        def sk_metric(preds, target):
            return sk_roc_auc(
                np.asarray(target), np.asarray(preds), multi_class="ovr",
                average="macro" if average == "macro" else "weighted", labels=list(range(NUM_CLASSES)),
            )

        self.run_functional_metric_test(
            _preds_mc, _target_mc, metric_functional=auroc, sk_metric=sk_metric,
            metric_args={"num_classes": NUM_CLASSES, "average": average},
        )


class TestAUROCBinary(MetricTester):
    atol = 1e-5

    def test_auroc_binary(self):
        self.run_class_metric_test(
            preds=_preds_binary,
            target=_target_binary,
            metric_class=AUROC,
            sk_metric=lambda p, t: sk_roc_auc(np.asarray(t), np.asarray(p)),
            check_merge=False,
            check_jit=False,
        )

    def test_auroc_max_fpr(self):
        for max_fpr in (0.1, 0.5):
            result = auroc(jnp.asarray(_preds_binary[0]), jnp.asarray(_target_binary[0]), max_fpr=max_fpr)
            expected = sk_roc_auc(_target_binary[0], _preds_binary[0], max_fpr=max_fpr)
            np.testing.assert_allclose(np.asarray(result), expected, atol=1e-5)

    def test_auroc_max_fpr_degenerate_target_raises(self):
        preds = jnp.asarray([0.1, 0.6, 0.3, 0.9])
        with pytest.raises(ValueError, match="no negative samples"):
            auroc(preds, jnp.ones(4, dtype=jnp.int32), max_fpr=0.5)
        with pytest.raises(ValueError, match="no positive samples"):
            auroc(preds, jnp.zeros(4, dtype=jnp.int32), max_fpr=0.5)


class TestAveragePrecision(MetricTester):
    atol = 1e-5

    def test_ap_binary(self):
        self.run_class_metric_test(
            preds=_preds_binary,
            target=_target_binary,
            metric_class=AveragePrecision,
            sk_metric=lambda p, t: sk_average_precision(np.asarray(t), np.asarray(p)),
            metric_args={"pos_label": 1},
            check_merge=False,
            check_jit=False,
        )

    def test_ap_multiclass_macro(self):
        def sk_metric(preds, target):
            target_oh = np.eye(NUM_CLASSES)[np.asarray(target)]
            scores = [
                sk_average_precision(target_oh[:, i], np.asarray(preds)[:, i]) for i in range(NUM_CLASSES)
            ]
            return np.mean(scores)

        self.run_class_metric_test(
            preds=_preds_mc,
            target=_target_mc,
            metric_class=AveragePrecision,
            sk_metric=sk_metric,
            metric_args={"num_classes": NUM_CLASSES, "average": "macro"},
            check_merge=False,
            check_jit=False,
        )

    def test_ap_functional(self):
        self.run_functional_metric_test(
            _preds_binary,
            _target_binary,
            metric_functional=average_precision,
            sk_metric=lambda p, t: sk_average_precision(np.asarray(t), np.asarray(p)),
            metric_args={"pos_label": 1},
        )


def test_auc_parity():
    x = np.sort(_rng.rand(20)).astype(np.float32)
    y = _rng.rand(20).astype(np.float32)
    np.testing.assert_allclose(np.asarray(auc(jnp.asarray(x), jnp.asarray(y))), sk_auc(x, y), atol=1e-6)
    # reorder path
    perm = _rng.permutation(20)
    np.testing.assert_allclose(
        np.asarray(auc(jnp.asarray(x[perm]), jnp.asarray(y[perm]), reorder=True)), sk_auc(x, y), atol=1e-6
    )
    m = AUC()
    m.update(jnp.asarray(x[:10]), jnp.asarray(y[:10]))
    m.update(jnp.asarray(x[10:]), jnp.asarray(y[10:]))
    np.testing.assert_allclose(np.asarray(m.compute()), sk_auc(x, y), atol=1e-6)


# ---------------------------------------------------------------------------
# binned family
# ---------------------------------------------------------------------------


def test_binned_pr_curve_matches_exact_at_fine_thresholds():
    """With thresholds exactly at the distinct prediction values, binned
    TP/FP/FN match the exact curve's confusion counts."""
    preds = np.round(_rng.rand(512).astype(np.float32), 2)
    target = _rng.randint(0, 2, 512)

    metric = BinnedPrecisionRecallCurve(num_classes=1, thresholds=101)
    metric.update(jnp.asarray(preds), jnp.asarray(target))
    precision, recall, thresholds = metric.compute()

    # oracle: brute-force per threshold (use the metric's own float32
    # thresholds — float64 linspace differs at bin boundaries)
    thr = np.asarray(metric.thresholds)
    tp = np.array([(preds >= t)[target == 1].sum() for t in thr])
    fp = np.array([(preds >= t)[target == 0].sum() for t in thr])
    fn = np.array([(preds < t)[target == 1].sum() for t in thr])
    eps = 1e-6
    expected_precision = (tp + eps) / (tp + fp + eps)
    expected_recall = tp / (tp + fn + eps)

    np.testing.assert_allclose(np.asarray(precision)[:-1], expected_precision, atol=1e-4)
    np.testing.assert_allclose(np.asarray(recall)[:-1], expected_recall, atol=1e-4)


def test_binned_pr_multiclass_shapes():
    metric = BinnedPrecisionRecallCurve(num_classes=NUM_CLASSES, thresholds=11)
    metric.update(jnp.asarray(_preds_mc[0]), jnp.asarray(_target_mc[0]))
    precision, recall, thresholds = metric.compute()
    assert len(precision) == NUM_CLASSES
    assert precision[0].shape == (12,)
    assert thresholds[0].shape == (11,)


def test_binned_average_precision_close_to_exact():
    preds = _rng.rand(4096).astype(np.float32)
    target = (preds + 0.3 * _rng.randn(4096) > 0.5).astype(np.int32)
    metric = BinnedAveragePrecision(num_classes=1, thresholds=201)
    metric.update(jnp.asarray(preds), jnp.asarray(target))
    binned = float(metric.compute())
    exact = sk_average_precision(target, preds)
    assert abs(binned - exact) < 0.01


def test_binned_recall_at_fixed_precision():
    preds = jnp.asarray([0.0, 0.2, 0.5, 0.8], dtype=jnp.float32)
    target = jnp.asarray([0, 1, 1, 0])
    metric = BinnedRecallAtFixedPrecision(num_classes=1, thresholds=10, min_precision=0.5)
    recall, threshold = metric(preds, target)
    assert float(recall) == pytest.approx(1.0, abs=1e-4)
    assert float(threshold) == pytest.approx(1 / 9, abs=1e-4)


def test_binned_recall_at_fixed_precision_no_valid():
    preds = jnp.asarray([0.9, 0.9], dtype=jnp.float32)
    target = jnp.asarray([0, 0])
    metric = BinnedRecallAtFixedPrecision(num_classes=1, thresholds=5, min_precision=0.99)
    recall, threshold = metric(preds, target)
    assert float(recall) == 0.0
    assert float(threshold) == pytest.approx(1e6)


def test_binned_update_is_jittable():
    import jax

    metric = BinnedPrecisionRecallCurve(num_classes=NUM_CLASSES, thresholds=11)
    state = metric.init_state()
    state = jax.jit(metric.update_state)(state, jnp.asarray(_preds_mc[0]), jnp.asarray(_target_mc[0]))
    eager = metric.update_state(metric.init_state(), jnp.asarray(_preds_mc[0]), jnp.asarray(_target_mc[0]))
    for k in eager:
        np.testing.assert_allclose(np.asarray(state[k]), np.asarray(eager[k]), atol=1e-5)


def test_binned_merge_and_sync():
    metric = BinnedPrecisionRecallCurve(num_classes=1, thresholds=21)
    s1 = metric.update_state(metric.init_state(), jnp.asarray(_preds_binary[0]), jnp.asarray(_target_binary[0]))
    s2 = metric.update_state(metric.init_state(), jnp.asarray(_preds_binary[1]), jnp.asarray(_target_binary[1]))
    merged = metric.merge_states(s1, s2)
    p_merged, r_merged, _ = metric.compute_state(merged)

    both = metric.update_state(s1, jnp.asarray(_preds_binary[1]), jnp.asarray(_target_binary[1]))
    p_both, r_both, _ = metric.compute_state(both)
    np.testing.assert_allclose(np.asarray(p_merged), np.asarray(p_both), atol=1e-6)
    np.testing.assert_allclose(np.asarray(r_merged), np.asarray(r_both), atol=1e-6)


def test_auroc_rank_multiclass_exact_parity():
    """Rank-statistic AUROC must equal sklearn's curve-based value exactly."""
    import jax
    from metrics_tpu.functional.classification.auroc import auroc_rank_multiclass

    preds = jnp.asarray(_preds_mc[0])
    target = jnp.asarray(_target_mc[0])
    for average, sk_avg in [("macro", "macro"), ("weighted", "weighted")]:
        got = auroc_rank_multiclass(preds, target, NUM_CLASSES, average=average)
        want = sk_roc_auc(np.asarray(target), np.asarray(preds), multi_class="ovr",
                          average=sk_avg, labels=list(range(NUM_CLASSES)))
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-6)
    # jit parity
    jitted = jax.jit(lambda p, t: auroc_rank_multiclass(p, t, NUM_CLASSES))(preds, target)
    eager = auroc_rank_multiclass(preds, target, NUM_CLASSES)
    np.testing.assert_allclose(np.asarray(jitted), np.asarray(eager), atol=1e-6)


def test_auroc_rank_handles_ties():
    from metrics_tpu.functional.classification.auroc import auroc_rank_multiclass

    rng = np.random.RandomState(1)
    p = np.round(rng.rand(200).astype(np.float32), 1)  # heavy ties
    target = rng.randint(0, 2, 200)
    preds = np.stack([1 - p, p], axis=1)
    got = auroc_rank_multiclass(jnp.asarray(preds), jnp.asarray(target), 2)
    # both one-vs-rest AUCs equal the binary AUC, so macro == binary
    want = sk_roc_auc(target, p)
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-6)
