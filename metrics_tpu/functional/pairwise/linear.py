"""Pairwise linear (dot-product) similarity.

Behavior parity with /root/reference/torchmetrics/functional/pairwise/linear.py:20-80.
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.pairwise.helpers import _check_input, _reduce_distance_matrix, _zero_diagonal

Array = jax.Array


def _pairwise_linear_similarity_update(
    x: Array, y: Optional[Array] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    distance = jnp.matmul(x, y.T, precision=jax.lax.Precision.HIGHEST)
    return _zero_diagonal(distance, zero_diagonal)


def pairwise_linear_similarity(
    x: Array, y: Optional[Array] = None, reduction: Optional[str] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    """Pairwise dot-product similarity between rows of x (and y).

    Example:
        >>> import jax.numpy as jnp
        >>> x = jnp.array([[2., 3.], [3., 5.], [5., 8.]])
        >>> y = jnp.array([[1., 0.], [2., 1.]])
        >>> pairwise_linear_similarity(x, y)
        Array([[ 2.,  7.],
               [ 3., 11.],
               [ 5., 18.]], dtype=float32)
    """
    distance = _pairwise_linear_similarity_update(x, y, zero_diagonal)
    return _reduce_distance_matrix(distance, reduction)
