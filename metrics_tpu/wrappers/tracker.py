"""MetricTracker — a time-series of metric (or collection) snapshots.

Behavior parity with /root/reference/torchmetrics/wrappers/tracker.py:24-185.
"""
from copy import deepcopy
from typing import Any, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.collections import MetricCollection
from metrics_tpu.core.metric import Metric
from metrics_tpu.observability.recorder import _DEFAULT_RECORDER as _TELEMETRY
from metrics_tpu.utils.prints import rank_zero_warn

Array = jax.Array


class MetricTracker:
    """Tracks a metric (or collection) over multiple steps/epochs.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Accuracy
        >>> tracker = MetricTracker(Accuracy(num_classes=10))
        >>> for epoch in range(3):
        ...     tracker.increment()
        ...     tracker.update(jnp.arange(10) % 10, (jnp.arange(10) * (epoch + 2)) % 10)
        >>> tracker.n_steps
        3
    """

    def __init__(self, metric: Union[Metric, MetricCollection], maximize: Union[bool, List[bool]] = True) -> None:
        if not isinstance(metric, (Metric, MetricCollection)):
            raise TypeError(
                f"Metric arg need to be an instance of a metrics_tpu `Metric` or `MetricCollection` but got {metric}"
            )
        self._base_metric = metric
        if not isinstance(maximize, (bool, list)):
            raise ValueError("Argument `maximize` should either be a single bool or list of bool")
        if isinstance(maximize, list) and isinstance(metric, MetricCollection) and len(maximize) != len(metric):
            raise ValueError("The len of argument `maximize` should match the length of the metric collection")
        self.maximize = maximize
        self._steps: List[Union[Metric, MetricCollection]] = []
        self._increment_called = False

    @property
    def n_steps(self) -> int:
        return len(self._steps)

    def increment(self) -> None:
        """Create a fresh copy of the base metric for a new tracking step."""
        self._increment_called = True
        self._steps.append(deepcopy(self._base_metric))
        self._steps[-1].reset()
        if _TELEMETRY.enabled:
            # every increment deep-copies the base metric and KEEPS the old
            # step — the tracker is a per-step memory multiplier, so the
            # event stream carries the running total
            _TELEMETRY.record_event(
                "tracker_increment",
                n_steps=len(self._steps),
                total_state_bytes=self.total_state_bytes(),
            )

    def state_footprint(self) -> Dict[str, Any]:
        """Per-step state footprints (``step0`` ... ``stepN`` keys), one
        entry per retained snapshot — the tracker holds EVERY step's states
        alive, which is the growth this exposes."""
        return {f"step{i}": m.state_footprint() for i, m in enumerate(self._steps)}

    def total_state_bytes(self) -> int:
        """Total bytes held across all retained steps."""
        return sum(m.total_state_bytes() for m in self._steps)

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        self._check_for_increment("forward")
        return self._steps[-1](*args, **kwargs)

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        return self.forward(*args, **kwargs)

    def update(self, *args: Any, **kwargs: Any) -> None:
        self._check_for_increment("update")
        self._steps[-1].update(*args, **kwargs)

    def compute(self) -> Any:
        self._check_for_increment("compute")
        return self._steps[-1].compute()

    def compute_all(self) -> Union[Array, Dict[str, Array]]:
        """Metric values for all tracked steps."""
        self._check_for_increment("compute_all")
        res = [metric.compute() for metric in self._steps]
        if isinstance(self._base_metric, MetricCollection):
            keys = res[0].keys()
            return {k: jnp.stack([r[k] for r in res], axis=0) for k in keys}
        return jnp.stack(res, axis=0)

    def reset(self) -> None:
        """Reset the current step's metric."""
        if self._steps:
            self._steps[-1].reset()

    def reset_all(self) -> None:
        """Reset all tracked metrics."""
        for metric in self._steps:
            metric.reset()

    def best_metric(
        self, return_step: bool = False
    ) -> Union[
        Optional[float],
        Tuple[Optional[float], Optional[int]],
        Dict[str, Union[float, None]],
        Tuple[Dict[str, Union[float, None]], Dict[str, Union[int, None]]],
    ]:
        """The best observed value (and, with ``return_step``, the step it
        occurred at, as ``(value, step)``). ``None`` (per entry) when the
        tracked values are non-scalar and have no total order."""
        res = self.compute_all()
        if isinstance(res, dict):
            maximize = self.maximize if isinstance(self.maximize, list) else [self.maximize] * len(res)
            value, idx = {}, {}
            for i, (k, v) in enumerate(res.items()):
                try:
                    f = jnp.argmax if maximize[i] else jnp.argmin
                    best = int(f(v))
                    value[k], idx[k] = float(v[best]), best
                except (ValueError, TypeError):
                    rank_zero_warn(
                        f"Encountered the following error when trying to get the best metric for metric {k}:"
                        " this is probably due to the 'best' not being defined for this metric."
                        " Returning `None` instead.",
                        UserWarning,
                    )
                    value[k], idx[k] = None, None
            if return_step:
                return value, idx
            return value

        try:
            f = jnp.argmax if self.maximize else jnp.argmin
            idx_best = int(f(res))
            # reshape(()) accepts size-1 per-step values (e.g. a (steps, 1)
            # multioutput history, where torch .item() would also succeed)
            # and raises for genuinely non-scalar ones
            value = float(jnp.asarray(res[idx_best]).reshape(()))
        except (ValueError, TypeError):
            # non-scalar per-step values (e.g. a tracked ConfusionMatrix)
            # have no total order; warn and return None — the same contract
            # as the collection branch above (the reference instead fails
            # with an opaque tensor-conversion error here)
            rank_zero_warn(
                "Encountered an error when trying to get the best metric:"
                " this is probably due to the 'best' not being defined for this metric."
                " Returning `None` instead.",
                UserWarning,
            )
            value, idx_best = None, None
        if return_step:
            return value, idx_best
        return value

    def _check_for_increment(self, method: str) -> None:
        if not self._increment_called:
            raise ValueError(f"`{method}` cannot be called before `.increment()` has been called")
