"""Optional-dependency availability gates.

Parity with /root/reference/torchmetrics/utilities/imports.py:94-118: every
optional third-party package used by a metric (or by a test oracle) gets a
module-level boolean so import of the package never hard-fails.
"""
from importlib.util import find_spec


def _package_available(name: str) -> bool:
    try:
        return find_spec(name) is not None
    except (ImportError, ModuleNotFoundError, ValueError):
        return False


_SCIPY_AVAILABLE = _package_available("scipy")
_SKLEARN_AVAILABLE = _package_available("sklearn")
_NLTK_AVAILABLE = _package_available("nltk")
_ROUGE_SCORE_AVAILABLE = _package_available("rouge_score")
_SACREBLEU_AVAILABLE = _package_available("sacrebleu")
_TRANSFORMERS_AVAILABLE = _package_available("transformers")
_JIWER_AVAILABLE = _package_available("jiwer")
_PESQ_AVAILABLE = _package_available("pesq")
_PYSTOI_AVAILABLE = _package_available("pystoi")
_PYCOCOTOOLS_AVAILABLE = _package_available("pycocotools")
_TORCH_AVAILABLE = _package_available("torch")
_TORCHVISION_AVAILABLE = _package_available("torchvision")
_TORCH_FIDELITY_AVAILABLE = _package_available("torch_fidelity")
_LPIPS_AVAILABLE = _package_available("lpips")
_BERTSCORE_AVAILABLE = _package_available("bert_score")
_REGEX_AVAILABLE = _package_available("regex")
_FLAX_AVAILABLE = _package_available("flax")
_ORBAX_AVAILABLE = _package_available("orbax.checkpoint")
