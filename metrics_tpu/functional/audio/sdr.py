"""SDR / SI-SDR (parity: /root/reference/torchmetrics/functional/audio/sdr.py:23-241).

The reference delegates the BSS-eval distortion-filter solve to the
``fast_bss_eval`` package (torch/numpy Toeplitz + conjugate gradient —
SURVEY §2.9). Here the whole pipeline is TPU-native jnp:

- correlation statistics via rFFT (one batched FFT per signal, O(T log T),
  XLA-fused, instead of fast_bss_eval's per-pair time-domain fallback),
- the ``[L, L]`` Toeplitz system assembled by a vectorized gather and
  solved with ``jnp.linalg.solve`` (MXU-friendly dense solve), or
- optionally an FFT-matvec conjugate-gradient loop (``use_cg_iter``) that
  never materializes the Toeplitz matrix — O(L log L) per iteration via
  circulant embedding. Unpreconditioned (the reference's CG uses a
  circulant preconditioner); with the default 10 iterations both agree
  with the direct solve to ~1e-3 dB on speech-scale signals, which the
  tests pin.
"""
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _check_same_shape

Array = jax.Array


def _l2_normalize(x: Array, eps: float) -> Array:
    """Scale to unit L2 norm along time (fast_bss_eval helpers._normalize)."""
    return x / jnp.clip(jnp.linalg.norm(x, axis=-1, keepdims=True), eps, None)


def _next_pow2(n: int) -> int:
    return 1 << (n - 1).bit_length()


def _correlation_stats(target: Array, preds: Array, length: int):
    """Auto-correlation of target and target↔preds cross-correlation, first
    ``length`` lags, via rFFT (fast_bss_eval metrics.compute_stats semantics).
    """
    n_fft = _next_pow2(target.shape[-1] + length)
    tf = jnp.fft.rfft(target, n=n_fft, axis=-1)
    pf = jnp.fft.rfft(preds, n=n_fft, axis=-1)
    acf = jnp.fft.irfft(jnp.abs(tf) ** 2, n=n_fft, axis=-1)[..., :length]
    xcorr = jnp.fft.irfft(jnp.conj(tf) * pf, n=n_fft, axis=-1)[..., :length]
    return acf, xcorr


def _toeplitz_solve(acf: Array, xcorr: Array) -> Array:
    """Direct dense solve of ``toeplitz(acf) · h = xcorr`` (batched)."""
    length = acf.shape[-1]
    idx = jnp.abs(jnp.arange(length)[:, None] - jnp.arange(length)[None, :])
    r_mat = acf[..., idx]  # [..., L, L] symmetric Toeplitz
    return jnp.linalg.solve(r_mat, xcorr[..., None])[..., 0]


def _toeplitz_matvec(acf: Array, v: Array) -> Array:
    """``toeplitz(acf) @ v`` without materializing the matrix: embed the
    symmetric Toeplitz operator in a circulant of size 2L and multiply in
    the Fourier domain."""
    length = acf.shape[-1]
    # first column of the 2L circulant: [acf_0..acf_{L-1}, 0, acf_{L-1}..acf_1]
    circ = jnp.concatenate(
        [acf, jnp.zeros_like(acf[..., :1]), jnp.flip(acf[..., 1:], axis=-1)], axis=-1
    )
    n = 2 * length
    prod = jnp.fft.irfft(
        jnp.fft.rfft(circ, n=n, axis=-1) * jnp.fft.rfft(v, n=n, axis=-1), n=n, axis=-1
    )
    return prod[..., :length]


def _toeplitz_cg(acf: Array, xcorr: Array, n_iter: int) -> Array:
    """Fixed-iteration conjugate gradient on the Toeplitz normal equations,
    FFT matvec, jit-friendly fori_loop (no data-dependent stopping)."""

    def matvec(v: Array) -> Array:
        return _toeplitz_matvec(acf, v)

    x = jnp.zeros_like(xcorr)
    r = xcorr - matvec(x)
    p = r
    rs = jnp.sum(r * r, axis=-1, keepdims=True)

    def body(_, carry):
        x, r, p, rs = carry
        ap = matvec(p)
        alpha = rs / jnp.clip(jnp.sum(p * ap, axis=-1, keepdims=True), 1e-20, None)
        x = x + alpha * p
        r = r - alpha * ap
        rs_new = jnp.sum(r * r, axis=-1, keepdims=True)
        p = r + (rs_new / jnp.clip(rs, 1e-20, None)) * p
        return x, r, p, rs_new

    x, _, _, _ = jax.lax.fori_loop(0, n_iter, body, (x, r, p, rs))
    return x


@partial(jax.jit, static_argnames=("use_cg_iter", "filter_length", "zero_mean"))
def _sdr_kernel(
    preds: Array,
    target: Array,
    use_cg_iter: Optional[int],
    filter_length: int,
    zero_mean: bool,
    load_diag: Optional[Array],
) -> Array:
    eps = jnp.finfo(preds.dtype).eps
    if zero_mean:
        preds = preds - jnp.mean(preds, axis=-1, keepdims=True)
        target = target - jnp.mean(target, axis=-1, keepdims=True)

    preds = _l2_normalize(preds, eps)
    target = _l2_normalize(target, eps)

    acf, xcorr = _correlation_stats(target, preds, filter_length)
    if load_diag is not None:
        acf = acf.at[..., 0].add(load_diag)

    if use_cg_iter is not None:
        sol = _toeplitz_cg(acf, xcorr, use_cg_iter)
    else:
        sol = _toeplitz_solve(acf, xcorr)

    # coherence = energy of preds captured by the length-L filtered target
    coh = jnp.sum(xcorr * sol, axis=-1)
    ratio = coh / (1 - coh)
    return 10.0 * jnp.log10(ratio)


def signal_distortion_ratio(
    preds: Array,
    target: Array,
    use_cg_iter: Optional[int] = None,
    filter_length: int = 512,
    zero_mean: bool = False,
    load_diag: Optional[float] = None,
) -> Array:
    """Signal-to-distortion ratio with a length-``filter_length`` allowed
    distortion filter (BSS-eval v4 semantics; reference sdr.py:36-196).

    Args:
        preds: estimate, shape ``[..., time]``.
        target: reference, shape ``[..., time]``.
        use_cg_iter: if given, solve the filter with this many conjugate-
            gradient iterations instead of the dense solve.
        filter_length: allowed distortion-filter length (default 512).
        zero_mean: subtract time-axis means first.
        load_diag: diagonal loading to stabilize near-singular systems.

    Returns:
        SDR in dB, shape ``[...]``.
    """
    _check_same_shape(preds, target)
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        preds = preds.astype(jnp.float32)
    if preds.dtype == jnp.float16 or preds.dtype == jnp.bfloat16:
        preds = preds.astype(jnp.float32)
    if target.dtype != preds.dtype:
        target = target.astype(preds.dtype)
    diag = None if load_diag is None else jnp.asarray(load_diag, preds.dtype)
    return _sdr_kernel(preds, target, use_cg_iter, filter_length, zero_mean, diag)


def scale_invariant_signal_distortion_ratio(preds: Array, target: Array, zero_mean: bool = False) -> Array:
    """SI-SDR: SNR after optimal scalar rescaling of the target (sdr.py:198-241).

    Example:
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> scale_invariant_signal_distortion_ratio(preds, target)
        Array(18.402992, dtype=float32)
    """
    _check_same_shape(preds, target)
    eps = jnp.finfo(preds.dtype).eps

    if zero_mean:
        target = target - jnp.mean(target, axis=-1, keepdims=True)
        preds = preds - jnp.mean(preds, axis=-1, keepdims=True)

    alpha = (jnp.sum(preds * target, axis=-1, keepdims=True) + eps) / (
        jnp.sum(target**2, axis=-1, keepdims=True) + eps
    )
    target_scaled = alpha * target
    noise = target_scaled - preds
    val = (jnp.sum(target_scaled**2, axis=-1) + eps) / (jnp.sum(noise**2, axis=-1) + eps)
    return 10 * jnp.log10(val)
