"""SDR / SI-SDR parity.

Oracles (fast_bss_eval, the reference's substrate, is not installed here):
1. the reference's own hard-coded doctest value for torch.manual_seed(1)
   randn(8000) inputs (/root/reference/torchmetrics/functional/audio/sdr.py:92-97),
2. an independent scipy ``solve_toeplitz`` implementation of the BSS-eval
   filter solve on random fixtures,
3. the reference scale_invariant_signal_distortion_ratio (pure torch).
"""
from functools import partial

import numpy as np
import pytest

from metrics_tpu.audio import ScaleInvariantSignalDistortionRatio, SignalDistortionRatio
from metrics_tpu.functional.audio import (
    scale_invariant_signal_distortion_ratio,
    signal_distortion_ratio,
)
from tests.helpers.reference import load_reference_module
from tests.helpers.testers import MetricTester

NUM_BATCHES, BATCH_SIZE, TIME = 2, 4, 1000

_rng = np.random.RandomState(7)
_preds = _rng.randn(NUM_BATCHES, BATCH_SIZE, TIME).astype(np.float32)
# correlate target with preds so SDR values are in a realistic range
_target = (0.6 * _preds + 0.4 * _rng.randn(NUM_BATCHES, BATCH_SIZE, TIME)).astype(np.float32)


def _scipy_sdr(preds, target, filter_length=512, zero_mean=False, load_diag=None):
    """Independent BSS-eval SDR: time-domain-exact FFT stats + scipy Toeplitz solve."""
    from scipy.linalg import solve_toeplitz

    preds = np.asarray(preds, np.float64)
    target = np.asarray(target, np.float64)
    out = np.empty(preds.shape[:-1])
    for idx in np.ndindex(*preds.shape[:-1]):
        p, t = preds[idx], target[idx]
        if zero_mean:
            p, t = p - p.mean(), t - t.mean()
        p = p / np.linalg.norm(p)
        t = t / np.linalg.norm(t)
        n = 1 << (len(t) + filter_length - 1).bit_length()
        tf, pf = np.fft.rfft(t, n), np.fft.rfft(p, n)
        acf = np.fft.irfft(np.abs(tf) ** 2, n)[:filter_length]
        xcorr = np.fft.irfft(np.conj(tf) * pf, n)[:filter_length]
        if load_diag is not None:
            acf[0] += load_diag
        sol = solve_toeplitz(acf, xcorr)
        coh = xcorr @ sol
        out[idx] = 10 * np.log10(coh / (1 - coh))
    return out


def _scipy_sdr_mean(preds, target, **kw):
    return _scipy_sdr(preds, target, **kw).mean()


def _ref_si_sdr(preds, target, zero_mean):
    import torch

    ref = load_reference_module("torchmetrics.functional.audio.sdr")
    val = ref.scale_invariant_signal_distortion_ratio(
        torch.tensor(np.asarray(preds)), torch.tensor(np.asarray(target)), zero_mean
    )
    return val.mean().numpy()


def test_sdr_matches_reference_doctest_value():
    """The reference documents tensor(-12.0589) for manual_seed(1) randn(8000)
    (sdr.py:92-97); regenerating the identical fixture through torch."""
    torch = pytest.importorskip("torch")
    torch.manual_seed(1)
    preds = torch.randn(8000).numpy()
    target = torch.randn(8000).numpy()
    assert float(signal_distortion_ratio(preds, target)) == pytest.approx(-12.0589, abs=1e-3)


@pytest.mark.parametrize("zero_mean", [False, True])
class TestSDR(MetricTester):
    atol = 1e-2

    def test_sdr_class(self, zero_mean):
        self.run_class_metric_test(
            preds=_preds,
            target=_target,
            metric_class=SignalDistortionRatio,
            sk_metric=partial(_scipy_sdr_mean, zero_mean=zero_mean),
            metric_args={"zero_mean": zero_mean},
        )

    def test_sdr_functional(self, zero_mean):
        self.run_functional_metric_test(
            preds=_preds,
            target=_target,
            metric_functional=lambda p, t, zero_mean: signal_distortion_ratio(p, t, zero_mean=zero_mean).mean(),
            sk_metric=partial(_scipy_sdr_mean, zero_mean=zero_mean),
            metric_args={"zero_mean": zero_mean},
        )


def test_sdr_cg_close_to_direct():
    """10 CG iterations must agree with the dense solve to ~1e-2 dB."""
    direct = signal_distortion_ratio(_preds[0], _target[0])
    cg = signal_distortion_ratio(_preds[0], _target[0], use_cg_iter=10)
    np.testing.assert_allclose(np.asarray(cg), np.asarray(direct), atol=1e-2)


def test_sdr_load_diag():
    val = signal_distortion_ratio(_preds[0], _target[0], load_diag=1e-4)
    oracle = _scipy_sdr(_preds[0], _target[0], load_diag=1e-4)
    np.testing.assert_allclose(np.asarray(val), oracle, atol=1e-2)


@pytest.mark.parametrize("zero_mean", [False, True])
class TestSISDR(MetricTester):
    atol = 1e-3

    def test_si_sdr_class(self, zero_mean):
        self.run_class_metric_test(
            preds=_preds,
            target=_target,
            metric_class=ScaleInvariantSignalDistortionRatio,
            sk_metric=partial(_ref_si_sdr, zero_mean=zero_mean),
            metric_args={"zero_mean": zero_mean},
        )

    def test_si_sdr_functional(self, zero_mean):
        self.run_functional_metric_test(
            preds=_preds,
            target=_target,
            metric_functional=lambda p, t, zero_mean: scale_invariant_signal_distortion_ratio(
                p, t, zero_mean=zero_mean
            ).mean(),
            sk_metric=partial(_ref_si_sdr, zero_mean=zero_mean),
            metric_args={"zero_mean": zero_mean},
        )
