#!/usr/bin/env python
"""Fail if any ``metrics_tpu/`` module calls ``print()`` directly.

All user-facing output from library code must route through the rank-zero
helpers in ``metrics_tpu/utils/prints.py`` (``rank_zero_print`` /
``rank_zero_info`` / ``rank_zero_warn``) so multi-host jobs emit one copy
and logging stays filterable. A raw ``print()`` in library code spams every
process in a pod job.

AST-based: only real ``print(...)`` call sites count — doctest examples and
other string content never false-positive. Exit status 0 when clean, 1 with
a ``path:line`` listing otherwise. Run from anywhere:

    python scripts/check_no_print.py
"""
import ast
import pathlib
import sys

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
PACKAGE = REPO_ROOT / "metrics_tpu"

# the one module allowed to touch print: it defines the gated helpers
ALLOWED = {PACKAGE / "utils" / "prints.py"}


def print_call_lines(path: pathlib.Path):
    """Line numbers of every ``print(...)`` call expression in ``path``."""
    tree = ast.parse(path.read_text(), filename=str(path))
    return [
        node.lineno
        for node in ast.walk(tree)
        if isinstance(node, ast.Call)
        and isinstance(node.func, ast.Name)
        and node.func.id == "print"
    ]


def main() -> int:
    offenders = []
    for path in sorted(PACKAGE.rglob("*.py")):
        if path in ALLOWED:
            continue
        for lineno in print_call_lines(path):
            offenders.append(f"{path.relative_to(REPO_ROOT)}:{lineno}")
    if offenders:
        sys.stderr.write(
            "raw print() calls found in metrics_tpu/ — use the rank-zero helpers"
            " from metrics_tpu/utils/prints.py instead:\n"
        )
        for offender in offenders:
            sys.stderr.write(f"  {offender}\n")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
