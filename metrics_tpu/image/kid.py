"""Kernel Inception Distance (polynomial MMD over feature subsets).

Behavior parity with /root/reference/torchmetrics/image/kid.py:29-269.
``feature`` accepts any callable ``imgs -> [N, d]`` or an int depth for the
bundled Flax InceptionV3 (see fid.py).

State modes: by DEFAULT extracted features stream into two fixed-size
Gumbel-key reservoirs (``metrics_tpu/sketches/reservoir.py``) of
``reservoir_size`` rows each — O(k·d) memory however long the stream, with
a ``"merge"``-reduced leaf that unions across ranks. While a stream fits
its reservoir the rows are the exact features in arrival order, so the
subset draws (host RNG, unchanged) reproduce the cat-state path
bit-for-bit; beyond it, subsets come from a uniform k-row sample of the
stream. ``exact=True`` restores the reference's unbounded feature lists
(and its large-memory warning — fired only on that path).
"""
from typing import Any, Callable, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.core.metric import Metric
from metrics_tpu.sketches.compat import register_exact_list_states, warn_exact_buffer
from metrics_tpu.sketches.reservoir import (
    reservoir_fill,
    reservoir_init,
    reservoir_insert,
    reservoir_merge_fx,
    reservoir_rows,
)
from metrics_tpu.utils.data import dim_zero_cat

Array = jax.Array


def maximum_mean_discrepancy(k_xx: Array, k_xy: Array, k_yy: Array) -> Array:
    """Unbiased MMD^2 estimate from kernel matrices. Reference kid.py:29-47."""
    m = k_xx.shape[0]

    kt_xx_sum = jnp.sum(k_xx) - jnp.sum(jnp.diag(k_xx))
    kt_yy_sum = jnp.sum(k_yy) - jnp.sum(jnp.diag(k_yy))
    k_xy_sum = jnp.sum(k_xy)

    value = (kt_xx_sum + kt_yy_sum) / (m * (m - 1))
    return value - 2 * k_xy_sum / (m**2)


def poly_kernel(
    f1: Array, f2: Array, degree: int = 3, gamma: Optional[float] = None, coef: float = 1.0
) -> Array:
    """Polynomial kernel. Reference kid.py:50-56."""
    if gamma is None:
        gamma = 1.0 / f1.shape[1]
    return (jnp.matmul(f1, f2.T, precision=jax.lax.Precision.HIGHEST) * gamma + coef) ** degree


def poly_mmd(
    f_real: Array, f_fake: Array, degree: int = 3, gamma: Optional[float] = None, coef: float = 1.0
) -> Array:
    """Polynomial-kernel MMD. Reference kid.py:59-66."""
    k_11 = poly_kernel(f_real, f_real, degree, gamma, coef)
    k_22 = poly_kernel(f_fake, f_fake, degree, gamma, coef)
    k_12 = poly_kernel(f_real, f_fake, degree, gamma, coef)
    return maximum_mean_discrepancy(k_11, k_12, k_22)


class KernelInceptionDistance(Metric):
    """Computes KID (mean and std of polynomial MMD over random subsets)."""

    #: stays eager even though the bundled extractor is traced-pure (the
    #: declaration below): the reservoir width is discovered lazily from
    #: the first feature batch (`_init_reservoirs`) and compute() draws
    #: its MMD subsets with host RNG — see docs/differences.md
    __jit_unsafe__ = True
    __exact_mode_attr__ = "_exact"
    __traced_callable_attrs__ = ("inception",)
    is_differentiable = False
    higher_is_better = False

    def __init__(
        self,
        feature: Union[int, Callable] = 2048,
        subsets: int = 100,
        subset_size: int = 1000,
        degree: int = 3,
        gamma: Optional[float] = None,
        coef: float = 1.0,
        seed: Optional[int] = None,
        feature_extractor_weights_path: str = None,
        exact: bool = False,
        reservoir_size: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)

        feature_dim: Optional[int] = None
        if isinstance(feature, int):
            valid_int_input = (64, 192, 768, 2048)
            if feature not in valid_int_input:
                raise ValueError(
                    f"Integer input to argument `feature` must be one of {valid_int_input}, but got {feature}."
                )
            from metrics_tpu.models.inception import build_fid_inception

            self.inception = build_fid_inception(feature, feature_extractor_weights_path)
            feature_dim = feature  # the bundled heads emit [N, depth] features
        elif callable(feature):
            self.inception = feature
        else:
            raise TypeError("Got unknown input to argument `feature`")

        if not (isinstance(subsets, int) and subsets > 0):
            raise ValueError("Argument `subsets` expected to be integer larger than 0")
        self.subsets = subsets
        if not (isinstance(subset_size, int) and subset_size > 0):
            raise ValueError("Argument `subset_size` expected to be integer larger than 0")
        self.subset_size = subset_size
        if not (isinstance(degree, int) and degree > 0):
            raise ValueError("Argument `degree` expected to be integer larger than 0")
        self.degree = degree
        if gamma is not None and not (isinstance(gamma, float) and gamma > 0):
            raise ValueError("Argument `gamma` expected to be `None` or float larger than 0")
        self.gamma = gamma
        if not (isinstance(coef, float) and coef > 0):
            raise ValueError("Argument `coef` expected to be float larger than 0")
        self.coef = coef
        self._rng = np.random.RandomState(seed)

        self._exact = bool(exact)
        if reservoir_size is None:
            reservoir_size = max(2 * subset_size, 2048)
        if not (isinstance(reservoir_size, int) and reservoir_size >= subset_size):
            raise ValueError(
                "Argument `reservoir_size` expected to be an int >= `subset_size`,"
                f" got {reservoir_size}"
            )
        self._reservoir_size = reservoir_size
        # per-rank key stream: identical seeds across ranks would draw
        # identical priorities and bias the cross-rank reservoir union
        self._key_seed = (0 if seed is None else int(seed)) * 1_000_003 + jax.process_index()

        if self._exact:
            register_exact_list_states(self, ("real_features", "fake_features"), dist_reduce_fx=None)
            warn_exact_buffer("KernelInceptionDistance", "extracted features")
        elif feature_dim is not None:
            self._init_reservoirs(feature_dim)
        # callable extractors leave the feature dimension unknown until the
        # first (host-side; the metric is declared jit-unsafe) update

    def _init_reservoirs(self, feature_dim: int) -> None:
        self._feature_dim = feature_dim
        self.add_state(
            "real_features",
            default=reservoir_init(self._reservoir_size, feature_dim),
            dist_reduce_fx=reservoir_merge_fx(),
        )
        self.add_state(
            "fake_features",
            default=reservoir_init(self._reservoir_size, feature_dim),
            dist_reduce_fx=reservoir_merge_fx(),
        )
        self.add_state("n_seen_real", default=jnp.asarray(0, jnp.int32), dist_reduce_fx="sum")
        self.add_state("n_seen_fake", default=jnp.asarray(0, jnp.int32), dist_reduce_fx="sum")

    _feature_dim: Optional[int] = None

    def load_state_dict(self, state_dict, prefix: str = "") -> None:
        """Checkpoint restore must work before the first update even for
        callable extractors (whose feature dimension is otherwise learned
        lazily): the reservoir layout is recovered from the saved leaf's
        column count, then the ordinary restore applies."""
        if not self._exact and self._feature_dim is None:
            saved = state_dict.get(prefix + "real_features")
            if saved is not None and getattr(saved, "ndim", 0) == 2:
                self._init_reservoirs(int(saved.shape[1]) - 1)
        super().load_state_dict(state_dict, prefix=prefix)

    def _update(self, imgs: Array, real: bool) -> None:
        features = self.inception(imgs)
        if self._exact:
            if real:
                self.real_features.append(features)
            else:
                self.fake_features.append(features)
            return
        if self._feature_dim is None:
            self._init_reservoirs(int(jnp.asarray(features).shape[-1]))
        if real:
            self.real_features = reservoir_insert(
                self.real_features, features, self.n_seen_real, seed=self._key_seed
            )
            self.n_seen_real = self.n_seen_real + jnp.asarray(features).shape[0]
        else:
            self.fake_features = reservoir_insert(
                self.fake_features, features, self.n_seen_fake, seed=self._key_seed + 1
            )
            self.n_seen_fake = self.n_seen_fake + jnp.asarray(features).shape[0]

    def _pool(self, real: bool) -> Array:
        """The sampled feature pool: the exact stream (arrival order) inside
        the lossless window, a uniform ``k``-row sample beyond it."""
        leaf = jnp.asarray(self.real_features if real else self.fake_features)
        n = int(reservoir_fill(leaf))
        return reservoir_rows(leaf)[:n]

    def _compute(self) -> Tuple[Array, Array]:
        getattr(self.inception, "finalize", lambda: None)()  # flush async range check of the last batch
        if self._exact:
            real_features = dim_zero_cat(self.real_features)
            fake_features = dim_zero_cat(self.fake_features)
        else:
            if self._feature_dim is None:
                raise ValueError("Argument `subset_size` should be smaller than the number of samples")
            real_features = self._pool(real=True)
            fake_features = self._pool(real=False)

        n_samples_real = real_features.shape[0]
        if n_samples_real < self.subset_size:
            raise ValueError("Argument `subset_size` should be smaller than the number of samples")
        n_samples_fake = fake_features.shape[0]
        if n_samples_fake < self.subset_size:
            raise ValueError("Argument `subset_size` should be smaller than the number of samples")

        kid_scores_ = []
        for _ in range(self.subsets):
            perm = self._rng.permutation(n_samples_real)
            f_real = real_features[perm[: self.subset_size]]
            perm = self._rng.permutation(n_samples_fake)
            f_fake = fake_features[perm[: self.subset_size]]
            kid_scores_.append(poly_mmd(f_real, f_fake, self.degree, self.gamma, self.coef))
        kid_scores = jnp.stack(kid_scores_)
        # ddof=1: reference kid.py returns torch.std (unbiased) over subsets
        return jnp.mean(kid_scores), jnp.std(kid_scores, ddof=1)
