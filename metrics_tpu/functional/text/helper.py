"""Shared text-metric helpers: input validation + vectorized edit distance/LCS.

Behavior parity with /root/reference/torchmetrics/functional/text/helper.py
(`_edit_distance` :347-368, `_validate_inputs` :307-344).  The reference runs
pure-Python O(N·M) cell loops; here both DPs are re-expressed as row-wise
vectorized numpy recurrences (the left-neighbor dependency is resolved with a
prefix min/max cascade), giving the same exact integers orders of magnitude
faster.  Tokenization and string handling remain host-side by design — text
metrics feed scalar device states (SURVEY §7.8).
"""
from typing import List, Sequence, Tuple, Union

import numpy as np


def _validate_inputs(
    ref_corpus: Union[Sequence[str], Sequence[Sequence[str]]],
    hyp_corpus: Union[str, Sequence[str]],
) -> Tuple[Sequence[Sequence[str]], Sequence[str]]:
    """Normalize reference/hypothesis corpora to ``Sequence[Sequence[str]]`` / ``Sequence[str]``."""
    if isinstance(hyp_corpus, str):
        hyp_corpus = [hyp_corpus]

    if all(isinstance(ref, str) for ref in ref_corpus):
        if len(hyp_corpus) == 1:
            ref_corpus = [ref_corpus]  # type: ignore[list-item]
        else:
            ref_corpus = [[ref] for ref in ref_corpus]  # type: ignore[misc]

    if hyp_corpus and all(ref for ref in ref_corpus) and len(ref_corpus) != len(hyp_corpus):
        raise ValueError(f"Corpus has different size {len(ref_corpus)} != {len(hyp_corpus)}")
    return ref_corpus, hyp_corpus


def _token_ids(a: Sequence[str], b: Sequence[str]) -> Tuple[np.ndarray, np.ndarray]:
    """Map two token sequences into a shared integer id space."""
    vocab: dict = {}
    aid = np.fromiter((vocab.setdefault(t, len(vocab)) for t in a), np.int64, len(a))
    bid = np.fromiter((vocab.setdefault(t, len(vocab)) for t in b), np.int64, len(b))
    return aid, bid


def _edit_distance(prediction_tokens: List[str], reference_tokens: List[str]) -> int:
    """Levenshtein distance between two token sequences.

    Same integers as the reference cell-loop DP (helper.py:347-368); each DP
    row is one vectorized numpy step.  The in-row insertion dependency
    ``dp[j] = min(dp[j], dp[j-1]+1)`` telescopes to
    ``min_k<=j (cand[k] + (j-k))``, computed as a running min of
    ``cand[k]-k`` plus ``j``.
    """
    n, m = len(prediction_tokens), len(reference_tokens)
    if n == 0:
        return m
    if m == 0:
        return n
    pid, rid = _token_ids(prediction_tokens, reference_tokens)

    jrange = np.arange(m + 1, dtype=np.int64)
    prev = jrange.copy()
    cand = np.empty(m + 1, np.int64)
    for i in range(1, n + 1):
        subst = (rid != pid[i - 1]).astype(np.int64)
        cand[0] = i
        np.minimum(prev[1:] + 1, prev[:-1] + subst, out=cand[1:])
        prev = np.minimum.accumulate(cand - jrange) + jrange
    return int(prev[m])


def _lcs(pred_tokens: Sequence[str], target_tokens: Sequence[str]) -> int:
    """Length of the longest common subsequence (reference rouge.py:76-91).

    Row-vectorized: within a row the left-neighbor max telescopes to a plain
    running maximum (LCS rows are non-decreasing).
    """
    n, m = len(pred_tokens), len(target_tokens)
    if n == 0 or m == 0:
        return 0
    pid, tid = _token_ids(pred_tokens, target_tokens)

    prev = np.zeros(m + 1, np.int64)
    cand = np.empty(m + 1, np.int64)
    for i in range(1, n + 1):
        eq = (tid == pid[i - 1]).astype(np.int64)
        cand[0] = 0
        np.maximum(prev[1:], prev[:-1] + eq, out=cand[1:])
        prev = np.maximum.accumulate(cand)
    return int(prev[m])
