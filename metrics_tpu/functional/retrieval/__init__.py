from metrics_tpu.functional.retrieval.average_precision import retrieval_average_precision  # noqa: F401
from metrics_tpu.functional.retrieval.fall_out import retrieval_fall_out  # noqa: F401
from metrics_tpu.functional.retrieval.hit_rate import retrieval_hit_rate  # noqa: F401
from metrics_tpu.functional.retrieval.ndcg import retrieval_normalized_dcg  # noqa: F401
from metrics_tpu.functional.retrieval.precision import retrieval_precision  # noqa: F401
from metrics_tpu.functional.retrieval.r_precision import retrieval_r_precision  # noqa: F401
from metrics_tpu.functional.retrieval.recall import retrieval_recall  # noqa: F401
from metrics_tpu.functional.retrieval.reciprocal_rank import retrieval_reciprocal_rank  # noqa: F401
