"""Modular MeanAveragePrecision (COCO mAP/mAR) for object detection.

Behavior parity with /root/reference/torchmetrics/detection/map.py:133-735
(pycocotools-style evaluation, the reference's heaviest CPU-bound path,
SURVEY §3.4).  The compute pipeline is re-architected TPU-first: the
per-(image, class, area, threshold) Python matching loops become one jitted
static-shape kernel (see metrics_tpu/functional/detection/mean_ap.py).

State modes: by DEFAULT each image's detections and ground truths are
packed into ONE fixed-width row of a reservoir table
(``sketches/reservoir.py``) — ``det_slots`` capped detections,
``gt_slots`` ground truths, plus the image's global arrival index, all
flattened into ``[max_images, 1 + row_cols]`` float32.  Admission uses
DETERMINISTIC hash-key priorities (:func:`reservoir_key` of the global
image index, the retrieval table's ``_qid_key`` contract): the admitted
image set is a pure function of the index set, so results are invariant
to batch chunking, padding, and cross-rank merge order.  While
``images_seen <= max_images`` the table holds every image in arrival
order and ``compute()`` reproduces the unbounded list path bit-for-bit;
past capacity it evaluates a uniform ~``max_images``-image subsample.
``exact=True`` restores the reference's unbounded per-image lists (and
its large-memory warning).

Capacity caveats (see docs/image_detection_states.md): detections are
capped PER IMAGE at ``det_slots`` (top scores, arrival order preserved),
a stricter cut than the reference's per-(image, class) ``max_det`` cap —
identical unless one image carries more than ``det_slots`` detections
across ALL classes.  An image with more than ``gt_slots`` ground truths
raises (raise ``gt_slots`` at construction).  Global image indices are
stored as float32 — exact below 2**24 images.
"""
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.detection.mean_ap import (
    _calculate_precision_recall,
    _match_units_kernel_packed,
    _pack_units,
    _summarize,
    _unpack_bool_bits,
)
from metrics_tpu.sketches.compat import register_exact_list_states, warn_exact_buffer
from metrics_tpu.sketches.moments import moments_merge_fx
from metrics_tpu.sketches.reservoir import (
    detection_table_init,
    reservoir_insert_keyed,
    reservoir_key,
    reservoir_merge_fx,
)
from metrics_tpu.utils.checks import _is_concrete

Array = jax.Array

# cap on chunk_size * D * G: bounds the device IoU buffer at ~16 MB f32
_UNIT_CHUNK_ELEMS = 1 << 22

_BBOX_AREA_RANGES = {
    # reference map.py:254-259
    "all": (0.0, 1e10),
    "small": (0.0, 32.0 ** 2),
    "medium": (32.0 ** 2, 96.0 ** 2),
    "large": (96.0 ** 2, 1e10),
}

_NEG_INF = -np.inf


def _input_validator(preds: Sequence[dict], targets: Sequence[dict]) -> None:
    """Validate the list-of-dicts input format (reference map.py:83-123)."""
    if not isinstance(preds, Sequence):
        raise ValueError("Expected argument `preds` to be of type Sequence")
    if not isinstance(targets, Sequence):
        raise ValueError("Expected argument `target` to be of type Sequence")
    if len(preds) != len(targets):
        raise ValueError("Expected argument `preds` and `target` to have the same length")

    for k in ["boxes", "scores", "labels"]:
        if any(k not in p for p in preds):
            raise ValueError(f"Expected all dicts in `preds` to contain the `{k}` key")
    for k in ["boxes", "labels"]:
        if any(k not in p for p in targets):
            raise ValueError(f"Expected all dicts in `target` to contain the `{k}` key")

    def _is_arr(x: Any) -> bool:
        return isinstance(x, (jnp.ndarray, np.ndarray))

    if any(not _is_arr(p["boxes"]) for p in preds):
        raise ValueError("Expected all boxes in `preds` to be of type Tensor")
    if any(not _is_arr(p["scores"]) for p in preds):
        raise ValueError("Expected all scores in `preds` to be of type Tensor")
    if any(not _is_arr(p["labels"]) for p in preds):
        raise ValueError("Expected all labels in `preds` to be of type Tensor")
    if any(not _is_arr(t["boxes"]) for t in targets):
        raise ValueError("Expected all boxes in `target` to be of type Tensor")
    if any(not _is_arr(t["labels"]) for t in targets):
        raise ValueError("Expected all labels in `target` to be of type Tensor")

    for i, item in enumerate(targets):
        n_boxes = item["boxes"].shape[0] if item["boxes"].ndim > 1 else len(item["boxes"])
        if n_boxes != len(item["labels"]):
            raise ValueError(
                f"Input boxes and labels of sample {i} in targets have a"
                f" different length (expected {n_boxes} labels, got {len(item['labels'])})"
            )
    for i, item in enumerate(preds):
        n_boxes = item["boxes"].shape[0] if item["boxes"].ndim > 1 else len(item["boxes"])
        if not (n_boxes == len(item["labels"]) == len(item["scores"])):
            raise ValueError(
                f"Input boxes, labels and scores of sample {i} in predictions have a"
                f" different length (expected {n_boxes} labels and scores,"
                f" got {len(item['labels'])} labels and {len(item['scores'])} scores)"
            )


def _to_xyxy_np(boxes: Any, box_format: str) -> np.ndarray:
    """Normalize a per-image box array to host float32 ``[n, 4]`` xyxy.

    Host numpy on purpose: per-image boxes are tiny and ragged, and keeping
    them on device would mean hundreds of latency-bound host↔device
    transfers at pack time (the packed static buffers are shipped to the
    device in one piece instead).
    """
    boxes = np.asarray(boxes, dtype=np.float32)
    if boxes.size == 0:
        return np.zeros((0, 4), np.float32)
    boxes = boxes.reshape(-1, 4)
    if box_format == "xyxy":
        return boxes
    a, b, c, d = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    if box_format == "xywh":
        return np.stack([a, b, a + c, b + d], axis=1)
    return np.stack([a - c / 2, b - d / 2, a + c / 2, b + d / 2], axis=1)  # cxcywh


def _unique_classes(det_labels: List[np.ndarray], gt_labels: List[np.ndarray]) -> List[int]:
    """Sorted unique class ids across detections and ground truths (map.py:329-333)."""
    labels = list(det_labels) + list(gt_labels)
    if not labels:
        return []
    cat = np.concatenate([np.asarray(l).reshape(-1) for l in labels])
    return sorted(int(c) for c in np.unique(cat))


class MeanAveragePrecision(Metric):
    """Computes COCO-style Mean Average Precision / Recall for object detection.

    Inputs are per-image dicts: predictions with ``boxes`` ``[n, 4]``,
    ``scores`` ``[n]``, ``labels`` ``[n]``; targets with ``boxes`` and
    ``labels`` (reference map.py:271-313).  The fused/traced path instead
    takes batched padded dicts — predictions with ``boxes [B, D, 4]``,
    ``scores [B, D]``, ``labels [B, D]``, ``n [B]``; targets with
    ``boxes [B, G, 4]``, ``labels [B, G]``, ``n [B]``.

    Args:
        box_format: input box layout — "xyxy", "xywh" or "cxcywh".
        iou_thresholds / rec_thresholds / max_detection_thresholds /
            class_metrics: the reference's evaluation grid (map.py:250-253).
        max_images: streaming table capacity in IMAGES; lossless (bit-equal
            to the list path) while the stream fits, a deterministic uniform
            image subsample past it.
        det_slots: per-image detection capacity (default: the largest
            ``max_detection_thresholds`` entry); extra detections are
            dropped lowest-score-first.
        gt_slots: per-image ground-truth capacity (default ``det_slots``);
            an image exceeding it raises.
        exact: restore the reference's unbounded per-image list states.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.detection import MeanAveragePrecision
        >>> preds = [dict(
        ...     boxes=jnp.array([[258.0, 41.0, 606.0, 285.0]]),
        ...     scores=jnp.array([0.536]),
        ...     labels=jnp.array([0]))]
        >>> target = [dict(
        ...     boxes=jnp.array([[214.0, 41.0, 562.0, 285.0]]),
        ...     labels=jnp.array([0]))]
        >>> metric = MeanAveragePrecision()
        >>> metric.update(preds, target)
        >>> float(metric.compute()["map"])  # doctest: +ELLIPSIS
        0.6000...
    """

    __exact_mode_attr__ = "_exact"
    __fused_mask_valid__ = True
    is_differentiable = False
    higher_is_better = True

    def __init__(
        self,
        box_format: str = "xyxy",
        iou_thresholds: Optional[List[float]] = None,
        rec_thresholds: Optional[List[float]] = None,
        max_detection_thresholds: Optional[List[int]] = None,
        class_metrics: bool = False,
        max_images: int = 4096,
        det_slots: Optional[int] = None,
        gt_slots: Optional[int] = None,
        exact: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)

        allowed_box_formats = ("xyxy", "xywh", "cxcywh")
        if box_format not in allowed_box_formats:
            raise ValueError(f"Expected argument `box_format` to be one of {allowed_box_formats} but got {box_format}")
        self.box_format = box_format
        # defaults: reference map.py:250-253
        self.iou_thresholds = list(iou_thresholds) if iou_thresholds else [
            0.5 + 0.05 * i for i in range(10)
        ]
        self.rec_thresholds = list(rec_thresholds) if rec_thresholds else [
            0.01 * i for i in range(101)
        ]
        self.max_detection_thresholds = sorted(max_detection_thresholds or [1, 10, 100])
        self.bbox_area_ranges = dict(_BBOX_AREA_RANGES)

        if not isinstance(class_metrics, bool):
            raise ValueError("Expected argument `class_metrics` to be a boolean")
        self.class_metrics = class_metrics

        last_max_det = self.max_detection_thresholds[-1]
        det_slots = last_max_det if det_slots is None else det_slots
        gt_slots = det_slots if gt_slots is None else gt_slots
        for name, val in (("max_images", max_images), ("det_slots", det_slots), ("gt_slots", gt_slots)):
            if not (isinstance(val, int) and val > 0):
                raise ValueError(f"Argument `{name}` expected to be a positive int, got {val}")
        if det_slots < last_max_det:
            raise ValueError(
                f"Argument `det_slots` ({det_slots}) must cover the largest"
                f" max_detection threshold ({last_max_det})"
            )
        self._det_slots = det_slots
        self._gt_slots = gt_slots
        self._max_images = max_images
        # row: [global_idx, rank, n_det, n_gt, det boxes 4D, scores D,
        #       labels D, gt boxes 4G, labels G]
        self._row_cols = 4 + 6 * det_slots + 5 * gt_slots

        self._exact = bool(exact)
        if self._exact:
            register_exact_list_states(
                self,
                (
                    "detection_boxes",
                    "detection_scores",
                    "detection_labels",
                    "groundtruth_boxes",
                    "groundtruth_labels",
                ),
                dist_reduce_fx=None,
            )
            warn_exact_buffer("MeanAveragePrecision", "detections and ground truths")
        else:
            self.add_state(
                "table",
                default=detection_table_init(max_images, self._row_cols),
                dist_reduce_fx=reservoir_merge_fx(),
            )
            # moments reducer, not "sum": cross-rank reduction is the same
            # element-wise addition, but the merge_like tag tells the fused
            # bucketing path this leaf self-masks pad rows via n_valid — the
            # generic k*delta pad correction would double-subtract them
            self.add_state(
                "images_seen", default=jnp.zeros((), jnp.int32), dist_reduce_fx=moments_merge_fx()
            )

    def _boxes_to_xyxy(self, boxes: Array) -> Array:
        """Traced ``[..., 4]`` box-format conversion (the device counterpart
        of :func:`_to_xyxy_np`; ``self.box_format`` is static)."""
        if self.box_format == "xyxy":
            return boxes
        a, b, c, d = boxes[..., 0], boxes[..., 1], boxes[..., 2], boxes[..., 3]
        if self.box_format == "xywh":
            return jnp.stack([a, b, a + c, b + d], axis=-1)
        return jnp.stack([a - c / 2, b - d / 2, a + c / 2, b + d / 2], axis=-1)  # cxcywh

    def _pack_images_host(self, preds: Sequence[dict], target: Sequence[dict]):
        """Canonicalize ragged list-of-dicts input into the padded batched
        dict layout the traced tail consumes (boxes stay in ``box_format``;
        the shared ``_boxes_to_xyxy`` converts both paths)."""
        _input_validator(preds, target)
        b = len(preds)
        D, G = self._det_slots, self._gt_slots
        d_boxes = np.zeros((b, D, 4), np.float32)
        d_scores = np.zeros((b, D), np.float32)
        d_labels = np.zeros((b, D), np.float32)
        d_n = np.zeros((b,), np.int32)
        g_boxes = np.zeros((b, G, 4), np.float32)
        g_labels = np.zeros((b, G), np.float32)
        g_n = np.zeros((b,), np.int32)
        for i, (p, t) in enumerate(zip(preds, target)):
            pb = np.asarray(p["boxes"], np.float32)
            pb = pb.reshape(-1, 4) if pb.size else np.zeros((0, 4), np.float32)
            ps = np.asarray(p["scores"], np.float32).reshape(-1)
            pl = np.asarray(p["labels"], np.float32).reshape(-1)
            nd = pb.shape[0]
            if nd > D:
                # keep the top-D by score, restored to arrival order (ties
                # break low-index-first, matching the traced lax.top_k cap)
                keep = np.sort(np.argsort(-ps, kind="stable")[:D])
                pb, ps, pl = pb[keep], ps[keep], pl[keep]
                nd = D
            tb = np.asarray(t["boxes"], np.float32)
            tb = tb.reshape(-1, 4) if tb.size else np.zeros((0, 4), np.float32)
            tl = np.asarray(t["labels"], np.float32).reshape(-1)
            ng = tb.shape[0]
            if ng > G:
                raise ValueError(
                    f"Image {i} carries {ng} ground-truth boxes but the streaming table"
                    f" holds {G} per image — raise `gt_slots` (or use `exact=True`)"
                )
            d_boxes[i, :nd] = pb
            d_scores[i, :nd] = ps
            d_labels[i, :nd] = pl
            d_n[i] = nd
            g_boxes[i, :ng] = tb
            g_labels[i, :ng] = tl
            g_n[i] = ng
        return (
            dict(boxes=d_boxes, scores=d_scores, labels=d_labels, n=d_n),
            dict(boxes=g_boxes, labels=g_labels, n=g_n),
        )

    def _update_exact(self, preds: Sequence[dict], target: Sequence[dict]) -> None:
        _input_validator(preds, target)

        # states are host numpy: ragged per-image data never round-trips the
        # device; only the packed static buffers do (once, at compute time)
        for item in preds:
            self.detection_boxes.append(_to_xyxy_np(item["boxes"], self.box_format))
            self.detection_labels.append(np.asarray(item["labels"]).reshape(-1).astype(np.int32))
            self.detection_scores.append(np.asarray(item["scores"]).reshape(-1).astype(np.float32))
        for item in target:
            self.groundtruth_boxes.append(_to_xyxy_np(item["boxes"], self.box_format))
            self.groundtruth_labels.append(np.asarray(item["labels"]).reshape(-1).astype(np.int32))

    def _update(
        self,
        preds: Any,
        target: Any,
        n_valid: Optional[Array] = None,
    ) -> None:
        if self._exact:
            self._update_exact(preds, target)
            return
        if not isinstance(preds, dict) and _is_concrete(preds, target):
            # ragged list-of-dicts API: validate (reference error messages)
            # and canonicalize on host; batched padded dicts skip ahead
            preds, target = self._pack_images_host(preds, target)

        d_boxes = self._boxes_to_xyxy(jnp.asarray(preds["boxes"], jnp.float32))
        d_scores = jnp.asarray(preds["scores"], jnp.float32)
        d_labels = jnp.asarray(preds["labels"], jnp.float32)
        d_n = jnp.asarray(preds["n"], jnp.int32)
        g_boxes = self._boxes_to_xyxy(jnp.asarray(target["boxes"], jnp.float32))
        g_labels = jnp.asarray(target["labels"], jnp.float32)
        g_n = jnp.asarray(target["n"], jnp.int32)

        b, d_in = d_scores.shape
        if b == 0:
            return
        g_in = g_labels.shape[1]
        if g_in > self._gt_slots:
            raise ValueError(
                f"got {g_in} ground-truth slots but the streaming table holds"
                f" {self._gt_slots} per image — raise `gt_slots`"
            )
        if d_in > self._det_slots:
            # per-image cap: keep the top-det_slots valid scores, restored
            # to arrival order (sorted kept indices)
            slot = jnp.arange(d_in, dtype=jnp.int32)
            masked = jnp.where(slot[None, :] < d_n[:, None], d_scores, -jnp.inf)
            _, idx = jax.lax.top_k(masked, self._det_slots)
            idx = jnp.sort(idx, axis=1)
            d_boxes = jnp.take_along_axis(d_boxes, idx[:, :, None], axis=1)
            d_scores = jnp.take_along_axis(d_scores, idx, axis=1)
            d_labels = jnp.take_along_axis(d_labels, idx, axis=1)
            d_n = jnp.minimum(d_n, self._det_slots)
            d_in = self._det_slots

        # zero dead slots so admitted rows are bit-deterministic, then pad
        # the slot axes up to the table's static capacity
        d_live = jnp.arange(d_in, dtype=jnp.int32)[None, :] < d_n[:, None]
        d_boxes = jnp.where(d_live[:, :, None], d_boxes, 0.0)
        d_scores = jnp.where(d_live, d_scores, 0.0)
        d_labels = jnp.where(d_live, d_labels, 0.0)
        g_live = jnp.arange(g_in, dtype=jnp.int32)[None, :] < g_n[:, None]
        g_boxes = jnp.where(g_live[:, :, None], g_boxes, 0.0)
        g_labels = jnp.where(g_live, g_labels, 0.0)
        dpad = self._det_slots - d_in
        gpad = self._gt_slots - g_in
        if dpad:
            d_boxes = jnp.pad(d_boxes, ((0, 0), (0, dpad), (0, 0)))
            d_scores = jnp.pad(d_scores, ((0, 0), (0, dpad)))
            d_labels = jnp.pad(d_labels, ((0, 0), (0, dpad)))
        if gpad:
            g_boxes = jnp.pad(g_boxes, ((0, 0), (0, gpad), (0, 0)))
            g_labels = jnp.pad(g_labels, ((0, 0), (0, gpad)))

        # hash-key admission over global image indices: pad rows (masked by
        # n_valid) advance neither the index cursor nor the table. The
        # process index joins the hash input (KID's seed-folding idiom) so
        # ranks holding the same local indices draw decorrelated priorities.
        valid = jnp.arange(b) < n_valid if n_valid is not None else jnp.ones((b,), bool)
        global_idx = self.images_seen + jnp.cumsum(valid.astype(jnp.int32)) - 1
        rank = jax.process_index()
        keys = reservoir_key(jnp.asarray(global_idx, jnp.uint32) + jnp.uint32(rank) * jnp.uint32(1 << 24))
        rows = jnp.concatenate(
            [
                global_idx.astype(jnp.float32)[:, None],
                jnp.full((b, 1), rank, jnp.float32),
                d_n.astype(jnp.float32)[:, None],
                g_n.astype(jnp.float32)[:, None],
                d_boxes.reshape(b, -1),
                d_scores,
                d_labels,
                g_boxes.reshape(b, -1),
                g_labels,
            ],
            axis=1,
        )
        self.table = reservoir_insert_keyed(self.table, rows, keys, n_valid=n_valid)
        self.images_seen = self.images_seen + jnp.sum(valid.astype(jnp.int32))

    def _compute(self) -> Dict[str, Array]:
        if self._exact:
            return self._compute_from_lists(
                self.detection_boxes,
                self.detection_scores,
                self.detection_labels,
                self.groundtruth_boxes,
                self.groundtruth_labels,
            )

        # unpack admitted table rows back into per-image host lists, in
        # rank-major arrival order — the reference's DDP gather order —
        # (bit-equal to the list path while lossless)
        leaf = np.asarray(self.table)  # tracelint: disable=TL-TRACE — compute() IS the host COCO pipeline; only _update runs under the fused trace
        rows = leaf[leaf[:, 0] > _NEG_INF, 1:]
        rows = rows[np.lexsort((rows[:, 0], rows[:, 1]))]
        D, G = self._det_slots, self._gt_slots
        n = rows.shape[0]
        nd = rows[:, 2].astype(np.int32)
        ng = rows[:, 3].astype(np.int32)
        # whole-matrix slices + casts (one pass over the leaf), then cheap
        # per-image views — a per-row python unpack would dominate compute()
        # at serving scale
        off = 4
        db = rows[:, off : off + 4 * D].astype(np.float32).reshape(n, D, 4)
        off += 4 * D
        ds = rows[:, off : off + D].astype(np.float32)
        off += D
        dl = rows[:, off : off + D].astype(np.int32)
        off += D
        gb = rows[:, off : off + 4 * G].astype(np.float32).reshape(n, G, 4)
        off += 4 * G
        gl = rows[:, off : off + G].astype(np.int32)
        return self._compute_from_lists(
            [db[i, : nd[i]] for i in range(n)],
            [ds[i, : nd[i]] for i in range(n)],
            [dl[i, : nd[i]] for i in range(n)],
            [gb[i, : ng[i]] for i in range(n)],
            [gl[i, : ng[i]] for i in range(n)],
        )

    def _compute_from_lists(
        self,
        det_boxes: List[np.ndarray],
        det_scores: List[np.ndarray],
        det_labels: List[np.ndarray],
        gt_boxes: List[np.ndarray],
        gt_labels: List[np.ndarray],
    ) -> Dict[str, Array]:
        classes = _unique_classes(det_labels, gt_labels)
        num_classes = len(classes)
        area_ranges = list(self.bbox_area_ranges.values())
        num_areas = len(area_ranges)
        T = len(self.iou_thresholds)
        R = len(self.rec_thresholds)
        M = len(self.max_detection_thresholds)
        last_max_det = self.max_detection_thresholds[-1]

        packed = _pack_units(
            [np.asarray(b) for b in det_boxes],
            [np.asarray(s, np.float64) for s in det_scores],
            [np.asarray(l) for l in det_labels],
            [np.asarray(b) for b in gt_boxes],
            [np.asarray(l) for l in gt_labels],
            classes,
            last_max_det,
        )

        if packed is None:
            precision = -np.ones((T, R, num_classes, num_areas, M))
            recall = -np.ones((T, num_classes, num_areas, M))
        else:
            # chunk units through the kernel so peak device memory is bounded
            # by chunk*D*G regardless of dataset size (COCO-scale U can reach
            # ~10^5 units; the [U, D, G] IoU buffer must not scale with it)
            U = packed.det_boxes.shape[0]
            chunk = max(1, _UNIT_CHUNK_ELEMS // max(packed.det_boxes.shape[1] * packed.gt_boxes.shape[1], 1))
            dm_parts, dao_parts, npig_parts = [], [], []
            iou_thrs = jnp.asarray(self.iou_thresholds, jnp.float32)
            areas_arr = jnp.asarray(np.asarray(area_ranges, np.float32))
            for lo in range(0, U, chunk):
                hi = min(lo + chunk, U)
                n = hi - lo
                pad = chunk - n if U > chunk else 0  # keep one compiled shape
                dm, dao, npig_c = _match_units_kernel_packed(
                    jnp.asarray(np.pad(packed.det_boxes[lo:hi], ((0, pad), (0, 0), (0, 0)))),
                    jnp.asarray(np.pad(packed.det_valid[lo:hi], ((0, pad), (0, 0)))),
                    jnp.asarray(np.pad(packed.gt_boxes[lo:hi], ((0, pad), (0, 0), (0, 0)))),
                    jnp.asarray(np.pad(packed.gt_valid[lo:hi], ((0, pad), (0, 0)))),
                    iou_thrs,
                    areas_arr,
                )
                max_det_dim = packed.det_boxes.shape[1]
                dm_parts.append(_unpack_bool_bits(np.asarray(dm)[:n], max_det_dim))
                dao_parts.append(_unpack_bool_bits(np.asarray(dao)[:n], max_det_dim))
                npig_parts.append(np.asarray(npig_c)[:n])
            det_matches = np.concatenate(dm_parts)
            det_area_out = np.concatenate(dao_parts)
            npig = np.concatenate(npig_parts)
            precision, recall = _calculate_precision_recall(
                packed,
                det_matches,
                det_area_out,
                npig,
                num_classes,
                num_areas,
                self.iou_thresholds,
                self.rec_thresholds,
                self.max_detection_thresholds,
            )

        area_keys = list(self.bbox_area_ranges.keys())

        def summ(avg_prec: bool, iou_thr: Optional[float] = None, area: str = "all", mdet: int = last_max_det,
                 prec: np.ndarray = precision, rec: np.ndarray = recall) -> float:
            return _summarize(
                prec, rec, avg_prec, self.iou_thresholds,
                iou_threshold=iou_thr,
                area_idx=area_keys.index(area),
                mdet_idx=self.max_detection_thresholds.index(mdet),
            )

        # the reference's top-level `map` summarize call keeps _summarize's
        # hardcoded max_dets=100 default (map.py:484,591) — with custom
        # thresholds lacking 100 the selection is empty and the value is -1
        has_100 = 100 in self.max_detection_thresholds

        results: Dict[str, Array] = {}
        results["map"] = jnp.asarray(summ(True, mdet=100) if has_100 else -1.0, jnp.float32)
        results["map_50"] = jnp.asarray(
            summ(True, iou_thr=0.5) if 0.5 in self.iou_thresholds else -1.0, jnp.float32
        )
        results["map_75"] = jnp.asarray(
            summ(True, iou_thr=0.75) if 0.75 in self.iou_thresholds else -1.0, jnp.float32
        )
        results["map_small"] = jnp.asarray(summ(True, area="small"), jnp.float32)
        results["map_medium"] = jnp.asarray(summ(True, area="medium"), jnp.float32)
        results["map_large"] = jnp.asarray(summ(True, area="large"), jnp.float32)
        for mdet in self.max_detection_thresholds:
            results[f"mar_{mdet}"] = jnp.asarray(summ(False, mdet=mdet), jnp.float32)
        results["mar_small"] = jnp.asarray(summ(False, area="small"), jnp.float32)
        results["mar_medium"] = jnp.asarray(summ(False, area="medium"), jnp.float32)
        results["mar_large"] = jnp.asarray(summ(False, area="large"), jnp.float32)

        # per-class metrics (reference map.py:713-728)
        map_per_class = [-1.0]
        mar_per_class = [-1.0]
        if self.class_metrics and num_classes:
            map_per_class = []
            mar_per_class = []
            for k in range(num_classes):
                cls_prec = precision[:, :, k : k + 1]
                cls_rec = recall[:, k : k + 1]
                map_per_class.append(summ(True, mdet=100, prec=cls_prec, rec=cls_rec) if has_100 else -1.0)
                mar_per_class.append(summ(False, mdet=last_max_det, prec=cls_prec, rec=cls_rec))
        results["map_per_class"] = jnp.asarray(map_per_class, jnp.float32)
        results[f"mar_{last_max_det}_per_class"] = jnp.asarray(mar_per_class, jnp.float32)
        return results
