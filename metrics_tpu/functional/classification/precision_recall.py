"""Precision / recall functional kernels.

Behavior parity with /root/reference/torchmetrics/functional/classification/
precision_recall.py:23-434, with the macro class-removal re-expressed as a
jit-safe ignore mask (identical numerics through ``_reduce_stat_scores``).
"""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.stat_scores import (
    _check_avg_arguments,
    _reduce_stat_scores,
    _stat_scores_update,
)
from metrics_tpu.utils.enums import AverageMethod, MDMCAverageMethod

Array = jax.Array


def _mask_macro_none(
    numerator: Array,
    denominator: Array,
    tp: Array,
    fp: Array,
    fn: Array,
    average: Optional[str],
    mdmc_average: Optional[str],
) -> Tuple[Array, Array]:
    """Shared absent-class masking for macro / none averaging."""
    numerator = numerator.astype(jnp.float32)
    denominator = denominator.astype(jnp.float32)
    if average == AverageMethod.MACRO and mdmc_average != MDMCAverageMethod.SAMPLEWISE:
        cond = (tp + fp + fn) == 0
        numerator = jnp.where(cond, 0.0, numerator)
        denominator = jnp.where(cond, -1.0, denominator)
    if average == AverageMethod.NONE and mdmc_average != MDMCAverageMethod.SAMPLEWISE:
        cond = (tp | fn | fp) == 0
        numerator = jnp.where(cond, -1.0, numerator)
        denominator = jnp.where(cond, -1.0, denominator)
    return numerator, denominator


def _precision_compute(
    tp: Array,
    fp: Array,
    fn: Array,
    average: str,
    mdmc_average: Optional[str],
) -> Array:
    """Reference precision_recall.py:23-78."""
    numerator, denominator = _mask_macro_none(tp, tp + fp, tp, fp, fn, average, mdmc_average)
    return _reduce_stat_scores(
        numerator=numerator,
        denominator=denominator,
        weights=None if average != AverageMethod.WEIGHTED else (tp + fn),
        average=average,
        mdmc_average=mdmc_average,
    )


def _recall_compute(
    tp: Array,
    fp: Array,
    fn: Array,
    average: str,
    mdmc_average: Optional[str],
) -> Array:
    """Reference precision_recall.py:221-276."""
    numerator, denominator = _mask_macro_none(tp, tp + fn, tp, fp, fn, average, mdmc_average)
    return _reduce_stat_scores(
        numerator=numerator,
        denominator=denominator,
        weights=None if average != AverageMethod.WEIGHTED else (tp + fn),
        average=average,
        mdmc_average=mdmc_average,
    )


def precision(
    preds: Array,
    target: Array,
    average: str = "micro",
    mdmc_average: Optional[str] = None,
    ignore_index: Optional[int] = None,
    num_classes: Optional[int] = None,
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    multiclass: Optional[bool] = None,
) -> Array:
    """One-shot precision. Reference precision_recall.py:81-218.

    Example:
        >>> import jax.numpy as jnp
        >>> preds  = jnp.array([2, 0, 2, 1])
        >>> target = jnp.array([1, 1, 2, 0])
        >>> precision(preds, target, average='macro', num_classes=3)
        Array(0.16666667, dtype=float32)
    """
    _check_avg_arguments(average, mdmc_average, num_classes, ignore_index)
    reduce = "macro" if average in ("weighted", "none", None) else average
    tp, fp, _, fn = _stat_scores_update(
        preds,
        target,
        reduce=reduce,
        mdmc_reduce=mdmc_average,
        threshold=threshold,
        num_classes=num_classes,
        top_k=top_k,
        multiclass=multiclass,
        ignore_index=ignore_index,
    )
    return _precision_compute(tp, fp, fn, average, mdmc_average)


def recall(
    preds: Array,
    target: Array,
    average: str = "micro",
    mdmc_average: Optional[str] = None,
    ignore_index: Optional[int] = None,
    num_classes: Optional[int] = None,
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    multiclass: Optional[bool] = None,
) -> Array:
    """One-shot recall. Reference precision_recall.py:279-416.

    Example:
        >>> import jax.numpy as jnp
        >>> preds  = jnp.array([2, 0, 2, 1])
        >>> target = jnp.array([1, 1, 2, 0])
        >>> recall(preds, target, average='macro', num_classes=3)
        Array(0.33333334, dtype=float32)
    """
    _check_avg_arguments(average, mdmc_average, num_classes, ignore_index)
    reduce = "macro" if average in ("weighted", "none", None) else average
    tp, fp, _, fn = _stat_scores_update(
        preds,
        target,
        reduce=reduce,
        mdmc_reduce=mdmc_average,
        threshold=threshold,
        num_classes=num_classes,
        top_k=top_k,
        multiclass=multiclass,
        ignore_index=ignore_index,
    )
    return _recall_compute(tp, fp, fn, average, mdmc_average)


def precision_recall(
    preds: Array,
    target: Array,
    average: str = "micro",
    mdmc_average: Optional[str] = None,
    ignore_index: Optional[int] = None,
    num_classes: Optional[int] = None,
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    multiclass: Optional[bool] = None,
) -> Tuple[Array, Array]:
    """Both precision and recall from one stat-scores pass. Reference :419-556."""
    _check_avg_arguments(average, mdmc_average, num_classes, ignore_index)
    reduce = "macro" if average in ("weighted", "none", None) else average
    tp, fp, _, fn = _stat_scores_update(
        preds,
        target,
        reduce=reduce,
        mdmc_reduce=mdmc_average,
        threshold=threshold,
        num_classes=num_classes,
        top_k=top_k,
        multiclass=multiclass,
        ignore_index=ignore_index,
    )
    return (
        _precision_compute(tp, fp, fn, average, mdmc_average),
        _recall_compute(tp, fp, fn, average, mdmc_average),
    )
