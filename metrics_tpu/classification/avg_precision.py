"""Modular AveragePrecision (cat-state, exact sorted mode).

Behavior parity with /root/reference/torchmetrics/classification/avg_precision.py:28-143.
"""
from typing import Any, List, Optional, Union

import jax

from metrics_tpu.classification._capacity import CapacityCurveMixin
from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.exact_curve import (
    binary_average_precision_fixed,
    multiclass_average_precision_fixed,
)
from metrics_tpu.functional.classification.average_precision import (
    _average_precision_compute,
    _average_precision_update,
)
from metrics_tpu.utils.data import dim_zero_cat

Array = jax.Array


class AveragePrecision(CapacityCurveMixin, Metric):
    """Computes the average precision score.

    Example:
        >>> import jax.numpy as jnp
        >>> pred = jnp.array([0., 1., 2., 3.])
        >>> target = jnp.array([0, 1, 1, 1])
        >>> average_precision = AveragePrecision(pos_label=1)
        >>> average_precision(pred, target)
        Array(1., dtype=float32)
    """

    __jit_unsafe__ = True
    is_differentiable = False
    higher_is_better = True

    def __init__(
        self,
        num_classes: Optional[int] = None,
        pos_label: Optional[int] = None,
        average: Optional[str] = "macro",
        capacity: Optional[int] = None,
        multilabel: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.pos_label = pos_label
        allowed_average = ("micro", "macro", "weighted", "none", None)
        if average not in allowed_average:
            raise ValueError(f"Expected argument `average` to be one of {allowed_average} but got {average}")
        self.average = average
        # TPU-native exact mode: static [capacity] buffers, fully jit-safe.
        # Binary keeps the flat triple; num_classes >= 2 keeps [capacity, C]
        # score rows (one-vs-rest AP per class); `multilabel=True`
        # additionally stores [capacity, C] indicator targets.
        if (
            capacity is not None
            and num_classes is not None
            and num_classes >= 2
            and not multilabel
            and average == "micro"
        ):
            # parity with the unbounded path and capacity-mode AUROC
            # (reference avg_precision.py raises for micro + multi-class input)
            raise ValueError("Cannot use `micro` average with multi-class input")
        self._init_capacity_case(capacity, num_classes, multilabel)
        if capacity is None:
            self.add_state("preds", default=[], dist_reduce_fx="cat")
            self.add_state("target", default=[], dist_reduce_fx="cat")

    def _update(self, preds: Array, target: Array) -> None:
        if self._capacity is not None:
            self._capacity_update(preds, target, pos_label=self.pos_label)
            return
        preds, target, num_classes, pos_label = _average_precision_update(
            preds, target, self.num_classes, self.pos_label, self.average
        )
        self.preds.append(preds)
        self.target.append(target)
        self.num_classes = num_classes
        self.pos_label = pos_label

    def _compute(self) -> Union[Array, List[Array]]:
        if self._capacity is not None:
            if self._capacity_cols is not None:
                return multiclass_average_precision_fixed(
                    *self._capacity_buffers_2d(),
                    self.num_classes,
                    average="none" if self.average is None else self.average,
                    multilabel=self._capacity_multilabel,
                )
            return binary_average_precision_fixed(*self._capacity_buffers())
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _average_precision_compute(preds, target, self.num_classes, self.pos_label, self.average)
