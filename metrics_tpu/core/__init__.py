from metrics_tpu.core.fused import FUSED_ENTRY, FusedUpdate  # noqa: F401
from metrics_tpu.core.metric import CompositionalMetric, Metric  # noqa: F401
from metrics_tpu.core.pipeline import (  # noqa: F401
    AsyncQueueFull,
    AsyncUpdateHandle,
    AsyncWorkerError,
)

__all__ = [
    "AsyncQueueFull",
    "AsyncUpdateHandle",
    "AsyncWorkerError",
    "CompositionalMetric",
    "FUSED_ENTRY",
    "FusedUpdate",
    "Metric",
]
