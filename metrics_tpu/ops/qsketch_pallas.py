"""Pallas TPU kernel: fused quantile-sketch compaction (sort -> bucket).

Every sketched metric (AUROC, CalibrationError, Spearman, ...) past its
lossless window pays the merging-t-digest compaction in
``sketches/quantile.py::_compact_rows`` on overflow: a stable lexsort by
key, a weight prefix-sum to mid-quantile positions, the tail-adaptive
``k1(q) = (capacity / 2pi) * asin(2q - 1)`` bucket map, and a segment-sum
weighted-centroid merge. XLA lowers the sort generically (multi-pass HBM
round-trips) and cannot fuse it with the bucket arithmetic; this kernel
keeps the whole chain resident in VMEM:

* **Sort** — a bitonic compare-exchange network over the padded
  power-of-two row count, expressed as pure reshape + ``where`` stages
  (no gathers). Each element carries its original index as a tiebreak, so
  the network's output permutation is EXACTLY the fallback's stable
  ``lexsort((arange, key))`` — bitonic networks are not stable, but with
  the index tiebreak every composite key is distinct and the sorted order
  is unique.
* **Prefix sum** — the sorted weights' inclusive cumsum by log-step
  shift-adds (Hillis-Steele), still on-chip.
* **Bucket map** — mid-quantile positions through the k1 scale to integer
  bucket ids, plus the weighted rows ``[w, w*key, w*payload]`` the
  centroid merge consumes.

The segment-sum centroid merge itself reuses the SAME tiled one-hot MXU
kernel that serves bincount and the sliced scatter
(:func:`metrics_tpu.ops.scatter_pallas.segment_sum_tiled`), and the cheap
O(capacity) epilogue (weighted-mean divide, embed, stable pack) stays jnp.

Data is staged TRANSPOSED — ``[cols, n_pad]`` with the row axis on the
128-wide lane dimension — so the handful of sketch columns (2 + payload)
do not each pad to a full lane tile; compare-exchange reshapes only ever
split the lane axis.

Parity contract (pinned in ``tests/ops/``): with integer-valued weights
the prefix sum is order-independent-exact in f32, so sorted order, bucket
ids, and merged centroids are BIT-identical to the jnp path; with
arbitrary float weights the summation-order difference can flip a
bucket boundary, so parity is pinned at the sketch level — quantile
queries within the advertised ``rank_error_bound``.
"""
import functools
from typing import Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl

from metrics_tpu.ops.dispatch import dispatch, register_kernel
from metrics_tpu.ops.scatter_pallas import segment_sum_tiled

try:  # TPU-specific memory spaces; absent on CPU-only installs
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except ImportError:  # pragma: no cover
    _VMEM = None

Array = jax.Array
ArrayLike = Union[Array, np.ndarray]

#: largest padded row count the fused sort kernel accepts: 2**15 rows keep
#: the [cols, n_pad] stage plus the network's live temporaries well under
#: the ~16 MB VMEM budget at sketch-typical column counts
_MAX_SORT_ROWS = 1 << 15
#: below this the sort is too small for the kernel to matter; jnp path
_MIN_SORT_ROWS = 1 << 10


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def _bitonic_by_key(key: Array, idx: Array, data: Array, n_pad: int) -> Tuple[Array, Array]:
    """Ascending bitonic network on composite ``(key, idx)``; ``data``
    rides the permutation. ``key``/``idx`` are ``[1, n_pad]``, ``data`` is
    ``[cols, n_pad]``. Static Python loops — the network fully unrolls at
    trace time."""
    cols = data.shape[0]
    k = 2
    while k <= n_pad:
        j = k // 2
        while j >= 1:
            m = n_pad // (2 * j)
            kr = key.reshape(1, m, 2, j)
            ir = idx.reshape(1, m, 2, j)
            dr = data.reshape(cols, m, 2, j)
            klo, khi = kr[:, :, 0, :], kr[:, :, 1, :]
            ilo, ihi = ir[:, :, 0, :], ir[:, :, 1, :]
            gt = (klo > khi) | ((klo == khi) & (ilo > ihi))
            lt = (klo < khi) | ((klo == khi) & (ilo < ihi))
            # direction per 2j-block: bit k of the element index i = b*2j + r
            # (r < 2j <= k) depends only on the block index b
            blk = jax.lax.broadcasted_iota(jnp.int32, (1, m, 1), 1)
            asc = ((blk * (2 * j)) & k) == 0
            swap = jnp.where(asc, gt, lt)  # [1, m, j]
            key = jnp.stack(
                [jnp.where(swap, khi, klo), jnp.where(swap, klo, khi)], axis=2
            ).reshape(1, n_pad)
            idx = jnp.stack(
                [jnp.where(swap, ihi, ilo), jnp.where(swap, ilo, ihi)], axis=2
            ).reshape(1, n_pad)
            dlo, dhi = dr[:, :, 0, :], dr[:, :, 1, :]
            data = jnp.stack(
                [jnp.where(swap, dhi, dlo), jnp.where(swap, dlo, dhi)], axis=2
            ).reshape(cols, n_pad)
            j //= 2
        k *= 2
    return key, data


def _make_sort_bucket_kernel(capacity: int, n_pad: int, n_seg: int):
    def kernel(data_ref, wvals_ref, bucket_ref):
        data = data_ref[:, :]  # [cols, n_pad]: row 0 = weight, row 1 = key
        w0 = data[0:1, :]
        occ = w0 > 0
        key = jnp.where(occ, data[1:2, :], jnp.inf)
        idx = jax.lax.broadcasted_iota(jnp.float32, (1, n_pad), 1)
        _, srt = _bitonic_by_key(key, idx, data, n_pad)

        sw = srt[0:1, :]
        # inclusive prefix sum by log-step shift-adds
        cum = sw
        t = 1
        while t < n_pad:
            cum = cum + jnp.concatenate(
                [jnp.zeros((1, t), jnp.float32), cum[:, : n_pad - t]], axis=1
            )
            t *= 2
        total = jnp.clip(jnp.sum(sw), 1e-30, None)
        q = jnp.clip((cum - sw / 2.0) / total, 0.0, 1.0)
        scale = capacity / (2.0 * jnp.pi)
        k1 = scale * jnp.arcsin(2.0 * q - 1.0)
        bucket_ref[:, :] = jnp.clip(
            jnp.floor(k1).astype(jnp.int32) + capacity // 4 + 1, 0, n_seg - 1
        )
        # weighted rows for the centroid merge: [w, w*key, w*payload]
        wvals_ref[:, :] = jnp.concatenate([sw, sw * srt[1:, :]], axis=0)

    return kernel


@functools.partial(jax.jit, static_argnames=("capacity", "interpret"))
def qsketch_sort_bucket_tiled(
    rows: ArrayLike, capacity: int, interpret: bool = False
) -> Tuple[Array, Array]:
    """The fused sort->cumsum->bucket stage: ``[n, cols]`` sketch rows in,
    ``(weighted_rows [n_pad, cols], bucket_ids [n_pad])`` out, with the
    zero-weight pad rows bucketed harmlessly (they carry no weight)."""
    rows = jnp.asarray(rows, jnp.float32)
    n, cols = rows.shape
    n_pad = _next_pow2(max(n, 2))
    n_seg = capacity // 2 + 4
    data = jnp.zeros((cols, n_pad), jnp.float32).at[:, :n].set(rows.T)

    kwargs = {}
    if not interpret and _VMEM is not None:
        kwargs = {
            "in_specs": [pl.BlockSpec(memory_space=_VMEM)],
            "out_specs": (
                pl.BlockSpec(memory_space=_VMEM),
                pl.BlockSpec(memory_space=_VMEM),
            ),
        }
    wvals, bucket = pl.pallas_call(
        _make_sort_bucket_kernel(capacity, n_pad, n_seg),
        out_shape=(
            jax.ShapeDtypeStruct((cols, n_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, n_pad), jnp.int32),
        ),
        interpret=interpret,
        **kwargs,
    )(data)
    return wvals.T, bucket[0]


def _qsketch_compact_pallas(rows: Array, capacity: int, interpret: bool = False) -> Array:
    """The full fused compaction: sort/bucket kernel + the shared tiled
    segment-sum kernel for the centroid merge + the jnp epilogue shared
    with the fallback (weighted-mean divide, embed at bucket order,
    stable pack)."""
    n_seg = capacity // 2 + 4
    wvals, bucket = qsketch_sort_bucket_tiled(rows, capacity, interpret=interpret)
    seg = segment_sum_tiled(wvals, bucket, n_seg, interpret=interpret)  # [n_seg, cols]
    from metrics_tpu.sketches.quantile import _finalize_compact

    return _finalize_compact(seg[:, 0], seg[:, 1:], rows)


def _qsketch_compact_jnp(rows: Array, capacity: int) -> Array:
    from metrics_tpu.sketches.quantile import _compact_rows_jnp

    return _compact_rows_jnp(rows, capacity)


def _qsketch_route(rows: Array, capacity: int) -> bool:
    n, cols = rows.shape
    return (
        rows.dtype == jnp.float32
        and _MIN_SORT_ROWS <= n
        and _next_pow2(n) <= _MAX_SORT_ROWS
        and cols <= 16
    )


register_kernel(
    "qsketch_compact",
    pallas_fn=_qsketch_compact_pallas,
    jnp_fn=_qsketch_compact_jnp,
    route=_qsketch_route,
)


def qsketch_compact_dispatch(rows: ArrayLike, capacity: int) -> Array:
    """Registry-routed merging-t-digest compaction pass (the overflow step
    of ``qsketch_insert``/``qsketch_merge``). Semantics of
    ``sketches/quantile.py::_compact_rows_jnp``; see the module docstring
    for the per-backend parity contract. The rows' dtype is preserved —
    non-f32 sketch leaves (bf16 precision sweeps) route to the jnp path,
    and inside ``_absorb``'s ``lax.cond`` both branches must keep the
    leaf's exact dtype."""
    return dispatch("qsketch_compact", jnp.asarray(rows), capacity)
