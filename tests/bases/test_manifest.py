"""Fusibility manifest (tracelint v2 tentpole): freshness, the package-wide
declaration gate, and static-verdict vs runtime-probe agreement.

The committed ``scripts/fusibility_manifest.json`` is a build artifact of
``python scripts/tracelint.py --manifest`` that the fused update path
consults at runtime, so three invariants are tier-1:

* the committed file matches a fresh full-package analysis (staleness);
* for every ``Metric`` subclass, the static verdict agrees with the
  declared ``__jit_unsafe__`` — genuinely-dynamic classes are allowlisted
  HERE, each with its machine-derived reason asserted, so the jit-unsafe
  set can only shrink deliberately;
* ``fusible`` verdicts agree with the runtime ``jax.eval_shape`` probe for
  real input signatures (the verdict the fused path trusts INSTEAD of
  probing).
"""
import pathlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import metrics_tpu  # noqa: F401  (imports every metric module: subclass walk)
from metrics_tpu.analysis import build_manifest, load_manifest, render_manifest
from metrics_tpu.analysis.manifest import DEFAULT_MANIFEST, class_key, lookup_class
from metrics_tpu.core.fused import _pure_update, _state_pytree
from metrics_tpu.core.metric import Metric

REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]
MANIFEST_PATH = REPO_ROOT / DEFAULT_MANIFEST


@pytest.fixture(scope="module")
def committed():
    data = load_manifest(MANIFEST_PATH)
    assert data is not None, f"missing/invalid committed manifest at {MANIFEST_PATH}"
    return data


def _all_metric_subclasses():
    seen = set()

    def walk(cls):
        for sub in cls.__subclasses__():
            if sub not in seen:
                seen.add(sub)
                walk(sub)

    walk(Metric)
    return sorted(
        (c for c in seen if (c.__module__ or "").startswith("metrics_tpu.")),
        key=lambda c: (c.__module__, c.__qualname__),
    )


# ---------------------------------------------------------------------------
# freshness
# ---------------------------------------------------------------------------

class TestFreshness:
    def test_committed_manifest_is_fresh(self, committed):
        """Byte-for-byte: the committed manifest equals a fresh analysis
        (exactly what CI's `tracelint --manifest --check` enforces)."""
        assert render_manifest(build_manifest()) == render_manifest(committed)

    def test_manifest_covers_every_runtime_metric_class(self, committed):
        metrics = committed["metrics"]
        missing = [
            class_key(cls)
            for cls in _all_metric_subclasses()
            if class_key(cls) is not None and class_key(cls) not in metrics
        ]
        assert missing == [], f"metric classes absent from the manifest: {missing}"

    def test_schema_fields(self, committed):
        for key, entry in committed["metrics"].items():
            assert entry["verdict"] in ("fusible", "unsafe", "unknown"), key
            if entry["verdict"] == "unsafe":
                assert entry["reason"] in ("cat-growth", "host-sync", "data-dependent-shape"), key
            else:
                assert entry["reason"] is None, key
            assert isinstance(entry["states"], dict), key
            for state in entry["states"].values():
                assert state["container"] in ("array", "list", "unknown"), key


# ---------------------------------------------------------------------------
# package-wide declaration gate
# ---------------------------------------------------------------------------

#: every class explicitly declared ``__jit_unsafe__ = True`` must appear
#: here with the abstract interpreter's machine-derived classification —
#: (verdict, reason). Shrinking this list (ROADMAP item 2: sketch-backed
#: states) is progress; ADDING to it is a reviewed decision.
GENUINELY_DYNAMIC = {
    # unbounded cat-state accumulation
    # (the curve family — AUROC / ROC / PRC / AveragePrecision — left this
    # list in the sketch-state conversion: their DEFAULT mode is now the
    # fixed-shape streaming sketch, declared False, with `exact=True`
    # instances guarded at runtime by instance-level __jit_unsafe__)
    # (the image/detection family — FID / InceptionScore /
    # MeanAveragePrecision — left this list in the streaming-state
    # conversion: their DEFAULT mode is exact moment statistics / the
    # per-image reservoir table, declared False, with `exact=True`
    # instances guarded at runtime by instance-level __jit_unsafe__)
    "AUC": ("unsafe", "cat-growth"),
    # reservoir-backed, but the reservoir WIDTH is discovered lazily from
    # the first feature batch (`add_state` inside `_update` via
    # `_init_reservoirs` — a trace-time state mutation the interpreter
    # reports as an unresolved call), and compute()'s seeded MMD subset
    # draws are host RNG; stays on the eager path by design
    # (docs/differences.md)
    "KernelInceptionDistance": ("unknown", None),
    # (the retrieval family left this list in the table-state conversion:
    # the DEFAULT mode is the fixed-capacity per-query table, declared
    # False, with `exact=True` instances guarded at runtime by
    # instance-level __jit_unsafe__ — same shape as the curve family)
    "BERTScore": ("unsafe", "cat-growth"),
    "CHRFScore": ("unsafe", "cat-growth"),
    "ExtendedEditDistance": ("unsafe", "cat-growth"),
    "TranslationEditRate": ("unsafe", "cat-growth"),
    # host-side processing (strings / DSP / torch encoders)
    "PerceptualEvaluationSpeechQuality": ("unsafe", "host-sync"),
    "ShortTimeObjectiveIntelligibility": ("unsafe", "host-sync"),
    "LearnedPerceptualImagePatchSimilarity": ("unsafe", "host-sync"),
    "BLEUScore": ("unsafe", "host-sync"),
    "CharErrorRate": ("unsafe", "host-sync"),
    "MatchErrorRate": ("unsafe", "host-sync"),
    "ROUGEScore": ("unsafe", "host-sync"),
    "SacreBLEUScore": ("unsafe", "host-sync"),
    "WordErrorRate": ("unsafe", "host-sync"),
    "WordInfoLost": ("unsafe", "host-sync"),
    "WordInfoPreserved": ("unsafe", "host-sync"),
    # beyond the lattice: child registries / dict inputs (probe decides)
    "SQuAD": ("unknown", None),
    "BootStrapper": ("unknown", None),
    "ClasswiseWrapper": ("unknown", None),
    "MinMaxMetric": ("unknown", None),
    "MultioutputWrapper": ("unknown", None),
}

#: UNDECLARED classes the interpreter still proves unsafe for a non-cat
#: reason: the runtime probe already excludes them from fusion (inherited
#: ``__jit_unsafe__ = False`` is not an explicit claim), but drift here
#: should be a conscious decision
UNDECLARED_UNSAFE = {
    "PermutationInvariantTraining": ("unsafe", "host-sync"),
}


class TestDeclarationGate:
    def test_every_declared_true_is_allowlisted_with_reason(self, committed):
        metrics = committed["metrics"]
        for cls in _all_metric_subclasses():
            key = class_key(cls)
            entry = metrics.get(key) if key else None
            if entry is None or "__jit_unsafe__" not in cls.__dict__:
                continue
            if not cls.__dict__["__jit_unsafe__"]:
                continue
            expected = GENUINELY_DYNAMIC.get(cls.__qualname__)
            assert expected is not None, (
                f"{key} declares __jit_unsafe__=True but is not in the "
                "GENUINELY_DYNAMIC allowlist; add it WITH its machine-derived reason"
            )
            verdict, reason = expected
            assert entry["verdict"] == verdict, (key, entry["verdict"], verdict)
            assert entry["reason"] == reason, (key, entry["reason"], reason)

    def test_declared_true_never_statically_fusible(self, committed):
        """The TL-DECL invariant at package scope: a True declaration with a
        fusible verdict is a stale declaration."""
        stale = [
            key
            for key, entry in committed["metrics"].items()
            if entry["declared_jit_unsafe"] is True and entry["verdict"] == "fusible"
        ]
        assert stale == [], f"stale __jit_unsafe__=True declarations: {stale}"

    def test_declared_false_never_host_or_shape_unsafe(self, committed):
        """The reverse TL-DECL invariant: an explicit False with a host-sync
        or data-dependent-shape verdict would crash the fused build.
        (cat-growth does NOT contradict False: list states are excluded
        from fusion by a separate runtime check, not the declaration.)"""
        contradicted = [
            key
            for key, entry in committed["metrics"].items()
            if entry["declared_jit_unsafe"] is False
            and entry["verdict"] == "unsafe"
            and entry["reason"] in ("host-sync", "data-dependent-shape")
        ]
        assert contradicted == [], f"contradicted __jit_unsafe__=False declarations: {contradicted}"

    def test_undeclared_unsafe_set_is_pinned(self, committed):
        found = {
            key.split("::")[1]: (entry["verdict"], entry["reason"])
            for key, entry in committed["metrics"].items()
            if entry["declared_jit_unsafe"] is None
            and entry["verdict"] == "unsafe"
            and entry["reason"] in ("host-sync", "data-dependent-shape")
        }
        assert found == UNDECLARED_UNSAFE

    def test_static_fusibility_classmethod(self):
        from metrics_tpu.classification import ConfusionMatrix
        from metrics_tpu.regression import MeanSquaredError

        entry = ConfusionMatrix.static_fusibility()
        assert entry is not None and entry["verdict"] == "fusible"
        assert entry["states"]["confmat"]["dist_reduce_fx"] == "sum"
        assert MeanSquaredError.static_fusibility()["verdict"] == "fusible"

        class Local(MeanSquaredError):  # outside the package: no entry
            pass

        assert Local.static_fusibility() is None


# ---------------------------------------------------------------------------
# static verdict vs runtime eval_shape probe
# ---------------------------------------------------------------------------

def _probe_ok(metric, args, kwargs=None):
    # kwargs close over the traced lambda CONCRETELY — the channel for
    # static flags the fused dispatcher keys the compile cache on (FID's
    # `real`), which would fail the probe if abstracted into tracers
    kwargs = kwargs or {}
    try:
        jax.eval_shape(
            lambda s, a: _pure_update(metric, s, a, kwargs), _state_pytree(metric), args
        )
        return True
    except Exception:
        return False


class TestProbeAgreement:
    def _cases(self):
        from metrics_tpu.classification import (
            Accuracy,
            CohenKappa,
            ConfusionMatrix,
            F1Score,
            Precision,
            Recall,
        )
        from metrics_tpu.regression import MeanAbsoluteError, MeanSquaredError

        rng = np.random.RandomState(3)
        n, c = 32, 5
        probs = rng.rand(n, c).astype(np.float32)
        probs /= probs.sum(-1, keepdims=True)
        cls_args = (jnp.asarray(probs), jnp.asarray(rng.randint(0, c, n)))
        reg_args = (
            jnp.asarray(rng.rand(n).astype(np.float32)),
            jnp.asarray(rng.rand(n).astype(np.float32)),
        )
        yield Accuracy(), cls_args
        yield Precision(num_classes=c, average="macro"), cls_args
        yield Recall(num_classes=c, average="macro"), cls_args
        yield F1Score(num_classes=c, average="macro"), cls_args
        yield ConfusionMatrix(num_classes=c), cls_args
        yield CohenKappa(num_classes=c), cls_args
        yield MeanSquaredError(), reg_args
        yield MeanAbsoluteError(), reg_args

    def test_fusible_verdicts_agree_with_probe(self):
        """Every currently-fused collection member: a `fusible` verdict must
        imply a passing probe (the verdict REPLACES the probe at runtime),
        and a failing probe must never carry a `fusible` verdict."""
        checked = 0
        fusible_seen = 0
        for metric, args in self._cases():
            entry = lookup_class(type(metric))
            assert entry is not None, type(metric).__qualname__
            ok = _probe_ok(metric, args)
            if entry["verdict"] == "fusible":
                fusible_seen += 1
                assert ok, f"{type(metric).__qualname__}: fusible verdict but probe fails"
            if not ok:
                assert entry["verdict"] != "fusible", type(metric).__qualname__
            checked += 1
        assert checked == 8
        # the skip-probe win must actually exist in a standard collection
        assert fusible_seen >= 2

    def test_every_fusible_class_instantiable_probe_agrees(self, committed):
        """All fusible-verdict classes with argument-free (or num_classes)
        constructors: instantiate and probe with family-typical inputs."""
        import importlib

        rng = np.random.RandomState(0)

        def identity(x):
            return x

        ctor = {
            "ConfusionMatrix": dict(num_classes=4),
            "CohenKappa": dict(num_classes=4),
            "JaccardIndex": dict(num_classes=4),
            "MatthewsCorrCoef": dict(num_classes=4),
            # streaming image/detection states probe with an identity
            # extractor (the bundled InceptionV3 needs local weights) and
            # slots sized to the padded batch below
            "FrechetInceptionDistance": dict(feature=identity, feature_dim=8),
            "InceptionScore": dict(feature=identity, num_classes=8),
            "MeanAveragePrecision": dict(
                max_images=64, det_slots=4, gt_slots=4, max_detection_thresholds=[1, 4]
            ),
        }
        reg = (
            jnp.asarray(rng.rand(16).astype(np.float32)),
            jnp.asarray(rng.rand(16).astype(np.float32)),
        )
        labels = (jnp.asarray(rng.randint(0, 4, 16)), jnp.asarray(rng.randint(0, 4, 16)))
        hinge = (jnp.asarray(rng.randn(16).astype(np.float32)), jnp.asarray(rng.randint(0, 2, 16)))
        audio = (
            jnp.asarray(rng.randn(2, 400).astype(np.float32)),
            jnp.asarray(rng.randn(2, 400).astype(np.float32)),
        )
        retrieval = (
            jnp.asarray(rng.rand(16).astype(np.float32)),
            jnp.asarray(rng.randint(0, 2, 16)),
            jnp.asarray(rng.randint(0, 4, 16)),
        )
        image = (jnp.asarray(rng.rand(16, 8).astype(np.float32)),)
        detection = (  # the padded per-image dict batch the fused path feeds
            dict(
                boxes=jnp.asarray(rng.rand(6, 4, 4).astype(np.float32)),
                scores=jnp.asarray(rng.rand(6, 4).astype(np.float32)),
                labels=jnp.asarray(rng.randint(0, 3, (6, 4))),
                n=jnp.asarray(rng.randint(0, 5, 6)),
            ),
            dict(
                boxes=jnp.asarray(rng.rand(6, 4, 4).astype(np.float32)),
                labels=jnp.asarray(rng.randint(0, 3, (6, 4))),
                n=jnp.asarray(rng.randint(1, 5, 6)),
            ),
        )
        for key, entry in committed["metrics"].items():
            if entry["verdict"] != "fusible":
                continue
            rel, cls_name = key.split("::")
            module = importlib.import_module("metrics_tpu." + rel[:-3].replace("/", "."))
            cls = getattr(module, cls_name)
            if getattr(cls, "__abstractmethods__", None):
                continue  # family bases (RetrievalMetric) probe via subclasses
            metric = cls(**ctor.get(cls_name, {}))
            kwargs = None
            if rel.startswith("audio/"):
                args = audio
            elif rel.startswith("retrieval/"):
                args = retrieval  # (preds, target, indexes)
            elif rel.startswith("regression/"):
                args = reg
            elif rel.startswith("detection/"):
                args = detection
            elif rel.startswith("image/"):
                args = image
                if cls_name == "FrechetInceptionDistance":
                    kwargs = dict(real=True)  # static dispatch flag
            elif cls_name == "HingeLoss":
                args = hinge
            else:
                args = labels
            assert _probe_ok(metric, args, kwargs), f"{key}: fusible verdict but probe fails"
