"""metrics_tpu.observability — structured telemetry for the metric runtime.

A process-local :class:`MetricRecorder` registry collects typed events
(``update``/``compute``/``forward``/``sync``) from the core runtime, detects
silent XLA recompiles via per-entry-point signature counting, accounts
cross-device sync traffic (gather bytes, world size, pad waste), and tracks
state-memory high-water marks. Exporters render the stream as a JSONL event
log, a Prometheus text page, or a human summary table.

Everything is OFF by default; the disabled hot-path cost is one bool check
(no event allocation). Enable with::

    from metrics_tpu.observability import get_recorder
    get_recorder().enable(recompile_threshold=8)
    ...  # run your eval loop
    get_recorder().export_jsonl("telemetry.jsonl")

or set ``METRICS_TPU_TELEMETRY=/path/to/telemetry.jsonl`` in the
environment, which auto-enables the default recorder and lets entry points
(``bench.py --telemetry``, ``__graft_entry__.py --telemetry``) append their
events to that one artifact across subprocesses. See docs/observability.md.
"""
import os
from typing import Dict

from metrics_tpu.observability.aggregate import aggregate_across_hosts, counter_payload, merge_payloads
from metrics_tpu.observability.collector import (
    FleetCollector,
    PublisherStatus,
    SnapshotQueue,
    SnapshotSink,
)
from metrics_tpu.observability.exporters import (
    PeriodicExporter,
    export_jsonl,
    render_prometheus,
    summary,
    write_prometheus,
)
from metrics_tpu.observability.drift import (
    categorical_drift,
    histogram_drift,
    js_divergence_hist,
    kl_divergence_hist,
    psi_divergence,
    reference_edges,
    sketch_drift,
    state_drift,
    total_variation,
)
from metrics_tpu.observability.health import (
    AlarmState,
    BurnRateRule,
    DriftRule,
    HealthMonitor,
    HealthSnapshot,
    MemoryBudget,
    MemoryLeak,
    Rule,
    ThresholdRule,
    default_rules,
    render_health,
)
from metrics_tpu.observability.memory import (
    MemoryLedger,
    MemoryObservatory,
    backend_memory_stats,
    cache_plane_inventory,
    cache_plane_total,
    host_rss_bytes,
    live_metrics,
    register_cache_plane,
    unregister_cache_plane,
)
from metrics_tpu.observability.freshness import (
    FreshnessStamp,
    merge_stamps,
    stamp_from_payload,
)
from metrics_tpu.observability.profiling import compiled_cost, metric_compile_cost
from metrics_tpu.observability.recorder import (
    _DEFAULT_RECORDER,
    EVENT_TYPES,
    TELEMETRY_ENV_VAR,
    MetricRecorder,
    current_span_id,
)
from metrics_tpu.observability.timeseries import (
    TelemetrySeries,
    TimeSeriesRegistry,
    merge_registry_payloads,
    registry_from_payload,
    series_from_payload,
)
from metrics_tpu.observability.trace import current_span_context, export_perfetto, span
from metrics_tpu.observability.wire import (
    Snapshot,
    WireError,
    decode_snapshot,
    encode_snapshot,
    manifest_fingerprint,
    members_of,
    snapshot_states,
    states_key,
)

__all__ = [
    "MetricRecorder",
    "EVENT_TYPES",
    "TELEMETRY_ENV_VAR",
    "activate_telemetry",
    "get_recorder",
    "recorders",
    "telemetry_enabled",
    "maybe_export_env",
    "export_jsonl",
    "render_prometheus",
    "write_prometheus",
    "summary",
    "PeriodicExporter",
    "compiled_cost",
    "metric_compile_cost",
    "span",
    "current_span_id",
    "current_span_context",
    "export_perfetto",
    "aggregate_across_hosts",
    "counter_payload",
    "merge_payloads",
    "FleetCollector",
    "PublisherStatus",
    "SnapshotQueue",
    "SnapshotSink",
    "Snapshot",
    "WireError",
    "decode_snapshot",
    "encode_snapshot",
    "manifest_fingerprint",
    "members_of",
    "snapshot_states",
    "states_key",
    "TelemetrySeries",
    "TimeSeriesRegistry",
    "merge_registry_payloads",
    "registry_from_payload",
    "series_from_payload",
    "FreshnessStamp",
    "merge_stamps",
    "stamp_from_payload",
    "AlarmState",
    "BurnRateRule",
    "DriftRule",
    "HealthMonitor",
    "HealthSnapshot",
    "MemoryBudget",
    "MemoryLeak",
    "MemoryLedger",
    "MemoryObservatory",
    "backend_memory_stats",
    "cache_plane_inventory",
    "cache_plane_total",
    "host_rss_bytes",
    "live_metrics",
    "register_cache_plane",
    "unregister_cache_plane",
    "Rule",
    "ThresholdRule",
    "categorical_drift",
    "default_rules",
    "histogram_drift",
    "js_divergence_hist",
    "kl_divergence_hist",
    "psi_divergence",
    "reference_edges",
    "render_health",
    "sketch_drift",
    "state_drift",
    "total_variation",
]

_RECORDERS: Dict[str, MetricRecorder] = {"default": _DEFAULT_RECORDER}


def get_recorder(name: str = "default") -> MetricRecorder:
    """The process-local recorder registry. ``"default"`` is the instance
    wired into the runtime hot paths; named instances are for ad-hoc user
    instrumentation (they share nothing with the default one)."""
    rec = _RECORDERS.get(name)
    if rec is None:
        rec = _RECORDERS[name] = MetricRecorder(name)
    return rec


def recorders() -> Dict[str, MetricRecorder]:
    """Snapshot of the registry (name -> recorder)."""
    return dict(_RECORDERS)


def telemetry_enabled() -> bool:
    """Whether the default recorder is currently collecting."""
    return _DEFAULT_RECORDER.enabled


def activate_telemetry(argv, default_path: str = "telemetry.jsonl"):
    """The one ``--telemetry[=path]`` activation sequence shared by the
    entry points (``bench.py``, ``__graft_entry__.py``): parse the flag out
    of ``argv``; when present, enable the default recorder, pin the
    ``METRICS_TPU_TELEMETRY`` env var so spawned subprocesses inherit the
    artifact (they append via ``maybe_export_env``), and truncate the
    artifact file. An empty ``--telemetry=`` value falls back to
    ``default_path``. Returns ``(abs_path_or_None, remaining_argv)``."""
    path = None
    rest = []
    for arg in argv:
        if arg == "--telemetry":
            path = default_path
        elif arg.startswith("--telemetry="):
            path = arg.split("=", 1)[1] or default_path
        else:
            rest.append(arg)
    if path is not None:
        path = os.path.abspath(path)
        os.environ[TELEMETRY_ENV_VAR] = path
        _DEFAULT_RECORDER.enable()
        open(path, "w").close()  # truncate: this run's processes append
    return path, rest


def maybe_export_env() -> str:
    """Append the default recorder's events to the ``METRICS_TPU_TELEMETRY``
    path if that env var is set and anything was recorded; returns the path
    written or ``""``. Safe to call unconditionally at entry-point exit —
    the mechanism bench.py/__graft_entry__.py subprocesses use to land their
    events in the parent's artifact."""
    path = os.environ.get(TELEMETRY_ENV_VAR)
    if path and _DEFAULT_RECORDER.enabled and _DEFAULT_RECORDER.events():
        export_jsonl(path, recorder=_DEFAULT_RECORDER, append=True)
        _DEFAULT_RECORDER.reset()
        return path
    return ""


# env-var activation: lets subprocess entry points (and users who cannot
# edit the launch script) turn collection on without a code change
if os.environ.get(TELEMETRY_ENV_VAR):
    _DEFAULT_RECORDER.enable()
