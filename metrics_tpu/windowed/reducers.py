"""Tagged reducers for windowed metric state (``metrics_tpu.windowed``).

A :class:`~metrics_tpu.windowed.WindowedMetric` leaf is still *sum-shaped*
across ranks — same-bucket ring rows (and decayed sums of lock-stepped
streams) add elementwise — but it must NOT be ``dim_zero_sum`` itself: the
fused kernel's pad-and-mask correction subtracts ``k * delta(last_row)``
from every ``dim_zero_sum`` leaf, and a windowed metric already performs
its own slot-aware correction inside ``_update`` (the probe's delta would
land at the DEFAULT state's ring slot, not the live one — a silent
double-correction). These module-level classes are that distinction made
typed: callables with the stacked-leaves fold contract of
``Metric._sync_dist`` / ``sync_in_mesh``, tagged so every consumer
(``merge_states``, the fused bucket-eligibility check, tracelint, the
manifest) can recognize windowed leaves without importing jax-heavy
modules at decision time:

* ``windowed_kind`` — ``"ring"`` or ``"decay"`` (which window semantics
  the leaf carries);
* ``inner_reduce`` — the wrapped metric's own reducer the window rows
  fold through (``"sum"`` here; ring max/min leaves keep the plain
  ``dim_zero_max``/``dim_zero_min`` reducers — an elementwise extremum is
  already both pad-immune and rank-correct);
* ``merge_like`` (ring-of-sketches only) — rides the fused merge-gather
  round of ``sync_pytree_in_mesh`` and the stacked-pair ``merge_states``
  contract, folding per-slot instead of flattening the ring axis.

All classes are module-level (pickle/deepcopy-safe) like the sketch
reducers in :mod:`metrics_tpu.sketches.quantile`.
"""
from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

__all__ = ["decay_sum_fx", "ring_merge_fx", "ring_sum_fx"]


class _WindowedSumReduce:
    """Cross-rank fold for a windowed sum leaf: elementwise sum of the
    stacked per-rank leaves (ring rows align on bucket index across
    lock-stepped ranks; decayed sums of synchronized streams are additive).
    Distinct from ``dim_zero_sum`` ON PURPOSE — see the module docstring."""

    inner_reduce = "sum"

    def __init__(self, kind: str) -> None:
        self.windowed_kind = kind
        self.__name__ = f"{kind}_sum"

    def __call__(self, stacked: Any) -> Any:
        return jnp.sum(jnp.asarray(stacked), axis=0)

    def __reduce__(self):  # pickle via the public constructors
        return (ring_sum_fx if self.windowed_kind == "ring" else decay_sum_fx, ())


_RING_SUM = _WindowedSumReduce("ring")
_DECAY_SUM = _WindowedSumReduce("decay")


def ring_sum_fx() -> _WindowedSumReduce:
    """The shared ring-of-sums ``dist_reduce_fx`` (``add_state`` maps the
    string ``"ring"`` here)."""
    return _RING_SUM


def decay_sum_fx() -> _WindowedSumReduce:
    """The shared decayed-sum ``dist_reduce_fx`` (``add_state`` maps the
    string ``"decay"`` here)."""
    return _DECAY_SUM


class _RingMergeReduce:
    """Cross-rank fold for a ring-of-sketches leaf ``[R, capacity, cols]``:
    the stacked per-rank rings ``[world, R, capacity, cols]`` fold pairwise
    with the wrapped metric's own merge reducer vmapped over the ring axis,
    so slot ``i`` of every rank merges with slot ``i`` of every other —
    never across buckets. Inside each sketch's lossless window the fold is
    rank-order concatenation per slot, bit-identical to a cat-gather."""

    merge_like = True
    windowed_kind = "ring"
    __name__ = "ring_merge"

    def __init__(self, inner: Any) -> None:
        self._inner = inner
        self.sketch_kind = getattr(inner, "sketch_kind", "quantile")

    def __call__(self, stacked: Any) -> Any:
        stacked = jnp.asarray(stacked)
        if stacked.ndim == 3:  # single-rank passthrough: [R, capacity, cols]
            return stacked
        inner = self._inner
        out = stacked[0]
        for i in range(1, stacked.shape[0]):
            out = jax.vmap(lambda a, b: inner(jnp.stack([a, b])))(out, stacked[i])
        return out

    def __reduce__(self):
        return (ring_merge_fx, (self._inner,))


def ring_merge_fx(inner: Any) -> _RingMergeReduce:
    """Ring-axis wrapper for a tagged ``merge_like`` reducer (the wrapped
    metric's own sketch merge) — see :class:`_RingMergeReduce`."""
    return _RingMergeReduce(inner)
