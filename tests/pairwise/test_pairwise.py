"""Pairwise metrics vs sklearn oracles."""
import numpy as np
import pytest
from sklearn.metrics.pairwise import (
    cosine_similarity as sk_cosine,
    euclidean_distances as sk_euclidean,
    linear_kernel as sk_linear,
    manhattan_distances as sk_manhattan,
)

import jax.numpy as jnp

from metrics_tpu.functional import (
    pairwise_cosine_similarity,
    pairwise_euclidean_distance,
    pairwise_linear_similarity,
    pairwise_manhattan_distance,
)

_rng = np.random.RandomState(42)
X = _rng.rand(12, 5).astype(np.float32)
Y = _rng.rand(8, 5).astype(np.float32)


@pytest.mark.parametrize(
    "tpu_fn, sk_fn",
    [
        (pairwise_cosine_similarity, sk_cosine),
        (pairwise_euclidean_distance, sk_euclidean),
        (pairwise_linear_similarity, sk_linear),
        (pairwise_manhattan_distance, sk_manhattan),
    ],
)
def test_pairwise_two_inputs(tpu_fn, sk_fn):
    got = tpu_fn(jnp.asarray(X), jnp.asarray(Y))
    np.testing.assert_allclose(np.asarray(got), sk_fn(X, Y), atol=1e-5)


@pytest.mark.parametrize(
    "tpu_fn, sk_fn",
    [
        (pairwise_cosine_similarity, sk_cosine),
        (pairwise_euclidean_distance, sk_euclidean),
        (pairwise_linear_similarity, sk_linear),
        (pairwise_manhattan_distance, sk_manhattan),
    ],
)
def test_pairwise_single_input_zero_diagonal(tpu_fn, sk_fn):
    got = np.asarray(tpu_fn(jnp.asarray(X)))
    expected = sk_fn(X, X)
    np.fill_diagonal(expected, 0)
    np.testing.assert_allclose(got, expected, atol=1e-5)


@pytest.mark.parametrize("reduction", ["mean", "sum"])
def test_pairwise_reduction(reduction):
    got = pairwise_euclidean_distance(jnp.asarray(X), jnp.asarray(Y), reduction=reduction)
    full = sk_euclidean(X, Y)
    expected = full.mean(-1) if reduction == "mean" else full.sum(-1)
    np.testing.assert_allclose(np.asarray(got), expected, atol=1e-4)
    with pytest.raises(ValueError):
        pairwise_euclidean_distance(jnp.asarray(X), reduction="bad")


def test_pairwise_invalid_shapes():
    with pytest.raises(ValueError):
        pairwise_cosine_similarity(jnp.ones(5))
    with pytest.raises(ValueError):
        pairwise_cosine_similarity(jnp.ones((4, 5)), jnp.ones((4, 3)))


def test_pairwise_jit():
    import jax

    got = jax.jit(pairwise_euclidean_distance)(jnp.asarray(X), jnp.asarray(Y))
    np.testing.assert_allclose(np.asarray(got), sk_euclidean(X, Y), atol=1e-5)


# ---------------------------------------------------------------------------
# reference-parity sweep: reduction x zero_diagonal x one/two-matrix forms
# (reference tests/pairwise/test_pairwise_distance.py parametrization)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("reduction", [None, "mean", "sum"])
@pytest.mark.parametrize("zero_diagonal", [None, True, False])
@pytest.mark.parametrize("two_matrices", [False, True], ids=["xx", "xy"])
@pytest.mark.parametrize(
    "fn_name",
    [
        "pairwise_cosine_similarity",
        "pairwise_euclidean_distance",
        "pairwise_linear_similarity",
        "pairwise_manhattan_distance",
    ],
)
def test_pairwise_reference_grid(fn_name, reduction, zero_diagonal, two_matrices):
    pytest.importorskip("torch")
    import torch

    from tests.helpers.reference import load_reference_module

    import metrics_tpu.functional as F

    ref_fn = getattr(load_reference_module("torchmetrics.functional"), fn_name)
    ours_fn = getattr(F, fn_name)

    x = _rng.rand(6, 4).astype(np.float32)
    y = _rng.rand(5, 4).astype(np.float32) if two_matrices else None
    kwargs = {"reduction": reduction, "zero_diagonal": zero_diagonal}

    if fn_name == "pairwise_euclidean_distance" and not two_matrices and zero_diagonal is False:
        # the reference's expand-the-square form goes sqrt(tiny negative) on
        # the self-distance diagonal in float32 and yields NaN (poisoning any
        # reduction); ours clamps to 0 — compare the raw matrix off-diagonal
        # only, once (the reduction axis is meaningless against NaN output)
        if reduction is not None:
            pytest.skip("reference NaN diagonal poisons reductions; raw-matrix cell covers this")
        got_m = ours_fn(jnp.asarray(x), zero_diagonal=False)
        want_m = ref_fn(torch.as_tensor(x), zero_diagonal=False).numpy()
        mask = ~np.eye(len(x), dtype=bool)
        np.testing.assert_allclose(np.asarray(got_m)[mask], want_m[mask], rtol=1e-4, atol=1e-5)
        assert not np.isnan(np.asarray(got_m)).any()  # ours never NaNs
        return

    got = ours_fn(jnp.asarray(x), None if y is None else jnp.asarray(y), **kwargs)
    want = ref_fn(torch.as_tensor(x), None if y is None else torch.as_tensor(y), **kwargs)
    np.testing.assert_allclose(np.asarray(got), want.numpy(), rtol=1e-4, atol=1e-5)
