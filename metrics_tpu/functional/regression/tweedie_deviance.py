"""Tweedie deviance score.

Behavior parity with /root/reference/torchmetrics/functional/regression/
tweedie_deviance.py:27-170. The power-dependent domain validations are
value-dependent, so they run only on concrete (non-traced) arrays; under jit
the deviance math itself is branch-free per (static) power.
"""
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.scipy.special import xlogy as _xlogy

from metrics_tpu.utils.checks import _check_same_shape, _is_concrete

Array = jax.Array


def _validate_domain(preds: Array, targets: Array, power: float) -> None:
    if not _is_concrete(preds, targets):
        return
    if power == 1:
        if bool(jnp.any(preds <= 0)) or bool(jnp.any(targets < 0)):
            raise ValueError(
                f"For power={power}, 'preds' has to be strictly positive and 'targets' cannot be negative."
            )
    elif power == 2:
        if bool(jnp.any(preds <= 0)) or bool(jnp.any(targets <= 0)):
            raise ValueError(f"For power={power}, both 'preds' and 'targets' have to be strictly positive.")
    elif power < 0:
        if bool(jnp.any(preds <= 0)):
            raise ValueError(f"For power={power}, 'preds' has to be strictly positive.")
    elif 1 < power < 2:
        if bool(jnp.any(preds <= 0)) or bool(jnp.any(targets < 0)):
            raise ValueError(
                f"For power={power}, 'targets' has to be strictly positive and 'preds' cannot be negative."
            )
    elif power > 2:
        if bool(jnp.any(preds <= 0)) or bool(jnp.any(targets <= 0)):
            raise ValueError(f"For power={power}, both 'preds' and 'targets' have to be strictly positive.")


def _tweedie_deviance_score_update(preds: Array, targets: Array, power: float = 0.0) -> Tuple[Array, Array]:
    _check_same_shape(preds, targets)

    if 0 < power < 1:
        raise ValueError(f"Deviance Score is not defined for power={power}.")
    _validate_domain(preds, targets, power)

    if power == 0:
        deviance_score = jnp.square(targets - preds)
    elif power == 1:  # Poisson
        deviance_score = 2 * (_xlogy(targets, targets / preds) + preds - targets)
    elif power == 2:  # Gamma
        deviance_score = 2 * (jnp.log(preds / targets) + (targets / preds) - 1)
    else:
        term_1 = jnp.power(jnp.maximum(targets, 0.0), 2 - power) / ((1 - power) * (2 - power))
        term_2 = targets * jnp.power(preds, 1 - power) / (1 - power)
        term_3 = jnp.power(preds, 2 - power) / (2 - power)
        deviance_score = 2 * (term_1 - term_2 + term_3)

    sum_deviance_score = jnp.sum(deviance_score)
    num_observations = jnp.asarray(deviance_score.size)
    return sum_deviance_score, num_observations


def _tweedie_deviance_score_compute(sum_deviance_score: Array, num_observations: Array) -> Array:
    return sum_deviance_score / num_observations


def tweedie_deviance_score(preds: Array, targets: Array, power: float = 0.0) -> Array:
    """Computes the Tweedie deviance score.

    Example:
        >>> import jax.numpy as jnp
        >>> targets = jnp.array([1.0, 2.0, 3.0, 4.0])
        >>> preds = jnp.array([4.0, 3.0, 2.0, 1.0])
        >>> tweedie_deviance_score(preds, targets, power=2)
        Array(1.2083333, dtype=float32)
    """
    sum_deviance_score, num_observations = _tweedie_deviance_score_update(preds, targets, power=power)
    return _tweedie_deviance_score_compute(sum_deviance_score, num_observations)
