"""Generate stored oracle fixtures for the text engines and the SDR solver.

Run from the repo root:

    python scripts/make_text_audio_oracle.py

Always (re)writes the ENGINE csvs — our scores over deterministic corpora:

- ``tests/text/fixtures/text_engine_scores.csv``: SacreBLEU across the full
  tokenize x lowercase grid, TER across its argument cube, chrF/chrF++ and
  EED variants, over the committed MT corpus (tests/text/inputs.py). These
  pin the most intricate hand-built engines (Tercom shift DP, chrF n-gram
  F-scores, sacre tokenizers) against numeric drift, unconditionally.
- ``tests/audio/fixtures/sdr_engine_scores.csv``: SDR (dense + CG solve)
  and SI-SDR over a seeded corpus — pinning the Toeplitz solver path.

When the official oracle packages are importable (sacrebleu,
fast_bss_eval — a networked environment), also writes the
``*_official_scores.csv`` counterparts; the fixture tests then bound
|engine − official| from storage in every environment afterwards.
"""
import csv
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
sys.path.insert(0, os.path.join(ROOT, "tests"))

# drift pins must be bit-comparable to the suite's runs: use its exact
# backend config (8-virtual-device forced CPU) — float accumulation differs
# ~1e-5 (BLEU) / ~0.03 dB (SDR) between CPU and the TPU backend otherwise
from tests.helpers.force_cpu import setup_forced_cpu  # noqa: E402

setup_forced_cpu()

import numpy as np  # noqa: E402


def _write(path, scores):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", newline="") as fh:
        w = csv.writer(fh)
        w.writerow(["case", "score"])
        for k in sorted(scores):
            w.writerow([k, f"{scores[k]:.6f}"])
    print(f"wrote {path} ({len(scores)} values)")


def _flat_corpus():
    from tests.text.oracle_corpus import flat_corpus

    return flat_corpus()


def text_engine_scores():
    from tests.text.oracle_corpus import engine_scores

    return engine_scores()


def text_official_scores():
    """sacrebleu-package scores over the same corpus (BLEU, TER, CHRF)."""
    from sacrebleu.metrics import BLEU, CHRF, TER

    preds, targets = _flat_corpus()
    n_refs = len(targets[0])
    targets_t = [[t[i] for t in targets] for i in range(n_refs)]

    out = {}
    for tokenize in ("none", "13a", "zh", "intl", "char"):
        for lowercase in (False, True):
            bleu = BLEU(tokenize=tokenize, lowercase=lowercase)
            out[f"sacrebleu_{tokenize}_lc{int(lowercase)}"] = (
                bleu.corpus_score(preds, targets_t).score / 100
            )
    for normalize in (False, True):
        for no_punct in (False, True):
            for lowercase in (False, True):
                ter = TER(normalized=normalize, no_punct=no_punct, case_sensitive=not lowercase)
                key = f"ter_norm{int(normalize)}_nopunct{int(no_punct)}_lc{int(lowercase)}"
                out[key] = ter.corpus_score(preds, targets_t).score / 100
    out["chrf"] = CHRF(word_order=0).corpus_score(preds, targets_t).score / 100
    out["chrfpp"] = CHRF(word_order=2).corpus_score(preds, targets_t).score / 100
    out["chrf_lc"] = CHRF(word_order=0, lowercase=True).corpus_score(preds, targets_t).score / 100
    return out


def _sdr_corpus():
    from tests.audio.sdr_corpus import sdr_corpus

    return sdr_corpus()


def sdr_engine_scores():
    from tests.audio.sdr_corpus import engine_scores

    return engine_scores()


def sdr_official_scores():
    import fast_bss_eval
    import torch

    preds, target = _sdr_corpus()
    tp, tt = torch.as_tensor(preds), torch.as_tensor(target)
    out = {}
    vals = fast_bss_eval.sdr(tt, tp)
    out["sdr_ch0"], out["sdr_ch1"] = float(vals[0]), float(vals[1])
    vals_cg = fast_bss_eval.sdr(tt, tp, use_cg_iter=10)
    out["sdr_cg_ch0"], out["sdr_cg_ch1"] = float(vals_cg[0]), float(vals_cg[1])
    return out


def main():
    _write(os.path.join(ROOT, "tests", "text", "fixtures", "text_engine_scores.csv"), text_engine_scores())
    _write(os.path.join(ROOT, "tests", "audio", "fixtures", "sdr_engine_scores.csv"), sdr_engine_scores())

    try:
        import sacrebleu  # noqa: F401
    except ImportError:
        print("sacrebleu not installed — text_official_scores.csv not written")
    else:
        _write(
            os.path.join(ROOT, "tests", "text", "fixtures", "text_official_scores.csv"),
            text_official_scores(),
        )

    try:
        import fast_bss_eval  # noqa: F401
    except ImportError:
        print("fast_bss_eval not installed — sdr_official_scores.csv not written")
    else:
        _write(
            os.path.join(ROOT, "tests", "audio", "fixtures", "sdr_official_scores.csv"),
            sdr_official_scores(),
        )


if __name__ == "__main__":
    main()
