"""Health/SLO engine tests (ISSUE 11 tentpole): threshold + burn-rate rule
semantics, the six standard alarm classes tripping AND clearing (the
fault-injection acceptance pin, driven synthetically here and end-to-end by
the serving-loop smoke), snapshot status escalation, the JSONL alarm log,
Prometheus/terminal rendering, and the PeriodicExporter hardening
satellite (export_errors counted, thread keeps ticking)."""
import json
import time

import jax.numpy as jnp
import pytest

from metrics_tpu.aggregation import MeanMetric
from metrics_tpu.observability import (
    BurnRateRule,
    HealthMonitor,
    PeriodicExporter,
    ThresholdRule,
    default_rules,
    export_perfetto,
    get_recorder,
    render_health,
    render_prometheus,
    summary,
)
from metrics_tpu.observability.recorder import (
    SERIES_ASYNC_DROPPED,
    SERIES_ASYNC_ENQUEUED,
    SERIES_ASYNC_QUEUE_DEPTH,
    SERIES_ASYNC_STALENESS,
    SERIES_HOT_SLICE_SHARE,
    SERIES_RECOMPILES,
    SERIES_SKETCH_FILL,
)
from metrics_tpu.observability.timeseries import TimeSeriesRegistry

T0 = 50_000.0

#: the six standard alarm classes default_rules covers (the critical queue
#: escalation rides the same class)
ALARM_CLASSES = (
    "queue_saturation",
    "staleness",
    "drop_rate",
    "recompile_storm",
    "sketch_fill",
    "hot_slice_skew",
)


@pytest.fixture
def recorder():
    rec = get_recorder()
    rec.reset()
    rec.enable()
    try:
        yield rec
    finally:
        rec.disable()
        rec.detach_timeseries()
        rec.reset()


def _registry(**kwargs):
    kwargs.setdefault("bucket_seconds", 1.0)
    kwargs.setdefault("n_buckets", 60)
    kwargs.setdefault("sketch_capacity", 64)
    return TimeSeriesRegistry(**kwargs)


# ---------------------------------------------------------------------------
# rule semantics
# ---------------------------------------------------------------------------

def test_threshold_rule_fires_and_clears_with_the_window():
    reg = _registry()
    rule = ThresholdRule("hot", "lat", stat="max", threshold=100.0, window_s=5.0)
    for i in range(5):
        reg.observe("lat", 500.0, t=T0 + i)
    firing, value, detail = rule.evaluate(reg, now=T0 + 5)
    assert firing and value == 500.0 and "max(lat" in detail
    # the same data, twenty seconds later: outside the window -> clear
    firing, value, _ = rule.evaluate(reg, now=T0 + 25)
    assert not firing and value is None


def test_threshold_rule_stats_paths():
    reg = _registry()
    for i, v in enumerate([1.0, 2.0, 3.0, 4.0]):
        reg.observe("s", v, t=T0 + i * 0.5)
    now = T0 + 2
    checks = {
        "max": 4.0,
        "min": 1.0,
        "mean": 2.5,
        "count": 4.0,
        "total": 10.0,
        "rate": 1.0,  # 10 over a 10s window
    }
    for stat, expect in checks.items():
        window = 10.0
        rule = ThresholdRule("r", "s", stat=stat, threshold=-1.0, window_s=window)
        firing, value, _ = rule.evaluate(reg, now=now)
        assert firing and value == pytest.approx(expect), stat
    p95 = ThresholdRule("r", "s", stat="p95", threshold=3.5, window_s=10.0)
    firing, value, _ = p95.evaluate(reg, now=now)
    assert firing and value == pytest.approx(4.0, abs=0.5)


def test_threshold_rule_min_count_and_absent_series():
    reg = _registry()
    rule = ThresholdRule("r", "missing", stat="max", threshold=0.0)
    firing, value, detail = rule.evaluate(reg, now=T0)
    assert not firing and "absent" in detail
    rule = ThresholdRule("r", "s", stat="p95", threshold=0.0, min_count=5)
    reg.observe("s", 10.0, t=T0)
    firing, _, detail = rule.evaluate(reg, now=T0)
    assert not firing and "observation" in detail


def test_threshold_rule_validation():
    with pytest.raises(ValueError, match="stat"):
        ThresholdRule("r", "s", stat="p101x", threshold=1)
    with pytest.raises(ValueError, match="op"):
        ThresholdRule("r", "s", stat="max", threshold=1, op="!=")
    with pytest.raises(ValueError, match="severity"):
        ThresholdRule("r", "s", stat="max", threshold=1, severity="page")


def test_burn_rate_rule_multiwindow():
    reg = _registry()
    # long window: 100 offered/s with zero drops, then a drop spike in the
    # last 3 seconds (30% drop ratio)
    for i in range(12):
        reg.observe("ok", 100.0, kind="counter", t=T0 + i)
    for i in range(9, 12):
        reg.observe("bad", 43.0, kind="counter", t=T0 + i)
    rule = BurnRateRule(
        "drops", numerator="bad", denominator=("ok", "bad"), budget=0.01,
        short_window_s=3.0, long_window_s=12.0, burn_threshold=2.0,
    )
    now = T0 + 12
    firing, short_burn, detail = rule.evaluate(reg, now=now)
    assert firing  # short burn ~30x budget, long burn ~9.7x
    assert short_burn == pytest.approx((129.0 / 429.0) / 0.01, rel=1e-3)
    # spike alone in the SHORT window but long window healthy -> no page
    calm = BurnRateRule(
        "drops2", numerator="bad", denominator=("ok", "bad"), budget=0.01,
        short_window_s=3.0, long_window_s=12.0, burn_threshold=15.0,
    )
    firing, _, _ = calm.evaluate(reg, now=now)
    assert not firing


def test_burn_rate_rule_zero_traffic_never_fires():
    reg = _registry()
    rule = BurnRateRule("drops", numerator="bad", denominator="ok", budget=0.1,
                        short_window_s=2.0, long_window_s=10.0)
    firing, value, detail = rule.evaluate(reg, now=T0)
    assert not firing and value is None and "no traffic" in detail


def test_burn_rate_validation():
    with pytest.raises(ValueError, match="budget"):
        BurnRateRule("r", "a", "b", budget=1.5)
    with pytest.raises(ValueError, match="short_window"):
        BurnRateRule("r", "a", "b", budget=0.1, short_window_s=10, long_window_s=5)


# ---------------------------------------------------------------------------
# monitor: the six alarm classes trip AND clear (synthetic acceptance pin)
# ---------------------------------------------------------------------------

def _inject_fault_signals(reg, t):
    """One synthetic burst of every standard fault signal at time ``t``."""
    for i in range(6):
        reg.observe(SERIES_ASYNC_QUEUE_DEPTH, 9.0, t=t + i * 0.1)
        reg.observe(SERIES_ASYNC_STALENESS, 8.0, t=t + i * 0.1)
        reg.observe(SERIES_ASYNC_ENQUEUED, 1.0, kind="counter", t=t + i * 0.1)
        reg.observe(SERIES_ASYNC_DROPPED, 5.0, kind="counter", t=t + i * 0.1)
        reg.observe(SERIES_RECOMPILES, 3.0, kind="counter", t=t + i * 0.1)
        reg.observe(SERIES_SKETCH_FILL, 0.97, t=t + i * 0.1)
        reg.observe(SERIES_HOT_SLICE_SHARE, 0.9, t=t + i * 0.1)


def _inject_healthy_signals(reg, t):
    for i in range(6):
        reg.observe(SERIES_ASYNC_QUEUE_DEPTH, 1.0, t=t + i * 0.1)
        reg.observe(SERIES_ASYNC_STALENESS, 0.0, t=t + i * 0.1)
        reg.observe(SERIES_ASYNC_ENQUEUED, 10.0, kind="counter", t=t + i * 0.1)
        reg.observe(SERIES_SKETCH_FILL, 0.1, t=t + i * 0.1)
        reg.observe(SERIES_HOT_SLICE_SHARE, 0.05, t=t + i * 0.1)


def test_all_six_alarm_classes_trip_and_clear(tmp_path):
    """The acceptance pin, driven synthetically (deterministic, no sleeps):
    every standard alarm class fires under fault signals and clears once
    the windows roll past them."""
    reg = _registry()
    log = tmp_path / "alarms.jsonl"
    monitor = HealthMonitor(
        default_rules(window_s=5.0), registry=reg, alarm_log_path=str(log)
    )
    snap0 = monitor.evaluate(now=T0)
    assert snap0.status == "ok" and not snap0.firing

    _inject_fault_signals(reg, T0 + 1)
    snap1 = monitor.evaluate(now=T0 + 2)
    firing = {a.name for a in snap1.firing}
    for cls in ALARM_CLASSES:
        assert cls in firing, cls
    assert "queue_saturation_critical" in firing
    assert snap1.status == "critical"

    # recovery: healthy signals, evaluated after the fault fell out of
    # every window (max window 5s)
    _inject_healthy_signals(reg, T0 + 10)
    snap2 = monitor.evaluate(now=T0 + 11)
    assert snap2.status == "ok" and not snap2.firing

    cleared = monitor.fired_and_cleared()
    for cls in ALARM_CLASSES:
        assert cls in cleared, cls

    # the JSONL alarm log carries one fired and one cleared row per alarm
    rows = [json.loads(line) for line in log.read_text().splitlines()]
    by_event = {}
    for r in rows:
        by_event.setdefault(r["event"], set()).add(r["alarm"])
        assert r["severity"] in ("warn", "critical") and "t" in r
    for cls in ALARM_CLASSES:
        assert cls in by_event["fired"] and cls in by_event["cleared"]
    cleared_rows = [r for r in rows if r["event"] == "cleared"]
    assert all(r["duration_s"] >= 0 for r in cleared_rows)


def test_status_escalation_warn_vs_critical():
    reg = _registry()
    reg.observe("s", 10.0, t=T0)
    warn_rule = ThresholdRule("w", "s", stat="max", threshold=5.0, window_s=5.0, severity="warn")
    crit_rule = ThresholdRule("c", "s", stat="max", threshold=50.0, window_s=5.0, severity="critical")
    monitor = HealthMonitor([warn_rule, crit_rule], registry=reg)
    snap = monitor.evaluate(now=T0 + 1)
    assert snap.status == "warn"  # only the warn rule fires
    reg.observe("s", 100.0, t=T0 + 1)
    snap = monitor.evaluate(now=T0 + 2)
    assert snap.status == "critical"


def test_monitor_rejects_duplicate_rule_names():
    r1 = ThresholdRule("same", "s", stat="max", threshold=1.0)
    r2 = ThresholdRule("same", "s", stat="min", threshold=1.0)
    with pytest.raises(ValueError, match="duplicate"):
        HealthMonitor([r1, r2])


def test_broken_rule_does_not_kill_the_sweep():
    class Broken(ThresholdRule):
        def evaluate(self, registry, now=None):
            raise RuntimeError("boom")

    reg = _registry()
    reg.observe("s", 10.0, t=T0)
    ok_rule = ThresholdRule("ok", "s", stat="max", threshold=5.0, window_s=5.0)
    monitor = HealthMonitor([Broken("bad", "s", stat="max", threshold=1.0), ok_rule], registry=reg)
    snap = monitor.evaluate(now=T0 + 1)
    states = {a.name: a for a in snap.alarms}
    assert not states["bad"].firing and "failed" in states["bad"].detail
    assert states["ok"].firing


def test_render_health_and_snapshot_json():
    reg = _registry()
    reg.observe("s", 10.0, t=T0)
    monitor = HealthMonitor(
        [ThresholdRule("loud", "s", stat="max", threshold=5.0, window_s=5.0)], registry=reg
    )
    snap = monitor.evaluate(now=T0 + 1)
    text = render_health(snap)
    assert "health: WARN" in text and "FIRING" in text and "loud" in text
    doc = snap.to_json()
    assert doc["status"] == "warn" and doc["alarms"][0]["name"] == "loud"
    assert json.loads(json.dumps(doc)) == doc  # JSON-safe


def test_prometheus_lines_and_exporter_integration(tmp_path, recorder):
    registry = recorder.attach_timeseries(bucket_seconds=1.0, n_buckets=30, sketch_capacity=64)
    registry.observe("s", 10.0)
    monitor = HealthMonitor(
        [ThresholdRule("loud", "s", stat="max", threshold=5.0, window_s=60.0)],
        registry=registry,
    )
    prom_path = tmp_path / "metrics.prom"
    exporter = PeriodicExporter(interval_s=30.0, prometheus_path=str(prom_path), health=monitor)
    exporter.export_once()
    page = prom_path.read_text()
    assert "metrics_tpu_health_status 1" in page
    assert 'metrics_tpu_alarm_firing{alarm="loud",severity="warn"} 1' in page
    assert 'metrics_tpu_alarm_value{alarm="loud"} 10' in page
    # the windowed families ride the same page, labeled with the seconds
    # ACTUALLY covered (60s requested, clamped to the 30-bucket ring span)
    assert 'metrics_tpu_window_quantile{series="s",q="0.99",window_s="30"}' in page
    assert 'metrics_tpu_window_count{series="s",window_s="30"} 1' in page


# ---------------------------------------------------------------------------
# PeriodicExporter hardening satellite
# ---------------------------------------------------------------------------

def test_exporter_tick_failure_counted_and_thread_survives(tmp_path, recorder):
    m = MeanMetric()
    m.update(jnp.ones((2,)))
    bad_path = tmp_path / "no_such_dir" / "metrics.prom"  # _atomic_write fails
    exporter = PeriodicExporter(interval_s=0.05, prometheus_path=str(bad_path))
    with pytest.warns(UserWarning, match="PeriodicExporter tick failed"):
        exporter.start()
        deadline = time.time() + 5.0
        while exporter.export_errors < 2 and time.time() < deadline:
            time.sleep(0.05)
    try:
        # several ticks failed, every one was counted, the thread is alive
        assert exporter.export_errors >= 2
        assert recorder.export_errors() >= 2
        assert exporter._thread is not None and exporter._thread.is_alive()
    finally:
        exporter.stop()
    # the count surfaces in the summary, the Prometheus page, and health
    assert "exporter tick(s) failed" in summary(recorder)
    page = render_prometheus(recorder)
    sample = next(
        line for line in page.splitlines()
        if line.startswith("metrics_tpu_export_errors_total")
    )
    assert int(sample.split()[-1]) >= 2
    monitor = HealthMonitor(default_rules(), recorder=recorder)
    assert monitor.evaluate().export_errors >= 2


def test_exporter_recovers_after_failures(tmp_path, recorder):
    m = MeanMetric()
    m.update(jnp.ones((2,)))
    missing = tmp_path / "later"
    exporter = PeriodicExporter(interval_s=30.0, prometheus_path=str(missing / "m.prom"))
    with pytest.raises(FileNotFoundError):
        exporter.export_once()  # manual tick: raises to the caller
    missing.mkdir()
    exporter.export_once()  # same exporter, next tick succeeds
    assert (missing / "m.prom").exists()


# ---------------------------------------------------------------------------
# Perfetto thread-track satellite
# ---------------------------------------------------------------------------

def test_perfetto_async_worker_labeled_track(tmp_path, recorder):
    from metrics_tpu import MeanSquaredError, MetricCollection

    col = MetricCollection({"mse": MeanSquaredError()})
    handle = col.compile_update_async(queue_depth=2)
    x = jnp.ones((8,))
    try:
        for _ in range(3):
            col.update_async(x, x)
        handle.flush()
    finally:
        handle.close()
    path = tmp_path / "trace.json"
    export_perfetto(str(path), recorder=recorder)
    doc = json.loads(path.read_text())
    meta = {
        (e["name"], e["args"]["name"]): e
        for e in doc["traceEvents"]
        if e.get("ph") == "M"
    }
    names = [k[1] for k in meta]
    assert any("metrics-tpu-async-update" in n for n in names)
    assert any(k[0] == "process_name" for k in meta)
    worker_meta = next(
        e for (kind, n), e in meta.items()
        if kind == "thread_name" and "metrics-tpu-async-update" in n
    )
    dequeues = [e for e in doc["traceEvents"] if e.get("cat") == "dequeue"]
    assert dequeues and all(e["tid"] == worker_meta["tid"] for e in dequeues)
    enqueues = [e for e in doc["traceEvents"] if e.get("cat") == "enqueue"]
    assert enqueues and all(e["tid"] != worker_meta["tid"] for e in enqueues)
