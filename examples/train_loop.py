"""Pure-JAX training loop with metrics on a device mesh.

The analog of the reference's Lightning integration
(/root/reference/integrations/test_lightning.py): metrics ride INSIDE the
jitted, shard_map-parallel train step via the pure-state API, sync over the
mesh with XLA collectives, and reset between epochs — no framework glue.

Run on any host: uses however many devices JAX sees (forced to 8 virtual
CPU devices below if only one is present).
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))  # repo root

import os

if "XLA_FLAGS" not in os.environ:
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from metrics_tpu import Accuracy, MeanSquaredError
from metrics_tpu.parallel.distributed import sync_in_mesh
from metrics_tpu.utils.compat import shard_map


def main() -> None:
    n_dev = len(jax.devices())
    mesh = Mesh(np.array(jax.devices()), ("data",))
    print(f"devices: {n_dev}")

    # toy linear classifier on random data
    rng = np.random.default_rng(0)
    num_classes, dim, batch_per_dev = 5, 16, 32
    w_true = rng.standard_normal((dim, num_classes))
    params = jnp.zeros((dim, num_classes))

    acc = Accuracy(num_classes=num_classes)
    mse = MeanSquaredError()

    def train_step(params, metric_state, x, y):
        def loss_fn(p):
            logits = x @ p
            one_hot = jax.nn.one_hot(y, num_classes)
            return jnp.mean((jax.nn.softmax(logits) - one_hot) ** 2), logits

        (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        grads = jax.lax.pmean(grads, "data")  # DP gradient sync over ICI
        params = params - 0.5 * grads

        # metric accumulation is part of the SAME jitted step
        acc_state, mse_state = metric_state
        acc_state = acc.update_state(acc_state, jax.nn.softmax(logits), y)
        mse_state = mse.update_state(
            mse_state, jax.nn.softmax(logits), jax.nn.one_hot(y, num_classes)
        )
        return params, (acc_state, mse_state), loss

    @jax.jit
    def epoch(params, x_all, y_all):
        def body(params, metric_state, x, y):
            def scan_fn(carry, batch):
                params, metric_state = carry
                params, metric_state, loss = train_step(params, metric_state, *batch)
                return (params, metric_state), loss

            (params, metric_state), losses = jax.lax.scan(
                scan_fn, (params, metric_state), (x, y)
            )
            # epoch end: one in-mesh sync per metric, every device gets the
            # global value (psum/all_gather over the "data" axis)
            acc_state, mse_state = metric_state
            acc_synced = sync_in_mesh(acc_state, acc.state_reductions(), "data")
            mse_synced = sync_in_mesh(mse_state, mse.state_reductions(), "data")
            return (
                params,
                acc.compute_state(acc_synced)[None],
                mse.compute_state(mse_synced)[None],
                jnp.mean(losses)[None],
            )

        return shard_map(
            lambda p, x, y: body(
                p,
                # init states are replicated constants; mark them as varying
                # over the mesh axis so the scan carry types line up
                jax.tree_util.tree_map(
                    lambda v: jax.lax.pvary(v, ("data",)),
                    (acc.init_state(), mse.init_state()),
                ),
                x[0],
                y[0],
            ),
            mesh=mesh,
            in_specs=(P(), P("data"), P("data")),
            out_specs=(P(), P("data"), P("data"), P("data")),
        )(params, x_all, y_all)

    steps_per_epoch = 10
    for epoch_idx in range(3):
        x = rng.standard_normal((n_dev, steps_per_epoch, batch_per_dev, dim)).astype(np.float32)
        logits_true = x @ w_true
        y = np.argmax(logits_true + 0.5 * rng.standard_normal(logits_true.shape), -1).astype(np.int32)
        x = x.reshape(n_dev, steps_per_epoch, batch_per_dev, dim)
        y = y.reshape(n_dev, steps_per_epoch, batch_per_dev)

        params, acc_val, mse_val, loss = epoch(params, jnp.asarray(x), jnp.asarray(y))
        print(
            f"epoch {epoch_idx}: loss={float(jnp.mean(loss)):.4f}"
            f" accuracy={float(acc_val[0]):.4f} mse={float(mse_val[0]):.4f}"
        )


if __name__ == "__main__":
    main()
