from metrics_tpu.retrieval.base import RetrievalMetric  # noqa: F401
from metrics_tpu.retrieval.table import (  # noqa: F401
    retrieval_table_fill,
    retrieval_table_init,
    retrieval_table_insert,
    retrieval_table_layout,
    retrieval_table_merge,
    retrieval_table_merge_fx,
)
from metrics_tpu.retrieval.average_precision import RetrievalMAP  # noqa: F401
from metrics_tpu.retrieval.fall_out import RetrievalFallOut  # noqa: F401
from metrics_tpu.retrieval.hit_rate import RetrievalHitRate  # noqa: F401
from metrics_tpu.retrieval.ndcg import RetrievalNormalizedDCG  # noqa: F401
from metrics_tpu.retrieval.precision import RetrievalPrecision  # noqa: F401
from metrics_tpu.retrieval.r_precision import RetrievalRPrecision  # noqa: F401
from metrics_tpu.retrieval.recall import RetrievalRecall  # noqa: F401
from metrics_tpu.retrieval.reciprocal_rank import RetrievalMRR  # noqa: F401
