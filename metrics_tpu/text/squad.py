"""Modular SQuAD metric.

Behavior parity with /root/reference/torchmetrics/text/squad.py:29-151.
"""
from typing import Any, Dict

import jax
import jax.numpy as jnp

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.text.squad import (
    PREDS_TYPE,
    TARGETS_TYPE,
    _squad_compute,
    _squad_input_check,
    _squad_update,
)

Array = jax.Array


class SQuAD(Metric):
    """SQuAD v1 exact-match + token-F1 over accumulated question/answer pairs.

    Example:
        >>> preds = [{"prediction_text": "1976", "id": "56e10a3be3433e1400422b22"}]
        >>> target = [{"answers": {"answer_start": [97], "text": ["1976"]}, "id": "56e10a3be3433e1400422b22"}]
        >>> metric = SQuAD()
        >>> {k: float(v) for k, v in metric(preds, target).items()}
        {'exact_match': 100.0, 'f1': 100.0}
    """

    is_differentiable = False
    higher_is_better = True
    __jit_unsafe__ = True  # update consumes Python dicts of strings

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("f1_score", default=jnp.asarray(0.0, jnp.float32), dist_reduce_fx="sum")
        self.add_state("exact_match", default=jnp.asarray(0.0, jnp.float32), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0, jnp.int32), dist_reduce_fx="sum")

    def _update(self, preds: PREDS_TYPE, target: TARGETS_TYPE) -> None:
        preds_dict, target_dict = _squad_input_check(preds, target)
        f1_score, exact_match, total = _squad_update(preds_dict, target_dict)
        self.f1_score = self.f1_score + f1_score
        self.exact_match = self.exact_match + exact_match
        self.total = self.total + total

    def _compute(self) -> Dict[str, Array]:
        return _squad_compute(self.f1_score, self.exact_match, self.total)
