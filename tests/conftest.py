"""Test session configuration: force CPU with 8 virtual devices so mesh /
collective tests run without TPU hardware (SURVEY.md §4 implication).
Setup logic is shared with the repo-root conftest via
tests/helpers/force_cpu.py."""
import os

from tests.helpers.force_cpu import setup_forced_cpu

setup_forced_cpu()

import jax  # noqa: E402

if not os.environ.get("METRICS_TPU_TEST_ON_TPU"):
    assert jax.device_count() >= 8, f"expected >=8 virtual devices, got {jax.device_count()}"

# telemetry hermeticity: a METRICS_TPU_TELEMETRY in the inherited environment
# would auto-enable collection and write artifacts from library code under
# test — strip it so tier-1 always exercises the disabled-by-default path
os.environ.pop("METRICS_TPU_TELEMETRY", None)


def pytest_sessionfinish(session, exitstatus):
    """Tier-1 invariant: telemetry stays DISABLED by default.

    No test may leave the default recorder enabled (the observability tests
    enable it inside try/finally fixtures), and no default JSONL artifact
    may have appeared — both would mean library code was silently paying
    telemetry costs, or writing files, during an ordinary test run.
    """
    from metrics_tpu.observability import get_recorder

    assert not get_recorder().enabled, (
        "the default MetricRecorder was left ENABLED after the test session —"
        " telemetry must stay off by default (some test is missing its"
        " disable/reset teardown)"
    )
    for stray in ("telemetry.jsonl", "BENCH_telemetry.jsonl"):
        assert not os.path.exists(stray), (
            f"a telemetry artifact ({stray}) appeared during the test run —"
            " telemetry must not write files unless explicitly enabled"
        )
