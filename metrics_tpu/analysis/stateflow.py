"""State-lifecycle dataflow: the TL-FLOW analysis.

Every ``add_state`` leaf carries a ``dist_reduce_fx`` contract that sync,
``merge_states``, and the fused kernel all trust. This pass checks that the
class's own lifecycle honors it:

* **Reducer-consistent writes** — a ``"sum"``-reduced leaf must accumulate
  additively in update methods (``self.x = self.x + delta`` / ``+=`` /
  ``.at[...].add``): a plain overwrite discards prior batches on this rank
  AND double-counts nothing on others after a cross-rank sum, and an
  extremum update (``jnp.maximum``) makes per-rank values non-additive. The
  dual holds for ``"max"``/``"min"`` leaves, where an additive write breaks
  the idempotent-extremum contract.
* **Reset restoration** — a class that overrides ``reset`` must either call
  ``super().reset()`` (which restores every registered default) or assign
  each leaf itself; a leaf missed by an overriding reset survives across
  epochs and silently inflates the next accumulation.
* **Live leaves** — a leaf registered by a class that defines its own
  update but never touches the leaf anywhere in the file is dead weight:
  it still costs sync bytes every ``compute`` and suggests a typo'd
  attribute name (write hits ``__setattr__`` but not the registry).

Only leaves with a CONSTANT string reducer are checked (config-dependent
reducers — the StatScores ``"cat"``-or-``"sum"`` idiom — and custom
callables have no statically-checkable write contract). Findings surface
through the ``TL-FLOW`` rule in :mod:`.rules`.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

from .engine import FileContext

#: methods whose writes are ACCUMULATION (the reducer contract applies);
#: reset/sync/bind/merge/load writes are restoration and exempt
_UPDATE_METHODS = {"_update", "update", "update_state"}

#: additive accumulation spellings for sum-reduced leaves
_ADDITIVE_AUG_OPS = (ast.Add, ast.Sub)
_EXTREMUM_FNS = {"maximum", "minimum", "max", "min"}
_ADD_METHOD_NAMES = {"add"}  # self.x.at[idx].add(v)

#: slice-axis scatter reducers (metrics_tpu/sliced/): a `segment_sum` of
#: per-row deltas combined with the prior value IS additive accumulation,
#: and `segment_max`/`segment_min` results folded through the matching
#: extremum are extremum-consistent — but a scatter-EXTREMUM write
#: (`self.x.at[ids].max(v)`, or a segment_max folded into a sum leaf)
#: silently breaks the additivity the cross-rank sum relies on
_SEGMENT_EXTREMUM_FNS = {"segment_max": "max", "segment_min": "min"}
_SCATTER_EXTREMUM_METHODS = {"max": "max", "min": "min"}


@dataclass(frozen=True)
class FlowFinding:
    node: ast.AST
    message: str


def _state_reducers(class_node: ast.ClassDef) -> Dict[str, str]:
    """name -> constant string reducer, for this class's own add_state calls."""
    from .interp import _reducer_of  # shared reducer extraction

    out: Dict[str, str] = {}
    for node in ast.walk(class_node):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not (
            isinstance(func, ast.Attribute)
            and func.attr == "add_state"
            and isinstance(func.value, ast.Name)
            and func.value.id == "self"
        ):
            continue
        if node.args and isinstance(node.args[0], ast.Constant) and isinstance(node.args[0].value, str):
            reducer = _reducer_of(node)
            if isinstance(reducer, str) and reducer in {
                "sum", "mean", "max", "min", "cat", "merge", "ring", "decay",
                "moments",
            }:
                out[node.args[0].value] = reducer
    return out


def _mentions_self_attr(node: ast.AST, attr: str) -> bool:
    for sub in ast.walk(node):
        if (
            isinstance(sub, ast.Attribute)
            and sub.attr == attr
            and isinstance(sub.value, ast.Name)
            and sub.value.id == "self"
        ):
            return True
    return False


def _self_attr_writes(method: ast.FunctionDef) -> Iterator[Tuple[ast.stmt, str, str]]:
    """(stmt, state name, kind) for writes to self.<attr>; kind is
    "assign" or the AugAssign op class name."""
    for node in ast.walk(method):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    yield node, tgt.attr, "assign"
        elif isinstance(node, ast.AugAssign):
            tgt = node.target
            if (
                isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"
            ):
                yield node, tgt.attr, type(node.op).__name__
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            tgt = node.target
            if (
                isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"
            ):
                yield node, tgt.attr, "assign"


def _is_extremum_rhs(rhs: ast.AST, attr: str) -> bool:
    """``jnp.maximum(self.attr, ...)``-shaped RHS (top-level call)."""
    if not isinstance(rhs, ast.Call):
        return False
    name = rhs.func.attr if isinstance(rhs.func, ast.Attribute) else (
        rhs.func.id if isinstance(rhs.func, ast.Name) else None
    )
    if name not in _EXTREMUM_FNS:
        return False
    return any(_mentions_self_attr(a, attr) for a in rhs.args)


def _scatter_extremum_kind(rhs: ast.AST, attr: str) -> Optional[str]:
    """``"max"``/``"min"`` when the RHS is a slice-axis scatter-extremum
    over ``self.<attr>`` — ``self.x.at[ids].max(v)`` / ``.min(v)``, or a
    ``segment_max``/``segment_min`` call anywhere in an expression that
    also reads the prior value (``jnp.maximum(self.x, segment_max(...))``
    is caught by the top-level extremum check; this covers the ``.at``
    scatter spelling that check cannot see)."""
    if (
        isinstance(rhs, ast.Call)
        and isinstance(rhs.func, ast.Attribute)
        and rhs.func.attr in _SCATTER_EXTREMUM_METHODS
        and _mentions_self_attr(rhs.func.value, attr)
    ):
        return _SCATTER_EXTREMUM_METHODS[rhs.func.attr]
    return None


def _segment_extremum_name(rhs: ast.AST) -> Optional[str]:
    """The first ``segment_max``/``segment_min`` call name inside ``rhs``."""
    for sub in ast.walk(rhs):
        if isinstance(sub, ast.Call):
            name = _last_call_name(sub)
            if name in _SEGMENT_EXTREMUM_FNS:
                return name
    return None


def _additive_segment_extremum(rhs: ast.AST) -> Optional[str]:
    """The ``segment_max``/``segment_min`` call name when it is a TOP-LEVEL
    additive operand (``self.x + segment_max(...)``): summing a scattered
    extremum reads the prior value, so the overwrite check passes it, yet
    the accumulated quantity is an extremum — not additive across ranks.
    Only the direct-operand shape is flagged; an extremum buried deeper
    (e.g. an indicator derived from one) may legitimately be additive."""
    if not (isinstance(rhs, ast.BinOp) and isinstance(rhs.op, _ADDITIVE_AUG_OPS)):
        return None
    for side in (rhs.left, rhs.right):
        if isinstance(side, ast.Call):
            name = _last_call_name(side)
            if name in _SEGMENT_EXTREMUM_FNS:
                return name
    return None


def _is_additive_rhs(rhs: ast.AST, attr: str) -> bool:
    """Additive accumulation forms: ``self.x + e`` / ``e + self.x`` /
    ``self.x - e`` (top-level BinOp) or ``self.x.at[...].add(...)``."""
    if isinstance(rhs, ast.BinOp) and isinstance(rhs.op, _ADDITIVE_AUG_OPS):
        return _mentions_self_attr(rhs.left, attr) or _mentions_self_attr(rhs.right, attr)
    if (
        isinstance(rhs, ast.Call)
        and isinstance(rhs.func, ast.Attribute)
        and rhs.func.attr in _ADD_METHOD_NAMES
        and _mentions_self_attr(rhs.func.value, attr)
    ):
        return True
    return False


def _is_bare_self_attr(node: ast.AST, attr: str) -> bool:
    """``self.<attr>`` exactly — no scaling, no indexing, no wrapping."""
    return (
        isinstance(node, ast.Attribute)
        and node.attr == attr
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    )


def _direct_unscaled_additive(rhs: ast.AST, attr: str) -> bool:
    """``self.x + e`` / ``e + self.x`` / ``self.x - e`` with the BARE
    (unscaled) prior value as a top-level operand — the write shape that
    never decays a decay leaf and ignores a ring leaf's rotation. A scaled
    operand (``alpha * self.x + e``) deliberately does NOT match."""
    if not (isinstance(rhs, ast.BinOp) and isinstance(rhs.op, _ADDITIVE_AUG_OPS)):
        return False
    return _is_bare_self_attr(rhs.left, attr) or _is_bare_self_attr(rhs.right, attr)


def _has_scaled_prior(rhs: ast.AST, attr: str) -> bool:
    """An ``alpha * self.x``-shaped multiplicative subexpression anywhere
    in ``rhs`` — the decayed-accumulation signature."""
    for sub in ast.walk(rhs):
        if isinstance(sub, ast.BinOp) and isinstance(sub.op, (ast.Mult, ast.Pow)):
            if _mentions_self_attr(sub.left, attr) or _mentions_self_attr(sub.right, attr):
                return True
    return False


def _is_ring_rotation(rhs: ast.AST, attr: str) -> bool:
    """A ``.at[...]`` namespace write on the leaf itself (``self.x.at[
    slot].set(row)`` / ``.add`` / ``.max`` / ``.min``) — the ring-rotation
    idiom: one slot changes, the other buckets' rows are untouched."""
    return (
        isinstance(rhs, ast.Call)
        and isinstance(rhs.func, ast.Attribute)
        and rhs.func.attr in ("set", "add", "max", "min", "multiply", "mul")
        and _mentions_self_attr(rhs.func.value, attr)
    )


def _locals_reading_attr(method: ast.FunctionDef, attrs: Iterable[str]) -> Dict[str, Set[str]]:
    """attr -> local names whose assigned value reads ``self.<attr>``
    (transitively through other such locals) — the two-step accumulation
    idiom ``new_total = self.total + x; self.total = new_total`` reads the
    prior value even though the final write's RHS does not mention it."""
    readers: Dict[str, Set[str]] = {attr: set() for attr in attrs}
    changed = True
    while changed:
        changed = False
        for node in ast.walk(method):
            if not (isinstance(node, ast.Assign) and node.value is not None):
                continue
            names = [t.id for t in node.targets if isinstance(t, ast.Name)]
            if not names:
                continue
            for attr, locs in readers.items():
                if _mentions_self_attr(node.value, attr) or any(
                    isinstance(sub, ast.Name) and sub.id in locs
                    for sub in ast.walk(node.value)
                ):
                    for name in names:
                        if name not in locs:
                            locs.add(name)
                            changed = True
    return readers


def _check_update_writes(
    method: ast.FunctionDef, reducers: Dict[str, str]
) -> Iterator[FlowFinding]:
    readers = _locals_reading_attr(method, reducers)
    for stmt, attr, kind in _self_attr_writes(method):
        reducer = reducers.get(attr)
        if reducer is None:
            continue
        rhs = getattr(stmt, "value", None)

        def rhs_reads_prior(expr: ast.AST) -> bool:
            if _mentions_self_attr(expr, attr):
                return True
            return any(
                isinstance(sub, ast.Name) and sub.id in readers[attr]
                for sub in ast.walk(expr)
            )

        # streaming-moment leaves ("moments", `moments_merge_fx()`) are
        # element-wise summable sufficient statistics: the cross-rank merge
        # IS addition, so every "sum" write contract applies verbatim
        if reducer in ("sum", "moments"):
            if kind == "assign":
                scatter = _scatter_extremum_kind(rhs, attr) if rhs is not None else None
                seg_add = _additive_segment_extremum(rhs) if rhs is not None else None
                if seg_add is not None:
                    yield FlowFinding(
                        stmt,
                        f"`\"{reducer}\"`-reduced state `{attr}` accumulates a `{seg_add}` "
                        f"result in `{method.name}`; a scattered extremum summed into "
                        "the state is not additive across ranks — segment-SUM the "
                        "per-slice deltas, or declare the state "
                        '`dist_reduce_fx="max"/"min"` and fold through the extremum',
                    )
                elif scatter is not None:
                    seg = _segment_extremum_name(rhs)
                    spelled = f"`segment_{scatter}`" if seg else f"`.at[...].{scatter}(...)`"
                    yield FlowFinding(
                        stmt,
                        f"`\"{reducer}\"`-reduced state `{attr}` updated with a slice-axis "
                        f"scatter-extremum ({spelled}) in `{method.name}`; scattered "
                        "extrema are not additive across ranks — declare the state "
                        '`dist_reduce_fx="max"/"min"` or segment-SUM the per-slice '
                        "deltas instead",
                    )
                elif rhs is not None and _is_extremum_rhs(rhs, attr):
                    yield FlowFinding(
                        stmt,
                        f"`\"{reducer}\"`-reduced state `{attr}` updated with an extremum "
                        f"(`{_last_call_name(rhs)}`) in `{method.name}`; per-rank values stop "
                        "being additive and the cross-rank sum double-counts — declare the "
                        'state `dist_reduce_fx="max"/"min"` or accumulate additively',
                    )
                elif rhs is not None and not rhs_reads_prior(rhs):
                    yield FlowFinding(
                        stmt,
                        f"`\"{reducer}\"`-reduced state `{attr}` overwritten in `{method.name}` "
                        "without reading its prior value; the overwrite discards earlier "
                        "batches on this rank — accumulate additively "
                        f"(`self.{attr} = self.{attr} + delta`)",
                    )
            elif kind not in ("Add", "Sub"):
                yield FlowFinding(
                    stmt,
                    f"`\"{reducer}\"`-reduced state `{attr}` mutated with `{kind}` in "
                    f"`{method.name}`; only additive accumulation keeps per-rank values "
                    "summable across the mesh",
                )
        elif reducer == "merge":
            # sketch leaves (metrics_tpu/sketches/): the leaf is a PACKED
            # structure whose only consistent accumulation is a self-merging
            # transform — an insert/merge call that receives the prior leaf.
            # Element-wise arithmetic corrupts the (weight, key, payload)
            # layout the cross-rank merge reducer trusts.
            if kind in ("Add", "Sub") or (
                kind == "assign"
                and isinstance(rhs, ast.BinOp)
                and isinstance(rhs.op, _ADDITIVE_AUG_OPS)
            ):
                yield FlowFinding(
                    stmt,
                    f"`\"merge\"`-reduced sketch state `{attr}` accumulated additively in "
                    f"`{method.name}`; a packed sketch leaf is not element-wise summable — "
                    "route the batch through the sketch's insert/merge transform "
                    f"(`self.{attr} = qsketch_insert(self.{attr}, ...)`)",
                )
            elif kind == "assign" and rhs is not None and not rhs_reads_prior(rhs):
                yield FlowFinding(
                    stmt,
                    f"`\"merge\"`-reduced sketch state `{attr}` overwritten in "
                    f"`{method.name}` without reading its prior value; the overwrite "
                    "discards earlier batches on this rank — insert into the prior leaf "
                    "instead",
                )
            elif kind not in ("assign", "Add", "Sub"):
                yield FlowFinding(
                    stmt,
                    f"`\"merge\"`-reduced sketch state `{attr}` mutated with `{kind}` in "
                    f"`{method.name}`; only the sketch's own insert/merge transforms keep "
                    "the packed layout mergeable across ranks",
                )
        elif reducer == "decay":
            # exponentially-decayed sum leaves (metrics_tpu/windowed/):
            # the one consistent accumulation is decay-then-add — the prior
            # value must be SCALED before the delta lands. A plain additive
            # write type-checks and sums, but the leaf silently stops
            # forgetting: it degrades to an all-of-time sum while every
            # consumer still reads it as "the recent window".
            if kind in ("Add", "Sub"):
                yield FlowFinding(
                    stmt,
                    f"`\"decay\"`-reduced state `{attr}` accumulated with a plain"
                    f" `{kind}` in `{method.name}`; an unscaled addition never decays"
                    " — write the decayed form"
                    f" (`self.{attr} = alpha * self.{attr} + delta`)",
                )
            elif kind == "assign" and rhs is not None:
                if _direct_unscaled_additive(rhs, attr) and not _has_scaled_prior(rhs, attr):
                    yield FlowFinding(
                        stmt,
                        f"`\"decay\"`-reduced state `{attr}` accumulated additively"
                        f" without scaling the prior value in `{method.name}`; the"
                        " leaf degrades to an all-of-time sum — write the decayed"
                        f" form (`self.{attr} = alpha * self.{attr} + delta`)",
                    )
                elif not rhs_reads_prior(rhs):
                    yield FlowFinding(
                        stmt,
                        f"`\"decay\"`-reduced state `{attr}` overwritten in"
                        f" `{method.name}` without reading its prior value; the"
                        " overwrite discards the decayed history on this rank",
                    )
        elif reducer == "ring":
            # ring-of-buckets leaves (metrics_tpu/windowed/): accumulation
            # is a ROTATION — one slot is read, combined, and written back
            # with `.at[slot].set(...)`; a whole-leaf additive write pours
            # the batch into EVERY bucket's row, so expired buckets never
            # evict and every window over-counts.
            if kind in ("Add", "Sub"):
                yield FlowFinding(
                    stmt,
                    f"`\"ring\"`-reduced state `{attr}` accumulated with a"
                    f" whole-leaf `{kind}` in `{method.name}`; ring leaves rotate"
                    " one slot per bucket — write through"
                    f" `self.{attr} = self.{attr}.at[slot].set(row)`",
                )
            elif kind == "assign" and rhs is not None:
                if _is_ring_rotation(rhs, attr):
                    pass  # the ring-rotation idiom: reducer-consistent
                elif _direct_unscaled_additive(rhs, attr):
                    yield FlowFinding(
                        stmt,
                        f"`\"ring\"`-reduced state `{attr}` accumulated with a"
                        f" whole-leaf addition in `{method.name}`; the batch lands"
                        " in every bucket's row and expired buckets never evict —"
                        f" rotate one slot (`self.{attr}.at[slot].set(row)`)",
                    )
                elif not rhs_reads_prior(rhs):
                    yield FlowFinding(
                        stmt,
                        f"`\"ring\"`-reduced state `{attr}` overwritten in"
                        f" `{method.name}` without reading its prior value; the"
                        " overwrite wipes every bucket's row, not one slot",
                    )
        elif reducer in ("max", "min"):
            additive = (kind in ("Add", "Sub")) or (
                kind == "assign" and rhs is not None and _is_additive_rhs(rhs, attr)
            )
            scatter = (
                _scatter_extremum_kind(rhs, attr) if kind == "assign" and rhs is not None else None
            )
            if additive:
                yield FlowFinding(
                    stmt,
                    f"`\"{reducer}\"`-reduced state `{attr}` accumulated additively in "
                    f"`{method.name}`; an extremum-reduced leaf must be updated with "
                    f"`jnp.{'maximum' if reducer == 'max' else 'minimum'}(self.{attr}, ...)` "
                    "or its cross-rank reduction is meaningless",
                )
            elif scatter is not None and scatter != reducer:
                # a matching scatter-extremum (`.at[ids].max` into a
                # "max"-reduced leaf) is the reducer-consistent sliced form
                # and passes; only the MISMATCHED direction is flagged
                yield FlowFinding(
                    stmt,
                    f"`\"{reducer}\"`-reduced state `{attr}` updated with a "
                    f"`.at[...].{scatter}(...)` scatter in `{method.name}`; the scatter "
                    f"direction contradicts the declared `\"{reducer}\"` reduction",
                )


def _last_call_name(rhs: ast.AST) -> str:
    if isinstance(rhs, ast.Call):
        if isinstance(rhs.func, ast.Attribute):
            return rhs.func.attr
        if isinstance(rhs.func, ast.Name):
            return rhs.func.id
    return "?"


def _calls_super_reset(method: ast.FunctionDef) -> bool:
    for node in ast.walk(method):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr == "reset":
            # super().reset() / Metric.reset(self): base-class reset restores
            # every registered default. `child.reset()` on some OTHER object
            # does NOT — it must not satisfy the restoration check.
            if isinstance(func.value, ast.Call) and isinstance(func.value.func, ast.Name) and func.value.func.id == "super":
                return True
            if (
                isinstance(func.value, ast.Name)
                and func.value.id != "self"
                and any(isinstance(a, ast.Name) and a.id == "self" for a in node.args)
            ):
                return True
    return False


def _check_reset(
    class_node: ast.ClassDef, reducers: Dict[str, str], all_states: Set[str]
) -> Iterator[FlowFinding]:
    reset = next(
        (s for s in class_node.body if isinstance(s, ast.FunctionDef) and s.name == "reset"),
        None,
    )
    if reset is None or _calls_super_reset(reset):
        return
    restored = {attr for _, attr, _ in _self_attr_writes(reset)}
    missing = sorted(all_states - restored)
    if missing:
        yield FlowFinding(
            reset,
            f"`reset` override restores {sorted(restored & all_states)} but not "
            f"{missing} and never calls `super().reset()`; unrestored state leaks "
            "across epochs",
        )


def _check_live_leaves(
    ctx: FileContext, class_node: ast.ClassDef, own_states: Set[str]
) -> Iterator[FlowFinding]:
    has_update = any(
        isinstance(s, ast.FunctionDef) and s.name in ("_update", "update")
        for s in class_node.body
    )
    if not has_update or not own_states:
        return
    # liveness is file-scoped: in-file subclasses and helpers may own the
    # read/write side of a base-registered leaf. The add_state name argument
    # itself does not count as a touch — it IS the registration.
    registration_names: Set[int] = set()
    for node in ast.walk(ctx.tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "add_state"
            and node.args
        ):
            registration_names.add(id(node.args[0]))
    touched: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            touched.add(node.attr)
        elif (
            isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and id(node) not in registration_names
        ):
            # getattr(self, name) / dynamic state access by string literal
            touched.add(node.value)
    for name in sorted(own_states):
        if name not in touched:
            yield FlowFinding(
                class_node,
                f"state `{name}` is registered but never read or written anywhere in "
                "this file; dead state still pays sync bytes every compute (typo'd "
                "attribute?)",
            )


def analyze_class(ctx: FileContext, class_node: ast.ClassDef) -> List[FlowFinding]:
    """All TL-FLOW findings for one class."""
    reducers = _state_reducers(class_node)
    findings: List[FlowFinding] = []
    own_states: Set[str] = set()
    for node in ast.walk(class_node):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "add_state"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "self"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            own_states.add(node.args[0].value)
    for stmt in class_node.body:
        if isinstance(stmt, ast.FunctionDef) and stmt.name in _UPDATE_METHODS:
            findings.extend(_check_update_writes(stmt, reducers))
    findings.extend(_check_reset(class_node, reducers, own_states))
    findings.extend(_check_live_leaves(ctx, class_node, own_states))
    return findings
