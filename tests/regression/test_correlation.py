"""Pearson / Spearman correlation vs scipy oracles, and CosineSimilarity."""
import numpy as np
import pytest
from scipy.stats import pearsonr, spearmanr

from metrics_tpu.functional import cosine_similarity, pearson_corrcoef, spearman_corrcoef
from metrics_tpu.regression import CosineSimilarity, PearsonCorrCoef, SpearmanCorrCoef
from tests.helpers.testers import BATCH_SIZE, NUM_BATCHES, MetricTester

_rng = np.random.RandomState(123)
_preds = _rng.rand(NUM_BATCHES, BATCH_SIZE).astype(np.float32)
_target = (_rng.rand(NUM_BATCHES, BATCH_SIZE) + 0.3 * _preds).astype(np.float32)
# discrete-valued inputs exercise the tie-averaging rank path
_preds_ties = _rng.randint(0, 10, (NUM_BATCHES, BATCH_SIZE)).astype(np.float32)
_target_ties = _rng.randint(0, 10, (NUM_BATCHES, BATCH_SIZE)).astype(np.float32)


def _sk_pearson(preds, target):
    return pearsonr(np.asarray(target, np.float64), np.asarray(preds, np.float64))[0]


def _sk_spearman(preds, target):
    return spearmanr(np.asarray(target, np.float64), np.asarray(preds, np.float64))[0]


class TestPearson(MetricTester):
    atol = 1e-4

    def test_pearson_class(self):
        self.run_class_metric_test(
            preds=_preds,
            target=_target,
            metric_class=PearsonCorrCoef,
            sk_metric=_sk_pearson,
        )

    def test_pearson_functional(self):
        self.run_functional_metric_test(
            _preds, _target, metric_functional=pearson_corrcoef, sk_metric=_sk_pearson
        )

    def test_pearson_differentiability(self):
        self.run_differentiability_test(
            _preds, _target, metric_class=PearsonCorrCoef, metric_functional=pearson_corrcoef
        )


@pytest.mark.parametrize(
    "preds, target",
    [(_preds, _target), (_preds_ties, _target_ties)],
)
class TestSpearman(MetricTester):
    atol = 1e-4

    def test_spearman_class(self, preds, target):
        self.run_class_metric_test(
            preds=preds,
            target=target,
            metric_class=SpearmanCorrCoef,
            sk_metric=_sk_spearman,
        )

    def test_spearman_functional(self, preds, target):
        self.run_functional_metric_test(
            preds, target, metric_functional=spearman_corrcoef, sk_metric=_sk_spearman
        )


_preds_cos = _rng.rand(NUM_BATCHES, BATCH_SIZE, 4).astype(np.float32)
_target_cos = _rng.rand(NUM_BATCHES, BATCH_SIZE, 4).astype(np.float32)


def _sk_cosine(preds, target, reduction="sum"):
    preds, target = np.asarray(preds, np.float64), np.asarray(target, np.float64)
    sim = (preds * target).sum(-1) / (np.linalg.norm(preds, axis=-1) * np.linalg.norm(target, axis=-1))
    if reduction == "sum":
        return sim.sum()
    if reduction == "mean":
        return sim.mean()
    return sim


@pytest.mark.parametrize("reduction", ["sum", "mean"])
class TestCosineSimilarity(MetricTester):
    atol = 1e-4

    def test_cosine_class(self, reduction):
        self.run_class_metric_test(
            preds=_preds_cos,
            target=_target_cos,
            metric_class=CosineSimilarity,
            sk_metric=lambda p, t: _sk_cosine(p, t, reduction),
            metric_args={"reduction": reduction},
        )

    def test_cosine_functional(self, reduction):
        self.run_functional_metric_test(
            _preds_cos,
            _target_cos,
            metric_functional=cosine_similarity,
            sk_metric=lambda p, t: _sk_cosine(p, t, reduction),
            metric_args={"reduction": reduction},
        )


def test_pearson_1d_only():
    import jax.numpy as jnp

    with pytest.raises(ValueError):
        pearson_corrcoef(jnp.ones((4, 2, 2)), jnp.ones((4, 2, 2)))
    with pytest.raises(ValueError):
        spearman_corrcoef(jnp.ones((4, 2, 2)), jnp.ones((4, 2, 2)))
