"""Translation Edit Rate (TER).

Behavior parity with /root/reference/torchmetrics/functional/text/ter.py:57-630
(itself a port of sacrebleu's near-exact Tercom reimplementation): the Tercom
tokenizer, the greedy shift search with Tercom's candidate-ranking heuristics
and limits, and the beam-limited Levenshtein with the substitute > delete >
insert tie preference that fixes the alignment trace.

Architecture departures from the reference: the shift-search hot path (up to
1000 candidate re-scorings per sentence) uses a ROW-VECTORIZED numpy DP for
the scalar edit distance (the prefix-relaxation trick handles the in-row
insert dependency), replacing the reference's per-cell Python loops plus
prefix trie cache (_LevenshteinEditDistance, helper.py:64-306); the
operation-trace DP (needed once per shift iteration, not per candidate) walks
only the beam window. Scalar edit-distance VALUES are tie-independent, so the
vectorized kernel is exact; the trace DP reproduces the reference's
preference order exactly.

Host-side string processing feeding scalar device states (SURVEY §2.7).
"""
import math
import re
from functools import lru_cache
from typing import Dict, Iterator, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.functional.text.helper import _validate_inputs

Array = jax.Array

# Tercom-inspired limits (reference ter.py:50-54)
_MAX_SHIFT_SIZE = 10
_MAX_SHIFT_DIST = 50
_MAX_SHIFT_CANDIDATES = 1000

# beam-limited DP (reference helper.py:36-40)
_BEAM_WIDTH = 25
_INT_INF = int(1e16)

# edit-operation codes for the trace DP
_OP_NOTHING, _OP_SUBSTITUTE, _OP_INSERT, _OP_DELETE = 0, 1, 2, 3


class _TercomTokenizer:
    """Tercom normalizer/tokenizer (rule tables fixed by the Tercom spec;
    reference ter.py:57-193, following sacrebleu's tokenizer_ter.py)."""

    _ASIAN_PUNCTUATION = r"([、。〈-】〔-〟｡-･・])"
    _FULL_WIDTH_PUNCTUATION = r"([．，？：；！＂（）])"

    def __init__(
        self,
        normalize: bool = False,
        no_punctuation: bool = False,
        lowercase: bool = True,
        asian_support: bool = False,
    ) -> None:
        self.normalize = normalize
        self.no_punctuation = no_punctuation
        self.lowercase = lowercase
        self.asian_support = asian_support

    @lru_cache(maxsize=2**16)
    def __call__(self, sentence: str) -> str:
        if not sentence:
            return ""
        if self.lowercase:
            sentence = sentence.lower()
        if self.normalize:
            sentence = self._normalize_general_and_western(sentence)
            if self.asian_support:
                sentence = self._normalize_asian(sentence)
        if self.no_punctuation:
            sentence = self._remove_punct(sentence)
            if self.asian_support:
                sentence = self._remove_asian_punct(sentence)
        return " ".join(sentence.split())

    @staticmethod
    def _normalize_general_and_western(sentence: str) -> str:
        sentence = f" {sentence} "
        rules = [
            (r"\n-", ""),
            (r"\n", " "),
            (r"&quot;", '"'),
            (r"&amp;", "&"),
            (r"&lt;", "<"),
            (r"&gt;", ">"),
            (r"([{-~[-` -&(-+:-@/])", r" \1 "),
            (r"'s ", r" 's "),
            (r"'s$", r" 's"),
            (r"([^0-9])([\.,])", r"\1 \2 "),
            (r"([\.,])([^0-9])", r" \1 \2"),
            (r"([0-9])(-)", r"\1 \2 "),
        ]
        for pattern, replacement in rules:
            sentence = re.sub(pattern, replacement, sentence)
        return sentence

    @classmethod
    def _normalize_asian(cls, sentence: str) -> str:
        sentence = re.sub(r"([一-鿿㐀-䶿])", r" \1 ", sentence)
        sentence = re.sub(r"([㇀-㇯⺀-⻿])", r" \1 ", sentence)
        sentence = re.sub(r"([㌀-㏿豈-﫿︰-﹏])", r" \1 ", sentence)
        sentence = re.sub(r"([㈀-㼢])", r" \1 ", sentence)
        sentence = re.sub(r"(^|^[぀-ゟ])([぀-ゟ]+)(?=$|^[぀-ゟ])", r"\1 \2 ", sentence)
        sentence = re.sub(r"(^|^[゠-ヿ])([゠-ヿ]+)(?=$|^[゠-ヿ])", r"\1 \2 ", sentence)
        sentence = re.sub(r"(^|^[ㇰ-ㇿ])([ㇰ-ㇿ]+)(?=$|^[ㇰ-ㇿ])", r"\1 \2 ", sentence)
        sentence = re.sub(cls._ASIAN_PUNCTUATION, r" \1 ", sentence)
        sentence = re.sub(cls._FULL_WIDTH_PUNCTUATION, r" \1 ", sentence)
        return sentence

    @staticmethod
    def _remove_punct(sentence: str) -> str:
        return re.sub(r"[\.,\?:;!\"\(\)]", "", sentence)

    @classmethod
    def _remove_asian_punct(cls, sentence: str) -> str:
        sentence = re.sub(cls._ASIAN_PUNCTUATION, r"", sentence)
        return re.sub(cls._FULL_WIDTH_PUNCTUATION, r"", sentence)


def _preprocess_sentence(sentence: str, tokenizer: _TercomTokenizer) -> str:
    return tokenizer(sentence.rstrip())


_MAX_CACHE_SIZE = 10000


class _RefEditScorer:
    """Edit distances of candidate hypotheses against ONE fixed reference.

    Reproduces the reference _LevenshteinEditDistance (helper.py:64-306)
    semantics EXACTLY — including the prefix-row trie cache, whose frozen
    rows (computed under an earlier call's beam window) are deliberately
    reused by later calls with different lengths; this quirk influences
    Tercom shift choices and therefore final TER values — but computes each
    new row with a vectorized numpy kernel instead of per-cell Python loops.
    """

    def __init__(self, reference_tokens: List[str]) -> None:
        self.reference_tokens = reference_tokens
        self._vocab: Dict[str, int] = {}
        self.ref_ids = self._intern(reference_tokens)
        m = len(self.ref_ids)
        self._initial_row = (
            np.arange(m + 1, dtype=np.int64),
            np.full(m + 1, _OP_INSERT, np.int8),
        )
        # trie over hypothesis word ids: wid -> (child_dict, (cost_row, op_row))
        self._trie: Dict[int, tuple] = {}
        self._cache_size = 0

    def _intern(self, tokens: Sequence[str]) -> Tuple[int, ...]:
        return tuple(self._vocab.setdefault(t, len(self._vocab)) for t in tokens)

    @staticmethod
    def _beam_bounds(i: int, n_pred: int, n_ref: int, length_ratio: float) -> Tuple[int, int]:
        """Row window of the beam-limited DP (reference helper.py:131-143)."""
        beam = (
            math.ceil(length_ratio / 2 + _BEAM_WIDTH)
            if _BEAM_WIDTH < length_ratio / 2
            else _BEAM_WIDTH
        )
        pseudo_diag = math.floor(i * length_ratio)
        min_j = max(0, pseudo_diag - beam)
        max_j = n_ref + 1 if i == n_pred else min(n_ref + 1, pseudo_diag + beam)
        return min_j, max_j

    def _compute_row(
        self, prev_cost: np.ndarray, word_id: int, min_j: int, max_j: int
    ) -> Tuple[np.ndarray, np.ndarray]:
        """One DP row, vectorized, with the reference's tie order
        (substitute/nothing first, then delete, then insert;
        helper.py:160-174). The in-row insert dependency is resolved with a
        prefix-min; insert wins a cell only when strictly cheaper."""
        m = len(self.ref_ids)
        cols = np.arange(m + 1, dtype=np.int64)
        ref_arr = np.asarray(self.ref_ids, np.int64) if m else np.zeros(0, np.int64)

        sub_cost = (ref_arr != word_id).astype(np.int64)
        diag = prev_cost[:-1] + sub_cost
        top = prev_cost[1:] + 1
        pre = np.full(m + 1, _INT_INF, np.int64)
        pre[0] = prev_cost[0] + 1  # delete-only first column
        np.minimum(diag, top, out=pre[1:])
        pre_op = np.empty(m + 1, np.int8)
        pre_op[0] = _OP_DELETE
        pre_op[1:] = np.where(
            top < diag,
            _OP_DELETE,
            np.where(sub_cost == 0, _OP_NOTHING, _OP_SUBSTITUTE),
        )
        pre[:min_j] = _INT_INF
        pre[max_j:] = _INT_INF

        cost = np.minimum(pre, np.minimum.accumulate(pre - cols) + cols)
        op = np.where(cost < pre, _OP_INSERT, pre_op).astype(np.int8)
        cost[:min_j] = _INT_INF
        cost[max_j:] = _INT_INF
        return cost, op

    def _rows(self, pred_ids: Tuple[int, ...]) -> List[Tuple[np.ndarray, np.ndarray]]:
        """All DP rows for this hypothesis: initial + cached prefix + fresh."""
        rows: List[Tuple[np.ndarray, np.ndarray]] = [self._initial_row]
        node = self._trie
        start = 0
        for wid in pred_ids:
            if wid in node:
                node, row = node[wid]
                rows.append(row)
                start += 1
            else:
                break

        n, m = len(pred_ids), len(self.ref_ids)
        length_ratio = m / n if pred_ids else 1.0
        new_rows: List[Tuple[np.ndarray, np.ndarray]] = []
        prev_cost = rows[-1][0]
        for i in range(start + 1, n + 1):
            min_j, max_j = self._beam_bounds(i, n, m, length_ratio)
            row = self._compute_row(prev_cost, pred_ids[i - 1], min_j, max_j)
            new_rows.append(row)
            rows.append(row)
            prev_cost = row[0]

        # cache the fresh rows (reference helper.py:218-249: size checked
        # once at entry, then the whole suffix is added)
        if self._cache_size < _MAX_CACHE_SIZE:
            node = self._trie
            for wid in pred_ids[:start]:
                node = node[wid][0]
            for wid, row in zip(pred_ids[start:], new_rows):
                if wid not in node:
                    node[wid] = ({}, row)
                    self._cache_size += 1
                node = node[wid][0]
        return rows

    def distance(self, prediction_tokens: Sequence[str]) -> int:
        rows = self._rows(self._intern(prediction_tokens))
        return int(rows[-1][0][len(self.ref_ids)])

    def distance_with_trace(self, prediction_tokens: Sequence[str]) -> Tuple[int, List[int]]:
        pred_ids = self._intern(prediction_tokens)
        rows = self._rows(pred_ids)
        i, j = len(pred_ids), len(self.ref_ids)
        trace: List[int] = []
        while i > 0 or j > 0:
            operation = int(rows[i][1][j])
            trace.append(operation)
            if operation in (_OP_NOTHING, _OP_SUBSTITUTE):
                i, j = i - 1, j - 1
            elif operation == _OP_INSERT:
                j -= 1
            else:  # delete
                i -= 1
        trace.reverse()
        return int(rows[len(pred_ids)][0][len(self.ref_ids)]), trace


def _flip_trace(trace: List[int]) -> List[int]:
    """Rewrite the a->b recipe as b->a (swap inserts and deletes)."""
    swap = {_OP_INSERT: _OP_DELETE, _OP_DELETE: _OP_INSERT}
    return [swap.get(operation, operation) for operation in trace]


def _trace_to_alignment(trace: List[int]) -> Tuple[Dict[int, int], List[int], List[int]]:
    """Alignment map + per-position error flags from an operation trace
    (reference helper.py:398-446)."""
    ref_pos = pred_pos = -1
    alignments: Dict[int, int] = {}
    ref_errors: List[int] = []
    pred_errors: List[int] = []
    for operation in trace:
        if operation == _OP_NOTHING:
            pred_pos += 1
            ref_pos += 1
            alignments[ref_pos] = pred_pos
            ref_errors.append(0)
            pred_errors.append(0)
        elif operation == _OP_SUBSTITUTE:
            pred_pos += 1
            ref_pos += 1
            alignments[ref_pos] = pred_pos
            ref_errors.append(1)
            pred_errors.append(1)
        elif operation == _OP_INSERT:
            pred_pos += 1
            pred_errors.append(1)
        elif operation == _OP_DELETE:
            ref_pos += 1
            alignments[ref_pos] = pred_pos  # deleted ref words map to the last hyp position
            ref_errors.append(1)
        else:
            raise ValueError(f"Unknown operation {operation!r}")
    return alignments, ref_errors, pred_errors


def _find_shifted_pairs(pred_words: List[str], target_words: List[str]) -> Iterator[Tuple[int, int, int]]:
    """All matching word sub-sequences eligible for a Tercom shift
    (reference ter.py:209-247)."""
    for pred_start in range(len(pred_words)):
        for target_start in range(len(target_words)):
            if abs(target_start - pred_start) > _MAX_SHIFT_DIST:
                continue
            for length in range(1, _MAX_SHIFT_SIZE):
                if pred_words[pred_start + length - 1] != target_words[target_start + length - 1]:
                    break
                yield pred_start, target_start, length
                if len(pred_words) == pred_start + length or len(target_words) == target_start + length:
                    break


def _shift_is_pointless(
    alignments: Dict[int, int],
    pred_errors: List[int],
    target_errors: List[int],
    pred_start: int,
    target_start: int,
    length: int,
) -> bool:
    """Tercom corner cases: skip shifts of already-correct spans, spans whose
    target is already matched, and shifts within the own sub-sequence
    (reference ter.py:250-291)."""
    if sum(pred_errors[pred_start : pred_start + length]) == 0:
        return True
    if sum(target_errors[target_start : target_start + length]) == 0:
        return True
    if pred_start <= alignments[target_start] < pred_start + length:
        return True
    return False


def _perform_shift(words: List[str], start: int, length: int, target: int) -> List[str]:
    """Move ``words[start:start+length]`` to position ``target``
    (reference ter.py:294-327)."""
    span = words[start : start + length]
    if target < start:
        return words[:target] + span + words[target:start] + words[start + length :]
    if target > start + length:
        return words[:start] + words[start + length : target] + span + words[target:]
    return words[:start] + words[start + length : length + target] + span + words[length + target :]


def _shift_words(
    pred_words: List[str],
    target_words: List[str],
    scorer: _RefEditScorer,
    checked_candidates: int,
) -> Tuple[int, List[str], int]:
    """One round of Tercom's greedy shift selection: try every eligible
    shifted candidate, ranked by (edit-distance gain, span length, earliest
    pred position, earliest target position) (reference ter.py:329-410)."""
    edit_distance, inverted_trace = scorer.distance_with_trace(pred_words)
    trace = _flip_trace(inverted_trace)
    alignments, target_errors, pred_errors = _trace_to_alignment(trace)

    best: Optional[Tuple[int, int, int, int, List[str]]] = None
    for pred_start, target_start, length in _find_shifted_pairs(pred_words, target_words):
        if _shift_is_pointless(alignments, pred_errors, target_errors, pred_start, target_start, length):
            continue

        prev_idx = -1
        for offset in range(-1, length):
            if target_start + offset == -1:
                idx = 0
            elif target_start + offset in alignments:
                idx = alignments[target_start + offset] + 1
            else:
                break  # offset aims past the reference
            if idx == prev_idx:
                continue
            prev_idx = idx

            shifted_words = _perform_shift(pred_words, pred_start, length, idx)
            candidate = (
                edit_distance - scorer.distance(shifted_words),
                length,
                -pred_start,
                -idx,
                shifted_words,
            )
            checked_candidates += 1
            if not best or candidate > best:
                best = candidate

        if checked_candidates >= _MAX_SHIFT_CANDIDATES:
            break

    if not best:
        return 0, pred_words, checked_candidates
    best_score, _, _, _, shifted_words = best
    return best_score, shifted_words, checked_candidates


def _translation_edit_rate(pred_words: List[str], target_words: List[str]) -> int:
    """Edits needed to turn ``pred_words`` into ``target_words`` including
    shifts (reference ter.py:413-444)."""
    if len(target_words) == 0:
        return 0

    scorer = _RefEditScorer(target_words)
    num_shifts = 0
    checked_candidates = 0
    input_words = pred_words
    while True:
        delta, new_input_words, checked_candidates = _shift_words(
            input_words, target_words, scorer, checked_candidates
        )
        if checked_candidates >= _MAX_SHIFT_CANDIDATES or delta <= 0:
            break
        num_shifts += 1
        input_words = new_input_words

    return num_shifts + scorer.distance(input_words)


def _compute_sentence_statistics(
    pred_words: List[str], target_words: List[List[str]]
) -> Tuple[float, float]:
    """Best edit count over references + average reference length. NOTE: the
    reference evaluates ``_translation_edit_rate(tgt_words, pred_words)``
    with swapped roles (ter.py:467) — preserved for parity."""
    tgt_lengths = 0.0
    best_num_edits = float(2e16)
    for tgt_words in target_words:
        num_edits = _translation_edit_rate(tgt_words, pred_words)
        tgt_lengths += len(tgt_words)
        if num_edits < best_num_edits:
            best_num_edits = float(num_edits)
    avg_tgt_len = tgt_lengths / len(target_words) if target_words else 0.0
    return best_num_edits, avg_tgt_len


def _compute_ter_score_from_statistics(num_edits: float, tgt_length: float) -> float:
    if tgt_length > 0 and num_edits > 0:
        return num_edits / tgt_length
    if tgt_length == 0 and num_edits > 0:
        return 1.0
    return 0.0


def _ter_update(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    tokenizer: _TercomTokenizer,
) -> Tuple[float, float, List[float]]:
    """Per-batch totals: (sum best edits, sum avg reference length,
    sentence-level scores)."""
    target, preds = _validate_inputs(target, preds)

    total_num_edits = 0.0
    total_tgt_length = 0.0
    sentence_ter: List[float] = []
    for pred, tgt in zip(preds, target):
        tgt_words = [_preprocess_sentence(t, tokenizer).split() for t in tgt]
        pred_words = _preprocess_sentence(pred, tokenizer).split()
        num_edits, tgt_length = _compute_sentence_statistics(pred_words, tgt_words)
        total_num_edits += num_edits
        total_tgt_length += tgt_length
        sentence_ter.append(_compute_ter_score_from_statistics(num_edits, tgt_length))
    return total_num_edits, total_tgt_length, sentence_ter


def _ter_compute(total_num_edits: Array, total_tgt_length: Array) -> Array:
    score = jnp.where(
        (total_tgt_length > 0) & (total_num_edits > 0),
        total_num_edits / jnp.clip(total_tgt_length, 1e-38, None),
        jnp.where((total_tgt_length == 0) & (total_num_edits > 0), 1.0, 0.0),
    )
    return score.astype(jnp.float32)


def translation_edit_rate(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    normalize: bool = False,
    no_punctuation: bool = False,
    lowercase: bool = True,
    asian_support: bool = False,
    return_sentence_level_score: bool = False,
) -> Union[Array, Tuple[Array, List[Array]]]:
    """Corpus-level Translation Edit Rate.

    Example:
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
        >>> float(translation_edit_rate(preds, target))  # doctest: +ELLIPSIS
        0.1538461...
    """
    for name, value in [
        ("normalize", normalize),
        ("no_punctuation", no_punctuation),
        ("lowercase", lowercase),
        ("asian_support", asian_support),
    ]:
        if not isinstance(value, bool):
            raise ValueError(f"Expected argument `{name}` to be of type boolean but got {value}.")

    tokenizer = _TercomTokenizer(normalize, no_punctuation, lowercase, asian_support)
    total_num_edits, total_tgt_length, sentence_ter = _ter_update(preds, target, tokenizer)
    score = _ter_compute(jnp.asarray(total_num_edits), jnp.asarray(total_tgt_length))
    if return_sentence_level_score:
        return score, [jnp.asarray(s, jnp.float32) for s in sentence_ter]
    return score
