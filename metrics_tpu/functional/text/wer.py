"""Word Error Rate (parity: /root/reference/torchmetrics/functional/text/wer.py)."""
from typing import List, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.helper import _edit_distance

Array = jax.Array


def _wer_update(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Tuple[Array, Array]:
    """Sum edit operations and reference word counts over the batch (wer.py:23-48)."""
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [target]
    errors = 0
    total = 0
    for pred, tgt in zip(preds, target):
        pred_tokens = pred.split()
        tgt_tokens = tgt.split()
        errors += _edit_distance(pred_tokens, tgt_tokens)
        total += len(tgt_tokens)
    return jnp.asarray(errors, jnp.float32), jnp.asarray(total, jnp.float32)


def _wer_compute(errors: Array, total: Array) -> Array:
    return errors / total


def word_error_rate(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Array:
    """Word error rate of transcription(s) vs reference(s); 0 is perfect.

    Example:
        >>> preds = ["this is the prediction", "there is an other sample"]
        >>> target = ["this is the reference", "there is another one"]
        >>> word_error_rate(preds=preds, target=target)
        Array(0.5, dtype=float32)
    """
    errors, total = _wer_update(preds, target)
    return _wer_compute(errors, total)
