#!/usr/bin/env python
"""Fetch pretrained torch weights and convert them to this framework's Flax
``.npz`` artifacts, with a checksummed manifest.

The reference downloads torch weights at metric-construction time
(/root/reference/torchmetrics/image/fid.py:26-57 pulls torch-fidelity's
InceptionV3; /root/reference/torchmetrics/image/lpip.py:28-41 wraps the
``lpips`` package nets; functional/text/bert.py:262-346 pulls HuggingFace
encoders). This framework keeps metric construction offline-safe instead:
run this script ONCE where network access exists, then point the metrics at
the produced artifacts:

    python scripts/fetch_and_convert_weights.py --dest ~/.cache/metrics_tpu/weights
    export METRICS_TPU_WEIGHTS=~/.cache/metrics_tpu/weights

    FrechetInceptionDistance(feature_extractor_weights_path=f"{dest}/inception_fid.npz")
    LearnedPerceptualImagePatchSimilarity(net_type="alex",
        net_weights_path=f"{dest}/lpips_alex.npz")
    BERTScore(model_name_or_path=f"{dest}/bertscore/roberta-large")

Every artifact is hashed into ``MANIFEST.json`` (sha256 + source), and the
gated tests in ``tests/image/test_real_weights.py`` verify end-to-end parity
against the torch originals wherever both the artifacts and the oracle
packages exist.
"""
import argparse
import hashlib
import json
import sys
from pathlib import Path

# canonical FID weights (TF-Inception 2015-12-05 port) — the same network the
# reference's torch-fidelity/pytorch-fid backends download
PT_FID_INCEPTION_URL = (
    "https://github.com/mseitzer/pytorch-fid/releases/download/fid_weights/"
    "pt_inception-2015-12-05-6726825d.pth"
)


def _sha256(path: Path) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def fetch_inception(dest: Path, manifest: dict) -> None:
    """torch-fidelity / pytorch-fid FID InceptionV3 -> inception_fid.npz."""
    import numpy as np
    import torch

    from metrics_tpu.models.inception import convert_torch_fidelity_weights

    state_dict = None
    source = None
    try:  # preferred: the torch-fidelity package the reference itself uses
        from torch_fidelity.feature_extractor_inceptionv3 import FeatureExtractorInceptionV3

        net = FeatureExtractorInceptionV3("inception-v3-compat", ["2048"])
        state_dict = net.state_dict()
        source = "torch_fidelity.FeatureExtractorInceptionV3"
    except Exception:
        pass
    if state_dict is None:
        state_dict = torch.hub.load_state_dict_from_url(
            PT_FID_INCEPTION_URL, map_location="cpu", progress=True
        )
        source = PT_FID_INCEPTION_URL

    variables = convert_torch_fidelity_weights(state_dict)
    out = dest / "inception_fid.npz"
    np.savez(out, variables=np.asarray(variables, dtype=object))
    manifest["inception_fid.npz"] = {"sha256": _sha256(out), "source": source}
    print(f"wrote {out} ({source})")


def fetch_lpips(dest: Path, manifest: dict, nets=("alex", "vgg")) -> None:
    """``lpips`` package nets (backbone + linear heads) -> lpips_<net>.npz."""
    import numpy as np

    try:
        import lpips as lpips_pkg
    except ImportError:
        print("SKIP lpips: the `lpips` package is not installed (pip install lpips)")
        return

    from metrics_tpu.models.lpips import convert_lpips_weights

    for net in nets:
        sd = lpips_pkg.LPIPS(net=net).state_dict()
        variables = convert_lpips_weights(sd, net_type=net)
        out = dest / f"lpips_{net}.npz"
        np.savez(out, variables=np.asarray(variables, dtype=object))
        manifest[out.name] = {"sha256": _sha256(out), "source": f"lpips.LPIPS(net='{net}') v{lpips_pkg.__version__}"}
        print(f"wrote {out}")


def fetch_bert(dest: Path, manifest: dict, model_name: str) -> None:
    """HuggingFace Flax encoder + tokenizer -> bertscore/<name>/ checkpoint."""
    try:
        from transformers import AutoTokenizer, FlaxAutoModel
    except ImportError:
        print("SKIP bert: `transformers` is not installed")
        return

    out = dest / "bertscore" / model_name.replace("/", "__")
    out.mkdir(parents=True, exist_ok=True)
    AutoTokenizer.from_pretrained(model_name).save_pretrained(out)
    # from_pt=True converts torch-only checkpoints to Flax on the fly
    try:
        model = FlaxAutoModel.from_pretrained(model_name)
    except Exception:
        model = FlaxAutoModel.from_pretrained(model_name, from_pt=True)
    model.save_pretrained(out)
    weights = out / "flax_model.msgpack"
    # key by the hashed FILE so the checksum test can verify it directly
    # (drop the directory-keyed entry older manifests may carry)
    manifest.pop(f"bertscore/{out.name}", None)
    manifest[f"bertscore/{out.name}/flax_model.msgpack"] = {
        "sha256": _sha256(weights) if weights.exists() else None,
        "source": f"huggingface:{model_name}",
    }
    print(f"wrote {out}")


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--dest", default="~/.cache/metrics_tpu/weights", help="artifact directory")
    parser.add_argument(
        "--only",
        nargs="*",
        choices=("inception", "lpips", "bert"),
        default=("inception", "lpips", "bert"),
    )
    parser.add_argument(
        "--bert-model",
        default="roberta-large",
        help="HF encoder to fetch (reference bert_score default: roberta-large)",
    )
    args = parser.parse_args()

    dest = Path(args.dest).expanduser()
    dest.mkdir(parents=True, exist_ok=True)
    manifest_path = dest / "MANIFEST.json"
    manifest = json.loads(manifest_path.read_text()) if manifest_path.exists() else {}

    failures = []
    for component, fn in (
        ("inception", lambda: fetch_inception(dest, manifest)),
        ("lpips", lambda: fetch_lpips(dest, manifest)),
        ("bert", lambda: fetch_bert(dest, manifest, args.bert_model)),
    ):
        if component not in args.only:
            continue
        try:
            fn()
        except Exception as exc:  # keep going; report at the end
            failures.append((component, exc))
            print(f"FAILED {component}: {exc}")

    manifest_path.write_text(json.dumps(manifest, indent=2, sort_keys=True) + "\n")
    print(f"manifest: {manifest_path}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
