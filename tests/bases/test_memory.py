"""Device-memory observatory tests (ISSUE 18 tentpole + satellites): the
MemoryLedger's buffer-identity dedup (fused group state and donated
buffers counted once, never twice), the reset-to-baseline leak
regression for sliced/windowed/retrieval state, the cache-plane registry
(register/unregister, raising callbacks, the repo's built-in planes, the
retrieval layout eviction totals riding the compute read event), the
``set_dtype`` footprint staleness fix (theoretical == live for
fixed-shape metrics), the one-bool disabled hot path, the
``memory_budget`` / ``memory_leak`` alarm classes firing and clearing,
and the Prometheus memory families + fleet wire merge under the strict
exposition parser."""
import gc
import time

import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import MeanSquaredError, MetricCollection
from metrics_tpu.aggregation import MeanMetric
from metrics_tpu.observability import (
    HealthMonitor,
    MemoryBudget,
    MemoryLeak,
    MemoryLedger,
    MemoryObservatory,
    cache_plane_inventory,
    counter_payload,
    default_rules,
    get_recorder,
    merge_payloads,
    register_cache_plane,
    render_prometheus,
    unregister_cache_plane,
)
from metrics_tpu.observability.recorder import (
    SERIES_MEM_BYTES_PER_TENANT,
    SERIES_MEM_UNACCOUNTED,
)
from metrics_tpu.observability.timeseries import TimeSeriesRegistry
from metrics_tpu.retrieval import RetrievalMAP
from metrics_tpu.retrieval.base import layout_cache_totals
from metrics_tpu.sliced import SlicedMetric
from metrics_tpu.windowed import WindowedMetric

from .test_freshness import parse_prometheus_strict

T0 = 1_000_000.0


@pytest.fixture
def recorder():
    """The default recorder, enabled for one test and ALWAYS disabled+reset
    after — the session-level conftest asserts nothing leaks."""
    rec = get_recorder()
    rec.reset()
    rec.enable()
    try:
        yield rec
    finally:
        rec.disable()
        rec.detach_timeseries()
        rec.reset()


def _batch(n=8, seed=0):
    rng = np.random.RandomState(seed)
    return (
        jnp.asarray(rng.rand(n).astype(np.float32)),
        jnp.asarray(rng.rand(n).astype(np.float32)),
    )


# ----------------------------------------------------------------------
# the ledger: identity dedup, donation, per-device attribution
# ----------------------------------------------------------------------
class TestLedger:
    def test_fused_group_state_counted_once(self):
        # satellite 4: after a fused update, group members receive the
        # LEADER's new state arrays — two MSE twins alias the same buffers,
        # and the sum of their individual footprints double-counts what the
        # device actually holds
        col = MetricCollection({"a": MeanSquaredError(), "b": MeanSquaredError()})
        preds, target = _batch()
        col.update(preds, target)  # discovery
        col.compile_update()
        col.update(preds, target)
        rep = MemoryLedger(list(col.values())).measure()
        naive = sum(m.total_state_bytes() for m in col.values())
        assert rep["n_shared"] >= 1
        assert rep["total_bytes"] < naive
        assert rep["n_metrics"] == 2

    def test_aliased_state_counted_once(self):
        m1, m2 = MeanSquaredError(), MeanSquaredError()
        preds, target = _batch()
        m1.update(preds, target)
        m2.update(preds, target)
        independent = MemoryLedger([m1, m2]).measure()["total_bytes"]
        m2.sum_squared_error = m1.sum_squared_error  # hand-aliased buffer
        rep = MemoryLedger([m1, m2]).measure()
        assert rep["n_shared"] >= 1
        assert rep["total_bytes"] < independent

    def test_donated_buffers_count_zero(self):
        # the donated-buffer contract: a deleted (donated-away) array holds
        # no committed device bytes, so the ledger must not bill it
        m = MeanSquaredError()
        preds, target = _batch()
        m.update(preds, target)
        base = MemoryLedger([m]).measure()["total_bytes"]
        m.sum_squared_error.delete()
        rep = MemoryLedger([m]).measure()
        assert rep["n_donated"] >= 1
        assert rep["total_bytes"] < base

    def test_per_device_breakdown_sums_to_total(self):
        m = SlicedMetric(MeanSquaredError(), num_slices=16)
        preds, target = _batch()
        m.update(jnp.asarray(np.arange(8) % 16), preds, target)
        rep = MemoryLedger([m]).measure()
        assert rep["total_bytes"] > 0
        assert sum(rep["per_device"].values()) == rep["total_bytes"]
        # sliced attribution: the whole state is per-tenant here
        assert rep["sliced_bytes"] == rep["total_bytes"]
        assert rep["num_tenants"] == 16
        assert rep["bytes_per_tenant"] == pytest.approx(rep["total_bytes"] / 16)


# ----------------------------------------------------------------------
# satellite 4 (leak regression): reset returns the ledger to baseline
# ----------------------------------------------------------------------
class TestResetBaseline:
    @pytest.mark.parametrize(
        "factory,update",
        [
            (
                lambda: SlicedMetric(MeanSquaredError(), num_slices=16),
                lambda m, p, t: m.update(jnp.asarray(np.arange(8) % 16), p, t),
            ),
            (
                lambda: WindowedMetric(MeanSquaredError(), window=4),
                lambda m, p, t: m.update(p, t),
            ),
            (
                lambda: RetrievalMAP(),
                lambda m, p, t: m.update(
                    p, jnp.asarray((np.arange(8) % 2).astype(np.int64)),
                    indexes=jnp.asarray(np.arange(8) % 3),
                ),
            ),
        ],
        ids=["sliced", "windowed", "retrieval"],
    )
    def test_reset_returns_to_post_init_bytes(self, factory, update):
        m = factory()
        baseline = MemoryLedger([m]).measure()["total_bytes"]
        preds, target = _batch()
        for seed in range(3):
            p, t = _batch(seed=seed)
            update(m, p, t)
        jnp.asarray(0.0).block_until_ready()
        m.compute()
        grown = MemoryLedger([m]).measure()["total_bytes"]
        m.reset()
        assert MemoryLedger([m]).measure()["total_bytes"] == baseline
        # retrieval rides the fixed-capacity state table: updates must not
        # grow committed bytes AT ALL, that is the whole point of the table
        if isinstance(m, RetrievalMAP):
            assert grown == baseline
        else:
            assert grown >= baseline


# ----------------------------------------------------------------------
# satellite 1: set_dtype footprint staleness fix
# ----------------------------------------------------------------------
class TestSetDtypeFootprint:
    def test_footprint_event_stamps_theoretical_and_live(self, recorder):
        m = MeanSquaredError()
        preds, target = _batch()
        m.update(preds, target)
        m.set_dtype(jnp.float16)
        evs = [e for e in recorder.events() if e.get("type") == "footprint"]
        assert evs, "set_dtype must emit a footprint event"
        last = evs[-1]
        assert last["cast_to"] == "float16"
        # fixed-shape metric: the defaults-predicted bytes and the live
        # state walk must agree — the staleness this satellite fixes
        assert last["theoretical_bytes"] == last["live_bytes"]
        assert m.total_state_bytes() == m.theoretical_state_bytes()

    def test_footprint_reflects_cast_sizes(self, recorder):
        m = MeanSquaredError()
        preds, target = _batch()
        m.update(preds, target)
        before = m.total_state_bytes()
        m.set_dtype(jnp.float16)
        # float states halve; count states keep their integer dtype — the
        # footprint must reflect the cast immediately (the staleness bug)
        assert m.total_state_bytes() < before
        assert m.total_state_bytes() == m.theoretical_state_bytes()
        # the cached computed value survives the cast at the new dtype
        m2 = MeanSquaredError()
        m2.update(preds, target)
        float(m2.compute())
        m2.set_dtype(jnp.float16)
        assert m2._computed is not None
        assert jnp.asarray(m2._computed).dtype == jnp.float16


# ----------------------------------------------------------------------
# boundary events + the one-bool disabled hot path
# ----------------------------------------------------------------------
class TestBoundaries:
    def test_disabled_records_nothing(self):
        rec = get_recorder()
        assert not rec.enabled
        m = MeanSquaredError()
        preds, target = _batch()
        m.update(preds, target)
        float(m.compute())
        m.reset()
        totals = rec.memory_totals()
        assert totals["events"] == 0 and totals["update_boundaries"] == 0
        assert not [e for e in rec.events() if e.get("type") == "memory"]

    def test_boundary_counters_and_throttled_events(self, recorder):
        m = MeanSquaredError()
        preds, target = _batch()
        for _ in range(5):
            m.update(preds, target)
        float(m.compute())
        m.reset()
        totals = recorder.memory_totals()
        assert totals["update_boundaries"] >= 5
        assert totals["compute_boundaries"] >= 1
        assert totals["reset_boundaries"] >= 1
        evs = [e for e in recorder.events() if e.get("type") == "memory"]
        # counters are exact, typed rows are throttled per kind: 5 eager
        # updates inside one throttle interval emit ONE update row
        update_rows = [e for e in evs if e.get("kind") == "update"]
        assert len(update_rows) == 1
        assert update_rows[0]["live_bytes"] == m.total_state_bytes() or (
            update_rows[0]["live_bytes"] > 0
        )


# ----------------------------------------------------------------------
# the cache-plane registry (tentpole) + satellites 2/3
# ----------------------------------------------------------------------
class TestCachePlanes:
    def test_register_unregister_and_raising_callback(self):
        register_cache_plane("test_plane", lambda: 123)
        try:
            assert cache_plane_inventory()["test_plane"] == 123
        finally:
            assert unregister_cache_plane("test_plane")
        assert "test_plane" not in cache_plane_inventory()

        def boom():
            raise RuntimeError("dead cache")

        register_cache_plane("test_boom", boom)
        try:
            # a dying callback reports 0, never poisons the inventory
            assert cache_plane_inventory()["test_boom"] == 0
        finally:
            unregister_cache_plane("test_boom")

    def test_builtin_planes_registered(self):
        inv = cache_plane_inventory()
        assert {
            "reader_cache",
            "fused_compile",
            "retrieval_layout",
            "sketch_scratch",
            "sliced_value_cache",
            "windowed_fold_memo",
        } <= set(inv)
        assert all(isinstance(v, int) and v >= 0 for v in inv.values())

    def test_reader_cache_plane_tracks_compiles(self):
        m = SlicedMetric(MeanSquaredError(), num_slices=8)
        preds, target = _batch()
        m.update(jnp.asarray(np.arange(8) % 8), preds, target)
        m.compute()
        # the instance's per-entry executable bytes feed the global plane
        assert m._readers.nbytes() >= 0
        assert len(m._readers._cache) >= 1
        assert cache_plane_inventory()["reader_cache"] >= m._readers.nbytes()

    def test_layout_eviction_totals_and_read_event(self, recorder):
        # satellite 3: the compute read event carries the layout-cache
        # totals alongside cache_hit, and a finalized metric's eviction
        # shows up in the counters with the dropped bytes
        rm = RetrievalMAP()
        idx = jnp.asarray(np.repeat(np.arange(3), 5))
        preds = jnp.asarray(np.linspace(0.0, 1.0, 15, dtype=np.float32))
        target = jnp.asarray((np.arange(15) % 5 == 0).astype(np.int64))
        rm.update(preds, target, indexes=idx)
        float(rm.compute())
        evs = [
            e for e in recorder.events()
            if e.get("type") == "read" and e.get("kind") == "compute"
        ]
        cold = [e for e in evs if e.get("cache_hit") is False]
        assert cold and cold[-1]["layout_entries"] >= 1
        assert "layout_evictions" in cold[-1] and "layout_evicted_bytes" in cold[-1]
        before = layout_cache_totals()
        del rm
        gc.collect()
        after = layout_cache_totals()
        assert after["evictions"] > before["evictions"]
        assert after["evicted_bytes"] > before["evicted_bytes"]
        assert after["entries"] < before["entries"] or before["entries"] == 0


# ----------------------------------------------------------------------
# the two new alarm classes: fire AND clear
# ----------------------------------------------------------------------
class TestMemoryRules:
    def test_default_rules_cover_memory_classes(self):
        rules = default_rules(tenant_bytes_limit=1024, unaccounted_growth_bytes=1e6)
        budget = next(r for r in rules if r.name == "memory_budget")
        leak = next(r for r in rules if r.name == "memory_leak")
        assert isinstance(budget, MemoryBudget) and isinstance(leak, MemoryLeak)
        assert budget.threshold == 1024.0
        # absent series: a monitor with no observatory polling stays clean
        registry = TimeSeriesRegistry(bucket_seconds=1.0, n_buckets=60)
        mon = HealthMonitor(rules, registry=registry)
        snap = mon.evaluate(now=T0)
        assert snap.status == "ok" and not snap.firing

    def test_budget_fires_and_clears_on_threshold(self):
        registry = TimeSeriesRegistry(bucket_seconds=1.0, n_buckets=60)
        rule = MemoryBudget(100.0, window_s=5.0)
        monitor = HealthMonitor([rule], registry=registry)
        for i in range(4):
            registry.observe(SERIES_MEM_BYTES_PER_TENANT, 500.0, t=T0 + i)
        snap = monitor.evaluate(now=T0 + 4)
        assert {a.name for a in snap.firing} == {"memory_budget"}
        # the live-tunable threshold: ops restoring the ceiling clears the
        # alarm on the very next evaluation, same samples
        rule.threshold = 1000.0
        snap = monitor.evaluate(now=T0 + 5)
        assert snap.status == "ok"
        assert "memory_budget" in monitor.fired_and_cleared()

    def test_leak_fires_on_monotone_growth_only(self):
        registry = TimeSeriesRegistry(bucket_seconds=1.0, n_buckets=120)
        rule = MemoryLeak(growth_bytes=1000.0, window_s=8.0, min_count=4)
        monitor = HealthMonitor([rule], registry=registry)
        # noisy but FLAT residue: never fires
        for i in range(8):
            registry.observe(SERIES_MEM_UNACCOUNTED, 5000.0 + (i % 2) * 400, t=T0 + i)
        snap = monitor.evaluate(now=T0 + 8)
        assert snap.status == "ok"
        # steady growth: every recent sample above every prior one by more
        # than the bound
        for i in range(8):
            registry.observe(SERIES_MEM_UNACCOUNTED, 10_000.0 + i * 2000, t=T0 + 20 + i)
        snap = monitor.evaluate(now=T0 + 28)
        assert {a.name for a in snap.firing} == {"memory_leak"}
        # recovery: the residue flattens, the window rolls past the growth
        for i in range(10):
            registry.observe(SERIES_MEM_UNACCOUNTED, 24_000.0, t=T0 + 29 + i)
        snap = monitor.evaluate(now=T0 + 39)
        assert snap.status == "ok"
        assert "memory_leak" in monitor.fired_and_cleared()


# ----------------------------------------------------------------------
# the observatory poll + Prometheus families + fleet wire
# ----------------------------------------------------------------------
class TestObservatoryExposition:
    def test_observe_derives_unaccounted(self, recorder):
        recorder.attach_timeseries(bucket_seconds=1.0, n_buckets=60, sketch_capacity=64)
        m = SlicedMetric(MeanSquaredError(), num_slices=8)
        preds, target = _batch()
        m.update(jnp.asarray(np.arange(8) % 8), preds, target)
        obs = MemoryObservatory(recorder=recorder)
        rep = obs.observe()
        assert rep["total_bytes"] > 0
        assert rep["cache_plane_bytes"] >= 0
        # CPU boxes report via host RSS; a device backend reports directly —
        # either way the residue must be derivable and positive (the process
        # holds far more than metric state)
        assert rep["source"] in ("backend", "host_rss")
        assert rep["device_bytes_in_use"] > 0
        assert rep["unaccounted_bytes"] == (
            rep["device_bytes_in_use"] - rep["total_bytes"] - rep["cache_plane_bytes"]
        )
        totals = recorder.memory_totals()
        assert totals["observations"] >= 1
        assert totals["ledger_bytes"] == rep["total_bytes"]
        assert totals["max_unaccounted_bytes"] >= rep["unaccounted_bytes"]

    def test_prometheus_memory_families_strict(self, recorder):
        m = MeanSquaredError()
        preds, target = _batch()
        m.update(preds, target)
        MemoryObservatory(recorder=recorder).observe()
        page = render_prometheus(recorder)
        assert 'metrics_tpu_memory_boundaries_total{boundary="update"}' in page
        assert "metrics_tpu_memory_observations_total" in page
        assert 'metrics_tpu_memory_ledger_bytes{window="last"}' in page
        assert 'metrics_tpu_memory_unaccounted_bytes{window="max"}' in page
        assert "metrics_tpu_memory_plane_evictions_total" in page
        parse_prometheus_strict(page)  # whole page must stay well-formed

    def test_fleet_wire_merge_sums_counts_maxes_gauges(self, recorder):
        m = MeanSquaredError()
        preds, target = _batch()
        m.update(preds, target)
        MemoryObservatory(recorder=recorder).observe()
        payload = counter_payload(recorder)
        assert payload["memory"]["update_boundaries"] >= 1
        other = dict(payload)
        other["process"] = 1
        merged = merge_payloads([payload, other])
        mem = merged["memory"]
        # host-summable counts add, point-in-time gauges take the fleet max
        assert mem["update_boundaries"] == 2 * payload["memory"]["update_boundaries"]
        assert mem["ledger_bytes"] == payload["memory"]["ledger_bytes"]
        page = render_prometheus(recorder, aggregate=merged)
        assert "metrics_tpu_memory_ledger_bytes" in page
        parse_prometheus_strict(page)

    def test_memory_events_ride_the_wire_payload(self, recorder):
        # the FleetCollector stitches per-host payloads: memory totals must
        # survive a JSON round-trip (no numpy scalars, no callables)
        import json

        m = MeanSquaredError()
        preds, target = _batch()
        m.update(preds, target)
        MemoryObservatory(recorder=recorder).observe()
        payload = counter_payload(recorder)
        round_tripped = json.loads(json.dumps(payload))
        assert round_tripped["memory"] == payload["memory"]
