"""tracelint baseline: checked-in grandfathered violations.

Entries are keyed on ``(rule, path, snippet)`` — the stripped source line,
not the line number — so edits elsewhere in a file never invalidate the
baseline, while any change to the offending line itself (including a fix)
surfaces immediately. Duplicate identical lines are handled by count.
"""
from __future__ import annotations

import json
import pathlib
from collections import Counter
from typing import Dict, Iterable, List, Tuple

from .engine import Violation

BASELINE_VERSION = 1

BaselineKey = Tuple[str, str, str]


def load_baseline(path: pathlib.Path) -> Counter:
    """Load a baseline file into a ``Counter[(rule, path, snippet)]``.

    A missing file is an empty baseline (fresh checkouts lint strictly).
    """
    path = pathlib.Path(path)
    if not path.exists():
        return Counter()
    data = json.loads(path.read_text())
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path} has version {data.get('version')!r}; this tracelint "
            f"reads version {BASELINE_VERSION} — regenerate with --baseline-update"
        )
    counts: Counter = Counter()
    for entry in data.get("entries", []):
        key = (entry["rule"], entry["path"], entry["snippet"])
        counts[key] += int(entry.get("count", 1))
    return counts


def save_baseline(path: pathlib.Path, violations: Iterable[Violation], notes: Dict[BaselineKey, str] = None) -> None:
    """Write the baseline for ``violations`` (sorted, deterministic)."""
    counts: Counter = Counter(v.key() for v in violations)
    lines: Dict[BaselineKey, int] = {}
    for v in violations:
        lines.setdefault(v.key(), v.line)
    entries = []
    for key in sorted(counts):
        rule, vpath, snippet = key
        entry = {
            "rule": rule,
            "path": vpath,
            "snippet": snippet,
            "count": counts[key],
            # informational only (never matched): where the entry was last seen
            "last_seen_line": lines[key],
        }
        if notes and key in notes:
            entry["note"] = notes[key]
        entries.append(entry)
    payload = {"version": BASELINE_VERSION, "tool": "tracelint", "entries": entries}
    pathlib.Path(path).write_text(json.dumps(payload, indent=2) + "\n")


def split_by_baseline(
    violations: Iterable[Violation], baseline: Counter
) -> Tuple[List[Violation], List[Violation], Counter]:
    """Partition into (new, baselined, stale-baseline-remainder)."""
    remaining = Counter(baseline)
    new: List[Violation] = []
    grandfathered: List[Violation] = []
    for v in violations:
        key = v.key()
        if remaining.get(key, 0) > 0:
            remaining[key] -= 1
            grandfathered.append(v)
        else:
            new.append(v)
    stale = Counter({k: n for k, n in remaining.items() if n > 0})
    return new, grandfathered, stale
