"""``python -m metrics_tpu.analysis`` — the tracelint CLI."""
import sys

from .cli import main

if __name__ == "__main__":
    sys.exit(main())
