"""Capacity-mode vs unbounded-mode consistency across the curve family.

TPU-native invariant with no reference analog: for every curve metric, the
static-capacity exact mode (jit-safe buffers, classification/_capacity.py)
must produce the SAME values as the unbounded cat-state mode on identical
data — across binary/multiclass/multilabel cases, averaging modes, tied
scores, uneven batch splits, and merge/sync layouts. sklearn parity for both
modes individually lives in test_exact_curve.py / test_curves.py; this grid
pins the two implementations against each other so they can never drift.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from metrics_tpu import AUROC, AveragePrecision, PrecisionRecallCurve, ROC
from tests.helpers.testers import NUM_CLASSES

_rng = np.random.default_rng(77)
N = 160


def _binary_data(ties):
    preds = _rng.random(N).astype(np.float32)
    if ties:
        preds = np.round(preds * 8) / 8
    target = (_rng.random(N) < 0.45).astype(np.int32)
    target[:2] = [0, 1]  # both classes present
    return preds, target


def _multiclass_data(ties):
    preds = _rng.random((N, NUM_CLASSES)).astype(np.float32)
    if ties:
        preds = np.round(preds * 8) / 8
    target = _rng.integers(0, NUM_CLASSES, N).astype(np.int32)
    target[:NUM_CLASSES] = np.arange(NUM_CLASSES)  # every class present
    return preds, target


def _multilabel_data(ties):
    preds = _rng.random((N, NUM_CLASSES)).astype(np.float32)
    if ties:
        preds = np.round(preds * 8) / 8
    target = (_rng.random((N, NUM_CLASSES)) < 0.4).astype(np.int32)
    target[0] = 1
    target[1] = 0
    return preds, target


def _update_in_batches(metric, preds, target, splits):
    lo = 0
    for hi in splits + [len(preds)]:
        metric.update(jnp.asarray(preds[lo:hi]), jnp.asarray(target[lo:hi]))
        lo = hi
    return metric


@pytest.mark.parametrize("ties", [False, True], ids=["unique", "ties"])
@pytest.mark.parametrize("splits", [[], [37], [10, 100]], ids=["one", "two", "three"])
class TestBinaryCapacityConsistency:
    def test_auroc(self, ties, splits):
        preds, target = _binary_data(ties)
        unbounded = _update_in_batches(AUROC(), preds, target, splits)
        capacity = _update_in_batches(AUROC(capacity=2 * N), preds, target, splits)
        np.testing.assert_allclose(
            float(capacity.compute()), float(unbounded.compute()), atol=1e-5
        )

    def test_average_precision(self, ties, splits):
        preds, target = _binary_data(ties)
        unbounded = _update_in_batches(AveragePrecision(pos_label=1), preds, target, splits)
        capacity = _update_in_batches(AveragePrecision(capacity=2 * N), preds, target, splits)
        np.testing.assert_allclose(
            float(capacity.compute()), float(unbounded.compute()), atol=1e-5
        )

    def test_roc_points(self, ties, splits):
        preds, target = _binary_data(ties)
        unbounded = _update_in_batches(ROC(pos_label=1), preds, target, splits)
        capacity = _update_in_batches(ROC(capacity=2 * N), preds, target, splits)
        u_fpr, u_tpr, u_thr = (np.asarray(v) for v in unbounded.compute())
        fpr, tpr, thr, mask = (np.asarray(v) for v in capacity.compute())
        np.testing.assert_allclose(fpr[mask], u_fpr, atol=1e-6)
        np.testing.assert_allclose(tpr[mask], u_tpr, atol=1e-6)
        np.testing.assert_allclose(thr[mask][1:], u_thr[1:], atol=1e-6)

    def test_prc_points(self, ties, splits):
        preds, target = _binary_data(ties)
        unbounded = _update_in_batches(PrecisionRecallCurve(pos_label=1), preds, target, splits)
        capacity = _update_in_batches(PrecisionRecallCurve(capacity=2 * N), preds, target, splits)
        u_prec, u_rec, u_thr = (np.asarray(v) for v in unbounded.compute())
        prec, rec, thr, mask, last = (np.asarray(v) for v in capacity.compute())
        np.testing.assert_allclose(np.concatenate([prec[mask][::-1], [last[0]]]), u_prec, atol=1e-6)
        np.testing.assert_allclose(np.concatenate([rec[mask][::-1], [last[1]]]), u_rec, atol=1e-6)
        np.testing.assert_allclose(thr[mask][::-1], u_thr, atol=1e-6)


@pytest.mark.parametrize("ties", [False, True], ids=["unique", "ties"])
@pytest.mark.parametrize("average", ["macro", "weighted", "none"])
class TestMulticlassCapacityConsistency:
    def test_auroc(self, ties, average):
        preds, target = _multiclass_data(ties)
        unbounded = AUROC(num_classes=NUM_CLASSES, average=average)
        unbounded.update(jnp.asarray(preds), jnp.asarray(target))
        capacity = AUROC(num_classes=NUM_CLASSES, average=average, capacity=2 * N)
        capacity.update(jnp.asarray(preds[:50]), jnp.asarray(target[:50]))
        capacity.update(jnp.asarray(preds[50:]), jnp.asarray(target[50:]))
        np.testing.assert_allclose(
            np.asarray(capacity.compute()), np.asarray(unbounded.compute()), atol=1e-5
        )

    def test_average_precision(self, ties, average):
        preds, target = _multiclass_data(ties)
        unbounded = AveragePrecision(num_classes=NUM_CLASSES, average=None)
        unbounded.update(jnp.asarray(preds), jnp.asarray(target))
        capacity = AveragePrecision(num_classes=NUM_CLASSES, average="none", capacity=2 * N)
        capacity.update(jnp.asarray(preds), jnp.asarray(target))
        got = np.asarray(capacity.compute())
        want = np.asarray([np.asarray(v) for v in unbounded.compute()])
        # unbounded 'none' may score absent classes 0 where capacity uses NaN;
        # every class is present here so values must agree exactly
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_roc_per_class(self, ties, average):
        if average != "macro":
            pytest.skip("curve points are average-independent")
        preds, target = _multiclass_data(ties)
        unbounded = ROC(num_classes=NUM_CLASSES)
        unbounded.update(jnp.asarray(preds), jnp.asarray(target))
        capacity = ROC(num_classes=NUM_CLASSES, capacity=2 * N)
        capacity.update(jnp.asarray(preds), jnp.asarray(target))
        u_fpr, u_tpr, u_thr = unbounded.compute()
        fpr, tpr, thr, mask = (np.asarray(v) for v in capacity.compute())
        for k in range(NUM_CLASSES):
            np.testing.assert_allclose(fpr[k][mask[k]], np.asarray(u_fpr[k]), atol=1e-6)
            np.testing.assert_allclose(tpr[k][mask[k]], np.asarray(u_tpr[k]), atol=1e-6)

    def test_prc_per_class(self, ties, average):
        if average != "macro":
            pytest.skip("curve points are average-independent")
        preds, target = _multiclass_data(ties)
        unbounded = PrecisionRecallCurve(num_classes=NUM_CLASSES)
        unbounded.update(jnp.asarray(preds), jnp.asarray(target))
        capacity = PrecisionRecallCurve(num_classes=NUM_CLASSES, capacity=2 * N)
        capacity.update(jnp.asarray(preds), jnp.asarray(target))
        u_prec, u_rec, u_thr = unbounded.compute()
        prec, rec, thr, mask, last = (np.asarray(v) for v in capacity.compute())
        for k in range(NUM_CLASSES):
            np.testing.assert_allclose(
                np.concatenate([prec[k][mask[k]][::-1], [last[k, 0]]]),
                np.asarray(u_prec[k]),
                atol=1e-6,
            )
            np.testing.assert_allclose(
                np.concatenate([rec[k][mask[k]][::-1], [last[k, 1]]]),
                np.asarray(u_rec[k]),
                atol=1e-6,
            )


@pytest.mark.parametrize("ties", [False, True], ids=["unique", "ties"])
class TestMultilabelCapacityConsistency:
    def test_roc_and_prc(self, ties):
        preds, target = _multilabel_data(ties)
        u_roc = ROC(num_classes=NUM_CLASSES)
        u_roc.update(jnp.asarray(preds), jnp.asarray(target))
        c_roc = ROC(num_classes=NUM_CLASSES, capacity=2 * N, multilabel=True)
        c_roc.update(jnp.asarray(preds), jnp.asarray(target))
        u_fpr, u_tpr, _ = u_roc.compute()
        fpr, tpr, thr, mask = (np.asarray(v) for v in c_roc.compute())
        for k in range(NUM_CLASSES):
            np.testing.assert_allclose(fpr[k][mask[k]], np.asarray(u_fpr[k]), atol=1e-6)
            np.testing.assert_allclose(tpr[k][mask[k]], np.asarray(u_tpr[k]), atol=1e-6)

    def test_average_precision_macro(self, ties):
        preds, target = _multilabel_data(ties)
        per_class = []
        from sklearn.metrics import average_precision_score

        for k in range(NUM_CLASSES):
            per_class.append(average_precision_score(target[:, k], preds[:, k]))
        capacity = AveragePrecision(
            num_classes=NUM_CLASSES, capacity=2 * N, multilabel=True, average="macro"
        )
        capacity.update(jnp.asarray(preds), jnp.asarray(target))
        np.testing.assert_allclose(float(capacity.compute()), np.mean(per_class), atol=1e-5)


def test_capacity_state_dict_roundtrip_consistency():
    """A capacity-mode metric saved and restored mid-accumulation continues
    to agree with the unbounded metric."""
    preds, target = _binary_data(False)
    unbounded = AUROC()
    unbounded.update(jnp.asarray(preds), jnp.asarray(target))

    m = AUROC(capacity=2 * N)
    m.update(jnp.asarray(preds[:80]), jnp.asarray(target[:80]))
    restored = AUROC(capacity=2 * N)
    restored.load_state_dict(m.state_dict())
    restored.update(jnp.asarray(preds[80:]), jnp.asarray(target[80:]))
    np.testing.assert_allclose(
        float(restored.compute()), float(unbounded.compute()), atol=1e-5
    )


def test_capacity_jit_epoch_equals_unbounded():
    """A whole scanned epoch in one jit (the TPU deployment shape) matches
    the eager unbounded metric."""
    preds, target = _multiclass_data(False)
    m = AUROC(num_classes=NUM_CLASSES, capacity=N)

    n_steps, bs = 8, N // 8

    @jax.jit
    def epoch(p, t):
        def step(state, i):
            return m.update_state(state, jax.lax.dynamic_slice_in_dim(p, i * bs, bs), jax.lax.dynamic_slice_in_dim(t, i * bs, bs)), 0.0

        state, _ = jax.lax.scan(step, m.init_state(), jnp.arange(n_steps))
        return m.compute_state(state)

    got = float(epoch(jnp.asarray(preds), jnp.asarray(target)))
    unbounded = AUROC(num_classes=NUM_CLASSES)
    unbounded.update(jnp.asarray(preds), jnp.asarray(target))
    np.testing.assert_allclose(got, float(unbounded.compute()), atol=1e-5)
