"""Version-compat shims for jax APIs that moved between releases.

``shard_map`` graduated from ``jax.experimental.shard_map`` to the
top-level ``jax.shard_map`` namespace; builds in the wild sit on either
side of the move (this container's jax has only the experimental path).
Import it from here so library code, benchmarks, docs examples, and tests
run on both:

    from metrics_tpu.utils.compat import shard_map

The call signature (``mesh=``, ``in_specs=``, ``out_specs=``) is identical
on both sides of the move.
"""

import jax

if callable(getattr(jax, "shard_map", None)):  # newer jax: top-level export
    shard_map = jax.shard_map
else:  # older jax: experimental namespace (or a non-callable module stub)
    from jax.experimental.shard_map import shard_map

__all__ = ["shard_map"]
