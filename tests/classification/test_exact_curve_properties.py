"""Property-based fuzz of the fixed-capacity exact-curve kernels: generated
score/label mixes (extreme ties, constant scores, class imbalance) must
match sklearn at 1e-6 and behave sanely at the degenerate edges."""
import numpy as np
from hypothesis import assume, given, settings, strategies as st
from sklearn.metrics import average_precision_score, roc_auc_score

import jax.numpy as jnp

from metrics_tpu.functional.classification.exact_curve import (
    binary_auroc_fixed,
    binary_average_precision_fixed,
    curve_buffer_init,
    curve_buffer_update,
)

_settings = settings(max_examples=60, deadline=None)


@st.composite
def _scored_labels(draw):
    n = draw(st.integers(4, 64))
    quant = draw(st.sampled_from([None, 2, 10]))  # None=continuous, else tie-heavy
    rng = np.random.default_rng(draw(st.integers(0, 2**31)))
    scores = rng.random(n).astype(np.float32)
    if quant:
        scores = np.round(scores * quant) / quant
    labels = (rng.random(n) < draw(st.floats(0.1, 0.9))).astype(np.int32)
    return scores, labels


@given(_scored_labels())
@_settings
def test_auroc_ap_match_sklearn(data):
    scores, labels = data
    assume(0 < labels.sum() < len(labels))
    state = curve_buffer_init(128)
    state = curve_buffer_update(state, jnp.asarray(scores), jnp.asarray(labels))
    auroc = float(binary_auroc_fixed(state["preds"], state["target"], state["valid"]))
    ap = float(binary_average_precision_fixed(state["preds"], state["target"], state["valid"]))
    np.testing.assert_allclose(auroc, roc_auc_score(labels, scores), atol=1e-6)
    np.testing.assert_allclose(ap, average_precision_score(labels, scores), atol=1e-6)


@given(_scored_labels(), st.integers(1, 5))
@_settings
def test_split_updates_equal_single(data, n_chunks):
    scores, labels = data
    assume(0 < labels.sum() < len(labels))
    one = curve_buffer_update(curve_buffer_init(128), jnp.asarray(scores), jnp.asarray(labels))
    many = curve_buffer_init(128)
    for s, l in zip(np.array_split(scores, n_chunks), np.array_split(labels, n_chunks)):
        if len(s):
            many = curve_buffer_update(many, jnp.asarray(s), jnp.asarray(l))
    a1 = float(binary_auroc_fixed(one["preds"], one["target"], one["valid"]))
    a2 = float(binary_auroc_fixed(many["preds"], many["target"], many["valid"]))
    np.testing.assert_allclose(a1, a2, atol=1e-7)


@given(st.integers(4, 32))
@_settings
def test_constant_scores_give_half_auroc(n):
    """All-tied scores: AUROC must be exactly 0.5 (the chance diagonal)."""
    labels = np.zeros(n, np.int32)
    labels[: n // 2] = 1
    state = curve_buffer_update(
        curve_buffer_init(64), jnp.full(n, 0.7, jnp.float32), jnp.asarray(labels)
    )
    auroc = float(binary_auroc_fixed(state["preds"], state["target"], state["valid"]))
    np.testing.assert_allclose(auroc, 0.5, atol=1e-7)
